"""Self-driving fleet: the decision cores behind quarantine + re-plan.

PR 10 made the straggler *nameable* (``hvd_step_skew_seconds``,
``hvd_straggler_total{rank}``) and PR 13 made a healthy fleet's cost
*predictable* (``hvd_sim_divergence_ratio{hop}``); this module is the
control loop that ACTS on both signals (ROADMAP item 5, the FlexLink
lesson applied to the whole fleet: measure, then adapt). It holds the
pure, unit-testable decision logic; the :class:`ElasticDriver` wires it
to the supervision beat, and ``docs/fault_tolerance.md`` ("Self-driving
fleet") documents the resulting decision ladder:

1. **Slowness quarantine** (:class:`StragglerPolicy`): consume the
   per-step straggler charges the driver's :class:`StepSkewTracker`
   emits; when ONE rank is charged the last-finisher above threshold for
   ``HOROVOD_QUARANTINE_STRIKES`` of the last
   ``HOROVOD_QUARANTINE_WINDOW`` observed steps, propose quarantining
   its host. Vetoes are part of the policy (and of its tests): never
   below min world size, never two hosts in one beat. The driver reuses
   the blacklist cooldown/decay/relapse-doubling machinery with a
   distinct ``reason="slow"`` ledger so death strikes and sloth strikes
   decay independently.
2. **Live re-plan** (:func:`propose_replan`): when observed per-hop cost
   drifts from the model beyond ``HOROVOD_REPLAN_DIVERGENCE``
   (calibrated constants vs generation defaults — the same alpha-beta
   entries ``fleet_sim.py --replay`` diffs) or the skew trend says the
   current plan is mispriced, re-price the tuner's free objectives on
   the DRIFTED model and propose the best (topo algorithm, wire dtype,
   bucket knobs) configuration — published only when it is STRICTLY
   better than the current one and every implied plan passes the
   symbolic verifier (:func:`verify_replan`).

Everything here is jax-free (the compositor's planning layer and the
tuner's free objectives are pure python), so the driver process never
pays a backend import.
"""

from __future__ import annotations

import os
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

# --------------------------------------------------------------- knobs
QUARANTINE_STRIKES_ENV = "HOROVOD_QUARANTINE_STRIKES"
QUARANTINE_WINDOW_ENV = "HOROVOD_QUARANTINE_WINDOW"
QUARANTINE_COOLDOWN_ENV = "HOROVOD_QUARANTINE_COOLDOWN_S"
REPLAN_DIVERGENCE_ENV = "HOROVOD_REPLAN_DIVERGENCE"
REPLAN_SKEW_ENV = "HOROVOD_REPLAN_SKEW_S"
REPLAN_CHECK_ENV = "HOROVOD_REPLAN_CHECK_S"
REPLAN_SPEC_ENV = "HOROVOD_REPLAN_SPEC"
SPARES_ENV = "HOROVOD_SPARES"

DEFAULT_QUARANTINE_WINDOW_FACTOR = 2  # window = factor * strikes


def _env_int(env: Dict[str, str], name: str, default: int) -> int:
    try:
        return int(env.get(name, "") or default)
    except ValueError:
        return default


def _env_float(env: Dict[str, str], name: str, default: float) -> float:
    try:
        return float(env.get(name, "") or default)
    except ValueError:
        return default


# ------------------------------------------------- slowness quarantine
@dataclass(frozen=True)
class QuarantineDecision:
    """One policy verdict: quarantine ``host`` because ``rank`` was the
    charged straggler for ``charges`` of the last ``window`` steps."""

    host: str
    rank: int
    charges: int
    window: int


class StragglerPolicy:
    """Sliding-window strike accumulator over the driver's per-step
    straggler charges.

    ``observe()`` is fed every step the skew tracker emits (charged or
    not — the window is "the last N steps", not "the last N charges"),
    so a rank that stops lagging DECAYS out as healthy steps push its
    charges off the window. ``decide()`` returns at most ONE decision
    per call (one host per supervision beat) and applies the min-world
    veto itself, so the safety properties are unit-testable without a
    fleet. ``reset_generation()`` drops the ledger: ranks are renumbered
    across a resize, so charges must never survive one.
    """

    def __init__(self, strikes: int = 0, window: Optional[int] = None):
        self.strikes = max(int(strikes), 0)
        if window is None:
            window = DEFAULT_QUARANTINE_WINDOW_FACTOR * max(self.strikes, 1)
        self.window = max(int(window), max(self.strikes, 1))
        self._steps: "deque[Tuple[int, Optional[int]]]" = deque(
            maxlen=self.window
        )
        self.generation: Optional[int] = None
        self.vetoes = 0

    @staticmethod
    def from_env(env: Optional[Dict[str, str]] = None) -> "StragglerPolicy":
        e = env if env is not None else os.environ
        strikes = _env_int(e, QUARANTINE_STRIKES_ENV, 0)
        window = _env_int(e, QUARANTINE_WINDOW_ENV, 0) or None
        return StragglerPolicy(strikes=strikes, window=window)

    @property
    def enabled(self) -> bool:
        return self.strikes > 0

    def reset_generation(self, gen: Optional[int] = None) -> None:
        self._steps.clear()
        self.generation = None if gen is None else int(gen)

    def observe(self, step: int, skew_s: float, worst_rank: int,
                charged: bool) -> None:
        """Record one emitted step: ``charged`` is the driver's existing
        straggler verdict (skew above threshold → the last finisher is
        charged one ``hvd_straggler_total``)."""
        self._steps.append((int(step), int(worst_rank) if charged else None))

    def charges(self) -> Dict[int, int]:
        """Charged-step count per rank inside the current window."""
        out: Dict[int, int] = {}
        for _, rank in self._steps:
            if rank is not None:
                out[rank] = out.get(rank, 0) + 1
        return out

    def decide(
        self,
        rank_to_host: Dict[int, str],
        slots_by_host: Dict[str, int],
        min_world: int,
    ) -> Optional[QuarantineDecision]:
        """At most one quarantine per beat: the most-charged rank at or
        above the strike threshold, vetoed when removing its host would
        drop the fleet below ``min_world`` (``slots_by_host`` is the
        AVAILABLE capacity per host — spare slots on healthy hosts are
        exactly what makes a quarantine affordable). A decision consumes
        the offender's charges so the same evidence is never spent
        twice."""
        if not self.enabled:
            return None
        charges = self.charges()
        ranked = sorted(
            ((n, r) for r, n in charges.items() if n >= self.strikes),
            key=lambda t: (-t[0], t[1]),
        )
        for n, rank in ranked:
            host = rank_to_host.get(rank)
            if host is None:
                continue  # departed rank: stale charge, nothing to act on
            remaining = sum(
                c for h, c in slots_by_host.items() if h != host
            )
            if remaining < min_world:
                self.vetoes += 1
                return None  # quarantining ANY offender would kill the job
            # Spend the evidence: drop this rank's charges from the
            # window (healthy peers keep theirs — but only one decision
            # leaves this call, so two hosts can never fall in one beat).
            self._steps = deque(
                ((s, None if r == rank else r) for s, r in self._steps),
                maxlen=self.window,
            )
            return QuarantineDecision(
                host=host, rank=rank, charges=n, window=self.window
            )
        return None


# ---------------------------------------------------- serving autoscale
@dataclass(frozen=True)
class ScaleDecision:
    """One serving-autoscale verdict (docs/serving.md "Autoscale"):
    ``action`` is ``"scale-out"`` (promote a spare into a new DP serving
    replica) or ``"scale-in"`` (quarantine-shrink one replica away);
    ``reason`` names the triggering signal."""

    action: str
    reason: str
    depth: float
    slo_burn: float


class ServeScalePolicy:
    """Queue-depth / SLO-burn autoscaler for ``hvd.serve()`` — the same
    pure-policy discipline as :class:`StragglerPolicy`: no clock, no
    threads; the engine feeds one :meth:`observe` beat per autoscale
    tick (queue depth, SLO violations and completions since the last
    beat) and :meth:`decide` returns at most one verdict per call.

    Triggers over the sliding ``window`` of beats:

    - **scale-out** when mean queue depth >= ``scale_out_depth`` OR the
      SLO burn fraction (violations / completions) >= ``slo_burn`` —
      the serving analogue of spare promotion.
    - **scale-in** when mean depth <= ``scale_in_depth`` AND burn is
      under half the threshold — the quarantine-shrink verb.

    Vetoes are the policy's own: never below ``min_replicas``, never
    above ``max_replicas``, and a ``cooldown`` of beats after any
    decision so one burst cannot thrash the fleet both ways.
    """

    def __init__(self, scale_out_depth: float = 16.0,
                 scale_in_depth: float = 1.0, slo_burn: float = 0.1,
                 window: int = 8, cooldown: int = 4,
                 min_replicas: int = 1, max_replicas: int = 8):
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        if min_replicas < 1 or max_replicas < min_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{min_replicas}..{max_replicas}"
            )
        self.scale_out_depth = float(scale_out_depth)
        self.scale_in_depth = float(scale_in_depth)
        self.slo_burn = float(slo_burn)
        self.window = int(window)
        self.cooldown = max(int(cooldown), 0)
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        # (queue_depth, slo_violations, completions) per beat.
        self._beats: "deque[Tuple[float, int, int]]" = deque(
            maxlen=self.window
        )
        self._beat = 0
        self._last_decision_beat: Optional[int] = None

    @staticmethod
    def from_env(env: Optional[Dict[str, str]] = None,
                 *, min_replicas: int = 1,
                 max_replicas: int = 8) -> "ServeScalePolicy":
        from ..common import env as _env

        e = env if env is not None else os.environ
        return ServeScalePolicy(
            scale_out_depth=_env_float(
                e, _env.HOROVOD_SERVE_SCALE_OUT_DEPTH, 16.0
            ),
            scale_in_depth=_env_float(
                e, _env.HOROVOD_SERVE_SCALE_IN_DEPTH, 1.0
            ),
            slo_burn=_env_float(e, _env.HOROVOD_SERVE_SLO_BURN, 0.1),
            window=_env_int(e, _env.HOROVOD_SERVE_SCALE_WINDOW, 8),
            cooldown=_env_int(e, _env.HOROVOD_SERVE_SCALE_COOLDOWN, 4),
            min_replicas=min_replicas,
            max_replicas=max_replicas,
        )

    def observe(self, queue_depth: float, slo_violations: int,
                completions: int) -> None:
        """One autoscale beat: instantaneous queue depth plus the SLO
        violations and completed requests SINCE the previous beat."""
        self._beats.append(
            (float(queue_depth), int(slo_violations), int(completions))
        )
        self._beat += 1

    def burn(self) -> float:
        """SLO-violation fraction over the window (0 with no traffic —
        an idle fleet is not burning its SLO)."""
        viol = sum(v for _, v, _ in self._beats)
        done = sum(c for _, _, c in self._beats)
        return (viol / done) if done else 0.0

    def mean_depth(self) -> float:
        if not self._beats:
            return 0.0
        return sum(d for d, _, _ in self._beats) / len(self._beats)

    def decide(self, replicas: int) -> Optional[ScaleDecision]:
        """At most one verdict per call, None inside the cooldown or
        before the window has filled (no decisions on a cold start)."""
        if len(self._beats) < self.window:
            return None
        if (self._last_decision_beat is not None
                and self._beat - self._last_decision_beat <= self.cooldown):
            return None
        depth = self.mean_depth()
        burn = self.burn()
        if ((depth >= self.scale_out_depth or burn >= self.slo_burn)
                and replicas < self.max_replicas):
            self._last_decision_beat = self._beat
            reason = ("queue-depth" if depth >= self.scale_out_depth
                      else "slo-burn")
            return ScaleDecision("scale-out", reason, depth, burn)
        if (depth <= self.scale_in_depth and burn < self.slo_burn / 2
                and replicas > self.min_replicas):
            self._last_decision_beat = self._beat
            return ScaleDecision("scale-in", "idle", depth, burn)
        return None


# ------------------------------------------------------------- re-plan
def divergence_ratios(default_model, calibrated_model) -> Dict[str, float]:
    """Per-hop drift between the generation-default alpha-beta entries
    and the calibrated ones, as a symmetric ratio >= 1 (1.0 = no drift).
    The bandwidth and latency drifts are folded with ``max`` — either
    constant moving means the planner priced the link wrong."""
    out: Dict[str, float] = {}
    calibrated = {h.name: h for h in calibrated_model.hops}
    for h in default_model.hops:
        c = calibrated.get(h.name)
        if c is None:
            continue
        ratio = 1.0
        if c.bandwidth_gbps > 0 and h.bandwidth_gbps > 0:
            r = h.bandwidth_gbps / c.bandwidth_gbps
            ratio = max(ratio, r, 1.0 / r)
        if c.latency_us > 0 and h.latency_us > 0:
            r = c.latency_us / h.latency_us
            ratio = max(ratio, r, 1.0 / r)
        out[h.name] = round(ratio, 6)
    return out


def max_divergence(ratios: Dict[str, float]) -> float:
    """The drift scalar the ``HOROVOD_REPLAN_DIVERGENCE`` threshold
    gates on: the largest per-hop |ratio - 1|."""
    return round(
        max((abs(r - 1.0) for r in ratios.values()), default=0.0), 6
    )


def skew_trend(samples, min_n: int = 8) -> Optional[float]:
    """The ``StepSkewTracker``-trend trigger scalar: mean cross-rank
    step skew over the recent window, or None while the evidence is
    thinner than ``min_n`` steps (one noisy step must never re-plan a
    fleet). Sustained skew above ``HOROVOD_REPLAN_SKEW_S`` says the
    current plan is mispriced for the fleet as it actually behaves —
    the re-plan then re-prices on whatever calibrated constants are
    available (generation defaults when none are)."""
    xs = [float(s) for s in samples]
    if len(xs) < max(int(min_n), 1):
        return None
    return round(sum(xs) / len(xs), 6)


def replay_divergence(report: Dict) -> Dict[str, float]:
    """Per-hop modeled/measured ratios from a ``fleet_sim.py --replay``
    report (the ``hvd_sim_divergence_ratio`` block): the OTHER drift
    source the trigger accepts. ``null`` entries (hop never measured)
    are skipped — absence of evidence is not drift."""
    out: Dict[str, float] = {}
    block = report.get("divergence") or report.get(
        "hvd_sim_divergence_ratio") or {}
    for hop, ratio in block.items():
        if ratio is None:
            continue
        try:
            r = float(ratio)
        except (TypeError, ValueError):
            continue
        if r > 0:
            out[str(hop)] = round(max(r, 1.0 / r), 6)
    return out


_DEFAULT_CONFIG_KEYS = (
    "fusion_threshold_bytes", "first_bucket_bytes", "topo_algorithm",
    "wire_dtype",
)


def _normalize_config(config: Optional[Dict]) -> Dict:
    from ..common.env import Config

    cfg = dict(config or {})
    base = Config.from_env()
    cfg.setdefault("fusion_threshold_bytes",
                   int(base.fusion_threshold_bytes))
    cfg.setdefault("first_bucket_bytes",
                   int(base.fusion_first_bucket_bytes))
    cfg.setdefault("topo_algorithm", "auto")
    cfg.setdefault("wire_dtype", "f32")
    return {k: cfg[k] for k in _DEFAULT_CONFIG_KEYS}


def candidate_configs(model, current: Dict) -> List[Dict]:
    """The deterministic re-plan grid: every topo choice the compositor
    can realize on this model x both wire dtypes, over the current
    bucket knobs plus the tuner's canonical first-bucket alternatives.
    Small on purpose — a re-plan prices in one supervision beat; the
    full GP search stays offline (tools/autotune_compiled.py)."""
    topos = ["auto", "flat"]
    if model.levels > 1:
        topos += ["two-level", "split"]
    first_buckets = sorted({
        int(current["first_bucket_bytes"]), 1 << 20, 4 << 20,
    })
    out: List[Dict] = []
    for topo in topos:
        for wire in ("f32", "int8"):
            for fb in first_buckets:
                out.append({
                    "fusion_threshold_bytes":
                        int(current["fusion_threshold_bytes"]),
                    "first_bucket_bytes": fb,
                    "topo_algorithm": topo,
                    "wire_dtype": wire,
                })
    return out


@dataclass
class ReplanProposal:
    """A priced, not-yet-verified re-plan: the winning knob set, the
    incumbent it beats, and the modeled evidence (exposed-us on the
    drifted model) that justifies publishing it."""

    config: Dict
    current: Dict
    current_exposed_us: float
    replanned_exposed_us: float
    trigger: str
    drift: float
    per_hop: Dict[str, float] = field(default_factory=dict)

    def to_notice(self, notice_id: int, gen: int, epoch: int) -> Dict:
        """The KV document workers adopt at a commit boundary. Stable
        key order (the driver serializes it sort_keys) and no wall
        clock — the notice must journal/diff deterministically."""
        return {
            "id": int(notice_id),
            "gen": int(gen),
            "epoch": int(epoch),
            "trigger": self.trigger,
            "drift": round(self.drift, 6),
            "per_hop": {k: v for k, v in sorted(self.per_hop.items())},
            "config": dict(self.config),
            "current": dict(self.current),
            "modeled": {
                "current_exposed_us": round(self.current_exposed_us, 4),
                "replanned_exposed_us": round(self.replanned_exposed_us, 4),
            },
        }


def propose_replan(
    spec,
    model,
    current_config: Optional[Dict],
    calibration,
    trigger: str = "divergence",
    per_hop: Optional[Dict[str, float]] = None,
    drift: float = 0.0,
) -> Optional[ReplanProposal]:
    """Re-price the free objectives on the CALIBRATED (drifted) model
    and return the best configuration — or None when the incumbent is
    already the best (a re-plan that does not strictly win modeled step
    time is never published; the smoke gates on this)."""
    from ..tune.objective import free_objectives

    current = _normalize_config(current_config)
    cur_obj = free_objectives(spec, current, model, calibration=calibration)
    best_cfg, best_obj = current, cur_obj
    for cand in candidate_configs(model, current):
        if cand == current:
            continue
        obj = free_objectives(spec, cand, model, calibration=calibration)
        if obj["exposed_us"] < best_obj["exposed_us"] or (
            obj["exposed_us"] == best_obj["exposed_us"]
            and obj["wire_bytes"] < best_obj["wire_bytes"]
        ):
            best_cfg, best_obj = cand, obj
    if best_cfg == current:
        return None
    if not best_obj["exposed_us"] < cur_obj["exposed_us"]:
        return None
    return ReplanProposal(
        config=best_cfg,
        current=current,
        current_exposed_us=float(cur_obj["exposed_us"]),
        replanned_exposed_us=float(best_obj["exposed_us"]),
        trigger=trigger,
        drift=float(drift),
        per_hop=dict(per_hop or {}),
    )


def price_resize(param_bytes: int, n_old: int, n_new: int, model=None, *,
                 opt_slots: int = 2, quantized: bool = False,
                 itemsize: int = 4) -> Dict:
    """Price the sharded-state redistribution of a world resize
    (quarantine shrink, spare-promotion grow, scale-in/out) so the
    re-plan ladder can weigh "resize now" against its wire cost: the
    ZeRO-1 optimizer state (``opt_slots`` f32 vectors per parameter —
    Adam 2, momentum 1 — plus the EF residual on the int8 wire) is
    sharded 1/N and must re-partition when N changes
    (``parallel/reshard`` executes the move this prices).

    ``model`` (an ``InterconnectModel``) turns bytes into a modeled
    time over its OUTERMOST hop — a resize re-forms the world, so the
    redistribution crosses the slowest fabric; ranks move their slices
    in parallel, so the serialized bytes are ``moved / min(n)``."""
    from ..parallel.reshard import resize_redistribution

    elements = max(int(param_bytes) // 4, 0)  # f32 master elements
    copies = int(opt_slots) + (1 if quantized else 0)
    out = resize_redistribution(
        elements, itemsize, int(n_old), int(n_new),
        quantized=quantized, copies=copies,
    )
    out["param_bytes"] = int(param_bytes)
    out["opt_slots"] = int(opt_slots)
    out["quantized"] = bool(quantized)
    if model is not None:
        hop = model.hops[0]
        per_rank = out["moved_bytes"] / max(min(int(n_old), int(n_new)), 1)
        bytes_per_us = float(hop.bandwidth_gbps) * 1000.0
        out["modeled_time_us"] = round(
            float(hop.latency_us) + per_rank / bytes_per_us, 4
        )
        out["hop"] = hop.name
    return out


def verify_replan(spec, config: Dict, model, calibration) -> List:
    """Symbolically verify every stream-group plan ``config`` implies
    (the tuner's pre-pin gate, ``analysis/plan_verify``): a re-plan
    notice is published only when this returns no findings — the driver
    must never steer the fleet onto a plan the checker can refute."""
    from ..analysis.plan_verify import verify_plan
    from ..tune.objective import calibrated_model, group_plans

    if calibration is not None:
        model, _ = calibrated_model(model, calibration,
                                    where="replan-verify")
    findings: List = []
    for plan in group_plans(spec, config, model):
        findings.extend(verify_plan(plan, model))
    return findings


# --------------------------------------------- observed-program spec
def spec_from_windows(windows: Dict[int, dict]):
    """Reconstruct the fleet's observed program (layer name -> payload
    bytes) from collected trace windows: the per-collective spans the
    runtime records carry ``nbytes``, so the driver can price a re-plan
    against what the fleet ACTUALLY reduces without any side channel.
    ``HOROVOD_REPLAN_SPEC`` (inline JSON or a path;
    ``{"layers": [["name", bytes], ...]}``) overrides for operators who
    want the re-plan priced against a declared program. Returns None
    when neither source yields a byte."""
    from ..tune.objective import ProgramSpec

    raw = os.environ.get(REPLAN_SPEC_ENV, "").strip()
    if raw:
        import json as _json

        text = raw
        if not raw.lstrip().startswith("{"):
            with open(raw) as f:
                text = f.read()
        doc = _json.loads(text)
        layers = tuple(
            (str(n), int(b)) for n, b in doc.get("layers", []) if int(b) > 0
        )
        if layers:
            return ProgramSpec(
                name=str(doc.get("name", "replan-spec")), layers=layers
            )
    seen: Dict[str, int] = {}
    order: List[str] = []
    for _, doc in sorted(windows.items()):
        for ev in doc.get("events") or []:
            name = str(ev.get("name", ""))
            if not name.startswith(("hvd_response", "hvd_plan")):
                continue
            args = ev.get("args") or {}
            nbytes = args.get("nbytes", args.get("bytes"))
            if not nbytes:
                continue
            key = str(args.get("tensor", "")) or name
            if key not in seen:
                order.append(key)
            seen[key] = max(seen.get(key, 0), int(nbytes))
    layers = tuple((k, seen[k]) for k in order if seen[k] > 0)
    if not layers:
        return None
    return ProgramSpec(name="observed", layers=layers)


def model_for_world(world: Optional[Dict], generation: Optional[str] = None):
    """The interconnect model the driver prices re-plans on, derived
    from the published world doc's assignment structure (local/cross
    sizes) exactly as ``topo.model_from_topology`` derives it from a
    live process: a homogeneous local>1 x cross>1 grid gets the
    DCN x ICI ladder, anything else collapses to one flat ICI hop.
    ``HOROVOD_TOPOLOGY_MODEL`` overrides apply as everywhere else."""
    from ..topo import model as _tm

    size = len((world or {}).get("assignments") or {}) or 1
    locals_ = {
        int(a.get("local_size", 1))
        for a in (world or {}).get("assignments", {}).values()
    } or {1}
    crosses = {
        int(a.get("cross_size", 1))
        for a in (world or {}).get("assignments", {}).values()
    } or {1}
    gen = generation or _tm.detect_generation()
    local = locals_.pop() if len(locals_) == 1 else 0
    cross = crosses.pop() if len(crosses) == 1 else 0
    if local > 1 and cross > 1 and local * cross == size:
        model = _tm.synthetic_model(local, cross, generation=gen)
    else:
        model = _tm.synthetic_model(size, generation=gen)
    return _tm.apply_override(model)
