"""HMAC-authenticated TCP request/response services for launcher-time
coordination: task registration, ring-wise NIC reachability probing, and
remote command execution.

Parity surface (behavior, not code) with the reference launcher's probe
plane:

- secret key + HMAC-SHA256 digest framing — ``run/common/util/secret.py:26-36``
- ``Wire`` message format (digest | length | pickled body) —
  ``run/common/util/network.py`` ``Wire`` class
- driver service collecting per-task addresses and host hashes, task
  services pinged ring-wise with *interface matching* to weed out NAT'ed /
  unroutable interfaces — ``run/driver/driver_service.py``,
  ``run/task/task_service.py``, ``run/task_fn.py:1-67``

The TPU-native deviation: on TPU pods the launcher usually already knows the
topology from slice metadata (``launcher.tpu_pod_allocation``), so this
probe plane is only engaged for the generic multi-host ssh path, and the
discovered interface set is exported as ``HOROVOD_IFACE`` for the
rendezvous/control plane rather than feeding an MPI ``-mca btl_tcp_if``
flag.
"""

from __future__ import annotations

import concurrent.futures
import hashlib
import hmac
import logging
import os
import pickle
import socket
import socketserver
import struct
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .. import metrics as _metrics
from ..fault import injector as _fault
from ..fault.backoff import Backoff, retry_call

logger = logging.getLogger("horovod_tpu.run")

SECRET_LENGTH = 32
DIGEST_LENGTH = 32
SECRET_ENV = "HOROVOD_SECRET_KEY"
MAX_MESSAGE_BYTES = 64 * 1024 * 1024
# Server-side wait window for rendezvous phases that block on a peer
# (replaces the old hardcoded 60 s); see common/env.py.
COORD_WAIT_TIMEOUT_ENV = "HOROVOD_COORD_WAIT_TIMEOUT_S"


def coord_wait_timeout(default: float = 60.0) -> float:
    try:
        return float(os.environ.get(COORD_WAIT_TIMEOUT_ENV, "") or default)
    except ValueError:
        return default


def make_secret_key() -> bytes:
    return os.urandom(SECRET_LENGTH)


def compute_digest(key: bytes, message: bytes) -> bytes:
    return hmac.new(key, message, hashlib.sha256).digest()


def check_digest(key: bytes, message: bytes, digest: bytes) -> bool:
    return hmac.compare_digest(compute_digest(key, message), digest)


def encode_key(key: bytes) -> str:
    return key.hex()


def decode_key(text: str) -> bytes:
    return bytes.fromhex(text)


class WireError(Exception):
    """Digest mismatch or malformed frame."""


class Wire:
    """digest(32) | body_len(4, network order) | pickled body.

    Every frame is authenticated with HMAC-SHA256 before unpickling — an
    unauthenticated peer cannot reach the pickle layer.
    """

    def __init__(self, key: bytes):
        self._key = key

    def write(self, obj: Any, wfile) -> None:
        body = pickle.dumps(obj)
        wfile.write(compute_digest(self._key, body))
        wfile.write(struct.pack("!I", len(body)))
        wfile.write(body)
        wfile.flush()

    def read(self, rfile) -> Any:
        digest = _read_exact(rfile, DIGEST_LENGTH)
        (length,) = struct.unpack("!I", _read_exact(rfile, 4))
        if length > MAX_MESSAGE_BYTES:
            raise WireError(f"frame too large: {length} bytes")
        body = _read_exact(rfile, length)
        if not check_digest(self._key, body, digest):
            raise WireError("security error: digest did not match the message")
        return pickle.loads(body)


def _read_exact(rfile, n: int) -> bytes:
    chunks = []
    remaining = n
    while remaining:
        chunk = rfile.read(remaining)
        if not chunk:
            raise EOFError("connection closed mid-frame")
        chunks.append(chunk)
        remaining -= len(chunk)
    return b"".join(chunks)


# --- messages -------------------------------------------------------------


class PingRequest:
    pass


@dataclass
class PingResponse:
    service_name: str
    source_address: str


@dataclass
class AckResponse:
    pass


@dataclass
class RegisterTaskRequest:
    index: int
    addresses: Dict[str, List[Tuple[str, int]]]
    host_hash: str


@dataclass
class AllTaskAddressesRequest:
    index: int


@dataclass
class AllTaskAddressesResponse:
    addresses: Dict[str, List[Tuple[str, int]]]


@dataclass
class RegisterTaskToTaskAddressesRequest:
    index: int
    addresses: Dict[str, List[Tuple[str, int]]]


@dataclass
class AddressCheckFinishedSignal:
    index: int


@dataclass
class RunCommandRequest:
    command: str
    env: Dict[str, str]


@dataclass
class CommandExitCodeRequest:
    pass


@dataclass
class CommandExitCodeResponse:
    terminated: bool
    exit_code: Optional[int]


@dataclass
class ErrorResponse:
    """Structured server-side failure: the handler's error travels back to
    the client instead of dying as a silent EOF (the client would
    otherwise fail over to other addresses and eventually report the
    wrong thing)."""

    message: str
    kind: str = "error"  # "error" | "timeout"


class NoValidAddressesFound(Exception):
    pass


class RPCUnavailableError(ConnectionError):
    """A control-plane RPC endpoint could not be reached within the retry
    budget. Subclasses ConnectionError so existing transport-failure
    handling still matches, while the message names the endpoints, how
    long they have been failing across consecutive sends, and the retry
    budget spent. Raised ``from`` the final transport error instead of
    rebuilding it — reconstructing an OSError subclass from a bare
    string loses ``errno`` (and would TypeError on exception types
    without a one-string constructor)."""


class RemoteTimeoutError(RuntimeError):
    """A rendezvous phase timed out ON THE SERVER (e.g. a peer task never
    registered). Deliberately not an OSError/TimeoutError: the server
    already waited out the configured window, so the client-side retry
    budget must NOT spin on it."""


# --- interface enumeration ------------------------------------------------


def local_addresses(nic: Optional[str] = None) -> Dict[str, List[Tuple[str, int]]]:
    """Map interface name → [(ipv4_addr, port)] for a given bound port.

    Port is filled in by the service; this returns addr stubs with port 0.
    Mirrors the psutil enumeration the reference services use to advertise
    every candidate interface; falls back to an ioctl(SIOCGIFADDR)
    enumeration when psutil is absent (it is not a hard dependency).
    """
    result: Dict[str, List[Tuple[str, int]]] = {}
    try:
        import psutil

        for intf, addrs in psutil.net_if_addrs().items():
            if nic and intf != nic:
                continue
            for a in addrs:
                if a.family == socket.AF_INET:
                    result.setdefault(intf, []).append((a.address, 0))
        return result
    except ImportError:
        pass
    import fcntl

    for _, intf in socket.if_nameindex():
        if nic and intf != nic:
            continue
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            try:
                packed = fcntl.ioctl(
                    s.fileno(),
                    0x8915,  # SIOCGIFADDR
                    struct.pack("256s", intf.encode()[:15]),
                )
                addr = socket.inet_ntoa(packed[20:24])
                result.setdefault(intf, []).append((addr, 0))
            except OSError:
                continue  # interface without an IPv4 address
    return result


# --- services -------------------------------------------------------------


class BasicService:
    """Threaded TCP server answering one authenticated request per
    connection. Subclasses extend ``_handle``."""

    def __init__(self, service_name: str, key: bytes, nic: Optional[str] = None):
        self._service_name = service_name
        self._wire = Wire(key)
        self._nic = nic
        service = self

        class _Handler(socketserver.StreamRequestHandler):
            def handle(self):
                try:
                    req = service._wire.read(self.rfile)
                    try:
                        resp = service._handle(req, self.client_address)
                        if resp is None:
                            raise RuntimeError("handler returned no response")
                    except TimeoutError as exc:
                        # A phase timeout is an ANSWER, not a dropped
                        # connection: ship it back so the client can name
                        # the phase and the missing peers.
                        resp = ErrorResponse(str(exc), kind="timeout")
                    service._wire.write(resp, self.wfile)
                except (EOFError, WireError):
                    pass  # unauthenticated / truncated client; drop quietly

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._cond = threading.Condition()
        self._server = _Server(("0.0.0.0", 0), _Handler)
        self._port = self._server.socket.getsockname()[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        # NOT started here: a request racing in before a subclass finished
        # initializing its own state would crash the handler. Subclass
        # __init__ (or the creator, for a bare BasicService) calls start().

    def start(self) -> None:
        if not self._thread.is_alive():
            self._thread.start()

    @property
    def port(self) -> int:
        return self._port

    def addresses(self) -> Dict[str, List[Tuple[str, int]]]:
        out = {}
        for intf, addrs in local_addresses(self._nic).items():
            out[intf] = [(a, self._port) for a, _ in addrs]
        return out

    def _handle(self, req: Any, client_address) -> Any:
        if isinstance(req, PingRequest):
            return PingResponse(self._service_name, client_address[0])
        raise RuntimeError(
            f"{self._service_name}: unknown request {type(req).__name__}"
        )

    def shutdown(self) -> None:
        if self._thread.is_alive():
            # socketserver.shutdown() blocks on an event that only
            # serve_forever() sets — calling it on a never-started server
            # would deadlock; just close the socket in that case.
            self._server.shutdown()
        self._server.server_close()
        if self._thread.is_alive():
            self._thread.join(timeout=5)


class BasicClient:
    """Connects to the first reachable advertised address; with
    ``match_intf=True`` keeps only interfaces whose service-visible source
    address proves a working route (the reference's NAT-weeding check)."""

    def __init__(
        self,
        service_name: str,
        addresses: Dict[str, List[Tuple[str, int]]],
        key: bytes,
        match_intf: bool = False,
        retries: int = 3,
        timeout: float = 5.0,
    ):
        self._service_name = service_name
        self._wire = Wire(key)
        self._timeout = timeout
        # Control-plane RPC retry budget (HOROVOD_RPC_* knobs): a dropped
        # or delayed message costs one backoff, not the job.
        self._backoff = Backoff.from_env()
        # First instant of the current consecutive-failure streak: a
        # dead peer reads as "endpoint down for Ns", not a bare error.
        self._down_since: Optional[float] = None
        self._addresses = self._probe(addresses, match_intf, retries)
        if not self._addresses:
            raise NoValidAddressesFound(
                f"no usable address for {service_name!r} among {addresses}"
            )

    def _endpoints(self) -> str:
        """Compact 'host:port' list of the verified addresses, for error
        messages (which endpoint was actually dialed and found dead)."""
        flat = sorted({
            f"{a}:{p}" for addrs in self._addresses.values()
            for a, p in addrs
        })
        return ",".join(flat) or "<no-verified-address>"

    def addresses(self) -> Dict[str, List[Tuple[str, int]]]:
        return self._addresses

    def _probe(self, addresses, match_intf: bool, retries: int):
        """Probe every advertised address concurrently so one dead NIC
        (the exact case match_intf exists to weed out) costs max-over-
        addresses wall-clock, not sum — sequential retries x 5s against
        two unroutable interfaces would blow the callers' 60s barriers."""
        local = local_addresses() if match_intf else {}
        # Unreachable addresses time out on connect; a short connect
        # budget per attempt keeps the worst case well under the ring
        # barriers while reachable peers answer in milliseconds.
        probe_timeout = min(self._timeout, 2.0)

        def probe_one(intf, addr):
            for _ in range(retries):
                try:
                    resp = self._request(
                        PingRequest(), addr, connect_timeout=probe_timeout
                    )
                except (OSError, EOFError, WireError):
                    continue
                if not isinstance(resp, PingResponse):
                    continue
                if resp.service_name != self._service_name:
                    return False  # a different service answered; wrong port
                if match_intf:
                    # NAT weeding (reference network.py match_intf): the
                    # source address the *server* saw must belong to our
                    # own same-named interface — i.e. reaching the peer's
                    # intf X must route out of our intf X.
                    own = {a for a, _ in local.get(intf, [])}
                    if resp.source_address not in own:
                        return False
                return True
            return False

        flat = [(intf, addr) for intf, addrs in addresses.items()
                for addr in addrs]
        if not flat:
            return {}
        with concurrent.futures.ThreadPoolExecutor(
            max_workers=min(16, len(flat))
        ) as pool:
            results = list(pool.map(lambda ia: probe_one(*ia), flat))

        usable: Dict[str, List[Tuple[str, int]]] = {}
        for (intf, addr), ok in zip(flat, results):
            if ok:
                usable.setdefault(intf, []).append(addr)
        # Keep the verified subset even when some advertised addresses on
        # an interface failed (e.g. a stale alias): every address in
        # `usable` proved a working route, which is what callers need.
        return usable

    def _request(self, req: Any, addr: Tuple[str, int],
                 timeout: Optional[float] = None,
                 connect_timeout: Optional[float] = None) -> Any:
        if _fault.ACTIVE:
            # Chaos tap: a 'drop' here raises before the socket opens (a
            # lost request); retries re-enter the tap with a fresh hit
            # count, so bounded drop bursts are survivable by design.
            directive = _fault.fault_point("rpc", type(req).__name__)
        else:
            directive = None
        repeats = 2 if directive == "duplicate" else 1
        for _ in range(repeats):
            with socket.create_connection(
                addr,
                timeout=connect_timeout if connect_timeout is not None
                else self._timeout,
            ) as sock:
                # A request the server intentionally blocks on (e.g. the
                # driver's wait-for-peer-registration) needs a read window
                # longer than the connect default.
                sock.settimeout(timeout if timeout is not None else self._timeout)
                rfile = sock.makefile("rb")
                wfile = sock.makefile("wb")
                self._wire.write(req, wfile)
                resp = self._wire.read(rfile)
        if isinstance(resp, ErrorResponse):
            if resp.kind == "timeout":
                raise RemoteTimeoutError(resp.message)
            raise RuntimeError(resp.message)
        return resp

    def send(self, req: Any, timeout: Optional[float] = None) -> Any:
        """One authenticated request/response, sweeping every verified
        address, with bounded exponential-backoff retries around the whole
        sweep (``HOROVOD_RPC_RETRIES`` / ``HOROVOD_RPC_BACKOFF_*``)."""
        req_name = type(req).__name__
        if _metrics.ACTIVE:
            _metrics.TAP.inc("hvd_rpc_requests_total", request=req_name)

        def sweep() -> Any:
            last_err: Optional[Exception] = None
            for addrs in self._addresses.values():
                for addr in addrs:
                    try:
                        return self._request(req, addr, timeout=timeout)
                    except (OSError, EOFError, WireError) as e:
                        # EOF = server handler raised and closed without a
                        # response; try the remaining advertised addresses.
                        last_err = e
            raise last_err or NoValidAddressesFound(self._service_name)

        def on_retry(attempt, exc, delay):
            if _metrics.ACTIVE:
                _metrics.TAP.inc("hvd_rpc_retries_total", request=req_name)
            logger.warning(
                "%s: %s failed (%s); retry %d in %.2fs",
                self._service_name, req_name, exc, attempt + 1, delay,
            )

        import time as _time

        try:
            result = retry_call(
                sweep,
                retryable=(OSError, EOFError, WireError),
                backoff=self._backoff,
                describe=(
                    f"{self._service_name} at {self._endpoints()}: "
                    f"{req_name}"
                ),
                on_retry=on_retry,
            )
        except RemoteTimeoutError:
            self._down_since = None  # the server answered; it is up
            if _metrics.ACTIVE:
                _metrics.TAP.inc("hvd_rpc_timeouts_total", request=req_name)
            raise
        except (OSError, EOFError, WireError) as exc:
            now = _time.monotonic()
            if self._down_since is None:
                self._down_since = now
            if _metrics.ACTIVE:
                _metrics.TAP.inc("hvd_rpc_failures_total", request=req_name)
            raise RPCUnavailableError(
                f"{exc} [endpoint {self._endpoints()} failing for "
                f"{now - self._down_since:.1f}s; retry budget "
                f"{self._backoff.retries + 1} attempts spent]"
            ) from exc
        except Exception:
            if _metrics.ACTIVE:
                _metrics.TAP.inc("hvd_rpc_failures_total", request=req_name)
            raise
        self._down_since = None
        return result


class DriverService(BasicService):
    """Collects per-task registrations (addresses + host hash) and
    task→next-task verified addresses (``run/driver/driver_service.py``
    semantics)."""

    NAME = "horovod_tpu driver service"

    def __init__(self, num_tasks: int, key: bytes, nic: Optional[str] = None,
                 wait_timeout: Optional[float] = None):
        super().__init__(self.NAME, key, nic)
        self._num_tasks = num_tasks
        # Honors HOROVOD_COORD_WAIT_TIMEOUT_S (or the launcher-plumbed
        # value) instead of the old hardcoded 60 s.
        self._wait_timeout = (
            coord_wait_timeout() if wait_timeout is None else wait_timeout
        )
        self._task_addrs: Dict[int, Dict[str, List[Tuple[str, int]]]] = {}
        self._task_to_task_addrs: Dict[int, Dict[str, List[Tuple[str, int]]]] = {}
        self._host_hashes: Dict[int, str] = {}
        self.start()

    def _handle(self, req: Any, client_address) -> Any:
        if isinstance(req, RegisterTaskRequest):
            with self._cond:
                self._task_addrs[req.index] = req.addresses
                self._host_hashes[req.index] = req.host_hash
                self._cond.notify_all()
            return AckResponse()
        if isinstance(req, AllTaskAddressesRequest):
            with self._cond:
                ok = self._cond.wait_for(
                    lambda: req.index in self._task_addrs,
                    timeout=self._wait_timeout,
                )
                addrs = self._task_addrs.get(req.index)
                registered = sorted(self._task_addrs)
            if not ok or addrs is None:
                # Travels back to the asking task as an ErrorResponse —
                # it raises RemoteTimeoutError naming the phase and the
                # missing peer instead of silently proceeding.
                raise TimeoutError(
                    "rendezvous phase 'all-task-addresses' timed out "
                    f"after {self._wait_timeout:g}s: task {req.index} "
                    f"never registered (registered tasks: {registered})"
                )
            return AllTaskAddressesResponse(addrs)
        if isinstance(req, RegisterTaskToTaskAddressesRequest):
            with self._cond:
                self._task_to_task_addrs[req.index] = req.addresses
                self._cond.notify_all()
            return AckResponse()
        return super()._handle(req, client_address)

    def wait_for_initial_registration(self, timeout: Optional[float] = None) -> None:
        timeout = self._wait_timeout if timeout is None else timeout
        with self._cond:
            ok = self._cond.wait_for(
                lambda: len(self._task_addrs) >= self._num_tasks, timeout=timeout
            )
            missing = sorted(
                set(range(self._num_tasks)) - set(self._task_addrs)
            )
        if not ok:
            raise TimeoutError(
                "rendezvous phase 'initial-registration' timed out after "
                f"{timeout:g}s; tasks never registered: {missing}"
            )

    def wait_for_task_to_task_addresses(self, timeout: Optional[float] = None) -> None:
        timeout = self._wait_timeout if timeout is None else timeout
        with self._cond:
            ok = self._cond.wait_for(
                lambda: len(self._task_to_task_addrs) >= self._num_tasks,
                timeout=timeout,
            )
            missing = sorted(
                set(range(self._num_tasks)) - set(self._task_to_task_addrs)
            )
        if not ok:
            raise TimeoutError(
                "rendezvous phase 'ring-address-check' timed out after "
                f"{timeout:g}s; tasks that never reported verified "
                f"addresses: {missing}"
            )

    def task_addresses_for(self, index: int):
        with self._cond:
            return dict(self._task_addrs.get(index, {}))

    def host_hashes(self) -> Dict[int, str]:
        with self._cond:
            return dict(self._host_hashes)

    def common_interfaces(self) -> List[str]:
        """Interfaces proven routable on every ring hop — the intersection
        the reference computes in ``run/run.py:198-268``."""
        with self._cond:
            sets = [set(v.keys()) for v in self._task_to_task_addrs.values()]
        if not sets:
            return []
        common = set.intersection(*sets)
        return sorted(common)


class TaskService(BasicService):
    """Per-task probe service: answers pings (interface matching), relays
    the ring 'address check finished' signal, and can run a shell command
    on behalf of the driver (``run/common/service/task_service.py``
    semantics — used by the Spark integration's rsh agent)."""

    NAME_FORMAT = "horovod_tpu task service #%d"

    def __init__(self, index: int, key: bytes, nic: Optional[str] = None):
        super().__init__(self.NAME_FORMAT % index, key, nic)
        self.index = index
        self._check_finished = False
        self._command_exit: Optional[int] = None
        self._command_started = False
        self.start()

    def _handle(self, req: Any, client_address) -> Any:
        if isinstance(req, AddressCheckFinishedSignal):
            with self._cond:
                self._check_finished = True
                self._cond.notify_all()
            return AckResponse()
        if isinstance(req, RunCommandRequest):
            self._start_command(req.command, req.env)
            return AckResponse()
        if isinstance(req, CommandExitCodeRequest):
            with self._cond:
                return CommandExitCodeResponse(
                    terminated=self._command_started
                    and self._command_exit is not None,
                    exit_code=self._command_exit,
                )
        return super()._handle(req, client_address)

    def _start_command(self, command: str, env: Dict[str, str]) -> None:
        from . import safe_shell_exec

        def _run():
            # ManagedProcess directly: safe_shell_exec.execute() installs
            # signal handlers, which is main-thread-only.
            mp = safe_shell_exec.ManagedProcess(
                command, env={**os.environ, **env}, shell=True
            )
            code = mp.wait()
            with self._cond:
                self._command_exit = code
                self._cond.notify_all()

        with self._cond:
            self._command_started = True
        threading.Thread(target=_run, daemon=True).start()

    def wait_for_address_check_finished(self, timeout: float = 60.0) -> None:
        with self._cond:
            ok = self._cond.wait_for(lambda: self._check_finished, timeout=timeout)
        if not ok:
            raise TimeoutError(f"task {self.index}: ring check signal missing")

    def wait_for_command_exit(self, timeout: Optional[float] = None) -> int:
        with self._cond:
            ok = self._cond.wait_for(
                lambda: self._command_exit is not None, timeout=timeout
            )
        if not ok:
            raise TimeoutError("command did not terminate")
        return int(self._command_exit)  # type: ignore[arg-type]


class DriverClient(BasicClient):
    def __init__(self, addresses, key, match_intf: bool = False, retries: int = 3):
        super().__init__(DriverService.NAME, addresses, key, match_intf, retries)

    def register_task(self, index, addresses, host_hash) -> None:
        self.send(RegisterTaskRequest(index, addresses, host_hash))

    def all_task_addresses(self, index):
        # The driver blocks up to its configured wait window for the peer
        # to register (slow ssh spawn); the read window must outlast it.
        return self.send(
            AllTaskAddressesRequest(index),
            timeout=coord_wait_timeout() + 5.0,
        ).addresses

    def register_task_to_task_addresses(self, index, addresses) -> None:
        self.send(RegisterTaskToTaskAddressesRequest(index, addresses))


class TaskClient(BasicClient):
    def __init__(self, index, addresses, key, match_intf=False, retries=3):
        super().__init__(
            TaskService.NAME_FORMAT % index, addresses, key, match_intf, retries
        )
        self.index = index

    def signal_address_check_finished(self) -> None:
        self.send(AddressCheckFinishedSignal(self.index))

    def run_command(self, command: str, env: Dict[str, str]) -> None:
        self.send(RunCommandRequest(command, env))

    def command_exit_code(self) -> CommandExitCodeResponse:
        return self.send(CommandExitCodeRequest())


def host_hash() -> str:
    """Stable identifier grouping tasks that share a host (the reference
    hashes hostname; same-hash tasks share local_rank space)."""
    return hashlib.md5(socket.gethostname().encode()).hexdigest()


def run_task_probe(
    index: int,
    num_tasks: int,
    driver_addresses: Dict[str, List[Tuple[str, int]]],
    key: bytes,
    nic: Optional[str] = None,
    timeout: float = 60.0,
) -> None:
    """One task's side of the ring NIC probe (``run/task_fn.py:23-53``):
    register with the driver, ping the next task with interface matching,
    report the verified addresses, pass the baton."""
    task = TaskService(index, key, nic)
    try:
        driver = DriverClient(driver_addresses, key)
        driver.register_task(index, task.addresses(), host_hash())
        next_index = (index + 1) % num_tasks
        next_addresses = driver.all_task_addresses(next_index)
        next_task = TaskClient(
            next_index, next_addresses, key, match_intf=True, retries=10
        )
        driver.register_task_to_task_addresses(
            next_index, next_task.addresses()
        )
        next_task.signal_address_check_finished()
        task.wait_for_address_check_finished(timeout)
    finally:
        task.shutdown()


def interface_address(name: str) -> Optional[str]:
    """First IPv4 address bound to interface ``name`` (None if absent)."""
    addrs = local_addresses(name).get(name)
    return addrs[0][0] if addrs else None


def discover_common_interfaces(
    hosts: Sequence[str],
    *,
    key: Optional[bytes] = None,
    ssh_launch=None,
    ssh_port: Optional[int] = None,
    timeout: float = 60.0,
    return_addresses: bool = False,
) -> Any:
    """Driver-side orchestration: start a DriverService, launch one probe
    task per host (via ``ssh_launch(host, command_argv, env)`` or locally),
    and return the interface names routable around the whole ring.

    With ``return_addresses=True`` also returns each host's registered
    per-interface addresses, ``{host: {intf: [(addr, port), ...]}}`` —
    the launcher uses these to dial rank 0's controller by its probed
    routable address rather than its (possibly unresolvable) hostname."""
    import subprocess
    import sys

    key = key or make_secret_key()
    driver = DriverService(len(hosts), key, wait_timeout=timeout)
    procs = []
    try:
        addrs = driver.addresses()
        for i, host in enumerate(hosts):
            argv = [
                sys.executable,
                "-m",
                "horovod_tpu.run.probe",
                str(i),
                str(len(hosts)),
            ]
            env = {
                **os.environ,
                SECRET_ENV: encode_key(key),
                "HOROVOD_PROBE_DRIVER_ADDRS": repr_addresses(addrs),
            }
            from .launcher import _is_local

            if _is_local(host):
                procs.append(subprocess.Popen(argv, env=env))
            elif ssh_launch is not None:
                procs.append(ssh_launch(host, argv, env))
            else:
                import shlex

                # The secret is shipped over ssh's stdin, never on the
                # command line — argv is visible to every user via ps.
                env_str = " ".join(
                    f"{k}={shlex.quote(v)}"
                    for k, v in env.items()
                    if k != SECRET_ENV
                    and k.startswith(("HOROVOD_", "PATH", "PYTHONPATH"))
                )
                remote = (
                    f"IFS= read -r _HVDKEY; {env_str} {SECRET_ENV}=\"$_HVDKEY\" "
                    f"{' '.join(shlex.quote(a) for a in argv)}"
                )
                from .launcher import ssh_base_cmd

                p = subprocess.Popen(
                    ssh_base_cmd(host, ssh_port) + [remote],
                    stdin=subprocess.PIPE,
                )
                p.stdin.write((encode_key(key) + "\n").encode())
                p.stdin.close()
                procs.append(p)
        driver.wait_for_initial_registration(timeout)
        driver.wait_for_task_to_task_addresses(timeout)
        common = driver.common_interfaces()
        if return_addresses:
            host_addrs = {
                host: driver.task_addresses_for(i)
                for i, host in enumerate(hosts)
            }
            return common, host_addrs
        return common
    finally:
        deadline = 3.0  # grace for clean exits, shared across all procs
        import time as _time

        t0 = _time.monotonic()
        for p in procs:
            remaining = max(0.0, deadline - (_time.monotonic() - t0))
            try:
                p.wait(timeout=remaining)
            except Exception:
                p.kill()
        for p in procs:
            try:
                p.wait(timeout=5)  # reap; no zombies for the driver lifetime
            except Exception:
                pass
        driver.shutdown()


def repr_addresses(addrs: Dict[str, List[Tuple[str, int]]]) -> str:
    return ";".join(
        f"{intf}={','.join(f'{a}:{p}' for a, p in lst)}"
        for intf, lst in addrs.items()
    )


def parse_addresses(text: str) -> Dict[str, List[Tuple[str, int]]]:
    out: Dict[str, List[Tuple[str, int]]] = {}
    for part in filter(None, text.split(";")):
        intf, _, rest = part.partition("=")
        for item in filter(None, rest.split(",")):
            host, _, port = item.rpartition(":")
            out.setdefault(intf, []).append((host, int(port)))
    return out
