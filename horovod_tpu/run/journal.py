"""Durable control-plane journal for the elastic driver.

Everything the :class:`~horovod_tpu.run.elastic_driver.ElasticDriver`
needs to survive its own death lives ONLY in that process's memory: the
generation counter, world membership, blacklist/cooldown state, and the
rendezvous-critical keys of the HTTP KV store. This module write-ahead
journals that state to disk so ``horovodrun --resume`` (or a supervisor)
can replay it, rebind the rendezvous port, and re-enter the elastic loop
at the recorded generation instead of respawning an otherwise-healthy
fleet (docs/fault_tolerance.md "Control-plane availability").

Disciplines:

- **Atomicity** — every journal write goes through the same same-dir
  tmp + ``fsync`` + ``os.replace`` pattern as ``utils/checkpoint.py``:
  a driver killed mid-write leaves the previous complete journal, never
  a torn one. Replay is a pure function of the journal bytes, so
  resuming twice from the same journal yields identical state (the
  idempotence the chaos suite asserts).
- **Epoch fencing** — the journal carries a monotonically-increasing
  *driver epoch*. Every open of an existing journal (resume or not)
  bumps it, and the live driver advertises it on the KV plane
  (``elastic/driver``); workers reject any driver presenting an epoch
  LOWER than one they have already seen, so a stale driver that lost a
  supervisor race can never re-capture a fleet its successor owns.
- **Monotonic-safe deadlines** — blacklist quarantines are tracked on
  the monotonic clock in memory (immune to NTP steps) but serialized as
  absolute wall-clock deadlines PLUS the remaining quarantine at write
  time. Restore trusts the wall deadline only up to that remaining
  budget: a resume on a backwards-skewed clock cannot re-extend a
  quarantine, and a forwards skew (or genuine elapsed downtime) expires
  it — a resumed driver neither re-quarantines healthy hosts nor
  forgets active quarantines.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional

JOURNAL_BASENAME = "driver_journal.json"
JOURNAL_ENV = "HOROVOD_DRIVER_JOURNAL"

# Journal schema version: replay refuses documents from the future so a
# downgraded driver fails loudly instead of resuming with half a state.
# v2 (self-driving fleet, docs/fault_tolerance.md "Self-driving fleet")
# adds the slowness-quarantine ledger (``slow_strikes``,
# ``blacklist_reasons``), the published re-plan notice (``replan``), and
# the hot-spare pool (``spare_ids``).
_VERSION = 2

# Record keys introduced by v2. A document that CLAIMS an older version
# while carrying them is mixed state (e.g. an operator splicing new
# records into an old journal, or a partial downgrade-then-upgrade):
# replay refuses it loudly rather than silently dropping — or silently
# trusting — the new records.
_V2_KEYS = ("slow_strikes", "blacklist_reasons", "replan", "spare_ids")


def default_path(output_dir: Optional[str],
                 env: Optional[Dict[str, str]] = None) -> Optional[str]:
    """Journal location: explicit ``HOROVOD_DRIVER_JOURNAL`` wins, else
    the driver's ``--output-dir`` (where the rest of the postmortem
    artifacts live), else journaling is disabled (None)."""
    e = env if env is not None else os.environ
    explicit = e.get(JOURNAL_ENV, "").strip()
    if explicit:
        return explicit
    if output_dir:
        return os.path.join(output_dir, JOURNAL_BASENAME)
    return None


def _atomic_write_bytes(path: str, data: bytes) -> None:
    """Same discipline as utils/checkpoint.py: readers see the complete
    old document or the complete new one, never a torn journal."""
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


# ------------------------------------------------- blacklist (de)serialization
def blacklist_to_journal(
    blacklist: Dict[str, Optional[float]],
    *,
    now_mono: Optional[float] = None,
    now_wall: Optional[float] = None,
) -> Dict[str, Dict[str, Any]]:
    """Serialize monotonic quarantine deadlines as absolute wall-clock
    deadlines plus the remaining quarantine at write time (the clamp
    restore needs to be skew-safe). ``None`` deadlines (permanent
    blacklist) survive as-is."""
    now_mono = time.monotonic() if now_mono is None else now_mono
    now_wall = time.time() if now_wall is None else now_wall
    out: Dict[str, Dict[str, Any]] = {}
    for host, deadline in blacklist.items():
        if deadline is None:
            out[host] = {"permanent": True}
        else:
            remaining = max(0.0, deadline - now_mono)
            out[host] = {
                "deadline_unix": now_wall + remaining,
                "remaining_s": remaining,
            }
    return out


def blacklist_from_journal(
    doc: Dict[str, Dict[str, Any]],
    *,
    now_mono: Optional[float] = None,
    now_wall: Optional[float] = None,
) -> Dict[str, Optional[float]]:
    """Restore quarantine deadlines onto THIS process's monotonic clock.

    The wall-clock deadline is trusted only up to the remaining budget
    recorded at write time: ``remaining = clamp(deadline - now_wall,
    0, remaining_at_write)``. A clock skewed backwards across the
    restart (deadline appears far in the future) cannot quarantine a
    host for longer than it had left; a clock skewed forwards — or real
    elapsed downtime — shortens or expires it, which is the correct
    reading (the host served its time while the driver was down).
    Entries restored at zero remaining are dropped (re-admitted), never
    re-quarantined."""
    now_mono = time.monotonic() if now_mono is None else now_mono
    now_wall = time.time() if now_wall is None else now_wall
    out: Dict[str, Optional[float]] = {}
    for host, entry in doc.items():
        if entry.get("permanent"):
            out[host] = None
            continue
        try:
            deadline_unix = float(entry["deadline_unix"])
            budget = max(0.0, float(entry.get("remaining_s", 0.0)))
        except (KeyError, TypeError, ValueError):
            continue  # malformed entry: re-admit rather than wedge resume
        remaining = min(max(0.0, deadline_unix - now_wall), budget)
        if remaining > 0.0:
            out[host] = now_mono + remaining
    return out


class DriverJournal:
    """One JSON document, atomically rewritten on every control-plane
    state transition (generation publish, blacklist change, KV-scope
    change, epoch bump). ``replay()`` is side-effect free and pure in
    the journal bytes."""

    def __init__(self, path: str):
        self.path = path
        self._state: Dict[str, Any] = {"version": _VERSION, "epoch": 0}
        self.writes = 0

    # ------------------------------------------------------------- open
    @staticmethod
    def open(path: str) -> "DriverJournal":
        """Open (and fence) the journal at ``path``: any recorded epoch
        is bumped — whether this is a resume or a fresh job reusing the
        directory — so the new driver's epoch is strictly greater than
        every driver that ever wrote this journal. The bump is persisted
        immediately (write-ahead: the fence must be durable before the
        driver advertises itself)."""
        j = DriverJournal(path)
        prior = j.replay()
        if prior is not None:
            j._state = dict(prior)
        j._state["epoch"] = int(j._state.get("epoch", 0)) + 1
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        j._write()
        return j

    # ----------------------------------------------------------- replay
    def replay(self) -> Optional[Dict[str, Any]]:
        """Parse the journal from disk; None when absent or unreadable
        (a torn write is impossible by construction, but an operator-
        truncated file degrades to a fresh start, loudly at the
        caller)."""
        try:
            with open(self.path, "rb") as f:
                doc = json.loads(f.read().decode("utf-8"))
        except (OSError, ValueError):
            return None
        if not isinstance(doc, dict):
            return None
        version = int(doc.get("version", 0))
        if version > _VERSION:
            raise RuntimeError(
                f"driver journal {self.path} is version "
                f"{doc.get('version')} but this build understands "
                f"<= {_VERSION}; refusing to resume with partial state"
            )
        if version < 2:
            present = sorted(k for k in _V2_KEYS if k in doc)
            if present:
                raise RuntimeError(
                    f"driver journal {self.path} claims version "
                    f"{version} but carries v2 records {present}; the "
                    "document is mixed state — refusing to resume "
                    "rather than silently dropping the newer records"
                )
        return doc

    # ----------------------------------------------------------- record
    @property
    def epoch(self) -> int:
        return int(self._state.get("epoch", 0))

    @property
    def state(self) -> Dict[str, Any]:
        return dict(self._state)

    def record(self, **updates: Any) -> None:
        """Merge ``updates`` into the journal state and persist
        atomically. This is the write-ahead point: callers journal a
        transition BEFORE exposing it to workers (KV publish), so a
        crash between the two replays a state the fleet has not yet
        outrun."""
        self._state.update(updates)
        self._write()

    def _write(self) -> None:
        self._state["version"] = _VERSION
        self._state["written_unix"] = time.time()
        data = json.dumps(
            self._state, sort_keys=True, separators=(",", ":")
        ).encode("utf-8")
        _atomic_write_bytes(self.path, data)
        self.writes += 1
