from .run import run, run_commandline, parse_args, check_build  # noqa: F401
