"""CLI/YAML config → HOROVOD_* env mapping.

Role parity with the reference's ``run/common/util/config_parser.py``: all
three config surfaces (env vars, CLI flags, YAML file) converge on the same
``HOROVOD_*`` env names read at init, with CLI taking precedence over YAML.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

# arg attribute name -> env var
ARG_TO_ENV = {
    "fusion_threshold_mb": "HOROVOD_FUSION_THRESHOLD",
    "cycle_time_ms": "HOROVOD_CYCLE_TIME",
    "cache_capacity": "HOROVOD_CACHE_CAPACITY",
    "hierarchical_allreduce": "HOROVOD_HIERARCHICAL_ALLREDUCE",
    "hierarchical_allgather": "HOROVOD_HIERARCHICAL_ALLGATHER",
    "autotune": "HOROVOD_AUTOTUNE",
    "autotune_log_file": "HOROVOD_AUTOTUNE_LOG",
    "autotune_warmup_samples": "HOROVOD_AUTOTUNE_WARMUP_SAMPLES",
    "autotune_steps_per_sample": "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE",
    "autotune_bayes_opt_max_samples": "HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES",
    "autotune_gaussian_process_noise": "HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE",
    "timeline_filename": "HOROVOD_TIMELINE",
    "timeline_mark_cycles": "HOROVOD_TIMELINE_MARK_CYCLES",
    "stall_check_disable": "HOROVOD_STALL_CHECK_DISABLE",
    "stall_check_time_seconds": "HOROVOD_STALL_CHECK_TIME_SECONDS",
    "stall_shutdown_time_seconds": "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS",
    "log_level": "HOROVOD_LOG_LEVEL",
    "start_timeout": "HOROVOD_START_TIMEOUT",
    "mesh_axes": "HOROVOD_TPU_MESH_AXES",
}

# YAML section/key -> arg attribute (reference config file layout).
_YAML_MAP = {
    ("fusion", "threshold-mb"): "fusion_threshold_mb",
    ("fusion", "cycle-time-ms"): "cycle_time_ms",
    ("cache", "capacity"): "cache_capacity",
    ("hierarchy", "allreduce"): "hierarchical_allreduce",
    ("hierarchy", "allgather"): "hierarchical_allgather",
    ("autotune", "enabled"): "autotune",
    ("autotune", "log-file"): "autotune_log_file",
    ("autotune", "warmup-samples"): "autotune_warmup_samples",
    ("autotune", "steps-per-sample"): "autotune_steps_per_sample",
    ("autotune", "bayes-opt-max-samples"): "autotune_bayes_opt_max_samples",
    ("autotune", "gaussian-process-noise"): "autotune_gaussian_process_noise",
    ("timeline", "filename"): "timeline_filename",
    ("timeline", "mark-cycles"): "timeline_mark_cycles",
    ("stall-check", "disable"): "stall_check_disable",
    ("stall-check", "warning-time-seconds"): "stall_check_time_seconds",
    ("stall-check", "shutdown-time-seconds"): "stall_shutdown_time_seconds",
    ("logging", "level"): "log_level",
    ("tpu", "mesh-axes"): "mesh_axes",
}


def parse_config_file(path: str, args, overridden: set) -> None:
    """Apply YAML values to args for every attribute the CLI didn't
    explicitly set (CLI > YAML > defaults, as in the reference)."""
    import yaml

    with open(path) as f:
        doc = yaml.safe_load(f) or {}
    for (section, key), attr in _YAML_MAP.items():
        if attr in overridden:
            continue
        sec = doc.get(section)
        if isinstance(sec, dict) and key in sec:
            setattr(args, attr, sec[key])


def set_env_from_args(env: Dict[str, str], args) -> Dict[str, str]:
    for attr, env_name in ARG_TO_ENV.items():
        value = getattr(args, attr, None)
        # Precise unset test: numeric 0 is a VALID setting (e.g.
        # --cache-capacity 0 disables the cache); `in (None, False, "")`
        # would silently drop it (0 == False).
        if value is None or value is False or value == "":
            continue
        if attr == "fusion_threshold_mb":
            value = int(value) * 1024 * 1024
        if value is True:
            value = "1"
        env[env_name] = str(value)
    return env
