"""horovod_tpu.jax — the compiled-mode (performance-path) binding.

Where the reference's framework bindings enqueue per-tensor async ops into a
background loop (``horovod/tensorflow/__init__.py``,
``horovod/torch/__init__.py``), the TPU-native compiled mode moves the whole
reduction *inside* the jitted training step: gradients are bucket-fused at
trace time and reduced with single large XLA collectives over a named mesh
axis. This keeps Horovod's semantics (``DistributedOptimizer`` wrapping an
inner optimizer, Average/Sum/Adasum ops, fp16/bf16 compression) while letting
XLA overlap the collectives with backprop on ICI.

Typical use::

    import horovod_tpu.jax as hvd

    mesh = hvd.build_mesh()                 # one "data" axis over all chips
    tx = hvd.DistributedOptimizer(optax.sgd(0.01))
    step = hvd.make_train_step(loss_fn, tx, mesh)
    params = hvd.broadcast_variables(params, mesh)     # rank-0 state
    params, opt_state, loss = step(params, opt_state, batch)
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..common.compression import Compression
from ..common.types import Adasum, Average, ReduceOp, Sum
from ..guard import nonfinite as _nf
from ..guard import resolve_policy as _resolve_nonfinite
from ..ops import collectives as _c
from ..ops import fusion as _fusion
from ..ops.adasum import adasum_reduce_fn
from ..parallel.mesh import (
    CROSS_AXIS,
    DATA_AXIS,
    LOCAL_AXIS,
    POD_AXIS,
    build_hierarchical_mesh,
    build_mesh,
    build_three_level_mesh,
    hierarchy_axes,
)

_logger = logging.getLogger("horovod_tpu")

# Compiled-mode users reach collectives through jit, never through hvd.init;
# the perf-preset flags must land in XLA_FLAGS before the first backend
# touch, so the resolver runs at import (idempotent; "auto" is off-platform
# safe — it only adds TPU flags when a TPU platform is hinted).
from ..common import env as _env_mod  # noqa: E402

try:
    _env_mod.apply_xla_perf_preset()
except Exception:  # noqa: BLE001 - preset application must never block import
    pass

def _shard_map(fn, mesh, *, in_specs, out_specs, check: bool = False):
    """shard_map with version compatibility (check_vma in jax>=0.7,
    check_rep before; module moved from jax.experimental to jax core).

    ``check=True`` enables replication/varying-ness tracking — REQUIRED
    when differentiating through an in-body ``psum`` (e.g. the tensor-
    parallel row-parallel matmul): without it the psum transpose cannot
    see that the cotangent is replicated and multiplies gradients by the
    axis size. The default stays off for the collective-executor bodies,
    whose hand-written patterns predate the vma checker.
    """
    try:
        from jax import shard_map as _sm
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map as _sm
    try:
        return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_vma=check)
    except TypeError:  # pragma: no cover - older jax
        return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=check)


# In-jit primitives (usable inside shard_map/pmap bodies).
allreduce = _c.allreduce
allgather = _c.allgather
broadcast = _c.broadcast
alltoall = _c.alltoall
reducescatter = _c.reducescatter
hierarchical_allreduce = _c.hierarchical_allreduce
hierarchical_allgather = _c.hierarchical_allgather
hierarchical_reducescatter = _c.hierarchical_reducescatter
hierarchical_broadcast = _c.hierarchical_broadcast
hierarchical_alltoall = _c.hierarchical_alltoall

# Streamed (overlap) gradient reduction: register a parameter subtree (or a
# scanned layer stack's body) so its gradients are bucket-allreduced INSIDE
# the backward pass — see ops/fusion.py and docs/overlap.md.
reduce_in_backward = _fusion.reduce_in_backward
stream_scan_body = _fusion.stream_scan_body
stream_param_groups = _fusion.stream_param_groups


def collective_plan(collective: str = "allreduce",
                    nbytes: int = 4 * 1024 * 1024,
                    op: Optional[ReduceOp] = None) -> dict:
    """Compiled-mode alias of :func:`horovod_tpu.collective_plan` —
    the topology compositor's selected plan for one collective at one
    payload size (docs/topology.md)."""
    from .. import collective_plan as _cp

    return _cp(collective, nbytes, op)


def _resolve_hierarchical(hierarchical, mesh: Optional[Mesh] = None):
    """Resolve the tri-state ``hierarchical`` knob (docs/topology.md):

    - ``False`` / ``True`` pass through (True = the forced two-level
      lowering, reference parity).
    - ``"auto"`` consults the topology compositor: with a mesh, the
      hierarchy axes the caller built decide (a deliberate (pod,) cross,
      local grid -> per-bucket plan selection; a flat data mesh -> flat);
      without one, the detected process topology's homogeneity-gated
      model decides.

    Returns ``(mode, axes)`` where mode is False / True / "planned" and
    axes is the hierarchy axis tuple for planned mode (None otherwise).
    """
    if hierarchical == "auto":
        if mesh is not None:
            axes = hierarchy_axes(mesh)
            if axes:
                return "planned", axes
            return False, None
        from ..topo import resolve_model

        if resolve_model().eligible:
            return "planned", (CROSS_AXIS, LOCAL_AXIS)
        return False, None
    if hierarchical == "planned":
        return "planned", None
    return bool(hierarchical), None


def _select_reduce_fn(op: ReduceOp, hierarchical):
    if op == ReduceOp.ADASUM:
        return adasum_reduce_fn
    if hierarchical == "planned":
        from ..topo import compositor as _compositor

        return _compositor.auto_reduce_fn()
    if hierarchical:
        # axis_name must be the (cross, local) tuple: reduce-scatter rides
        # ICI (local), the shard psum rides DCN (cross).
        def fn(x, *, op, axis_name, prescale_factor=1.0, postscale_factor=1.0):
            cross_axis, local_axis = axis_name
            if prescale_factor != 1.0:
                x = x * prescale_factor
            out = _c.hierarchical_allreduce(
                x, op=op, local_axis=local_axis, cross_axis=cross_axis
            )
            if postscale_factor != 1.0:
                out = out * postscale_factor
            return out

        return fn
    return _c.allreduce


def _normalize_axis(axis_name, hierarchical):
    """hierarchical=True (or "planned") defaults the axis to the
    (cross, local) pair of a hierarchical mesh; a plain psum uses the
    tuple directly (XLA reduces over both axes), while the hierarchical
    reduce path splits it."""
    if hierarchical and isinstance(axis_name, str):
        if axis_name != DATA_AXIS:
            raise ValueError(
                "hierarchical=True needs a (cross, local) axis tuple, got "
                f"{axis_name!r}"
            )
        return (CROSS_AXIS, LOCAL_AXIS)
    return axis_name


def allreduce_gradients(
    grads: Any,
    *,
    op: ReduceOp = Average,
    axis_name=DATA_AXIS,
    fusion_threshold_bytes: Optional[int] = None,
    compression=Compression.none,
    hierarchical: Any = False,
    quantized: bool = False,
    nonfinite: Optional[str] = None,
) -> Any:
    """Fusion-bucketed allreduce of a gradient pytree (in-jit).

    The compiled-mode equivalent of the reference's per-gradient
    ``hvd.allreduce`` + background fusion: same-dtype leaves are concatenated
    into buckets up to the fusion threshold and each bucket becomes one XLA
    collective (see ops/fusion.py). ``quantized=True`` moves each bucket
    through the int8-wire ring allreduce (``ops/quantized.py``, ~1%
    gradient noise at 8 ranks) instead of a full-precision ``psum``.
    ``fusion_threshold_bytes=None`` resolves HOROVOD_FUSION_THRESHOLD
    (64 MB default, reference parity).

    ``nonfinite`` (None reads ``HOROVOD_GUARD_NONFINITE``) applies the
    non-finite sentinel around the reduce: ``zero`` sanitizes the local
    gradients BEFORE the wire (a poisoned rank's NaN never reaches its
    peers), ``warn`` detects on the reduced result and logs. The
    step-level policies (``skip``/``abort``) are applied by
    ``DistributedOptimizer`` / ``make_train_step``, not here.
    """
    fusion_threshold_bytes = _fusion.default_threshold_bytes(
        fusion_threshold_bytes
    )
    if hierarchical == "auto":
        hierarchical, _ = _resolve_hierarchical(hierarchical)
    axis_name = _normalize_axis(axis_name, hierarchical)
    nonfinite_policy = _resolve_nonfinite(nonfinite)
    if nonfinite_policy == "zero":
        grads = _nf.sanitize(grads)
    from ..analysis import preflight as _preflight

    if _preflight.enabled():
        # Opt-in trace-time pre-flight (HOROVOD_TPU_STATIC_CHECKS=1):
        # validates the fusion bucket plan and that the reduction axis is
        # actually bound before the collective is traced in.
        _preflight.check_gradient_tree(
            grads, fusion_threshold_bytes, axis_name
        )
    if quantized:
        if hierarchical or op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
            raise ValueError(
                "quantized=True supports flat SUM/AVERAGE reduction only"
            )
        if compression is not Compression.none:
            raise ValueError(
                "quantized=True already compresses the wire to int8; "
                "stacking cast compression would add loss for no "
                "bandwidth win"
            )

        def _quantized_reduce_fn(x, *, op, axis_name, prescale_factor=1.0,
                                 postscale_factor=1.0):
            from ..ops.quantized import quantized_ring_allreduce

            if not jnp.issubdtype(x.dtype, jnp.floating):
                # Integer buckets reduce exactly: a float32/int8 round
                # trip would silently corrupt exact sums. Buckets are
                # same-dtype (fusion groups by dtype), so per-bucket
                # dispatch loses nothing. Preserve the leaf dtype like
                # the quantized path does (AVERAGE's true-division
                # promotes to float; truncate back).
                out = _select_reduce_fn(op, False)(
                    x, op=op, axis_name=axis_name,
                    prescale_factor=prescale_factor,
                    postscale_factor=postscale_factor,
                )
                return out.astype(x.dtype)
            if prescale_factor != 1.0:
                x = x * prescale_factor
            out = quantized_ring_allreduce(
                x, axis_name=axis_name, average=(op == ReduceOp.AVERAGE)
            )
            if postscale_factor != 1.0:
                out = out * postscale_factor
            return out

        reduce_fn = _quantized_reduce_fn
    else:
        reduce_fn = _select_reduce_fn(op, hierarchical)
    if compression is not Compression.none:
        leaves, treedef = jax.tree.flatten(grads)
        compressed = [compression.compress(l) for l in leaves]
        grads = jax.tree.unflatten(treedef, [c for c, _ in compressed])
        ctxs = [ctx for _, ctx in compressed]
    reduced = _fusion.fused_allreduce(
        grads,
        op=op,
        axis_name=axis_name,
        threshold_bytes=fusion_threshold_bytes,
        reduce_fn=reduce_fn,
    )
    if compression is not Compression.none:
        leaves, treedef = jax.tree.flatten(reduced)
        leaves = [compression.decompress(l, ctx) for l, ctx in zip(leaves, ctxs)]
        reduced = jax.tree.unflatten(treedef, leaves)
    if nonfinite_policy == "warn":
        # Post-reduce detection: a NaN from ANY rank propagates through
        # SUM/AVERAGE, so every rank observes (and logs) the same event.
        _nf.note_detection("warn", "reduce")(_nf.local_flag(reduced))
    return reduced


def _check_overlap_rejections(overlap: bool, quantized: bool, op: ReduceOp):
    if not overlap:
        return
    if quantized:
        raise ValueError(
            "overlap=True streams full-precision bucket psums inside the "
            "backward; the quantized int8 ring allreduce dithers per bucket "
            "and runs post-hoc only — pick one"
        )
    if op not in _fusion._STREAMABLE_OPS:
        raise ValueError(
            f"overlap=True supports elementwise reduce ops "
            f"{_fusion._STREAMABLE_OPS}; got {op}"
        )


def DistributedOptimizer(  # noqa: N802 - API parity with hvd.DistributedOptimizer
    optimizer,
    *,
    op: ReduceOp = Average,
    axis_name: str = DATA_AXIS,
    fusion_threshold_bytes: Optional[int] = None,
    compression=Compression.none,
    hierarchical: Any = False,
    quantized: bool = False,
    backward_passes_per_step: int = 1,
    overlap: bool = False,
    nonfinite: Optional[str] = None,
):
    """Wrap an optax ``GradientTransformation`` so its update first
    allreduces gradients across the data axis.

    API parity with ``hvd.DistributedOptimizer``
    (``horovod/tensorflow/__init__.py:409-470``): the wrapped optimizer is
    used unchanged; only the gradients it sees are averaged across ranks.
    ``backward_passes_per_step > 1`` expects the caller to accumulate
    locally (see ``GradientAccumulator``) — the divisor is folded in here, as
    the reference does in the framework layer
    (``horovod/torch/mpi_ops.py:101-124``).

    ``overlap=True`` expects the model's layers to have been registered for
    streamed reduction (``hvd.reduce_in_backward`` /
    ``hvd.stream_param_groups`` applied to the params the loss consumes):
    the gradients then arrive ALREADY reduced from inside the backward pass
    and the post-hoc reduction here is skipped. If no layer was registered
    this falls back to the post-hoc reduction with a loud warning (and an
    ``overlap-no-streaming`` finding under HOROVOD_TPU_STATIC_CHECKS=1) —
    see docs/overlap.md.

    ``nonfinite`` (None reads ``HOROVOD_GUARD_NONFINITE``, resolved when
    the wrapper is built) applies the non-finite gradient guard: ``zero``
    sanitizes before the wire, ``warn`` logs, ``skip`` reaches cross-rank
    agreement on a skip flag and applies NO update on ANY rank for that
    step, ``abort`` behaves like ``skip`` here (an optax transformation
    cannot raise usefully from inside a trace) and is surfaced as a
    raised ``HorovodInternalError`` by ``make_train_step`` — see
    docs/fault_tolerance.md "Data-plane integrity".
    """
    import jax.numpy as jnp
    import optax

    _check_overlap_rejections(overlap, quantized, op)
    nonfinite_policy = _resolve_nonfinite(nonfinite)
    # "auto" without a mesh in hand: the detected process topology's
    # homogeneity-gated model decides (docs/topology.md); the mesh the
    # caller traces under must then carry the (cross, local) axes.
    hierarchical, _ = _resolve_hierarchical(hierarchical)
    norm_axis = _normalize_axis(axis_name, hierarchical)

    def init_fn(params):
        return optimizer.init(params)

    def update_fn(grads, state, params=None, **extra):
        prescale = 1.0 / backward_passes_per_step if backward_passes_per_step > 1 else 1.0
        do_reduce = True
        if overlap:
            reg = _fusion.take_stream_registrations()
            from ..analysis import preflight as _preflight

            findings = _preflight.check_overlap_streaming(
                reg, len(jax.tree.leaves(grads))
            )
            # No registered layer at all → the backward reduced nothing;
            # reduce post-hoc (correct, just without overlap). Partial
            # registration keeps the streamed contract (re-reducing here
            # would double-reduce the registered layers) — the finding
            # above already warned.
            do_reduce = reg["calls"] == 0
            if _preflight.enabled():
                _preflight._raise_or_log(findings)
            else:
                for f in findings:
                    _logger.warning("%s", f.render())
        flag = None
        if nonfinite_policy in ("skip", "abort"):
            # Pre-reduce local detection: catches a bad local gradient
            # even under MIN/MAX reductions, where NaN may not propagate.
            flag = _nf.local_flag(grads)
        if do_reduce:
            reduced = allreduce_gradients(
                grads,
                op=op,
                axis_name=axis_name,
                fusion_threshold_bytes=fusion_threshold_bytes,
                compression=compression,
                hierarchical=hierarchical,
                quantized=quantized,
                nonfinite=nonfinite_policy,
            )
        else:
            reduced = grads
            if nonfinite_policy == "zero":
                # Streamed groups sanitize pre-reduce when registered
                # with the policy; sanitizing the already-reduced grads
                # again is a harmless belt for manual registrations.
                reduced = _nf.sanitize(reduced)
            elif nonfinite_policy == "warn":
                _nf.note_detection("warn", "overlap")(
                    _nf.local_flag(reduced)
                )
        if flag is not None:
            # Agreement seam: psum of the flag — no rank applies a step
            # another rank skipped (same agreement shape the preemption
            # commit check uses). Post-reduce detection is OR-ed in so an
            # overflow created BY the summation is also caught.
            flag = jnp.maximum(flag, _nf.local_flag(reduced))
            flag = _nf.agree_flag(flag, norm_axis)
            _nf.note_detection(nonfinite_policy, "optimizer")(flag)
        if prescale != 1.0:
            reduced = jax.tree.map(lambda g: g * prescale, reduced)
        updates, new_state = optimizer.update(reduced, state, params, **extra)
        if flag is not None:
            # Skipped step: zero updates, optimizer state held.
            updates = _nf.select_on_flag(
                flag, jax.tree.map(jnp.zeros_like, updates), updates
            )
            new_state = _nf.select_on_flag(flag, state, new_state)
        return updates, new_state

    return optax.GradientTransformation(init_fn, update_fn)


def broadcast_variables(
    variables: Any, mesh: Mesh, *, root_rank: int = 0, axis_name: str = DATA_AXIS
) -> Any:
    """Make every rank's copy of a replicated pytree identical to root's
    (parity with ``broadcast_global_variables`` /
    ``broadcast_parameters``). Inside a single-controller mesh the arrays
    are already globally consistent, so this is a sharding-constraint
    replication; under multi-controller it lowers to an ICI broadcast."""
    def body(tree):
        return jax.tree.map(
            lambda x: _c.broadcast(x, root_rank=root_rank, axis_name=axis_name), tree
        )

    fn = _shard_map(body, mesh, in_specs=(P(),), out_specs=P())
    return jax.jit(fn)(variables)


def make_train_step(
    loss_fn: Callable[..., jax.Array],
    optimizer,
    mesh: Mesh,
    *,
    axis_name: str = DATA_AXIS,
    op: ReduceOp = Average,
    fusion_threshold_bytes: Optional[int] = None,
    compression=Compression.none,
    hierarchical: Any = False,
    quantized: bool = False,
    donate: bool = True,
    has_aux: bool = False,
    overlap: bool = False,
    first_bucket_bytes: Optional[int] = None,
    nonfinite: Optional[str] = None,
):
    """Build a jitted SPMD training step: per-shard grads → fused allreduce
    → optax update, with the batch sharded over ``axis_name`` and
    params/opt-state replicated.

    ``loss_fn(params, batch) -> loss`` (or ``(loss, aux)`` with
    ``has_aux=True``; aux leaves are pmean-averaged) is evaluated on each
    rank's local shard; gradient reduction uses the configured
    op/compression — the whole reference ``DistributedOptimizer`` pipeline
    as one XLA program. With ``hierarchical=True`` the mesh must have
    (cross, local) axes (see ``build_hierarchical_mesh``).

    ``overlap=True`` switches from the post-hoc whole-tree reduction to the
    streamed path (docs/overlap.md): the top-level children of ``params``
    are packed into DDP-style reverse-order groups (a smaller first bucket,
    ``first_bucket_bytes`` / HOROVOD_FUSION_FIRST_BUCKET_BYTES) and each
    group's psums are issued INSIDE the backward pass as soon as that
    group's gradients exist — independent collectives XLA can overlap with
    the remaining backward compute. Numerically identical to
    ``overlap=False`` (elementwise reductions commute with the split);
    ``quantized=True`` is rejected.

    ``nonfinite`` (None reads ``HOROVOD_GUARD_NONFINITE``, resolved when
    the step is built) applies the non-finite gradient guard around the
    reduce: ``zero`` sanitizes before the wire (per streamed group under
    ``overlap=True``), ``warn`` logs detections, ``skip`` cross-rank
    agrees on a skip flag and leaves params/opt-state UNCHANGED on every
    rank for that step, ``abort`` additionally raises
    ``HorovodInternalError`` from the returned step function so the
    elastic layer rolls back — docs/fault_tolerance.md "Data-plane
    integrity".
    """
    import jax.numpy as jnp
    import optax

    _check_overlap_rejections(overlap, quantized, op)
    # "auto": the mesh decides — a (pod,) cross, local hierarchy engages
    # per-bucket compositor plan selection (flat/two-level/split by
    # payload bytes, docs/topology.md); a flat data mesh stays flat. This
    # is what makes make_train_step(overlap=True) go hierarchical
    # automatically on multi-slice topologies.
    hierarchical, hier_axes = _resolve_hierarchical(hierarchical, mesh)
    if hierarchical == "planned" and hier_axes and axis_name == DATA_AXIS:
        axis_name = hier_axes
    axis_name = _normalize_axis(axis_name, hierarchical)
    nonfinite_policy = _resolve_nonfinite(nonfinite)

    def step(params, opt_state, batch):
        if overlap:
            def streamed_loss(p, b):
                p = _fusion.stream_param_groups(
                    p,
                    op=op,
                    axis_name=axis_name,
                    threshold_bytes=fusion_threshold_bytes,
                    first_bucket_bytes=first_bucket_bytes,
                    hierarchical=hierarchical,
                    compression=compression,
                    nonfinite=nonfinite_policy,
                )
                return loss_fn(p, b)

            grad_fn = jax.value_and_grad(streamed_loss, has_aux=has_aux)
        else:
            grad_fn = jax.value_and_grad(loss_fn, has_aux=has_aux)
        if has_aux:
            (loss, aux), grads = grad_fn(params, batch)
        else:
            loss, grads = grad_fn(params, batch)
            aux = None
        flag = None
        if not overlap:
            if nonfinite_policy in ("skip", "abort"):
                # Pre-reduce local detection (robust under MIN/MAX, where
                # NaN may not propagate through the reduction).
                flag = _nf.local_flag(grads)
            grads = allreduce_gradients(
                grads,
                op=op,
                axis_name=axis_name,
                fusion_threshold_bytes=fusion_threshold_bytes,
                compression=compression,
                hierarchical=hierarchical,
                quantized=quantized,
                nonfinite=nonfinite_policy,
            )
        else:
            # Streamed: grads left value_and_grad already reduced (the
            # custom_vjp backward rules issued the bucket psums); consume
            # the registration ledger so a later overlap DistributedOptimizer
            # trace doesn't credit THIS trace's registrations.
            _fusion.take_stream_registrations()
            if nonfinite_policy == "warn":
                _nf.note_detection("warn", "overlap")(
                    _nf.local_flag(grads)
                )
        if nonfinite_policy in ("skip", "abort"):
            # Agreement seam (psum of the flag): no rank applies a step
            # another rank skipped. Post-reduce detection is OR-ed in so
            # an overflow created BY the summation is also caught; under
            # overlap it is the only detection point (the flag cannot be
            # carried out of the custom_vjp backward rules).
            post = _nf.local_flag(grads)
            flag = post if flag is None else jnp.maximum(flag, post)
            flag = _nf.agree_flag(flag, axis_name)
            _nf.note_detection(nonfinite_policy, "train_step")(flag)
        loss = lax.pmean(loss, axis_name)
        updates, new_opt_state = optimizer.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        if flag is not None:
            # Skipped step: params and optimizer state held on EVERY rank.
            new_params = _nf.select_on_flag(flag, params, new_params)
            new_opt_state = _nf.select_on_flag(
                flag, opt_state, new_opt_state
            )
        outs = [new_params, new_opt_state, loss]
        if has_aux:
            aux = jax.tree.map(lambda a: lax.pmean(a, axis_name), aux)
            outs.append(aux)
        if nonfinite_policy == "abort":
            outs.append(flag)
        return tuple(outs)

    # Params/opt-state replicated; batch sharded on the data axis; every
    # output replicated. PartitionSpecs act as pytree prefixes.
    fn = _shard_map(
        step, mesh, in_specs=(P(), P(), P(axis_name)), out_specs=P()
    )
    jitted = jax.jit(fn, donate_argnums=(0, 1) if donate else ())
    if nonfinite_policy != "abort":
        return jitted

    def aborting_step(params, opt_state, batch):
        import numpy as np

        out = jitted(params, opt_state, batch)
        flag = out[-1]
        if float(np.asarray(flag)) > 0:
            from .. import HorovodInternalError

            raise HorovodInternalError(
                "non-finite gradient guard (policy abort): a rank "
                "produced NaN/Inf gradients this step; the update was "
                "not applied on any rank (cross-rank agreed) — rolling "
                "back via the elastic layer if one is active"
            )
        return out[:-1]

    return aborting_step


class GradientAccumulator:
    """Local gradient accumulation helper — parity with
    ``backward_passes_per_step`` (``horovod/torch/__init__.py:110-150``):
    accumulate ``n`` microbatch gradients locally, then allreduce once."""

    def __init__(self, n: int):
        self.n = n

    def init(self, grads: Any) -> Any:
        return jax.tree.map(jnp.zeros_like, grads)

    def add(self, acc: Any, grads: Any) -> Any:
        return jax.tree.map(jnp.add, acc, grads)

    def should_reduce(self, step_count: int) -> bool:
        return (step_count + 1) % self.n == 0
