"""horovod_tpu.jax — the compiled-mode (performance-path) binding.

Where the reference's framework bindings enqueue per-tensor async ops into a
background loop (``horovod/tensorflow/__init__.py``,
``horovod/torch/__init__.py``), the TPU-native compiled mode moves the whole
reduction *inside* the jitted training step: gradients are bucket-fused at
trace time and reduced with single large XLA collectives over a named mesh
axis. This keeps Horovod's semantics (``DistributedOptimizer`` wrapping an
inner optimizer, Average/Sum/Adasum ops, fp16/bf16 compression) while letting
XLA overlap the collectives with backprop on ICI.

Typical use::

    import horovod_tpu.jax as hvd

    mesh = hvd.build_mesh()                 # one "data" axis over all chips
    tx = hvd.DistributedOptimizer(optax.sgd(0.01))
    step = hvd.make_train_step(loss_fn, tx, mesh)
    params = hvd.broadcast_variables(params, mesh)     # rank-0 state
    params, opt_state, loss = step(params, opt_state, batch)
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .. import trace as _trace
from ..common.compression import Compression
from ..common.types import Adasum, Average, ReduceOp, Sum
from ..guard import nonfinite as _nf
from ..guard import resolve_policy as _resolve_nonfinite
from ..ops import collectives as _c
from ..ops import fusion as _fusion
from ..ops import quantized as _q
from ..ops.adasum import adasum_reduce_fn
from ..ops.quantized import EFState, ef_like
from ..parallel.zero import (
    Zero1State,
    init_zero1_stream_state,
    zero1_posthoc_reduce,
    zero1_stream_update,
)
from ..parallel.reshard import (  # noqa: F401 (re-exported API)
    LayoutManifest,
    Zero1Layout,
    build_manifest,
    reshard_zero1_state,
    zero1_layout_from_params,
)
from ..parallel.mesh import (
    CROSS_AXIS,
    DATA_AXIS,
    LOCAL_AXIS,
    POD_AXIS,
    build_hierarchical_mesh,
    build_mesh,
    build_three_level_mesh,
    hierarchy_axes,
)

_logger = logging.getLogger("horovod_tpu")

# Compiled-mode users reach collectives through jit, never through hvd.init;
# the perf-preset flags must land in XLA_FLAGS before the first backend
# touch, so the resolver runs at import (idempotent; "auto" is off-platform
# safe — it only adds TPU flags when a TPU platform is hinted).
from ..common import env as _env_mod  # noqa: E402

try:
    _env_mod.apply_xla_perf_preset()
except Exception:  # noqa: BLE001 - preset application must never block import
    pass

def _shard_map(fn, mesh, *, in_specs, out_specs, check: bool = False):
    """shard_map with version compatibility (check_vma in jax>=0.7,
    check_rep before; module moved from jax.experimental to jax core).

    ``check=True`` enables replication/varying-ness tracking — REQUIRED
    when differentiating through an in-body ``psum`` (e.g. the tensor-
    parallel row-parallel matmul): without it the psum transpose cannot
    see that the cotangent is replicated and multiplies gradients by the
    axis size. The default stays off for the collective-executor bodies,
    whose hand-written patterns predate the vma checker.
    """
    try:
        from jax import shard_map as _sm
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map as _sm
    try:
        return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_vma=check)
    except TypeError:  # pragma: no cover - older jax
        return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=check)


# In-jit primitives (usable inside shard_map/pmap bodies).
allreduce = _c.allreduce
allgather = _c.allgather
broadcast = _c.broadcast
alltoall = _c.alltoall
reducescatter = _c.reducescatter
hierarchical_allreduce = _c.hierarchical_allreduce
hierarchical_allgather = _c.hierarchical_allgather
hierarchical_reducescatter = _c.hierarchical_reducescatter
hierarchical_broadcast = _c.hierarchical_broadcast
hierarchical_alltoall = _c.hierarchical_alltoall

# Streamed (overlap) gradient reduction: register a parameter subtree (or a
# scanned layer stack's body) so its gradients are bucket-allreduced INSIDE
# the backward pass — see ops/fusion.py and docs/overlap.md.
reduce_in_backward = _fusion.reduce_in_backward
stream_scan_body = _fusion.stream_scan_body
stream_param_groups = _fusion.stream_param_groups

# Composed-parallelism sharding-rules engine (parallel/rules.py;
# docs/parallelism.md "Composed DP x TP fast path"): regex ->
# PartitionSpec tables drive mesh placement + gather/shard fns,
# preflighted by the Pass 5 validator. GPT_RULES is the shipped DP x TP
# table for models/transformer.py.
from ..parallel.rules import (  # noqa: E402
    GPT_RULES,
    gather_tree,
    local_shard_tree,
    make_shard_and_gather_fns,
    match_partition_rules,
    preflight_rules,
    shard_tree,
)


def collective_plan(collective: str = "allreduce",
                    nbytes: int = 4 * 1024 * 1024,
                    op: Optional[ReduceOp] = None,
                    wire_dtype: str = "f32") -> dict:
    """Compiled-mode alias of :func:`horovod_tpu.collective_plan` —
    the topology compositor's selected plan for one collective at one
    payload size (docs/topology.md). ``wire_dtype="int8"`` prices the
    int8+scales wire format (allreduce SUM/AVERAGE only): compressed
    bytes on the slow hop(s), full precision over ICI."""
    from .. import collective_plan as _cp

    return _cp(collective, nbytes, op, wire_dtype=wire_dtype)


def _resolve_quantized(quantized: Optional[bool]) -> bool:
    """Resolve the int8-wire knob: explicit argument >
    ``HOROVOD_QUANTIZED_WIRE`` env (1/true/int8 = on) > off."""
    if quantized is not None:
        return bool(quantized)
    import os

    from ..common import env as _env

    raw = os.environ.get(_env.HOROVOD_QUANTIZED_WIRE, "").strip().lower()
    return raw in ("1", "true", "yes", "on", "int8")


def error_feedback_state(opt_state: Any, params: Any) -> EFState:
    """Wrap an inner optimizer state with a zero error-feedback residual
    — the opt_state shape ``make_train_step(quantized=True)`` threads.
    Passing a plain opt_state into such a step also works (the residual
    is materialized as zeros on the first call and the step returns an
    :class:`EFState` from then on); this helper makes the structure
    explicit up front, e.g. for ``lax.scan`` carries that need a stable
    shape."""
    return EFState(inner=opt_state, residual=ef_like(params))


def _resolve_hierarchical(hierarchical, mesh: Optional[Mesh] = None):
    """Resolve the tri-state ``hierarchical`` knob (docs/topology.md):

    - ``False`` / ``True`` pass through (True = the forced two-level
      lowering, reference parity).
    - ``"auto"`` consults the topology compositor: with a mesh, the
      hierarchy axes the caller built decide (a deliberate (pod,) cross,
      local grid -> per-bucket plan selection; a flat data mesh -> flat);
      without one, the detected process topology's homogeneity-gated
      model decides.

    Returns ``(mode, axes)`` where mode is False / True / "planned" and
    axes is the hierarchy axis tuple for planned mode (None otherwise).
    """
    if hierarchical == "auto":
        if mesh is not None:
            axes = hierarchy_axes(mesh)
            if axes:
                return "planned", axes
            return False, None
        from ..topo import resolve_model

        if resolve_model().eligible:
            return "planned", (CROSS_AXIS, LOCAL_AXIS)
        return False, None
    if hierarchical == "planned":
        return "planned", None
    return bool(hierarchical), None


def _select_reduce_fn(op: ReduceOp, hierarchical, quantized: bool = False,
                      topo_algorithm: Optional[str] = None):
    if op == ReduceOp.ADASUM:
        return adasum_reduce_fn
    if hierarchical == "planned":
        from ..topo import compositor as _compositor

        return _compositor.auto_reduce_fn(
            quantized=quantized, algorithm=topo_algorithm
        )
    if quantized:
        # Flat: every hop int8 (the EQuARX ring). Hierarchical: int8 on
        # the outermost (DCN) hop only — reduce-scatter/all-gather stay
        # full precision over ICI (docs/topology.md).
        return _q.quantized_reduce_fn(
            "two-level" if hierarchical else "flat"
        )
    if hierarchical:
        # axis_name must be the (cross, local) tuple: reduce-scatter rides
        # ICI (local), the shard psum rides DCN (cross).
        def fn(x, *, op, axis_name, prescale_factor=1.0, postscale_factor=1.0):
            cross_axis, local_axis = axis_name
            if prescale_factor != 1.0:
                x = x * prescale_factor
            out = _c.hierarchical_allreduce(
                x, op=op, local_axis=local_axis, cross_axis=cross_axis
            )
            if postscale_factor != 1.0:
                out = out * postscale_factor
            return out

        return fn
    return _c.allreduce


def _normalize_axis(axis_name, hierarchical):
    """hierarchical=True (or "planned") defaults the axis to the
    (cross, local) pair of a hierarchical mesh; a plain psum uses the
    tuple directly (XLA reduces over both axes), while the hierarchical
    reduce path splits it."""
    if hierarchical and isinstance(axis_name, str):
        if axis_name != DATA_AXIS:
            raise ValueError(
                "hierarchical=True needs a (cross, local) axis tuple, got "
                f"{axis_name!r}"
            )
        return (CROSS_AXIS, LOCAL_AXIS)
    return axis_name


def allreduce_gradients(
    grads: Any,
    *,
    op: ReduceOp = Average,
    axis_name=DATA_AXIS,
    fusion_threshold_bytes: Optional[int] = None,
    compression=Compression.none,
    hierarchical: Any = False,
    quantized: Optional[bool] = None,
    nonfinite: Optional[str] = None,
    topo_algorithm: Optional[str] = None,
) -> Any:
    """Fusion-bucketed allreduce of a gradient pytree (in-jit).

    The compiled-mode equivalent of the reference's per-gradient
    ``hvd.allreduce`` + background fusion: same-dtype leaves are concatenated
    into buckets up to the fusion threshold and each bucket becomes one XLA
    collective (see ops/fusion.py). ``quantized=True`` (None reads
    ``HOROVOD_QUANTIZED_WIRE``) moves each float bucket through the
    int8-wire ring allreduce (``ops/quantized.py``, ~1% gradient noise at
    8 ranks) instead of a full-precision ``psum``; composed with
    ``hierarchical`` the wire compresses ONLY the outermost (DCN) hop —
    reduce-scatter/all-gather stay full precision over ICI. SUM/AVERAGE
    only; integer buckets always reduce exactly.
    ``fusion_threshold_bytes=None`` resolves HOROVOD_FUSION_THRESHOLD
    (64 MB default, reference parity).

    ``nonfinite`` (None reads ``HOROVOD_GUARD_NONFINITE``) applies the
    non-finite sentinel around the reduce: ``zero`` sanitizes the local
    gradients BEFORE the wire (a poisoned rank's NaN never reaches its
    peers), ``warn`` detects on the reduced result and logs. The
    step-level policies (``skip``/``abort``) are applied by
    ``DistributedOptimizer`` / ``make_train_step``, not here.

    ``topo_algorithm`` pins one compositor lowering for every bucket
    (the offline tuner's verdict, docs/autotune.md) — meaningful only
    when ``hierarchical`` resolves to planned mode.
    """
    fusion_threshold_bytes = _fusion.default_threshold_bytes(
        fusion_threshold_bytes
    )
    quantized = _resolve_quantized(quantized)
    if hierarchical == "auto":
        hierarchical, _ = _resolve_hierarchical(hierarchical)
    axis_name = _normalize_axis(axis_name, hierarchical)
    nonfinite_policy = _resolve_nonfinite(nonfinite)
    if nonfinite_policy == "zero":
        grads = _nf.sanitize(grads)
    from ..analysis import preflight as _preflight

    if _preflight.enabled():
        # Opt-in trace-time pre-flight (HOROVOD_TPU_STATIC_CHECKS=1):
        # validates the fusion bucket plan and that the reduction axis is
        # actually bound before the collective is traced in.
        _preflight.check_gradient_tree(
            grads, fusion_threshold_bytes, axis_name
        )
    if quantized:
        if op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
            raise ValueError(
                "quantized=True supports SUM/AVERAGE reduction only"
            )
        if compression is not Compression.none:
            raise ValueError(
                "quantized=True already compresses the wire to int8; "
                "stacking cast compression would add loss for no "
                "bandwidth win"
            )
    reduce_fn = _select_reduce_fn(op, hierarchical, quantized,
                                  topo_algorithm=topo_algorithm)
    if compression is not Compression.none:
        leaves, treedef = jax.tree.flatten(grads)
        compressed = [compression.compress(l) for l in leaves]
        grads = jax.tree.unflatten(treedef, [c for c, _ in compressed])
        ctxs = [ctx for _, ctx in compressed]
    reduced = _fusion.fused_allreduce(
        grads,
        op=op,
        axis_name=axis_name,
        threshold_bytes=fusion_threshold_bytes,
        reduce_fn=reduce_fn,
        wire_dtype=(
            "int8" if quantized and not hierarchical else "f32"
        ),
    )
    if compression is not Compression.none:
        leaves, treedef = jax.tree.flatten(reduced)
        leaves = [compression.decompress(l, ctx) for l, ctx in zip(leaves, ctxs)]
        reduced = jax.tree.unflatten(treedef, leaves)
    if nonfinite_policy == "warn":
        # Post-reduce detection: a NaN from ANY rank propagates through
        # SUM/AVERAGE, so every rank observes (and logs) the same event.
        _nf.note_detection("warn", "reduce")(_nf.local_flag(reduced))
    return reduced


def _check_overlap_rejections(overlap: bool, quantized: bool, op: ReduceOp):
    if quantized and op not in _fusion._QUANTIZABLE_OPS:
        raise ValueError(
            f"quantized=True supports {_fusion._QUANTIZABLE_OPS}; got {op} "
            "(per-hop int8 requantization accumulates in f32, which is "
            "only sound for additive reductions)"
        )
    if not overlap:
        return
    if op not in _fusion._STREAMABLE_OPS:
        raise ValueError(
            f"overlap=True supports elementwise reduce ops "
            f"{_fusion._STREAMABLE_OPS}; got {op}"
        )


def _resolve_error_feedback(error_feedback: Optional[bool],
                            quantized: bool, hierarchical) -> bool:
    """EF defaults ON for the flat int8 wire (where every byte is
    compressed and the residual compensates this rank's quantizer) and
    OFF for hierarchical/planned DCN-only compression (the quantizer
    sees post-local-reduction shards no per-rank residual can
    attribute); forcing it on there is an error, not a silent noop."""
    if not quantized:
        if error_feedback:
            raise ValueError("error_feedback=True requires quantized=True")
        return False
    if hierarchical:
        if error_feedback:
            raise ValueError(
                "error feedback compensates the flat int8 ring; the "
                "hierarchical DCN-only wire has no per-rank quantizer "
                "to compensate — leave error_feedback unset"
            )
        return False
    return True if error_feedback is None else bool(error_feedback)


def _zero1_distributed_optimizer(
    optimizer,
    *,
    op: ReduceOp,
    axis_name: str,
    fusion_threshold_bytes: Optional[int],
    first_bucket_bytes: Optional[int],
    compression,
    hierarchical: Any,
    quantized: bool,
    error_feedback: Optional[bool],
    overlap: bool,
    nonfinite: Optional[str],
    zero1_shards: Optional[int],
    tuned: Any,
):
    """The ``DistributedOptimizer(zero1=True)`` construction — see the
    public wrapper's docstring for the contract."""
    import optax

    from ..parallel import zero as _zero

    if zero1_shards is None or int(zero1_shards) < 1:
        raise ValueError(
            "DistributedOptimizer(zero1=True) needs zero1_shards=<data-"
            "axis size>: init builds the sharded state before any axis "
            "is bound, so the shard count cannot be inferred"
        )
    n_shards = int(zero1_shards)
    if op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        raise ValueError(
            f"zero1=True shards the optimizer update over a summed "
            f"gradient; op must be SUM/AVERAGE, got {ReduceOp(op).name}"
        )
    if compression is not Compression.none:
        raise ValueError(
            "zero1=True reduce-scatters raw buckets; cast compression "
            "has no shard-image form — use quantized=True instead"
        )
    if bool(hierarchical):
        raise ValueError(
            "DistributedOptimizer(zero1=True) runs over the flat data "
            "axis; hierarchical zero1 lives in make_train_step(zero1="
            "True, hierarchical='auto'), which owns the mesh"
        )
    if error_feedback:
        raise ValueError(
            "zero1 error feedback rides the streamed backward's side "
            "channel, which only make_train_step(zero1=True, "
            "quantized=True) can thread — leave error_feedback unset"
        )
    if tuned not in (None, False):
        _logger.warning(
            "DistributedOptimizer(zero1=True) ignores tuned=: the "
            "sharded state layout is keyed by the knobs the state was "
            "built with — apply tunings via make_train_step(zero1=True, "
            "tuned=...)"
        )
    nonfinite_policy = _resolve_nonfinite(nonfinite)
    if nonfinite_policy in ("skip", "abort"):
        raise ValueError(
            "nonfinite skip/abort need the step-level agreement seam "
            "(make_train_step); the zero1 optax wrapper supports "
            "off/zero/warn"
        )
    knobs = dict(
        threshold_bytes=fusion_threshold_bytes,
        first_bucket_bytes=first_bucket_bytes,
    )
    if _trace.ACTIVE:
        _trace.TAP.note_plan(
            optimizer="DistributedOptimizer",
            wire_dtype="int8" if quantized else "f32",
            overlap=bool(overlap), zero1=True,
        )

    def init_fn(params):
        return _zero.init_zero1_stream_state(
            optimizer, params, n_shards,
            quantized=quantized, error_feedback=False, **knobs,
        )

    def update_fn(grads, state, params=None, **extra):
        if params is None:
            raise ValueError(
                "DistributedOptimizer(zero1=True) needs the params "
                "argument: the shard-local update slices this rank's "
                "parameter shard"
            )
        if not isinstance(state, Zero1State):
            raise TypeError(
                "zero1 update expects the Zero1State this wrapper's "
                f"init built; got {type(state).__name__}"
            )
        state_rows = jax.tree.map(lambda s: s[0], state)
        do_reduce = True
        if overlap:
            reg = _fusion.take_stream_registrations()
            do_reduce = reg["calls"] == 0
            if do_reduce:
                _logger.warning(
                    "overlap=True but no parameter subtree was "
                    "registered with stream_param_groups(zero1=True); "
                    "reduce-scattering post-hoc (correct, zero overlap)"
                )
        if nonfinite_policy == "zero" and do_reduce:
            grads = _nf.sanitize(grads)
        if do_reduce:
            grads, _ = _zero.zero1_posthoc_reduce(
                grads, op=op, axis_name=axis_name, quantized=quantized,
                **knobs,
            )
        if nonfinite_policy == "warn":
            _nf.note_detection("warn", "zero1-optimizer")(
                _nf.local_flag(grads)
            )
        new_params, new_opt = _zero.zero1_stream_update(
            optimizer, params, state_rows.opt, grads,
            axis_name=axis_name, n_shards=n_shards,
            quantized=quantized, **knobs,
        )
        updates = jax.tree.map(
            lambda a, b: a - b, new_params, params
        )
        new_state = Zero1State(opt=new_opt, ef=state_rows.ef)
        return updates, jax.tree.map(lambda s: s[None], new_state)

    return optax.GradientTransformation(init_fn, update_fn)


def DistributedOptimizer(  # noqa: N802 - API parity with hvd.DistributedOptimizer
    optimizer,
    *,
    op: ReduceOp = Average,
    axis_name: str = DATA_AXIS,
    fusion_threshold_bytes: Optional[int] = None,
    compression=Compression.none,
    hierarchical: Any = False,
    quantized: Optional[bool] = None,
    error_feedback: Optional[bool] = None,
    backward_passes_per_step: int = 1,
    overlap: bool = False,
    nonfinite: Optional[str] = None,
    tuned: Any = None,
    topo_algorithm: Optional[str] = None,
    zero1: bool = False,
    zero1_shards: Optional[int] = None,
):
    """Wrap an optax ``GradientTransformation`` so its update first
    allreduces gradients across the data axis.

    API parity with ``hvd.DistributedOptimizer``
    (``horovod/tensorflow/__init__.py:409-470``): the wrapped optimizer is
    used unchanged; only the gradients it sees are averaged across ranks.
    ``backward_passes_per_step > 1`` expects the caller to accumulate
    locally (see ``GradientAccumulator``) — the divisor is folded in here, as
    the reference does in the framework layer
    (``horovod/torch/mpi_ops.py:101-124``).

    ``overlap=True`` expects the model's layers to have been registered for
    streamed reduction (``hvd.reduce_in_backward`` /
    ``hvd.stream_param_groups`` applied to the params the loss consumes):
    the gradients then arrive ALREADY reduced from inside the backward pass
    and the post-hoc reduction here is skipped. If no layer was registered
    this falls back to the post-hoc reduction with a loud warning (and an
    ``overlap-no-streaming`` finding under HOROVOD_TPU_STATIC_CHECKS=1) —
    see docs/overlap.md.

    ``nonfinite`` (None reads ``HOROVOD_GUARD_NONFINITE``, resolved when
    the wrapper is built) applies the non-finite gradient guard: ``zero``
    sanitizes before the wire, ``warn`` logs, ``skip`` reaches cross-rank
    agreement on a skip flag and applies NO update on ANY rank for that
    step, ``abort`` behaves like ``skip`` here (an optax transformation
    cannot raise usefully from inside a trace) and is surfaced as a
    raised ``HorovodInternalError`` by ``make_train_step`` — see
    docs/fault_tolerance.md "Data-plane integrity".

    ``quantized=True`` (None reads ``HOROVOD_QUANTIZED_WIRE``) moves the
    gradient buckets over the int8 wire; on the flat (non-hierarchical)
    path it carries an error-feedback residual in the optimizer state by
    default (``error_feedback``, EF-SGD: the quantization error is added
    back into the next step's gradient before quantization, preserving
    convergence). The wrapped state is then
    ``EFState(inner=<inner opt state>, residual=<f32 grads-like>)`` —
    ``tx.init(params)`` builds it, checkpoints carry it, and the guard's
    digest agreement excludes the rank-local residual. Under
    ``overlap=True`` the streamed registration owns the residual
    (``make_train_step`` threads it); this wrapper then leaves EF to the
    streamed path.

    ``tuned`` (None reads ``HOROVOD_TUNED_FILE``; a path or a
    :class:`horovod_tpu.tune.TunedConfig`) applies a pinned offline
    tuning (docs/autotune.md "Compiled-path offline tuning") to the
    knobs the caller left at their defaults. The gradient tree an
    optimizer sees carries no mesh, so only the params half of the
    tuning's step signature is checked here (``make_train_step`` checks
    both); a mismatch warns loudly and keeps the untuned defaults.
    ``topo_algorithm`` pins one compositor lowering under planned
    hierarchy — normally set via ``tuned``, exposed for hand
    experiments.

    ``zero1=True`` (with ``zero1_shards=<data-axis size>``) shards the
    optimizer state per streamed bucket (docs/overlap.md "Streamed
    ZeRO-1"): ``init`` builds the stacked :class:`Zero1State` — thread
    it through your ``shard_map`` with ``P(axis_name)`` on the leading
    axis — and ``update`` runs the shard-local optax update against the
    bucketized shard layout, returning full-tree updates
    (``gathered_new_params - params``; note ``apply_updates`` re-adds,
    so the result matches ``make_train_step(zero1=True)`` to float-add
    round-off, not bitwise). Under ``overlap=True`` the gradients must
    arrive as shard images from ``stream_param_groups(zero1=True)``;
    without registrations the wrapper reduce-scatters post-hoc (correct,
    zero overlap). Error feedback needs the backward side channel only
    ``make_train_step`` owns and is rejected here.
    """
    import jax.numpy as jnp
    import optax

    from .. import tune as _tune

    if zero1:
        return _zero1_distributed_optimizer(
            optimizer, op=op, axis_name=axis_name,
            fusion_threshold_bytes=fusion_threshold_bytes,
            first_bucket_bytes=None,
            compression=compression, hierarchical=hierarchical,
            quantized=_resolve_quantized(quantized),
            error_feedback=error_feedback, overlap=overlap,
            nonfinite=nonfinite, zero1_shards=zero1_shards,
            tuned=tuned,
        )
    tuned_cfg, tuned_source = _tune.resolve_tuned(tuned)
    caller_quantized = quantized
    caller_hierarchical = hierarchical
    caller_threshold = fusion_threshold_bytes
    quantized = _resolve_quantized(quantized)
    _check_overlap_rejections(overlap, quantized, op)
    nonfinite_policy = _resolve_nonfinite(nonfinite)
    # "auto" without a mesh in hand: the detected process topology's
    # homogeneity-gated model decides (docs/topology.md); the mesh the
    # caller traces under must then carry the (cross, local) axes.
    hierarchical, _ = _resolve_hierarchical(hierarchical)
    norm_axis = _normalize_axis(axis_name, hierarchical)
    # Under overlap the residual lives with the streamed registration
    # (the backward rule computes it); the optimizer cannot see it.
    use_ef = _resolve_error_feedback(
        error_feedback, quantized, hierarchical
    ) and not overlap
    if quantized and compression is not Compression.none:
        raise ValueError(
            "quantized=True already compresses the wire to int8; "
            "stacking cast compression would add loss for no bandwidth win"
        )

    base_knobs = {
        "fusion_threshold_bytes": fusion_threshold_bytes,
        "quantized": quantized,
        "hierarchical": hierarchical,
        "norm_axis": norm_axis,
        "use_ef": use_ef,
        "topo_algorithm": topo_algorithm,
    }
    _tuned_resolution: dict = {}

    def _knobs(tree, where):
        """Trace-time knob resolution: with a tuned config in hand, the
        first traced pytree (params at init, gradients at update — the
        same structure) decides whether the pinned knobs apply. The
        verdict is cached: init and update must agree or the EF state
        shape would be inconsistent."""
        if tuned_cfg is None:
            return base_knobs
        r = _tuned_resolution.get("r")
        if r is not None:
            return r
        live = _tune.step_signature(tree)
        matched = _tune.signatures_match(
            tuned_cfg.signature, live, require_mesh=False
        )
        if matched:
            tk = _tune.tuned_step_kwargs(tuned_cfg)
            q = (quantized if caller_quantized is not None
                 else tk["quantized"])
            h = (caller_hierarchical if caller_hierarchical is not False
                 else tk["hierarchical"])
            h, _ = _resolve_hierarchical(h)
            r = {
                "fusion_threshold_bytes": (
                    caller_threshold if caller_threshold is not None
                    else tk["fusion_threshold_bytes"]
                ),
                "quantized": q,
                "hierarchical": h,
                "norm_axis": _normalize_axis(axis_name, h),
                "use_ef": _resolve_error_feedback(
                    error_feedback, q, h
                ) and not overlap,
                "topo_algorithm": (
                    topo_algorithm if topo_algorithm is not None
                    else tk["topo_algorithm"]
                ),
            }
        else:
            _tune.warn_signature_mismatch(
                tuned_cfg, live.get("hash", "?"), "DistributedOptimizer"
            )
            r = base_knobs
        _tune.note_applied(
            tuned_source, tuned_cfg.signature_hash, matched,
            "DistributedOptimizer",
        )
        _tuned_resolution["r"] = r
        return r
    if _trace.ACTIVE:
        # Step-span correlation ids for loops driven by this optimizer:
        # the host-side step boundaries themselves come from wrap_step
        # or the elastic commit seam (an optax transformation runs
        # inside the caller's jit and has no host boundary of its own),
        # but every step span they record carries this wire/overlap
        # configuration. Disabled → not reached (NULL_TAP discipline).
        _trace.TAP.note_plan(
            optimizer="DistributedOptimizer",
            wire_dtype="int8" if quantized else "f32",
            overlap=bool(overlap),
        )

    def init_fn(params):
        if _knobs(params, "init")["use_ef"]:
            return EFState(
                inner=optimizer.init(params), residual=ef_like(params)
            )
        return optimizer.init(params)

    def update_fn(grads, state, params=None, **extra):
        k = _knobs(grads, "update")
        prescale = 1.0 / backward_passes_per_step if backward_passes_per_step > 1 else 1.0
        ef = None
        if k["use_ef"]:
            if isinstance(state, EFState):
                state, ef = state.inner, state.residual
            else:
                ef = ef_like(grads)
        do_reduce = True
        if overlap:
            reg = _fusion.take_stream_registrations()
            from ..analysis import preflight as _preflight

            findings = _preflight.check_overlap_streaming(
                reg, len(jax.tree.leaves(grads))
            )
            # No registered layer at all → the backward reduced nothing;
            # reduce post-hoc (correct, just without overlap). Partial
            # registration keeps the streamed contract (re-reducing here
            # would double-reduce the registered layers) — the finding
            # above already warned.
            do_reduce = reg["calls"] == 0
            if _preflight.enabled():
                _preflight._raise_or_log(findings)
            else:
                for f in findings:
                    _logger.warning("%s", f.render())
        flag = None
        if nonfinite_policy in ("skip", "abort"):
            # Pre-reduce local detection: catches a bad local gradient
            # even under MIN/MAX reductions, where NaN may not propagate.
            flag = _nf.local_flag(grads)
        new_ef = ef
        if do_reduce and ef is not None:
            # Error-feedback path: sentinel BEFORE the quantizer (a NaN
            # would poison its block's scale), then reduce g + e over
            # the int8 wire and carry the fresh residual.
            if nonfinite_policy == "zero":
                grads = _nf.sanitize(grads)
            reduced, new_ef = _fusion.quantized_ef_allreduce(
                grads, ef,
                op=op,
                axis_name=k["norm_axis"],
                threshold_bytes=k["fusion_threshold_bytes"],
                label="posthoc-ef",
            )
            if nonfinite_policy == "warn":
                _nf.note_detection("warn", "reduce")(
                    _nf.local_flag(reduced)
                )
        elif do_reduce:
            reduced = allreduce_gradients(
                grads,
                op=op,
                axis_name=axis_name,
                fusion_threshold_bytes=k["fusion_threshold_bytes"],
                compression=compression,
                hierarchical=k["hierarchical"],
                quantized=k["quantized"],
                nonfinite=nonfinite_policy,
                topo_algorithm=k["topo_algorithm"],
            )
        else:
            reduced = grads
            if nonfinite_policy == "zero":
                # Streamed groups sanitize pre-reduce when registered
                # with the policy; sanitizing the already-reduced grads
                # again is a harmless belt for manual registrations.
                reduced = _nf.sanitize(reduced)
            elif nonfinite_policy == "warn":
                _nf.note_detection("warn", "overlap")(
                    _nf.local_flag(reduced)
                )
        if flag is not None:
            # Agreement seam: psum of the flag — no rank applies a step
            # another rank skipped (same agreement shape the preemption
            # commit check uses). Post-reduce detection is OR-ed in so an
            # overflow created BY the summation is also caught.
            flag = jnp.maximum(flag, _nf.local_flag(reduced))
            flag = _nf.agree_flag(flag, k["norm_axis"])
            _nf.note_detection(nonfinite_policy, "optimizer")(flag)
        if prescale != 1.0:
            reduced = jax.tree.map(lambda g: g * prescale, reduced)
        updates, new_state = optimizer.update(reduced, state, params, **extra)
        if flag is not None:
            # Skipped step: zero updates, optimizer state held.
            updates = _nf.select_on_flag(
                flag, jax.tree.map(jnp.zeros_like, updates), updates
            )
            new_state = _nf.select_on_flag(flag, state, new_state)
        if k["use_ef"]:
            if flag is not None:
                # A skipped step discards the gradient, so the residual
                # computed from it must not carry either.
                new_ef = _nf.select_on_flag(flag, ef, new_ef)
            new_state = EFState(inner=new_state, residual=new_ef)
        return updates, new_state

    return optax.GradientTransformation(init_fn, update_fn)


def broadcast_variables(
    variables: Any, mesh: Mesh, *, root_rank: int = 0, axis_name: str = DATA_AXIS
) -> Any:
    """Make every rank's copy of a replicated pytree identical to root's
    (parity with ``broadcast_global_variables`` /
    ``broadcast_parameters``). Inside a single-controller mesh the arrays
    are already globally consistent, so this is a sharding-constraint
    replication; under multi-controller it lowers to an ICI broadcast."""
    def body(tree):
        return jax.tree.map(
            lambda x: _c.broadcast(x, root_rank=root_rank, axis_name=axis_name), tree
        )

    fn = _shard_map(body, mesh, in_specs=(P(),), out_specs=P())
    return jax.jit(fn)(variables)


def _build_train_step(
    loss_fn: Callable[..., jax.Array],
    optimizer,
    mesh: Mesh,
    *,
    axis_name: str = DATA_AXIS,
    op: ReduceOp = Average,
    fusion_threshold_bytes: Optional[int] = None,
    compression=Compression.none,
    hierarchical: Any = False,
    quantized: Optional[bool] = None,
    error_feedback: Optional[bool] = None,
    donate: bool = True,
    has_aux: bool = False,
    overlap: bool = False,
    first_bucket_bytes: Optional[int] = None,
    nonfinite: Optional[str] = None,
    topo_algorithm: Optional[str] = None,
    zero1: bool = False,
):
    """Build a jitted SPMD training step: per-shard grads → fused allreduce
    → optax update, with the batch sharded over ``axis_name`` and
    params/opt-state replicated.

    ``loss_fn(params, batch) -> loss`` (or ``(loss, aux)`` with
    ``has_aux=True``; aux leaves are pmean-averaged) is evaluated on each
    rank's local shard; gradient reduction uses the configured
    op/compression — the whole reference ``DistributedOptimizer`` pipeline
    as one XLA program. With ``hierarchical=True`` the mesh must have
    (cross, local) axes (see ``build_hierarchical_mesh``).

    ``overlap=True`` switches from the post-hoc whole-tree reduction to the
    streamed path (docs/overlap.md): the top-level children of ``params``
    are packed into DDP-style reverse-order groups (a smaller first bucket,
    ``first_bucket_bytes`` / HOROVOD_FUSION_FIRST_BUCKET_BYTES) and each
    group's psums are issued INSIDE the backward pass as soon as that
    group's gradients exist — independent collectives XLA can overlap with
    the remaining backward compute. Numerically identical to
    ``overlap=False`` (elementwise reductions commute with the split).

    ``quantized=True`` (None reads ``HOROVOD_QUANTIZED_WIRE``) moves each
    gradient bucket over the int8 wire (``ops/quantized.py``) — composed
    with ``overlap=True`` the quantize→ring-reduce→dequantize runs inside
    the backward trace per streamed bucket, preserving the
    scheduler-overlap property; composed with ``hierarchical`` only the
    outermost (DCN) hop is compressed. On the flat wire an error-feedback
    residual (``error_feedback``, default on; EF-SGD) rides the optimizer
    state: the step accepts a plain ``optimizer.init(params)`` opt_state
    and returns ``EFState(inner=..., residual=...)`` from the first call
    on (or start from :func:`error_feedback_state` for a stable
    structure, e.g. under ``lax.scan``).

    ``nonfinite`` (None reads ``HOROVOD_GUARD_NONFINITE``, resolved when
    the step is built) applies the non-finite gradient guard around the
    reduce: ``zero`` sanitizes before the wire (per streamed group under
    ``overlap=True``), ``warn`` logs detections, ``skip`` cross-rank
    agrees on a skip flag and leaves params/opt-state UNCHANGED on every
    rank for that step, ``abort`` additionally raises
    ``HorovodInternalError`` from the returned step function so the
    elastic layer rolls back — docs/fault_tolerance.md "Data-plane
    integrity".
    """
    import jax.numpy as jnp
    import optax

    quantized = _resolve_quantized(quantized)
    _check_overlap_rejections(overlap, quantized, op)
    if quantized and compression is not Compression.none:
        raise ValueError(
            "quantized=True already compresses the wire to int8; "
            "stacking cast compression would add loss for no bandwidth win"
        )
    if zero1:
        return _build_zero1_train_step(
            loss_fn, optimizer, mesh,
            axis_name=axis_name, op=op,
            fusion_threshold_bytes=fusion_threshold_bytes,
            compression=compression, hierarchical=hierarchical,
            quantized=quantized, error_feedback=error_feedback,
            donate=donate, has_aux=has_aux, overlap=overlap,
            first_bucket_bytes=first_bucket_bytes, nonfinite=nonfinite,
            topo_algorithm=topo_algorithm,
        )
    # "auto": the mesh decides — a (pod,) cross, local hierarchy engages
    # per-bucket compositor plan selection (flat/two-level/split by
    # payload bytes, docs/topology.md); a flat data mesh stays flat. This
    # is what makes make_train_step(overlap=True) go hierarchical
    # automatically on multi-slice topologies.
    hierarchical, hier_axes = _resolve_hierarchical(hierarchical, mesh)
    if hierarchical == "planned" and hier_axes and axis_name == DATA_AXIS:
        axis_name = hier_axes
    axis_name = _normalize_axis(axis_name, hierarchical)
    nonfinite_policy = _resolve_nonfinite(nonfinite)
    use_ef = _resolve_error_feedback(error_feedback, quantized, hierarchical)
    # A pinned compositor algorithm only reaches the lowering in planned
    # mode; anywhere else (flat mesh, forced two-level) it is moot.
    pin_algorithm = topo_algorithm if hierarchical == "planned" else None

    def step(params, opt_state, batch):
        # EF residual rides the opt_state as EFState(inner, residual);
        # a plain opt_state (first step, old checkpoint) materializes a
        # zero residual and the step returns EFState from then on.
        ef = None
        if use_ef:
            if isinstance(opt_state, EFState):
                opt_state, ef = opt_state.inner, opt_state.residual
            else:
                ef = ef_like(params)
        if overlap and use_ef:
            def streamed_loss_ef(p, e, b):
                p = _fusion.stream_param_groups(
                    p,
                    op=op,
                    axis_name=axis_name,
                    threshold_bytes=fusion_threshold_bytes,
                    first_bucket_bytes=first_bucket_bytes,
                    hierarchical=hierarchical,
                    compression=compression,
                    quantized=True,
                    ef=e,
                    nonfinite=nonfinite_policy,
                    algorithm=pin_algorithm,
                )
                return loss_fn(p, b)

            # Differentiating w.r.t. the residual is the EF side
            # channel: the streamed backward rule returns the NEXT
            # residual as ef's "gradient" (ops/fusion.py).
            grad_fn = jax.value_and_grad(
                streamed_loss_ef, argnums=(0, 1), has_aux=has_aux
            )
        elif overlap:
            def streamed_loss(p, b):
                p = _fusion.stream_param_groups(
                    p,
                    op=op,
                    axis_name=axis_name,
                    threshold_bytes=fusion_threshold_bytes,
                    first_bucket_bytes=first_bucket_bytes,
                    hierarchical=hierarchical,
                    compression=compression,
                    quantized=quantized,
                    nonfinite=nonfinite_policy,
                    algorithm=pin_algorithm,
                )
                return loss_fn(p, b)

            grad_fn = jax.value_and_grad(streamed_loss, has_aux=has_aux)
        else:
            grad_fn = jax.value_and_grad(loss_fn, has_aux=has_aux)
        new_ef = ef
        if overlap and use_ef:
            if has_aux:
                (loss, aux), (grads, new_ef) = grad_fn(params, ef, batch)
            else:
                loss, (grads, new_ef) = grad_fn(params, ef, batch)
                aux = None
        elif has_aux:
            (loss, aux), grads = grad_fn(params, batch)
        else:
            loss, grads = grad_fn(params, batch)
            aux = None
        flag = None
        if not overlap:
            if nonfinite_policy in ("skip", "abort"):
                # Pre-reduce local detection (robust under MIN/MAX, where
                # NaN may not propagate through the reduction).
                flag = _nf.local_flag(grads)
            if use_ef:
                # Sentinel BEFORE the quantizer (a NaN would poison its
                # block's scale), then reduce g + e over the int8 wire
                # and carry the fresh residual.
                if nonfinite_policy == "zero":
                    grads = _nf.sanitize(grads)
                grads, new_ef = _fusion.quantized_ef_allreduce(
                    grads, ef,
                    op=op,
                    axis_name=axis_name,
                    threshold_bytes=fusion_threshold_bytes,
                    label="posthoc-ef",
                )
                if nonfinite_policy == "warn":
                    _nf.note_detection("warn", "reduce")(
                        _nf.local_flag(grads)
                    )
            else:
                grads = allreduce_gradients(
                    grads,
                    op=op,
                    axis_name=axis_name,
                    fusion_threshold_bytes=fusion_threshold_bytes,
                    compression=compression,
                    hierarchical=hierarchical,
                    quantized=quantized,
                    nonfinite=nonfinite_policy,
                    topo_algorithm=pin_algorithm,
                )
        else:
            # Streamed: grads left value_and_grad already reduced (the
            # custom_vjp backward rules issued the bucket psums); consume
            # the registration ledger so a later overlap DistributedOptimizer
            # trace doesn't credit THIS trace's registrations.
            _fusion.take_stream_registrations()
            if nonfinite_policy == "warn":
                _nf.note_detection("warn", "overlap")(
                    _nf.local_flag(grads)
                )
        if nonfinite_policy in ("skip", "abort"):
            # Agreement seam (psum of the flag): no rank applies a step
            # another rank skipped. Post-reduce detection is OR-ed in so
            # an overflow created BY the summation is also caught; under
            # overlap it is the only detection point (the flag cannot be
            # carried out of the custom_vjp backward rules).
            post = _nf.local_flag(grads)
            flag = post if flag is None else jnp.maximum(flag, post)
            flag = _nf.agree_flag(flag, axis_name)
            _nf.note_detection(nonfinite_policy, "train_step")(flag)
        loss = lax.pmean(loss, axis_name)
        updates, new_opt_state = optimizer.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        if flag is not None:
            # Skipped step: params and optimizer state held on EVERY rank.
            new_params = _nf.select_on_flag(flag, params, new_params)
            new_opt_state = _nf.select_on_flag(
                flag, opt_state, new_opt_state
            )
        if use_ef:
            if flag is not None:
                # A skipped step discards the gradient, so the residual
                # computed from it must not carry either.
                new_ef = _nf.select_on_flag(flag, ef, new_ef)
            new_opt_state = EFState(inner=new_opt_state, residual=new_ef)
        outs = [new_params, new_opt_state, loss]
        if has_aux:
            aux = jax.tree.map(lambda a: lax.pmean(a, axis_name), aux)
            outs.append(aux)
        if nonfinite_policy == "abort":
            outs.append(flag)
        return tuple(outs)

    # Params/opt-state replicated; batch sharded on the data axis; every
    # output replicated. PartitionSpecs act as pytree prefixes.
    fn = _shard_map(
        step, mesh, in_specs=(P(), P(), P(axis_name)), out_specs=P()
    )
    jitted = jax.jit(fn, donate_argnums=(0, 1) if donate else ())

    def _maybe_trace(step_fn):
        # Fleet-tracing step tap (docs/timeline.md "Step spans"):
        # host-side step-boundary timestamps + step index, stamped with
        # the build-time correlation ids so one trace links step →
        # bucket → collective → hop. NULL_TAP discipline: disabled →
        # the jitted function is returned UNCHANGED (wrap_step(f) is f).
        return _trace.wrap_step(
            step_fn,
            overlap=overlap,
            quantized=quantized,
            hierarchical=str(hierarchical),
            wire_dtype="int8" if quantized else "f32",
            op=ReduceOp(op).name,
            nonfinite=nonfinite_policy,
        )

    if nonfinite_policy != "abort":
        return _maybe_trace(jitted)

    def aborting_step(params, opt_state, batch):
        import numpy as np

        out = jitted(params, opt_state, batch)
        flag = out[-1]
        if float(np.asarray(flag)) > 0:
            from .. import HorovodInternalError

            if _trace.ACTIVE:
                # Flight recorder: the abort is about to unwind into the
                # elastic rollback — persist the last moments first.
                _trace.TAP.flight_dump("guard-abort")
            raise HorovodInternalError(
                "non-finite gradient guard (policy abort): a rank "
                "produced NaN/Inf gradients this step; the update was "
                "not applied on any rank (cross-rank agreed) — rolling "
                "back via the elastic layer if one is active"
            )
        return out[:-1]

    return _maybe_trace(aborting_step)


def _build_zero1_train_step(
    loss_fn: Callable[..., jax.Array],
    optimizer,
    mesh: Mesh,
    *,
    axis_name: str = DATA_AXIS,
    op: ReduceOp = Average,
    fusion_threshold_bytes: Optional[int] = None,
    compression=Compression.none,
    hierarchical: Any = False,
    quantized: bool = False,
    error_feedback: Optional[bool] = None,
    donate: bool = True,
    has_aux: bool = False,
    overlap: bool = False,
    first_bucket_bytes: Optional[int] = None,
    nonfinite: Optional[str] = None,
    topo_algorithm: Optional[str] = None,
):
    """The streamed-ZeRO-1 step (docs/overlap.md "Streamed ZeRO-1"):
    ``step(params, zero1_state, batch)`` with the optimizer state
    sharded per streamed bucket (``init_zero1_stream_state``). Under
    ``overlap=True`` each bucket reduce-scatters INSIDE the backward
    trace — each rank keeps only its shard's cotangents, (n-1)/n of the
    gradient payload rides the wire, and the scheduler hides it behind
    the remaining backward compute; ``overlap=False`` runs the identical
    per-bucket reduction post-hoc (bitwise-equal, zero overlap). The
    shard-local optax update and parameter all-gather run against the
    same bucket plan (``parallel/zero.zero1_stream_update``).

    ``quantized=True`` moves each bucket through the int8 ring
    reduce-scatter with the error-feedback residual carried SHARDED in
    the ``Zero1State`` (flat axis only — DCN-only compression has no
    RS+AG form); ``hierarchical="auto"`` on a multi-slice mesh lowers
    each bucket's RS/AG via the compositor's two-level schedules (only
    the 1/L shard crosses DCN). ``topo_algorithm`` pins nothing here —
    the RS lowering is determined by the axis shape — except ``"split"``
    which has no reduce-scatter form and raises.
    """
    import jax.numpy as jnp

    from ..parallel import zero as _zero

    if op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        raise ValueError(
            f"zero1=True shards the optimizer update over a summed "
            f"gradient; op must be SUM/AVERAGE, got {ReduceOp(op).name}"
        )
    if compression is not Compression.none:
        raise ValueError(
            "zero1=True reduce-scatters raw buckets; cast compression "
            "has no shard-image form — use quantized=True instead"
        )
    if topo_algorithm == "split":
        raise ValueError(
            "topo_algorithm='split' has no reduce-scatter decomposition; "
            "zero1 lowers flat or two-level by the mesh shape"
        )
    hierarchical, hier_axes = _resolve_hierarchical(hierarchical, mesh)
    if hierarchical == "planned" and hier_axes and axis_name == DATA_AXIS:
        axis_name = hier_axes
    axis_name = _normalize_axis(axis_name, hierarchical)
    if quantized and not isinstance(axis_name, str):
        raise ValueError(
            "quantized zero1 runs the flat int8 ring reduce-scatter "
            "over ONE axis; hierarchical (DCN-only) compression is not "
            "defined for the RS+AG decomposition — drop hierarchical or "
            "quantized"
        )
    nonfinite_policy = _resolve_nonfinite(nonfinite)
    use_ef = _resolve_error_feedback(error_feedback, quantized, False)
    axes = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
    n_shards = 1
    for a in axes:
        n_shards *= int(mesh.shape[a])
    knobs = dict(
        threshold_bytes=fusion_threshold_bytes,
        first_bucket_bytes=first_bucket_bytes,
    )
    state_spec = P(axes[0] if len(axes) == 1 else axes)

    def step(params, opt_state, batch):
        if not isinstance(opt_state, Zero1State):
            raise TypeError(
                "zero1=True expects the sharded Zero1State from "
                "hvd.init_zero1_stream_state(optimizer, params, "
                f"{n_shards}, ...); got {type(opt_state).__name__}"
            )
        state = jax.tree.map(lambda s: s[0], opt_state)
        ef = None
        if use_ef:
            if state.ef is None:
                raise ValueError(
                    "the quantized zero1 wire carries a SHARDED "
                    "error-feedback residual in the optimizer state; "
                    "rebuild it with init_zero1_stream_state(..., "
                    "quantized=True) or pass error_feedback=False"
                )
            ef = state.ef
        new_ef = ef
        if overlap and use_ef:
            def streamed_loss_ef(p, e, b):
                p = _fusion.stream_param_groups(
                    p, op=op, axis_name=axis_name,
                    quantized=True, ef=e, nonfinite=nonfinite_policy,
                    zero1=True, **knobs,
                )
                return loss_fn(p, b)

            grad_fn = jax.value_and_grad(
                streamed_loss_ef, argnums=(0, 1), has_aux=has_aux
            )
            if has_aux:
                (loss, aux), (grads, new_ef) = grad_fn(params, ef, batch)
            else:
                loss, (grads, new_ef) = grad_fn(params, ef, batch)
                aux = None
        elif overlap:
            def streamed_loss(p, b):
                p = _fusion.stream_param_groups(
                    p, op=op, axis_name=axis_name,
                    hierarchical=hierarchical, quantized=quantized,
                    nonfinite=nonfinite_policy, zero1=True, **knobs,
                )
                return loss_fn(p, b)

            grad_fn = jax.value_and_grad(streamed_loss, has_aux=has_aux)
            if has_aux:
                (loss, aux), grads = grad_fn(params, batch)
            else:
                loss, grads = grad_fn(params, batch)
                aux = None
        else:
            grad_fn = jax.value_and_grad(loss_fn, has_aux=has_aux)
            if has_aux:
                (loss, aux), grads = grad_fn(params, batch)
            else:
                loss, grads = grad_fn(params, batch)
                aux = None
            if nonfinite_policy == "zero":
                grads = _nf.sanitize(grads)
            grads, new_ef = _zero.zero1_posthoc_reduce(
                grads, op=op, axis_name=axis_name, quantized=quantized,
                ef=ef, **knobs,
            )
        if overlap:
            # Consume the registration ledger (same discipline as the
            # streamed allreduce step).
            _fusion.take_stream_registrations()
        flag = None
        if nonfinite_policy in ("skip", "abort"):
            # Post-reduce detection: zero1 is SUM/AVERAGE-only, so a NaN
            # from any rank propagates into its shard image; the psum
            # agreement seam makes every rank skip together.
            flag = _nf.agree_flag(_nf.local_flag(grads), axis_name)
            _nf.note_detection(nonfinite_policy, "train_step")(flag)
        elif nonfinite_policy == "warn":
            _nf.note_detection("warn", "zero1")(_nf.local_flag(grads))
        loss = lax.pmean(loss, axis_name)
        new_params, new_opt = _zero.zero1_stream_update(
            optimizer, params, state.opt, grads,
            axis_name=axis_name, n_shards=n_shards,
            quantized=quantized, **knobs,
        )
        if flag is not None:
            new_params = _nf.select_on_flag(flag, params, new_params)
            new_opt = _nf.select_on_flag(flag, state.opt, new_opt)
            if use_ef:
                new_ef = _nf.select_on_flag(flag, ef, new_ef)
        new_state = Zero1State(
            opt=new_opt, ef=new_ef if use_ef else state.ef
        )
        outs = [
            new_params,
            jax.tree.map(lambda s: s[None], new_state),
            loss,
        ]
        if has_aux:
            aux = jax.tree.map(lambda a: lax.pmean(a, axis_name), aux)
            outs.append(aux)
        if nonfinite_policy == "abort":
            outs.append(flag)
        return tuple(outs)

    fn = _shard_map(
        step, mesh,
        in_specs=(P(), state_spec, P(axes[0] if len(axes) == 1 else axes)),
        out_specs=(P(), state_spec, P()) + ((P(),) * (
            (1 if has_aux else 0) + (1 if nonfinite_policy == "abort" else 0)
        )),
    )
    jitted = jax.jit(fn, donate_argnums=(0, 1) if donate else ())

    def _maybe_trace(step_fn):
        return _trace.wrap_step(
            step_fn,
            overlap=overlap,
            quantized=quantized,
            hierarchical=str(hierarchical),
            wire_dtype="int8" if quantized else "f32",
            op=ReduceOp(op).name,
            nonfinite=nonfinite_policy,
            zero1=True,
        )

    if nonfinite_policy != "abort":
        return _maybe_trace(jitted)

    def aborting_step(params, opt_state, batch):
        import numpy as np

        out = jitted(params, opt_state, batch)
        flag = out[-1]
        if float(np.asarray(flag)) > 0:
            from .. import HorovodInternalError

            if _trace.ACTIVE:
                _trace.TAP.flight_dump("guard-abort")
            raise HorovodInternalError(
                "non-finite gradient guard (policy abort): a rank "
                "produced NaN/Inf gradients this step; the zero1 update "
                "was not applied on any rank (cross-rank agreed)"
            )
        return out[:-1]

    return _maybe_trace(aborting_step)


# --- composed DP x TP fast path ----------------------------------------------
#
# docs/parallelism.md "Composed DP x TP fast path": a sharding-rules
# table (parallel/rules.py, regex -> PartitionSpec, first-match-wins)
# places the param tree on a (data, model) mesh; the loss runs on local
# shards calling parallel/tp.py layers bound to the model axis (ONE
# forward psum per Megatron half-block, its backward conjugate handled
# by tp_block_input/psum_replicated_grad); and the ENTIRE PR-4/9/12
# reduction stack — streamed per-bucket reduce-scatter ZeRO-1, the int8
# wire, bucket fusion — runs scoped to the DATA axis only. TP psums are
# never bucketized, never quantized, never re-planned onto DCN.


def init_composed_zero1_state(
    optimizer,
    params,
    rules: Any,
    mesh: Mesh,
    *,
    model_axis: str = "model",
    axis_name: Any = DATA_AXIS,
    threshold_bytes: Optional[int] = None,
    first_bucket_bytes: Optional[int] = None,
    quantized: bool = False,
):
    """:class:`Zero1State` for ``make_train_step(rules=..., zero1=True)``:
    per MODEL rank, the streamed per-bucket state of that rank's local
    param shards (``parallel/rules.local_shard_tree`` slices them), with
    the per-bucket stacks laid out ``[n_data, n_model, ...]`` — shard
    the leading two axes ``P(data, model)``; the step indexes its
    ``[0, 0]`` cell. The bucket partition is over each model rank's
    LOCAL leaves, so it round-trips bitwise with the in-step update.
    Composed mode carries no EF residual (the sharded-EF side channel is
    a single-axis feature); the int8 wire still applies per DP bucket."""
    from ..parallel import rules as _rules

    from ..parallel import zero as _zero

    rules = _rules.resolve_rules(rules)
    specs = _rules.match_partition_rules(rules, params)
    n_model = int(mesh.shape[model_axis])
    n_data = 1
    for ax in (tuple(axis_name) if isinstance(axis_name, (tuple, list))
               else (axis_name,)):
        n_data *= int(mesh.shape[ax])
    states = []
    for m in range(n_model):
        local = _rules.local_shard_tree(
            params, specs, {model_axis: (m, n_model)}
        )
        states.append(_zero.init_zero1_stream_state(
            optimizer, local, n_data,
            threshold_bytes=threshold_bytes,
            first_bucket_bytes=first_bucket_bytes,
            quantized=quantized, error_feedback=False,
        ))
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=1), *states)


def _build_composed_train_step(
    loss_fn: Callable[..., jax.Array],
    optimizer,
    mesh: Mesh,
    *,
    rules: Any,
    model_axis: str,
    axis_name: str = DATA_AXIS,
    op: ReduceOp = Average,
    fusion_threshold_bytes: Optional[int] = None,
    compression=Compression.none,
    hierarchical: Any = False,
    quantized: Optional[bool] = None,
    error_feedback: Optional[bool] = None,
    donate: bool = True,
    has_aux: bool = False,
    overlap: bool = False,
    first_bucket_bytes: Optional[int] = None,
    nonfinite: Optional[str] = None,
    topo_algorithm: Optional[str] = None,
    zero1: bool = False,
    tp_overlap: Optional[bool] = None,
    tuned_cfg: Any = None,
    tuned_source: str = "none",
):
    """The composed step: ``step(params, opt_state, batch)`` with params
    placed by the rule table (sharded leaves enter as local shards),
    batch sharded over the data axis, and gradient reduction scoped to
    the data axis only. Replicated-leaf gradients come out of the
    backward already FULL and model-identical — ``parallel/tp.py``'s
    f/g conjugate psums (``tp_block_input`` + ``row_parallel``) reduce
    the cotangents at every replicated->sharded boundary — so the DP
    reduction is the only gradient collective this step adds.

    The build is deferred to the first call: the live params decide the
    spec tree (validated by the Pass 5 preflight ALWAYS — not gated on
    HOROVOD_TPU_STATIC_CHECKS) and the optimizer state's placement is
    matched by the same rule table (optax trees embed the param names).
    """
    import optax

    from ..common.compat import needs_explicit_grad_reduce
    from ..parallel import rules as _rules
    from ..parallel import tp as _tp
    from ..parallel import zero as _zero
    from .. import tune as _tune

    rules = _rules.resolve_rules(rules)
    # The DP scope may itself be hierarchical — an explicit
    # ("cross", "local") axis TUPLE runs the zero1 RS/AG through the
    # compositor's two-level lowerings, still strictly on the data
    # axes. The model axis stays a single flat ICI axis.
    dp_axes = (
        tuple(axis_name) if isinstance(axis_name, (tuple, list))
        else (axis_name,)
    )
    for ax in dp_axes + (model_axis,):
        if ax not in mesh.axis_names:
            raise ValueError(
                f"composed mode needs mesh axes ({axis_name!r}, "
                f"{model_axis!r}); mesh has {tuple(mesh.axis_names)}"
            )
    if model_axis in dp_axes:
        raise ValueError(
            f"model_axis {model_axis!r} cannot also be a data axis"
        )
    axis_name = dp_axes[0] if len(dp_axes) == 1 else dp_axes
    if hierarchical == "auto":
        hierarchical = False  # the explicit axis tuple IS the hierarchy
    if hierarchical:
        raise ValueError(
            "composed rules= mode scopes hierarchy to the DP axes "
            "EXPLICITLY: pass axis_name=('cross', 'local') for a "
            "two-level DP scope instead of hierarchical=True — the TP "
            "psums must never be re-planned onto DCN, so the knob that "
            "re-plans the whole step is rejected"
        )
    if topo_algorithm is not None:
        raise ValueError(
            "topo_algorithm pins a compositor plan; the composed DP "
            "axis lowers flat and TP psums are never re-planned — drop "
            "topo_algorithm"
        )
    if compression is not Compression.none:
        raise ValueError(
            "composed mode rejects cast compression; use "
            "quantized=True for the DP-axis int8 wire"
        )
    if error_feedback:
        raise ValueError(
            "error feedback rides the single-axis streamed side "
            "channel; composed mode runs the int8 wire EF-off"
        )
    if op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        raise ValueError(
            f"composed mode reduces SUM/AVERAGE over the data axis; "
            f"got {ReduceOp(op).name}"
        )
    quantized = _resolve_quantized(quantized)
    _check_overlap_rejections(overlap, quantized, op)
    if quantized and len(dp_axes) > 1:
        raise ValueError(
            "quantized composed DP runs the flat int8 ring over ONE "
            "data axis; the two-level DP scope has no int8 RS+AG form "
            "— drop quantized or the axis tuple"
        )
    nonfinite_policy = _resolve_nonfinite(nonfinite)
    n_model = int(mesh.shape[model_axis])
    n_data = 1
    for ax in dp_axes:
        n_data *= int(mesh.shape[ax])
    # Old jax: the custom_vjp conjugate psums carry transpose
    # correctness and check_rep only constrains; new jax (vma): the
    # checker IS the transpose machinery and must be on.
    check = not needs_explicit_grad_reduce()

    built: dict = {}

    def _build(params, opt_state):
        threshold = fusion_threshold_bytes
        first = first_bucket_bytes
        if tuned_cfg is not None:
            live = _tune.step_signature(params, mesh=mesh)
            matched = _tune.signatures_match(tuned_cfg.signature, live)
            if matched:
                tk = _tune.tuned_step_kwargs(tuned_cfg)
                if threshold is None:
                    threshold = tk["fusion_threshold_bytes"]
                if first is None:
                    first = tk["first_bucket_bytes"]
            else:
                _tune.warn_signature_mismatch(
                    tuned_cfg, live.get("hash", "?"),
                    "make_train_step(rules=...)",
                )
            _tune.note_applied(
                tuned_source, tuned_cfg.signature_hash, matched,
                "make_train_step(rules=...)",
            )
        # Pass 5 preflight — ALWAYS enforced for the composed path.
        _rules.preflight_rules(rules, mesh, params)
        specs = _rules.match_partition_rules(rules, params)
        if zero1:
            if not isinstance(opt_state, Zero1State):
                raise TypeError(
                    "composed zero1=True expects the Zero1State from "
                    "hvd.init_composed_zero1_state(optimizer, params, "
                    f"rules, mesh, ...); got {type(opt_state).__name__}"
                )
            state_spec: Any = P(
                dp_axes if len(dp_axes) > 1 else dp_axes[0], model_axis
            )
        else:
            state_spec = _rules.match_partition_rules(rules, opt_state)
        knobs = dict(threshold_bytes=threshold, first_bucket_bytes=first)

        def step(params, opt_state, batch):
            if zero1:
                state = jax.tree.map(lambda s: s[0, 0], opt_state)

            def local_loss(p, b):
                if overlap:
                    p = _fusion.stream_param_groups(
                        p, op=op, axis_name=axis_name,
                        quantized=quantized, nonfinite=nonfinite_policy,
                        zero1=zero1, **knobs,
                    )
                # Pin the TP-path selection for the trace: tp_apply
                # (and any user loss built on parallel/tp.py) consults
                # tp.overlap_active() so `tp_overlap=True` here reaches
                # the model without threading a flag through user code.
                # None keeps HOROVOD_TP_OVERLAP in charge.
                with _tp.overlap_scope(tp_overlap):
                    return loss_fn(p, b)

            grad_fn = jax.value_and_grad(local_loss, has_aux=has_aux)
            if has_aux:
                (loss, aux), grads = grad_fn(params, batch)
            else:
                loss, grads = grad_fn(params, batch)
                aux = None
            flag = None
            if overlap:
                _fusion.take_stream_registrations()
            else:
                if nonfinite_policy in ("skip", "abort"):
                    flag = _nf.local_flag(grads)
                if nonfinite_policy == "zero":
                    grads = _nf.sanitize(grads)
                if zero1:
                    grads, _ = _zero.zero1_posthoc_reduce(
                        grads, op=op, axis_name=axis_name,
                        quantized=quantized, **knobs,
                    )
                else:
                    grads = _fusion.fused_allreduce(
                        grads, op=op, axis_name=axis_name,
                        threshold_bytes=threshold,
                        reduce_fn=(
                            _q.quantized_reduce_fn("flat")
                            if quantized else None
                        ),
                        label="composed-posthoc",
                        wire_dtype="int8" if quantized else "f32",
                    )
            if nonfinite_policy in ("skip", "abort"):
                post = _nf.local_flag(grads)
                flag = post if flag is None else jnp.maximum(flag, post)
                # Agreement over EVERY axis: a model rank's NaN must
                # skip the step on every rank of the whole mesh.
                flag = _nf.agree_flag(flag, dp_axes + (model_axis,))
                _nf.note_detection(nonfinite_policy, "composed")(flag)
            elif nonfinite_policy == "warn":
                _nf.note_detection("warn", "composed")(
                    _nf.local_flag(grads)
                )
            loss = lax.pmean(lax.pmean(loss, axis_name), model_axis)
            if zero1:
                new_params, new_opt = _zero.zero1_stream_update(
                    optimizer, params, state.opt, grads,
                    axis_name=axis_name, n_shards=n_data,
                    quantized=quantized, **knobs,
                )
                if flag is not None:
                    new_params = _nf.select_on_flag(
                        flag, params, new_params
                    )
                    new_opt = _nf.select_on_flag(flag, state.opt, new_opt)
                new_state = jax.tree.map(
                    lambda s: s[None, None],
                    Zero1State(opt=new_opt, ef=state.ef),
                )
            else:
                updates, new_opt = optimizer.update(
                    grads, opt_state, params
                )
                new_params = optax.apply_updates(params, updates)
                if flag is not None:
                    new_params = _nf.select_on_flag(
                        flag, params, new_params
                    )
                    new_opt = _nf.select_on_flag(flag, opt_state, new_opt)
                new_state = new_opt
            outs = [new_params, new_state, loss]
            if has_aux:
                outs.append(jax.tree.map(
                    lambda a: lax.pmean(
                        lax.pmean(a, axis_name), model_axis
                    ),
                    aux,
                ))
            if nonfinite_policy == "abort":
                outs.append(flag)
            return tuple(outs)

        extra = (1 if has_aux else 0) + (
            1 if nonfinite_policy == "abort" else 0
        )
        fn = _shard_map(
            step, mesh, check=check,
            in_specs=(specs, state_spec, P(axis_name)),
            out_specs=(specs, state_spec, P()) + (P(),) * extra,
        )
        jitted = jax.jit(fn, donate_argnums=(0, 1) if donate else ())

        def _maybe_trace(step_fn):
            return _trace.wrap_step(
                step_fn,
                composed=True, tp=n_model, dp=n_data,
                tp_overlap=_tp.tp_overlap_enabled(tp_overlap),
                overlap=overlap, quantized=quantized, zero1=zero1,
                wire_dtype="int8" if quantized else "f32",
                op=ReduceOp(op).name, nonfinite=nonfinite_policy,
            )

        if nonfinite_policy != "abort":
            return _maybe_trace(jitted), jitted, specs, state_spec

        def aborting_step(params, opt_state, batch):
            import numpy as np

            out = jitted(params, opt_state, batch)
            flag = out[-1]
            if float(np.asarray(flag)) > 0:
                from .. import HorovodInternalError

                if _trace.ACTIVE:
                    _trace.TAP.flight_dump("guard-abort")
                raise HorovodInternalError(
                    "non-finite gradient guard (policy abort): a rank "
                    "produced NaN/Inf gradients this step; the composed "
                    "update was not applied on any rank (cross-rank "
                    "agreed over data AND model axes)"
                )
            return out[:-1]

        return _maybe_trace(aborting_step), jitted, specs, state_spec

    def dispatch(params, opt_state, batch):
        if "step" not in built:
            step, jitted, specs, state_spec = _build(params, opt_state)
            built["step"] = step
            # The inner jax.jit step — HLO inspection (tests assert the
            # one-psum-per-block TP structure off it).
            dispatch.jitted = jitted
            # Digest integration (guard/digest.strip_rank_local): the
            # spec trees mark which leaves are TP-sharded — attach as
            # State.sharding_specs so cross-rank digests hash their
            # LAYOUT, never their (legitimately divergent) bytes.
            dispatch.sharding_specs = {
                "params": specs,
                **({} if zero1 else {"opt_state": state_spec}),
            }
        return built["step"](params, opt_state, batch)

    dispatch.sharding_specs = None
    dispatch.jitted = None
    return dispatch


def make_train_step(
    loss_fn: Callable[..., jax.Array],
    optimizer,
    mesh: Mesh,
    *,
    axis_name: str = DATA_AXIS,
    op: ReduceOp = Average,
    fusion_threshold_bytes: Optional[int] = None,
    compression=Compression.none,
    hierarchical: Any = False,
    quantized: Optional[bool] = None,
    error_feedback: Optional[bool] = None,
    donate: bool = True,
    has_aux: bool = False,
    overlap: bool = False,
    first_bucket_bytes: Optional[int] = None,
    nonfinite: Optional[str] = None,
    tuned: Any = None,
    topo_algorithm: Optional[str] = None,
    zero1: bool = False,
    rules: Any = None,
    model_axis: str = "model",
    tp_overlap: Optional[bool] = None,
):
    """See :func:`_build_train_step` for the core semantics — this public
    wrapper adds pinned offline tuning (docs/autotune.md "Compiled-path
    offline tuning").

    ``rules`` (docs/parallelism.md "Composed DP x TP fast path") switches
    to the composed builder: a sharding-rules table (a ``(regex,
    PartitionSpec)`` sequence or a shipped name like ``"gpt"`` —
    ``parallel/rules.py``) places params and optimizer state on the
    ``(axis_name, model_axis)`` mesh, ``loss_fn`` runs on the LOCAL
    shards calling ``parallel/tp.py`` layers bound to ``model_axis``
    (e.g. ``models.transformer.tp_apply``), and the whole
    overlap/quantized/zero1 reduction stack applies to the DATA axis
    only — TP psums are never bucketized, quantized, or re-planned.
    ``tp_overlap=True`` (default: the ``HOROVOD_TP_OVERLAP`` knob)
    additionally routes the TP layers through the chunked
    collective-matmul primitives (docs/parallelism.md "Fused TP
    overlap"): the residual stream token-shards over ``model_axis`` and
    the block psums dissolve into bidirectional ppermute chains
    overlapped with the matmuls — zero model-axis all-reduces in the
    step's HLO. ``zero1=True`` then takes the state from
    :func:`init_composed_zero1_state`. The returned step exposes
    ``step.sharding_specs`` (after the first call) for the guard's
    digest agreement (``guard/digest.strip_rank_local``).

    ``zero1=True`` (docs/overlap.md "Streamed ZeRO-1") shards the
    optimizer state per streamed bucket over the data axis: the step
    takes the :class:`Zero1State` from :func:`init_zero1_stream_state`
    (built with the SAME threshold/first-bucket/quantized knobs),
    reduce-scatters each gradient bucket — inside the backward with
    ``overlap=True`` — and all-gathers the shard-updated parameters.
    Composes with ``quantized=True`` (int8 ring RS, sharded EF residual)
    and ``hierarchical="auto"`` (two-level RS/AG on multi-slice meshes);
    a matching ``tuned`` config fills the same knobs it fills for the
    allreduce paths.

    ``tuned`` takes a ``tuned.json`` path, a
    :class:`horovod_tpu.tune.TunedConfig`, ``None`` (read
    ``HOROVOD_TUNED_FILE``), or ``False`` (explicitly untuned). With a
    tuning in hand the step build is deferred to the FIRST call: the
    live params' abstract signature (pytree structure + leaf
    shapes/dtypes + mesh axes) is compared against the tuning's key —
    on a match the pinned knobs fill every knob the caller left at its
    default (explicit arguments always win); on a mismatch a loud
    warning is logged and the step builds with untuned defaults, never
    with stale knobs. The applied source lands in ``hvd_tuned_info``
    (docs/metrics.md) and in eager plan verdicts
    (``core/xla_executor.py``).

    A tuned build is bitwise-identical to passing the same knob values
    by hand — ``horovod_tpu.tune.tuned_step_kwargs`` is the exact
    mapping, asserted by ``make tune-smoke``.
    """
    from .. import tune as _tune

    kwargs = dict(
        axis_name=axis_name, op=op,
        fusion_threshold_bytes=fusion_threshold_bytes,
        compression=compression, hierarchical=hierarchical,
        quantized=quantized, error_feedback=error_feedback,
        donate=donate, has_aux=has_aux, overlap=overlap,
        first_bucket_bytes=first_bucket_bytes, nonfinite=nonfinite,
        topo_algorithm=topo_algorithm, zero1=zero1,
    )
    tuned_cfg, tuned_source = _tune.resolve_tuned(tuned)
    if rules is not None:
        return _build_composed_train_step(
            loss_fn, optimizer, mesh, rules=rules, model_axis=model_axis,
            tp_overlap=tp_overlap,
            tuned_cfg=tuned_cfg, tuned_source=tuned_source, **kwargs,
        )
    if tp_overlap is not None:
        raise ValueError(
            "tp_overlap selects the fused collective-matmul TP path of "
            "the composed builder — pass rules=... (and a model axis); "
            "without tensor parallelism there is no TP psum to fuse"
        )
    if tuned_cfg is None:
        return _build_train_step(loss_fn, optimizer, mesh, **kwargs)

    state: dict = {}

    def dispatch(params, opt_state, batch):
        step = state.get("step")
        if step is None:
            live = _tune.step_signature(params, mesh=mesh)
            matched = _tune.signatures_match(tuned_cfg.signature, live)
            kw = dict(kwargs)
            if matched:
                tk = _tune.tuned_step_kwargs(tuned_cfg)
                if kw["fusion_threshold_bytes"] is None:
                    kw["fusion_threshold_bytes"] = tk[
                        "fusion_threshold_bytes"]
                if kw["first_bucket_bytes"] is None:
                    kw["first_bucket_bytes"] = tk["first_bucket_bytes"]
                if kw["quantized"] is None:
                    kw["quantized"] = tk["quantized"]
                if kw["hierarchical"] is False:
                    kw["hierarchical"] = tk["hierarchical"]
                if kw["topo_algorithm"] is None:
                    kw["topo_algorithm"] = tk["topo_algorithm"]
                if kw["zero1"] and kw["topo_algorithm"] == "split":
                    # No reduce-scatter decomposition of the FlexLink
                    # split exists; the zero1 lowering is decided by the
                    # mesh shape — fall back to per-bucket selection.
                    kw["topo_algorithm"] = None
            else:
                _tune.warn_signature_mismatch(
                    tuned_cfg, live.get("hash", "?"), "make_train_step"
                )
            _tune.note_applied(
                tuned_source, tuned_cfg.signature_hash, matched,
                "make_train_step",
            )
            step = _build_train_step(loss_fn, optimizer, mesh, **kw)
            state["step"] = step
        return step(params, opt_state, batch)

    return dispatch


def make_decode_step(
    *,
    n_heads: int,
    mesh: Optional[Mesh] = None,
    rules: Any = None,
    cache_rules: Any = None,
    model_axis: str = "model",
    dtype: Any = jnp.float32,
):
    """Build the compiled batched one-token greedy-decode step for
    ``hvd.serve()`` (docs/serving.md):

        step(params, cache, tokens, positions, page_table)
            -> (next_tokens [B] int32, new_cache)

    ``cache`` is the paged decode-state pytree
    (``serve/kvcache.make_decode_state``) and ``page_table`` the [B,
    max_pages] slot→page map; the forward is
    ``models/transformer.tp_decode_apply`` — the same param tree the
    composed train step shards, consumed as TP-local shards with ONE
    psum per Megatron half-block.

    With ``mesh`` + ``rules`` the step is shard_mapped: params placed by
    the rule table and the cache by ``cache_rules`` (default
    ``parallel/rules.GPT_CACHE_RULES`` — head dim over ``model_axis``),
    BOTH preflighted by the Pass 5 validator against the live trees
    before anything is traced, the composed-path discipline.
    tokens/positions/page_table replicate: data parallelism in serving
    is ENGINE-level (each DP replica runs its own step on its own
    batches — ``serve/engine.py``), not a mesh axis of the decode step.
    With ``mesh=None`` it is the dense single-chip reference the parity
    tests compare against the full-recompute :func:`tp_apply`.

    The build is deferred to the first call: the live params + cache
    decide the spec trees.
    """
    from ..common.compat import needs_explicit_grad_reduce
    from ..models.transformer import tp_decode_apply
    from ..parallel import rules as _rules

    if (mesh is None) != (rules is None):
        raise ValueError(
            "make_decode_step shards by TABLE: pass mesh= and rules= "
            "together (or neither for the dense reference)"
        )
    if mesh is not None and model_axis not in mesh.axis_names:
        raise ValueError(
            f"decode mesh needs axis {model_axis!r}; mesh has "
            f"{tuple(mesh.axis_names)}"
        )

    built: dict = {}

    def _build(params, cache):
        if mesh is None:
            def step(params, cache, tokens, positions, page_table):
                logits, new_cache = tp_decode_apply(
                    params, tokens, positions, cache, page_table,
                    n_heads=n_heads, model_axis=None, dtype=dtype,
                )
                next_tokens = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return next_tokens, new_cache

            return jax.jit(step)

        crules = (
            _rules.GPT_CACHE_RULES if cache_rules is None
            else _rules.resolve_rules(cache_rules)
        )
        resolved = _rules.resolve_rules(rules)
        # Pass 5 preflight over BOTH tables — always enforced.
        _rules.preflight_rules(resolved, mesh, params)
        _rules.preflight_rules(crules, mesh, cache)
        specs = _rules.match_partition_rules(resolved, params)
        cache_specs = _rules.match_partition_rules(crules, cache)

        def step(params, cache, tokens, positions, page_table):
            logits, new_cache = tp_decode_apply(
                params, tokens, positions, cache, page_table,
                n_heads=n_heads, model_axis=model_axis, dtype=dtype,
            )
            next_tokens = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return next_tokens, new_cache

        fn = _shard_map(
            step, mesh, check=not needs_explicit_grad_reduce(),
            in_specs=(specs, cache_specs, P(), P(), P()),
            out_specs=(P(), cache_specs),
        )
        return jax.jit(fn)

    def dispatch(params, cache, tokens, positions, page_table):
        if "step" not in built:
            built["step"] = _build(params, cache)
        return built["step"](params, cache, tokens, positions, page_table)

    return dispatch


class GradientAccumulator:
    """Local gradient accumulation helper — parity with
    ``backward_passes_per_step`` (``horovod/torch/__init__.py:110-150``):
    accumulate ``n`` microbatch gradients locally, then allreduce once."""

    def __init__(self, n: int):
        self.n = n

    def init(self, grads: Any) -> Any:
        return jax.tree.map(jnp.zeros_like, grads)

    def add(self, acc: Any, grads: Any) -> Any:
        return jax.tree.map(jnp.add, acc, grads)

    def should_reduce(self, step_count: int) -> bool:
        return (step_count + 1) % self.n == 0
