"""Eager-vs-compiled allreduce micro-benchmark worker.

Runs under the launcher (``hvdrun -np 2``) on the CPU backend and measures,
at a real communicator size, three latencies per payload size:

 - ``eager_np_us``   — numpy input through the full eager pipeline
   (enqueue → native-core negotiation → compiled XLA psum → host copy out);
 - ``eager_dev_us``  — jax-array input through the same pipeline's
   device-resident fast path (no ``device_put``/``np.asarray``; pack +
   collective + unpack are one executable, outputs stay on device);
 - ``compiled_us``   — the bare jitted ``shard_map(psum)`` on device-resident
   data: the floor, i.e. what the compiled training path pays.

``eager_* - compiled`` is the per-call overhead of the eager control plane —
the number the reference pays between framework op and NCCL launch
(VERDICT round-1 weak #3). Rank 0 prints one JSON line ``{"rows": [...]}``.

This is a CPU tool by design: multi-rank needs one device per process, and
the benchmark's subject (host-side pipeline overhead) is
platform-independent.
"""

from __future__ import annotations

import json
import sys
import time


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from horovod_tpu.jax import _shard_map

    size, rank = hvd.size(), hvd.rank()
    assert size > 1, "micro_bench must run under the launcher (-np >= 2)"

    # Same mesh the eager executor builds: one (leading) device per process.
    from horovod_tpu.core.xla_executor import rank_mesh_devices

    mesh_devices = rank_mesh_devices()
    mesh = Mesh(np.array(mesh_devices), ("micro",))
    sharding = NamedSharding(mesh, P("micro"))
    local_device = mesh_devices[rank]
    # Floor layout matches the eager executor's zero-copy device path
    # exactly (dim0-sharded global, local array = its own shard): the
    # floor must lower-bound the pipeline, not measure a different
    # (leading-axis) layout with its own copy behavior.
    psum_fn = jax.jit(
        _shard_map(
            lambda x: lax.psum(x, "micro"), mesh,
            in_specs=(P("micro"),), out_specs=P(),
        )
    )

    def global_arr(x_np):
        local = jax.device_put(x_np, local_device)
        return jax.make_array_from_single_device_arrays(
            (size * x_np.shape[0],) + x_np.shape[1:], sharding, [local]
        )

    rows = []
    for nbytes in (1 << 10, 1 << 16, 1 << 20, 1 << 24):
        n = nbytes // 4
        x_np = np.random.RandomState(rank).randn(n).astype(np.float32)
        x_dev = jnp.asarray(x_np)
        reps = max(3, min(30, (1 << 22) // nbytes))

        # Compiled floor: psum on device-resident data, carrier prebuilt.
        garr = global_arr(x_np)
        jax.block_until_ready(psum_fn(garr))
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(psum_fn(garr))
            ts.append(time.perf_counter() - t0)
        t_comp, t_comp_med = sum(ts) / reps, sorted(ts)[reps // 2]

        # Eager, numpy input (host pack + device_put + collective + asarray).
        # One name reused across reps — the training-steady-state pattern
        # (grad names repeat every step), which also exercises the core's
        # response-cache bit path like the reference's repeat iterations.
        hvd.allreduce(x_np, name=f"micro_np_{nbytes}")
        ts = []
        for i in range(reps):
            t0 = time.perf_counter()
            hvd.allreduce(x_np, name=f"micro_np_{nbytes}")
            ts.append(time.perf_counter() - t0)
        t_np, t_np_med = sum(ts) / reps, sorted(ts)[reps // 2]

        # Eager, device input (zero-host-copy fast path), same-name reuse.
        jax.block_until_ready(
            hvd.allreduce(x_dev, name=f"micro_dev_{nbytes}")
        )
        ts = []
        for i in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(
                hvd.allreduce(x_dev, name=f"micro_dev_{nbytes}")
            )
            ts.append(time.perf_counter() - t0)
        t_dev, t_dev_med = sum(ts) / reps, sorted(ts)[reps // 2]

        rows.append({
            "bytes": nbytes,
            "np": size,
            "eager_np_us": round(t_np * 1e6, 1),
            "eager_dev_us": round(t_dev * 1e6, 1),
            "compiled_us": round(t_comp * 1e6, 1),
            "overhead_np_us": round((t_np - t_comp) * 1e6, 1),
            "overhead_dev_us": round((t_dev - t_comp) * 1e6, 1),
            # Medians: robust to scheduler spikes (CI hosts can be a
            # single shared core; a 10ms preemption in one rep dominates
            # the mean).
            "eager_np_med_us": round(t_np_med * 1e6, 1),
            "eager_dev_med_us": round(t_dev_med * 1e6, 1),
            "compiled_med_us": round(t_comp_med * 1e6, 1),
            "overhead_np_med_us": round((t_np_med - t_comp_med) * 1e6, 1),
            "overhead_dev_med_us": round((t_dev_med - t_comp_med) * 1e6, 1),
        })
        # Keep ranks in lockstep between payload sizes.
        hvd.allreduce(np.zeros(1, np.float32), name=f"micro_bar_{nbytes}")

    if rank == 0:
        print(json.dumps({"rows": rows}), flush=True)
    hvd.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
