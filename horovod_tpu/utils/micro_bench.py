"""Eager-vs-compiled allreduce micro-benchmark worker.

Runs under the launcher (``hvdrun -np 2``) on the CPU backend and measures,
at a real communicator size, three latencies per payload size:

 - ``eager_np_us``   — numpy input through the full eager pipeline
   (enqueue → native-core negotiation → compiled XLA psum → host copy out);
 - ``eager_dev_us``  — jax-array input through the same pipeline's
   device-resident fast path (no ``device_put``/``np.asarray``; pack +
   collective + unpack are one executable, outputs stay on device);
 - ``compiled_us``   — the bare jitted ``shard_map(psum)`` on device-resident
   data: the floor, i.e. what the compiled training path pays.

``eager_* - compiled`` is the per-call overhead of the eager control plane —
the number the reference pays between framework op and NCCL launch
(VERDICT round-1 weak #3). Rank 0 prints one JSON line ``{"rows": [...]}``.

This is a CPU tool by design: multi-rank needs one device per process, and
the benchmark's subject (host-side pipeline overhead) is
platform-independent.
"""

from __future__ import annotations

import json
import sys
import time


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from horovod_tpu.jax import _shard_map

    size, rank = hvd.size(), hvd.rank()
    assert size > 1, "micro_bench must run under the launcher (-np >= 2)"

    # Same mesh the eager executor builds: one (leading) device per process.
    from horovod_tpu.core.xla_executor import rank_mesh_devices

    mesh_devices = rank_mesh_devices()
    mesh = Mesh(np.array(mesh_devices), ("micro",))
    sharding = NamedSharding(mesh, P("micro"))
    local_device = mesh_devices[rank]
    psum_fn = jax.jit(
        _shard_map(
            lambda x: lax.psum(x[0], "micro"), mesh,
            in_specs=(P("micro"),), out_specs=P(),
        )
    )

    def global_arr(x_np):
        local = jax.device_put(x_np[None, ...], local_device)
        return jax.make_array_from_single_device_arrays(
            (size,) + x_np.shape, sharding, [local]
        )

    rows = []
    for nbytes in (1 << 10, 1 << 16, 1 << 20, 1 << 24):
        n = nbytes // 4
        x_np = np.random.RandomState(rank).randn(n).astype(np.float32)
        x_dev = jnp.asarray(x_np)
        reps = max(3, min(30, (1 << 22) // nbytes))

        # Compiled floor: psum on device-resident data, carrier prebuilt.
        garr = global_arr(x_np)
        jax.block_until_ready(psum_fn(garr))
        t0 = time.perf_counter()
        for _ in range(reps):
            jax.block_until_ready(psum_fn(garr))
        t_comp = (time.perf_counter() - t0) / reps

        # Eager, numpy input (host pack + device_put + collective + asarray).
        hvd.allreduce(x_np, name=f"micro_np_warm_{nbytes}")
        t0 = time.perf_counter()
        for i in range(reps):
            hvd.allreduce(x_np, name=f"micro_np_{nbytes}_{i}")
        t_np = (time.perf_counter() - t0) / reps

        # Eager, device input (zero-host-copy fast path).
        jax.block_until_ready(
            hvd.allreduce(x_dev, name=f"micro_dev_warm_{nbytes}")
        )
        t0 = time.perf_counter()
        for i in range(reps):
            jax.block_until_ready(
                hvd.allreduce(x_dev, name=f"micro_dev_{nbytes}_{i}")
            )
        t_dev = (time.perf_counter() - t0) / reps

        rows.append({
            "bytes": nbytes,
            "np": size,
            "eager_np_us": round(t_np * 1e6, 1),
            "eager_dev_us": round(t_dev * 1e6, 1),
            "compiled_us": round(t_comp * 1e6, 1),
            "overhead_np_us": round((t_np - t_comp) * 1e6, 1),
            "overhead_dev_us": round((t_dev - t_comp) * 1e6, 1),
        })
        # Keep ranks in lockstep between payload sizes.
        hvd.allreduce(np.zeros(1, np.float32), name=f"micro_bar_{nbytes}")

    if rank == 0:
        print(json.dumps({"rows": rows}), flush=True)
    hvd.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
