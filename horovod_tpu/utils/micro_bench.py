"""Eager-vs-compiled allreduce micro-benchmark worker.

Runs under the launcher (``hvdrun -np 2``) on the CPU backend and measures,
at a real communicator size, three latencies per payload size:

 - ``eager_np_us``   — numpy input through the full eager pipeline
   (enqueue → native-core negotiation → compiled XLA psum → host copy out);
 - ``eager_dev_us``  — jax-array input through the same pipeline's
   device-resident fast path (no ``device_put``/``np.asarray``; pack +
   collective + unpack are one executable, outputs stay on device);
 - ``compiled_us``   — the data-plane floor: the executor's OWN device
   path (identical global-array construction + the SAME cached
   executable an eager call uses) invoked directly, without the control
   plane. ``eager - compiled`` therefore isolates exactly the control
   plane (enqueue, negotiation, plan dispatch, thread handoffs) by
   construction. An independently-built ``shard_map(psum)`` is also
   timed (``ref_psum_*`` columns) for cross-checking, but it is a
   DIFFERENT collective program — at bandwidth-bound sizes its time can
   exceed the eager path's, which is why basing overhead on it produced
   negative rows (VERDICT r4 #2).

``eager_* - compiled`` is the per-call overhead of the eager control plane —
the number the reference pays between framework op and NCCL launch
(VERDICT round-1 weak #3). Rank 0 prints one JSON line ``{"rows": [...]}``.

This is a CPU tool by design: multi-rank needs one device per process, and
the benchmark's subject (host-side pipeline overhead) is
platform-independent.
"""

from __future__ import annotations

import json
import sys
import time


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from horovod_tpu.jax import _shard_map

    size, rank = hvd.size(), hvd.rank()
    assert size > 1, "micro_bench must run under the launcher (-np >= 2)"

    # Same mesh the eager executor builds: one (leading) device per process.
    from horovod_tpu.core.xla_executor import rank_mesh_devices

    mesh_devices = rank_mesh_devices()
    mesh = Mesh(np.array(mesh_devices), ("micro",))
    sharding = NamedSharding(mesh, P("micro"))
    local_device = mesh_devices[rank]
    # Floor layout matches the eager executor's zero-copy device path
    # exactly (dim0-sharded global, local array = its own shard): the
    # floor must lower-bound the pipeline, not measure a different
    # (leading-axis) layout with its own copy behavior.
    psum_fn = jax.jit(
        _shard_map(
            lambda x: lax.psum(x, "micro"), mesh,
            in_specs=(P("micro"),), out_specs=P(),
        )
    )

    def global_arr(x_np):
        local = jax.device_put(x_np, local_device)
        return jax.make_array_from_single_device_arrays(
            (size * x_np.shape[0],) + x_np.shape[1:], sharding, [local]
        )

    # Untimed alignment barrier before every timed floor rep: the eager
    # pipeline's negotiation aligns the ranks right before its collective
    # launches, so an UNsynchronized floor loop measures peer-arrival
    # skew as latency and can exceed the full eager time at
    # bandwidth-bound sizes (negative overhead, VERDICT r4 #2). A tiny
    # psum aligns ranks to within microseconds at negligible cost
    # (psum_fn specializes per shape; only the array is tiny).
    _bar = global_arr(np.zeros(1, np.float32))

    def align():
        jax.block_until_ready(psum_fn(_bar))

    rows = []
    for nbytes in (1 << 10, 1 << 16, 1 << 20, 1 << 24):
        n = nbytes // 4
        x_np = np.random.RandomState(rank).randn(n).astype(np.float32)
        x_dev = jnp.asarray(x_np)
        # Rep counts sized so the median is stable (VERDICT r4 #2: 3 reps
        # at 16 MB let harness noise exceed signal and produced negative
        # overhead rows): >=10 even for the largest payload, 100 for the
        # latency-dominated small ones.
        reps = max(10, min(100, (1 << 25) // nbytes))

        # Compiled floor: the executor's own data-plane path, no control
        # plane. Both ranks call it in lockstep (deterministic loop), so
        # the cross-rank collective stays ordered without negotiation.
        # The pure-Python Runtime fallback (native core unavailable /
        # HOROVOD_TPU_CORE=python) has no .executor — fall back to the
        # independent psum program as the floor there, flagged per row.
        from horovod_tpu.common.types import ReduceOp, TensorTableEntry

        rt_ex = getattr(hvd._rt(), "executor", None)
        if rt_ex is not None and hasattr(rt_ex, "_allreduce_device"):
            floor_source = "executor_device_path"

            def floor_call():
                e = TensorTableEntry(name=f"floor_{nbytes}", tensor=x_dev)
                return rt_ex._allreduce_device(
                    [e], op=ReduceOp.SUM, adasum=False, hier=False,
                    pre=1.0, post=1.0, participants=size,
                )[f"floor_{nbytes}"]
        else:
            floor_source = "independent_psum"
            _floor_garr = global_arr(x_np)

            def floor_call():
                return psum_fn(_floor_garr)

        jax.block_until_ready(floor_call())
        ts = []
        for _ in range(reps):
            align()
            t0 = time.perf_counter()
            jax.block_until_ready(floor_call())
            ts.append(time.perf_counter() - t0)
        t_comp, t_comp_med = sum(ts) / reps, sorted(ts)[reps // 2]
        # Noise band of the floor itself (IQR): at bandwidth-bound sizes
        # run-to-run variance of the collective exceeds the control
        # plane's contribution, and an overhead below the band is
        # indistinguishable from zero — report it as such instead of a
        # meaningless (sometimes negative) difference.
        srt = sorted(ts)
        noise_band = srt[(3 * reps) // 4] - srt[reps // 4]

        # Independent reference program (cross-check only; see module
        # docstring for why it must not be the overhead baseline).
        garr = global_arr(x_np)
        jax.block_until_ready(psum_fn(garr))
        ts = []
        for _ in range(reps):
            align()
            t0 = time.perf_counter()
            jax.block_until_ready(psum_fn(garr))
            ts.append(time.perf_counter() - t0)
        t_ref, t_ref_med = sum(ts) / reps, sorted(ts)[reps // 2]

        # Eager, numpy input (host pack + device_put + collective + asarray).
        # One name reused across reps — the training-steady-state pattern
        # (grad names repeat every step), which also exercises the core's
        # response-cache bit path like the reference's repeat iterations.
        hvd.allreduce(x_np, name=f"micro_np_{nbytes}")
        ts = []
        for i in range(reps):
            t0 = time.perf_counter()
            hvd.allreduce(x_np, name=f"micro_np_{nbytes}")
            ts.append(time.perf_counter() - t0)
        t_np, t_np_med = sum(ts) / reps, sorted(ts)[reps // 2]

        # Eager, device input (zero-host-copy fast path), same-name reuse.
        jax.block_until_ready(
            hvd.allreduce(x_dev, name=f"micro_dev_{nbytes}")
        )
        ts = []
        for i in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(
                hvd.allreduce(x_dev, name=f"micro_dev_{nbytes}")
            )
            ts.append(time.perf_counter() - t0)
        t_dev, t_dev_med = sum(ts) / reps, sorted(ts)[reps // 2]

        def _ovh(eager_med):
            d = eager_med - t_comp_med
            if abs(d) <= noise_band:
                return 0.0, True
            return round(d * 1e6, 1), False

        ovh_np, np_noise = _ovh(t_np_med)
        ovh_dev, dev_noise = _ovh(t_dev_med)
        rows.append({
            "bytes": nbytes,
            "np": size,
            "reps": reps,
            "noise_band_us": round(noise_band * 1e6, 1),
            "overhead_within_noise": {"np": np_noise, "dev": dev_noise},
            "floor_source": floor_source,
            # Medians FIRST-CLASS: robust to scheduler spikes (CI hosts
            # can be a single shared core; one 10ms preemption dominates
            # a mean). Quote these; the means are kept for reference.
            "eager_np_med_us": round(t_np_med * 1e6, 1),
            "eager_dev_med_us": round(t_dev_med * 1e6, 1),
            "compiled_med_us": round(t_comp_med * 1e6, 1),
            "overhead_np_med_us": ovh_np,
            "overhead_dev_med_us": ovh_dev,
            "eager_np_us": round(t_np * 1e6, 1),
            "eager_dev_us": round(t_dev * 1e6, 1),
            "compiled_us": round(t_comp * 1e6, 1),
            "overhead_np_us": round((t_np - t_comp) * 1e6, 1),
            "overhead_dev_us": round((t_dev - t_comp) * 1e6, 1),
            "ref_psum_med_us": round(t_ref_med * 1e6, 1),
            "ref_psum_us": round(t_ref * 1e6, 1),
        })
        # Keep ranks in lockstep between payload sizes.
        hvd.allreduce(np.zeros(1, np.float32), name=f"micro_bar_{nbytes}")

    if rank == 0:
        print(json.dumps({"rows": rows}), flush=True)
    hvd.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
