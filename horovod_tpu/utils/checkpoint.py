"""Checkpoint/resume helpers.

The reference delegates checkpointing to the frameworks and supplies the
*consistency* primitives (broadcast of restored state + rank-0-saves
convention; SURVEY.md §5 "Checkpoint / resume"). This module packages that
pattern for JAX pytrees: orbax-backed when available, npz fallback, with
``restore_checkpoint(..., broadcast=True)`` ensuring every rank resumes
from identical state.

Sharded checkpoints (docs/fault_tolerance.md "Elastic resharding"): when
``save_checkpoint`` is given a :class:`~horovod_tpu.parallel.reshard.
LayoutManifest`, every rank writes its OWN shard payload
(``step_{N}.rank{r}.npz`` — TP-sharded leaves sliced per the rules
engine's specs, Zero1State rows per the bucket layout) and rank 0 writes
the layout manifest LAST (``manifest_step_{N}.json``, same tmp+fsync+
replace discipline), so a reader either sees a complete checkpoint or no
manifest at all. ``restore_checkpoint`` then assembles the GLOBAL leaves
from the shard payloads and, when the target's Zero1State carries a
different shard count than the manifest, routes the state through the
reshard planner — a checkpoint taken at one world shape restores onto a
different one. Manifest-less legacy checkpoints restore replicated
exactly as before; a torn manifest refuses loudly rather than falling
back silently.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any, List, Optional, Tuple

import numpy as np

logger = logging.getLogger("horovod_tpu.checkpoint")


def _flatten(tree: Any):
    import jax

    leaves, treedef = jax.tree.flatten(tree)
    return leaves, treedef


def _atomic_write(final_path: str, write_fn) -> None:
    """Write via a same-directory temp file + ``os.replace`` so a crash
    mid-write can never leave a torn file under the final name: readers
    see the complete old content or the complete new content, nothing in
    between (POSIX rename atomicity)."""
    tmp = f"{final_path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            write_fn(f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, final_path)
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


def _reshard_mod():
    from ..parallel import reshard

    return reshard


def _is_zero1(node: Any) -> bool:
    from ..parallel.zero import Zero1State

    return isinstance(node, Zero1State)


def _rank_local_paths(tree: Any) -> List[str]:
    """Tree paths of rank-local nodes (Zero1State shard stacks, EF
    residuals) — the leaves ``guard/digest.strip_rank_local`` excludes
    from cross-rank agreement and a broadcast must never clobber."""
    import jax

    from ..ops.quantized import EFState
    from ..parallel.rules import _key_name
    from ..parallel.zero import Zero1State

    def stop(n):
        return isinstance(n, (Zero1State, EFState))

    flat = jax.tree_util.tree_flatten_with_path(tree, is_leaf=stop)[0]
    return [
        "/".join(_key_name(k) for k in path)
        for path, leaf in flat if stop(leaf)
    ]


def _sharded_flatten(tree: Any):
    """Flatten with Zero1State nodes as leaves — the unit the manifest
    and the per-rank payloads are keyed by."""
    import jax

    return jax.tree.flatten(tree, is_leaf=_is_zero1)


def save_checkpoint(path: str, tree: Any, step: int = 0,
                    use_orbax: Optional[bool] = None, *,
                    manifest: Any = None, rank: int = 0) -> str:
    """Save a pytree. Without ``manifest``: the legacy replicated form —
    call from rank 0 only (the reference convention: 'save only on
    rank 0'). With ``manifest`` (a ``parallel/reshard.LayoutManifest``
    from ``build_manifest``): the sharded form — EVERY rank calls with
    its own ``rank`` and writes only its shard payload; rank 0
    additionally writes the manifest and the ``latest.json`` pointer,
    LAST, so it must save after the other ranks' payloads are durable
    (after a barrier on a real fleet; last in the loop for in-process
    virtual meshes).

    Writes are ATOMIC (temp file + ``os.replace``, fsynced) for every
    payload, the manifest, and the pointer — a kill mid-save leaves the
    previous checkpoint fully restorable instead of a torn "latest"
    (the orbax path is already atomic via its own finalize rename)."""
    if manifest is not None:
        return _save_sharded(path, tree, step, manifest, rank)
    if use_orbax is None:
        try:
            import orbax.checkpoint  # noqa: F401

            use_orbax = True
        except ImportError:
            use_orbax = False
    os.makedirs(path, exist_ok=True)
    if use_orbax:
        import orbax.checkpoint as ocp

        ckpt_dir = os.path.join(os.path.abspath(path), f"step_{step}")
        ckptr = ocp.PyTreeCheckpointer()
        ckptr.save(ckpt_dir, tree, force=True)
    else:
        leaves, _ = _flatten(tree)
        payload = {
            f"leaf_{i}": np.asarray(l) for i, l in enumerate(leaves)
        }
        _atomic_write(
            os.path.join(path, f"step_{step}.npz"),
            lambda f: np.savez(f, **payload),
        )
    meta = json.dumps({"step": step, "orbax": use_orbax}).encode()
    _atomic_write(
        os.path.join(path, "latest.json"), lambda f: f.write(meta)
    )
    return path


def _zero1_row_index(manifest, entry: dict, rank: int) -> int:
    R = _reshard_mod()
    axis = entry.get("axis", "data")
    coords = R.rank_coords(manifest.mesh_axes, rank)
    if axis not in coords:
        raise ValueError(
            f"zero1 layout is scoped to axis {axis!r} but the manifest "
            f"mesh {manifest.mesh_axes} has no such axis"
        )
    return coords[axis]


def _save_sharded(path: str, tree: Any, step: int, manifest: Any,
                  rank: int) -> str:
    import jax

    R = _reshard_mod()
    os.makedirs(path, exist_ok=True)
    from ..parallel.rules import _key_name

    mesh_sizes = dict(manifest.mesh_axes)
    coords = R.rank_coords(manifest.mesh_axes, rank)
    flat = jax.tree_util.tree_flatten_with_path(
        tree, is_leaf=_is_zero1
    )[0]
    payload = {}
    li = zi = 0
    for key_path, leaf in flat:
        name = "/".join(_key_name(k) for k in key_path)
        if _is_zero1(leaf):
            entry = manifest.zero1.get(name)
            if entry is None:
                raise ValueError(
                    f"tree holds a Zero1State at {name!r} but the "
                    f"manifest records none there (known: "
                    f"{sorted(manifest.zero1)}) — rebuild the manifest"
                )
            row = _zero1_row_index(manifest, entry, rank)
            for j, arr in enumerate(jax.tree.leaves(leaf)):
                payload[f"z{zi}_{j}"] = np.asarray(
                    jax.device_get(arr)
                )[row]
            zi += 1
            continue
        entry = manifest.leaves[li]
        arr = np.asarray(jax.device_get(leaf))
        if list(arr.shape) != list(entry["shape"]):
            raise ValueError(
                f"leaf {entry['path']} has shape {arr.shape} but the "
                f"manifest records {entry['shape']} — save_checkpoint "
                f"expects the GLOBAL (host-view) leaf; rebuild the "
                f"manifest for this tree"
            )
        sl = R.leaf_slices(entry["spec"], arr.shape, mesh_sizes, coords)
        payload[f"leaf_{li}"] = arr[sl]
        li += 1
    if li != len(manifest.leaves) or zi != len(manifest.zero1):
        raise ValueError(
            f"tree/manifest mismatch: tree has {li} leaves + {zi} "
            f"zero1 nodes, manifest records {len(manifest.leaves)} + "
            f"{len(manifest.zero1)} — rebuild the manifest for this tree"
        )
    _atomic_write(
        os.path.join(path, f"step_{step}.rank{rank}.npz"),
        lambda f: np.savez(f, **payload),
    )
    if rank == 0:
        man = R.LayoutManifest(
            mesh_axes=manifest.mesh_axes, leaves=manifest.leaves,
            zero1=manifest.zero1, rules_id=manifest.rules_id,
            step=int(step),
        )
        blob = man.to_json().encode()
        _atomic_write(
            os.path.join(path, f"manifest_step_{step}.json"),
            lambda f: f.write(blob),
        )
        meta = json.dumps({
            "step": int(step), "orbax": False, "sharded": True,
            "world": man.world,
        }).encode()
        _atomic_write(
            os.path.join(path, "latest.json"), lambda f: f.write(meta)
        )
    return path


def latest_step(path: str) -> Optional[int]:
    meta = os.path.join(path, "latest.json")
    if not os.path.exists(meta):
        return None
    with open(meta) as f:
        return int(json.load(f)["step"])


def restore_checkpoint(path: str, target: Any, step: Optional[int] = None,
                       broadcast: bool = True, root_rank: int = 0,
                       ef_policy: str = "fold") -> Any:
    """Restore a pytree saved by ``save_checkpoint``.

    Sharded checkpoints (a ``manifest_step_{N}.json`` next to per-rank
    payloads) are assembled to GLOBAL leaves from every rank's shard; a
    Zero1State in ``target`` whose leading shard count differs from the
    manifest's layout is routed through the reshard planner
    (``parallel/reshard``), so a checkpoint saved at one world shape
    restores onto a different one. A torn manifest (unparsable, or
    missing rank payloads) refuses loudly — it never silently falls
    back to the legacy path. ``ef_policy`` ("fold"/"zero") governs
    error-feedback residuals across a shard-count change.

    Legacy manifest-less checkpoints restore replicated exactly as
    before. With ``broadcast=True`` (default) the restored state is
    broadcast from ``root_rank`` so ranks that resumed from stale or
    missing files still end up consistent — unless the tree contains
    RANK-LOCAL leaves (Zero1State shards, EF residuals), which a
    broadcast would clobber with rank 0's rows: that refuses loudly,
    naming the offending paths (use a sharded checkpoint, or
    broadcast=False)."""
    meta_path = os.path.join(path, "latest.json")
    tree = target
    restored_sharded = False
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
        step = meta["step"] if step is None else step
        man_path = os.path.join(path, f"manifest_step_{step}.json")
        if meta.get("sharded") or os.path.exists(man_path):
            tree = _restore_sharded(path, target, step, ef_policy)
            restored_sharded = True
        elif meta.get("orbax"):
            import orbax.checkpoint as ocp

            ckptr = ocp.PyTreeCheckpointer()
            tree = ckptr.restore(
                os.path.join(os.path.abspath(path), f"step_{step}"),
                item=target,
            )
        else:
            import jax

            data = np.load(os.path.join(path, f"step_{step}.npz"))
            leaves, treedef = _flatten(target)
            restored = [data[f"leaf_{i}"] for i in range(len(leaves))]
            tree = jax.tree.unflatten(treedef, restored)
    if broadcast and not restored_sharded:
        import horovod_tpu as hvd

        if hvd.is_initialized() and hvd.size() > 1:
            offending = _rank_local_paths(tree)
            if offending:
                raise ValueError(
                    "restore_checkpoint(broadcast=True) would overwrite "
                    "RANK-LOCAL state with rank "
                    f"{root_rank}'s rows at: {offending} — Zero1State "
                    "shards and EF residuals are distinct per rank by "
                    "construction. Save a sharded checkpoint "
                    "(save_checkpoint(..., manifest=build_manifest(...)))"
                    " or pass broadcast=False and restore each rank's "
                    "own payload (docs/fault_tolerance.md 'Elastic "
                    "resharding')."
                )
            tree = hvd.broadcast_variables(tree, root_rank=root_rank)
    return tree


def _restore_sharded(path: str, target: Any, step: int,
                     ef_policy: str) -> Any:
    import jax

    R = _reshard_mod()
    man_path = os.path.join(path, f"manifest_step_{step}.json")
    try:
        with open(man_path) as f:
            manifest = R.LayoutManifest.from_json(f.read())
    except FileNotFoundError:
        raise RuntimeError(
            f"checkpoint at {path} step {step} is marked sharded but "
            f"{os.path.basename(man_path)} is missing — the save was "
            f"torn before the manifest landed; refusing to guess a "
            f"layout (restore an older step)"
        )
    except (json.JSONDecodeError, ValueError, KeyError) as e:
        raise RuntimeError(
            f"checkpoint layout manifest {man_path} is torn or invalid "
            f"({e}); refusing to restore from a checkpoint whose layout "
            f"cannot be trusted (restore an older step)"
        )
    payloads = []
    for r in range(manifest.world):
        p = os.path.join(path, f"step_{step}.rank{r}.npz")
        if not os.path.exists(p):
            raise RuntimeError(
                f"sharded checkpoint at {path} step {step} is missing "
                f"the rank-{r} payload {os.path.basename(p)} (manifest "
                f"says world={manifest.world}) — the save was torn; "
                f"refusing partial restore"
            )
        payloads.append(np.load(p))

    mesh_sizes = dict(manifest.mesh_axes)
    leaves, treedef = _sharded_flatten(target)
    n_z_target = sum(1 for l in leaves if _is_zero1(l))
    if len(leaves) - n_z_target != len(manifest.leaves) or \
            n_z_target != len(manifest.zero1):
        raise ValueError(
            f"restore target has {len(leaves) - n_z_target} leaves + "
            f"{n_z_target} Zero1State nodes but the checkpoint records "
            f"{len(manifest.leaves)} + {len(manifest.zero1)} — the "
            f"target tree does not match what was saved"
        )
    zero1_paths = sorted(manifest.zero1)
    out = []
    li = zi = 0
    for leaf in leaves:
        if _is_zero1(leaf):
            entry = manifest.zero1[zero1_paths[zi]]
            out.append(_restore_zero1(
                R, manifest, entry, payloads, zi, leaf, ef_policy
            ))
            zi += 1
            continue
        entry = manifest.leaves[li]
        out.append(_assemble_leaf(R, entry, payloads, li, mesh_sizes,
                                  manifest.mesh_axes))
        li += 1
    return jax.tree.unflatten(treedef, out)


def _assemble_leaf(R, entry: dict, payloads, li: int, mesh_sizes,
                   mesh_axes) -> np.ndarray:
    shape = tuple(int(d) for d in entry["shape"])
    first = payloads[0][f"leaf_{li}"]
    if entry.get("spec") is None:
        return first
    out = np.zeros(shape, dtype=first.dtype)
    for r, data in enumerate(payloads):
        coords = R.rank_coords(mesh_axes, r)
        sl = R.leaf_slices(entry["spec"], shape, mesh_sizes, coords)
        out[sl] = data[f"leaf_{li}"]
    return out


def _restore_zero1(R, manifest, entry: dict, payloads, zi: int,
                   target_node: Any, ef_policy: str) -> Any:
    import jax

    layout = R.Zero1Layout.from_dict(entry)
    axis = entry.get("axis", "data")
    for a, size in manifest.mesh_axes:
        if a != axis and int(size) != 1:
            raise ValueError(
                f"sharded checkpoint holds Zero1State scoped to axis "
                f"{axis!r} on mesh {manifest.mesh_axes}: restoring "
                f"zero1 state saved with a non-trivial {a!r} axis "
                f"needs a re-init from the gathered params (the rows "
                f"differ per {a!r} coordinate) — docs/fault_tolerance.md"
                f" 'Elastic resharding'"
            )
    # One payload row per data-axis coordinate, stacked in row order.
    rows_by_idx = {}
    for r in range(manifest.world):
        idx = _zero1_row_index(manifest, entry, r)
        rows_by_idx.setdefault(idx, r)
    if sorted(rows_by_idx) != list(range(layout.n_shards)):
        raise ValueError(
            f"manifest mesh {manifest.mesh_axes} yields zero1 rows "
            f"{sorted(rows_by_idx)} but the layout has "
            f"{layout.n_shards} shards — the manifest is inconsistent"
        )
    t_leaves, t_def = jax.tree.flatten(target_node)
    stacked = []
    for j in range(len(t_leaves)):
        rows = [
            payloads[rows_by_idx[i]][f"z{zi}_{j}"]
            for i in range(layout.n_shards)
        ]
        stacked.append(np.stack(rows))
    old_state = jax.tree.unflatten(t_def, stacked)
    target_n = R._state_n_shards(target_node)
    if target_n is None or target_n == layout.n_shards:
        return old_state
    new_state, report = R.reshard_zero1_state(
        old_state, target_n, layout=layout, ef_policy=ef_policy,
        trigger="checkpoint", axis=axis,
    )
    logger.info(
        "checkpoint restore resharded zero1 state %d->%d shards "
        "(%d bytes moved)", layout.n_shards, target_n,
        report["moved_bytes"],
    )
    return new_state
