"""Checkpoint/resume helpers.

The reference delegates checkpointing to the frameworks and supplies the
*consistency* primitives (broadcast of restored state + rank-0-saves
convention; SURVEY.md §5 "Checkpoint / resume"). This module packages that
pattern for JAX pytrees: orbax-backed when available, npz fallback, with
``restore_checkpoint(..., broadcast=True)`` ensuring every rank resumes
from identical state.
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional

import numpy as np


def _flatten(tree: Any):
    import jax

    leaves, treedef = jax.tree.flatten(tree)
    return leaves, treedef


def _atomic_write(final_path: str, write_fn) -> None:
    """Write via a same-directory temp file + ``os.replace`` so a crash
    mid-write can never leave a torn file under the final name: readers
    see the complete old content or the complete new content, nothing in
    between (POSIX rename atomicity)."""
    tmp = f"{final_path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            write_fn(f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, final_path)
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


def save_checkpoint(path: str, tree: Any, step: int = 0,
                    use_orbax: Optional[bool] = None) -> str:
    """Save a pytree. Call from rank 0 only (the reference convention:
    'save only on rank 0').

    Writes are ATOMIC (temp file + ``os.replace``, fsynced) for both the
    npz payload and the ``latest.json`` pointer — a kill mid-save leaves
    the previous checkpoint fully restorable instead of a torn "latest"
    (the orbax path is already atomic via its own finalize rename). The
    pointer is written LAST, after the payload it names is durable."""
    if use_orbax is None:
        try:
            import orbax.checkpoint  # noqa: F401

            use_orbax = True
        except ImportError:
            use_orbax = False
    os.makedirs(path, exist_ok=True)
    if use_orbax:
        import orbax.checkpoint as ocp

        ckpt_dir = os.path.join(os.path.abspath(path), f"step_{step}")
        ckptr = ocp.PyTreeCheckpointer()
        ckptr.save(ckpt_dir, tree, force=True)
    else:
        leaves, _ = _flatten(tree)
        payload = {
            f"leaf_{i}": np.asarray(l) for i, l in enumerate(leaves)
        }
        _atomic_write(
            os.path.join(path, f"step_{step}.npz"),
            lambda f: np.savez(f, **payload),
        )
    meta = json.dumps({"step": step, "orbax": use_orbax}).encode()
    _atomic_write(
        os.path.join(path, "latest.json"), lambda f: f.write(meta)
    )
    return path


def latest_step(path: str) -> Optional[int]:
    meta = os.path.join(path, "latest.json")
    if not os.path.exists(meta):
        return None
    with open(meta) as f:
        return int(json.load(f)["step"])


def restore_checkpoint(path: str, target: Any, step: Optional[int] = None,
                       broadcast: bool = True, root_rank: int = 0) -> Any:
    """Restore a pytree saved by ``save_checkpoint``. With
    ``broadcast=True`` (default) the restored state is broadcast from
    ``root_rank`` so ranks that resumed from stale/missing files still end
    up consistent — the reference's restart pattern."""
    meta_path = os.path.join(path, "latest.json")
    tree = target
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
        step = meta["step"] if step is None else step
        if meta.get("orbax"):
            import orbax.checkpoint as ocp

            ckptr = ocp.PyTreeCheckpointer()
            tree = ckptr.restore(
                os.path.join(os.path.abspath(path), f"step_{step}"),
                item=target,
            )
        else:
            import jax

            data = np.load(os.path.join(path, f"step_{step}.npz"))
            leaves, treedef = _flatten(target)
            restored = [data[f"leaf_{i}"] for i in range(len(leaves))]
            tree = jax.tree.unflatten(treedef, restored)
    if broadcast:
        import horovod_tpu as hvd

        if hvd.is_initialized() and hvd.size() > 1:
            tree = hvd.broadcast_variables(tree, root_rank=root_rank)
    return tree
