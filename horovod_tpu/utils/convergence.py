"""Convergence evidence for the lossy/sharded data paths.

Trains the same small transformer LM from identical init on identical
batches under three gradient paths — full-precision DP, int8-quantized
wire (``ops/quantized.py``), and int8 wire composed with ZeRO-1 sharded
optimizer state (``parallel/zero.py``) — and records the loss curves.
This backs the "~1% gradient noise is acceptable" claim with an actual
end-to-end trajectory instead of per-call error bounds (round-3 VERDICT
weak #7): the quantized curves must track fp32 within a small relative
gap, not merely bound per-step error.

Run standalone for the committed artifact (8 virtual CPU devices):

    python -m horovod_tpu.utils.convergence --steps 300

prints one JSON line with the curves and final-loss gaps; the test suite
runs fewer steps and asserts the gap bound.
"""

from __future__ import annotations

import argparse
import json
import sys


def run(steps: int = 300, record_every: int = 10, seed: int = 0,
        d_model: int = 128, n_layers: int = 2, n_heads: int = 4,
        vocab: int = 512, seq_len: int = 64, batch_per_dev: int = 2,
        lr: float = 1e-3, n_batches: int = 8) -> dict:
    """Returns {"curves": {cfg: [loss...]}, "final": {...},
    "rel_gap_vs_fp32": {...}}; same init, same data order per config."""
    import numpy as np

    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import PartitionSpec as P

    import horovod_tpu.jax as hvdj
    from horovod_tpu.jax import _shard_map
    from horovod_tpu.models.transformer import TransformerLM
    from horovod_tpu.parallel.mesh import build_mesh
    from horovod_tpu.parallel.zero import init_zero1_state, zero1_update

    devices = jax.devices()
    n_dev = len(devices)
    mesh = build_mesh({"data": n_dev})
    global_batch = batch_per_dev * n_dev

    model = TransformerLM(
        vocab_size=vocab, d_model=d_model, n_heads=n_heads,
        n_layers=n_layers, max_len=seq_len,
    )
    rng = np.random.RandomState(seed)
    # A small fixed dataset the model can start memorizing within a few
    # hundred steps — the curves must move, or the comparison is vacuous.
    data = [
        (jnp.asarray(rng.randint(0, vocab, (global_batch, seq_len)),
                     jnp.int32),
         jnp.asarray(rng.randint(0, vocab, (global_batch, seq_len)),
                     jnp.int32))
        for _ in range(n_batches)
    ]
    params0 = model.init(jax.random.PRNGKey(seed), data[0][0][:1])["params"]
    tx = optax.adamw(lr)

    def loss_fn(p, tok, lab):
        logits = model.apply({"params": p}, tok)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, lab
        ).mean()

    def make_replicated_step(quantized):
        def step(p, s, tok, lab):
            loss, grads = jax.value_and_grad(loss_fn)(p, tok, lab)
            grads = hvdj.allreduce_gradients(grads, quantized=quantized)
            updates, s = tx.update(grads, s, p)
            p = optax.apply_updates(p, updates)
            return p, s, jax.lax.pmean(loss, "data")

        return jax.jit(_shard_map(
            step, mesh,
            in_specs=(P(), P(), P("data"), P("data")),
            out_specs=(P(), P(), P()),
        ))

    def make_zero1_step(quantized):
        def step(p, s_stacked, tok, lab):
            s = jax.tree.map(lambda x: x[0], s_stacked)
            loss, grads = jax.value_and_grad(loss_fn)(p, tok, lab)
            p, s = zero1_update(
                tx, p, s, grads, axis_name="data", n_shards=n_dev,
                quantized=quantized,
            )
            return (p, jax.tree.map(lambda x: x[None], s),
                    jax.lax.pmean(loss, "data"))

        return jax.jit(_shard_map(
            step, mesh,
            in_specs=(P(), P("data"), P("data"), P("data")),
            out_specs=(P(), P("data"), P()),
        ))

    configs = {
        "fp32": (make_replicated_step(False), lambda: tx.init(params0)),
        "quantized": (make_replicated_step(True), lambda: tx.init(params0)),
        "quantized+zero1": (
            make_zero1_step(True),
            lambda: init_zero1_state(tx, params0, n_dev, quantized=True),
        ),
    }

    curves: dict = {}
    for name, (step_fn, init_state) in configs.items():
        p = jax.tree.map(jnp.copy, params0)
        s = init_state()
        losses = []
        for i in range(steps):
            tok, lab = data[i % n_batches]
            p, s, loss = step_fn(p, s, tok, lab)
            if i % record_every == 0 or i == steps - 1:
                losses.append(round(float(loss), 4))
        curves[name] = losses

    final = {k: v[-1] for k, v in curves.items()}
    gaps = {
        k: round(abs(v - final["fp32"]) / max(final["fp32"], 1e-9), 4)
        for k, v in final.items()
    }
    return {
        "n_devices": n_dev,
        "steps": steps,
        "model": {
            "d_model": d_model, "n_layers": n_layers, "vocab": vocab,
            "seq_len": seq_len, "global_batch": global_batch,
            "optimizer": f"adamw(lr={lr})",
        },
        "curves": curves,
        "final_loss": final,
        "rel_gap_vs_fp32": gaps,
    }


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=300)
    parser.add_argument("--cpu-devices", type=int, default=8)
    args = parser.parse_args()

    import os
    import re

    flags = os.environ.get("XLA_FLAGS", "")
    new = f"--xla_force_host_platform_device_count={args.cpu_devices}"
    if "xla_force_host_platform_device_count" in flags:
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+", new, flags
        )
    else:
        flags = (flags + " " + new).strip()
    os.environ["XLA_FLAGS"] = flags
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

    print(json.dumps(run(steps=args.steps)), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
