"""Horovod Timeline — Chrome-tracing ("catapult") JSON writer.

Parity with the reference timeline (``horovod/common/timeline.h:47-126``,
``timeline.cc``): a dedicated writer thread fed by a lock-free queue records
per-tensor NEGOTIATE_* phases, top-level op events, nested activities, and
optional cycle markers. Enabled by ``HOROVOD_TIMELINE=<file>``.

On TPU the activity names map to the XLA path: QUEUE → FUSION_PACK →
XLA_ALLREDUCE / XLA_ALLGATHER / XLA_BROADCAST → FUSION_UNPACK → CALLBACK.
The JSON loads in chrome://tracing / Perfetto exactly like the reference's.
"""

from __future__ import annotations

import json
import logging
import queue
import threading
import time
from typing import Optional

from .. import metrics as _metrics
from .. import trace as _trace

logger = logging.getLogger("horovod_tpu.timeline")

# Activity names, mirroring reference common.h:31-59 where applicable.
QUEUE = "QUEUE"
FUSION_PACK = "MEMCPY_IN_FUSION_BUFFER"
FUSION_UNPACK = "MEMCPY_OUT_FUSION_BUFFER"
XLA_ALLREDUCE = "XLA_ALLREDUCE"
XLA_ALLGATHER = "XLA_ALLGATHER"
XLA_BROADCAST = "XLA_BROADCAST"
XLA_ALLTOALL = "XLA_ALLTOALL"
XLA_REDUCESCATTER = "XLA_REDUCESCATTER"
XLA_ADASUM = "XLA_ADASUM"
NEGOTIATE_PREFIX = "NEGOTIATE_"
CYCLE_NAME = "CYCLE"


class TimelineWriter:
    """Background thread that serializes events to the trace file."""

    def __init__(self, filename: str):
        self._queue: "queue.Queue[Optional[dict]]" = queue.Queue()
        self._filename = filename
        self._healthy = True
        self._drop_lock = threading.Lock()
        self._crash_exc: Optional[BaseException] = None
        self._warned = False
        # Events lost to a dead writer thread or an undrained shutdown —
        # also counted in hvd_timeline_dropped_total so a silently
        # truncated trace is visible on /metrics.
        self.dropped = 0
        self._thread = threading.Thread(
            target=self._run, name="hvd_timeline_writer", daemon=True
        )
        self._thread.start()

    def _note_drops(self, n: int, why: str) -> None:
        if n <= 0:
            return
        with self._drop_lock:
            self.dropped += n
            first = not self._warned
            self._warned = True
        if _metrics.ACTIVE:
            _metrics.TAP.inc("hvd_timeline_dropped_total", n)
        if first:
            # One-shot: name the ORIGINAL failure — every later enqueue
            # is dropped for the same root cause, and re-warning per
            # event would bury it.
            logger.warning(
                "timeline %s: dropping events (%s; original error: %r); "
                "further drops are counted in hvd_timeline_dropped_total "
                "only", self._filename, why, self._crash_exc,
            )

    def enqueue(self, event: dict) -> None:
        if self._healthy:
            self._queue.put(event)
        else:
            self._note_drops(1, "writer thread died")

    def shutdown(self, timeout: float = 5.0) -> None:
        self._queue.put(None)
        self._thread.join(timeout=timeout)
        if self._thread.is_alive():
            # The join timed out with the thread still draining (or
            # wedged on a slow filesystem): whatever is still queued
            # will never be written by the time callers treat the file
            # as final — say so instead of returning as if complete.
            pending = self._queue.qsize()
            logger.warning(
                "timeline: writer thread still alive after %.1fs "
                "shutdown timeout; ~%d queued event(s) will not reach "
                "%s", timeout, pending, self._filename,
            )
            self._note_drops(max(pending, 1), "shutdown join timed out")

    def _run(self) -> None:
        try:
            with open(self._filename, "w") as f:
                # Chrome tracing JSON array format; leave unterminated like
                # the reference so partial traces still load
                # (timeline.cc WriteAtFileStart writes "[\n").
                f.write("[\n")
                first = True
                while True:
                    ev = self._queue.get()
                    if ev is None:
                        break
                    if not first:
                        f.write(",\n")
                    json.dump(ev, f)
                    first = False
                    if self._queue.empty():
                        f.flush()
                f.write("\n]\n")
        except OSError as exc:
            self._crash_exc = exc
            self._healthy = False
            # Anything already queued behind the crash is lost too.
            self._note_drops(self._queue.qsize(), "writer thread died")


class Timeline:
    """Per-process timeline state machine.

    States per tensor: NEGOTIATING → TOP_LEVEL → ACTIVITY (reference
    ``timeline.h:77-126``). Thread-safe; no-ops when not initialized.
    """

    def __init__(self):
        self._writer: Optional[TimelineWriter] = None
        self._lock = threading.RLock()
        self._start = time.perf_counter()
        self._tensor_tids: dict[str, int] = {}
        self._next_tid = 1
        self._rank = 0

    def initialize(self, filename: str, rank: int = 0) -> None:
        with self._lock:
            if self._writer is not None or not filename:
                return
            self._rank = rank
            # A restarted session (runtime start/stop_timeline) gets its
            # own clock origin and re-emits thread_name metadata into
            # ITS file — stale tids would leave unnamed tracks.
            self._start = time.perf_counter()
            self._tensor_tids.clear()
            self._next_tid = 1
            self._writer = TimelineWriter(filename)
            self._emit(
                {
                    "name": "process_name",
                    "ph": "M",
                    "pid": self._rank,
                    "args": {"name": f"rank {self._rank}"},
                }
            )

    @property
    def initialized(self) -> bool:
        return self._writer is not None

    def shutdown(self) -> None:
        with self._lock:
            if self._writer is not None:
                self._writer.shutdown()
                self._writer = None

    def _now_us(self) -> float:
        return (time.perf_counter() - self._start) * 1e6

    def _tid(self, tensor_name: str) -> int:
        tid = self._tensor_tids.get(tensor_name)
        if tid is None:
            tid = self._next_tid
            self._next_tid += 1
            self._tensor_tids[tensor_name] = tid
            self._emit(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": self._rank,
                    "tid": tid,
                    "args": {"name": tensor_name},
                }
            )
        return tid

    def _emit(self, ev: dict) -> None:
        if self._writer is not None:
            self._writer.enqueue(ev)
            if _trace.ACTIVE:
                # Fleet tracing mirror (docs/timeline.md "Fleet
                # tracing"): the same record lands in the bounded trace
                # ring, wall-clock stamped, so the driver-merged fleet
                # view and the flight recorder carry the per-tensor
                # phases too. Disabled → not reached.
                _trace.TAP.timeline_event(ev)

    def metadata(self, name: str, args: dict) -> None:
        """Emit a process-scoped metadata record (Chrome-trace "M" phase) —
        run facts a trace reader needs to interpret timings, e.g. the XLA
        perf-preset flags the run compiled under."""
        with self._lock:
            if self._writer is None:
                return
            self._emit(
                {"name": name, "ph": "M", "pid": self._rank, "args": args}
            )

    # --- public recording API ---
    def negotiate_start(self, tensor_name: str, op_name: str) -> None:
        self._dur_begin(tensor_name, NEGOTIATE_PREFIX + op_name)

    def negotiate_rank_ready(self, tensor_name: str, rank: int) -> None:
        with self._lock:
            if self._writer is None:
                return
            self._emit(
                {
                    "name": str(rank),
                    "ph": "i",
                    "s": "t",
                    "pid": self._rank,
                    "tid": self._tid(tensor_name),
                    "ts": self._now_us(),
                }
            )

    def negotiate_end(self, tensor_name: str, op_name: str) -> None:
        self._dur_end(tensor_name, NEGOTIATE_PREFIX + op_name)

    def start(self, tensor_name: str, op_name: str) -> None:
        self._dur_begin(tensor_name, op_name)

    def end(self, tensor_name: str, op_name: str) -> None:
        self._dur_end(tensor_name, op_name)

    def activity_start(self, tensor_name: str, activity: str) -> None:
        self._dur_begin(tensor_name, activity)

    def activity_end(self, tensor_name: str, activity: str) -> None:
        self._dur_end(tensor_name, activity)

    def mark_cycle_start(self) -> None:
        with self._lock:
            if self._writer is None:
                return
            self._emit(
                {
                    "name": CYCLE_NAME,
                    "ph": "i",
                    "s": "g",
                    "pid": self._rank,
                    "tid": 0,
                    "ts": self._now_us(),
                }
            )

    def _dur_begin(self, tensor_name: str, name: str) -> None:
        with self._lock:
            if self._writer is None:
                return
            self._emit(
                {
                    "name": name,
                    "ph": "B",
                    "pid": self._rank,
                    "tid": self._tid(tensor_name),
                    "ts": self._now_us(),
                }
            )

    def _dur_end(self, tensor_name: str, name: str) -> None:
        with self._lock:
            if self._writer is None:
                return
            self._emit(
                {
                    "name": name,
                    "ph": "E",
                    "pid": self._rank,
                    "tid": self._tid(tensor_name),
                    "ts": self._now_us(),
                }
            )
