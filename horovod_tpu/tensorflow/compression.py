"""Gradient compression for TF tensors — parity with
``horovod/tensorflow/compression.py:46-74``."""

from __future__ import annotations


class Compressor:
    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor(Compressor):
    @staticmethod
    def compress(tensor):
        import tensorflow as tf

        ctx = tensor.dtype
        if tensor.dtype.is_floating and tensor.dtype.size > 2:
            tensor = tf.cast(tensor, tf.float16)
        return tensor, ctx

    @staticmethod
    def decompress(tensor, ctx):
        import tensorflow as tf

        if ctx is not None and tensor.dtype != ctx:
            tensor = tf.cast(tensor, ctx)
        return tensor


class BF16Compressor(Compressor):
    """TPU-native: bf16 wire format."""

    @staticmethod
    def compress(tensor):
        import tensorflow as tf

        ctx = tensor.dtype
        if tensor.dtype.is_floating and tensor.dtype.size > 2:
            tensor = tf.cast(tensor, tf.bfloat16)
        return tensor, ctx

    @staticmethod
    def decompress(tensor, ctx):
        import tensorflow as tf

        if ctx is not None and tensor.dtype != ctx:
            tensor = tf.cast(tensor, ctx)
        return tensor


class Compression:
    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
