"""Graph-native TF collectives: real custom AsyncOpKernels.

Role parity with the reference's compiled TF extension
(``horovod/tensorflow/mpi_ops.cc:287-339``): inside a ``tf.function``
graph, collectives execute as first-class ``HorovodTpu*`` graph nodes —
no ``PyFunc``/``EagerPyFunc`` hop, shape inference declared at
registration, and the TF executor never blocked (the kernel enqueues
into the runtime and returns; the runtime's executor thread finishes the
op through the library's ``hvd_tf_finish``, which allocates the output
with the post-negotiation shape — how dynamically-shaped allgather
works, like the reference's post-coordination ``AllocateOutput``).

The kernel source is ``cpp/src/tf_ops.cc``; it is compiled on first use
against the installed TensorFlow's headers (``tf.sysconfig``) and cached
next to ``libhvd_core.so``. When TF or a toolchain is unavailable the
binding falls back to the ``tf.py_function`` path transparently.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import sys
import sysconfig
import threading
from typing import Optional

import numpy as np

logger = logging.getLogger("horovod_tpu")

_lock = threading.Lock()
_state: dict = {"tried": False, "ops": None, "cdll": None}

# TF DataType enum -> numpy dtype (DT_* values are stable public ABI).
_TF_DTYPE_TO_NP = {
    1: np.float32,    # DT_FLOAT
    2: np.float64,    # DT_DOUBLE
    3: np.int32,      # DT_INT32
    4: np.uint8,      # DT_UINT8
    6: np.int8,       # DT_INT8
    9: np.int64,      # DT_INT64
    19: np.float16,   # DT_HALF
}


def _np_dtype(tf_enum: int):
    if tf_enum == 14:  # DT_BFLOAT16
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(_TF_DTYPE_TO_NP[tf_enum])


def _lib_path() -> str:
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))),
        "cpp", "libhvd_tf_ops.so",
    )


def _src_path() -> str:
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))),
        "cpp", "src", "tf_ops.cc",
    )


def _build(src: str, out: str) -> None:
    """Compile the op library with the installed TF's flags (the same
    recipe the reference's setup.py uses for its TF extension, reduced
    to one translation unit)."""
    import tensorflow as tf

    # Compile to a per-process temp file and rename into place: rename is
    # atomic, so concurrent ranks on a fresh checkout never load a
    # half-linked library, and a killed build leaves no corrupt cache.
    tmp = f"{out}.build.{os.getpid()}"
    cmd = (
        ["g++", "-O2", "-std=c++17", "-fPIC", "-shared", src, "-o", tmp]
        + tf.sysconfig.get_compile_flags()
        + tf.sysconfig.get_link_flags()
        + [f"-I{sysconfig.get_paths()['include']}"]
    )
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=600
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"tf_ops build failed: {proc.stderr[-2000:]}"
            )
        os.replace(tmp, out)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def _trampoline(handle, out_index, kind, ptr, shape, tf_dtype, name,
                root_rank, reduce_op, prescale, postscale,
                group_id=0, group_size=0):
    """Called (with the GIL) from the kernel's ComputeAsync on a TF
    executor thread. Enqueues into the eager runtime and returns
    immediately; completion calls back into the library."""
    from .. import _rt
    from ..common.types import ReduceOp

    cdll = _state["cdll"]
    np_dtype = _np_dtype(tf_dtype)
    n = 1
    for d in shape:
        n *= d
    buf = (ctypes.c_char * (n * np_dtype.itemsize)).from_address(ptr)
    view = np.frombuffer(buf, dtype=np_dtype).reshape(shape)

    def finish_error(msg: str, runtime_failure: bool = False) -> None:
        # [hvd-collective-failure] is the stable marker elastic's
        # matcher keys on (horovod_tpu/elastic: _is_collective_failure).
        # ONLY runtime failures carry it — a deterministic validation
        # error (int64 range, unknown kind, duplicate name) must surface
        # to the user, not spin the elastic rollback loop forever.
        if runtime_failure:
            msg = f"[hvd-collective-failure] {msg}"
        cdll.hvd_tf_finish(
            ctypes.c_longlong(handle), out_index, 1, msg.encode(),
            None, None, 0, ctypes.c_longlong(0),
        )

    # The data plane computes in 32-bit (jax x64 disabled); a 64-bit int
    # payload that cannot round-trip must fail loudly, matching the eager
    # binding's guard.
    if np_dtype in (np.dtype(np.int64),) and view.size:
        if not np.array_equal(view.astype(np.int32).astype(np.int64), view):
            finish_error(
                "int64 payload exceeds int32 range: the XLA data plane "
                "runs with x64 disabled"
            )
            return

    def callback(status, output) -> None:
        try:
            if not status.ok():
                finish_error(status.reason or "collective failed",
                             runtime_failure=True)
                return
            out = np.asarray(output)
            if out.dtype != np_dtype:
                out = out.astype(np_dtype)
            # ascontiguousarray PROMOTES 0-d arrays to shape (1,) (numpy
            # ndmin=1 wart) — restore the true shape or every scalar
            # collective output would come back as [1].
            out = np.ascontiguousarray(out).reshape(out.shape)
            dims = (ctypes.c_longlong * max(out.ndim, 1))(*(
                out.shape if out.ndim else (1,)
            ))
            cdll.hvd_tf_finish(
                ctypes.c_longlong(handle), out_index, 0, b"",
                out.ctypes.data_as(ctypes.c_void_p), dims, out.ndim,
                ctypes.c_longlong(out.nbytes),
            )
        except Exception as exc:  # noqa: BLE001 - must never lose done()
            logger.exception("tf graph-op completion failed")
            try:
                finish_error(str(exc))
            except Exception:  # noqa: BLE001
                pass

    try:
        rt = _rt()
        if kind == "allreduce":
            rt.enqueue_allreduce(
                name, view, reduce_op=ReduceOp(reduce_op),
                prescale_factor=prescale, postscale_factor=postscale,
                callback=callback,
                group_id=group_id, group_size=group_size,
            )
        elif kind == "allgather":
            rt.enqueue_allgather(name, view, callback=callback)
        elif kind == "broadcast":
            rt.enqueue_broadcast(name, view, root_rank, callback=callback)
        elif kind == "alltoall":
            rt.enqueue_alltoall(name, view, callback=callback)
        else:
            finish_error(f"unknown collective kind {kind!r}")
    except Exception as exc:  # noqa: BLE001
        import horovod_tpu as _hvd

        finish_error(
            str(exc),
            runtime_failure=isinstance(exc, _hvd.HorovodInternalError),
        )


def load():
    """Build (if stale) + load the op library and register the
    trampoline. Returns the TF op module, or None when unavailable."""
    with _lock:
        if _state["tried"]:
            return _state["ops"]
        _state["tried"] = True
        try:
            import tensorflow as tf

            src, out = _src_path(), _lib_path()
            if not os.path.exists(out) or (
                os.path.exists(src)
                and os.path.getmtime(src) > os.path.getmtime(out)
            ):
                _build(src, out)
            try:
                ops = tf.load_op_library(out)
            except Exception:
                # A cached library from another TF build (or a corrupt
                # file) fails to load; rebuild once before giving up.
                _build(src, out)
                ops = tf.load_op_library(out)
            cdll = ctypes.CDLL(out)
            cdll.hvd_tf_set_trampoline.argtypes = [ctypes.py_object]
            cdll.hvd_tf_set_trampoline.restype = None
            cdll.hvd_tf_finish.argtypes = [
                ctypes.c_longlong, ctypes.c_int, ctypes.c_int,
                ctypes.c_char_p, ctypes.c_void_p,
                ctypes.POINTER(ctypes.c_longlong),
                ctypes.c_int, ctypes.c_longlong,
            ]
            cdll.hvd_tf_finish.restype = None
            cdll.hvd_tf_set_trampoline(_trampoline)
            _state["ops"] = ops
            _state["cdll"] = cdll
        except Exception as exc:  # noqa: BLE001
            logger.warning(
                "graph-native TF ops unavailable (%s); tf.function "
                "collectives fall back to py_function", exc,
            )
            _state["ops"] = None
        return _state["ops"]


def available() -> bool:
    return load() is not None


def supported_tf_dtypes():
    """The dtypes the custom ops register for attr T (must mirror the
    constraint list in cpp/src/tf_ops.cc); shared by every graph-dispatch
    guard so the set cannot silently diverge between call sites."""
    import tensorflow as tf

    return (
        tf.float16, tf.bfloat16, tf.float32, tf.float64,
        tf.int32, tf.int64, tf.uint8, tf.int8,
    )


_name_counter = [0]
_name_lock = threading.Lock()


def auto_name(prefix: str) -> str:
    """Deterministic per-trace names: all ranks trace the same program in
    the same order, so the counter sequence matches across ranks (the
    reference gets the same property from TF node-name uniquification)."""
    with _name_lock:
        _name_counter[0] += 1
        return f"{prefix}.graph.{_name_counter[0]}"
