"""Alias module: the reference exposes the Keras binding as BOTH
``horovod.keras`` and ``horovod.tensorflow.keras``; scripts written
against the latter import path port unchanged
(``import horovod_tpu.tensorflow.keras as hvd``)."""

from ..keras import *  # noqa: F401,F403
from ..keras import DistributedOptimizer, callbacks, load_model  # noqa: F401
from ..keras import elastic  # noqa: F401  (hvd.elastic.* attribute access)
