"""Elastic API for the TensorFlow binding (upstream
``horovod.tensorflow.elastic``): ``run`` and ``TensorFlowState`` (raw
``tf.Variable`` collections + plain counters) re-exported from the core
elastic module. For Keras models use ``horovod_tpu.keras.elastic``.
"""

from __future__ import annotations

from ..elastic import (  # noqa: F401
    HostsUpdatedInterrupt,
    ObjectState,
    State,
    TensorFlowState,
    run,
)

__all__ = [
    "run",
    "State",
    "ObjectState",
    "TensorFlowState",
    "HostsUpdatedInterrupt",
]
