"""horovod_tpu.tensorflow — TensorFlow binding.

API parity with ``horovod/tensorflow/__init__.py``: ``allreduce`` with
Average/Sum/Adasum semantics and IndexedSlices-via-allgather,
``broadcast_variables`` / ``broadcast_global_variables``,
``DistributedGradientTape``, ``DistributedOptimizer`` (tf.compat.v1 +
keras-optimizer styles), Compression.

Eager-first: collectives run through the shared eager runtime (native
control plane + XLA data plane) by converting EagerTensors to numpy at the
boundary. Inside ``tf.function`` graphs the op is wrapped with
``tf.py_function`` — correct, though the recommended high-throughput path
on TPU is the JAX compiled mode.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .. import (  # noqa: F401 - basics re-exported like the reference
    Adasum,
    Average,
    Sum,
    cross_rank,
    cross_size,
    init,
    is_initialized,
    local_rank,
    local_size,
    rank,
    shutdown,
    size,
)
from .. import allgather as _allgather_np
from .. import allreduce as _allreduce_np
from .. import alltoall as _alltoall_np
from .. import broadcast as _broadcast_np
from ..common.types import ReduceOp
from .compression import Compression


def _np_op(fn, tensor, *args, **kwargs):
    """Run a numpy-level collective on a TF tensor, eagerly or inside a
    graph via py_function."""
    import tensorflow as tf

    def run(t):
        out = fn(t.numpy(), *args, **kwargs)
        return tf.convert_to_tensor(np.asarray(out))

    if tf.executing_eagerly() and not isinstance(tensor, tf.Tensor):
        tensor = tf.convert_to_tensor(tensor)
    if tf.executing_eagerly() and hasattr(tensor, "numpy"):
        return run(tensor)
    return tf.py_function(run, [tensor], Tout=tensor.dtype)


def allreduce(tensor, average=None, device_dense="", device_sparse="",
              compression=Compression.none, op=None,
              prescale_factor=1.0, postscale_factor=1.0, name=None):
    """Reference semantics (``tensorflow/__init__.py:44-118``): Average by
    default; ``tf.IndexedSlices`` reduce as gathered values/indices."""
    import tensorflow as tf

    if op is None and average is None:
        rop = ReduceOp.AVERAGE
    elif op is not None:
        rop = op
    else:
        rop = ReduceOp.AVERAGE if average else ReduceOp.SUM

    if isinstance(tensor, tf.IndexedSlices):
        # Sparse path: allgather values+indices; Average divides by size
        # (reference tensorflow/__init__.py:75-91).
        values = allgather(tensor.values)
        indices = allgather(tensor.indices)
        if rop == ReduceOp.AVERAGE:
            values = values / size()
        return tf.IndexedSlices(values, indices,
                                dense_shape=tensor.dense_shape)

    compressed, ctx = compression.compress(tensor)
    out = _np_op(
        _allreduce_np, compressed, op=rop, name=name,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
    )
    return compression.decompress(out, ctx)


def allgather(tensor, name=None):
    return _np_op(_allgather_np, tensor, name)


def broadcast(tensor, root_rank, name=None):
    return _np_op(_broadcast_np, tensor, root_rank, name)


def alltoall(tensor, name=None):
    return _np_op(_alltoall_np, tensor, name)


def broadcast_variables(variables, root_rank: int = 0) -> None:
    """Assign every variable the root's value (reference
    ``broadcast_variables``, ``tensorflow/__init__.py:139-227``)."""
    import tensorflow as tf

    for i, var in enumerate(variables):
        # tf.Variable has read_value(); Keras-3 backend variables expose
        # .value instead — convert_to_tensor covers both.
        value = tf.convert_to_tensor(var)
        var.assign(broadcast(value, root_rank, name=f"bcast.var.{i}"))


def broadcast_global_variables(root_rank: int = 0) -> None:
    import tensorflow as tf

    if hasattr(tf.compat.v1, "global_variables"):
        broadcast_variables(tf.compat.v1.global_variables(), root_rank)


class DistributedGradientTape:
    """Wraps tf.GradientTape; ``gradient()`` allreduces the results
    (reference ``tensorflow/__init__.py:473-530``)."""

    def __init__(self, tape, device_dense="", device_sparse="",
                 compression=Compression.none, op=None):
        self._tape = tape
        self._compression = compression
        self._op = op if op is not None else Average

    def __enter__(self):
        self._tape.__enter__()
        return self

    def __exit__(self, *exc):
        return self._tape.__exit__(*exc)

    def __getattr__(self, item):
        return getattr(self._tape, item)

    def gradient(self, target, sources, output_gradients=None):
        grads = self._tape.gradient(target, sources, output_gradients)
        return [
            allreduce(g, compression=self._compression, op=self._op,
                      name=f"DistributedGradientTape.grad.{i}")
            if g is not None else None
            for i, g in enumerate(grads)
        ]


def DistributedOptimizer(optimizer, name=None, use_locking=False,  # noqa: N802
                         device_dense="", device_sparse="",
                         compression=Compression.none, sparse_as_dense=False,
                         op=None, backward_passes_per_step=1):
    """Wrap a Keras optimizer so gradients are allreduced before apply
    (API parity with ``tensorflow/__init__.py:409-470``)."""
    cls = _make_distributed_optimizer_class(
        optimizer.__class__, compression=compression, op=op
    )
    # Fresh instance with the same config; Keras builds slots lazily on the
    # first apply_gradients, so no state transfer is needed for a new model.
    return cls.from_config(optimizer.get_config())


def _make_distributed_optimizer_class(base, compression=Compression.none,
                                      op=None):
    """Subclass ``base`` so gradients are allreduced before apply.

    The subclass keeps the base class name (as the reference does when
    building the wrapper type) so a saved model's optimizer config remains
    deserializable; ``horovod_tpu.keras.load_model`` maps saved class names
    back onto these wrappers (reference ``_keras/__init__.py:111+``)."""
    reduce_op = op if op is not None else Average

    # Never stack wrappers: subclassing an already-distributed class would
    # allreduce twice per step (and square the size factor under op=Sum).
    while getattr(base, "_hvd_distributed", False):
        base = base.__bases__[0]

    class _Distributed(base):  # type: ignore[valid-type, misc]
        _hvd_distributed = True

        def apply_gradients(self, grads_and_vars, **kwargs):
            gv = [
                (
                    allreduce(g, compression=compression, op=reduce_op,
                              name=f"DistributedOptimizer.grad.{i}")
                    if g is not None else None,
                    v,
                )
                for i, (g, v) in enumerate(grads_and_vars)
            ]
            return super().apply_gradients(gv, **kwargs)

    _Distributed.__name__ = base.__name__
    _Distributed.__qualname__ = base.__qualname__
    return _Distributed


class BroadcastGlobalVariablesHook:
    """tf.compat.v1 SessionRunHook parity shim: in eager/TF2 use
    ``broadcast_variables`` or the Keras callback instead."""

    def __init__(self, root_rank: int = 0, device=""):
        self.root_rank = root_rank

    def begin(self):
        broadcast_global_variables(self.root_rank)
