"""horovod_tpu.tensorflow — TensorFlow binding.

API parity with ``horovod/tensorflow/__init__.py``: ``allreduce`` with
Average/Sum/Adasum semantics and IndexedSlices-via-allgather,
``broadcast_variables`` / ``broadcast_global_variables``,
``DistributedGradientTape``, ``DistributedOptimizer`` (tf.compat.v1 +
keras-optimizer styles), Compression.

Data path (the role of the reference's graph-native HorovodAllreduceOp,
``tensorflow/mpi_ops.cc:287-339``): EagerTensors hand their buffer to the
XLA data plane **zero-copy via DLPack** — no ``.numpy()`` host copy — and
ride the eager executor's device-resident fast path; results come back the
same way. Inside ``tf.function`` graphs collectives execute as
**graph-native custom AsyncOpKernels** (``HorovodTpu*`` nodes,
``cpp/src/tf_ops.cc`` — compiled on first use against the installed TF;
``tf.py_function`` remains only as the no-toolchain fallback), and every
collective carries a registered gradient via ``tf.custom_gradient``
(parity with the reference's RegisterGradient set,
``tensorflow/mpi_ops.py:107-198``), so allreduce/allgather/broadcast are
differentiable in both eager and graph mode.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .. import (  # noqa: F401 - basics re-exported like the reference
    Adasum,
    Average,
    Sum,
    cross_rank,
    cross_size,
    init,
    is_initialized,
    local_rank,
    local_size,
    rank,
    shutdown,
    size,
)
from .. import allgather as _allgather_np
from .. import allreduce as _allreduce_np
from .. import alltoall as _alltoall_np
from .. import broadcast as _broadcast_np
from ..common.types import ReduceOp
from .compression import Compression


def _to_jax(t):
    """EagerTensor -> jax array, zero-copy via the DLPack protocol (falls
    back to a numpy copy for dtypes/layouts DLPack rejects)."""
    import jax

    try:
        return jax.dlpack.from_dlpack(t)
    except Exception:
        return t.numpy()


def _from_jax(out):
    """Collective result -> TF tensor; zero-copy for jax arrays (the
    executor's device-resident path returns them)."""
    import jax
    import tensorflow as tf

    if isinstance(out, jax.Array):
        try:
            return tf.experimental.dlpack.from_dlpack(out.__dlpack__())
        except Exception:
            pass
    return tf.convert_to_tensor(np.asarray(out))


def _restore_dtype(out, t):
    """Restore the caller's dtype on a data-plane result: jax (x64
    disabled) narrows 64-bit ints/floats — TF optimizer counters are
    int64 scalars. Int payloads that do not survive the 32-bit round
    trip must fail loudly, not wrap silently; float64 loses precision by
    design (the data plane computes in float32)."""
    import tensorflow as tf

    if out.dtype != t.dtype:
        if t.dtype.is_integer and not bool(
            tf.reduce_all(tf.cast(tf.cast(t, out.dtype), t.dtype) == t)
        ):
            raise ValueError(
                f"{t.dtype.name} payload exceeds {out.dtype.name} "
                "range: the XLA data plane runs with x64 disabled"
            )
        out = tf.cast(out, t.dtype)
    return out


def _np_op(fn, tensor, *args, keep_shape=True, **kwargs):
    """Run an eager-runtime collective on a TF tensor, eagerly or inside a
    graph via py_function. Either way the payload crosses frameworks via
    DLPack, never a host copy (reference role: mpi_ops.cc:287-339 gets the
    buffer out of TF without staging).

    ``keep_shape``: py_function erases static shapes; allreduce/broadcast/
    alltoall are shape-preserving (the reference graph ops declare this via
    shape inference), so restore it — Keras optimizers require known
    gradient shapes. allgather passes False (dim 0 grows)."""
    import tensorflow as tf

    def run(t):
        return _restore_dtype(_from_jax(fn(_to_jax(t), *args, **kwargs)), t)

    if tf.executing_eagerly() and not isinstance(tensor, tf.Tensor):
        tensor = tf.convert_to_tensor(tensor)
    if tf.executing_eagerly() and hasattr(tensor, "numpy"):
        return run(tensor)
    # Graph mode: emit a first-class HorovodTpu* node (AsyncOpKernel,
    # cpp/src/tf_ops.cc) — no PyFunc/EagerPyFunc in the concrete graph,
    # parity with the reference's compiled op (mpi_ops.cc:287-339).
    out = _graph_dispatch(fn, tensor, *args, **kwargs)
    if out is not None:
        return out
    out = tf.py_function(run, [tensor], Tout=tensor.dtype)
    if keep_shape:
        out.set_shape(tensor.shape)
    elif tensor.shape.rank is not None:
        out.set_shape([None] + list(tensor.shape)[1:])
    return out


def _graph_dispatch(fn, tensor, *args, **kwargs):
    """Map an eager-runtime collective call onto its graph-native custom
    op. Returns None when the op library is unavailable (py_function
    fallback) or ``fn`` has no graph twin.

    Contract with the ``_np_op`` call sites: ``name`` always travels as a
    keyword; ``broadcast``'s root rank is the sole positional extra (it
    is positional-required in the eager fn too). Keeping the protocol
    keyword-based means a call-site refactor cannot silently desync the
    tensor names negotiated across ranks."""
    import tensorflow as tf

    from . import graph_ops

    # Dtypes outside the custom op's registered T set (bool, int16,
    # complex, ...) must keep the py_function path instead of raising a
    # trace-time TypeError.
    if tensor.dtype not in graph_ops.supported_tf_dtypes():
        return None
    ops = graph_ops.load()
    if ops is None:
        return None
    name = kwargs.get("name")
    if fn is _allreduce_np:
        return ops.horovod_tpu_allreduce(
            tensor,
            tensor_name=name or graph_ops.auto_name("allreduce"),
            reduce_op=int(kwargs.get("op", ReduceOp.SUM)),
            prescale_factor=float(kwargs.get("prescale_factor", 1.0)),
            postscale_factor=float(kwargs.get("postscale_factor", 1.0)),
        )
    if fn is _allgather_np:
        return ops.horovod_tpu_allgather(
            tensor, tensor_name=name or graph_ops.auto_name("allgather")
        )
    if fn is _broadcast_np:
        return ops.horovod_tpu_broadcast(
            tensor,
            tensor_name=name or graph_ops.auto_name("broadcast"),
            root_rank=int(args[0]),
        )
    if fn is _alltoall_np:
        return ops.horovod_tpu_alltoall(
            tensor, tensor_name=name or graph_ops.auto_name("alltoall")
        )
    return None


def allreduce(tensor, average=None, device_dense="", device_sparse="",
              compression=Compression.none, op=None,
              prescale_factor=1.0, postscale_factor=1.0, name=None):
    """Reference semantics (``tensorflow/__init__.py:44-118``): Average by
    default; ``tf.IndexedSlices`` reduce as gathered values/indices."""
    import tensorflow as tf

    if op is None and average is None:
        rop = ReduceOp.AVERAGE
    elif op is not None:
        rop = op
    else:
        rop = ReduceOp.AVERAGE if average else ReduceOp.SUM

    if isinstance(tensor, tf.IndexedSlices):
        # Sparse path: allgather values+indices; Average divides by size
        # (reference tensorflow/__init__.py:75-91).
        values = allgather(tensor.values)
        indices = allgather(tensor.indices)
        if rop == ReduceOp.AVERAGE:
            values = values / size()
        return tf.IndexedSlices(values, indices,
                                dense_shape=tensor.dense_shape)

    compressed, ctx = compression.compress(tensor)

    @tf.custom_gradient
    def _ar(x):
        y = _np_op(
            _allreduce_np, x, op=rop, name=name,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor,
        )

        def grad(dy):
            # Adjoint of the WHOLE wrapped op (which, unlike the reference,
            # includes the Average divisor and scale factors inside):
            # y_j = post * (1/N?) sum_i (pre * x_i)  =>  dx = same op on dy.
            # The reference reaches the same math by sum-allreducing the
            # gradient and letting autodiff handle its separate /size op
            # (mpi_ops.py:107-118). Adasum's adjoint is intractable; follow
            # the reference in using a plain SUM for it.
            grad_op = (ReduceOp.AVERAGE if rop == ReduceOp.AVERAGE
                       else ReduceOp.SUM)
            return _np_op(
                _allreduce_np, dy, op=grad_op,
                prescale_factor=prescale_factor,
                postscale_factor=postscale_factor,
                name=f"{name}.grad" if name else None,
            )

        return y, grad

    out = _ar(compressed)
    return compression.decompress(out, ctx)


def allgather(tensor, name=None):
    import tensorflow as tf

    @tf.custom_gradient
    def _ag(x):
        y = _np_op(_allgather_np, x, name=name, keep_shape=False)

        def grad(dy):
            # Reference gradient (mpi_ops.py:140-163): sum the upstream
            # gradient across ranks, then take this rank's row range of the
            # concatenation (ranks may contribute different dim-0 sizes).
            dsum = _np_op(_allreduce_np, dy, op=ReduceOp.SUM,
                          name=f"{name}.grad" if name else None)
            d0 = tf.reshape(tf.cast(tf.shape(x)[0], tf.int32), [1])
            sizes = tf.reshape(
                _np_op(_allgather_np, d0,
                       name=f"{name}.grad.sizes" if name else None,
                       keep_shape=False),
                [size()],
            )
            return tf.split(dsum, num_or_size_splits=sizes, axis=0)[rank()]

        return y, grad

    return _ag(tensor)


def broadcast(tensor, root_rank, name=None):
    import tensorflow as tf

    @tf.custom_gradient
    def _bc(x):
        y = _np_op(_broadcast_np, x, root_rank, name=name)

        def grad(dy):
            # Reference gradient (mpi_ops.py:185-198): allreduce the
            # upstream gradient; non-root ranks contribute zero input so
            # their gradient is zeroed.
            g = _np_op(_allreduce_np, dy, op=ReduceOp.SUM,
                       name=f"{name}.grad" if name else None)
            return g if rank() == root_rank else g * 0

        return y, grad

    return _bc(tensor)


def alltoall(tensor, name=None):
    import tensorflow as tf

    @tf.custom_gradient
    def _a2a(x):
        y = _np_op(_alltoall_np, x, name=name)

        def grad(dy):
            # alltoall with equal splits is an involution: routing the
            # upstream gradient back through it returns each shard home
            # (TPU-native extension; the reference has no alltoall).
            return _np_op(_alltoall_np, dy,
                          name=f"{name}.grad" if name else None)

        return y, grad

    return _a2a(tensor)


def grouped_allreduce(tensors, average=None, compression=Compression.none,
                      op=None, prescale_factor=1.0, postscale_factor=1.0,
                      name=None):
    """Allreduce a list of tensors as one first-class group
    (later-reference ``hvd.grouped_allreduce`` parity). Eager tensors
    ride the runtime's group barrier and fuse into a single plan, with
    a registered gradient (the group's adjoint is a grouped reduce of
    the upstream gradients, same op mapping as ``allreduce``); inside
    ``tf.function`` the whole group lowers to ONE multi-input/
    multi-output HorovodTpuGroupedAllreduce node — graph pruning cannot
    split a first-class group (per-member nodes deadlocked when a
    gradient-only function pruned some members) — and still executes as
    one coordinator plan."""
    import tensorflow as tf

    from .. import grouped_allreduce as _grouped_np

    if op is None and average is None:
        rop = ReduceOp.AVERAGE
    elif op is not None:
        rop = op
    else:
        rop = ReduceOp.AVERAGE if average else ReduceOp.SUM

    if not tf.executing_eagerly():
        return _graph_grouped_allreduce(
            list(tensors), rop, compression,
            prescale_factor, postscale_factor, name,
        )

    compressed, ctxs = [], []
    for t in tensors:
        c, ctx = compression.compress(tf.convert_to_tensor(t))
        compressed.append(c)
        ctxs.append(ctx)

    def _run_group(xs, group_op, group_name):
        outs = _grouped_np(
            [_to_jax(x) for x in xs], op=group_op, name=group_name,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor,
        )
        return [
            _restore_dtype(_from_jax(o), x) for o, x in zip(outs, xs)
        ]

    @tf.custom_gradient
    def _gar(*xs):
        ys = _run_group(xs, rop, name)

        def grad(*dys):
            # Same adjoint mapping as allreduce: the averaged op's
            # adjoint is the averaged op; everything else reduces the
            # upstream gradients with SUM.
            grad_op = (ReduceOp.AVERAGE if rop == ReduceOp.AVERAGE
                       else ReduceOp.SUM)
            return tuple(_run_group(
                dys, grad_op, f"{name}.grad" if name else None
            ))

        return tuple(ys), grad

    outs = _gar(*compressed)
    return [
        compression.decompress(o, ctx) for o, ctx in zip(outs, ctxs)
    ]


def _graph_grouped_allreduce(tensors, rop, compression,
                             prescale_factor, postscale_factor, name):
    """Graph-mode grouped allreduce: one HorovodTpu* node per member,
    all carrying the same group id + member count, so the coordinator
    fuses the whole group into ONE plan inside tf.function exactly like
    the eager path. Falls back to independent per-tensor allreduces
    (cycle fusion) when the op library is unavailable."""
    import tensorflow as tf

    from .. import _group_id
    from . import graph_ops

    if rop == ReduceOp.ADASUM:
        # Consistent with the torch binding: Adasum has no grouped form
        # (its adjoint/delta semantics are per-optimizer, not per-list).
        raise ValueError(
            "grouped_allreduce does not support op=Adasum; use the "
            "delta-space Adasum optimizer path instead"
        )
    ops = graph_ops.load()
    dtypes = {tf.convert_to_tensor(t).dtype for t in tensors}
    supported = (
        ops is not None
        and len(dtypes) == 1  # the grouped op is homogeneous (N * T)
        and next(iter(dtypes)) in graph_ops.supported_tf_dtypes()
        # int64 members can fail the data-dependent range guard WITHOUT
        # enqueuing, which would strand the rest of a first-class group
        # at the coordinator — int64 lists take the per-tensor fallback,
        # where each op fails loudly on its own.
        and next(iter(dtypes)) != tf.int64
    )
    if not supported:
        return [
            allreduce(t, compression=compression, op=rop,
                      prescale_factor=prescale_factor,
                      postscale_factor=postscale_factor,
                      name=f"{name}.{i}" if name else None)
            for i, t in enumerate(tensors)
        ]

    base = name or graph_ops.auto_name("grouped_allreduce")
    compressed, ctxs = [], []
    for t in tensors:
        c, ctx = compression.compress(tf.convert_to_tensor(t))
        compressed.append(c)
        ctxs.append(ctx)

    def _emit(xs, group_base, group_op):
        # ONE multi-input/multi-output node for the whole group: graph
        # pruning is all-or-nothing by construction. Per-member nodes
        # deadlocked — a gradient-only tf.function pruned some members
        # (even through control deps, which grappler strips), leaving
        # the coordinator's group barrier waiting forever.
        outs = ops.horovod_tpu_grouped_allreduce(
            tensors=list(xs), tensor_name=group_base,
            reduce_op=int(group_op),
            prescale_factor=float(prescale_factor),
            postscale_factor=float(postscale_factor),
            group_id=_group_id(group_base),
        )
        return list(outs)

    @tf.custom_gradient
    def _gar(*xs):
        ys = _emit(list(xs), base, rop)

        def grad(*dys):
            # Group adjoint: grouped reduce of the upstream gradients
            # (AVERAGE's adjoint is AVERAGE; everything else SUM).
            grad_op = (ReduceOp.AVERAGE if rop == ReduceOp.AVERAGE
                       else ReduceOp.SUM)
            return tuple(_emit(list(dys), f"{base}.grad", grad_op))

        return tuple(ys), grad

    outs = _gar(*compressed)
    return [
        compression.decompress(o, ctx) for o, ctx in zip(outs, ctxs)
    ]


def broadcast_variables(variables, root_rank: int = 0) -> None:
    """Assign every variable the root's value (reference
    ``broadcast_variables``, ``tensorflow/__init__.py:139-227``)."""
    import tensorflow as tf

    for i, var in enumerate(variables):
        # tf.Variable has read_value(); Keras-3 backend variables expose
        # .value instead — convert_to_tensor covers both.
        value = tf.convert_to_tensor(var)
        var.assign(broadcast(value, root_rank, name=f"bcast.var.{i}"))


def broadcast_global_variables(root_rank: int = 0) -> None:
    import tensorflow as tf

    if hasattr(tf.compat.v1, "global_variables"):
        broadcast_variables(tf.compat.v1.global_variables(), root_rank)


def _densify_if_sparse(g):
    """sparse_as_dense support: convert an IndexedSlices gradient to a
    dense tensor so it rides the dense-allreduce path (reference
    ``tensorflow/__init__.py:235`` upstream)."""
    import tensorflow as tf

    if isinstance(g, tf.IndexedSlices):
        return tf.convert_to_tensor(g)
    return g


class DistributedGradientTape:
    """Wraps tf.GradientTape; ``gradient()`` allreduces the results
    (reference ``tensorflow/__init__.py:473-530``)."""

    def __init__(self, tape, device_dense="", device_sparse="",
                 compression=Compression.none, sparse_as_dense=False,
                 op=None):
        self._tape = tape
        self._compression = compression
        self._sparse_as_dense = sparse_as_dense
        self._op = op if op is not None else Average

    def __enter__(self):
        self._tape.__enter__()
        return self

    def __exit__(self, *exc):
        return self._tape.__exit__(*exc)

    def __getattr__(self, item):
        return getattr(self._tape, item)

    def gradient(self, target, sources, output_gradients=None):
        import tensorflow as tf

        grads = self._tape.gradient(target, sources, output_gradients)
        # Mirror the sources structure (a single tensor source yields a
        # single gradient, not a list — reference uses nest the same way).
        flat = tf.nest.flatten(grads)
        if self._sparse_as_dense:
            flat = [_densify_if_sparse(g) for g in flat]
        reduced = [
            allreduce(g, compression=self._compression, op=self._op,
                      name=f"DistributedGradientTape.grad.{i}")
            if g is not None else None
            for i, g in enumerate(flat)
        ]
        return tf.nest.pack_sequence_as(grads, reduced)


def DistributedOptimizer(optimizer, name=None, use_locking=False,  # noqa: N802
                         device_dense="", device_sparse="",
                         compression=Compression.none, sparse_as_dense=False,
                         op=None, backward_passes_per_step=1):
    """Wrap a Keras optimizer so gradients are allreduced before apply
    (API parity with ``tensorflow/__init__.py:409-470``)."""
    cls = _make_distributed_optimizer_class(
        optimizer.__class__, compression=compression,
        sparse_as_dense=sparse_as_dense, op=op
    )
    # Fresh instance with the same config; Keras builds slots lazily on the
    # first apply_gradients, so no state transfer is needed for a new model.
    return cls.from_config(optimizer.get_config())


def _make_distributed_optimizer_class(base, compression=Compression.none,
                                      sparse_as_dense=False, op=None):
    """Subclass ``base`` so gradients are allreduced before apply.

    The subclass keeps the base class name (as the reference does when
    building the wrapper type) so a saved model's optimizer config remains
    deserializable; ``horovod_tpu.keras.load_model`` maps saved class names
    back onto these wrappers (reference ``_keras/__init__.py:111+``)."""
    reduce_op = op if op is not None else Average

    # Never stack wrappers: subclassing an already-distributed class would
    # allreduce twice per step (and square the size factor under op=Sum).
    while getattr(base, "_hvd_distributed", False):
        base = base.__bases__[0]

    class _Distributed(base):  # type: ignore[valid-type, misc]
        _hvd_distributed = True

        def apply_gradients(self, grads_and_vars, **kwargs):
            if reduce_op == ReduceOp.ADASUM:
                return self._apply_adasum(list(grads_and_vars), **kwargs)
            gv = []
            for i, (g, v) in enumerate(grads_and_vars):
                if sparse_as_dense:
                    g = _densify_if_sparse(g)
                gv.append((
                    allreduce(g, compression=compression, op=reduce_op,
                              name=f"DistributedOptimizer.grad.{i}")
                    if g is not None else None,
                    v,
                ))
            return super().apply_gradients(gv, **kwargs)

        def _apply_adasum(self, gv, **kwargs):
            """Delta-space Adasum (reference
            ``tensorflow/__init__.py:313-407`` _DistributedAdasumOptimizer):
            step locally on own gradients, Adasum-reduce the parameter
            delta, rebase. Adaptive state (Adam moments) stays local."""
            import tensorflow as tf

            tracked = [v for g, v in gv if g is not None]
            starts = [tf.identity(v) for v in tracked]
            result = super().apply_gradients(gv, **kwargs)
            for i, (v, start) in enumerate(zip(tracked, starts)):
                delta = v - start
                compressed, ctx = compression.compress(delta)
                reduced = compression.decompress(
                    allreduce(compressed, op=Adasum,
                              name=f"AdasumOptimizer.delta.{i}"),
                    ctx,
                )
                v.assign(start + tf.cast(reduced, v.dtype))
            return result

    _Distributed.__name__ = base.__name__
    _Distributed.__qualname__ = base.__qualname__
    return _Distributed


class BroadcastGlobalVariablesHook:
    """tf.compat.v1 SessionRunHook parity shim: in eager/TF2 use
    ``broadcast_variables`` or the Keras callback instead."""

    def __init__(self, root_rank: int = 0, device=""):
        self.root_rank = root_rank

    def begin(self):
        broadcast_global_variables(self.root_rank)
