"""Collective compositor: hierarchical lowering plans for every collective.

Where ``ops/collectives.py:hierarchical_allreduce`` was a hand-written
special case (local reduce-scatter -> cross allreduce -> local allgather,
the NCCLHierarchicalAllreduce re-expression), this module generalizes the
idea to the whole op set, HiCCL-style (PAPERS.md, arXiv:2408.05962): every
collective is composed from single-hop primitives (reduce-scatter /
allreduce / all-gather / tree-broadcast / all-to-all / local permute)
mapped onto the explicit interconnect hierarchy of ``topo/model.py``, and
an analytic alpha-beta cost model selects the algorithm per (topology,
payload bytes, op).

Two layers, deliberately separable:

- **Planning** (:func:`select_plan`, :class:`Plan`) is pure Python — no
  jax, deterministic, stable JSON. ``tools/topo_plan.py`` and the CI
  smoke consume only this layer.
- **Lowering** (:func:`lower_allreduce` & friends) executes a selected
  algorithm inside a ``shard_map`` trace over the model's mesh axes.
  Every hierarchical lowering is numerically equal to the flat one:
  bitwise for regroupings that commute (MIN/MAX, int sums, gather/
  scatter/permute compositions), tolerance-level for float SUM (the
  association changes) — asserted at 2/4/8 simulated ranks by
  ``tests/test_topo.py``.

Algorithms:

- ``flat`` — one XLA collective over the whole axis tuple (today's
  default path; XLA routes mixed ICI/DCN itself).
- ``ring`` / ``recursive-halving`` — explicit single-hop schedules over
  ``ppermute`` (bandwidth-optimal ring reduce-scatter+allgather; MPICH
  recursive halving-doubling for latency-bound payloads, power-of-two
  ranks only). Cross-rank bitwise-identical by construction: every
  element's reduction is computed once and copied.
- ``two-level`` — the hierarchical composition, generalized to any hop
  depth: allreduce = RS(inner) -> allreduce(outer...) -> AG(inner);
  reduce-scatter pre-permutes blocks locally so the big payload stays on
  ICI; allgather/broadcast/alltoall chain per-hop stages inner->outer.
- ``split`` — FlexLink-style (PAPERS.md) concurrent-link mode for
  multi-slice allreduce: the payload is split into two buckets
  proportional to per-hop bandwidth; the ICI-share bucket lowers
  hierarchically (DCN carries only its 1/L shards) while the DCN-share
  bucket lowers flat — two independent collectives XLA schedules
  concurrently, so the slow hop is driven instead of idled.
- ``two-level-sa`` — scatter-allgather broadcast for large payloads:
  ICI multicast inside the root slice, 1/L shards over DCN, ICI
  allgather to reassemble.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..common.quant import (
    WIRE_BF16,
    WIRE_DTYPES,
    WIRE_F32,
    WIRE_INT8,
    bf16_wire_bytes,
    int8_wire_bytes,
)
from ..common.types import ReduceOp
from .model import Hop, InterconnectModel

COLLECTIVES = (
    "allreduce", "allgather", "reducescatter", "broadcast", "alltoall",
)

# Reduce ops the hierarchical compositions support. PRODUCT stays
# flat-only (the butterfly in ops/collectives.py); ADASUM has its own
# hierarchical schedule in ops/adasum.py.
_HIER_REDUCE_OPS = (
    ReduceOp.SUM, ReduceOp.AVERAGE, ReduceOp.MIN, ReduceOp.MAX,
)

# Stable stage metadata (consumed by analysis/plan_verify.py): the base
# primitive kind behind each stage label. Suffixes encode the schedule
# variant (``-ring`` / ``-halving`` / ``-doubling`` / ``-tree``), for
# split mode the bucket (``-b0`` / ``-b1``), and for the chunked
# collective-matmul direction stages the round count (``-r<N>`` — the
# rounds depend on the chunk count, not just the hop size). ``local``
# stages move no bytes over any hop.
STAGE_KINDS = {
    "all_reduce": "allreduce",
    "reduce_scatter": "reducescatter",
    "all_gather": "allgather",
    "broadcast": "broadcast",
    "all_to_all": "alltoall",
    "block_permute": "local",
    "collective_matmul_fwd": "collmm",
    "collective_matmul_bwd": "collmm",
}


def _rounds_tag(name: str) -> Tuple[str, Optional[int]]:
    """Strip a trailing ``-r<N>`` round-count tag: ``"x-r6"`` ->
    ``("x", 6)``."""
    head, sep, tail = name.rpartition("-r")
    if sep and tail.isdigit():
        return head, int(tail)
    return name, None


def stage_kind(primitive: str) -> Tuple[str, str, Optional[int]]:
    """Decompose a stage label into ``(kind, variant, bucket)``:
    ``"reduce_scatter-ring-b1"`` -> ``("reducescatter", "ring", 1)``.
    Unknown labels return kind ``"?"`` (the verifier rejects them)."""
    name = primitive
    bucket: Optional[int] = None
    for b in (0, 1):
        if name.endswith(f"-b{b}"):
            name, bucket = name[: -3], b
            break
    variant = ""
    for suffix in ("ring", "halving", "doubling", "tree"):
        if name.endswith("-" + suffix):
            name, variant = name[: -(len(suffix) + 1)], suffix
            break
    name, _ = _rounds_tag(name)
    return STAGE_KINDS.get(name, "?"), variant, bucket


def perm_rounds(primitive: str, size: int) -> Optional[List[List[Tuple[int, int]]]]:
    """The explicit per-round ``ppermute`` schedule a ring/halving stage
    stands for, as ``[[(src, dst), ...], ...]`` over ``range(size)`` —
    the metadata the symbolic plan verifier checks for bijectivity and
    round counts. Non-permute stages (XLA-native collectives, trees,
    local relayouts) return None."""
    kind, variant, _ = stage_kind(primitive)
    n = int(size)
    if kind == "collmm":
        # Chunked collective-matmul direction stage: the round count
        # rides the ``-r<N>`` tag (hops x chunks — not derivable from
        # the hop size alone); every round is the same +1 (fwd) or -1
        # (bwd) ring shift.
        base = primitive
        for suffix in ("-ring",):
            if base.endswith(suffix):
                base = base[: -len(suffix)]
        _, r = _rounds_tag(base)
        if r is None or n <= 1:
            return []
        step = 1 if "_fwd" in primitive else -1
        perm = [(i, (i + step) % n) for i in range(n)]
        return [list(perm) for _ in range(r)]
    if variant == "ring":
        if n <= 1:
            return []
        fwd = [(i, (i + 1) % n) for i in range(n)]
        return [list(fwd) for _ in range(n - 1)]
    if variant in ("halving", "doubling"):
        if n <= 1:
            return []
        if n & (n - 1):
            return [[(i, i) for i in range(n)]]  # caught as a bad round
        k = n.bit_length() - 1
        dists = [n >> (t + 1) for t in range(k)]
        if variant == "doubling":
            dists = list(reversed(dists))
        return [[(i, i ^ d) for i in range(n)] for d in dists]
    return None


@dataclass(frozen=True)
class Stage:
    """One primitive of a lowering schedule: ``bytes_on_wire`` is the
    per-rank traffic this stage puts on its hop, ``rounds`` its latency
    cost in units of the hop's per-round latency. ``wire_dtype`` is the
    stage's wire format: ``"f32"`` (full precision — the payload's own
    width) or ``"int8"`` (blockwise int8+scales, ``common/quant.py``),
    in which case ``bytes_on_wire`` is the COMPRESSED traffic."""

    primitive: str
    hop: str
    axis: str
    bytes_on_wire: int
    rounds: int
    wire_dtype: str = WIRE_F32

    def to_dict(self) -> dict:
        return {
            "primitive": self.primitive,
            "hop": self.hop,
            "axis": self.axis,
            "bytes_on_wire": int(self.bytes_on_wire),
            "rounds": int(self.rounds),
            "wire_dtype": self.wire_dtype,
        }


@dataclass(frozen=True)
class Plan:
    """A selected lowering: the compositor's machine-readable verdict,
    exposed via ``hvd.collective_plan()`` / ``tools/topo_plan.py`` and
    recorded as ``hvd_topo_plan_info`` / ``hvd_topo_bytes_per_hop``."""

    collective: str
    op: str
    algorithm: str
    nbytes: int
    hop_sizes: Tuple[int, ...]
    stages: Tuple[Stage, ...]
    cost_us: float
    # FlexLink split mode only: (flat-bucket bytes, hierarchical-bucket
    # bytes), proportional to per-hop bandwidth.
    split_bytes: Tuple[int, ...] = ()
    # Requested wire format ("f32" or "int8"). An int8 plan must carry
    # at least one int8 stage — a plan claiming compression without a
    # quantize stage fails the symbolic verifier
    # (analysis/plan_verify.py).
    wire_dtype: str = WIRE_F32

    @property
    def bytes_per_hop(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for s in self.stages:
            if s.hop == "-":  # wireless local relayout stages
                continue
            out[s.hop] = out.get(s.hop, 0) + int(s.bytes_on_wire)
        return out

    def to_dict(self) -> dict:
        return {
            "collective": self.collective,
            "op": self.op,
            "algorithm": self.algorithm,
            "nbytes": int(self.nbytes),
            "hop_sizes": list(self.hop_sizes),
            "cost_us": round(float(self.cost_us), 4),
            "bytes_per_hop": {
                k: int(v) for k, v in sorted(self.bytes_per_hop.items())
            },
            "split_bytes": list(self.split_bytes),
            "wire_dtype": self.wire_dtype,
            "stages": [s.to_dict() for s in self.stages],
        }


def _op_name(op: Any) -> str:
    if isinstance(op, ReduceOp):
        return op.name
    return str(op or "-")


def _stage_cost_us(stage: Stage, hop: Hop) -> float:
    # GB/s == 1e3 bytes/us.
    return (
        hop.latency_us * stage.rounds
        + stage.bytes_on_wire / (hop.bandwidth_gbps * 1e3)
    )


def _plan_cost_us(stages: Sequence[Stage],
                  model: InterconnectModel) -> float:
    by_name = {h.name: h for h in model.hops}
    return sum(_stage_cost_us(s, by_name[s.hop]) for s in stages)


def _bottleneck(model: InterconnectModel) -> Hop:
    """The hop a flat (whole-tuple) collective is bound by: the slowest
    one — on a multi-slice model XLA's global collective cannot move
    cross-slice traffic faster than DCN."""
    return min(model.hops, key=lambda h: h.bandwidth_gbps)


def split_fractions(model: InterconnectModel) -> Tuple[float, float]:
    """FlexLink split for 2-level allreduce: payload fractions of the
    two pipelined hierarchical buckets, proportional to per-hop
    bandwidth (inner/ICI share first). Balanced this way, bucket 0's
    DCN stage runs while bucket 1's ICI stages do — both links stay
    driven instead of the fast one idling through the slow hop."""
    inner_bw = model.inner.bandwidth_gbps
    outer_bw = model.hops[0].bandwidth_gbps
    total = inner_bw + outer_bw
    return inner_bw / total, outer_bw / total


# --- candidate schedules (planning layer, pure python) -----------------------


def _flat_stages(model: InterconnectModel, primitive: str, nbytes: int,
                 bytes_factor: float, rounds: int) -> List[Stage]:
    b = _bottleneck(model)
    return [Stage(
        primitive=primitive, hop=b.name, axis="+".join(model.axes),
        bytes_on_wire=int(nbytes * bytes_factor), rounds=rounds,
    )]


def _compress_stage(s: Stage) -> Stage:
    """Re-declare a stage with the int8+scales wire format: same
    schedule, compressed bytes."""
    return Stage(
        primitive=s.primitive, hop=s.hop, axis=s.axis,
        bytes_on_wire=int8_wire_bytes(s.bytes_on_wire), rounds=s.rounds,
        wire_dtype=WIRE_INT8,
    )


def _cast_stage(s: Stage) -> Stage:
    """Re-declare a stage with the bf16 cast wire format: same schedule,
    half the bytes, no scales. A cast commutes with any data movement
    and any SUM/AVERAGE, so unlike int8 this applies to EVERY stage of
    every candidate."""
    return Stage(
        primitive=s.primitive, hop=s.hop, axis=s.axis,
        bytes_on_wire=bf16_wire_bytes(s.bytes_on_wire), rounds=s.rounds,
        wire_dtype=WIRE_BF16,
    )


def _candidates_allreduce(model: InterconnectModel, nbytes: int,
                          op: ReduceOp,
                          wire_dtype: str = WIRE_F32
                          ) -> Dict[str, List[Stage]]:
    n = model.size
    int8 = wire_dtype == WIRE_INT8
    cands: Dict[str, List[Stage]] = {}
    if op not in _HIER_REDUCE_OPS:
        # PRODUCT/ADASUM have no compositor regrouping: one flat plan.
        if model.levels == 1:
            h = model.hops[0]
            return {"flat": [Stage(
                "all_reduce", h.name, h.axis,
                int(nbytes * 2 * (n - 1) / max(n, 1)), max(2 * (n - 1), 0),
            )]} if n > 1 else {"flat": []}
        return {"flat": _flat_stages(
            model, "all_reduce", nbytes, 2 * (n - 1) / n, 2 * (n - 1)
        )}
    if model.levels == 1:
        h = model.hops[0]
        if n <= 1:
            return {"flat": []}
        cands["ring"] = [
            Stage("reduce_scatter-ring", h.name, h.axis,
                  int(nbytes * (n - 1) / n), n - 1),
            Stage("all_gather-ring", h.name, h.axis,
                  int(nbytes * (n - 1) / n), n - 1),
        ]
        if int8:
            # The EQuARX ring: both phases move int8+scales (the only
            # single-level quantized lowering shipped; halving-doubling
            # has no quantized schedule).
            return {"ring": [_compress_stage(s) for s in cands["ring"]]}
        if n & (n - 1) == 0 and op in _HIER_REDUCE_OPS:
            k = int(math.log2(n))
            cands["recursive-halving"] = [
                Stage("reduce_scatter-halving", h.name, h.axis,
                      int(nbytes * (n - 1) / n), k),
                Stage("all_gather-doubling", h.name, h.axis,
                      int(nbytes * (n - 1) / n), k),
            ]
        return cands
    # Multi-level: flat rides the bottleneck hop as a ring.
    cands["flat"] = _flat_stages(
        model, "all_reduce", nbytes, 2 * (n - 1) / n, 2 * (n - 1)
    )
    if int8:
        # Flat quantized = chained int8 rings, every hop compressed;
        # two-level quantized = compressed-on-DCN-only (the outermost
        # all_reduce stage moves int8+scales, the inner reduce-scatter/
        # all-gather stay full precision over ICI). Split has no
        # quantized lowering and is not offered.
        cands["flat"] = [_compress_stage(s) for s in cands["flat"]]
        two = _two_level_allreduce_stages(model, nbytes, op)
        outer = model.hops[0].name
        cands["two-level"] = [
            _compress_stage(s)
            if s.primitive == "all_reduce" and s.hop == outer else s
            for s in two
        ]
        return cands
    if op in _HIER_REDUCE_OPS:
        cands["two-level"] = _two_level_allreduce_stages(model, nbytes, op)
        if (
            model.levels == 2
            and op in (ReduceOp.SUM, ReduceOp.AVERAGE)
            and nbytes >= 2 * model.size
        ):
            cands["split"] = _split_allreduce_stages(model, nbytes)
    return cands


def _two_level_allreduce_stages(model: InterconnectModel, nbytes: int,
                                op: ReduceOp) -> List[Stage]:
    if op in (ReduceOp.MIN, ReduceOp.MAX):
        # Per-hop reduction chain: full payload on every hop, log-depth
        # rounds each (XLA's single-axis all-reduce).
        return [
            Stage("all_reduce", h.name, h.axis, int(nbytes),
                  max(1, math.ceil(math.log2(max(h.size, 2)))))
            for h in reversed(model.hops)
        ]
    # SUM/AVERAGE: RS(inner) -> allreduce(outer...) -> AG(inner),
    # recursively — the shard shrinks by each inner size.
    stages: List[Stage] = []
    remaining = nbytes
    inner_path: List[Tuple[Hop, int]] = []
    for h in reversed(model.hops[1:]):  # inner hops, innermost first
        s = h.size
        stages.append(Stage(
            "reduce_scatter", h.name, h.axis,
            int(remaining * (s - 1) / s), s - 1,
        ))
        inner_path.append((h, remaining))
        remaining = math.ceil(remaining / s)
    top = model.hops[0]
    n0 = top.size
    stages.append(Stage(
        "all_reduce", top.name, top.axis,
        int(remaining * 2 * (n0 - 1) / n0), 2 * (n0 - 1),
    ))
    for h, nb in reversed(inner_path):
        s = h.size
        stages.append(Stage(
            "all_gather", h.name, h.axis, int(nb * (s - 1) / s), s - 1,
        ))
    return stages


def _split_allreduce_stages(model: InterconnectModel,
                            nbytes: int) -> List[Stage]:
    f0, _ = split_fractions(model)
    nb0 = int(nbytes * f0)
    stages = [Stage(
        s.primitive + "-b0", s.hop, s.axis, s.bytes_on_wire, s.rounds,
    ) for s in _two_level_allreduce_stages(model, nb0, ReduceOp.SUM)]
    stages += [Stage(
        s.primitive + "-b1", s.hop, s.axis, s.bytes_on_wire, s.rounds,
    ) for s in _two_level_allreduce_stages(
        model, nbytes - nb0, ReduceOp.SUM
    )]
    return stages


def _candidates_allgather(model: InterconnectModel,
                          nbytes: int) -> Dict[str, List[Stage]]:
    n = model.size
    if model.levels == 1:
        h = model.hops[0]
        return {"ring": [Stage(
            "all_gather-ring", h.name, h.axis, int(nbytes * (n - 1)),
            max(n - 1, 0),
        )]}
    cands = {"flat": _flat_stages(
        model, "all_gather", nbytes, n - 1, n - 1
    )}
    stages: List[Stage] = []
    gathered = nbytes
    for h in reversed(model.hops):  # innermost first
        s = h.size
        stages.append(Stage(
            "all_gather", h.name, h.axis, int(gathered * (s - 1)), s - 1,
        ))
        gathered *= s
    cands["two-level"] = stages
    return cands


def _candidates_reducescatter(model: InterconnectModel, nbytes: int,
                              wire_dtype: str = WIRE_F32
                              ) -> Dict[str, List[Stage]]:
    n = model.size
    int8 = wire_dtype == WIRE_INT8
    if model.levels == 1:
        h = model.hops[0]
        ring = [Stage(
            "reduce_scatter-ring", h.name, h.axis,
            int(nbytes * (n - 1) / max(n, 1)), max(n - 1, 0),
        )]
        if int8:
            # The int8 ring RS (ops/quantized.py, ZeRO-1's gradient
            # hop): the single reduce-scatter phase of the EQuARX ring,
            # every hop int8+scales.
            return {"ring": [_compress_stage(s) for s in ring]}
        return {"ring": ring}
    cands = {"flat": _flat_stages(
        model, "reduce_scatter", nbytes, (n - 1) / n, n - 1
    )}
    stages: List[Stage] = [Stage(
        "block_permute", "-", "-", 0, 0,  # local relayout, no wire
    )]
    remaining = nbytes
    for h in reversed(model.hops):  # innermost first
        s = h.size
        stages.append(Stage(
            "reduce_scatter", h.name, h.axis,
            int(remaining * (s - 1) / s), s - 1,
        ))
        remaining = math.ceil(remaining / s)
    cands["two-level"] = stages
    if int8:
        # Planning-level quantized RS on a hierarchy: flat rides the
        # bottleneck as the int8 ring; two-level compresses only the
        # outermost (DCN) stage — the 1/L shard that actually crosses
        # the slow hop — like the allreduce DCN-only construction.
        outer = model.hops[0].name
        cands["flat"] = [_compress_stage(s) for s in cands["flat"]]
        cands["two-level"] = [
            _compress_stage(s) if s.hop == outer else s
            for s in cands["two-level"]
        ]
    return cands


def _candidates_broadcast(model: InterconnectModel,
                          nbytes: int) -> Dict[str, List[Stage]]:
    if model.levels == 1:
        h = model.hops[0]
        k = max(1, math.ceil(math.log2(max(h.size, 2))))
        if h.size <= 1:
            return {"tree": []}
        return {"tree": [Stage(
            "broadcast-tree", h.name, h.axis, int(nbytes) * k, k,
        )]}
    b = _bottleneck(model)
    n = model.size
    k_all = max(1, math.ceil(math.log2(max(n, 2))))
    cands = {"flat": [Stage(
        "broadcast-tree", b.name, "+".join(model.axes),
        int(nbytes) * k_all, k_all,
    )]}
    # Per-hop trees, inner -> outer (full payload each hop).
    tree: List[Stage] = []
    for h in reversed(model.hops):
        k = max(1, math.ceil(math.log2(max(h.size, 2))))
        tree.append(Stage(
            "broadcast-tree", h.name, h.axis, int(nbytes) * k, k,
        ))
    cands["two-level"] = tree
    # Scatter-allgather: tree inside the root slice, 1/L shards over the
    # outer hops, inner allgather to reassemble.
    inner = model.inner
    L = inner.size
    k_in = max(1, math.ceil(math.log2(max(L, 2))))
    sa: List[Stage] = [Stage(
        "broadcast-tree", inner.name, inner.axis, int(nbytes) * k_in, k_in,
    )]
    shard = math.ceil(nbytes / L)
    for h in reversed(model.hops[:-1]):
        k = max(1, math.ceil(math.log2(max(h.size, 2))))
        sa.append(Stage(
            "broadcast-tree", h.name, h.axis, int(shard) * k, k,
        ))
    sa.append(Stage(
        "all_gather", inner.name, inner.axis,
        int(nbytes * (L - 1) / L), L - 1,
    ))
    cands["two-level-sa"] = sa
    return cands


def _candidates_alltoall(model: InterconnectModel,
                         nbytes: int) -> Dict[str, List[Stage]]:
    n = model.size
    if model.levels == 1:
        h = model.hops[0]
        return {"flat": [Stage(
            "all_to_all", h.name, h.axis,
            int(nbytes * (n - 1) / max(n, 1)), max(n - 1, 0),
        )]}
    cands = {"flat": _flat_stages(
        model, "all_to_all", nbytes, (n - 1) / n, n - 1
    )}
    stages: List[Stage] = []
    for h in model.hops:  # outermost first (the lowering's phase order)
        s = h.size
        stages.append(Stage(
            "all_to_all", h.name, h.axis,
            int(nbytes * (s - 1) / s), s - 1,
        ))
    cands["two-level"] = stages
    return cands


def _effective_model(model: InterconnectModel) -> InterconnectModel:
    if model.eligible or model.levels <= 1:
        return model
    # Collapse to the flat view: hierarchy exists but is unsafe.
    return InterconnectModel(
        hops=(Hop(
            name=_bottleneck(model).name,
            axis="+".join(model.axes),
            size=model.size,
            bandwidth_gbps=_bottleneck(model).bandwidth_gbps,
            latency_us=_bottleneck(model).latency_us,
        ),),
        generation=model.generation, eligible=False,
        source=model.source,
    )


def candidate_plans(
    model: InterconnectModel,
    collective: str,
    nbytes: int,
    op: Any = ReduceOp.SUM,
    wire_dtype: str = WIRE_F32,
) -> Dict[str, Plan]:
    """Every candidate lowering the compositor can emit for
    ``collective`` at this payload on this model, as fully-formed costed
    :class:`Plan` objects keyed by algorithm name. :func:`select_plan`
    picks the cheapest of these; the symbolic plan verifier
    (``analysis/plan_verify.py``) checks every one of them.
    ``wire_dtype="int8"`` (allreduce and reduce-scatter, SUM/AVERAGE
    only — reduce-scatter is ZeRO-1's gradient hop) prices the
    quantized wire: every hop compressed for flat/ring, only the
    outermost (DCN) hop for two-level. ``wire_dtype="bf16"`` is the
    pure-cast rung (docs/topology.md): half the bytes on EVERY stage of
    EVERY candidate of EVERY collective — a cast commutes with any data
    movement and any additive reduction, needs no scales and no error
    feedback."""
    if collective not in COLLECTIVES:
        raise ValueError(
            f"unknown collective {collective!r}; one of {COLLECTIVES}"
        )
    if wire_dtype not in WIRE_DTYPES:
        raise ValueError(
            f"unknown wire_dtype {wire_dtype!r}; one of {WIRE_DTYPES}"
        )
    nbytes = max(int(nbytes), 0)
    op_enum = op if isinstance(op, ReduceOp) else None
    if isinstance(op, str) and op not in ("-", ""):
        op_enum = ReduceOp[op.upper()]
    if op_enum is None:
        op_enum = ReduceOp.SUM
    if wire_dtype == WIRE_INT8 and (
        collective not in ("allreduce", "reducescatter")
        or op_enum not in (ReduceOp.SUM, ReduceOp.AVERAGE)
    ):
        raise ValueError(
            "wire_dtype='int8' is an allreduce/reduce-scatter "
            f"SUM/AVERAGE construction (got {collective}/"
            f"{_op_name(op_enum)}): per-hop int8 requantization "
            "accumulates in f32, which is only sound for additive "
            "reductions"
        )
    eff = _effective_model(model)
    if collective == "allreduce":
        cands = _candidates_allreduce(eff, nbytes, op_enum, wire_dtype)
    elif collective == "allgather":
        cands = _candidates_allgather(eff, nbytes)
    elif collective == "reducescatter":
        cands = _candidates_reducescatter(eff, nbytes, wire_dtype)
    elif collective == "broadcast":
        cands = _candidates_broadcast(eff, nbytes)
    else:
        cands = _candidates_alltoall(eff, nbytes)
    if not cands:
        cands = {"flat": []}
    if wire_dtype == WIRE_BF16:
        # The cast applies uniformly after the fact: same schedules,
        # every wire stage at half the bytes (local relayouts move no
        # wire bytes and stay as-is).
        cands = {
            name: [_cast_stage(s) if s.hop != "-" else s for s in stages]
            for name, stages in cands.items()
        }
    op_label = _op_name(
        op_enum if collective in ("allreduce", "reducescatter") else None
    )
    plans: Dict[str, Plan] = {}
    for name in sorted(cands):
        stages = cands[name]
        if name == "split":
            cost = _split_cost_us(
                eff,
                bf16_wire_bytes(nbytes) if wire_dtype == WIRE_BF16
                else nbytes,
            )
            f0, _ = split_fractions(eff)
            nb0 = int(nbytes * f0)
            split_bytes: Tuple[int, ...] = (nb0, nbytes - nb0)
        else:
            cost = _plan_cost_us(
                [s for s in stages if s.hop != "-"], eff
            )
            split_bytes = ()
        plans[name] = Plan(
            collective=collective,
            op=op_label,
            algorithm=name,
            nbytes=nbytes,
            hop_sizes=tuple(h.size for h in eff.hops),
            stages=tuple(stages),
            cost_us=float(cost),
            split_bytes=split_bytes,
            wire_dtype=wire_dtype,
        )
    return plans


def select_plan(
    model: InterconnectModel,
    collective: str,
    nbytes: int,
    op: Any = ReduceOp.SUM,
    wire_dtype: str = WIRE_F32,
) -> Plan:
    """Cost every candidate algorithm for ``collective`` at this payload
    on this model and return the cheapest as a :class:`Plan`. An
    ineligible model (ragged/interleaved layout, or a single hop) only
    considers single-level algorithms — the "safe to go hierarchical"
    gate from ``Topology.is_homogeneous``."""
    plans = candidate_plans(model, collective, nbytes, op, wire_dtype)
    best: Optional[Plan] = None
    for name in sorted(plans):  # deterministic tie-break
        plan = plans[name]
        if best is None or plan.cost_us < best.cost_us:
            best = plan
    return best


def _split_cost_us(model: InterconnectModel, nbytes: int) -> float:
    """Pipelined estimate for the split mode: across the two buckets,
    each hop's bandwidth terms sum to the same totals as one two-level
    pass (splitting is size-linear), but the hops run CONCURRENTLY —
    take the max of the per-hop busy times — while the latency terms pay
    twice (two dispatched schedules). That is what makes split lose to
    plain two-level for small payloads (latency-bound) and win for large
    ones (the faster hop's busy time hides inside the slower's)."""
    one = _two_level_allreduce_stages(model, nbytes, ReduceOp.SUM)
    by_name = {h.name: h for h in model.hops}
    busy: Dict[str, float] = {}
    alpha = 0.0
    for s in one:
        hop = by_name[s.hop]
        busy[s.hop] = busy.get(s.hop, 0.0) + (
            s.bytes_on_wire / (hop.bandwidth_gbps * 1e3)
        )
        alpha += hop.latency_us * s.rounds
    return max(busy.values()) + 2 * alpha


# --- collective-matmul plan kind (fused TP overlap) --------------------------
#
# docs/parallelism.md "Fused TP overlap": ops/collective_matmul.py's
# all_gather_matmul / matmul_reduce_scatter dissolve the Megatron TP
# psum into bidirectional chunked ppermute chains that ride the wire
# WHILE the MXU multiplies. These plans price one such primitive:
# cost = max(compute, wire) + ramp, where ramp is the pipeline fill (the
# first sub-chunk's hop, which nothing can hide) — more chunks shrink
# the ramp but pay more per-round latency, the trade the tuner searches.

COLLECTIVE_MATMUL_FLAVORS = ("all_gather_matmul", "matmul_reduce_scatter")


def ring_hops(n: int) -> Tuple[int, int]:
    """Hops each ring direction carries for a bidirectional pass over
    ``n`` ranks: ``(ceil((n-1)/2), floor((n-1)/2))`` — together exactly
    the ``n-1`` deliveries, split so both link directions work."""
    n = int(n)
    if n <= 1:
        return (0, 0)
    return (-(-(n - 1) // 2), (n - 1) // 2)


def collective_matmul_cost_us(
    model: InterconnectModel,
    nbytes: int,
    *,
    chunks: int = 1,
    compute_us: float = 0.0,
    wire_dtype: str = WIRE_F32,
) -> Dict[str, float]:
    """Price ONE chunked collective-matmul primitive on the innermost
    hop (the TP axis rides ICI): ``wire`` is the busier ring direction's
    time (the directions run concurrently), ``ramp`` the first
    sub-chunk's un-hideable delivery, ``cost = max(compute, wire) +
    ramp`` and ``exposed = cost - compute`` — what the step pays beyond
    the matmul it had to run anyway. Compare against the classic
    exposed-psum constant (``sim.tp_fixed_comm_us``)."""
    hop = model.hops[-1]
    n = hop.size
    compute_us = float(compute_us)
    if n <= 1:
        return {
            "cost_us": round(compute_us, 4), "exposed_us": 0.0,
            "wire_us": 0.0, "ramp_us": 0.0,
        }
    h_fwd, h_bwd = ring_hops(n)
    c = max(int(chunks), 1)
    wire_bytes = (
        bf16_wire_bytes(nbytes) if wire_dtype == WIRE_BF16
        else int8_wire_bytes(nbytes) if wire_dtype == WIRE_INT8
        else int(nbytes)
    )
    bw = hop.bandwidth_gbps * 1e3  # bytes/us
    wire_fwd = hop.latency_us * h_fwd * c + wire_bytes * h_fwd / n / bw
    wire_bwd = hop.latency_us * h_bwd * c + wire_bytes * h_bwd / n / bw
    wire_us = max(wire_fwd, wire_bwd)
    ramp_us = hop.latency_us + wire_bytes / (n * c) / bw
    cost = max(compute_us, wire_us) + ramp_us
    return {
        "cost_us": round(cost, 4),
        "exposed_us": round(cost - compute_us, 4),
        "wire_us": round(wire_us, 4),
        "ramp_us": round(ramp_us, 4),
    }


def collective_matmul_plan(
    model: InterconnectModel,
    flavor: str,
    nbytes: int,
    *,
    chunks: int = 1,
    compute_us: float = 0.0,
    wire_dtype: str = WIRE_F32,
) -> Plan:
    """The machine-checkable schedule behind one fused primitive: one
    direction stage per ring (the bwd stage vanishes at n=2 where the
    backward ring carries nothing), each ``hops x chunks`` rounds of the
    same +-1 shift with EXACT symbolic bytes ``nbytes*hops/n`` — what
    ``analysis/plan_verify`` Pass 3 executes for per-round bijectivity
    and byte accounting. ``cost_us`` embeds the overlap model of
    :func:`collective_matmul_cost_us`."""
    if flavor not in COLLECTIVE_MATMUL_FLAVORS:
        raise ValueError(
            f"unknown collective_matmul flavor {flavor!r}; one of "
            f"{COLLECTIVE_MATMUL_FLAVORS}"
        )
    if wire_dtype not in WIRE_DTYPES:
        raise ValueError(
            f"unknown wire_dtype {wire_dtype!r}; one of {WIRE_DTYPES}"
        )
    if wire_dtype == WIRE_INT8:
        raise ValueError(
            "wire_dtype='int8' is an allreduce/reduce-scatter "
            "SUM/AVERAGE construction — the collective-matmul chunks "
            "are consumed by a matmul per hop, which has no blockwise "
            "requantization schedule; use 'bf16' for the cast rung"
        )
    nbytes = max(int(nbytes), 0)
    hop = model.hops[-1]
    n = hop.size
    h_fwd, h_bwd = ring_hops(n)
    c = max(int(chunks), 1)
    stages: List[Stage] = []
    for direction, hops in (("fwd", h_fwd), ("bwd", h_bwd)):
        if hops <= 0:
            continue
        s = Stage(
            primitive=(
                f"collective_matmul_{direction}-r{hops * c}-ring"
            ),
            hop=hop.name, axis=hop.axis,
            bytes_on_wire=int(nbytes * hops / n), rounds=hops * c,
        )
        stages.append(_cast_stage(s) if wire_dtype == WIRE_BF16 else s)
    priced = collective_matmul_cost_us(
        model, nbytes, chunks=c, compute_us=compute_us,
        wire_dtype=wire_dtype,
    )
    return Plan(
        collective="collective_matmul",
        op="SUM" if flavor == "matmul_reduce_scatter" else "-",
        algorithm=f"{flavor}-c{c}",
        nbytes=nbytes,
        hop_sizes=tuple(h.size for h in model.hops),
        stages=tuple(stages),
        cost_us=float(priced["cost_us"]),
        wire_dtype=wire_dtype,
    )


# --- lowering layer (inside shard_map traces) --------------------------------
#
# jax imports stay inside the functions so the planning layer (and
# tools/topo_plan.py) never pulls a backend in.


def _axes_tuple(axes) -> Tuple[str, ...]:
    if isinstance(axes, str):
        return (axes,)
    return tuple(axes)


def _sizes(axes: Tuple[str, ...]) -> List[int]:
    from ..common.compat import axis_size

    return [axis_size(a) for a in axes]


def _check_reduce_op(op: ReduceOp, collective: str) -> None:
    if op not in _HIER_REDUCE_OPS:
        raise ValueError(
            f"hierarchical {collective} supports "
            f"{[o.name for o in _HIER_REDUCE_OPS]}; got {op!r} "
            f"(PRODUCT/ADASUM have no hierarchical regrouping here — "
            f"use the flat lowering or ops/adasum.py)"
        )


def _allreduce_sum_axes(flat, axes: Tuple[str, ...]):
    """k-level SUM allreduce on a flat vector: RS(inner) -> recurse on
    the shard over the outer axes -> AG(inner). The k=2 case is exactly
    the old ``hierarchical_allreduce`` body."""
    import jax.numpy as jnp
    from jax import lax

    from ..common.compat import axis_size

    if len(axes) == 1:
        return lax.psum(flat, axes[0])
    inner = axes[-1]
    L = axis_size(inner)
    n = flat.shape[0]
    pad = (-n) % L
    if pad:
        flat = jnp.pad(flat, (0, pad))
    shard = lax.psum_scatter(flat, inner, scatter_dimension=0, tiled=True)
    shard = _allreduce_sum_axes(shard, axes[:-1])
    full = lax.all_gather(shard, inner, tiled=True)
    if pad:
        full = full[:n]
    return full


def _ring_allreduce(x, axis: str, combine=None):
    """Explicit ring allreduce over one hop: reduce-scatter ring then
    allgather ring via ``ppermute``, n-1 rounds each, bandwidth-optimal.
    Each chunk's reduction is a single accumulation chain along the ring
    and then copied, so every rank's result is bitwise identical.
    ``combine`` is the elementwise reduction (default add)."""
    import jax.numpy as jnp
    from jax import lax

    from ..common.compat import axis_size

    if combine is None:
        combine = jnp.add
    axes = _axes_tuple(axis)
    assert len(axes) == 1, "ring schedule is a single-hop primitive"
    axis = axes[0]
    n = axis_size(axis)
    if n == 1:
        return x
    shape = x.shape
    flat = x.reshape(-1)
    N = flat.shape[0]
    pad = (-N) % n
    if pad:
        flat = jnp.pad(flat, (0, pad))
    m = flat.shape[0] // n
    r = lax.axis_index(axis)
    fwd = [(i, (i + 1) % n) for i in range(n)]
    buf = flat
    for t in range(n - 1):
        send_idx = (r - t) % n
        send = lax.dynamic_slice(buf, (send_idx * m,), (m,))
        recv = lax.ppermute(send, axis, fwd)
        recv_idx = (r - t - 1) % n
        acc = combine(lax.dynamic_slice(buf, (recv_idx * m,), (m,)), recv)
        buf = lax.dynamic_update_slice(buf, acc, (recv_idx * m,))
    # Rank r now owns the fully-reduced chunk (r + 1) % n; forward it
    # around the ring.
    for t in range(n - 1):
        send_idx = (r + 1 - t) % n
        send = lax.dynamic_slice(buf, (send_idx * m,), (m,))
        recv = lax.ppermute(send, axis, fwd)
        recv_idx = (r - t) % n
        buf = lax.dynamic_update_slice(buf, recv, (recv_idx * m,))
    if pad:
        buf = buf[:N]
    return buf.reshape(shape)


def _rhd_allreduce(x, axis: str, combine):
    """MPICH recursive halving-doubling over one hop (power-of-two ranks):
    log2(n) halving exchanges reduce-scatter the vector, log2(n) doubling
    exchanges gather it back. ``combine`` is the elementwise reduction
    (add / minimum / maximum). Bitwise identical across ranks — every
    element's reduction tree is computed once by its segment owner."""
    import jax.numpy as jnp
    from jax import lax

    from ..common.compat import axis_size

    axes = _axes_tuple(axis)
    assert len(axes) == 1, "halving-doubling is a single-hop primitive"
    axis = axes[0]
    n = axis_size(axis)
    if n == 1:
        return x
    if n & (n - 1):
        raise ValueError(
            f"recursive-halving needs a power-of-two hop size, got {n}"
        )
    k = n.bit_length() - 1
    shape = x.shape
    flat = x.reshape(-1)
    N = flat.shape[0]
    pad = (-N) % n
    if pad:
        flat = jnp.pad(flat, (0, pad))
    r = lax.axis_index(axis)
    buf = flat
    bits = []
    # Halving phase: decide the high bit first (partner at distance n/2).
    for t in range(k):
        d = n >> (t + 1)
        half = buf.shape[0] // 2
        bit = (r >> (k - 1 - t)) & 1  # 0 -> keep low half, 1 -> keep high
        bits.append(bit)
        keep = lax.dynamic_slice(buf, (bit * half,), (half,))
        send = lax.dynamic_slice(buf, ((1 - bit) * half,), (half,))
        perm = [(i, i ^ d) for i in range(n)]
        recv = lax.ppermute(send, axis, perm)
        buf = combine(keep, recv)
    # Doubling phase: reverse the exchanges, rebuilding the vector.
    for t in reversed(range(k)):
        d = n >> (t + 1)
        bit = bits[t]
        perm = [(i, i ^ d) for i in range(n)]
        recv = lax.ppermute(buf, axis, perm)
        low_first = jnp.concatenate([buf, recv])
        high_first = jnp.concatenate([recv, buf])
        buf = jnp.where(bit == 0, low_first, high_first)
    if pad:
        buf = buf[:N]
    return buf.reshape(shape)


def lower_allreduce(
    x,
    axes,
    *,
    op: ReduceOp = ReduceOp.SUM,
    algorithm: str = "two-level",
    split_fraction: Optional[float] = None,
    wire_dtype: str = WIRE_F32,
):
    """Allreduce ``x`` over the hierarchy ``axes`` (outermost first) with
    the given algorithm. Numerically equal to
    ``lax.psum/pmin/pmax(x, tuple(axes))`` — exactly for f32 wire, to
    int8 quantization tolerance for ``wire_dtype="int8"`` (SUM/AVERAGE
    only): flat/ring lower through the int8 ring on every hop,
    two-level compresses only the outermost hop
    (``ops/quantized.quantized_hierarchical_allreduce``), to bf16
    rounding for ``wire_dtype="bf16"`` (any op, any algorithm: the
    payload casts down once on entry and back up on exit — the
    pure-cast rung, no scales, no error feedback)."""
    import jax.numpy as jnp
    from jax import lax

    from ..common.compat import axis_size

    axes = _axes_tuple(axes)
    total = axis_size(axes)
    if wire_dtype == WIRE_BF16:
        orig = x.dtype
        out = lower_allreduce(
            x.astype(jnp.bfloat16), axes, op=op, algorithm=algorithm,
            split_fraction=split_fraction, wire_dtype=WIRE_F32,
        )
        return out.astype(orig)
    if wire_dtype == WIRE_INT8:
        from ..ops.quantized import (
            quantized_hierarchical_allreduce,
            quantized_ring_allreduce,
        )

        if op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
            raise ValueError(
                f"wire_dtype='int8' supports SUM/AVERAGE; got {op}"
            )
        average = op == ReduceOp.AVERAGE
        if algorithm in ("flat", "ring", "recursive-halving"):
            return quantized_ring_allreduce(
                x, axis_name=axes if len(axes) > 1 else axes[0],
                average=average,
            )
        if algorithm == "two-level":
            return quantized_hierarchical_allreduce(
                x, axes, average=average
            )
        raise ValueError(
            f"allreduce algorithm {algorithm!r} has no int8 lowering"
        )
    if algorithm == "flat":
        from ..ops import collectives as _c

        return _c.allreduce(x, op=op, axis_name=axes)
    if algorithm == "ring":
        _check_reduce_op(op, "ring allreduce")
        combine = {
            ReduceOp.SUM: jnp.add,
            ReduceOp.AVERAGE: jnp.add,
            ReduceOp.MIN: jnp.minimum,
            ReduceOp.MAX: jnp.maximum,
        }[op]
        out = _ring_allreduce(x, axes[0], combine)
        if op == ReduceOp.AVERAGE:
            out = out / total
        return out
    if algorithm == "recursive-halving":
        _check_reduce_op(op, "allreduce")
        combine = {
            ReduceOp.SUM: jnp.add,
            ReduceOp.AVERAGE: jnp.add,
            ReduceOp.MIN: jnp.minimum,
            ReduceOp.MAX: jnp.maximum,
        }[op]
        out = _rhd_allreduce(x, axes[0], combine)
        if op == ReduceOp.AVERAGE:
            out = out / total
        return out
    _check_reduce_op(op, "allreduce")
    if op in (ReduceOp.MIN, ReduceOp.MAX):
        # Per-hop reduction chain, inner -> outer: each stage stays on
        # one hop; regrouping MIN/MAX commutes exactly (bitwise).
        red = lax.pmin if op == ReduceOp.MIN else lax.pmax
        out = x
        for a in reversed(axes):
            out = red(out, a)
        return out
    if algorithm == "two-level":
        flat = x.reshape(-1)
        out = _allreduce_sum_axes(flat, axes).reshape(x.shape)
        if op == ReduceOp.AVERAGE:
            out = out / total
        return out
    if algorithm == "split":
        if len(axes) != 2:
            raise ValueError("split mode composes exactly two hops")
        if split_fraction is None:
            split_fraction = 0.5
        flat = x.reshape(-1)
        N = flat.shape[0]
        n0 = max(min(int(N * split_fraction), N - 1), 1) if N > 1 else 0
        if n0 == 0:
            out = _allreduce_sum_axes(flat, axes)
        else:
            # Two independent hierarchical reductions XLA schedules
            # concurrently: bucket 0's DCN shard-allreduce overlaps
            # bucket 1's ICI reduce-scatter/allgather (FlexLink:
            # aggregate the links, don't idle one). Elementwise SUM
            # splits cleanly, so the concatenation equals the unsplit
            # reduction.
            part0 = _allreduce_sum_axes(flat[:n0], axes)
            part1 = _allreduce_sum_axes(flat[n0:], axes)
            out = jnp.concatenate([part0, part1])
        out = out.reshape(x.shape)
        if op == ReduceOp.AVERAGE:
            out = out / total
        return out
    raise ValueError(f"unknown allreduce algorithm {algorithm!r}")


def lower_allgather(x, axes, *, algorithm: str = "two-level"):
    """Allgather along dim 0 over the hierarchy: per-hop gathers chained
    inner -> outer reproduce the flat rank order exactly (the block
    layout rank = outer*inner_size + inner makes the concatenations
    nest)."""
    from jax import lax

    axes = _axes_tuple(axes)
    if algorithm == "flat" or len(axes) == 1:
        return lax.all_gather(x, axes if len(axes) > 1 else axes[0],
                              tiled=True)
    out = x
    for a in reversed(axes):
        out = lax.all_gather(out, a, tiled=True)
    return out


def lower_reducescatter(
    x, axes, *, op: ReduceOp = ReduceOp.SUM, algorithm: str = "two-level",
    scatter_axis: int = 0,
):
    """Reduce-scatter dim0 over the hierarchy. The two-level schedule
    pre-permutes dim0 blocks locally (free relayout, no wire) so the
    inner reduce-scatter runs FIRST — the big payload stays on ICI and
    only the 1/L shard crosses DCN — while the emitted shard still
    matches the flat op's outer-major rank order."""
    import jax.numpy as jnp
    from jax import lax

    from ..common.compat import axis_size

    axes = _axes_tuple(axes)
    if scatter_axis != 0:
        raise ValueError("compositor reduce-scatter scatters dim0")
    if op == ReduceOp.AVERAGE:
        x = x / axis_size(axes)
    elif op not in (ReduceOp.SUM, ReduceOp.ADASUM):
        raise ValueError(f"reducescatter supports SUM/AVERAGE, got {op}")
    if algorithm == "flat" or len(axes) == 1:
        return lax.psum_scatter(
            x, axes if len(axes) > 1 else axes[0],
            scatter_dimension=0, tiled=True,
        )
    sizes = _sizes(axes)
    n = 1
    for s in sizes:
        n *= s
    if x.shape[0] % n:
        raise ValueError(
            f"reduce-scatter dim0 ({x.shape[0]}) must be divisible by the "
            f"grid size ({n})"
        )

    def rs(v, axs, szs):
        if len(axs) == 1:
            return lax.psum_scatter(v, axs[0], scatter_dimension=0,
                                    tiled=True)
        L = szs[-1]
        M = 1
        for s in szs[:-1]:
            M *= s
        m = v.shape[0] // (M * L)
        # Block transpose: destination blocks are outer-major (o*L + l);
        # putting l outermost lets the inner hop scatter first.
        v = v.reshape((M, L, m) + v.shape[1:])
        v = jnp.swapaxes(v, 0, 1)
        v = v.reshape((M * L * m,) + v.shape[3:])
        shard = lax.psum_scatter(v, axs[-1], scatter_dimension=0,
                                 tiled=True)
        return rs(shard, axs[:-1], szs[:-1])

    return rs(x, axes, sizes)


def _axis_roots(root_rank: int, sizes: Sequence[int]) -> List[int]:
    """Decompose a global root rank (outer-major mixed radix) into
    per-axis root coordinates."""
    roots: List[int] = []
    rem = root_rank
    for s in reversed(sizes):  # innermost first
        roots.append(rem % s)
        rem //= s
    return list(reversed(roots))  # outer-major, matching axes order


def lower_broadcast(
    x, axes, *, root_rank: int = 0, algorithm: str = "two-level",
):
    """Broadcast the global ``root_rank``'s value over the hierarchy.
    ``two-level`` chains per-hop binomial trees inner -> outer;
    ``two-level-sa`` (large payloads) multicasts inside the root slice,
    moves only 1/L shards over the outer hops, and reassembles with an
    inner allgather. Exact: broadcast moves bits, no arithmetic."""
    import jax.numpy as jnp
    from jax import lax

    from ..common.compat import axis_size
    from ..ops.collectives import broadcast as _tree_bcast

    axes = _axes_tuple(axes)
    sizes = _sizes(axes)
    n = 1
    for s in sizes:
        n *= s
    if not 0 <= int(root_rank) < n:
        raise ValueError(
            f"root_rank {root_rank} out of range for grid of size {n}"
        )
    roots = _axis_roots(int(root_rank), sizes)
    if algorithm == "flat" or len(axes) == 1:
        if len(axes) == 1:
            return _tree_bcast(x, root_rank=int(root_rank),
                               axis_name=axes[0])
        # Flat over the tuple: chain is the canonical lowering anyway
        # (XLA has no native multi-axis tree broadcast primitive).
        algorithm = "two-level"
    if algorithm == "two-level":
        out = x
        for a, r in zip(reversed(axes), reversed(roots)):
            out = _tree_bcast(out, root_rank=r, axis_name=a)
        return out
    if algorithm == "two-level-sa":
        inner = axes[-1]
        L = sizes[-1]
        shape = x.shape
        # Stage 1: the root's slice gets the value over ICI.
        out = _tree_bcast(x, root_rank=roots[-1], axis_name=inner)
        flat = out.reshape(-1)
        N = flat.shape[0]
        pad = (-N) % L
        if pad:
            flat = jnp.pad(flat, (0, pad))
        m = flat.shape[0] // L
        li = lax.axis_index(inner)
        shard = lax.dynamic_slice(flat, (li * m,), (m,))
        # Stage 2: only the 1/L shard crosses the outer (DCN) hops.
        for a, r in zip(reversed(axes[:-1]), reversed(roots[:-1])):
            shard = _tree_bcast(shard, root_rank=r, axis_name=a)
        # Stage 3: reassemble over ICI.
        full = lax.all_gather(shard, inner, tiled=True)
        if pad:
            full = full[:N]
        return full.reshape(shape)
    raise ValueError(f"unknown broadcast algorithm {algorithm!r}")


def lower_alltoall(x, axes, *, algorithm: str = "two-level"):
    """All-to-all dim0 over the hierarchy: recursive two-phase exchange —
    outer-hop all-to-all grouping by destination slice, block transpose
    (local relayout), then the inner hops, another transpose restoring
    source-rank order. Exact: pure data movement."""
    import jax.numpy as jnp
    from jax import lax

    axes = _axes_tuple(axes)
    if algorithm == "flat" or len(axes) == 1:
        return lax.all_to_all(
            x, axes if len(axes) > 1 else axes[0],
            split_axis=0, concat_axis=0, tiled=True,
        )
    sizes = _sizes(axes)
    n = 1
    for s in sizes:
        n *= s
    if x.shape[0] % n:
        raise ValueError(
            f"alltoall dim0 ({x.shape[0]}) must be divisible by the grid "
            f"size ({n})"
        )

    def a2a(v, axs, szs):
        if len(axs) == 1:
            return lax.all_to_all(v, axs[0], split_axis=0, concat_axis=0,
                                  tiled=True)
        A = szs[0]
        R = 1
        for s in szs[1:]:
            R *= s
        m = v.shape[0] // (A * R)
        # Phase 1: exchange over the outer hop by destination-outer
        # index (blocks are destination-rank order, outer-major, so the
        # leading dim already groups by it).
        y = lax.all_to_all(v, axs[0], split_axis=0, concat_axis=0,
                           tiled=True)
        # y dim0 = [source-outer][dest-rest]; bring dest-rest leading so
        # the inner hops exchange per-destination.
        y = y.reshape((A, R, m) + y.shape[1:])
        y = jnp.swapaxes(y, 0, 1)
        y = y.reshape((R * A * m,) + y.shape[3:])
        z = a2a(y, axs[1:], szs[1:])
        # z dim0 = [source-rest][source-outer]; restore source-rank
        # (outer-major) order.
        z = z.reshape((R, A, m) + z.shape[1:])
        z = jnp.swapaxes(z, 0, 1)
        return z.reshape((A * R * m,) + z.shape[3:])

    return a2a(x, axes, sizes)


# --- metrics / introspection -------------------------------------------------


def record_plan(plan: Plan, where: str = "compositor") -> Plan:
    """Stamp a selected plan into the metrics registry (gated on the
    metrics tap, so production default cost is one boolean)."""
    from .. import metrics as _metrics
    from .. import trace as _trace

    if _trace.ACTIVE:
        # Correlation ids for the fleet-trace step spans: the selected
        # lowering algorithm + wire dtype ride every later step span so
        # one trace links step → bucket → collective → hop.
        _trace.TAP.note_plan(
            topo_algorithm=plan.algorithm,
            topo_collective=plan.collective,
            wire_dtype=getattr(plan, "wire_dtype", "f32"),
        )
    if _metrics.ACTIVE:
        _metrics.TAP.set(
            "hvd_topo_plan_info", 1.0,
            collective=plan.collective, algorithm=plan.algorithm,
            op=plan.op, where=where,
        )
        for hop, nb in plan.bytes_per_hop.items():
            _metrics.TAP.set(
                "hvd_topo_bytes_per_hop", float(nb),
                collective=plan.collective, hop=hop, where=where,
            )
    return plan


def model_for_axes(axes, generation: Optional[str] = None):
    """Interconnect model for a bound axis tuple, built INSIDE a trace
    (axis sizes come from the live axis bindings): innermost axis maps to
    the ICI hop, the next to DCN, a third to inter-pod DCN — with the
    ``HOROVOD_TOPOLOGY_MODEL`` override applied. This is how the streamed
    (overlap) path prices buckets against the mesh it is actually traced
    over rather than a detected process topology."""
    from .model import (
        DCN, ICI, POD_DCN, InterconnectModel, _mk_hop, apply_override,
        detect_generation,
    )

    axes = _axes_tuple(axes)
    sizes = _sizes(axes)
    generation = generation or detect_generation()
    names = (ICI, DCN, POD_DCN)
    hops = []
    for i, (a, s) in enumerate(zip(reversed(axes), reversed(sizes))):
        hops.append(_mk_hop(names[min(i, 2)], s, generation, axis=a))
    model = InterconnectModel(
        hops=tuple(reversed(hops)), generation=generation,
        eligible=len(axes) > 1 and sizes[-1] > 1, source="axes",
    )
    return apply_override(model)


def auto_reduce_fn(quantized: bool = False,
                   algorithm: Optional[str] = None):
    """A ``reduce_fn`` that builds the model from the bound axes at trace
    time and then defers to :func:`planned_reduce_fn` — the form the
    compiled-mode binding uses for ``hierarchical="auto"``.
    ``algorithm`` pins one allreduce lowering (the offline tuner's
    verdict, docs/autotune.md) instead of per-bucket cost selection."""

    def fn(x, *, op, axis_name, prescale_factor=1.0, postscale_factor=1.0):
        axes = _axes_tuple(axis_name)
        return planned_reduce_fn(
            model_for_axes(axes), axes, quantized=quantized,
            algorithm=algorithm,
        )(
            x, op=op, axis_name=axes,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor,
        )

    return fn


def planned_reduce_fn(model: InterconnectModel, axes=None,
                      quantized: bool = False,
                      algorithm: Optional[str] = None):
    """A ``reduce_fn`` for ``ops/fusion.py``: per bucket, select the
    allreduce plan for the bucket's payload on this model and lower it
    accordingly — this is what makes ``make_train_step(overlap=True)``
    go hierarchical automatically on multi-slice topologies, per bucket.
    ``axes`` defaults to the model's own axis tuple.

    ``quantized=True`` selects among the wire_dtype=int8 candidates
    (float SUM/AVERAGE buckets only — integer buckets and other ops fall
    back to full precision): the chosen plan lowers with int8 on every
    hop (flat/ring) or on the outermost hop only (two-level).

    Single-hop plan labels (``ring`` / ``recursive-halving``) lower via
    the native XLA collective: on one hop XLA already schedules its own
    ring/halving and the label is the cost model's estimate of that, not
    an instruction to hand-roll ``ppermute`` schedules inside a training
    step. The explicit schedules stay reachable through
    :func:`lower_allreduce` for tests and offline measurement. The int8
    ring is the exception — there IS no native quantized collective, so
    its explicit schedule is the lowering.

    ``algorithm`` (the offline tuner's pinned topo choice) bypasses cost
    selection: when the compositor offers that candidate at the bucket's
    payload it is used; a payload where the pin is unrealizable (e.g.
    split below its minimum size) falls back to cost selection — the
    same fallback the planner's own selection would make."""
    from ..common.types import dtype_from_array, dtype_size

    axes = _axes_tuple(axes if axes is not None else model.axes)

    def fn(x, *, op, axis_name=None, prescale_factor=1.0,
           postscale_factor=1.0):
        import jax.numpy as jnp

        use_axes = _axes_tuple(axis_name) if axis_name is not None else axes
        if prescale_factor != 1.0:
            x = x * prescale_factor
        nbytes = x.size * dtype_size(dtype_from_array(x))
        int8 = (
            quantized
            and op in (ReduceOp.SUM, ReduceOp.AVERAGE)
            and jnp.issubdtype(x.dtype, jnp.floating)
        )
        wire = WIRE_INT8 if int8 else WIRE_F32
        plan = None
        if algorithm:
            plan = candidate_plans(
                model, "allreduce", nbytes, op=op, wire_dtype=wire
            ).get(algorithm)
        if plan is None:
            plan = select_plan(
                model, "allreduce", nbytes, op=op, wire_dtype=wire
            )
        plan = record_plan(plan, where="stream")
        if int8:
            from ..ops.quantized import record_wire_bytes

            record_wire_bytes(nbytes, "stream")
        lower_algo = plan.algorithm
        frac = None
        if lower_algo == "split" and plan.nbytes:
            frac = plan.split_bytes[0] / plan.nbytes
        elif lower_algo in ("ring", "recursive-halving") or len(use_axes) == 1:
            # f32 single-hop labels lower natively; the int8 ring label
            # is handled by lower_allreduce's quantized branch.
            if not int8:
                lower_algo = "flat"
        out = lower_allreduce(
            x, use_axes, op=op, algorithm=lower_algo,
            split_fraction=frac, wire_dtype=wire,
        )
        if postscale_factor != 1.0:
            out = out * postscale_factor
        return out

    return fn
