"""Machine-readable interconnect model.

The reference hardcodes its hierarchy into backend op choices
(``NCCLHierarchicalAllreduce``: NCCL inside the node, MPI across); this
module makes the hierarchy *data*: an ordered list of :class:`Hop` entries
— outermost (slowest, DCN) first, innermost (fastest, ICI) last — each
carrying the mesh axis it rides, the rank count along it, and an
alpha-beta cost entry (per-hop latency + bandwidth). The collective
compositor (``topo/compositor.py``) lowers every collective into primitive
schedules over these hops and costs candidate algorithms against this
table, following HiCCL (PAPERS.md, arXiv:2408.05962): compose collectives
from multicast/reduce/fence primitives mapped onto an explicit
interconnect hierarchy.

Construction sources, in priority order:

1. ``HOROVOD_TOPOLOGY_MODEL`` — a JSON file path or inline JSON object.
   A full ``{"hops": [...]}`` document replaces the detected model;
   a ``{"<hop-name>": {"bandwidth_gbps": ...}}`` partial overrides cost
   entries on the detected hops (docs/topology.md has the schema).
2. The detected process topology (``common/topology.py``): LOCAL maps to
   one ICI hop, CROSS to one DCN hop. ``Topology.is_homogeneous`` is the
   "safe to go hierarchical" gate — a ragged or interleaved layout yields
   a flat (single-hop) model so no lowering ever puts a "local" stage on
   DCN.
3. Per-generation bandwidth/latency defaults (``GENERATION_DEFAULTS``) —
   deliberately coarse public numbers; they rank hops against each other
   (the only thing plan selection needs), they are not a benchmark.

Everything here is backend-free: building a model and selecting plans
never touches jax, so ``tools/topo_plan.py`` runs on any box.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from ..common import env as _env
from ..common.topology import Topology

# Canonical hop names (free-form in overrides, but the defaults and the
# mesh wiring use these).
ICI = "ici"
DCN = "dcn"
POD_DCN = "dcn-pod"

# Mesh axis each canonical hop rides (parallel/mesh.py axis names).
_HOP_AXES = {ICI: "local", DCN: "cross", POD_DCN: "pod"}

# Per-TPU-generation alpha-beta defaults: {hop: (bandwidth_gbps,
# latency_us)}. Bandwidths are coarse per-chip aggregates from public
# specs (ICI) and a per-chip share of a 200 Gbps host NIC (DCN); the
# inter-pod hop assumes WAN-ish DCN. Override any of them via
# HOROVOD_TOPOLOGY_MODEL — selection only needs the *ordering* and rough
# ratios to be right.
GENERATION_DEFAULTS: Dict[str, Dict[str, Tuple[float, float]]] = {
    "v3": {ICI: (70.0, 1.0), DCN: (12.5, 50.0), POD_DCN: (6.25, 200.0)},
    "v4": {ICI: (300.0, 1.0), DCN: (12.5, 50.0), POD_DCN: (6.25, 200.0)},
    "v5e": {ICI: (200.0, 1.0), DCN: (12.5, 50.0), POD_DCN: (6.25, 200.0)},
    "v5p": {ICI: (600.0, 1.0), DCN: (25.0, 50.0), POD_DCN: (6.25, 200.0)},
    "v6e": {ICI: (448.0, 1.0), DCN: (25.0, 50.0), POD_DCN: (6.25, 200.0)},
    # CPU test clusters / unknown hardware: keep the ICI >> DCN ordering
    # so plan *shapes* match what a real pod would select.
    "generic": {ICI: (50.0, 2.0), DCN: (5.0, 100.0), POD_DCN: (2.5, 400.0)},
}


@dataclass(frozen=True)
class Hop:
    """One interconnect level: ``size`` ranks reachable over this hop,
    riding mesh axis ``axis``, at ``bandwidth_gbps`` gigaBYTES/s per rank
    with ``latency_us`` per communication round."""

    name: str
    axis: str
    size: int
    bandwidth_gbps: float
    latency_us: float

    def __post_init__(self):
        if self.size < 1:
            raise ValueError(f"hop {self.name!r}: size must be >= 1")
        if self.bandwidth_gbps <= 0 or self.latency_us < 0:
            raise ValueError(
                f"hop {self.name!r}: bandwidth must be > 0 and latency >= 0"
            )

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "axis": self.axis,
            "size": self.size,
            "bandwidth_gbps": self.bandwidth_gbps,
            "latency_us": self.latency_us,
        }

    @staticmethod
    def from_dict(d: dict) -> "Hop":
        return Hop(
            name=str(d["name"]),
            axis=str(d.get("axis", d["name"])),
            size=int(d["size"]),
            bandwidth_gbps=float(d["bandwidth_gbps"]),
            latency_us=float(d["latency_us"]),
        )


@dataclass(frozen=True)
class InterconnectModel:
    """Ordered hop list, outermost (slowest) first. A single hop means a
    flat topology — the compositor then only considers single-level
    algorithms. ``eligible`` is the hierarchical-safety gate
    (``Topology.is_homogeneous`` + a genuine >1x>1 grid)."""

    hops: Tuple[Hop, ...]
    generation: str = "generic"
    eligible: bool = False
    source: str = "synthetic"

    def __post_init__(self):
        if not self.hops:
            raise ValueError("an interconnect model needs at least one hop")

    @property
    def size(self) -> int:
        n = 1
        for h in self.hops:
            n *= h.size
        return n

    @property
    def levels(self) -> int:
        return len(self.hops)

    @property
    def inner(self) -> Hop:
        return self.hops[-1]

    @property
    def axes(self) -> Tuple[str, ...]:
        """Mesh axis names, outermost first — the axis tuple the
        compositor lowerings take."""
        return tuple(h.axis for h in self.hops)

    def hop(self, name: str) -> Hop:
        for h in self.hops:
            if h.name == name:
                return h
        raise KeyError(f"no hop named {name!r} in {self.axes}")

    def to_dict(self) -> dict:
        return {
            "generation": self.generation,
            "eligible": self.eligible,
            "source": self.source,
            "size": self.size,
            "hops": [h.to_dict() for h in self.hops],
        }

    def to_json(self) -> str:
        """Stable serialization (sorted keys, no timestamps) — the CI
        smoke diffs two dumps byte-for-byte."""
        return json.dumps(self.to_dict(), sort_keys=True, indent=1)

    @staticmethod
    def from_dict(d: dict) -> "InterconnectModel":
        return InterconnectModel(
            hops=tuple(Hop.from_dict(h) for h in d["hops"]),
            generation=str(d.get("generation", "generic")),
            eligible=bool(d.get("eligible", len(d["hops"]) > 1)),
            source=str(d.get("source", "json")),
        )


def detect_generation() -> str:
    """TPU generation from the deployment env (TPU_ACCELERATOR_TYPE, e.g.
    "v5litepod-16"/"v4-32"), without touching a jax backend. Unknown or
    absent hardware maps to "generic"."""
    raw = (
        os.environ.get("TPU_ACCELERATOR_TYPE", "")
        or os.environ.get("TPU_TYPE", "")
    ).strip().lower()
    for gen in ("v6e", "v5p", "v5e", "v5lite", "v4", "v3"):
        if raw.startswith(gen):
            return "v5e" if gen == "v5lite" else gen
    return "generic"


def _costs(generation: str) -> Dict[str, Tuple[float, float]]:
    return GENERATION_DEFAULTS.get(generation, GENERATION_DEFAULTS["generic"])


def _mk_hop(name: str, size: int, generation: str,
            axis: Optional[str] = None) -> Hop:
    bw, lat = _costs(generation).get(
        name, _costs(generation).get(DCN, (5.0, 100.0))
    )
    return Hop(
        name=name, axis=axis or _HOP_AXES.get(name, name), size=size,
        bandwidth_gbps=bw, latency_us=lat,
    )


def synthetic_model(
    local: int,
    cross: int = 1,
    pod: int = 1,
    generation: str = "generic",
    eligible: Optional[bool] = None,
) -> InterconnectModel:
    """Hand-built model for tools and tests: (pod, cross, local) sizes
    with per-generation default costs. Degenerate (=1) outer levels are
    dropped, so ``synthetic_model(8)`` is a flat single-slice pod."""
    hops: List[Hop] = []
    if pod > 1:
        hops.append(_mk_hop(POD_DCN, pod, generation))
    if cross > 1:
        hops.append(_mk_hop(DCN, cross, generation))
    hops.append(_mk_hop(ICI, max(int(local), 1), generation))
    if eligible is None:
        eligible = len(hops) > 1
    return InterconnectModel(
        hops=tuple(hops), generation=generation, eligible=eligible,
        source="synthetic",
    )


def model_from_topology(
    topology: Topology, generation: Optional[str] = None
) -> InterconnectModel:
    """The detected-deployment model: LOCAL -> one ICI hop, CROSS -> one
    DCN hop. Non-homogeneous layouts (ragged or interleaved slices — see
    ``topology_from_slice_metadata``) and degenerate grids collapse to a
    flat ineligible model: the executor's (cross, local) mesh assumes the
    block rank layout, so "hierarchical" over a violated layout would
    silently put local stages on DCN."""
    generation = generation or detect_generation()
    grid = (
        topology.is_homogeneous
        and topology.local_size > 1
        and topology.cross_size > 1
        and topology.local_size * topology.cross_size == topology.size
    )
    if grid:
        return InterconnectModel(
            hops=(
                _mk_hop(DCN, topology.cross_size, generation),
                _mk_hop(ICI, topology.local_size, generation),
            ),
            generation=generation, eligible=True, source="topology",
        )
    return InterconnectModel(
        hops=(_mk_hop(ICI, max(topology.size, 1), generation),),
        generation=generation, eligible=False, source="topology",
    )


def model_from_mesh_shape(
    axis_sizes: Dict[str, int], generation: Optional[str] = None
) -> InterconnectModel:
    """Model for an explicitly-built hierarchical mesh ({axis: size} from
    ``Mesh.shape``): the caller constructed (pod, cross, local) axes on
    purpose, so eligibility follows from the axes existing — the
    homogeneity gate applies to *detected* process topologies, not to a
    deliberate mesh."""
    generation = generation or detect_generation()
    hops: List[Hop] = []
    pod = int(axis_sizes.get("pod", 1))
    cross = int(axis_sizes.get("cross", 1))
    local = int(axis_sizes.get("local", 1))
    if pod > 1:
        hops.append(_mk_hop(POD_DCN, pod, generation))
    if cross > 1:
        hops.append(_mk_hop(DCN, cross, generation))
    hops.append(_mk_hop(ICI, local, generation))
    return InterconnectModel(
        hops=tuple(hops), generation=generation,
        eligible=len(hops) > 1 and local > 1, source="mesh",
    )


def _load_override() -> Optional[dict]:
    raw = os.environ.get(_env.HOROVOD_TOPOLOGY_MODEL, "").strip()
    if not raw:
        return None
    if raw.startswith("{"):
        return json.loads(raw)
    with open(raw) as f:
        return json.load(f)


def apply_override(model: InterconnectModel) -> InterconnectModel:
    """Apply the HOROVOD_TOPOLOGY_MODEL knob: a document with a "hops"
    list replaces the model wholesale; otherwise each top-level key names
    a hop and its dict patches that hop's cost fields (unknown hop names
    raise — a typo'd override silently doing nothing is worse)."""
    doc = _load_override()
    if doc is None:
        return model
    if "hops" in doc:
        return InterconnectModel.from_dict(doc)
    names = {h.name for h in model.hops}
    patched = []
    unknown = [k for k in doc if k not in names]
    if unknown:
        raise ValueError(
            f"{_env.HOROVOD_TOPOLOGY_MODEL} overrides unknown hop(s) "
            f"{unknown}; this model has {sorted(names)}"
        )
    for h in model.hops:
        patch = doc.get(h.name, {})
        patched.append(Hop(
            name=h.name,
            axis=str(patch.get("axis", h.axis)),
            size=int(patch.get("size", h.size)),
            bandwidth_gbps=float(patch.get("bandwidth_gbps",
                                           h.bandwidth_gbps)),
            latency_us=float(patch.get("latency_us", h.latency_us)),
        ))
    return InterconnectModel(
        hops=tuple(patched), generation=model.generation,
        eligible=model.eligible, source=model.source + "+override",
    )


def resolve_model(topology: Optional[Topology] = None) -> InterconnectModel:
    """The model the runtime uses: detected topology (or the given one)
    with the env override applied."""
    if topology is None:
        from ..common import topology as _topo_mod

        topology = _topo_mod.detect()
    return apply_override(model_from_topology(topology))
