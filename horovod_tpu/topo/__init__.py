"""Topology-aware collective compositor (docs/topology.md).

``topo.model`` — the machine-readable interconnect hierarchy (hops with
per-generation bandwidth/latency defaults, ``HOROVOD_TOPOLOGY_MODEL``
override, homogeneity-gated eligibility).

``topo.compositor`` — hierarchical lowering plans for every collective
(allreduce / allgather / reduce-scatter / broadcast / alltoall), an
analytic cost model selecting ring vs. recursive-halving vs. two-level
vs. FlexLink-style split per (topology, payload bytes, op), and the
``shard_map`` lowerings that execute the selected plan.

Planning is backend-free (``tools/topo_plan.py`` dumps plans with no
accelerator); lowering runs inside jitted traces.
"""

from .model import (  # noqa: F401
    GENERATION_DEFAULTS,
    Hop,
    InterconnectModel,
    apply_override,
    detect_generation,
    model_from_mesh_shape,
    model_from_topology,
    resolve_model,
    synthetic_model,
)
from .compositor import (  # noqa: F401
    COLLECTIVES,
    Plan,
    Stage,
    auto_reduce_fn,
    candidate_plans,
    model_for_axes,
    lower_allgather,
    lower_allreduce,
    lower_alltoall,
    lower_broadcast,
    lower_reducescatter,
    perm_rounds,
    planned_reduce_fn,
    record_plan,
    select_plan,
    split_fractions,
    stage_kind,
)
