"""horovod_tpu — a TPU-native distributed training framework with
Horovod-capability parity.

Public API parity with ``horovod/common/basics.py`` + framework modules:
``init/shutdown/size/rank/local_rank/local_size``, eager
``allreduce/allgather/broadcast`` (sync and ``_async`` handle-based variants),
``join``, ``Compression``, ``Average/Sum/Adasum`` reduce ops — plus the
TPU-native compiled mode under :mod:`horovod_tpu.jax` (fusion-bucketed psum
inside pjit/shard_map) which is the performance path.

The data plane is XLA: collectives lower to ``jax.lax.psum`` /
``all_gather`` / ``ppermute`` over ICI (intra-slice) and DCN (inter-slice)
instead of NCCL/MPI/Gloo (see SURVEY.md §5 "Distributed communication
backend").
"""

from __future__ import annotations

import atexit
import threading
from typing import Any, Optional

from .common import topology as _topology_mod
from .common.compression import Compression
from .common.env import Config
from .common.types import (
    Adasum,
    Average,
    Max,
    Min,
    Product,
    ReduceOp,
    Status,
    Sum,
)
from .core.runtime import Runtime

__version__ = "0.1.0"

_lock = threading.Lock()
_runtime: Optional[Runtime] = None
_mesh = None


class HorovodInternalError(RuntimeError):
    pass


def init(config: Optional[Config] = None) -> None:
    """Initialize the runtime (reference ``hvd.init()``,
    ``horovod/common/basics.py:33-65``): detect topology, start the
    background loop, and stand up the data plane."""
    global _runtime
    import os as _os_mod

    if _os_mod.environ.get("HOROVOD_ELASTIC_SPARE") == "1":
        # Hot-spare gate (docs/fault_tolerance.md "Self-driving
        # fleet"): a spare worker parks HERE — before any backend or
        # topology detection — until a published world generation
        # claims its slot; promotion applies the assignment env and
        # falls through into a normal init. Deliberately outside the
        # lock: the wait can last the whole job.
        from .elastic import maybe_wait_as_spare

        maybe_wait_as_spare()
    with _lock:
        if _runtime is not None and _runtime.running:
            return
        cfg = config or Config.from_env()
        # XLA perf-flag preset (docs/overlap.md): must land in XLA_FLAGS
        # before the first backend touch below (jax.distributed /
        # jax.devices); idempotent if horovod_tpu.jax already applied it.
        from .common import env as _env_mod

        try:
            _env_mod.apply_xla_perf_preset(cfg.xla_perf_preset)
        except ValueError:
            raise
        except Exception:  # noqa: BLE001 - never block init on flag plumbing
            pass
        topo = _topology_mod.detect()
        import os as _os

        kind = _os.environ.get("HOROVOD_TPU_CORE", "native").lower()
        executor = None
        coord_addr = ""
        coord_port = 0
        if topo.size > 1:
            coord_addr = _os.environ.get("HOROVOD_CONTROLLER_ADDR", "")
            coord_port = int(_os.environ.get("HOROVOD_CONTROLLER_PORT", "0"))
            jax_coord = _os.environ.get("HOROVOD_JAX_COORDINATOR", "")
            if not coord_addr or not coord_port:
                raise HorovodInternalError(
                    f"size={topo.size} but HOROVOD_CONTROLLER_ADDR/PORT are "
                    "not set — launch multi-rank jobs with hvdrun "
                    "(python -m horovod_tpu.run)."
                )
            import jax as _jax

            if _os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
                # Multi-process CPU runs (test clusters, the launcher's
                # -np N mode) need the gloo cross-process collective
                # backend; without it every collective fails with
                # "Multiprocess computations aren't implemented on the
                # CPU backend".
                try:
                    _jax.config.update(
                        "jax_cpu_collectives_implementation", "gloo"
                    )
                except Exception:  # noqa: BLE001 - newer jax: on by default
                    pass

            if jax_coord:
                # Must run before any backend use; tolerate re-init.
                from .elastic import rejoin_mode as _rejoin_mode

                if (_os.environ.get("HOROVOD_ELASTIC") == "1"
                        and _rejoin_mode() == "inprocess"):
                    # Elastic worlds need failure-tolerant coordination: a
                    # dead peer must surface as a catchable collective
                    # error on survivors, not a fatal coordination-service
                    # abort — rollback re-forms the world in process
                    # (horovod_tpu/elastic). In 'respawn' mode (the
                    # fallback when these private surfaces are absent)
                    # workers die and resume from persisted commits, so
                    # the plain public initialize below is used instead.
                    _jax.config.update("jax_enable_recoverability", True)
                    from .elastic import _jax_distributed_initialize

                    def _dist_init():
                        _jax_distributed_initialize(
                            jax_coord, topo.size, topo.rank
                        )
                else:
                    def _dist_init():
                        _jax.distributed.initialize(
                            jax_coord, num_processes=topo.size,
                            process_id=topo.rank,
                        )
                try:
                    _dist_init()
                except RuntimeError as exc:
                    msg = str(exc).lower()
                    if "already" in msg:
                        pass
                    elif (_os.environ.get("HOROVOD_ELASTIC") == "1"
                          and _rejoin_mode() == "respawn"
                          and any(k in msg for k in (
                              "bind", "address already in use",
                              "address in use", "errno 98",
                              "failed to listen"))):
                        # The coordinator port was probed on the driver
                        # host (or a remote probe fell back) and lost the
                        # bind race here. Not this host's fault: exit
                        # with the respawn status so the driver re-forms
                        # the world with FRESH ports and records no
                        # blacklist strike, instead of burning one of the
                        # host's failure credits per collision.
                        import logging as _logging

                        _logging.getLogger("horovod_tpu").error(
                            "jax coordination endpoint could not bind "
                            "(%s); exiting for a respawn with fresh "
                            "ports", exc,
                        )
                        from .elastic import REJOIN_EXIT_CODE

                        _os._exit(REJOIN_EXIT_CODE)
                    else:
                        raise
            from .core.xla_executor import XlaPlanExecutor

            executor = XlaPlanExecutor(topo, config=cfg)
        if kind == "native":
            try:
                from .core.native_runtime import NativeRuntime

                _runtime = NativeRuntime(
                    cfg, topo, executor=executor,
                    coord_addr=coord_addr, coord_port=coord_port,
                )
                _start_profiler(cfg)
                _start_metrics_pusher(topo)
                _start_trace_pusher(topo)
                return
            except NotImplementedError:
                raise
            except Exception as exc:  # noqa: BLE001 - build/load failure
                import logging

                logging.getLogger("horovod_tpu").warning(
                    "native core unavailable (%s); using the pure-Python "
                    "runtime",
                    exc,
                )
        _runtime = Runtime(cfg, topo)
        _runtime.start()
        _start_profiler(cfg)
        _start_metrics_pusher(topo)
        _start_trace_pusher(topo)


def _start_profiler(cfg: Config) -> None:
    """Optional jax.profiler session (HOROVOD_PROFILER_DIR): plan
    executions carry the same hvd_plan_<id> TraceAnnotation the C++
    timeline stamps on the plan's catapult events, linking a slow cycle
    to its on-chip XLA profile (SURVEY §5 timeline parity)."""
    global _profiler_active
    if not getattr(cfg, "profiler_dir", ""):
        return
    try:
        import jax.profiler as _prof

        _prof.start_trace(cfg.profiler_dir)
        _profiler_active = True
    except Exception as exc:  # noqa: BLE001 - profiling is best-effort
        import logging

        logging.getLogger("horovod_tpu").warning(
            "could not start jax.profiler trace in %s: %s",
            cfg.profiler_dir, exc,
        )


_profiler_active = False
_metrics_pusher = None
_trace_pusher = None


def _start_trace_pusher(topo) -> None:
    """Worker-side fleet-trace publisher (docs/timeline.md "Fleet
    tracing"): with HOROVOD_TRACE set and an elastic KV rendezvous in
    the environment, estimate the clock offset against the driver (KV
    ping RTT/2, recorded as trace metadata) and push this rank's span
    window so the driver can merge the fleet. No-op otherwise."""
    global _trace_pusher
    from . import trace as _trace_mod

    if not _trace_mod.ACTIVE:
        return
    # The tap armed at import, possibly before this generation's rank
    # assignment landed in the env — adopt the live rank (an in-process
    # rejoin re-enters here after shutdown() stopped the old pusher).
    _trace_mod.TAP.rank = topo.rank
    if _trace_pusher is not None:
        return
    import os as _os

    addr = _os.environ.get("HOROVOD_ELASTIC_KV_ADDR", "")
    port = _os.environ.get("HOROVOD_ELASTIC_KV_PORT", "")
    if not addr or not port:
        return
    from .trace.pusher import TracePusher

    try:
        _trace_pusher = TracePusher(addr, int(port), topo.rank)
    except Exception as exc:  # noqa: BLE001 - tracing never blocks init
        import logging

        logging.getLogger("horovod_tpu").warning(
            "could not start the trace pusher: %s", exc
        )


def _start_metrics_pusher(topo) -> None:
    """Worker-side metrics publisher (docs/metrics.md): with
    HOROVOD_METRICS set and an elastic KV rendezvous in the environment,
    push this process's registry snapshot to the driver so its
    ``GET /metrics`` aggregates every rank. No-op otherwise — the
    in-process ``hvd.metrics()`` API needs no plumbing."""
    global _metrics_pusher
    from . import metrics as _metrics_mod

    if not _metrics_mod.ACTIVE or _metrics_pusher is not None:
        return
    import os as _os

    addr = _os.environ.get("HOROVOD_ELASTIC_KV_ADDR", "")
    port = _os.environ.get("HOROVOD_ELASTIC_KV_PORT", "")
    if not addr or not port:
        return
    from .metrics.export import MetricsPusher

    try:
        _metrics_pusher = MetricsPusher(addr, int(port), topo.rank)
    except Exception as exc:  # noqa: BLE001 - metrics never block init
        import logging

        logging.getLogger("horovod_tpu").warning(
            "could not start the metrics pusher: %s", exc
        )


def metrics_snapshot() -> dict:
    """Structured snapshot of this process's metrics registry — plain
    dicts/lists/numbers only (counters, gauges, and fixed-bucket
    histograms; see docs/metrics.md). Empty when ``HOROVOD_METRICS`` is
    unset. ``hvd.metrics()`` returns the flattened one-value-per-series
    view of the same data."""
    from . import metrics as _metrics_mod

    return _metrics_mod.snapshot()


def shutdown() -> None:
    global _runtime, _mesh, _profiler_active, _ps_barrier_seq
    global _metrics_pusher, _trace_pusher
    with _lock:
        if _runtime is not None:
            _runtime.shutdown()
            _runtime = None
        if _trace_pusher is not None:
            # Stopped AFTER the runtime so the final window carries the
            # teardown-time spans (same ordering as the metrics pusher).
            try:
                _trace_pusher.stop()
            except Exception:  # noqa: BLE001
                pass
            _trace_pusher = None
        if _metrics_pusher is not None:
            # Stopped AFTER the runtime so the final push carries the
            # teardown-time counter values.
            try:
                _metrics_pusher.stop()
            except Exception:  # noqa: BLE001
                pass
            _metrics_pusher = None
        _mesh = None
        # Process sets die with the runtime (a re-init starts clean, and
        # id assignment restarts so all ranks stay aligned).
        for ps in _process_sets.values():
            ps.process_set_id = None
        _process_sets.clear()
        _ps_barrier_seq = 0
        if _profiler_active:
            _profiler_active = False
            try:
                import jax.profiler as _prof

                _prof.stop_trace()
            except Exception:  # noqa: BLE001
                pass


def is_initialized() -> bool:
    return _runtime is not None and _runtime.running


def _rt() -> Runtime:
    if _runtime is None or not _runtime.running:
        raise HorovodInternalError(
            "Horovod has not been initialized; use hvd.init()."
        )
    return _runtime


atexit.register(shutdown)


# --- topology accessors (basics.py parity) ---
def size() -> int:
    return _rt().topology.size


def rank() -> int:
    return _rt().topology.rank


def local_rank() -> int:
    return _rt().topology.local_rank


def local_size() -> int:
    return _rt().topology.local_size


def cross_rank() -> int:
    return _rt().topology.cross_rank


def cross_size() -> int:
    return _rt().topology.cross_size


def is_homogeneous() -> bool:
    return _rt().topology.is_homogeneous


def collective_plan(
    collective: str = "allreduce",
    nbytes: int = 4 * 1024 * 1024,
    op: Optional[ReduceOp] = None,
    wire_dtype: str = "f32",
) -> dict:
    """The topology compositor's selected lowering plan for one
    collective at one payload size on THIS deployment's interconnect
    model (docs/topology.md): algorithm (flat / ring / recursive-halving
    / two-level / split), per-hop bytes-on-wire, per-stage schedule, and
    the analytic cost estimate. ``wire_dtype="int8"`` prices the
    quantized wire (allreduce SUM/AVERAGE only): int8+scales bytes on
    the compressed hop(s), full precision elsewhere. Uses the
    initialized runtime's topology when available, else fresh detection;
    honors the ``HOROVOD_TOPOLOGY_MODEL`` override. Pure cost-model
    output — no backend is touched, so this also works pre-init (the
    offline twin is ``tools/topo_plan.py``)."""
    from .topo import resolve_model, select_plan

    topo = (
        _runtime.topology if _runtime is not None
        else _topology_mod.detect()
    )
    model = resolve_model(topo)
    plan = select_plan(
        model, collective, int(nbytes),
        op=op if op is not None else ReduceOp.SUM,
        wire_dtype=wire_dtype,
    )
    out = plan.to_dict()
    out["model"] = model.to_dict()
    return out


# Build-capability probes (reference horovod_*_built/enabled,
# operations.cc:683-769). MPI/Gloo/NCCL/DDL/MLSL do not exist in the TPU
# build; XLA is the sole data plane.
def mpi_threads_supported() -> bool:
    return False


def mpi_built() -> bool:
    return False


def mpi_enabled() -> bool:
    return False


def gloo_built() -> bool:
    return False


def gloo_enabled() -> bool:
    return False


def nccl_built() -> bool:
    return False


def ddl_built() -> bool:
    return False


def mlsl_built() -> bool:
    return False


def xla_built() -> bool:
    return True


def xla_enabled() -> bool:
    return True


def mesh():
    """The global device mesh (lazily built; single ``data`` axis over all
    devices by default, or per ``HOROVOD_TPU_MESH_AXES``)."""
    global _mesh
    with _lock:
        if _mesh is None:
            from .parallel import mesh as mesh_mod

            cfg = _rt().config
            _mesh = mesh_mod.build_mesh(mesh_mod.parse_axes(cfg.mesh_axes) or None)
        return _mesh


# --- naming helper ---
_name_counters: dict = {}


def _auto_name(prefix: str, name: Optional[str]) -> str:
    if name is not None:
        return name
    with _lock:
        n = _name_counters.get(prefix, 0)
        _name_counters[prefix] = n + 1
    return f"{prefix}.noname.{n}"


def _preflight_record(op: str, name: str, psid: int, tensor: Any) -> None:
    """Opt-in submission-ledger hook (HOROVOD_TPU_STATIC_CHECKS=1): feeds
    the cross-rank ordering lint (analysis/ordering.py). No-op — a single
    cached env read — when the knob is off."""
    from .analysis import preflight

    if preflight.enabled():
        preflight.record_submission(op, name, psid, tensor)


def _resolve_op(average: Optional[bool], op: Optional[ReduceOp]) -> ReduceOp:
    # Reference horovod/torch/mpi_ops.py:101-124: `average` and `op` are
    # mutually exclusive; default Average.
    if average is not None and op is not None:
        raise ValueError('The op parameter supersedes average; provide only one.')
    if op is not None:
        return op
    if average is False:
        return ReduceOp.SUM
    return ReduceOp.AVERAGE


# --- process sets (later-reference horovod.ProcessSet parity) ---
class ProcessSet:
    """A subset of ranks that collectives can run over (the later
    reference's ``horovod.ProcessSet``). TPU-native design: a registered
    set becomes a sub-``Mesh`` over the member ranks' devices — only
    member processes execute the compiled collective (multi-controller
    JAX semantics), which is exactly the reference's per-set communicator
    without a NCCL/MPI comm split.

    Construct with a list of global ranks and register with
    :func:`add_process_set` (which must be called identically on every
    rank); ``hvd.global_process_set`` is the implicit all-ranks set."""

    def __init__(self, ranks=None):
        # None = the global set (all ranks, resolved at use time).
        self.ranks = (
            sorted({int(r) for r in ranks}) if ranks is not None else None
        )
        self.process_set_id: Optional[int] = None

    def _resolved_ranks(self) -> list:
        return self.ranks if self.ranks is not None else list(range(size()))

    def size(self) -> int:
        return len(self._resolved_ranks())

    def included(self) -> bool:
        return rank() in self._resolved_ranks()

    def rank(self) -> int:
        """This process's position within the set (set-local rank)."""
        rs = self._resolved_ranks()
        me = rank()
        if me not in rs:
            raise RuntimeError(
                f"rank {me} is not a member of process set "
                f"{self.process_set_id}"
            )
        return rs.index(me)

    def __repr__(self):
        return (f"ProcessSet(id={self.process_set_id}, "
                f"ranks={'GLOBAL' if self.ranks is None else self.ranks})")


global_process_set = ProcessSet(None)
global_process_set.process_set_id = 0

_process_sets: dict = {}
# Per-call barrier sequence, shared by add_process_set AND
# remove_process_set: the k-th registration call uses barrier name k and
# (for adds) set id k on EVERY rank — even ranks whose local validation
# failed, and even when one rank is adding while another removes — so any
# divergent call completes the allgather and fails loudly on all ranks
# instead of stranding the healthy ones inside it, and a failed call can
# never desynchronize id assignment (all ranks consumed the same value).
_ps_barrier_seq = 0


def _ps_barrier(payload, seq: int, n: int) -> list:
    """Cross-rank agreement exchange for process-set registration calls.
    ONE name per sequence number regardless of call type — an add on one
    rank racing a remove on another meets in the same allgather and the
    payload mismatch raises everywhere."""
    if n <= 1:
        return [payload]
    return allgather_object(payload, name=f"hvd.ps.bar.{seq}")


def _psid(process_set: Optional[ProcessSet]) -> int:
    if process_set is None or process_set.process_set_id == 0:
        return 0
    if process_set.process_set_id is None:
        raise ValueError(
            "process set must be registered with hvd.add_process_set() "
            "before use"
        )
    return int(process_set.process_set_id)


def add_process_set(process_set) -> ProcessSet:
    """Register a process set (a ``ProcessSet`` or a list of ranks).
    MUST be called identically, in the same order, on every rank — the
    registration performs a cross-rank agreement barrier so a divergent
    call (wrong ranks on one rank, different membership across ranks)
    fails loudly on EVERY rank instead of deadlocking the first
    collective: local validation failures enter the barrier too and
    poison it."""
    global _ps_barrier_seq
    ps = (process_set if isinstance(process_set, ProcessSet)
          else ProcessSet(process_set))
    rt = _rt()
    n = rt.topology.size
    with _lock:
        _ps_barrier_seq += 1
        seq = _ps_barrier_seq
    # Validate into an error payload rather than raising before the
    # barrier — a pre-barrier raise would strand every healthy peer
    # inside the agreement allgather.
    err = None
    if ps.ranks is None:
        err = "the global process set is registered implicitly"
    elif ps.process_set_id is not None:
        err = f"process set is already registered (id {ps.process_set_id})"
    elif not ps.ranks or ps.ranks[0] < 0 or ps.ranks[-1] >= n:
        err = f"process set ranks must lie in [0, {n})"
    psid = None
    if err is None:
        reg = getattr(rt, "register_process_set", None)
        if reg is None:
            err = "the active runtime does not support process sets"
        else:
            # The set id IS the barrier sequence number: consumed
            # identically on every rank by every registration call,
            # successful or not, so a failed call can never skew later
            # id assignment across ranks.
            psid = seq
            try:
                # Register BEFORE the barrier: a member may use the set
                # the moment its own barrier returns, which implies every
                # rank (the coordinator included) contributed — and hence
                # registered — already.
                reg(psid, ps.ranks)
            except Exception as exc:  # noqa: BLE001 - poisons the barrier
                err = str(exc)
                psid = None
    payload = (("add", psid, tuple(ps.ranks or ()))
               if err is None else ("err", err))
    agreement = _ps_barrier(payload, seq, n)
    unanimous = len(set(agreement)) == 1 and agreement[0][0] == "add"
    if err is not None or not unanimous:
        if psid is not None:
            try:
                rt.remove_process_set(psid)
            except Exception:  # noqa: BLE001 - best-effort rollback
                pass
        if err is not None:
            raise ValueError(err)
        raise ValueError(
            "add_process_set must be called identically on every rank; "
            f"cross-rank registrations: {agreement}"
        )
    with _lock:
        ps.process_set_id = psid
        _process_sets[psid] = ps
    return ps


def remove_process_set(process_set: ProcessSet) -> None:
    """Deregister a dynamic process set. Collective: call identically on
    every rank (barrier first, so no member removes the set while a peer
    still has ops in flight; a divergent call fails on all ranks)."""
    global _ps_barrier_seq
    rt = _rt()
    n = rt.topology.size
    with _lock:
        _ps_barrier_seq += 1
        seq = _ps_barrier_seq
    psid = process_set.process_set_id
    err = (
        "only registered non-global process sets can be removed"
        if psid in (None, 0) else None
    )
    payload = ("rm", psid) if err is None else ("err", err)
    agreement = _ps_barrier(payload, seq, n)
    if err is not None:
        raise ValueError(err)
    if any(a != ("rm", psid) for a in agreement):
        raise ValueError(
            "remove_process_set must be called identically on every "
            f"rank; cross-rank calls: {agreement}"
        )
    rt.remove_process_set(psid)
    with _lock:
        _process_sets.pop(psid, None)
        process_set.process_set_id = None


# --- eager collective API ---
def allreduce_async(
    tensor: Any,
    average: Optional[bool] = None,
    name: Optional[str] = None,
    op: Optional[ReduceOp] = None,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
    process_set: Optional[ProcessSet] = None,
    _group: tuple = (0, 0),
) -> int:
    rop = _resolve_op(average, op)
    rt = _rt()
    tensor_name = _auto_name("allreduce", name)
    psid = _psid(process_set)
    _preflight_record("allreduce", tensor_name, psid, tensor)
    if rop == ReduceOp.ADASUM:
        return rt.enqueue_adasum(
            tensor_name,
            tensor,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor,
            group_id=_group[0], group_size=_group[1],
            process_set_id=psid,
        )
    return rt.enqueue_allreduce(
        tensor_name,
        tensor,
        reduce_op=rop,
        prescale_factor=prescale_factor,
        postscale_factor=postscale_factor,
        group_id=_group[0], group_size=_group[1],
        process_set_id=psid,
    )


def allreduce(
    tensor: Any,
    average: Optional[bool] = None,
    name: Optional[str] = None,
    compression=Compression.none,
    op: Optional[ReduceOp] = None,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
    process_set: Optional[ProcessSet] = None,
) -> Any:
    tensor_compressed, ctx = compression.compress(tensor)
    handle = allreduce_async(
        tensor_compressed,
        average=average,
        name=name,
        op=op,
        prescale_factor=prescale_factor,
        postscale_factor=postscale_factor,
        process_set=process_set,
    )
    out = synchronize(handle)
    return compression.decompress(out, ctx)


def allgather_async(tensor: Any, name: Optional[str] = None,
                    process_set: Optional[ProcessSet] = None,
                    _group: tuple = (0, 0)) -> int:
    tensor_name = _auto_name("allgather", name)
    psid = _psid(process_set)
    _preflight_record("allgather", tensor_name, psid, tensor)
    return _rt().enqueue_allgather(
        tensor_name, tensor,
        process_set_id=psid,
        group_id=_group[0], group_size=_group[1],
    )


def allgather(tensor: Any, name: Optional[str] = None,
              process_set: Optional[ProcessSet] = None) -> Any:
    return synchronize(allgather_async(tensor, name, process_set))


def allgather_object(obj, name: Optional[str] = None,
                     process_set: Optional[ProcessSet] = None) -> list:
    """Gather one picklable object per (member) rank; every member gets
    the member-ordered list (later-reference API). Rides the uneven
    (Allgatherv-parity) dim0 allgather, so payload sizes may differ."""
    import pickle

    import numpy as np

    data = np.frombuffer(pickle.dumps(obj), dtype=np.uint8).copy()
    base = name or _auto_name("gather_obj", None)
    sizes = np.asarray(allgather(
        np.array([len(data)], dtype=np.int64), name=f"{base}.size",
        process_set=process_set,
    ))
    payload = np.asarray(allgather(
        data, name=f"{base}.data", process_set=process_set,
    ))
    out, off = [], 0
    for count in sizes.tolist():
        out.append(pickle.loads(payload[off:off + count].tobytes()))
        off += count
    return out


def broadcast_async(
    tensor: Any, root_rank: int, name: Optional[str] = None,
    process_set: Optional[ProcessSet] = None,
) -> int:
    # root_rank is a GLOBAL rank even within a process set (reference
    # process-set API semantics; the executor maps it to the member
    # position on the sub-mesh).
    tensor_name = _auto_name("broadcast", name)
    psid = _psid(process_set)
    _preflight_record("broadcast", tensor_name, psid, tensor)
    return _rt().enqueue_broadcast(
        tensor_name, tensor, root_rank,
        process_set_id=psid,
    )


def broadcast(tensor: Any, root_rank: int, name: Optional[str] = None,
              process_set: Optional[ProcessSet] = None) -> Any:
    return synchronize(broadcast_async(tensor, root_rank, name, process_set))


def alltoall_async(tensor: Any, name: Optional[str] = None,
                   process_set: Optional[ProcessSet] = None) -> int:
    tensor_name = _auto_name("alltoall", name)
    psid = _psid(process_set)
    _preflight_record("alltoall", tensor_name, psid, tensor)
    return _rt().enqueue_alltoall(
        tensor_name, tensor,
        process_set_id=psid,
    )


def alltoall(tensor: Any, splits: Any = None, name: Optional[str] = None,
             process_set: Optional[ProcessSet] = None) -> Any:
    """All-to-all scatter of dim0 blocks. Without ``splits``, dim0 must
    divide evenly by the set size and rank r receives block r from every
    rank. With ``splits`` (length ``size``, summing to dim0 — the later
    reference's alltoallv API, ``horovod.alltoall(tensor, splits)``),
    rank d receives the ``splits[d]``-row segment from every rank and the
    call returns ``(collected, received_splits)``.

    Uneven mechanics (MPI alltoallv re-expressed on the even TPU
    collective): a tiny allgather shares every rank's splits vector, each
    per-destination segment pads to a common block, an even
    ``lax.all_to_all`` moves the blocks, and the pads are sliced off —
    the same count-exchange + v-call shape MPI implementations use.

    Memory bound under skew: padding every block to the global max would
    allocate ``O(n * max_split)`` rows on EVERY rank — one hot
    destination (an EP router's overloaded expert) would blow the
    carrier up n-fold. Instead the exchange is chunked: the carrier is
    capped at ``k * total_rows / n`` rows (``k`` =
    ``HOROVOD_ALLTOALLV_CARRIER_FACTOR``, default 4; floor ``n`` rows)
    and hot blocks ride multiple rounds. Peak extra memory is
    ``O(max(n, k * total_rows / n))`` rows regardless of skew; balanced
    splits stay single-round (``k x mean >= max``), identical to the
    unchunked path. Rounds are derived from the globally-agreed count
    matrix, so every rank executes the same schedule."""
    if splits is None:
        return synchronize(alltoall_async(tensor, name, process_set))
    import numpy as np

    name = _auto_name("alltoall", name)
    rt = _rt()
    if process_set is not None and process_set.ranks is not None:
        n = process_set.size()
        me = process_set.rank()
    else:
        n = rt.topology.size
        me = rt.topology.rank
    splits = np.asarray(splits, np.int32).reshape(-1)
    local = np.asarray(tensor)
    if splits.shape[0] != n:
        raise ValueError(
            f"splits must have one entry per rank ({n}), got "
            f"{splits.shape[0]}"
        )
    if (splits < 0).any():
        raise ValueError(f"splits must be non-negative, got {splits.tolist()}")
    if int(splits.sum()) != int(local.shape[0]):
        raise ValueError(
            f"splits sum ({int(splits.sum())}) must equal dim0 "
            f"({int(local.shape[0])})"
        )
    # Count exchange: matrix[src, dst] = rows src sends to dst.
    matrix = np.asarray(
        allgather(splits, name=f"{name}.splits", process_set=process_set)
    ).reshape(n, n)
    received_splits = matrix[:, me].copy()
    max_block = int(matrix.max())
    if max_block == 0:
        empty = local[:0]
        return empty, received_splits
    chunk, rounds = _alltoallv_schedule(matrix, n)
    alltoall._last_carrier_rows = n * chunk  # test/diagnostic hook
    rest = local.shape[1:]
    offs = np.concatenate([[0], np.cumsum(splits)[:-1]])
    pieces: list = [[] for _ in range(n)]
    for r in range(rounds):
        lo = r * chunk
        padded = np.zeros((n * chunk,) + rest, local.dtype)
        for d in range(n):
            take = min(max(int(splits[d]) - lo, 0), chunk)
            if take:
                padded[d * chunk: d * chunk + take] = (
                    local[offs[d] + lo: offs[d] + lo + take]
                )
        round_name = f"{name}.round{r}" if rounds > 1 else name
        out = np.asarray(
            synchronize(alltoall_async(padded, round_name, process_set))
        )
        for s in range(n):
            take = min(max(int(received_splits[s]) - lo, 0), chunk)
            if take:
                pieces[s].append(out[s * chunk: s * chunk + take])
    collected = np.concatenate(
        [c for p in pieces for c in p]
    ) if received_splits.sum() else local[:0]
    return collected, received_splits


def _alltoallv_schedule(matrix: Any, n: int) -> tuple:
    """(chunk_rows, rounds) for the chunked uneven alltoall: carrier
    capped at ``factor * total_rows / n`` rows (floor ``n``) so a skewed
    split cannot allocate ``n * max_split`` on every rank."""
    import os

    import numpy as np

    m = np.asarray(matrix)
    max_block = int(m.max())
    factor = int(os.environ.get("HOROVOD_ALLTOALLV_CARRIER_FACTOR", "4"))
    cap = max(1, (factor * int(m.sum()) + n * n - 1) // (n * n))
    chunk = min(max_block, cap)
    rounds = (max_block + chunk - 1) // chunk
    return chunk, rounds


def reducescatter_async(
    tensor: Any, name: Optional[str] = None, op: Optional[ReduceOp] = None,
    process_set: Optional[ProcessSet] = None,
    _group: tuple = (0, 0),
) -> int:
    """Sum/average across ranks, scatter dim0 shards: rank r receives its
    dim0 shard of the reduction — ``d//size`` rows each when ``size``
    divides ``d``, and Allgatherv-parity uneven splits otherwise (rank r
    gets ``d//size + (1 if r < d%size else 0)`` rows, earlier ranks
    absorbing the remainder — the MPI_Reduce_scatter convention the
    later reference adopted). TPU-native extension (single
    ``lax.psum_scatter`` on the ICI ring, uneven dim0 via a static
    pad-gather sliced off after the collective); the reference op set
    stops at broadcast (``message.h:48-50``)."""
    op = op if op is not None else ReduceOp.SUM
    # Validate here, not only in the multi-rank executor, so a size-1 dev
    # run rejects exactly what a production job would.
    if op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        raise ValueError("reducescatter supports SUM/AVERAGE only")
    if not getattr(tensor, "shape", ()):
        raise ValueError("reducescatter needs a tensor with a dim0 to scatter")
    tensor_name = _auto_name("reducescatter", name)
    psid = _psid(process_set)
    _preflight_record("reducescatter", tensor_name, psid, tensor)
    return _rt().enqueue_reducescatter(
        tensor_name, tensor, reduce_op=op,
        process_set_id=psid,
        group_id=_group[0], group_size=_group[1],
    )


def reducescatter(
    tensor: Any, name: Optional[str] = None, op: Optional[ReduceOp] = None,
    process_set: Optional[ProcessSet] = None,
) -> Any:
    return synchronize(reducescatter_async(tensor, name, op, process_set))


def grouped_allreduce_async(
    tensors, average: Optional[bool] = None, name: Optional[str] = None,
    op: Optional[ReduceOp] = None,
    prescale_factor: float = 1.0, postscale_factor: float = 1.0,
    process_set: Optional[ProcessSet] = None,
):
    """Enqueue a list of tensors as ONE first-class group and return
    their handles. The group travels with the requests (a stable id +
    member count), and the coordinator holds members until every one is
    ready on every rank, then fuses them into a single collective
    regardless of cycle boundaries or the fusion threshold — the
    semantics of the later reference's grouped API, not best-effort
    cycle fusion. Members with heterogeneous dtypes/signatures execute
    as one plan per signature (observable via the core's
    grouped_splits counter).

    If an enqueue fails partway on THIS rank, the already-submitted
    members are synchronized before re-raising; peer ranks that
    submitted the full group see the incomplete group as stalled (the
    stall inspector warns and can shut the job down) — validate inputs
    before submission when cross-rank failure atomicity matters."""
    base = name if name is not None else _auto_name("grouped_allreduce", None)
    return _grouped_async(
        lambda t, n, g: allreduce_async(
            t, average=average, name=n, op=op,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor,
            process_set=process_set, _group=g,
        ),
        tensors, base,
    )


def _grouped_async(enqueue_one, tensors, base, validate_one=None) -> list:
    """Shared grouped-submission shape (the later reference's grouped
    APIs): every member carries the group id + count, so the coordinator
    HOLDS the group until all members are ready on all ranks — members
    complete together (one per-member plan; only allreduce groups
    additionally fuse into a single buffer). Every member is validated
    BEFORE any is enqueued: a mid-group failure would leave peers
    holding a never-completable group (see ``_drain_group``)."""
    from .common.types import dtype_from_array

    tensors = list(tensors)
    for t in tensors:
        dtype_from_array(t)
        if validate_one is not None:
            validate_one(t)
    from .analysis import preflight as _preflight

    if _preflight.enabled():
        # Static group lint BEFORE any member is enqueued: a group that
        # can never fuse as one collective (mixed dtypes) or that blows
        # the fusion-buffer budget is reported here instead of stranding
        # peers holding an incomplete group.
        _preflight.check_grouped(
            tensors, _rt().config.fusion_threshold_bytes, base
        )
    gid = _group_id(base)
    handles = []
    try:
        for i, t in enumerate(tensors):
            handles.append(
                enqueue_one(t, f"{base}.{i}", (gid, len(tensors)))
            )
    except Exception:
        _drain_group(handles)
        raise
    return handles


def grouped_allgather_async(tensors, name: Optional[str] = None,
                            process_set: Optional[ProcessSet] = None):
    """Allgather a list of tensors as ONE group: the coordinator holds
    the members until every one is ready on every rank, so they complete
    atomically (later-reference ``grouped_allgather``)."""
    base = name if name is not None else _auto_name("grouped_allgather", None)
    return _grouped_async(
        lambda t, n, g: allgather_async(t, n, process_set, _group=g),
        tensors, base,
    )


def grouped_allgather(tensors, name: Optional[str] = None,
                      process_set: Optional[ProcessSet] = None):
    return grouped_sync_first_error(
        grouped_allgather_async(tensors, name, process_set), synchronize
    )


def grouped_reducescatter_async(tensors, name: Optional[str] = None,
                                op: Optional[ReduceOp] = None,
                                process_set: Optional[ProcessSet] = None):
    """Reduce-scatter a list of tensors as ONE group (atomic completion;
    later-reference ``grouped_reducescatter``)."""
    base = (name if name is not None
            else _auto_name("grouped_reducescatter", None))
    rs_op = op if op is not None else ReduceOp.SUM
    if rs_op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        raise ValueError("reducescatter supports SUM/AVERAGE only")

    def validate_one(t):
        if not getattr(t, "shape", ()):
            raise ValueError(
                "reducescatter needs a tensor with a dim0 to scatter"
            )

    return _grouped_async(
        lambda t, n, g: reducescatter_async(t, n, op, process_set,
                                            _group=g),
        tensors, base, validate_one=validate_one,
    )


def grouped_reducescatter(tensors, name: Optional[str] = None,
                          op: Optional[ReduceOp] = None,
                          process_set: Optional[ProcessSet] = None):
    return grouped_sync_first_error(
        grouped_reducescatter_async(tensors, name, op, process_set),
        synchronize,
    )


def _group_id(base: str) -> int:
    """Cross-rank-stable nonzero group id derived from the base name
    (every rank traces the same name sequence; md5 makes collisions
    between distinct concurrent groups negligible). Masked to 63 bits:
    the id travels through signed-int64 channels (the TF custom op's
    int attr, the wire codec), where the top bit would overflow."""
    import hashlib

    raw = int.from_bytes(hashlib.md5(base.encode()).digest()[:8], "little")
    return (raw & ((1 << 63) - 1)) or 1


def _drain_group(handles) -> None:
    """Best-effort bounded wait on already-submitted group members after
    a mid-group enqueue failure. The group can never complete (the
    coordinator holds it until every member arrives), so an unbounded
    synchronize would deadlock here — wait briefly, then abandon; the
    stall inspector reports the orphaned members and peers recover via
    its warning/shutdown path."""
    for h in handles:
        try:
            _rt().synchronize(h, timeout=1.0)
        except Exception:  # noqa: BLE001 - surfacing the original error
            pass


def grouped_sync_first_error(handles, synchronize_fn):
    """Wait on every handle even when one fails (no orphaned results in
    the handle table); re-raise the first error. Shared by the top-level
    and framework grouped APIs."""
    outputs, first_error = [], None
    for h in handles:
        try:
            outputs.append(synchronize_fn(h))
        except Exception as exc:  # noqa: BLE001 - re-raised below
            if first_error is None:
                first_error = exc
    if first_error is not None:
        raise first_error
    return outputs


def grouped_allreduce(
    tensors, average: Optional[bool] = None, name: Optional[str] = None,
    op: Optional[ReduceOp] = None,
    prescale_factor: float = 1.0, postscale_factor: float = 1.0,
    process_set: Optional[ProcessSet] = None,
):
    """Synchronous :func:`grouped_allreduce_async`; returns outputs in
    input order. Every handle is waited on even when one fails, so no
    results are orphaned in the handle table; the first error wins."""
    handles = grouped_allreduce_async(
        tensors, average=average, name=name, op=op,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        process_set=process_set,
    )
    return grouped_sync_first_error(handles, synchronize)


def start_timeline(file_path: str, mark_cycles: bool = False) -> None:
    """Start the catapult timeline at runtime (later-reference
    ``hvd.start_timeline``): same trace the ``HOROVOD_TIMELINE`` env var
    produces, but scoped to the interesting window of a long run."""
    _rt().start_timeline(file_path, mark_cycles)


def stop_timeline() -> None:
    """Stop a runtime-started timeline (later-reference API)."""
    _rt().stop_timeline()


def join() -> None:
    """Signal this rank is out of data; blocks until all ranks join
    (reference ``hvd.join``, ``operations.cc:910-934``)."""
    synchronize(_rt().enqueue_join())


def barrier(name: Optional[str] = None,
            process_set: Optional[ProcessSet] = None) -> None:
    """Block until every member rank reaches the barrier (the later
    reference's ``hvd.barrier``): expressed as a one-element allreduce,
    whose negotiate-then-execute protocol IS a barrier."""
    import numpy as np

    allreduce(
        np.zeros((1,), np.float32), op=ReduceOp.SUM,
        name=_auto_name("barrier", name), process_set=process_set,
    )


def poll(handle: int) -> bool:
    return _rt().poll(handle)


def synchronize(handle: int, timeout: Optional[float] = None) -> Any:
    return _rt().synchronize(handle, timeout)


def broadcast_object(obj: Any, root_rank: int = 0,
                     name: Optional[str] = None,
                     process_set: Optional[ProcessSet] = None) -> Any:
    """Broadcast an arbitrary picklable object from root (later-reference
    API): a size broadcast then a uint8 payload broadcast — O(payload)
    per rank, unlike an object allgather's O(size × payload)."""
    import pickle

    import numpy as np

    name = name or _auto_name("bcast_obj", None)
    # root_rank is a GLOBAL rank (same convention as broadcast, which
    # maps it to the member position on a process set).
    if rank() == root_rank:
        data = np.frombuffer(pickle.dumps(obj), dtype=np.uint8).copy()
    else:
        data = np.zeros((0,), np.uint8)
    sz = np.asarray([data.shape[0]], np.int64)
    sz = np.asarray(broadcast(sz, root_rank, name=f"{name}.size",
                              process_set=process_set))
    payload = (data if data.shape[0] == int(sz[0])
               else np.zeros(int(sz[0]), np.uint8))
    payload = np.asarray(broadcast(payload, root_rank,
                                   name=f"{name}.data",
                                   process_set=process_set))
    return pickle.loads(payload.tobytes())


def broadcast_variables(variables: Any, root_rank: int = 0) -> Any:
    """Broadcast a pytree of arrays from root (reference
    ``broadcast_variables`` / ``broadcast_parameters``). All leaves are
    enqueued async first so one negotiation cycle can fuse them into a
    single plan — latency scales with payload, not leaf count."""
    import jax

    leaves, treedef = jax.tree.flatten(variables)
    handles = [
        broadcast_async(leaf, root_rank, name=f"bcast.var.{i}")
        for i, leaf in enumerate(leaves)
    ]
    return jax.tree.unflatten(treedef, [synchronize(h) for h in handles])


__all__ = [
    "init",
    "shutdown",
    "is_initialized",
    "size",
    "rank",
    "local_rank",
    "local_size",
    "cross_rank",
    "cross_size",
    "is_homogeneous",
    "mesh",
    "allreduce",
    "allreduce_async",
    "allgather",
    "allgather_async",
    "broadcast",
    "broadcast_async",
    "alltoall",
    "alltoall_async",
    "reducescatter",
    "reducescatter_async",
    "grouped_allreduce",
    "grouped_allreduce_async",
    "allgather_object",
    "broadcast_object",
    "ProcessSet",
    "global_process_set",
    "add_process_set",
    "remove_process_set",
    "join",
    "barrier",
    "start_timeline",
    "stop_timeline",
    "grouped_allgather",
    "grouped_allgather_async",
    "grouped_reducescatter",
    "grouped_reducescatter_async",
    "poll",
    "synchronize",
    "broadcast_variables",
    "Compression",
    "ReduceOp",
    "Average",
    "Sum",
    "Adasum",
    "Min",
    "Max",
    "Product",
    "Status",
    "mpi_threads_supported",
    "mpi_built",
    "mpi_enabled",
    "gloo_built",
    "gloo_enabled",
    "nccl_built",
    "ddl_built",
    "mlsl_built",
    "xla_built",
    "xla_enabled",
    "HorovodInternalError",
    "elastic",
    "metrics",
    "metrics_snapshot",
    "serve",
    "trace",
]

from . import elastic  # noqa: E402  (hvd.elastic.run / State / ObjectState)
# hvd.metrics is the metrics subpackage, made callable so hvd.metrics()
# returns the flat snapshot dict (see metrics/__init__.py).
from . import metrics  # noqa: E402, F401
# hvd.trace is the fleet-tracing subpackage (docs/timeline.md "Fleet
# tracing"): step tap, flight recorder, KV trace shipping.
from . import trace  # noqa: E402, F401
# hvd.serve() stands up the inference-serving engine (docs/serving.md);
# the subpackage stays importable as horovod_tpu.serve.
from .serve import serve  # noqa: E402, F401
