"""Elastic API for the torch binding (upstream ``horovod.torch.elastic``):
``run``/``TorchState`` re-exported from the core elastic module, plus
``ElasticSampler`` — a rank-sharding sampler that survives world resizes
without repeating or dropping data within an epoch.
"""

from __future__ import annotations

from ..elastic import (  # noqa: F401
    HostsUpdatedInterrupt,
    ObjectState,
    State,
    TorchState,
    run,
)

__all__ = [
    "run",
    "State",
    "ObjectState",
    "TorchState",
    "ElasticSampler",
    "HostsUpdatedInterrupt",
]


class ElasticSampler:
    """Shards dataset indices over the CURRENT world (re-reads
    ``hvd.rank()/size()`` on every ``__iter__``, so a re-formed world
    automatically re-partitions) and records processed batches so a
    rollback or membership change resumes the epoch where it left off
    instead of repeating data (upstream ``ElasticSampler`` role).

    Usage (mirrors upstream):

    ```python
    sampler = hvd.elastic.ElasticSampler(len(dataset), shuffle=True)
    loader = DataLoader(dataset, sampler=sampler, batch_size=B)
    state = hvd.elastic.TorchState(model, opt, sampler=sampler, epoch=0)
    # in the loop: sampler.record_batch(batch_idx, B); state.commit()
    # on epoch end: sampler.set_epoch(epoch + 1)
    ```

    The instance is picklable, so tracking it as a state attribute gives
    it commit/rollback/sync semantics for free (the sync source's
    processed-set wins after a re-formation).
    """

    def __init__(self, dataset_size: int, shuffle: bool = True,
                 seed: int = 0):
        self.dataset_size = int(dataset_size)
        self.shuffle = bool(shuffle)
        self.seed = int(seed)
        self.epoch = 0
        self.processed: set = set()
        self._local_order: list = []

    # -- epoch lifecycle ------------------------------------------------
    def set_epoch(self, epoch: int) -> None:
        """Start a new epoch: reshuffle and forget processed indices."""
        self.epoch = int(epoch)
        self.processed.clear()
        self._local_order = []

    def record_batch(self, batch_idx: int, batch_size: int) -> None:
        """Mark the ``batch_idx``-th batch of the current iteration order
        as processed (call after training on it, before ``commit()``)."""
        start = batch_idx * batch_size
        self.processed.update(
            self._local_order[start:start + batch_size]
        )

    # -- sampling -------------------------------------------------------
    def _remaining(self) -> list:
        import numpy as np

        order = list(range(self.dataset_size))
        if self.shuffle:
            np.random.RandomState(self.seed + self.epoch).shuffle(order)
        return [i for i in order if i not in self.processed]

    def __iter__(self):
        import horovod_tpu as hvd

        n = hvd.size() if hvd.is_initialized() else 1
        r = hvd.rank() if hvd.is_initialized() else 0
        remaining = self._remaining()
        # Pad by wrapping (modulo, like torch's DistributedSampler) so
        # every rank yields the same count even when fewer indices remain
        # than the pad needs — unequal counts would desync collectives.
        if remaining and len(remaining) % n:
            total = len(remaining) + (n - len(remaining) % n)
            remaining = [
                remaining[i % len(remaining)] for i in range(total)
            ]
        self._local_order = remaining[r::n]
        return iter(self._local_order)

    def __len__(self) -> int:
        import horovod_tpu as hvd

        n = hvd.size() if hvd.is_initialized() else 1
        rem = self.dataset_size - len(self.processed)
        return -(-rem // n)  # ceil

    # picklability: drop nothing — all attrs are plain data.
