"""Handle-based async collective ops on torch tensors.

API parity with ``horovod/torch/mpi_ops.py`` (allreduce[_async][_],
allgather, broadcast, poll, synchronize, join) — the divisor logic for
Average and the in-place variants follow the reference
(``mpi_ops.py:95-254``). The data path hands torch tensors to the XLA data
plane **zero-copy via DLPack** (the role of the reference's
``mpi_lib_v2`` C extension getting at the tensor buffer,
``torch/mpi_ops.cc``), which also routes them through the eager executor's
device-resident fast path; results come back the same way. Tensors DLPack
rejects fall back to the numpy bridge (zero-copy for contiguous CPU).

bfloat16 rides DLPack natively (jax understands bf16); only the numpy
fallback upcasts to fp32 (numpy has no bf16).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .. import _auto_name, _resolve_op, _rt
from ..common.types import Adasum, Average, ReduceOp, Sum  # noqa: F401

# handle -> (input_tensor_or_None, ctx) for in-place/average post-ops
_handle_meta: dict = {}


def _to_plane(tensor):
    """torch -> data plane, preferring a zero-copy DLPack handoff to a jax
    array (activates the executor's device-resident path)."""
    import torch

    t = tensor.detach()
    try:
        import jax

        return jax.dlpack.from_dlpack(t.contiguous())
    except Exception:
        if t.dtype == torch.bfloat16:
            t = t.float()
        return t.cpu().numpy()


# Back-compat alias (tests and older callers).
_to_numpy = _to_plane


def _from_plane(out, like):
    """Data-plane result -> torch tensor; zero-copy for jax arrays."""
    import torch

    if not isinstance(out, np.ndarray):
        try:
            result = torch.from_dlpack(out)
            if like is not None and result.dtype != like.dtype:
                result = result.to(like.dtype)
            return result
        except Exception:
            pass
    out = np.ascontiguousarray(np.asarray(out))
    result = torch.from_numpy(out)
    if like is not None and result.dtype != like.dtype:
        result = result.to(like.dtype)
    return result


def _from_numpy(arr, like):  # back-compat alias
    return _from_plane(arr, like)


def allreduce_async(tensor, average=None, name=None, op=None,
                    prescale_factor=1.0, postscale_factor=1.0) -> int:
    rop = _resolve_op(average, op)
    arr = _to_numpy(tensor)
    rt = _rt()
    tensor_name = _auto_name("allreduce.torch", name)
    if rop == ReduceOp.ADASUM:
        handle = rt.enqueue_adasum(
            tensor_name, arr, prescale_factor=prescale_factor,
            postscale_factor=postscale_factor,
        )
    else:
        handle = rt.enqueue_allreduce(
            tensor_name, arr, reduce_op=rop,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor,
        )
    _handle_meta[handle] = (None, tensor)
    return handle


def allreduce(tensor, average=None, name=None, compression=None, op=None,
              prescale_factor=1.0, postscale_factor=1.0):
    from .compression import Compression

    compression = compression or Compression.none
    compressed, ctx = compression.compress(tensor)
    handle = allreduce_async(compressed, average=average, name=name, op=op,
                             prescale_factor=prescale_factor,
                             postscale_factor=postscale_factor)
    return compression.decompress(synchronize(handle), ctx)


def allreduce_async_(tensor, average=None, name=None, op=None,
                     prescale_factor=1.0, postscale_factor=1.0) -> int:
    """In-place async allreduce: on synchronize, the result is copied back
    into ``tensor`` (reference allreduce_async_)."""
    handle = allreduce_async(tensor, average=average, name=name, op=op,
                             prescale_factor=prescale_factor,
                             postscale_factor=postscale_factor)
    _handle_meta[handle] = (tensor, tensor)
    return handle


def allreduce_(tensor, average=None, name=None, op=None,
               prescale_factor=1.0, postscale_factor=1.0):
    return synchronize(
        allreduce_async_(tensor, average=average, name=name, op=op,
                         prescale_factor=prescale_factor,
                         postscale_factor=postscale_factor)
    )


def _grouped_async_torch(kind, enqueue_name, tensors, name,
                         **enqueue_kwargs):
    """Shared grouped submission for the torch binding (later-reference
    grouped APIs): members convert BEFORE any enqueue, carry one group
    id, and complete atomically (held by the coordinator until all are
    ready on all ranks). A mid-group failure drains the already-
    submitted members AND drops their _handle_meta entries (the drain
    bypasses this module's synchronize, which is what normally pops
    them — leaking entries would pin the tensors forever)."""
    from .. import _drain_group, _group_id

    tensors = list(tensors)
    arrs = [_to_numpy(t) for t in tensors]
    base = _auto_name(f"{kind}.torch", name)
    gid = _group_id(base)
    rt = _rt()
    enqueue = getattr(rt, enqueue_name)
    handles = []
    try:
        for i, (t, arr) in enumerate(zip(tensors, arrs)):
            h = enqueue(f"{base}.{i}", arr,
                        group_id=gid, group_size=len(tensors),
                        **enqueue_kwargs)
            _handle_meta[h] = (None, t)
            handles.append(h)
    except Exception:
        _drain_group(handles)
        for h in handles:
            _handle_meta.pop(h, None)
        raise
    return handles


def grouped_allreduce_async(tensors, average=None, name=None, op=None,
                            prescale_factor=1.0, postscale_factor=1.0):
    """Enqueue ``tensors`` as ONE first-class group and return their
    handles (later-reference ``hvd.grouped_allreduce_async`` parity for
    torch): the coordinator holds the group until every member is ready
    on every rank and fuses it into a single plan regardless of cycle
    boundaries or the fusion threshold."""
    rop = _resolve_op(average, op)
    if rop == ReduceOp.ADASUM:
        raise ValueError(
            "grouped_allreduce does not support op=Adasum; use the "
            "DistributedAdasumOptimizer (delta-space) path instead"
        )
    return _grouped_async_torch(
        "grouped_allreduce", "enqueue_allreduce", tensors, name,
        reduce_op=rop, prescale_factor=prescale_factor,
        postscale_factor=postscale_factor,
    )


def grouped_allreduce(tensors, average=None, name=None, op=None,
                      prescale_factor=1.0, postscale_factor=1.0):
    """Synchronous grouped allreduce; returns outputs in input order."""
    from .. import grouped_sync_first_error

    handles = grouped_allreduce_async(
        tensors, average=average, name=name, op=op,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
    )
    return grouped_sync_first_error(handles, synchronize)


def grouped_allgather_async(tensors, name=None):
    return _grouped_async_torch(
        "grouped_allgather", "enqueue_allgather", tensors, name
    )


def grouped_allgather(tensors, name=None):
    from .. import grouped_sync_first_error

    return grouped_sync_first_error(
        grouped_allgather_async(tensors, name), synchronize
    )


def grouped_reducescatter_async(tensors, name=None, op=None):
    rop = op if op is not None else ReduceOp.SUM
    if rop not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        raise ValueError("reducescatter supports SUM/AVERAGE only")
    tensors = list(tensors)  # a generator must survive validation
    for t in tensors:
        if not getattr(t, "shape", ()):
            raise ValueError(
                "reducescatter needs a tensor with a dim0 to scatter"
            )
    return _grouped_async_torch(
        "grouped_reducescatter", "enqueue_reducescatter", tensors, name,
        reduce_op=rop,
    )


def grouped_reducescatter(tensors, name=None, op=None):
    from .. import grouped_sync_first_error

    return grouped_sync_first_error(
        grouped_reducescatter_async(tensors, name, op), synchronize
    )


def allgather_async(tensor, name=None) -> int:
    arr = _to_numpy(tensor)
    handle = _rt().enqueue_allgather(_auto_name("allgather.torch", name), arr)
    _handle_meta[handle] = (None, tensor)
    return handle


def allgather(tensor, name=None):
    return synchronize(allgather_async(tensor, name))


def broadcast_async(tensor, root_rank, name=None) -> int:
    arr = _to_numpy(tensor)
    handle = _rt().enqueue_broadcast(
        _auto_name("broadcast.torch", name), arr, root_rank
    )
    _handle_meta[handle] = (None, tensor)
    return handle


def broadcast(tensor, root_rank, name=None):
    return synchronize(broadcast_async(tensor, root_rank, name))


def broadcast_async_(tensor, root_rank, name=None) -> int:
    handle = broadcast_async(tensor, root_rank, name)
    _handle_meta[handle] = (tensor, tensor)
    return handle


def broadcast_(tensor, root_rank, name=None):
    return synchronize(broadcast_async_(tensor, root_rank, name))


def alltoall_async(tensor, name=None) -> int:
    arr = _to_numpy(tensor)
    handle = _rt().enqueue_alltoall(_auto_name("alltoall.torch", name), arr)
    _handle_meta[handle] = (None, tensor)
    return handle


def alltoall(tensor, splits=None, name=None):
    """Even alltoall, or — with ``splits`` (the later reference's
    alltoallv form) — returns ``(collected, received_splits)`` as torch
    tensors, delegating to the core uneven implementation."""
    if splits is None:
        return synchronize(alltoall_async(tensor, name))
    import torch

    import horovod_tpu as _hvd

    splits_np = (splits.detach().cpu().numpy()
                 if isinstance(splits, torch.Tensor) else splits)
    out, received = _hvd.alltoall(
        _to_numpy(tensor), splits_np, name=_auto_name("alltoall.torch", name)
    )
    # _from_plane handles the plane's dtypes (incl. ml_dtypes bfloat16,
    # which torch.from_numpy rejects).
    return _from_plane(out, tensor), torch.from_numpy(received.copy())


def poll(handle: int) -> bool:
    return _rt().poll(handle)


def synchronize(handle: int):
    out = _rt().synchronize(handle)
    inplace_target, like = _handle_meta.pop(handle, (None, None))
    result = _from_plane(out, like)
    if inplace_target is not None:
        with _no_grad():
            inplace_target.copy_(result.reshape(inplace_target.shape))
        return inplace_target
    return result


def _no_grad():
    import torch

    return torch.no_grad()


def join() -> None:
    from .. import join as _join

    _join()
