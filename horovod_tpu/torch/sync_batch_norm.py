"""Synchronized batch normalization for the torch binding.

Later-reference parity: upstream added ``horovod.torch.SyncBatchNorm``
(v0.21) so batch statistics are computed over the GLOBAL batch — small
per-rank batches otherwise give noisy, rank-divergent statistics. This is
an independent implementation of the standard two-allreduce scheme (the
textbook sync-BN formulation): the forward allreduces per-channel
[sum, sum-of-squares, count], the backward allreduces
[sum(dy), sum(dy·(x-mean))] and applies the batch-norm gradient identity.

Eval mode uses the (already synchronized) running stats and never
communicates. Weight/bias gradients stay local — a DistributedOptimizer
reduces them with every other gradient.
"""

from __future__ import annotations

import itertools

_ids = itertools.count()


def _hvd():
    import horovod_tpu as hvd

    return hvd


class _SyncBatchNormFunction:
    """Autograd function built lazily so importing this module never
    requires torch."""

    _cls = None

    @classmethod
    def get(cls):
        if cls._cls is not None:
            return cls._cls
        import numpy as np
        import torch

        class F(torch.autograd.Function):
            # Statistics are computed and allreduced in float32 (the
            # reference implementation does the same): bf16 has no numpy
            # path and f16 sums of squares overflow on realistic
            # activations; only the final normalized output returns to
            # the input dtype.
            @staticmethod
            def forward(ctx, x, weight, bias, eps, tag):
                hvd = _hvd()
                xf = x.float()
                dims = [0] + list(range(2, x.dim()))
                count_local = x.numel() // x.shape[1]
                stats = torch.cat([
                    xf.sum(dims),
                    (xf * xf).sum(dims),
                    torch.tensor([float(count_local)]),
                ])
                stats = torch.from_numpy(np.asarray(hvd.allreduce(
                    stats.detach().cpu().numpy(), op=hvd.Sum,
                    name=f"{tag}.fwd",
                )))
                c = x.shape[1]
                count = stats[-1]
                mean = stats[:c] / count
                var = stats[c:2 * c] / count - mean * mean
                invstd = torch.rsqrt(var + eps)
                shape = [1, c] + [1] * (x.dim() - 2)
                xhat = (xf - mean.view(shape)) * invstd.view(shape)
                y = (xhat * weight.float().view(shape)
                     + bias.float().view(shape)).to(x.dtype)
                ctx.save_for_backward(x, weight, mean, invstd, count)
                ctx.tag = tag
                return y, mean, var, count

            @staticmethod
            def backward(ctx, dy, _dmean, _dvar, _dcount):
                hvd = _hvd()
                x, weight, mean, invstd, count = ctx.saved_tensors
                c = x.shape[1]
                dims = [0] + list(range(2, x.dim()))
                shape = [1, c] + [1] * (x.dim() - 2)
                dyf = dy.float()
                xmu = x.float() - mean.view(shape)
                grad_stats = torch.cat([
                    dyf.sum(dims), (dyf * xmu).sum(dims)
                ])
                grad_stats = torch.from_numpy(np.asarray(hvd.allreduce(
                    grad_stats.detach().cpu().numpy(), op=hvd.Sum,
                    name=f"{ctx.tag}.bwd",
                )))
                sum_dy = grad_stats[:c] / count
                sum_dy_xmu = grad_stats[c:] / count
                # d/dx of (x - mean) * invstd * w  (batch-norm identity)
                dx = ((
                    dyf
                    - sum_dy.view(shape)
                    - xmu * (invstd.view(shape) ** 2)
                    * sum_dy_xmu.view(shape)
                ) * invstd.view(shape)
                    * weight.float().view(shape)).to(x.dtype)
                dweight = (
                    (dyf * xmu * invstd.view(shape)).sum(dims)
                ).to(weight.dtype)
                dbias = dyf.sum(dims).to(weight.dtype)
                return dx, dweight, dbias, None, None

        cls._cls = F
        return F


def _make_sync_batch_norm():
    import torch
    from torch.nn.modules.batchnorm import _BatchNorm

    class SyncBatchNorm(_BatchNorm):
        """Batch norm over the global batch (all ranks). Drop-in for
        ``nn.BatchNorm1d/2d/3d``; statistics are allreduced in training
        mode, running stats follow the usual momentum update (unbiased
        variance) and are identical on every rank by construction."""

        def __init__(self, *args, **kwargs):
            super().__init__(*args, **kwargs)
            self._tag = f"syncbn.{next(_ids)}"

        def _check_input_dim(self, x):
            if x.dim() < 2:
                raise ValueError(
                    f"expected at least 2D input (got {x.dim()}D)"
                )

        def forward(self, x):
            self._check_input_dim(x)
            hvd = _hvd()
            if (not self.training) or hvd.size() == 1:
                return super().forward(x)
            # Momentum bookkeeping only applies with running stats (torch's
            # own _BatchNorm.forward guards the same way; num_batches_tracked
            # is None without them).
            momentum = self.momentum
            if self.track_running_stats and self.momentum is None:
                self.num_batches_tracked += 1
                momentum = 1.0 / float(self.num_batches_tracked)
            weight = (self.weight if self.affine
                      else torch.ones(x.shape[1], dtype=x.dtype))
            bias = (self.bias if self.affine
                    else torch.zeros(x.shape[1], dtype=x.dtype))
            F = _SyncBatchNormFunction.get()
            y, mean, var, count = F.apply(x, weight, bias, self.eps,
                                          self._tag)
            if self.track_running_stats:
                with torch.no_grad():
                    unbiased = var * (count / (count - 1).clamp(min=1.0))
                    self.running_mean.mul_(1 - momentum).add_(
                        mean.detach(), alpha=momentum
                    )
                    self.running_var.mul_(1 - momentum).add_(
                        unbiased.detach(), alpha=momentum
                    )
                    if self.momentum is not None:
                        self.num_batches_tracked += 1
            return y

    return SyncBatchNorm
