"""Gradient compression for torch tensors — parity with
``horovod/torch/compression.py`` (fp16 on the wire)."""

from __future__ import annotations


class Compressor:
    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor(Compressor):
    @staticmethod
    def compress(tensor):
        import torch

        dtype = tensor.dtype
        if dtype in (torch.float32, torch.float64):
            tensor = tensor.half()
        return tensor, dtype

    @staticmethod
    def decompress(tensor, ctx):
        if ctx is not None and tensor.dtype != ctx:
            tensor = tensor.to(ctx)
        return tensor


class BF16Compressor(Compressor):
    """TPU-native addition: bf16 wire format."""

    @staticmethod
    def compress(tensor):
        import torch

        dtype = tensor.dtype
        if dtype in (torch.float32, torch.float64):
            tensor = tensor.bfloat16()
        return tensor, dtype

    @staticmethod
    def decompress(tensor, ctx):
        if ctx is not None and tensor.dtype != ctx:
            tensor = tensor.to(ctx)
        return tensor


class Compression:
    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
