"""horovod_tpu.torch — PyTorch binding.

API parity with ``horovod/torch/__init__.py``: hook-driven
``DistributedOptimizer`` (per-parameter grad hooks fire async allreduce;
``step()`` synchronizes), ``broadcast_parameters`` /
``broadcast_optimizer_state``, ``backward_passes_per_step`` local
accumulation, Compression, and the full handle-based op surface re-exported
from :mod:`.mpi_ops`.

The data plane is the shared eager runtime (native C++ control plane + XLA
executor); CPU torch tensors cross as zero-copy numpy views.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Tuple

from .. import (  # re-export basics (reference exposes these here too)
    Adasum,
    Average,
    Sum,
    barrier,
    cross_rank,
    cross_size,
    init,
    is_initialized,
    local_rank,
    local_size,
    rank,
    shutdown,
    size,
)
from .compression import Compression
from .mpi_ops import (  # noqa: F401
    allgather,
    allgather_async,
    allreduce,
    allreduce_,
    allreduce_async,
    allreduce_async_,
    alltoall,
    alltoall_async,
    broadcast,
    broadcast_,
    broadcast_async,
    broadcast_async_,
    grouped_allgather,
    grouped_allgather_async,
    grouped_allreduce,
    grouped_allreduce_async,
    grouped_reducescatter,
    grouped_reducescatter_async,
    join,
    poll,
    synchronize,
)


class _DistributedOptimizer:
    """Wraps a torch optimizer; mirrors the reference implementation
    (``horovod/torch/__init__.py:54-209``): a post-accumulate-grad hook per
    parameter fires an async in-place allreduce once
    ``backward_passes_per_step`` microbatches have accumulated; ``step()``
    synchronizes all outstanding handles, then steps the inner optimizer."""

    def __init__(self, optimizer, named_parameters=None,
                 compression=Compression.none, backward_passes_per_step=1,
                 op=Average, sparse_as_dense=False):
        self._opt = optimizer
        self._compression = compression
        self._op = op
        self._sparse_as_dense = sparse_as_dense
        self.backward_passes_per_step = backward_passes_per_step
        if named_parameters is not None:
            named = list(named_parameters)
        else:
            named = []
            i = 0
            for group in optimizer.param_groups:
                for p in group["params"]:
                    named.append((f"param.{i}", p))
                    i += 1
        # Duplicate-name guard (reference raises on dups).
        names = [n for n, _ in named]
        if len(names) != len(set(names)):
            raise ValueError(
                "named_parameters contains duplicate parameter names"
            )
        self._param_names = {p: n for n, p in named}
        self._handles: Dict[Any, Tuple[int, Any]] = {}
        self._grad_accs: List[Any] = []
        self._passes: Dict[Any, int] = {}
        self._hook_handles = []
        self._register_hooks()

    # delegation
    def __getattr__(self, item):
        return getattr(self._opt, item)

    @property
    def param_groups(self):
        return self._opt.param_groups

    def _register_hooks(self) -> None:
        import torch

        for group in self._opt.param_groups:
            for p in group["params"]:
                if not p.requires_grad:
                    continue
                self._passes[p] = 0

                def hook(param):
                    self._passes[param] += 1
                    if self._passes[param] == self.backward_passes_per_step:
                        self._passes[param] = 0
                        self._allreduce_grad_async(param)

                self._hook_handles.append(
                    p.register_post_accumulate_grad_hook(hook)
                )

    def _allreduce_grad_async(self, p) -> None:
        import torch

        name = self._param_names.get(p, f"param.{id(p)}")
        grad = p.grad
        if grad.is_sparse:
            # Sparse (embedding) gradients: the XLA wire is dense-only.
            # With sparse_as_dense=True the gradient densifies before the
            # allreduce (reference DistributedOptimizer option); without
            # it, fail with the reference's guidance instead of a deep
            # DLPack error.
            if not self._sparse_as_dense:
                raise ValueError(
                    "Gradient for parameter is sparse; construct "
                    "DistributedOptimizer with sparse_as_dense=True to "
                    "densify sparse gradients before the allreduce."
                )
            grad = grad.to_dense()
            with torch.no_grad():
                p.grad = grad
        if self.backward_passes_per_step > 1:
            grad = grad / self.backward_passes_per_step
        compressed, ctx = self._compression.compress(grad)
        handle = allreduce_async(
            compressed, name=f"DistributedOptimizer.{name}", op=self._op
        )
        self._handles[p] = (handle, ctx)

    def synchronize(self) -> None:
        import torch

        try:
            for group in self._opt.param_groups:
                for p in group["params"]:
                    if p not in self._handles and p.requires_grad \
                            and p.grad is not None:
                        # backward() was not run (or hook missed): reduce
                        # now, matching the reference's missing-handle
                        # path.
                        self._allreduce_grad_async(p)
            for p, (handle, ctx) in list(self._handles.items()):
                out = synchronize(handle)
                out = self._compression.decompress(out, ctx)
                with torch.no_grad():
                    p.grad.copy_(
                        out.reshape(p.grad.shape).to(p.grad.dtype)
                    )
            self._handles.clear()
        except Exception:
            # A failed collective (peer loss, shutdown) leaves the whole
            # in-flight set dead — drop it and reset the accumulation
            # counters so an elastic rollback can re-enter training
            # instead of tripping zero_grad()'s outstanding-handle guard.
            self._handles.clear()
            for k in self._passes:
                self._passes[k] = 0
            raise

    def step(self, closure=None):
        self.synchronize()
        return self._opt.step(closure)

    def reset(self) -> None:
        """Drop in-flight allreduce handles and accumulation counters —
        they reference a dead world after an elastic rollback. Called by
        ``TorchState`` restore/sync; harmless when idle."""
        self._handles.clear()
        for k in self._passes:
            self._passes[k] = 0

    def zero_grad(self, *args, **kwargs):
        if self._handles:
            raise AssertionError(
                "optimizer.zero_grad() was called after loss.backward() but "
                "before optimizer.step() or optimizer.synchronize()."
            )
        return self._opt.zero_grad(*args, **kwargs)

    def state_dict(self):
        return self._opt.state_dict()

    def load_state_dict(self, *args, **kwargs):
        return self._opt.load_state_dict(*args, **kwargs)


class _DistributedAdasumOptimizer:
    """Delta-space Adasum (reference ``horovod/torch/__init__.py:211-379``):
    the inner optimizer steps on LOCAL gradients, and what is Adasum-reduced
    is the parameter *delta* it produced — so adaptive state (Adam moments,
    momentum) stays local and the adaptive combine acts on the actual
    update direction, which is the Adasum paper's formulation."""

    def __init__(self, optimizer, named_parameters=None,
                 compression=Compression.none, backward_passes_per_step=1):
        self._opt = optimizer
        self._compression = compression
        self.backward_passes_per_step = backward_passes_per_step
        if named_parameters is not None:
            named = list(named_parameters)
            names = [n for n, _ in named]
            if len(names) != len(set(names)):
                raise ValueError(
                    "named_parameters contains duplicate parameter names"
                )
            self._param_names = {p: n for n, p in named}
        else:
            self._param_names = {}
            i = 0
            for group in optimizer.param_groups:
                for p in group["params"]:
                    self._param_names[p] = f"param.{i}"
                    i += 1

    def __getattr__(self, item):
        return getattr(self._opt, item)

    @property
    def param_groups(self):
        return self._opt.param_groups

    def step(self, closure=None):
        import torch

        if self.backward_passes_per_step > 1:
            if closure is not None:
                # A gradient-recomputing closure (LBFGS-style) would
                # overwrite p.grad after the division below, silently
                # dropping the accumulation normalization — refuse
                # rather than train on wrong gradients (the reference's
                # gradient-space wrapper has the same structural
                # limitation).
                raise ValueError(
                    "DistributedAdasumOptimizer does not support a step "
                    "closure together with backward_passes_per_step > 1: "
                    "the closure recomputes gradients after the "
                    "accumulation divisor is applied."
                )
            # N backward() calls accumulated into p.grad; average them
            # before the local step (same normalization as the
            # gradient-space wrapper).
            with torch.no_grad():
                for group in self._opt.param_groups:
                    for p in group["params"]:
                        if p.grad is not None:
                            p.grad.div_(self.backward_passes_per_step)
        # Only parameters the optimizer can update get cloned/reduced —
        # frozen (grad-None) params never produce a delta, and the skip is
        # structural, so it is consistent across ranks.
        if closure is not None and all(
            p.grad is None
            for group in self._opt.param_groups
            for p in group["params"]
            if p.requires_grad
        ):
            # No gradients exist at all, so the closure is the gradient
            # producer (LBFGS pattern): the delta snapshot below would be
            # empty and nothing would be Adasum-reduced. Fail before
            # stepping. (Partially-missing grads are legal — structurally
            # unused params stay grad-None forever — so the precise
            # check for closure-produced gradients runs AFTER the step.)
            raise ValueError(
                "DistributedAdasumOptimizer cannot reduce "
                "closure-computed gradients: call loss.backward() before "
                "step() so parameter deltas are observable."
            )
        starts = {}
        with torch.no_grad():
            for group in self._opt.param_groups:
                for p in group["params"]:
                    if p.grad is not None:
                        starts[p] = p.detach().clone()
        loss = self._opt.step(closure)
        if closure is not None:
            # Precise post-step detection: a param that was grad-None at
            # snapshot time but has a gradient now got it FROM the
            # closure — its locally-applied update was never
            # Adasum-reduced, so ranks would diverge silently. Fail loud.
            for group in self._opt.param_groups:
                for p in group["params"]:
                    if p not in starts and p.grad is not None:
                        raise RuntimeError(
                            "DistributedAdasumOptimizer: the step closure "
                            "produced gradients for parameters that had "
                            "none before step(); their updates cannot be "
                            "Adasum-reduced. Call loss.backward() before "
                            "step() instead."
                        )
        # Adasum-allreduce each parameter's local delta asynchronously,
        # then rebase: p = p_start + adasum(delta).
        handles = []
        with torch.no_grad():
            for group in self._opt.param_groups:
                for p in group["params"]:
                    if p not in starts:
                        continue
                    delta = p - starts[p]
                    name = self._param_names.get(p, f"param.{id(p)}")
                    compressed, ctx = self._compression.compress(delta)
                    handles.append((
                        p,
                        allreduce_async(
                            compressed,
                            name=f"AdasumOptimizer.delta.{name}",
                            op=Adasum,
                        ),
                        ctx,
                    ))
            for p, handle, ctx in handles:
                out = self._compression.decompress(synchronize(handle), ctx)
                p.copy_(starts[p] + out.reshape(p.shape).to(p.dtype))
        return loss

    def synchronize(self) -> None:
        """Adasum reduces inside step(); nothing is outstanding between
        steps (kept for API parity with _DistributedOptimizer)."""

    def zero_grad(self, *args, **kwargs):
        return self._opt.zero_grad(*args, **kwargs)

    def state_dict(self):
        return self._opt.state_dict()

    def load_state_dict(self, *args, **kwargs):
        return self._opt.load_state_dict(*args, **kwargs)


def DistributedOptimizer(optimizer, named_parameters=None,  # noqa: N802
                         compression=Compression.none,
                         backward_passes_per_step=1, op=Average,
                         sparse_as_dense=False):
    """API parity with ``hvd.DistributedOptimizer``
    (``horovod/torch/__init__.py:381-435``): ``op=Adasum`` dispatches to
    the delta-space Adasum optimizer exactly as the reference does;
    ``sparse_as_dense`` densifies sparse (embedding) gradients before
    the allreduce."""
    if op == Adasum:
        if sparse_as_dense:
            raise ValueError(
                "sparse_as_dense is not supported with op=Adasum: the "
                "delta-space Adasum optimizer reduces parameter deltas "
                "(always dense), not gradients."
            )
        return _DistributedAdasumOptimizer(
            optimizer, named_parameters=named_parameters,
            compression=compression,
            backward_passes_per_step=backward_passes_per_step,
        )
    return _DistributedOptimizer(
        optimizer, named_parameters=named_parameters, compression=compression,
        backward_passes_per_step=backward_passes_per_step, op=op,
        sparse_as_dense=sparse_as_dense,
    )


def broadcast_parameters(params, root_rank: int = 0) -> None:
    """Broadcast a state_dict or list of (name, tensor) from root
    (reference ``horovod/torch/__init__.py:381-435`` broadcast_parameters):
    every rank's tensors are overwritten in place with root's values."""
    if hasattr(params, "items"):
        items = list(params.items())
    else:
        items = list(params)
    handles = []
    for name, p in items:
        if p is None:
            continue
        handles.append(broadcast_async_(p.data if hasattr(p, "data") else p,
                                        root_rank, name=f"bcast.{name}"))
    for h in handles:
        synchronize(h)


def broadcast_optimizer_state(optimizer, root_rank: int = 0) -> None:
    """Broadcast optimizer state from root (reference
    ``horovod/torch/__init__.py:437-560``): scalars are wrapped as tensors,
    broadcast, and written back via callbacks."""
    import torch

    if isinstance(optimizer, (_DistributedOptimizer,
                              _DistributedAdasumOptimizer)):
        # Unwrap so the dummy state-materialization step below uses the
        # inner optimizer directly — the wrapped step() would fire
        # collectives that ranks skipping this branch never post.
        optimizer = optimizer._opt

    state_dict = optimizer.state_dict()
    # Newly constructed optimizers have no state: run a dummy step on zero
    # grads to materialize it (reference does exactly this). The zeroing
    # must be UNCONDITIONAL — a live gradient left from an interrupted
    # step (elastic rollback) would otherwise be applied as a real
    # parameter update here, silently moving the just-restored weights.
    # Existing grads are stashed and put back so callers keep theirs.
    if not state_dict.get("state"):
        stashed = []
        try:
            for group in optimizer.param_groups:
                for p in group["params"]:
                    if p.requires_grad:
                        stashed.append((p, p.grad))
                        p.grad = torch.zeros_like(p)
            optimizer.step()
        finally:
            for p, g in stashed:
                p.grad = g
        state_dict = optimizer.state_dict()

    callbacks = []
    handles = []

    def _bcast_scalar(container, key, value, name):
        t = torch.tensor([value], dtype=torch.float64)
        h = broadcast_async_(t, root_rank, name=name)

        def write_back():
            synchronize(h)
            casted = type(value)(t.item()) if not isinstance(value, bool) \
                else bool(t.item())
            container[key] = casted

        callbacks.append(write_back)

    for gi, group in enumerate(state_dict["param_groups"]):
        for key, value in group.items():
            if key == "params":
                continue
            if isinstance(value, (int, float)):
                _bcast_scalar(group, key, value, f"opt.group{gi}.{key}")
    for pid, pstate in state_dict["state"].items():
        for key, value in pstate.items():
            name = f"opt.state.{pid}.{key}"
            if torch.is_tensor(value):
                handles.append(broadcast_async_(value, root_rank, name=name))
            elif isinstance(value, (int, float)):
                _bcast_scalar(pstate, key, value, name)
    for h in handles:
        synchronize(h)
    for cb in callbacks:
        cb()
    optimizer.load_state_dict(state_dict)


def allgather_object(obj, name: Optional[str] = None) -> list:
    """Gather one picklable object per rank; every rank gets the full
    rank-ordered list (later-reference API, included for completeness).
    Rides the uneven (Allgatherv-parity) dim0 allgather, so payload sizes
    may differ per rank."""
    import pickle

    import numpy as np
    import torch

    data = np.frombuffer(pickle.dumps(obj), dtype=np.uint8).copy()
    sizes = allgather(
        torch.tensor([len(data)], dtype=torch.int64),
        name=f"{name or 'gather_obj'}.size",
    )
    payload = allgather(
        torch.from_numpy(data), name=f"{name or 'gather_obj'}.data"
    ).numpy()
    out, off = [], 0
    for n in sizes.tolist():
        out.append(pickle.loads(payload[off:off + n].tobytes()))
        off += n
    return out


def broadcast_object(obj, root_rank: int = 0, name: Optional[str] = None):
    """Broadcast an arbitrary picklable object (later-reference API) —
    delegates to the one core implementation (size broadcast + uint8
    payload broadcast); objects never touch torch tensors."""
    import horovod_tpu as _hvd

    return _hvd.broadcast_object(obj, root_rank=root_rank, name=name)


def __getattr__(name):
    # SyncBatchNorm subclasses torch.nn's _BatchNorm, so its class body
    # needs torch — built on first access to keep this module importable
    # without it.
    if name == "SyncBatchNorm":
        from .sync_batch_norm import _make_sync_batch_norm

        cls = _make_sync_batch_norm()
        globals()["SyncBatchNorm"] = cls
        return cls
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
