"""HTTP frontend for ``hvd.serve()`` — the request plane.

Rides the same ``ThreadingHTTPServer`` machinery as the rendezvous KV
plane (``run/http_server.py``): one threaded server, quiet logging,
SO_REUSEADDR. Endpoints (docs/serving.md):

- ``POST /v1/completions`` — body ``{"prompt": [token ids],
  "max_tokens": N}``; blocks until the engine ledgers the answer and
  returns ``{"id", "outcome", "completion"}``. Outcome maps to status:
  ``ok`` → 200, ``rejected`` (queue bound) → 429, ``dropped``
  (injected chaos) → 503 — a dropped request is still ANSWERED, the
  exactly-once contract is HTTP-visible.
- ``GET /healthz`` — live replica count + queue depth.
- ``GET /metrics`` — Prometheus exposition of this process's registry
  (the serving SLO catalog: ``hvd_request_*`` / ``hvd_serve_*``).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import urlparse

from .. import metrics as _metrics


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # quiet
        pass

    def _reply(self, status: int, body: bytes,
               ctype: str = "application/json") -> None:
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _reply_json(self, status: int, doc) -> None:
        self._reply(status, json.dumps(doc, sort_keys=True).encode())

    def do_GET(self):  # noqa: N802
        path = urlparse(self.path).path
        engine = self.server.engine
        if path == "/healthz":
            self._reply_json(200, {
                "replicas": engine.live_replicas(),
                "queue_depth": engine._batcher.depth(),
            })
            return
        if path == "/metrics":
            from ..metrics import export as _export

            body = _export.aggregate_kv_snapshots(
                {}, local_snapshot=_metrics.snapshot()
            ).encode()
            self._reply(200, body, ctype=_export.CONTENT_TYPE)
            return
        self._reply_json(404, {"error": f"no such endpoint {path!r}"})

    def do_POST(self):  # noqa: N802
        path = urlparse(self.path).path
        if path != "/v1/completions":
            self._reply_json(404, {"error": f"no such endpoint {path!r}"})
            return
        engine = self.server.engine
        try:
            length = int(self.headers.get("Content-Length", 0))
            doc = json.loads(self.rfile.read(length) or b"{}")
            prompt = doc["prompt"]
            max_tokens = int(doc.get("max_tokens", 16))
            rid = engine.submit(prompt, max_tokens=max_tokens)
        except (KeyError, TypeError, ValueError) as exc:
            self._reply_json(400, {"error": str(exc)})
            return
        comp = engine.result(rid, timeout=self.server.request_timeout_s)
        status = {"ok": 200, "rejected": 429, "dropped": 503}.get(
            comp.outcome, 500
        )
        self._reply_json(status, {
            "id": comp.id,
            "outcome": comp.outcome,
            "completion": list(comp.tokens),
        })


class _Server(ThreadingHTTPServer):
    allow_reuse_address = True
    daemon_threads = True


class ServeFrontend:
    """In-process threaded HTTP request plane over one ServeEngine
    (``port=0`` picks a free port, the KV-server idiom)."""

    def __init__(self, engine, port: int = 0,
                 request_timeout_s: float = 120.0):
        self._server = _Server(("0.0.0.0", port), _Handler)
        self._server.engine = engine
        self._server.request_timeout_s = float(request_timeout_s)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> int:
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="hvd_serve_http",
            daemon=True,
        )
        self._thread.start()
        return self.port

    def stop(self) -> None:
        self._server.shutdown()
        if self._thread:
            self._thread.join(timeout=5)
        self._server.server_close()
