"""``hvd.serve()`` — distributed inference serving (docs/serving.md).

The serving stack reuses the training fast path's machinery end to end:

- **Placement**: the model is TP-sharded by the SAME regex→PartitionSpec
  rule tables that place it for training (``parallel/rules.py``), and
  the paged KV cache by ``GPT_CACHE_RULES`` — both preflighted by the
  Pass 5 validator before the decode step is built.
- **Compute**: ``hvd.jax.make_decode_step`` compiles ONE batched
  one-token decode (``models/transformer.tp_decode_apply`` — the same
  one-psum-per-half-block Megatron structure as ``tp_apply``).
- **Scheduling**: a pure continuous batcher (:mod:`.batcher`) feeds DP
  replica loops (:mod:`.engine`); KV pages come from :mod:`.kvcache`.
- **Observability**: every request lands in the
  ``hvd_request_latency_seconds`` SLO histogram, the ``hvd_serve_*``
  gauges/counters (docs/metrics.md "Serving"), and an ``hvd_request``
  trace span (``tools/trace_merge.py``).
- **Chaos**: the ``request``/``replica`` fault sites (``fault/plan.py``)
  drop/delay requests and kill replicas mid-batch; the engine's ledger
  keeps every answer exactly-once.
- **Control**: ``run/selfdrive.ServeScalePolicy`` scales DP replicas
  out/in on queue depth and SLO burn (the spare-promotion /
  quarantine-shrink verbs applied to serving).

Entry points: :func:`serve` below (in-process), ``hvdrun --serve``
(launcher), ``python -m horovod_tpu.serve`` (standalone HTTP demo).
"""

from __future__ import annotations

from typing import Any, Optional

from .batcher import BatchDecision, ContinuousBatcher
from .engine import Completion, Request, ServeEngine
from .frontend import ServeFrontend
from .kvcache import (
    PagePool,
    PagePoolExhausted,
    decode_state_specs,
    make_decode_state,
    preflight_decode_state,
)

__all__ = [
    "BatchDecision",
    "Completion",
    "ContinuousBatcher",
    "PagePool",
    "PagePoolExhausted",
    "Request",
    "ServeEngine",
    "ServeFrontend",
    "ServeHandle",
    "decode_state_specs",
    "make_decode_state",
    "preflight_decode_state",
    "serve",
]


class ServeHandle:
    """What :func:`serve` returns: the engine plus (optionally) its HTTP
    frontend, with delegating conveniences so
    ``handle.submit(...); handle.result(...)`` reads naturally."""

    def __init__(self, engine: ServeEngine,
                 frontend: Optional[ServeFrontend] = None):
        self.engine = engine
        self.frontend = frontend

    @property
    def port(self) -> Optional[int]:
        return None if self.frontend is None else self.frontend.port

    def submit(self, prompt, max_tokens: int = 16,
               request_id: Optional[str] = None) -> str:
        return self.engine.submit(
            prompt, max_tokens=max_tokens, request_id=request_id
        )

    def result(self, request_id: str,
               timeout: Optional[float] = None) -> Completion:
        return self.engine.result(request_id, timeout=timeout)

    def drain(self, timeout: float = 60.0) -> None:
        self.engine.drain(timeout=timeout)

    def request_log(self):
        return self.engine.request_log()

    def stop(self) -> None:
        if self.frontend is not None:
            self.frontend.stop()
        self.engine.stop()

    def __enter__(self) -> "ServeHandle":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


def serve(
    params: Any,
    *,
    n_heads: int,
    mesh: Any = None,
    rules: Any = None,
    cache_rules: Any = None,
    config: Any = None,
    dtype: Any = None,
    scale_policy: Any = None,
    http: bool = False,
    request_timeout_s: float = 120.0,
) -> ServeHandle:
    """Stand up a serving engine over a :class:`TransformerLM` param
    tree. Model geometry (layer count, head dim, context length) is read
    off the live tree; every serving knob comes from the
    ``HOROVOD_SERVE_*`` environment via ``Config.from_env()`` (or an
    explicit ``config``). With ``mesh`` + ``rules`` the decode step runs
    TP-sharded (Pass 5 preflighted); ``http=True`` also binds the
    :class:`ServeFrontend` on ``config.serve_port`` (0 = pick a free
    port)."""
    import jax.numpy as jnp

    from ..common.env import Config
    from ..jax import make_decode_step
    from ..models.transformer import transformer_n_layers

    cfg = config if config is not None else Config.from_env()
    dtype = jnp.float32 if dtype is None else dtype
    emb = params["embeddings"]["embedding"]
    pos = params["pos_embeddings"]["embedding"]
    d_model = int(emb.shape[-1])
    if d_model % int(n_heads):
        raise ValueError(
            f"d_model {d_model} not divisible by n_heads {n_heads}"
        )
    head_dim = d_model // int(n_heads)
    max_context = min(
        int(pos.shape[0]),
        (int(cfg.serve_kv_pages) - 1) * int(cfg.serve_page_size),
    )
    step = make_decode_step(
        n_heads=int(n_heads), mesh=mesh, rules=rules,
        cache_rules=cache_rules, dtype=dtype,
    )
    engine = ServeEngine(
        params, step,
        n_layers=transformer_n_layers(params),
        n_heads=int(n_heads), head_dim=head_dim,
        num_pages=cfg.serve_kv_pages, page_size=cfg.serve_page_size,
        max_batch_size=cfg.serve_max_batch,
        max_wait_us=cfg.serve_max_wait_us,
        queue_bound=cfg.serve_queue_bound,
        max_context=max_context,
        replicas=cfg.serve_replicas,
        slo_ms=cfg.serve_slo_ms,
        scale_policy=scale_policy,
        cache_dtype=dtype,
    ).start()
    frontend = None
    if http:
        frontend = ServeFrontend(
            engine, port=cfg.serve_port,
            request_timeout_s=request_timeout_s,
        )
        frontend.start()
    return ServeHandle(engine, frontend)
