"""Continuous-batching policy — the pure decision core of ``hvd.serve()``.

:class:`ContinuousBatcher` is a policy object in the ``StragglerPolicy``
discipline (``run/selfdrive.py``): no wall clock, no threads, no jax —
every input is explicit (timestamps are caller-supplied microsecond
integers), so the max-wait/max-batch trade-off is unit-testable and the
fleet simulator (``sim/core.simulate_serve``) replays the exact shipping
policy under a virtual clock.

Dispatch rule (the classic continuous-batching contract):

- a batch becomes ready the moment ``max_batch_size`` requests are
  queued, **or**
- when the OLDEST queued request has waited ``max_wait_us`` — deadline
  on the head of a FIFO, which is the starvation-freedom bound: no
  request can wait more than ``max_wait_us`` beyond the front of the
  queue regardless of arrival pressure, because assembly is strictly
  oldest-first.

Admission is bounded by ``queue_bound``: :meth:`offer` refuses (returns
False) rather than queueing unboundedly — the engine surfaces that as an
HTTP 429 and the ``hvd_request_total{outcome="rejected"}`` counter.
Re-queued requests (a replica died mid-batch) re-enter at the FRONT via
:meth:`requeue`, keeping their original admission timestamps, so a
survivor of a replica kill does not go to the back of the line.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple


@dataclass(frozen=True)
class BatchDecision:
    """One :meth:`ContinuousBatcher.poll` verdict. ``ready`` batches
    carry the dispatched request ids (oldest first); ``reason`` is
    ``"full"`` / ``"deadline"`` for ready batches, ``"empty"`` /
    ``"waiting"`` otherwise."""

    ready: bool
    reason: str
    request_ids: Tuple[Any, ...] = ()


class ContinuousBatcher:
    """max-batch-size x max-wait-us continuous batcher (pure policy)."""

    def __init__(self, max_batch_size: int = 8, max_wait_us: int = 2000,
                 queue_bound: int = 1024):
        if max_batch_size < 1:
            raise ValueError(f"max_batch_size must be >= 1, got "
                             f"{max_batch_size}")
        if max_wait_us < 0:
            raise ValueError(f"max_wait_us must be >= 0, got {max_wait_us}")
        if queue_bound < 1:
            raise ValueError(f"queue_bound must be >= 1, got {queue_bound}")
        self.max_batch_size = int(max_batch_size)
        self.max_wait_us = int(max_wait_us)
        self.queue_bound = int(queue_bound)
        self._queue: List[Any] = []          # request ids, oldest first
        self._enqueued_us: Dict[Any, int] = {}

    @staticmethod
    def from_env(env: Optional[dict] = None) -> "ContinuousBatcher":
        import os

        from ..common import env as _env

        e = os.environ if env is None else env

        def _int(name: str, default: int) -> int:
            v = (e.get(name) or "").strip()
            try:
                return int(v) if v else default
            except ValueError:
                return default

        return ContinuousBatcher(
            max_batch_size=_int(_env.HOROVOD_SERVE_MAX_BATCH, 8),
            max_wait_us=_int(_env.HOROVOD_SERVE_MAX_WAIT_US, 2000),
            queue_bound=_int(_env.HOROVOD_SERVE_QUEUE_BOUND, 1024),
        )

    # ------------------------------------------------------------ queue
    def depth(self) -> int:
        return len(self._queue)

    def offer(self, request_id: Any, now_us: int) -> bool:
        """Admit one request at ``now_us``. False = queue bound hit (the
        caller must refuse the request loudly, not drop it silently)."""
        if request_id in self._enqueued_us:
            raise ValueError(f"request {request_id!r} is already queued")
        if len(self._queue) >= self.queue_bound:
            return False
        self._queue.append(request_id)
        self._enqueued_us[request_id] = int(now_us)
        return True

    def requeue(self, request_id: Any, enqueued_us: int) -> None:
        """Return an in-flight request to the FRONT of the queue (replica
        died mid-batch). Keeps the original admission timestamp so its
        max-wait deadline stays honest, and bypasses ``queue_bound`` —
        a re-queued request was already admitted once."""
        if request_id in self._enqueued_us:
            raise ValueError(f"request {request_id!r} is already queued")
        self._queue.insert(0, request_id)
        self._enqueued_us[request_id] = int(enqueued_us)

    def cancel(self, request_id: Any) -> bool:
        """Remove a queued request (client gone, injected drop)."""
        if request_id not in self._enqueued_us:
            return False
        self._queue.remove(request_id)
        del self._enqueued_us[request_id]
        return True

    def wait_us(self, request_id: Any, now_us: int) -> int:
        return int(now_us) - self._enqueued_us[request_id]

    # ----------------------------------------------------------- policy
    def poll(self, now_us: int, max_size: Optional[int] = None
             ) -> BatchDecision:
        """Assemble a batch at virtual time ``now_us``. Ready batches are
        REMOVED from the queue (single consumer per replica loop; the
        engine serializes pollers). ``max_size`` optionally caps the
        batch below ``max_batch_size`` (KV-page pressure)."""
        if not self._queue:
            return BatchDecision(False, "empty")
        cap = self.max_batch_size if max_size is None else max(
            1, min(int(max_size), self.max_batch_size)
        )
        if len(self._queue) >= cap:
            reason = "full"
        elif int(now_us) - self._enqueued_us[self._queue[0]] \
                >= self.max_wait_us:
            reason = "deadline"
        else:
            return BatchDecision(False, "waiting")
        ids = tuple(self._queue[:cap])
        del self._queue[:cap]
        for rid in ids:
            del self._enqueued_us[rid]
        return BatchDecision(True, reason, ids)

    def next_deadline_us(self) -> Optional[int]:
        """Virtual time at which the head of the queue forces a dispatch
        (None when empty) — what a real engine sleeps until and the
        simulator schedules its next dispatch event at."""
        if not self._queue:
            return None
        return self._enqueued_us[self._queue[0]] + self.max_wait_us
