"""The ``hvd.serve()`` engine: DP replicas over one continuous batcher.

Topology (docs/serving.md): ONE admission queue (the pure
:class:`~horovod_tpu.serve.batcher.ContinuousBatcher` under the engine's
condition variable) feeds N **replica loops**. Each replica owns a full
copy of the decode state — its own paged KV cache
(:func:`~horovod_tpu.serve.kvcache.make_decode_state`) and
:class:`~horovod_tpu.serve.kvcache.PagePool` — and runs the compiled
decode step (``hvd.jax.make_decode_step``: TP-sharded where a mesh is
given). Data parallelism in serving is REPLICA-level: replicas race on
the shared queue, which is exactly what makes mid-batch replica death
survivable.

Exactly-once is the engine's core invariant, held by one rule: a
request's completion is recorded under the engine lock the moment its
last token is produced, into a ledger that refuses duplicates. A
``kill_replica`` chaos fault (``fault/plan.py``, ``replica`` site)
surfaces as :class:`~horovod_tpu.fault.injector.ReplicaKilled` at the
replica loop boundary; the dying replica frees its batch's pages and
re-queues every NOT-yet-recorded batch member at the queue FRONT with
its original admission timestamp, then retires. A survivor replica picks
the work up; if the request had already been recorded, the ledger's
dedupe makes the re-queue a no-op. No request is ever answered twice,
none is ever lost.

Batches are padded to the fixed ``max_batch_size`` so the decode step
compiles ONCE: padded slots feed token 0 at position 0 through an
all-zeros page-table row — page 0 is the PagePool's reserved scratch
page, so padding can never touch a live request's cache.

Every request emits: the ``hvd_request_latency_seconds`` SLO histogram,
``hvd_request_total{outcome}``, per-batch ``hvd_serve_batch_occupancy``,
``hvd_serve_queue_depth`` / ``hvd_serve_kv_pages_in_use`` /
``hvd_serve_replicas`` gauges, ``hvd_serve_tokens_total``, and an
``hvd_request`` trace span (renderable by ``tools/trace_merge.py``).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .. import metrics as _metrics
from .. import trace as _trace
from ..fault import injector as _fault
from ..fault.injector import InjectedFault, ReplicaKilled
from .batcher import ContinuousBatcher
from .kvcache import PagePool, PagePoolExhausted, make_decode_state


@dataclass
class Request:
    """One admitted request (engine-internal bookkeeping)."""

    id: str
    prompt: Tuple[int, ...]
    max_tokens: int
    submit_t: float
    enqueued_us: int
    requeues: int = 0


@dataclass(frozen=True)
class Completion:
    """The answer ledgered for one request — recorded exactly once."""

    id: str
    prompt: Tuple[int, ...]
    tokens: Tuple[int, ...]
    outcome: str  # "ok" | "dropped" | "rejected"
    latency_s: float
    replica: Optional[int] = None


class _Replica:
    """One DP serving replica: its own KV cache + page pool + loop."""

    def __init__(self, idx: int, cache: Any, pool: PagePool):
        self.idx = idx
        self.cache = cache
        self.pool = pool
        self.pages: Dict[str, List[int]] = {}
        self.thread: Optional[threading.Thread] = None
        self.retired = False  # graceful scale-in flag
        self.alive = True


class ServeEngine:
    """Continuous-batching inference engine over DP decode replicas."""

    def __init__(
        self,
        params: Any,
        decode_step: Any,
        *,
        n_layers: int,
        n_heads: int,
        head_dim: int,
        num_pages: int = 256,
        page_size: int = 16,
        max_batch_size: int = 8,
        max_wait_us: int = 2000,
        queue_bound: int = 1024,
        max_context: int = 128,
        replicas: int = 1,
        slo_ms: float = 500.0,
        scale_policy: Any = None,
        cache_dtype: Any = None,
    ):
        self.params = params
        self.decode_step = decode_step
        self._cache_kw = dict(
            n_layers=int(n_layers), num_pages=int(num_pages),
            page_size=int(page_size), n_heads=int(n_heads),
            head_dim=int(head_dim), dtype=cache_dtype,
        )
        self.page_size = int(page_size)
        self.num_pages = int(num_pages)
        self.max_batch_size = int(max_batch_size)
        self.max_context = int(max_context)
        self.slo_s = float(slo_ms) / 1000.0
        self.scale_policy = scale_policy
        self._table_width = max(
            1, -(-self.max_context // self.page_size)
        )
        self._cond = threading.Condition()
        self._batcher = ContinuousBatcher(
            max_batch_size=max_batch_size, max_wait_us=max_wait_us,
            queue_bound=queue_bound,
        )
        self._requests: Dict[str, Request] = {}
        self._done: Dict[str, Completion] = {}
        self._done_events: Dict[str, threading.Event] = {}
        self._replicas: List[_Replica] = []
        self._n_initial = max(int(replicas), 1)
        self._next_id = 0
        self._stopping = False
        self._t0 = time.monotonic()
        # Autoscale beat accumulators (drained by autoscale_beat()).
        self._slo_violations_since = 0
        self._completions_since = 0
        # Chaos observability (asserted by tools/serve_smoke.py).
        self.requeues = 0
        # Occupancy accounting (bench.py --serve reports the mean).
        self.batches = 0
        self.batched_requests = 0

    # --------------------------------------------------------- lifecycle
    def start(self) -> "ServeEngine":
        for _ in range(self._n_initial):
            self.add_replica()
        return self

    def stop(self) -> None:
        with self._cond:
            self._stopping = True
            self._cond.notify_all()
        for rep in list(self._replicas):
            if rep.thread is not None:
                rep.thread.join(timeout=30)

    def __enter__(self) -> "ServeEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def add_replica(self) -> int:
        """Spawn one more DP replica (the autoscaler's spare-promotion
        verb; also how capacity returns after a chaos kill)."""
        with self._cond:
            idx = len(self._replicas)
            rep = _Replica(
                idx,
                make_decode_state(**self._cache_kw),
                PagePool(self.num_pages, self.page_size),
            )
            self._replicas.append(rep)
        rep.thread = threading.Thread(
            target=self._replica_loop, args=(rep,),
            name=f"hvd_serve_replica{idx}", daemon=True,
        )
        rep.thread.start()
        self._set_replica_gauge()
        return idx

    def retire_replica(self) -> Optional[int]:
        """Gracefully retire the newest live replica (the autoscaler's
        quarantine-shrink verb): it finishes its current batch, then
        exits. Refuses to retire the last replica."""
        with self._cond:
            live = [r for r in self._replicas if r.alive and not r.retired]
            if len(live) <= 1:
                return None
            rep = live[-1]
            rep.retired = True
            self._cond.notify_all()
            return rep.idx

    def live_replicas(self) -> int:
        with self._cond:
            return sum(
                1 for r in self._replicas if r.alive and not r.retired
            )

    # -------------------------------------------------------- admission
    def _now_us(self) -> int:
        return int((time.monotonic() - self._t0) * 1e6)

    def submit(self, prompt: Sequence[int],
               max_tokens: int = 16,
               request_id: Optional[str] = None) -> str:
        """Admit one request. Always returns the request id; a refused
        request (queue bound → ``rejected``, injected chaos →
        ``dropped``) is ledgered immediately with that outcome, so every
        submitted id resolves through :meth:`result` exactly once."""
        prompt = tuple(int(t) for t in prompt)
        max_tokens = int(max_tokens)
        if not prompt:
            raise ValueError("empty prompt")
        if max_tokens < 1:
            raise ValueError(f"max_tokens must be >= 1, got {max_tokens}")
        if len(prompt) + max_tokens > self.max_context:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_tokens ({max_tokens}) "
                f"exceeds max_context {self.max_context}"
            )
        with self._cond:
            if request_id is None:
                request_id = f"req{self._next_id}"
                self._next_id += 1
            rid = str(request_id)
            if rid in self._requests:
                raise ValueError(f"duplicate request id {rid!r}")
            req = Request(
                id=rid, prompt=prompt, max_tokens=max_tokens,
                submit_t=time.time(), enqueued_us=self._now_us(),
            )
            self._requests[rid] = req
            self._done_events[rid] = threading.Event()
        if _fault.ACTIVE:
            try:
                # Chaos tap, 'request' site: 'delay' sleeps here (pure
                # queueing latency), 'drop' discards the request — but
                # it is still ANSWERED, with outcome "dropped".
                _fault.fault_point("request", rid)
            except InjectedFault:
                self._finish(None, req, (), "dropped")
                return rid
        with self._cond:
            if not self._batcher.offer(rid, req.enqueued_us):
                self._requests[rid] = req  # keep for the ledger
                admitted = False
            else:
                admitted = True
                self._cond.notify_all()
            self._gauge("hvd_serve_queue_depth", self._batcher.depth())
        if not admitted:
            self._finish(None, req, (), "rejected")
        return rid

    def result(self, request_id: str,
               timeout: Optional[float] = None) -> Completion:
        ev = self._done_events[str(request_id)]
        if not ev.wait(timeout):
            raise TimeoutError(f"request {request_id!r} not finished")
        return self._done[str(request_id)]

    def drain(self, timeout: float = 60.0) -> None:
        """Block until every submitted request is ledgered."""
        deadline = time.monotonic() + timeout
        for rid, ev in list(self._done_events.items()):
            if not ev.wait(max(0.0, deadline - time.monotonic())):
                raise TimeoutError(f"request {rid!r} not finished")

    def request_log(self) -> Dict[str, Dict[str, Any]]:
        """The normalized request ledger the chaos smoke byte-compares
        across seeded runs: completions keyed by id, no timing."""
        with self._cond:
            return {
                rid: {
                    "prompt": list(c.prompt),
                    "completion": list(c.tokens),
                    "outcome": c.outcome,
                }
                for rid, c in sorted(self._done.items())
            }

    # ------------------------------------------------------ replica loop
    def _replica_loop(self, rep: _Replica) -> None:
        try:
            while True:
                with self._cond:
                    if self._stopping or rep.retired:
                        break
                    now = self._now_us()
                    decision = self._batcher.poll(now)
                    if not decision.ready:
                        wait_s = 0.005
                        if decision.reason == "waiting":
                            dl = self._batcher.next_deadline_us()
                            if dl is not None:
                                wait_s = max((dl - now) / 1e6, 0.0005)
                        self._cond.wait(wait_s)
                        continue
                    batch, starved = self._admit_pages(
                        rep, decision.request_ids
                    )
                    self._gauge(
                        "hvd_serve_queue_depth", self._batcher.depth()
                    )
                if not batch:
                    if starved:
                        # Pool exhausted: wait for completions to free
                        # pages rather than spinning on the same head.
                        with self._cond:
                            self._cond.wait(0.002)
                    continue
                try:
                    if _fault.ACTIVE:
                        # Chaos tap, 'replica' site: one hit per
                        # dispatched batch → kill_replica aborts this
                        # replica MID-BATCH, in-flight work re-queued.
                        _fault.fault_point("replica", f"replica{rep.idx}")
                    self._run_batch(rep, batch)
                except ReplicaKilled:
                    self._on_replica_killed(rep, batch)
                    return
        finally:
            with self._cond:
                rep.alive = False
                self._cond.notify_all()
            self._set_replica_gauge()

    def _admit_pages(
        self, rep: _Replica, ids: Tuple[str, ...]
    ) -> Tuple[List[Request], bool]:
        """Grant KV pages for a dequeued batch (caller holds the lock).
        Members the pool cannot cover go back to the queue FRONT in
        order — admission pressure is back-pressure, never loss."""
        batch: List[Request] = []
        starved: List[Request] = []
        for rid in ids:
            req = self._requests[rid]
            need = len(req.prompt) + req.max_tokens
            try:
                rep.pages[rid] = rep.pool.alloc(need, owner=rid)
                batch.append(req)
            except PagePoolExhausted:
                starved.append(req)
        for req in reversed(starved):
            self._batcher.requeue(req.id, req.enqueued_us)
        self._gauge("hvd_serve_kv_pages_in_use", self._pages_in_use())
        return batch, bool(starved)

    def _run_batch(self, rep: _Replica, batch: List[Request]) -> None:
        import numpy as np

        B = self.max_batch_size
        page_table = np.zeros((B, self._table_width), dtype=np.int32)
        tokens = np.zeros((B,), dtype=np.int32)
        positions = np.zeros((B,), dtype=np.int32)
        seqs = [list(r.prompt) for r in batch]
        pos = [0] * len(batch)
        active = [True] * len(batch)
        for i, r in enumerate(batch):
            pages = rep.pages[r.id]
            page_table[i, : len(pages)] = pages
        with self._cond:
            self.batches += 1
            self.batched_requests += len(batch)
        if _metrics.ACTIVE:
            _metrics.TAP.set("hvd_serve_batch_occupancy", len(batch))
        while any(active):
            for i in range(len(batch)):
                tokens[i] = seqs[i][pos[i]] if active[i] else 0
                positions[i] = pos[i] if active[i] else 0
            out, rep.cache = self.decode_step(
                self.params, rep.cache, tokens, positions, page_table
            )
            out = np.asarray(out)
            for i, r in enumerate(batch):
                if not active[i]:
                    continue
                if pos[i] == len(seqs[i]) - 1:
                    seqs[i].append(int(out[i]))
                pos[i] += 1
                if len(seqs[i]) - len(r.prompt) >= r.max_tokens:
                    active[i] = False
                    page_table[i, :] = 0  # slot back to scratch
                    self._finish(
                        rep, r, tuple(seqs[i][len(r.prompt):]), "ok"
                    )

    def _on_replica_killed(self, rep: _Replica,
                           batch: List[Request]) -> None:
        """The exactly-once half of chaos: free the dead batch's pages,
        re-queue every member whose answer is NOT yet ledgered at the
        queue front (original timestamps), retire the replica."""
        with self._cond:
            back = [r for r in batch if r.id not in self._done]
            for r in batch:
                pages = rep.pages.pop(r.id, None)
                if pages is not None:
                    rep.pool.free(pages)
            for r in reversed(back):
                r.requeues += 1
                self._batcher.requeue(r.id, r.enqueued_us)
            self.requeues += len(back)
            rep.retired = True
            self._cond.notify_all()
        if _metrics.ACTIVE:
            _metrics.TAP.inc("hvd_serve_requeues_total", len(back))
        if _trace.ACTIVE:
            _trace.TAP.event(
                "hvd_serve_replica_killed", cat="serve",
                replica=rep.idx, requeued=len(back),
            )
        self._set_replica_gauge()

    # --------------------------------------------------------- recording
    def _finish(self, rep: Optional[_Replica], req: Request,
                tokens: Tuple[int, ...], outcome: str) -> None:
        with self._cond:
            if req.id in self._done:
                return  # exactly-once: a duplicate answer is dropped here
            if rep is not None:
                pages = rep.pages.pop(req.id, None)
                if pages is not None:
                    rep.pool.free(pages)
            latency = time.time() - req.submit_t
            comp = Completion(
                id=req.id, prompt=req.prompt, tokens=tokens,
                outcome=outcome, latency_s=latency,
                replica=None if rep is None else rep.idx,
            )
            self._done[req.id] = comp
            if outcome == "ok":
                self._completions_since += 1
                if latency > self.slo_s:
                    self._slo_violations_since += 1
            self._cond.notify_all()
        if _metrics.ACTIVE:
            _metrics.TAP.observe("hvd_request_latency_seconds", latency)
            _metrics.TAP.inc("hvd_request_total", outcome=outcome)
            if tokens:
                _metrics.TAP.inc("hvd_serve_tokens_total", len(tokens))
            _metrics.TAP.set(
                "hvd_serve_kv_pages_in_use", self._pages_in_use()
            )
        if _trace.ACTIVE:
            _trace.TAP.event(
                "hvd_request", ph="X", cat="request", ts=req.submit_t,
                dur=latency, request_id=req.id, outcome=outcome,
                tokens=len(tokens), requeues=req.requeues,
            )
        self._done_events[req.id].set()

    # --------------------------------------------------------- autoscale
    def autoscale_beat(self) -> Optional[Any]:
        """Feed one beat to the :class:`ServeScalePolicy` (queue depth,
        SLO burn since the last beat) and APPLY its verdict: scale-out
        promotes a fresh replica, scale-in retires one. Returns the
        decision (None without a policy or verdict)."""
        if self.scale_policy is None:
            return None
        with self._cond:
            depth = self._batcher.depth()
            viol, comps = self._slo_violations_since, self._completions_since
            self._slo_violations_since = 0
            self._completions_since = 0
        self.scale_policy.observe(depth, viol, comps)
        decision = self.scale_policy.decide(self.live_replicas())
        if decision is None:
            return None
        if _metrics.ACTIVE:
            _metrics.TAP.inc(
                "hvd_serve_scale_decisions_total", action=decision.action
            )
        # Application goes through the elastic verbs so serving resizes
        # land in the same deterministic event ledger as training
        # membership changes (docs/serving.md "Autoscale").
        from .. import elastic as _elastic

        _elastic.apply_serve_scale(self, decision)
        return decision

    # ------------------------------------------------------------ gauges
    def _pages_in_use(self) -> int:
        return sum(r.pool.pages_in_use for r in self._replicas if r.alive)

    def _set_replica_gauge(self) -> None:
        if _metrics.ACTIVE:
            _metrics.TAP.set("hvd_serve_replicas", self.live_replicas())

    def _gauge(self, name: str, value: float) -> None:
        if _metrics.ACTIVE:
            _metrics.TAP.set(name, value)
