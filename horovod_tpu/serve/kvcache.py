"""Paged decode-state (KV-cache) for ``hvd.serve()``.

Two halves, cleanly split:

- :class:`PagePool` — the pure allocator. Fixed-size pages are granted
  and returned per request slot; exhaustion REFUSES loudly
  (:class:`PagePoolExhausted`) instead of over-committing, and the
  refusal is all-or-nothing so a half-admitted request can never leak
  pages. Page 0 is reserved as the scratch page padded (inactive) batch
  slots write into, so padding can never corrupt a live request's cache.

- :func:`make_decode_state` — the decode-state pytree: per layer,
  ``block_i/attention/cache_k`` / ``cache_v`` buffers of shape
  ``[num_pages, page_size, n_heads, head_dim]``. The names are chosen so
  the SAME regex→PartitionSpec machinery that places the params places
  the cache (``parallel/rules.GPT_CACHE_RULES`` shards the head dim over
  the "model" axis), and :func:`preflight_decode_state` runs the Pass 5
  validator over (cache rules, mesh, cache tree) before the decode step
  is ever built — a typo'd axis or a non-divisible head count fails at
  build time with a named finding, the composed-path discipline.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence


class PagePoolExhausted(RuntimeError):
    """KV-cache page allocation refused: the pool cannot cover the
    request. The engine keeps the request QUEUED (admission pressure is
    back-pressure, not data loss) and the batcher caps batch size to
    what the pool can hold."""


class PagePool:
    """Fixed-size KV-cache page allocator (pure python, no jax)."""

    #: index of the scratch page padded batch slots write into; never
    #: granted to a request.
    SCRATCH_PAGE = 0

    def __init__(self, num_pages: int, page_size: int):
        if num_pages < 2:
            raise ValueError(
                f"num_pages must be >= 2 (page 0 is the reserved scratch "
                f"page), got {num_pages}"
            )
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        # LIFO free list, deterministic: page ids descend so the first
        # alloc after construction is [1, 2, ...].
        self._free: List[int] = list(range(self.num_pages - 1, 0, -1))
        self._owner: Dict[int, Any] = {}

    # ------------------------------------------------------------ sizes
    @property
    def pages_free(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return (self.num_pages - 1) - len(self._free)

    def pages_for(self, tokens: int) -> int:
        """Pages needed to hold ``tokens`` cache positions."""
        return max(1, -(-int(tokens) // self.page_size))

    def can_admit(self, tokens: int) -> bool:
        return self.pages_for(tokens) <= self.pages_free

    # ------------------------------------------------------------ alloc
    def alloc(self, tokens: int, owner: Any = None) -> List[int]:
        """Grant the pages for a ``tokens``-position slot, all or
        nothing. Raises :class:`PagePoolExhausted` (pool unchanged) when
        the request cannot be covered."""
        n = self.pages_for(tokens)
        if n > len(self._free):
            raise PagePoolExhausted(
                f"KV-cache pool exhausted: request needs {n} pages "
                f"({tokens} tokens x page_size {self.page_size}) but only "
                f"{len(self._free)}/{self.num_pages - 1} are free"
            )
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self._owner[p] = owner
        return pages

    def free(self, pages: Sequence[int]) -> None:
        """Return a slot's pages. Double-free and foreign pages raise —
        a silent accounting error here becomes silent cross-request
        cache corruption."""
        for p in pages:
            if p not in self._owner:
                raise ValueError(
                    f"page {p} is not allocated (double free or foreign "
                    f"page)"
                )
        for p in pages:
            del self._owner[p]
            self._free.append(p)


# ---------------------------------------------------------- decode state
def make_decode_state(
    n_layers: int,
    *,
    num_pages: int,
    page_size: int,
    n_heads: int,
    head_dim: int,
    dtype: Any = None,
) -> Dict[str, Any]:
    """The paged decode-state pytree: per layer, zeroed
    ``cache_k``/``cache_v`` of shape [num_pages, page_size, n_heads,
    head_dim]. Leaf NAMES mirror the param tree's ``block_i/attention/``
    namespace so the rules engine places them by regex."""
    import jax.numpy as jnp

    dtype = jnp.bfloat16 if dtype is None else dtype
    shape = (int(num_pages), int(page_size), int(n_heads), int(head_dim))
    return {
        f"block_{i}": {
            "attention": {
                "cache_k": jnp.zeros(shape, dtype),
                "cache_v": jnp.zeros(shape, dtype),
            }
        }
        for i in range(int(n_layers))
    }


def decode_state_specs(cache_rules: Any, cache: Any) -> Any:
    """PartitionSpec tree for a decode state from a cache-rule table
    (first-match-wins, the param discipline)."""
    from ..parallel.rules import match_partition_rules

    return match_partition_rules(cache_rules, cache)


def preflight_decode_state(cache_rules: Any, mesh: Any, cache: Any,
                           *, suppress: Optional[Sequence[str]] = None
                           ) -> None:
    """Pass 5 over (cache rules, mesh, concrete cache tree) — ALWAYS
    enforced before a sharded decode step is built, exactly like the
    param table's preflight in the composed train path."""
    from ..parallel.rules import preflight_rules

    preflight_rules(cache_rules, mesh, cache, suppress=suppress)
