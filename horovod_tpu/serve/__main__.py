"""``python -m horovod_tpu.serve`` — the default ``hvdrun --serve``
command: initialize a (demo-sized) TransformerLM, stand up the engine +
HTTP frontend on ``HOROVOD_SERVE_PORT``, and serve until interrupted.

Demo geometry is env-tunable (``HVD_SERVE_DEMO_*``) so the same entry
point drives both the chaos smoke and a by-hand curl session; real
deployments call :func:`horovod_tpu.serve.serve` with their own params
and rule tables.
"""

from __future__ import annotations

import os
import sys
import time


def main(argv=None) -> int:
    import jax
    import jax.numpy as jnp

    from ..models.transformer import TransformerLM
    from ..run.selfdrive import ServeScalePolicy
    from . import serve

    vocab = int(os.environ.get("HVD_SERVE_DEMO_VOCAB", "128"))
    d_model = int(os.environ.get("HVD_SERVE_DEMO_D_MODEL", "64"))
    n_heads = int(os.environ.get("HVD_SERVE_DEMO_HEADS", "4"))
    n_layers = int(os.environ.get("HVD_SERVE_DEMO_LAYERS", "2"))
    max_len = int(os.environ.get("HVD_SERVE_DEMO_MAX_LEN", "128"))
    seed = int(os.environ.get("HVD_SERVE_DEMO_SEED", "0"))

    model = TransformerLM(vocab_size=vocab, d_model=d_model,
                          n_heads=n_heads, n_layers=n_layers,
                          max_len=max_len)
    params = model.init(
        jax.random.PRNGKey(seed), jnp.zeros((1, max_len), jnp.int32)
    )["params"]
    handle = serve(
        params, n_heads=n_heads, http=True,
        scale_policy=ServeScalePolicy.from_env(),
    )
    print(
        f"hvd.serve: listening on :{handle.port} "
        f"(replicas={handle.engine.live_replicas()}, "
        f"vocab={vocab}, d_model={d_model}, heads={n_heads}, "
        f"layers={n_layers})",
        flush=True,
    )
    try:
        while True:
            time.sleep(1.0)
            handle.engine.autoscale_beat()
    except KeyboardInterrupt:
        pass
    finally:
        handle.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
