"""Core types shared across the framework.

TPU-native re-design of the reference's ``horovod/common/common.h:104-250``
(``Status``, ``StatusType``, dtype enumeration, ``TensorTableEntry``). Rather
than abstract Tensor/OpContext adapters per framework, the TPU build keeps a
single canonical array representation (``jax.Array`` / ``numpy.ndarray``) and
lets framework bindings convert at the boundary.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Sequence, Tuple


class StatusType(enum.IntEnum):
    # Mirrors reference horovod/common/common.h:96-98 (OK/UNKNOWN_ERROR/
    # PRECONDITION_ERROR/ABORTED/INVALID_ARGUMENT/IN_PROGRESS).
    OK = 0
    UNKNOWN_ERROR = 1
    PRECONDITION_ERROR = 2
    ABORTED = 3
    INVALID_ARGUMENT = 4
    IN_PROGRESS = 5


@dataclass(frozen=True)
class Status:
    type: StatusType = StatusType.OK
    reason: str = ""

    def ok(self) -> bool:
        return self.type == StatusType.OK

    def in_progress(self) -> bool:
        return self.type == StatusType.IN_PROGRESS

    def timed_out(self) -> bool:
        """A wait gave up while the operation was still in progress: the
        type stays IN_PROGRESS (the op may yet complete) but the reason
        carries the diagnostic (tensor name, configured timeout)."""
        return self.type == StatusType.IN_PROGRESS and bool(self.reason)

    @staticmethod
    def OK() -> "Status":  # noqa: N802 - parity with reference naming
        return Status(StatusType.OK)

    @staticmethod
    def UnknownError(msg: str) -> "Status":  # noqa: N802
        return Status(StatusType.UNKNOWN_ERROR, msg)

    @staticmethod
    def PreconditionError(msg: str) -> "Status":  # noqa: N802
        return Status(StatusType.PRECONDITION_ERROR, msg)

    @staticmethod
    def Aborted(msg: str) -> "Status":  # noqa: N802
        return Status(StatusType.ABORTED, msg)

    @staticmethod
    def InvalidArgument(msg: str) -> "Status":  # noqa: N802
        return Status(StatusType.INVALID_ARGUMENT, msg)

    @staticmethod
    def InProgress() -> "Status":  # noqa: N802
        return Status(StatusType.IN_PROGRESS)

    @staticmethod
    def TimedOut(msg: str) -> "Status":  # noqa: N802
        return Status(StatusType.IN_PROGRESS, msg)


# Shutdown message text, parity with reference common.h:153-158.
SHUT_DOWN_ERROR = Status.Aborted(
    "Horovod has been shut down. This was caused by an exception on one of "
    "the ranks or an attempt to allreduce, allgather or broadcast a tensor "
    "after one of the ranks finished execution."
)

DUPLICATE_NAME_ERROR_FMT = (
    "Requested to {op} a tensor with the same name as another tensor that is "
    "currently being processed. If you want to request another tensor, use a "
    "different tensor name."
)


class DataType(enum.IntEnum):
    """Wire dtype enum; values align with reference message.h:27-41."""

    UINT8 = 0
    INT8 = 1
    UINT16 = 2
    INT16 = 3
    INT32 = 4
    INT64 = 5
    FLOAT16 = 6
    FLOAT32 = 7
    FLOAT64 = 8
    BOOL = 9
    # TPU-native additions (not in the reference wire format):
    BFLOAT16 = 10
    COMPLEX64 = 11


_NP_NAME_TO_DTYPE = {
    "uint8": DataType.UINT8,
    "int8": DataType.INT8,
    "uint16": DataType.UINT16,
    "int16": DataType.INT16,
    "int32": DataType.INT32,
    "int64": DataType.INT64,
    "float16": DataType.FLOAT16,
    "float32": DataType.FLOAT32,
    "float64": DataType.FLOAT64,
    "bool": DataType.BOOL,
    "bfloat16": DataType.BFLOAT16,
    "complex64": DataType.COMPLEX64,
}

_DTYPE_TO_NP_NAME = {v: k for k, v in _NP_NAME_TO_DTYPE.items()}

_DTYPE_SIZE = {
    DataType.UINT8: 1,
    DataType.INT8: 1,
    DataType.UINT16: 2,
    DataType.INT16: 2,
    DataType.INT32: 4,
    DataType.INT64: 8,
    DataType.FLOAT16: 2,
    DataType.FLOAT32: 4,
    DataType.FLOAT64: 8,
    DataType.BOOL: 1,
    DataType.BFLOAT16: 2,
    DataType.COMPLEX64: 8,
}


def dtype_from_array(array: Any) -> DataType:
    name = str(array.dtype)
    try:
        return _NP_NAME_TO_DTYPE[name]
    except KeyError:
        raise ValueError(f"Unsupported dtype for collective: {name}") from None


def dtype_size(dtype: DataType) -> int:
    return _DTYPE_SIZE[dtype]


def dtype_name(dtype: DataType) -> str:
    return _DTYPE_TO_NP_NAME[dtype]


class ReduceOp(enum.IntEnum):
    """Reduction ops exposed at the public API.

    Average/Sum/Adasum mirror the reference's enum
    (``horovod/common/operations.cc:771-779`` horovod_reduce_op_* and
    ``horovod/torch/mpi_ops.py`` Average/Sum/Adasum). Min/Max/Product are
    TPU-native extensions (XLA gives them for free).
    """

    AVERAGE = 1
    SUM = 2
    ADASUM = 3
    MIN = 4
    MAX = 5
    PRODUCT = 6


# Public aliases, parity with hvd.Average / hvd.Sum / hvd.Adasum.
Average = ReduceOp.AVERAGE
Sum = ReduceOp.SUM
Adasum = ReduceOp.ADASUM
Min = ReduceOp.MIN
Max = ReduceOp.MAX
Product = ReduceOp.PRODUCT


class RequestType(enum.IntEnum):
    # Parity with reference message.h:48-50 plus TPU-native ALLTOALL /
    # REDUCESCATTER extensions.
    ALLREDUCE = 0
    ALLGATHER = 1
    BROADCAST = 2
    JOIN = 3
    ALLTOALL = 4
    REDUCESCATTER = 5
    ADASUM = 6


class ResponseType(enum.IntEnum):
    # Parity with reference message.h:131-136.
    ALLREDUCE = 0
    ALLGATHER = 1
    BROADCAST = 2
    JOIN = 3
    ALLTOALL = 4
    REDUCESCATTER = 5
    ADASUM = 6
    ERROR = 7


@dataclass
class TensorTableEntry:
    """One pending named-tensor submission.

    Parity with reference ``common.h:209-234`` but holds a framework-neutral
    array plus the completion callback; device readiness events are not needed
    (JAX arrays are ready-by-construction once dispatched; the executor calls
    ``block_until_ready`` where required).
    """

    name: str
    tensor: Any  # jax.Array | np.ndarray
    root_rank: int = -1
    device: int = -1
    callback: Optional[Callable[[Status, Any], None]] = None
    reduce_op: ReduceOp = ReduceOp.SUM
    prescale_factor: float = 1.0
    postscale_factor: float = 1.0
    # Output slot filled by the executor (for async handles).
    output: Any = None
    context: dict = field(default_factory=dict)


@dataclass(frozen=True)
class TensorShape:
    dims: Tuple[int, ...]

    @staticmethod
    def of(array: Any) -> "TensorShape":
        return TensorShape(tuple(int(d) for d in array.shape))

    def num_elements(self) -> int:
        n = 1
        for d in self.dims:
            n *= d
        return n

    def __str__(self) -> str:
        return "[" + ", ".join(str(d) for d in self.dims) + "]"
