"""JAX version-compatibility helpers shared by ops/ and parallel/.

``jax.lax.axis_size`` only exists in newer jax; on older versions the
static-size idiom is ``lax.psum(1, axis_name)``, which constant-folds to a
Python int at trace time for a bound named axis (so it remains usable in
Python-level loops like the butterfly/binomial schedules).
"""

from __future__ import annotations

from jax import lax


def axis_size(axis_name) -> int:
    """Size of a bound named mesh axis (or product over a tuple of axes),
    as a static Python int inside a trace."""
    size_fn = getattr(lax, "axis_size", None)
    if size_fn is not None:
        return size_fn(axis_name)
    return lax.psum(1, axis_name)


def _make_psum_identity_bwd():
    import functools

    import jax

    @functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
    def psum_identity_bwd(x, axis_name):
        return lax.psum(x, axis_name)

    def fwd(x, axis_name):
        return lax.psum(x, axis_name), None

    def bwd(axis_name, _res, ct):
        return (ct,)

    psum_identity_bwd.defvjp(fwd, bwd)
    return psum_identity_bwd


_psum_identity_bwd = None


def psum_replicated_grad(x, axis_name):
    """``lax.psum`` whose transpose treats the cotangent as replicated
    (identity backward) — the behavior newer jax's vma rewrite produces
    for the share-then-reduce idiom (``psum(x * mask, axis)`` whose
    output feeds a replicated loss). On old jax the builtin transpose is
    ``psum(ct)``, which multiplies every upstream gradient by the axis
    size; this wrapper restores the correct cotangent. Only use when the
    consumer of the psum result is SPMD-identical across the axis (a
    replicated loss), which makes the cotangent replicated."""
    if not needs_explicit_grad_reduce():
        return lax.psum(x, axis_name)
    global _psum_identity_bwd
    if _psum_identity_bwd is None:
        _psum_identity_bwd = _make_psum_identity_bwd()
    return _psum_identity_bwd(x, axis_name)


def needs_explicit_grad_reduce() -> bool:
    """True on old jax (pre-vma shard_map): the checked transpose does
    NOT psum the cotangent of a replicated-in parameter over the axes it
    is invariant on — the caller must reduce explicitly. Newer jax's
    varying-manifest-axes machinery inserts that psum itself (an explicit
    one would double-count)."""
    return not (hasattr(lax, "pcast") or hasattr(lax, "pvary"))


def grad_psum(tree, axis_names):
    """Explicit data-parallel cotangent reduction for old jax; identity
    on new jax (see :func:`needs_explicit_grad_reduce`)."""
    if not needs_explicit_grad_reduce():
        return tree
    import jax

    return jax.tree.map(lambda g: lax.psum(g, axis_names), tree)


def assert_replicated(tree, axis_names):
    """Give every leaf of ``tree`` a replicated typing over ``axis_names``
    for old-jax replication-checked shard_map bodies.

    Newer jax's varying-manifest-axes tracking infers replication through
    optax/scan bodies on its own; the old ``check_rep`` checker cannot,
    and rejects out_specs that omit an axis it failed to prove. On old
    jax each leaf is washed through ``lax.pmax`` over the axes — the
    identity for values that are in fact equal across those ranks (which
    the callers guarantee: gradients were already psummed over every
    invariant axis by the checked transpose), dtype-preserving for ints
    (optimizer step counters), and rep-typed as replicated. On new jax
    this is a no-op. Only call on values that ARE replicated — the wash
    would silently pick the max of genuinely divergent shards."""
    if hasattr(lax, "pcast") or hasattr(lax, "pvary"):
        return tree
    import jax

    return jax.tree.map(lambda t: lax.pmax(t, axis_names), tree)
