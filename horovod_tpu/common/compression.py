"""Gradient compression algorithms.

Parity with ``horovod/tensorflow/compression.py:46-74`` /
``horovod/torch/compression.py``: an on-the-wire fp16 cast (compress before
the collective, decompress after). TPU-native addition: bf16 compression,
which is the natural TPU wire format (same exponent range as fp32, MXU
native).
"""

from __future__ import annotations

from typing import Any, Tuple


class Compressor:
    """Interface for compressing and decompressing a given tensor."""

    @staticmethod
    def compress(tensor: Any) -> Tuple[Any, Any]:
        """Returns (compressed_tensor, context) for decompression."""
        raise NotImplementedError

    @staticmethod
    def decompress(tensor: Any, ctx: Any) -> Any:
        raise NotImplementedError


class NoneCompressor(Compressor):
    """Default no-op compression."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class _CastCompressor(Compressor):
    _wire_dtype: str = "float16"

    @classmethod
    def compress(cls, tensor):
        dtype = tensor.dtype
        compressible = str(dtype) in ("float32", "float64", "torch.float32", "torch.float64")
        if compressible:
            if hasattr(tensor, "astype"):
                tensor = tensor.astype(cls._wire_dtype)
            else:  # torch tensor
                tensor = tensor.half() if cls._wire_dtype == "float16" else tensor.bfloat16()
        return tensor, dtype

    @classmethod
    def decompress(cls, tensor, ctx):
        dtype = ctx
        if dtype is not None and str(tensor.dtype) != str(dtype):
            if hasattr(tensor, "astype"):
                tensor = tensor.astype(dtype)
            else:  # torch tensor
                tensor = tensor.to(dtype)
        return tensor


class FP16Compressor(_CastCompressor):
    """Cast fp32/fp64 to fp16 for the collective (reference
    ``compression.py:46-66``)."""

    _wire_dtype = "float16"


class BF16Compressor(_CastCompressor):
    """TPU-native: cast to bfloat16 on the wire (no reference equivalent;
    preferred on TPU where bf16 collectives run at full ICI rate with fp32
    exponent range)."""

    _wire_dtype = "bfloat16"


class Compression:
    """Optional gradient compression algorithm used during allreduce
    (API parity with ``hvd.Compression``)."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
