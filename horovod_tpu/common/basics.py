"""ctypes binding to the native control-plane core (libhvd_core.so).

Parity with the reference's ``horovod/common/basics.py`` (HorovodBasics
loading the C library and exposing init/rank/size/...), extended with the
plan-queue handshake: the native core negotiates/fuses/caches and emits
execution plans; Python executes them on the XLA data plane and reports
completion.
"""

from __future__ import annotations

import ctypes
import json
import os
import subprocess
from typing import Any, List, Optional

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_CPP_DIR = os.path.join(_REPO_ROOT, "cpp")
_LIB_PATH = os.path.join(_CPP_DIR, "libhvd_core.so")

_lib: Optional[ctypes.CDLL] = None


class NativeCoreUnavailable(RuntimeError):
    pass


def ensure_built(rebuild: bool = False) -> str:
    """Build libhvd_core.so with make if it is missing."""
    if rebuild or not os.path.exists(_LIB_PATH):
        try:
            subprocess.run(
                ["make", "-C", _CPP_DIR], check=True, capture_output=True
            )
        except (subprocess.CalledProcessError, OSError) as e:
            out = getattr(e, "stderr", b"") or b""
            raise NativeCoreUnavailable(
                f"failed to build native core: {out.decode()[:500]}"
            ) from e
    return _LIB_PATH


def load() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    path = ensure_built()
    lib = ctypes.CDLL(path)
    lib.hvd_core_init.restype = ctypes.c_int
    lib.hvd_core_init.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_double, ctypes.c_longlong,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p,
        ctypes.c_int, ctypes.c_int,
        ctypes.c_char_p, ctypes.c_int,
    ]
    lib.hvd_core_shutdown.restype = None
    # Older prebuilt cores may predate the flush-hint export.
    if hasattr(lib, "hvd_core_flush_hint"):
        lib.hvd_core_flush_hint.restype = None
    lib.hvd_core_initialized.restype = ctypes.c_int
    for fn in ("rank", "size", "local_rank", "local_size", "cross_rank",
               "cross_size"):
        getattr(lib, f"hvd_core_{fn}").restype = ctypes.c_int
    lib.hvd_core_enqueue.restype = ctypes.c_longlong
    lib.hvd_core_enqueue.argtypes = [
        ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_longlong), ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_double, ctypes.c_double,
        ctypes.c_longlong, ctypes.c_int, ctypes.c_int,
        ctypes.c_char_p, ctypes.c_int,
    ]
    lib.hvd_core_grouped_splits.restype = ctypes.c_longlong
    lib.hvd_core_grouped_splits.argtypes = []
    lib.hvd_core_register_process_set.restype = ctypes.c_int
    lib.hvd_core_register_process_set.argtypes = [
        ctypes.c_int, ctypes.POINTER(ctypes.c_int), ctypes.c_int,
        ctypes.c_char_p, ctypes.c_int,
    ]
    lib.hvd_core_remove_process_set.restype = ctypes.c_int
    lib.hvd_core_remove_process_set.argtypes = [
        ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
    ]
    lib.hvd_core_enqueue_join.restype = ctypes.c_longlong
    lib.hvd_core_enqueue_join.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.hvd_core_next_plan.restype = ctypes.c_int
    lib.hvd_core_next_plan.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
    ]
    lib.hvd_core_plan_done.restype = None
    lib.hvd_core_plan_done.argtypes = [
        ctypes.c_ulonglong, ctypes.c_int, ctypes.c_char_p, ctypes.c_double,
        ctypes.c_longlong,
    ]
    lib.hvd_core_ticket_status.restype = ctypes.c_int
    lib.hvd_core_ticket_status.argtypes = [
        ctypes.c_ulonglong, ctypes.c_char_p, ctypes.c_int,
    ]
    lib.hvd_core_cycle_time_ms.restype = ctypes.c_double
    lib.hvd_core_tuned_flags.restype = ctypes.c_int
    lib.hvd_core_cache_size.restype = ctypes.c_longlong
    lib.hvd_core_start_timeline.restype = ctypes.c_int
    lib.hvd_core_start_timeline.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.hvd_core_stop_timeline.restype = None
    lib.hvd_core_fusion_threshold.restype = ctypes.c_longlong
    lib.hvd_core_timeline_activity.restype = None
    lib.hvd_core_timeline_activity.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
    ]
    _lib = lib
    return lib


class NativeCore:
    """Thin OO wrapper over the C ABI."""

    ERRBUF = 4096

    def __init__(self):
        self.lib = load()

    def init(self, cfg, topo, coord_addr: str = "", coord_port: int = 0) -> None:
        err = ctypes.create_string_buffer(self.ERRBUF)
        log_levels = {"trace": 0, "debug": 1, "info": 2, "warning": 3,
                      "warn": 3, "error": 4}
        rc = self.lib.hvd_core_init(
            topo.rank, topo.size, topo.local_rank, topo.local_size,
            topo.cross_rank, topo.cross_size,
            ctypes.c_double(cfg.cycle_time_ms),
            ctypes.c_longlong(cfg.fusion_threshold_bytes),
            cfg.cache_capacity,
            0 if cfg.stall_check_disable else int(cfg.stall_warning_time_seconds),
            int(cfg.stall_shutdown_time_seconds),
            1 if cfg.autotune else 0,
            cfg.autotune_warmup_samples,
            cfg.autotune_steps_per_sample,
            log_levels.get(cfg.log_level.lower(), 2),
            cfg.timeline_filename.encode(),
            coord_addr.encode(),
            coord_port,
            cfg.autotune_log_file.encode(),
            1 if cfg.hierarchical_allreduce else 0,
            1 if cfg.hierarchical_allgather else 0,
            err, self.ERRBUF,
        )
        if rc != 0:
            raise RuntimeError(f"native core init failed: {err.value.decode()}")

    def shutdown(self) -> None:
        self.lib.hvd_core_shutdown()

    def flush_hint(self) -> None:
        """Tell the core a producer is now blocked waiting: the next
        cycle may seal immediately (skip the fusion grace/linger). No-op
        on cores built before the export existed."""
        fn = getattr(self.lib, "hvd_core_flush_hint", None)
        if fn is not None:
            fn()

    def initialized(self) -> bool:
        return bool(self.lib.hvd_core_initialized())

    def enqueue(self, request_type: int, name: str, dtype: int,
                shape, root_rank: int, reduce_op: int,
                prescale: float, postscale: float,
                group_id: int = 0, group_size: int = 0,
                process_set_id: int = 0) -> int:
        err = ctypes.create_string_buffer(self.ERRBUF)
        arr = (ctypes.c_longlong * len(shape))(*shape)
        ticket = self.lib.hvd_core_enqueue(
            request_type, name.encode(), dtype, arr, len(shape), root_rank,
            reduce_op, ctypes.c_double(prescale), ctypes.c_double(postscale),
            ctypes.c_longlong(group_id), group_size, process_set_id,
            err, self.ERRBUF,
        )
        if ticket < 0:
            raise _CoreError(-ticket, err.value.decode())
        return int(ticket)

    def register_process_set(self, psid: int, ranks) -> None:
        err = ctypes.create_string_buffer(self.ERRBUF)
        arr = (ctypes.c_int * len(ranks))(*ranks)
        rc = self.lib.hvd_core_register_process_set(
            psid, arr, len(ranks), err, self.ERRBUF
        )
        if rc != 0:
            raise _CoreError(-rc, err.value.decode())

    def remove_process_set(self, psid: int) -> None:
        err = ctypes.create_string_buffer(self.ERRBUF)
        rc = self.lib.hvd_core_remove_process_set(psid, err, self.ERRBUF)
        if rc != 0:
            raise _CoreError(-rc, err.value.decode())

    def grouped_splits(self) -> int:
        """Groups that could not fuse into a single plan (heterogeneous
        member signatures) since init."""
        return int(self.lib.hvd_core_grouped_splits())

    def enqueue_join(self) -> int:
        err = ctypes.create_string_buffer(self.ERRBUF)
        ticket = self.lib.hvd_core_enqueue_join(err, self.ERRBUF)
        if ticket < 0:
            raise _CoreError(-ticket, err.value.decode())
        return int(ticket)

    def next_plan(self, timeout_ms: int = 100, bufsize: int = 1 << 20):
        buf = ctypes.create_string_buffer(bufsize)
        r = self.lib.hvd_core_next_plan(buf, bufsize, timeout_ms)
        if r > 0:
            return json.loads(buf.value.decode())
        return r  # 0 timeout, -1 shutdown, -2 too small

    def plan_done(self, plan_id: int, status: int, error: str,
                  duration_s: float, bytes_moved: int) -> None:
        self.lib.hvd_core_plan_done(
            plan_id, status, error.encode(), ctypes.c_double(duration_s),
            ctypes.c_longlong(bytes_moved),
        )

    def ticket_status(self, ticket: int):
        """Returns (state, error): state 0=in-progress, 1=ok, <0 error."""
        err = ctypes.create_string_buffer(self.ERRBUF)
        r = self.lib.hvd_core_ticket_status(ticket, err, self.ERRBUF)
        return r, (err.value.decode() if r < 0 else "")

    def cycle_time_ms(self) -> float:
        return float(self.lib.hvd_core_cycle_time_ms())

    def fusion_threshold(self) -> int:
        return int(self.lib.hvd_core_fusion_threshold())

    def tuned_flags(self) -> int:
        """Autotuned categorical bitmask: bit0 hierarchical_allreduce,
        bit1 hierarchical_allgather, bit2 cache_enabled."""
        return int(self.lib.hvd_core_tuned_flags())

    def cache_size(self) -> int:
        return int(self.lib.hvd_core_cache_size())

    def start_timeline(self, path: str, mark_cycles: bool = False) -> int:
        """Start the catapult timeline at runtime (later-reference
        hvd.start_timeline). Returns 0 ok, nonzero StatusCode."""
        return int(self.lib.hvd_core_start_timeline(
            path.encode(), 1 if mark_cycles else 0
        ))

    def stop_timeline(self) -> None:
        self.lib.hvd_core_stop_timeline()

    def timeline_activity(self, tensor: str, activity: str, begin: bool):
        self.lib.hvd_core_timeline_activity(
            tensor.encode(), activity.encode(), 1 if begin else 0
        )


class _CoreError(RuntimeError):
    def __init__(self, code: int, msg: str):
        super().__init__(msg)
        self.code = code
