"""Int8 wire-format constants and byte accounting — NO jax import.

The quantized ring allreduce (``ops/quantized.py``) and the topology
compositor's planning layer (``topo/compositor.py``) must agree on one
wire format: symmetric blockwise int8, one float32 scale per ``BLOCK``
elements, scales packed behind the payload in the same buffer. The
planning layer (and ``analysis/plan_verify.py``) runs with no backend at
all, so the format constants and the bytes-on-wire arithmetic live here,
jax-free, and both sides import them.
"""

from __future__ import annotations

# Elements sharing one scale. Small enough that a low-magnitude gradient
# leaf (layernorm/bias) packed into a fusion bucket next to a large-
# magnitude one keeps its own scales instead of rounding to zero against
# the bucket's global amax; 4 scale bytes per 256 payload bytes = 1.6%
# wire overhead.
BLOCK = 256

# Each scale is one float32.
SCALE_BYTES = 4

# Wire dtype labels used by compositor plans and the plan verifier.
# bf16 is a PURE cast rung: half the bytes of f32, no scales, no error
# feedback — valid for every collective (a cast commutes with any data
# movement and any SUM/AVERAGE), unlike int8 whose blockwise scales only
# compose with the allreduce/reduce-scatter constructions.
WIRE_F32 = "f32"
WIRE_BF16 = "bf16"
WIRE_INT8 = "int8"
WIRE_DTYPES = (WIRE_F32, WIRE_BF16, WIRE_INT8)


def int8_wire_bytes(nbytes: int, dtype_bytes: int = 4) -> int:
    """Bytes a stage that declared ``nbytes`` of full-precision traffic
    actually moves with the int8+scales format: one byte per element
    plus one f32 scale per BLOCK elements. ``dtype_bytes`` is the
    payload's full-precision element width (plans price f32)."""
    nbytes = max(int(nbytes), 0)
    if nbytes == 0:
        return 0
    elems = -(-nbytes // int(dtype_bytes))  # ceil
    blocks = -(-elems // BLOCK)
    return elems + SCALE_BYTES * blocks


def bf16_wire_bytes(nbytes: int, dtype_bytes: int = 4) -> int:
    """Bytes a stage that declared ``nbytes`` of full-precision traffic
    moves with the bf16 cast format: two bytes per element, no scales."""
    nbytes = max(int(nbytes), 0)
    if nbytes == 0:
        return 0
    elems = -(-nbytes // int(dtype_bytes))  # ceil
    return 2 * elems


def int8_saved_bytes(nbytes: int, dtype_bytes: int = 4) -> int:
    """Full-precision bytes minus the int8 wire bytes (>= 0 for any
    dtype wider than 1 byte)."""
    return max(int(nbytes) - int8_wire_bytes(nbytes, dtype_bytes), 0)
