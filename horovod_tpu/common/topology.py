"""Process/device topology discovery.

TPU-native replacement of the reference's rank discovery
(``horovod/common/mpi/mpi_controller.cc:25-81``: rank/size from MPI_Comm_rank,
local from MPI_Comm_split_type(SHARED), cross split by local_rank). Here the
same global/LOCAL/CROSS triple is derived, in priority order, from:

1. ``HOROVOD_RANK``/``HOROVOD_SIZE``/... env vars set by the launcher
   (parity with ``horovod/common/gloo/gloo_context.cc:113-157``),
2. an already-initialized ``jax.distributed`` runtime (authoritative —
   its process indices are ground truth): LOCAL = processes in this
   process's TPU *slice* (one ICI domain, possibly spanning hosts),
   CROSS = across slices over DCN (``topology_from_slice_metadata``),
3. the megascale multislice env (``MEGASCALE_SLICE_ID`` /
   ``MEGASCALE_NUM_SLICES`` + ``TPU_WORKER_*``): real multi-slice
   deployments get the (cross, local) = (DCN, ICI) grid with no
   hand-set topology vars, before jax is initialized
   (``_from_megascale_env``),
4. single-process fallback: rank 0 of 1.

The LOCAL axis maps onto ICI and the CROSS axis onto DCN — the analogue of
the reference's NCCL-local / MPI-cross communicator pair
(``horovod/common/common.h:110-114``). NOTE a deliberate parity deviation:
the reference's ``local_rank`` means "ranks on this host" (shared memory);
here it means "ranks in this ICI domain", which on a multi-host single
slice spans hosts. Host-scoped logic (e.g. dataset caching) should key on
hostname, not ``local_rank``, in this framework; the env path (1) remains
host-scoped when the launcher says so.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional

from . import env as env_mod


@dataclass(frozen=True)
class Topology:
    rank: int
    size: int
    local_rank: int
    local_size: int
    cross_rank: int
    cross_size: int
    # True when every host has the same number of local ranks
    # (reference: is_homogeneous_, mpi_controller.cc:74-81).
    is_homogeneous: bool = True
    source: str = "single"

    def __post_init__(self):
        if not (0 <= self.rank < self.size):
            raise ValueError(f"rank {self.rank} out of range for size {self.size}")
        if not (0 <= self.local_rank < self.local_size):
            raise ValueError(
                f"local_rank {self.local_rank} out of range for local_size "
                f"{self.local_size}"
            )


def _from_env() -> Optional[Topology]:
    rank = os.environ.get(env_mod.HOROVOD_RANK)
    size = os.environ.get(env_mod.HOROVOD_SIZE)
    if rank is None or size is None:
        return None
    rank, size = int(rank), int(size)
    local_rank = int(os.environ.get(env_mod.HOROVOD_LOCAL_RANK, 0))
    local_size = int(os.environ.get(env_mod.HOROVOD_LOCAL_SIZE, 1))
    cross_rank = int(os.environ.get(env_mod.HOROVOD_CROSS_RANK, rank // max(local_size, 1)))
    cross_size = int(
        os.environ.get(
            env_mod.HOROVOD_CROSS_SIZE, (size + local_size - 1) // max(local_size, 1)
        )
    )
    return Topology(
        rank=rank,
        size=size,
        local_rank=local_rank,
        local_size=local_size,
        cross_rank=cross_rank,
        cross_size=cross_size,
        is_homogeneous=(size == local_size * cross_size),
        source="env",
    )


def topology_from_slice_metadata(process_index: int,
                                 proc_slices) -> Topology:
    """Derive the (rank, LOCAL, CROSS) triple from TPU slice metadata.

    ``proc_slices``: iterable of (process_index, slice_index) pairs, one
    per process — what ``jax.devices()`` exposes as ``d.process_index`` /
    ``d.slice_index`` on (multi-slice) pods. Processes sharing a slice
    communicate over ICI and form the LOCAL axis; slices talk over DCN and
    form the CROSS axis — the analogue of the reference deriving local
    ranks from an MPI shared-memory split and cross ranks from splitting by
    local rank (``mpi_context.cc:149-158`` / ``mpi_controller.cc:25-81``).

    A single-slice pod therefore yields local = all processes, cross = 1
    (everything rides ICI); N equal slices yield local = procs-per-slice,
    cross = N.

    The hierarchical executor additionally assumes the block layout
    ``rank == cross_rank * local_size + local_rank`` when it reshapes the
    rank-ordered device list into a (cross, local) grid
    (``xla_executor.py``); process indices interleaved across slices (JAX
    assigns them by coordinator registration order) would silently put a
    "local" mesh row across DCN, so non-contiguous layouts are marked
    non-homogeneous, which keeps the executor on the flat lowering.
    """
    by_slice: dict = {}
    for p, s in sorted(set(proc_slices)):
        by_slice.setdefault(s, []).append(p)
    slices = sorted(by_slice)
    my_slice = next(
        s for s, procs in by_slice.items() if process_index in procs
    )
    local_procs = by_slice[my_slice]
    sizes = {len(v) for v in by_slice.values()}
    size = sum(len(v) for v in by_slice.values())
    # Block-layout invariant: slice k (in slice-id order) must own exactly
    # the contiguous process range [k*local, (k+1)*local).
    contiguous = all(
        by_slice[s] == list(range(k * len(by_slice[s]),
                                  (k + 1) * len(by_slice[s])))
        for k, s in enumerate(slices)
    )
    return Topology(
        rank=process_index,
        size=size,
        local_rank=local_procs.index(process_index),
        local_size=len(local_procs),
        cross_rank=slices.index(my_slice),
        cross_size=len(slices),
        is_homogeneous=(len(sizes) == 1 and contiguous),
        source="slice-metadata",
    )


def _from_megascale_env() -> Optional[Topology]:
    """Multi-slice (DCN) deployment detection from the megascale env —
    ``MEGASCALE_SLICE_ID`` / ``MEGASCALE_NUM_SLICES``, set per process by
    the Cloud TPU multislice runtime — combined with the per-slice worker
    env (``TPU_WORKER_ID``, ``TPU_WORKER_HOSTNAMES``). CROSS maps onto
    the DCN slice axis and LOCAL onto the ICI within-slice workers, with
    the block layout ``rank = slice_id * workers_per_slice + worker_id``
    the hierarchical executor assumes — no hand-set ``HOROVOD_*``
    topology vars needed. The analogue of the reference deriving its
    LOCAL/CROSS communicators at ``mpi_context.cc:149-158``; here the
    deployment env IS the authority, which is exactly where the
    hierarchical (ICI-then-DCN) lowerings earn their keep."""
    raw = os.environ.get("MEGASCALE_NUM_SLICES")
    if raw is None:
        return None
    try:
        num_slices = int(raw)
        slice_raw = os.environ.get("MEGASCALE_SLICE_ID")
        slice_id = int(slice_raw) if slice_raw is not None else 0
        hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
        local_size = len([h for h in hostnames.split(",") if h.strip()]) or 1
        worker_raw = os.environ.get("TPU_WORKER_ID")
        local_rank = int(worker_raw) if worker_raw is not None else 0
    except ValueError:
        return None
    # Degenerate env falls through to the next detection source instead
    # of crashing hvd.init() — including *absent* per-process ids when
    # the sizes say there must be more than one process: defaulting them
    # to 0 would give every process the same global rank (colliding
    # ranks hang or silently corrupt collectives).
    if num_slices > 1 and slice_raw is None:
        return None
    if local_size > 1 and worker_raw is None:
        return None
    if not (0 <= slice_id < num_slices and 0 <= local_rank < local_size):
        return None
    return Topology(
        rank=slice_id * local_size + local_rank,
        size=num_slices * local_size,
        local_rank=local_rank,
        local_size=local_size,
        cross_rank=slice_id,
        cross_size=num_slices,
        is_homogeneous=True,
        source="megascale-env",
    )


def _from_jax_distributed() -> Optional[Topology]:
    try:
        import jax
    except ImportError:  # pragma: no cover
        return None
    try:
        nproc = jax.process_count()
    except Exception:
        return None
    if nproc <= 1:
        return None
    rank = jax.process_index()
    try:
        # Multi-slice pods expose d.slice_index; a single slice (or a CPU
        # test cluster) groups every process into one ICI domain.
        pairs = {
            (d.process_index, getattr(d, "slice_index", 0) or 0)
            for d in jax.devices()
        }
        return topology_from_slice_metadata(rank, pairs)
    except Exception:
        return Topology(
            rank=rank, size=nproc, local_rank=0, local_size=1,
            cross_rank=rank, cross_size=nproc, is_homogeneous=True,
            source="jax.distributed",
        )


def detect() -> Topology:
    topo = _from_env()
    if topo is not None:
        return topo
    # An already-initialized jax.distributed runtime is authoritative
    # (its process indices are ground truth and interleaved layouts are
    # detected); the megascale env is the pre-init inference for real
    # multislice deployments.
    topo = _from_jax_distributed()
    if topo is not None:
        return topo
    topo = _from_megascale_env()
    if topo is not None:
        return topo
    return Topology(
        rank=0,
        size=1,
        local_rank=0,
        local_size=1,
        cross_rank=0,
        cross_size=1,
        is_homogeneous=True,
        source="single",
    )
