"""Process/device topology discovery.

TPU-native replacement of the reference's rank discovery
(``horovod/common/mpi/mpi_controller.cc:25-81``: rank/size from MPI_Comm_rank,
local from MPI_Comm_split_type(SHARED), cross split by local_rank). Here the
same global/LOCAL/CROSS triple is derived, in priority order, from:

1. ``HOROVOD_RANK``/``HOROVOD_SIZE``/... env vars set by the launcher
   (parity with ``horovod/common/gloo/gloo_context.cc:113-157``),
2. an already-initialized ``jax.distributed`` runtime (TPU pod slices: one
   process per host; local = chips on this host; cross = same chip index on
   other hosts — exactly the ICI/DCN split the hierarchical ops need),
3. single-process fallback: rank 0 of 1.

The LOCAL axis maps onto ICI (within a slice/host) and the CROSS axis onto
DCN (across slices/hosts) — the analogue of the reference's NCCL-local /
MPI-cross communicator pair (``horovod/common/common.h:110-114``).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional

from . import env as env_mod


@dataclass(frozen=True)
class Topology:
    rank: int
    size: int
    local_rank: int
    local_size: int
    cross_rank: int
    cross_size: int
    # True when every host has the same number of local ranks
    # (reference: is_homogeneous_, mpi_controller.cc:74-81).
    is_homogeneous: bool = True
    source: str = "single"

    def __post_init__(self):
        if not (0 <= self.rank < self.size):
            raise ValueError(f"rank {self.rank} out of range for size {self.size}")
        if not (0 <= self.local_rank < self.local_size):
            raise ValueError(
                f"local_rank {self.local_rank} out of range for local_size "
                f"{self.local_size}"
            )


def _from_env() -> Optional[Topology]:
    rank = os.environ.get(env_mod.HOROVOD_RANK)
    size = os.environ.get(env_mod.HOROVOD_SIZE)
    if rank is None or size is None:
        return None
    rank, size = int(rank), int(size)
    local_rank = int(os.environ.get(env_mod.HOROVOD_LOCAL_RANK, 0))
    local_size = int(os.environ.get(env_mod.HOROVOD_LOCAL_SIZE, 1))
    cross_rank = int(os.environ.get(env_mod.HOROVOD_CROSS_RANK, rank // max(local_size, 1)))
    cross_size = int(
        os.environ.get(
            env_mod.HOROVOD_CROSS_SIZE, (size + local_size - 1) // max(local_size, 1)
        )
    )
    return Topology(
        rank=rank,
        size=size,
        local_rank=local_rank,
        local_size=local_size,
        cross_rank=cross_rank,
        cross_size=cross_size,
        is_homogeneous=(size == local_size * cross_size),
        source="env",
    )


def _from_jax_distributed() -> Optional[Topology]:
    try:
        import jax
    except ImportError:  # pragma: no cover
        return None
    try:
        nproc = jax.process_count()
    except Exception:
        return None
    if nproc <= 1:
        return None
    rank = jax.process_index()
    # One process per host; every process contributes the same number of
    # local devices on TPU slices, which makes the topology homogeneous.
    local_size = 1
    return Topology(
        rank=rank,
        size=nproc,
        local_rank=0,
        local_size=local_size,
        cross_rank=rank,
        cross_size=nproc,
        is_homogeneous=True,
        source="jax.distributed",
    )


def detect() -> Topology:
    topo = _from_env()
    if topo is not None:
        return topo
    topo = _from_jax_distributed()
    if topo is not None:
        return topo
    return Topology(
        rank=0,
        size=1,
        local_rank=0,
        local_size=1,
        cross_rank=0,
        cross_size=1,
        is_homogeneous=True,
        source="single",
    )
