"""HOROVOD_* environment-knob parsing.

Parity with the reference's env surface (``horovod/common/common.h:62-87``
knob names, ``horovod/common/utils/env_parser.cc:49-163``). The same names
are honored so scripts/configs written for the reference keep working; a few
TPU-specific knobs are added under the same prefix.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field


# --- knob names (reference common.h:62-87) ---
HOROVOD_FUSION_THRESHOLD = "HOROVOD_FUSION_THRESHOLD"
HOROVOD_CYCLE_TIME = "HOROVOD_CYCLE_TIME"
HOROVOD_TIMELINE = "HOROVOD_TIMELINE"
HOROVOD_PROFILER_DIR = "HOROVOD_PROFILER_DIR"
HOROVOD_TIMELINE_MARK_CYCLES = "HOROVOD_TIMELINE_MARK_CYCLES"
HOROVOD_AUTOTUNE = "HOROVOD_AUTOTUNE"
HOROVOD_AUTOTUNE_LOG = "HOROVOD_AUTOTUNE_LOG"
HOROVOD_AUTOTUNE_WARMUP_SAMPLES = "HOROVOD_AUTOTUNE_WARMUP_SAMPLES"
HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE = "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE"
HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES = "HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES"
HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE = "HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE"
HOROVOD_HIERARCHICAL_ALLREDUCE = "HOROVOD_HIERARCHICAL_ALLREDUCE"
HOROVOD_HIERARCHICAL_ALLGATHER = "HOROVOD_HIERARCHICAL_ALLGATHER"
HOROVOD_CACHE_CAPACITY = "HOROVOD_CACHE_CAPACITY"
HOROVOD_STALL_CHECK_DISABLE = "HOROVOD_STALL_CHECK_DISABLE"
HOROVOD_STALL_CHECK_TIME_SECONDS = "HOROVOD_STALL_CHECK_TIME_SECONDS"
HOROVOD_STALL_SHUTDOWN_TIME_SECONDS = "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS"
HOROVOD_LOG_LEVEL = "HOROVOD_LOG_LEVEL"
HOROVOD_LOG_HIDE_TIMESTAMP = "HOROVOD_LOG_HIDE_TIMESTAMP"
HOROVOD_ADASUM_MPI_CHUNK_SIZE = "HOROVOD_ADASUM_MPI_CHUNK_SIZE"
HOROVOD_NUM_STREAMS = "HOROVOD_NUM_NCCL_STREAMS"  # kept for config parity
# Rank/topology env (reference gloo_context.cc:38-49 + gloo_run.py env).
HOROVOD_RANK = "HOROVOD_RANK"
HOROVOD_SIZE = "HOROVOD_SIZE"
HOROVOD_LOCAL_RANK = "HOROVOD_LOCAL_RANK"
HOROVOD_LOCAL_SIZE = "HOROVOD_LOCAL_SIZE"
HOROVOD_CROSS_RANK = "HOROVOD_CROSS_RANK"
HOROVOD_CROSS_SIZE = "HOROVOD_CROSS_SIZE"
HOROVOD_RENDEZVOUS_ADDR = "HOROVOD_GLOO_RENDEZVOUS_ADDR"
HOROVOD_RENDEZVOUS_PORT = "HOROVOD_GLOO_RENDEZVOUS_PORT"
HOROVOD_CONTROLLER = "HOROVOD_CONTROLLER"
HOROVOD_CPU_OPERATIONS = "HOROVOD_CPU_OPERATIONS"
# TPU-native additions.
HOROVOD_TPU_MESH_AXES = "HOROVOD_TPU_MESH_AXES"
HOROVOD_TPU_EAGER_BACKEND = "HOROVOD_TPU_EAGER_BACKEND"
# Opt-in collective-safety pre-flight (docs/static_analysis.md).
HOROVOD_TPU_STATIC_CHECKS = "HOROVOD_TPU_STATIC_CHECKS"
# Fault tolerance (docs/fault_tolerance.md).
# Stall escalation ladder: periodic re-warn and per-tensor abort windows
# on top of the reference's warn/shutdown pair.
HOROVOD_STALL_REWARN_TIME_SECONDS = "HOROVOD_STALL_REWARN_TIME_SECONDS"
HOROVOD_STALL_ABORT_TIME_SECONDS = "HOROVOD_STALL_ABORT_TIME_SECONDS"
# Control-plane RPC retry budget (fault/backoff.py reads these directly —
# launcher-side processes never construct a Config).
HOROVOD_RPC_RETRIES = "HOROVOD_RPC_RETRIES"
HOROVOD_RPC_BACKOFF_BASE_S = "HOROVOD_RPC_BACKOFF_BASE_S"
HOROVOD_RPC_BACKOFF_MAX_S = "HOROVOD_RPC_BACKOFF_MAX_S"
HOROVOD_RPC_BACKOFF_JITTER = "HOROVOD_RPC_BACKOFF_JITTER"
# Rendezvous server-side wait window (replaces the old hardcoded 60 s).
HOROVOD_COORD_WAIT_TIMEOUT_S = "HOROVOD_COORD_WAIT_TIMEOUT_S"
# Elastic blacklist quarantine: a blacklisted host is re-admitted after
# this many seconds (0 = never), and failure counts decay after it too.
HOROVOD_BLACKLIST_COOLDOWN_S = "HOROVOD_BLACKLIST_COOLDOWN_S"
# Graceful preemption drain (elastic workers): 0 disables the SIGTERM
# notice handler.
HOROVOD_PREEMPTION_GRACEFUL = "HOROVOD_PREEMPTION_GRACEFUL"
# Deterministic fault injection (fault/plan.py): the plan itself, the
# event-log path, and the seed for retry jitter in chaos runs.
HOROVOD_FAULT_PLAN = "HOROVOD_FAULT_PLAN"
HOROVOD_FAULT_EVENT_LOG = "HOROVOD_FAULT_EVENT_LOG"
HOROVOD_FAULT_SEED = "HOROVOD_FAULT_SEED"
# Runtime metrics (docs/metrics.md; horovod_tpu/metrics reads these
# directly, like the fault knobs — launcher-side processes never build a
# Config): enable the tap, pin the driver's /metrics (KV) port, and set
# the worker snapshot push cadence.
HOROVOD_METRICS = "HOROVOD_METRICS"
HOROVOD_METRICS_PORT = "HOROVOD_METRICS_PORT"
HOROVOD_METRICS_PUSH_INTERVAL_S = "HOROVOD_METRICS_PUSH_INTERVAL_S"
# Respawn-mode data-loss guard: fail (instead of loudly warning) when a
# restart generation > 1 finds no restored snapshot on any rank.
HOROVOD_ELASTIC_REQUIRE_SNAPSHOT = "HOROVOD_ELASTIC_REQUIRE_SNAPSHOT"

# Fusion buffer rounding unit: reference common.h:94 FUSION_BUFFER_ATOMIC_UNIT=64.
FUSION_BUFFER_ATOMIC_UNIT = 64


def _get_bool(name: str, default: bool = False) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.strip().lower() in ("1", "true", "yes", "on")


def _get_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    if v is None or not v.strip():
        return default
    try:
        return int(v)
    except ValueError:
        return default


def _get_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    if v is None or not v.strip():
        return default
    try:
        return float(v)
    except ValueError:
        return default


@dataclass
class Config:
    """Runtime knobs resolved at init.

    Defaults follow the reference: 64 MB fusion threshold and 5 ms cycle time
    (``operations.cc:411-417``), cache capacity 1024 (``global_state.h:88``),
    60 s stall warning (``stall_inspector.h:72-80``).
    """

    fusion_threshold_bytes: int = 64 * 1024 * 1024
    cycle_time_ms: float = 5.0
    cache_capacity: int = 1024
    cache_enabled: bool = True
    hierarchical_allreduce: bool = False
    hierarchical_allgather: bool = False
    autotune: bool = False
    autotune_log_file: str = ""
    autotune_warmup_samples: int = 3
    autotune_steps_per_sample: int = 10
    autotune_bayes_opt_max_samples: int = 20
    autotune_gaussian_process_noise: float = 0.8
    timeline_filename: str = ""
    # Optional jax.profiler trace session directory: started at init,
    # stopped at shutdown; plan executions inside carry the same
    # hvd_plan_<id> annotation the timeline stamps (SURVEY §5).
    profiler_dir: str = ""

    timeline_mark_cycles: bool = False
    stall_check_disable: bool = False
    stall_warning_time_seconds: float = 60.0
    stall_shutdown_time_seconds: float = 0.0
    # Escalation ladder between warn and shutdown: re-warn every
    # ``stall_rewarn_seconds`` (0 = reuse the warn interval) and abort the
    # individual stalled tensor — a named Status.Aborted handed to its
    # waiters — after ``stall_abort_time_seconds`` (0 = disabled).
    stall_rewarn_seconds: float = 0.0
    stall_abort_time_seconds: float = 0.0
    adasum_chunk_size: int = 1 << 26
    log_level: str = "warning"
    eager_backend: str = "auto"  # auto | xla | local
    mesh_axes: str = ""  # e.g. "data:8" or "data:4,model:2"
    # Run the collective-safety static analyzers as a pre-flight on
    # DistributedOptimizer/allreduce setup (analysis/preflight.py).
    static_checks: bool = False
    extra: dict = field(default_factory=dict)

    @staticmethod
    def from_env() -> "Config":
        cfg = Config()
        cfg.fusion_threshold_bytes = _get_int(
            HOROVOD_FUSION_THRESHOLD, cfg.fusion_threshold_bytes
        )
        # Reference accepts cycle time in ms as float via HOROVOD_CYCLE_TIME.
        cfg.cycle_time_ms = _get_float(HOROVOD_CYCLE_TIME, cfg.cycle_time_ms)
        cfg.cache_capacity = _get_int(HOROVOD_CACHE_CAPACITY, cfg.cache_capacity)
        cfg.cache_enabled = cfg.cache_capacity > 0
        cfg.hierarchical_allreduce = _get_bool(HOROVOD_HIERARCHICAL_ALLREDUCE)
        cfg.hierarchical_allgather = _get_bool(HOROVOD_HIERARCHICAL_ALLGATHER)
        cfg.autotune = _get_bool(HOROVOD_AUTOTUNE)
        cfg.autotune_log_file = os.environ.get(HOROVOD_AUTOTUNE_LOG, "")
        cfg.autotune_warmup_samples = _get_int(
            HOROVOD_AUTOTUNE_WARMUP_SAMPLES, cfg.autotune_warmup_samples
        )
        cfg.autotune_steps_per_sample = _get_int(
            HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE, cfg.autotune_steps_per_sample
        )
        cfg.autotune_bayes_opt_max_samples = _get_int(
            HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES, cfg.autotune_bayes_opt_max_samples
        )
        cfg.autotune_gaussian_process_noise = _get_float(
            HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE,
            cfg.autotune_gaussian_process_noise,
        )
        cfg.timeline_filename = os.environ.get(HOROVOD_TIMELINE, "")
        cfg.profiler_dir = os.environ.get(HOROVOD_PROFILER_DIR, "")
        cfg.timeline_mark_cycles = _get_bool(HOROVOD_TIMELINE_MARK_CYCLES)
        cfg.stall_check_disable = _get_bool(HOROVOD_STALL_CHECK_DISABLE)
        cfg.stall_warning_time_seconds = _get_float(
            HOROVOD_STALL_CHECK_TIME_SECONDS, cfg.stall_warning_time_seconds
        )
        cfg.stall_shutdown_time_seconds = _get_float(
            HOROVOD_STALL_SHUTDOWN_TIME_SECONDS, cfg.stall_shutdown_time_seconds
        )
        cfg.stall_rewarn_seconds = _get_float(
            HOROVOD_STALL_REWARN_TIME_SECONDS, cfg.stall_rewarn_seconds
        )
        cfg.stall_abort_time_seconds = _get_float(
            HOROVOD_STALL_ABORT_TIME_SECONDS, cfg.stall_abort_time_seconds
        )
        cfg.adasum_chunk_size = _get_int(
            HOROVOD_ADASUM_MPI_CHUNK_SIZE, cfg.adasum_chunk_size
        )
        cfg.log_level = os.environ.get(HOROVOD_LOG_LEVEL, cfg.log_level)
        cfg.eager_backend = os.environ.get(HOROVOD_TPU_EAGER_BACKEND, cfg.eager_backend)
        cfg.mesh_axes = os.environ.get(HOROVOD_TPU_MESH_AXES, cfg.mesh_axes)
        cfg.static_checks = _get_bool(HOROVOD_TPU_STATIC_CHECKS)
        return cfg
