"""HOROVOD_* environment-knob parsing.

Parity with the reference's env surface (``horovod/common/common.h:62-87``
knob names, ``horovod/common/utils/env_parser.cc:49-163``). The same names
are honored so scripts/configs written for the reference keep working; a few
TPU-specific knobs are added under the same prefix.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field


# --- knob names (reference common.h:62-87) ---
HOROVOD_FUSION_THRESHOLD = "HOROVOD_FUSION_THRESHOLD"
HOROVOD_CYCLE_TIME = "HOROVOD_CYCLE_TIME"
HOROVOD_TIMELINE = "HOROVOD_TIMELINE"
HOROVOD_PROFILER_DIR = "HOROVOD_PROFILER_DIR"
HOROVOD_TIMELINE_MARK_CYCLES = "HOROVOD_TIMELINE_MARK_CYCLES"
HOROVOD_AUTOTUNE = "HOROVOD_AUTOTUNE"
HOROVOD_AUTOTUNE_LOG = "HOROVOD_AUTOTUNE_LOG"
HOROVOD_AUTOTUNE_WARMUP_SAMPLES = "HOROVOD_AUTOTUNE_WARMUP_SAMPLES"
HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE = "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE"
HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES = "HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES"
HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE = "HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE"
HOROVOD_HIERARCHICAL_ALLREDUCE = "HOROVOD_HIERARCHICAL_ALLREDUCE"
HOROVOD_HIERARCHICAL_ALLGATHER = "HOROVOD_HIERARCHICAL_ALLGATHER"
HOROVOD_CACHE_CAPACITY = "HOROVOD_CACHE_CAPACITY"
HOROVOD_STALL_CHECK_DISABLE = "HOROVOD_STALL_CHECK_DISABLE"
HOROVOD_STALL_CHECK_TIME_SECONDS = "HOROVOD_STALL_CHECK_TIME_SECONDS"
HOROVOD_STALL_SHUTDOWN_TIME_SECONDS = "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS"
HOROVOD_LOG_LEVEL = "HOROVOD_LOG_LEVEL"
HOROVOD_LOG_HIDE_TIMESTAMP = "HOROVOD_LOG_HIDE_TIMESTAMP"
HOROVOD_ADASUM_MPI_CHUNK_SIZE = "HOROVOD_ADASUM_MPI_CHUNK_SIZE"
HOROVOD_NUM_STREAMS = "HOROVOD_NUM_NCCL_STREAMS"  # kept for config parity
# Rank/topology env (reference gloo_context.cc:38-49 + gloo_run.py env).
HOROVOD_RANK = "HOROVOD_RANK"
HOROVOD_SIZE = "HOROVOD_SIZE"
HOROVOD_LOCAL_RANK = "HOROVOD_LOCAL_RANK"
HOROVOD_LOCAL_SIZE = "HOROVOD_LOCAL_SIZE"
HOROVOD_CROSS_RANK = "HOROVOD_CROSS_RANK"
HOROVOD_CROSS_SIZE = "HOROVOD_CROSS_SIZE"
HOROVOD_RENDEZVOUS_ADDR = "HOROVOD_GLOO_RENDEZVOUS_ADDR"
HOROVOD_RENDEZVOUS_PORT = "HOROVOD_GLOO_RENDEZVOUS_PORT"
HOROVOD_CONTROLLER = "HOROVOD_CONTROLLER"
HOROVOD_CPU_OPERATIONS = "HOROVOD_CPU_OPERATIONS"
# TPU-native additions.
HOROVOD_TPU_MESH_AXES = "HOROVOD_TPU_MESH_AXES"
HOROVOD_TPU_EAGER_BACKEND = "HOROVOD_TPU_EAGER_BACKEND"
# Streamed (overlap) gradient reduction: size of the FIRST bucket to reduce
# in the backward pass (DDP idiom — small, so the wire starts early;
# docs/overlap.md). The reference HOROVOD_FUSION_THRESHOLD above is honored
# as the default for every later bucket.
HOROVOD_FUSION_FIRST_BUCKET_BYTES = "HOROVOD_FUSION_FIRST_BUCKET_BYTES"
# XLA performance-flag preset (docs/overlap.md): "auto" (default — the
# overlap preset when a TPU platform is detected, off elsewhere),
# "overlap" (async collectives + latency-hiding scheduler), or "off".
HOROVOD_XLA_PERF_PRESET = "HOROVOD_XLA_PERF_PRESET"
# Opt-in collective-safety pre-flight (docs/static_analysis.md).
HOROVOD_TPU_STATIC_CHECKS = "HOROVOD_TPU_STATIC_CHECKS"
# Fault tolerance (docs/fault_tolerance.md).
# Stall escalation ladder: periodic re-warn and per-tensor abort windows
# on top of the reference's warn/shutdown pair.
HOROVOD_STALL_REWARN_TIME_SECONDS = "HOROVOD_STALL_REWARN_TIME_SECONDS"
HOROVOD_STALL_ABORT_TIME_SECONDS = "HOROVOD_STALL_ABORT_TIME_SECONDS"
# Control-plane RPC retry budget (fault/backoff.py reads these directly —
# launcher-side processes never construct a Config).
HOROVOD_RPC_RETRIES = "HOROVOD_RPC_RETRIES"
HOROVOD_RPC_BACKOFF_BASE_S = "HOROVOD_RPC_BACKOFF_BASE_S"
HOROVOD_RPC_BACKOFF_MAX_S = "HOROVOD_RPC_BACKOFF_MAX_S"
HOROVOD_RPC_BACKOFF_JITTER = "HOROVOD_RPC_BACKOFF_JITTER"
# Rendezvous server-side wait window (replaces the old hardcoded 60 s).
HOROVOD_COORD_WAIT_TIMEOUT_S = "HOROVOD_COORD_WAIT_TIMEOUT_S"
# Elastic blacklist quarantine: a blacklisted host is re-admitted after
# this many seconds (0 = never), and failure counts decay after it too.
HOROVOD_BLACKLIST_COOLDOWN_S = "HOROVOD_BLACKLIST_COOLDOWN_S"
# Graceful preemption drain (elastic workers): 0 disables the SIGTERM
# notice handler.
HOROVOD_PREEMPTION_GRACEFUL = "HOROVOD_PREEMPTION_GRACEFUL"
# Deterministic fault injection (fault/plan.py): the plan itself, the
# event-log path, and the seed for retry jitter in chaos runs.
HOROVOD_FAULT_PLAN = "HOROVOD_FAULT_PLAN"
HOROVOD_FAULT_EVENT_LOG = "HOROVOD_FAULT_EVENT_LOG"
HOROVOD_FAULT_SEED = "HOROVOD_FAULT_SEED"
# Runtime metrics (docs/metrics.md; horovod_tpu/metrics reads these
# directly, like the fault knobs — launcher-side processes never build a
# Config): enable the tap, pin the driver's /metrics (KV) port, and set
# the worker snapshot push cadence.
HOROVOD_METRICS = "HOROVOD_METRICS"
HOROVOD_METRICS_PORT = "HOROVOD_METRICS_PORT"
HOROVOD_METRICS_PUSH_INTERVAL_S = "HOROVOD_METRICS_PUSH_INTERVAL_S"
# Respawn-mode data-loss guard: fail (instead of loudly warning) when a
# restart generation > 1 finds no restored snapshot on any rank.
HOROVOD_ELASTIC_REQUIRE_SNAPSHOT = "HOROVOD_ELASTIC_REQUIRE_SNAPSHOT"
# Data-plane integrity guard (docs/fault_tolerance.md "Data-plane
# integrity"; horovod_tpu/guard reads these directly, like the fault and
# metrics knobs): non-finite gradient policy (off|warn|zero|skip|abort),
# parameter-digest agreement cadence in commits (0 = off), and what a
# digest mismatch without an agreeing majority does (rollback|root).
HOROVOD_GUARD_NONFINITE = "HOROVOD_GUARD_NONFINITE"
HOROVOD_GUARD_DIGEST_STEPS = "HOROVOD_GUARD_DIGEST_STEPS"
HOROVOD_GUARD_NO_QUORUM = "HOROVOD_GUARD_NO_QUORUM"
# Control-plane availability (docs/fault_tolerance.md "Control-plane
# availability"; run/journal.py + run/elastic_driver.py + elastic read
# these directly): explicit driver-journal path (default:
# <output-dir>/driver_journal.json), consecutive failed commit-time
# driver probes before a worker votes to park, the --auto-resume
# supervisor's restart budget, and the KV blackout the restart_driver
# fault holds before replaying the journal in-process.
HOROVOD_DRIVER_JOURNAL = "HOROVOD_DRIVER_JOURNAL"
HOROVOD_DRIVER_LOST_PROBES = "HOROVOD_DRIVER_LOST_PROBES"
HOROVOD_DRIVER_MAX_RESTARTS = "HOROVOD_DRIVER_MAX_RESTARTS"
HOROVOD_FAULT_DRIVER_BLACKOUT_S = "HOROVOD_FAULT_DRIVER_BLACKOUT_S"
# Topology-aware collective compositor (docs/topology.md; horovod_tpu/topo
# reads these directly). HOROVOD_TOPOLOGY_MODEL is a JSON file path or
# inline JSON overriding the detected interconnect model (per-hop
# bandwidth/latency, or a full hop list). HOROVOD_TOPOLOGY_PLAN="auto"
# lets the eager executor enable hierarchical lowerings whenever the
# compositor's cost model selects a non-flat plan (the legacy
# HOROVOD_HIERARCHICAL_* booleans force them unconditionally); "off"
# (default) keeps plan selection advisory (metrics/introspection only).
HOROVOD_TOPOLOGY_MODEL = "HOROVOD_TOPOLOGY_MODEL"
HOROVOD_TOPOLOGY_PLAN = "HOROVOD_TOPOLOGY_PLAN"
# Quantized wire compression (docs/overlap.md "Quantized wire
# compression"): default for the compiled-mode ``quantized`` knob when
# the call site leaves it unset — "1"/"true"/"int8" moves gradient
# buckets over the int8+scales wire (flat: every hop; hierarchical:
# DCN only), with the EF residual carried in optimizer state.
HOROVOD_QUANTIZED_WIRE = "HOROVOD_QUANTIZED_WIRE"
# Fused TP overlap (docs/parallelism.md "Fused TP overlap"): route the
# composed DP×TP fast path's column/row layers through the chunked
# collective-matmul primitives (ops/collective_matmul.py) so the
# model-axis psums dissolve into ppermute chains that ride the wire
# while the MXU multiplies. HOROVOD_TP_OVERLAP_CHUNKS sub-chunks each
# ring hop's payload (0 = auto: one token chunk per rank).
HOROVOD_TP_OVERLAP = "HOROVOD_TP_OVERLAP"
HOROVOD_TP_OVERLAP_CHUNKS = "HOROVOD_TP_OVERLAP_CHUNKS"
# Compiled-path offline tuning (docs/autotune.md "Compiled-path offline
# tuning"): path to a ``tuned.json`` emitted by
# tools/autotune_compiled.py. ``make_train_step`` / DistributedOptimizer
# read it when their ``tuned`` argument is left unset and apply the
# pinned knobs IF the live step's signature matches; a mismatch warns
# loudly and runs untuned. horovod_tpu/tune reads this directly.
HOROVOD_TUNED_FILE = "HOROVOD_TUNED_FILE"
# Fleet-simulation calibration (docs/simulation.md): path to a
# ``calibration.json`` fitted by ``tools/fleet_sim.py --calibrate`` from
# merged trace data. The simulator, the tuner's cost objectives
# (``tune(calibration=...)``), and bench's sim block read it when their
# ``calibration`` argument is left unset and apply the per-hop constants
# IF the interconnect-model signature (hop ladder) matches; a mismatch
# warns loudly and runs on generation defaults. sim/calibrate.py reads
# this directly.
HOROVOD_CALIBRATION_FILE = "HOROVOD_CALIBRATION_FILE"
# Fleet tracing (docs/timeline.md "Fleet tracing"; horovod_tpu/trace
# reads these directly, like the fault/metrics/guard knobs):
# HOROVOD_TRACE arms the span ring + step tap + KV shipping;
# HOROVOD_TRACE_DIR points the flight recorder and the driver's
# collection at a directory (setting it alone also arms the recorder);
# the remaining knobs set the ring capacity (events), the worker push
# cadence, and the cross-rank step skew above which the slowest rank is
# charged one hvd_straggler_total count.
HOROVOD_TRACE = "HOROVOD_TRACE"
HOROVOD_TRACE_DIR = "HOROVOD_TRACE_DIR"
HOROVOD_TRACE_RING_EVENTS = "HOROVOD_TRACE_RING_EVENTS"
HOROVOD_TRACE_PUSH_INTERVAL_S = "HOROVOD_TRACE_PUSH_INTERVAL_S"
HOROVOD_TRACE_STRAGGLER_THRESHOLD_S = "HOROVOD_TRACE_STRAGGLER_THRESHOLD_S"
# Self-driving fleet (docs/fault_tolerance.md "Self-driving fleet";
# run/selfdrive.py reads these directly, like the trace knobs):
# HOROVOD_QUARANTINE_STRIKES arms the slowness quarantine — a rank
# charged the last finisher for that many of the last
# HOROVOD_QUARANTINE_WINDOW observed steps (default 2x strikes) gets its
# host quarantined with the blacklist cooldown/decay/relapse-doubling
# machinery on an independent reason="slow" ledger
# (HOROVOD_QUARANTINE_COOLDOWN_S, default = the blacklist cooldown;
# 0 = permanent). HOROVOD_REPLAN_DIVERGENCE arms the live re-plan: when
# the calibrated per-hop constants (HOROVOD_CALIBRATION_FILE) drift from
# the generation defaults beyond this |ratio-1| threshold, the driver
# re-prices the tuner's free objectives, verifies the winning plans
# symbolically, and publishes a commit-boundary re-plan notice (checked
# every HOROVOD_REPLAN_CHECK_S seconds; HOROVOD_REPLAN_SPEC optionally
# pins the program priced). HOROVOD_SPARES keeps that many hot-spare
# workers parked at the spare gate (hvdrun --spares wins). All unset =
# the control loop is off, driver behavior unchanged.
HOROVOD_QUARANTINE_STRIKES = "HOROVOD_QUARANTINE_STRIKES"
HOROVOD_QUARANTINE_WINDOW = "HOROVOD_QUARANTINE_WINDOW"
HOROVOD_QUARANTINE_COOLDOWN_S = "HOROVOD_QUARANTINE_COOLDOWN_S"
HOROVOD_REPLAN_DIVERGENCE = "HOROVOD_REPLAN_DIVERGENCE"
# HOROVOD_REPLAN_SKEW_S is the second trigger: a SUSTAINED mean
# cross-rank step skew (StepSkewTracker trend over the recent window)
# above this many seconds also re-plans, once per generation.
HOROVOD_REPLAN_SKEW_S = "HOROVOD_REPLAN_SKEW_S"
HOROVOD_REPLAN_CHECK_S = "HOROVOD_REPLAN_CHECK_S"
HOROVOD_REPLAN_SPEC = "HOROVOD_REPLAN_SPEC"
HOROVOD_SPARES = "HOROVOD_SPARES"

# --- distributed inference serving (docs/serving.md) ---
# HOROVOD_SERVE=1 switches a launched worker into serving mode (set by
# `hvdrun --serve`); HOROVOD_SERVE_PORT pins the HTTP frontend.
# HOROVOD_SERVE_REPLICAS is the number of DP serving replicas the engine
# runs; HOROVOD_SERVE_MAX_BATCH x HOROVOD_SERVE_MAX_WAIT_US shape the
# continuous batcher (a batch dispatches when full OR when its oldest
# request has waited max-wait — the starvation-freedom bound);
# HOROVOD_SERVE_QUEUE_BOUND caps admission (beyond it requests are
# refused loudly, never queued unboundedly). HOROVOD_SERVE_SLO_MS is the
# latency SLO target the selfdrive scale loop burns against;
# HOROVOD_SERVE_MAX_TOKENS bounds tokens generated per request.
# HOROVOD_SERVE_KV_PAGES x HOROVOD_SERVE_PAGE_SIZE size the paged
# decode-state (KV-cache) pool, allocated/freed per request slot.
HOROVOD_SERVE = "HOROVOD_SERVE"
HOROVOD_SERVE_PORT = "HOROVOD_SERVE_PORT"
HOROVOD_SERVE_REPLICAS = "HOROVOD_SERVE_REPLICAS"
HOROVOD_SERVE_MAX_BATCH = "HOROVOD_SERVE_MAX_BATCH"
HOROVOD_SERVE_MAX_WAIT_US = "HOROVOD_SERVE_MAX_WAIT_US"
HOROVOD_SERVE_QUEUE_BOUND = "HOROVOD_SERVE_QUEUE_BOUND"
HOROVOD_SERVE_SLO_MS = "HOROVOD_SERVE_SLO_MS"
HOROVOD_SERVE_MAX_TOKENS = "HOROVOD_SERVE_MAX_TOKENS"
HOROVOD_SERVE_KV_PAGES = "HOROVOD_SERVE_KV_PAGES"
HOROVOD_SERVE_PAGE_SIZE = "HOROVOD_SERVE_PAGE_SIZE"
# Queue-depth/SLO-burn scale triggers (run/selfdrive.ServeScalePolicy —
# the PR 14 "Remaining" hook): sustained mean queue depth above
# SCALE_OUT_DEPTH or an SLO-violation fraction above SLO_BURN proposes a
# DP scale-out (spare promotion); sustained depth below SCALE_IN_DEPTH
# with zero burn proposes a scale-in (quarantine-shrink). WINDOW is the
# sliding observation window in supervision beats, COOLDOWN the minimum
# beats between decisions (hysteresis).
HOROVOD_SERVE_SCALE_OUT_DEPTH = "HOROVOD_SERVE_SCALE_OUT_DEPTH"
HOROVOD_SERVE_SCALE_IN_DEPTH = "HOROVOD_SERVE_SCALE_IN_DEPTH"
HOROVOD_SERVE_SLO_BURN = "HOROVOD_SERVE_SLO_BURN"
HOROVOD_SERVE_SCALE_WINDOW = "HOROVOD_SERVE_SCALE_WINDOW"
HOROVOD_SERVE_SCALE_COOLDOWN = "HOROVOD_SERVE_SCALE_COOLDOWN"

# Fusion buffer rounding unit: reference common.h:94 FUSION_BUFFER_ATOMIC_UNIT=64.
FUSION_BUFFER_ATOMIC_UNIT = 64

# --- XLA performance-flag presets (docs/overlap.md) ---
# The flags the streamed-reduction path needs to turn N independent bucket
# psums into async all-reduce-start/-done pairs hidden behind backward
# compute. Applied to XLA_FLAGS before the backend initializes (flag
# parsing happens at first backend/compiler touch) and usable as
# compiler_options for AOT compiles (tools/tpu_profile_overlap.py).
XLA_PERF_PRESETS = {
    "off": {},
    "overlap": {
        "xla_tpu_enable_latency_hiding_scheduler": "true",
        "xla_tpu_enable_async_collective_fusion": "true",
        "xla_tpu_enable_async_collective_fusion_fuse_all_reduce": "true",
        "xla_enable_async_all_reduce": "true",
    },
}

# Record of the last apply_xla_perf_preset() call, for the timeline/metrics
# to stamp: {"preset": name, "flags": {...}, "applied": [...], "late": bool}.
_applied_perf_preset = None


def _tpu_platform_hinted() -> bool:
    """TPU detection WITHOUT initializing a jax backend: only an EXPLICIT
    platform pin counts. A merely-importable libtpu wheel is not enough —
    a CPU-platform process whose XLA flag registry doesn't know the
    xla_tpu_* names dies with "Unknown flags in XLA_FLAGS" at first
    backend touch, so guessing wrong is fatal, not just noisy. On a TPU VM
    with an unpinned platform, set HOROVOD_XLA_PERF_PRESET=overlap."""
    plats = (
        os.environ.get("JAX_PLATFORMS", "")
        or os.environ.get("JAX_PLATFORM_NAME", "")
    ).lower()
    return "tpu" in plats


def resolve_perf_preset(preset: str | None = None) -> tuple:
    """Resolve a preset name (None reads HOROVOD_XLA_PERF_PRESET, default
    "auto") to (name, flags). "auto" means the overlap preset on TPU and
    off elsewhere — the TPU-only xla_tpu_* flags would be noise on other
    platforms."""
    name = (preset or os.environ.get(HOROVOD_XLA_PERF_PRESET, "")
            or "auto").strip().lower()
    if name == "auto":
        name = "overlap" if _tpu_platform_hinted() else "off"
    if name not in XLA_PERF_PRESETS:
        raise ValueError(
            f"unknown {HOROVOD_XLA_PERF_PRESET} {name!r}; "
            f"choose from {sorted(XLA_PERF_PRESETS)} or 'auto'"
        )
    return name, dict(XLA_PERF_PRESETS[name])


def apply_xla_perf_preset(preset: str | None = None) -> dict:
    """Append the resolved preset's flags to XLA_FLAGS (idempotent — a flag
    already mentioned there is left alone, so user overrides win) and
    record what happened for the timeline/metrics. Must run before the
    first jax backend touch to take effect; when it runs late the record
    says so instead of lying about the flags being live."""
    global _applied_perf_preset
    name, flags = resolve_perf_preset(preset)
    applied = []
    if flags:
        current = os.environ.get("XLA_FLAGS", "")
        extra = []
        for k, v in flags.items():
            if k in current:
                continue
            extra.append(f"--{k}={v}")
            applied.append(k)
        if extra:
            os.environ["XLA_FLAGS"] = (current + " " + " ".join(extra)).strip()
    # A flag appended after the first backend touch is parsed too late to
    # take effect; record that rather than claiming the flags are live.
    late = False
    try:
        import sys

        if "jax" in sys.modules:
            from jax._src import xla_bridge as _xb

            late = bool(applied) and bool(getattr(_xb, "_backends", None))
    except Exception:  # noqa: BLE001 - best-effort introspection only
        pass
    record = {"preset": name, "flags": flags, "applied": applied,
              "late": late}
    _applied_perf_preset = record
    try:
        from .. import metrics as _metrics

        if _metrics.ACTIVE:
            _metrics.TAP.set(
                "hvd_xla_perf_preset_info", 1.0, preset=name,
                flags=",".join(sorted(flags)) or "none",
            )
    except Exception:  # noqa: BLE001 - metrics must never block init
        pass
    return record


def applied_perf_preset() -> dict | None:
    """The record of the last preset application (None before any)."""
    return _applied_perf_preset


def _get_bool(name: str, default: bool = False) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.strip().lower() in ("1", "true", "yes", "on")


def _get_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    if v is None or not v.strip():
        return default
    try:
        return int(v)
    except ValueError:
        return default


def _get_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    if v is None or not v.strip():
        return default
    try:
        return float(v)
    except ValueError:
        return default


@dataclass
class Config:
    """Runtime knobs resolved at init.

    Defaults follow the reference: 64 MB fusion threshold and 5 ms cycle time
    (``operations.cc:411-417``), cache capacity 1024 (``global_state.h:88``),
    60 s stall warning (``stall_inspector.h:72-80``).
    """

    fusion_threshold_bytes: int = 64 * 1024 * 1024
    # Streamed (overlap) reduction: first-bucket cap (DDP idiom) and the
    # XLA perf-flag preset name ("auto" resolves per platform).
    fusion_first_bucket_bytes: int = 1024 * 1024
    xla_perf_preset: str = "auto"
    # Compiled-path pinned tuning file ("" = untuned; docs/autotune.md).
    tuned_file: str = ""
    calibration_file: str = ""
    cycle_time_ms: float = 5.0
    cache_capacity: int = 1024
    cache_enabled: bool = True
    hierarchical_allreduce: bool = False
    hierarchical_allgather: bool = False
    # "auto" = the eager executor goes hierarchical whenever the topology
    # compositor's cost model selects a non-flat plan; "off" = planner is
    # advisory only (docs/topology.md).
    topology_plan: str = "off"
    autotune: bool = False
    autotune_log_file: str = ""
    autotune_warmup_samples: int = 3
    autotune_steps_per_sample: int = 10
    autotune_bayes_opt_max_samples: int = 20
    autotune_gaussian_process_noise: float = 0.8
    timeline_filename: str = ""
    # Optional jax.profiler trace session directory: started at init,
    # stopped at shutdown; plan executions inside carry the same
    # hvd_plan_<id> annotation the timeline stamps (SURVEY §5).
    profiler_dir: str = ""

    timeline_mark_cycles: bool = False
    stall_check_disable: bool = False
    stall_warning_time_seconds: float = 60.0
    stall_shutdown_time_seconds: float = 0.0
    # Escalation ladder between warn and shutdown: re-warn every
    # ``stall_rewarn_seconds`` (0 = reuse the warn interval) and abort the
    # individual stalled tensor — a named Status.Aborted handed to its
    # waiters — after ``stall_abort_time_seconds`` (0 = disabled).
    stall_rewarn_seconds: float = 0.0
    stall_abort_time_seconds: float = 0.0
    adasum_chunk_size: int = 1 << 26
    log_level: str = "warning"
    eager_backend: str = "auto"  # auto | xla | local
    mesh_axes: str = ""  # e.g. "data:8" or "data:4,model:2"
    # Run the collective-safety static analyzers as a pre-flight on
    # DistributedOptimizer/allreduce setup (analysis/preflight.py).
    static_checks: bool = False
    # Distributed inference serving (docs/serving.md): serve=True flips
    # a launched worker into `hvd.serve()` mode; the remaining fields
    # shape the continuous batcher, the paged KV-cache pool, and the
    # SLO target the selfdrive scale loop burns against.
    # Fused TP overlap: collective-matmul path selection for the
    # composed builder's tensor-parallel layers, and its chunking.
    tp_overlap: bool = False
    tp_overlap_chunks: int = 0
    serve: bool = False
    serve_port: int = 0
    serve_replicas: int = 1
    serve_max_batch: int = 8
    serve_max_wait_us: int = 2000
    serve_queue_bound: int = 1024
    serve_slo_ms: float = 500.0
    serve_max_tokens: int = 32
    serve_kv_pages: int = 256
    serve_page_size: int = 16
    extra: dict = field(default_factory=dict)

    @staticmethod
    def from_env() -> "Config":
        cfg = Config()
        cfg.fusion_threshold_bytes = _get_int(
            HOROVOD_FUSION_THRESHOLD, cfg.fusion_threshold_bytes
        )
        cfg.fusion_first_bucket_bytes = _get_int(
            HOROVOD_FUSION_FIRST_BUCKET_BYTES, cfg.fusion_first_bucket_bytes
        )
        cfg.xla_perf_preset = (
            os.environ.get(HOROVOD_XLA_PERF_PRESET, "") or cfg.xla_perf_preset
        )
        cfg.tuned_file = os.environ.get(HOROVOD_TUNED_FILE, cfg.tuned_file)
        cfg.calibration_file = os.environ.get(
            HOROVOD_CALIBRATION_FILE, cfg.calibration_file
        )
        # Reference accepts cycle time in ms as float via HOROVOD_CYCLE_TIME.
        cfg.cycle_time_ms = _get_float(HOROVOD_CYCLE_TIME, cfg.cycle_time_ms)
        cfg.cache_capacity = _get_int(HOROVOD_CACHE_CAPACITY, cfg.cache_capacity)
        cfg.cache_enabled = cfg.cache_capacity > 0
        cfg.hierarchical_allreduce = _get_bool(HOROVOD_HIERARCHICAL_ALLREDUCE)
        cfg.hierarchical_allgather = _get_bool(HOROVOD_HIERARCHICAL_ALLGATHER)
        cfg.topology_plan = (
            os.environ.get(HOROVOD_TOPOLOGY_PLAN, "") or cfg.topology_plan
        ).strip().lower()
        cfg.autotune = _get_bool(HOROVOD_AUTOTUNE)
        cfg.autotune_log_file = os.environ.get(HOROVOD_AUTOTUNE_LOG, "")
        cfg.autotune_warmup_samples = _get_int(
            HOROVOD_AUTOTUNE_WARMUP_SAMPLES, cfg.autotune_warmup_samples
        )
        cfg.autotune_steps_per_sample = _get_int(
            HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE, cfg.autotune_steps_per_sample
        )
        cfg.autotune_bayes_opt_max_samples = _get_int(
            HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES, cfg.autotune_bayes_opt_max_samples
        )
        cfg.autotune_gaussian_process_noise = _get_float(
            HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE,
            cfg.autotune_gaussian_process_noise,
        )
        cfg.timeline_filename = os.environ.get(HOROVOD_TIMELINE, "")
        cfg.profiler_dir = os.environ.get(HOROVOD_PROFILER_DIR, "")
        cfg.timeline_mark_cycles = _get_bool(HOROVOD_TIMELINE_MARK_CYCLES)
        cfg.stall_check_disable = _get_bool(HOROVOD_STALL_CHECK_DISABLE)
        cfg.stall_warning_time_seconds = _get_float(
            HOROVOD_STALL_CHECK_TIME_SECONDS, cfg.stall_warning_time_seconds
        )
        cfg.stall_shutdown_time_seconds = _get_float(
            HOROVOD_STALL_SHUTDOWN_TIME_SECONDS, cfg.stall_shutdown_time_seconds
        )
        cfg.stall_rewarn_seconds = _get_float(
            HOROVOD_STALL_REWARN_TIME_SECONDS, cfg.stall_rewarn_seconds
        )
        cfg.stall_abort_time_seconds = _get_float(
            HOROVOD_STALL_ABORT_TIME_SECONDS, cfg.stall_abort_time_seconds
        )
        cfg.adasum_chunk_size = _get_int(
            HOROVOD_ADASUM_MPI_CHUNK_SIZE, cfg.adasum_chunk_size
        )
        cfg.log_level = os.environ.get(HOROVOD_LOG_LEVEL, cfg.log_level)
        cfg.eager_backend = os.environ.get(HOROVOD_TPU_EAGER_BACKEND, cfg.eager_backend)
        cfg.mesh_axes = os.environ.get(HOROVOD_TPU_MESH_AXES, cfg.mesh_axes)
        cfg.static_checks = _get_bool(HOROVOD_TPU_STATIC_CHECKS)
        cfg.tp_overlap = _get_bool(HOROVOD_TP_OVERLAP)
        cfg.tp_overlap_chunks = _get_int(
            HOROVOD_TP_OVERLAP_CHUNKS, cfg.tp_overlap_chunks
        )
        cfg.serve = _get_bool(HOROVOD_SERVE)
        cfg.serve_port = _get_int(HOROVOD_SERVE_PORT, cfg.serve_port)
        cfg.serve_replicas = _get_int(
            HOROVOD_SERVE_REPLICAS, cfg.serve_replicas
        )
        cfg.serve_max_batch = _get_int(
            HOROVOD_SERVE_MAX_BATCH, cfg.serve_max_batch
        )
        cfg.serve_max_wait_us = _get_int(
            HOROVOD_SERVE_MAX_WAIT_US, cfg.serve_max_wait_us
        )
        cfg.serve_queue_bound = _get_int(
            HOROVOD_SERVE_QUEUE_BOUND, cfg.serve_queue_bound
        )
        cfg.serve_slo_ms = _get_float(HOROVOD_SERVE_SLO_MS, cfg.serve_slo_ms)
        cfg.serve_max_tokens = _get_int(
            HOROVOD_SERVE_MAX_TOKENS, cfg.serve_max_tokens
        )
        cfg.serve_kv_pages = _get_int(
            HOROVOD_SERVE_KV_PAGES, cfg.serve_kv_pages
        )
        cfg.serve_page_size = _get_int(
            HOROVOD_SERVE_PAGE_SIZE, cfg.serve_page_size
        )
        return cfg
