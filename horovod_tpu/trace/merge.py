"""Fleet trace merge: rank windows + driver events → one Chrome trace.

The rendering half of ``tools/trace_merge.py`` (importable so the tests
and the trace smoke drive it in-process). Input is the directory the
elastic driver collects into (``<output-dir>/trace/`` by default):

- ``rank.<r>.json``   — per-rank span windows (``TraceTap.window()``
  shape, persisted by ``ElasticDriver._trace_collect``);
- ``driver.json``     — the driver's own window (elastic/HA events);
- ``flight.rank<r>.json`` — flight-recorder dumps (``--postmortem``);
- ``postmortem.json`` — the driver-collected dump bundle.

Output is Chrome-tracing / Perfetto JSON: one process lane per rank
(pid = rank), the driver on its own high-pid lane, per-lane
``hvd_clock_offset`` metadata (the RTT/2 estimate is recorded, never
applied — timestamps stay raw wall clock), fault event-log lines as
instant markers, and — in postmortem mode — a ``DEATH:<reason>`` marker
per dumped rank so "the last N seconds before death, all ranks,
aligned" reads off one screen.

Determinism: given the same inputs the output bytes are identical
(events sorted on a total key, ``sort_keys`` JSON) — the property
``tools/trace_smoke.py`` locks across two runs.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, List, Optional, Tuple

# The driver's lane must sort after every plausible rank pid.
DRIVER_PID = 1_000_000

# Stable per-category virtual thread ids inside a rank's lane.
TID_STEPS = 0
TID_EVENTS = 1
TID_EVENT_LOG = 2
# Timeline-mirrored records keep their per-tensor tid, offset into their
# own band so they never collide with the bands above.
TID_TIMELINE_BASE = 10


def load_chrome_trace(path: str) -> List[dict]:
    """Load a catapult JSON array, tolerating the unterminated form both
    timeline writers leave behind on crash (reference behavior: partial
    traces must still load)."""
    with open(path) as f:
        text = f.read()
    try:
        return json.loads(text)
    except ValueError:
        repaired = text.rstrip().rstrip(",")
        if not repaired.endswith("]"):
            repaired += "\n]"
        return json.loads(repaired)


def _load_json(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    return doc if isinstance(doc, dict) else None


def read_dir(directory: str) -> Tuple[Dict[int, dict], Optional[dict]]:
    """Read the driver-collected rank windows (+ the driver's own) from
    a trace directory."""
    ranks: Dict[int, dict] = {}
    driver = None
    for fn in sorted(os.listdir(directory)):
        m = re.fullmatch(r"rank\.(\d+)\.json", fn)
        if m:
            doc = _load_json(os.path.join(directory, fn))
            if doc is not None:
                ranks[int(m.group(1))] = doc
        elif fn == "driver.json":
            driver = _load_json(os.path.join(directory, fn))
    return ranks, driver


def read_flight_dumps(directory: str) -> Dict[int, dict]:
    """Read flight-recorder dumps — the driver-collected
    ``postmortem.json`` bundle when present, else the raw per-rank dump
    files the workers wrote."""
    bundle = _load_json(os.path.join(directory, "postmortem.json"))
    dumps: Dict[int, dict] = {}
    if bundle and isinstance(bundle.get("dumps"), list):
        for doc in bundle["dumps"]:
            if isinstance(doc, dict) and "rank" in doc:
                dumps[int(doc["rank"])] = doc
        return dumps
    for fn in sorted(os.listdir(directory)):
        m = re.fullmatch(r"flight\.rank(\d+)\.json", fn)
        if m:
            doc = _load_json(os.path.join(directory, fn))
            if doc is not None:
                dumps[int(m.group(1))] = doc
    return dumps


def _lane_meta(pid: int, label: str) -> List[dict]:
    return [{
        "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
        "args": {"name": label},
    }]


def _thread_meta(pid: int) -> List[dict]:
    return [
        {"name": "thread_name", "ph": "M", "pid": pid, "tid": TID_STEPS,
         "args": {"name": "steps"}},
        {"name": "thread_name", "ph": "M", "pid": pid, "tid": TID_EVENTS,
         "args": {"name": "events"}},
        {"name": "thread_name", "ph": "M", "pid": pid,
         "tid": TID_EVENT_LOG, "args": {"name": "event_log"}},
    ]


def _emit_window(doc: dict, pid: int, base_ts: float,
                 label: str) -> List[dict]:
    out = _lane_meta(pid, label) + _thread_meta(pid)
    clock = doc.get("clock") or {}
    out.append({
        "name": "hvd_clock_offset", "ph": "M", "pid": pid, "tid": 0,
        "args": {
            "offset_s": clock.get("offset_s", 0.0),
            "rtt_s": clock.get("rtt_s", 0.0),
            "estimated": bool(clock.get("estimated", False)),
            "note": "recorded, not applied; timestamps are raw wall clock",
        },
    })

    def us(ts: float) -> float:
        return round((float(ts) - base_ts) * 1e6, 1)

    for ev in doc.get("events") or []:
        ph = ev.get("ph", "i")
        cat = ev.get("cat", "event")
        if ph == "M" and cat != "timeline":
            # Non-timeline metadata already rendered (clock) or carries
            # no timestamp worth a lane slot.
            continue
        if cat == "step":
            tid = TID_STEPS
        elif cat == "timeline":
            tid = TID_TIMELINE_BASE + int(ev.get("tid", 0) or 0)
        else:
            tid = TID_EVENTS
        rec: Dict[str, Any] = {
            "name": ev.get("name", ""),
            "ph": ph,
            "pid": pid,
            "tid": tid,
            "ts": us(ev.get("ts", base_ts)),
            "cat": cat,
        }
        if ph == "i":
            rec["s"] = "t"
        if "dur" in ev:
            rec["dur"] = round(float(ev["dur"]) * 1e6, 1)
        if ev.get("args"):
            rec["args"] = ev["args"]
        if ph == "M" and cat == "timeline":
            # Mirrored thread_name metadata names the per-tensor lanes.
            rec.pop("ts", None)
            rec.pop("s", None)
        out.append(rec)
    for line in doc.get("event_log") or []:
        if not isinstance(line, dict):
            continue
        out.append({
            "name": f"{line.get('site', '?')}:{line.get('action', '?')}",
            "ph": "i", "s": "t", "pid": pid, "tid": TID_EVENT_LOG,
            # Event-log lines carry no wall clock (they are the
            # byte-diffable deterministic record); pin them to the lane
            # origin, ordered by their sequence number.
            "ts": float(int(line.get("seq", 0) or 0)),
            "cat": "event_log",
            "args": {k: line[k] for k in sorted(line) if line[k] is not None},
        })
    return out


def _min_ts(docs: List[dict]) -> float:
    tss = [
        float(ev["ts"])
        for doc in docs
        for ev in (doc.get("events") or [])
        if "ts" in ev
    ] + [
        float(s[1])
        for doc in docs
        for s in (doc.get("steps") or [])
        if isinstance(s, (list, tuple)) and len(s) >= 3
    ]
    return min(tss) if tss else 0.0


def _sort_key(ev: dict):
    return (
        0 if ev.get("ph") == "M" else 1,
        ev.get("pid", 0),
        ev.get("ts", 0.0),
        ev.get("tid", 0),
        ev.get("name", ""),
        ev.get("ph", ""),
    )


def merge_windows(ranks: Dict[int, dict],
                  driver: Optional[dict] = None) -> dict:
    """Merge rank windows (+ the driver's) into one Chrome trace doc."""
    docs = [ranks[r] for r in sorted(ranks)]
    if driver:
        docs.append(driver)
    base = _min_ts(docs)
    events: List[dict] = []
    for r in sorted(ranks):
        events.extend(_emit_window(ranks[r], r, base, f"rank {r}"))
    if driver:
        events.extend(_emit_window(driver, DRIVER_PID, base, "driver"))
    events.sort(key=_sort_key)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "source": "horovod_tpu trace_merge",
            "ranks": sorted(ranks),
            "driver_lane": bool(driver),
            "clock_note": (
                "per-lane hvd_clock_offset metadata records each "
                "worker's KV-ping RTT/2 estimate against the driver; "
                "timestamps are raw wall clock"
            ),
        },
    }


def merge_postmortem(dumps: Dict[int, dict],
                     window_s: Optional[float] = None) -> dict:
    """Render flight-recorder dumps as the aligned last-moments view:
    every dumped rank gets its lane plus a ``DEATH:<reason>`` marker at
    its dump instant; ``window_s`` trims each lane to the final N
    seconds before its own death."""
    trimmed: Dict[int, dict] = {}
    for r, doc in dumps.items():
        d = dict(doc)
        if window_s is not None:
            cutoff = float(d.get("dumped_at", 0.0)) - float(window_s)
            d["events"] = [
                ev for ev in (d.get("events") or [])
                if float(ev.get("ts", 0.0)) >= cutoff
            ]
            d["steps"] = [
                s for s in (d.get("steps") or [])
                if isinstance(s, (list, tuple)) and len(s) >= 3
                and float(s[2]) >= cutoff
            ]
        trimmed[r] = d
    out = merge_windows(trimmed)
    base = _min_ts(list(trimmed.values()))
    deaths = []
    for r in sorted(trimmed):
        d = trimmed[r]
        deaths.append({
            "name": f"DEATH:{d.get('reason', 'unknown')}",
            "ph": "i", "s": "g", "pid": r, "tid": TID_EVENTS,
            "ts": round((float(d.get("dumped_at", base)) - base) * 1e6, 1),
            "cat": "death",
            "args": {"reason": d.get("reason", "unknown")},
        })
    out["traceEvents"] = sorted(
        out["traceEvents"] + deaths, key=_sort_key
    )
    out["otherData"]["postmortem"] = {
        "ranks": sorted(trimmed),
        "reasons": {str(r): trimmed[r].get("reason", "unknown")
                    for r in sorted(trimmed)},
    }
    return out


# Schema of the --stats summary (the fleet-sim calibrator's input
# contract, sim/calibrate.py). Bump on any shape change.
STATS_SCHEMA_VERSION = 1

# Span names that count as per-collective timing samples: the eager
# runtime's fused-response spans, the native runtime's plan spans
# (both carry payload bytes), and the simulator's hop-labeled stage
# spans (exact bytes/rounds per hop).
_COLLECTIVE_SPAN_PREFIXES = (
    "hvd_response", "hvd_plan", "hvd_collective_stage",
)


def _round9(v: float) -> float:
    return round(float(v), 9)


def stats_summary(ranks: Dict[int, dict],
                  driver: Optional[dict] = None) -> dict:
    """Machine-readable per-rank, per-stage timing summary of a trace
    directory — the calibrator's input contract (``sim/calibrate.py``).
    Pure data reduction: identical inputs give identical output bytes
    (floats rounded, keys sorted by the CLI's serializer), so two
    ``--stats`` passes over one trace diff clean."""
    out: Dict[str, Any] = {
        "schema_version": STATS_SCHEMA_VERSION,
        "world_size": len(ranks),
        "ranks": {},
    }
    for r in sorted(ranks):
        doc = ranks[r]
        steps = [
            [int(s[0]), _round9(s[1]), _round9(s[2])]
            for s in (doc.get("steps") or [])
            if isinstance(s, (list, tuple)) and len(s) >= 3
        ]
        durs = sorted(t1 - t0 for _, t0, t1 in steps)
        gaps = sorted(
            steps[i + 1][1] - steps[i][2] for i in range(len(steps) - 1)
        )

        def pct(xs, p):
            if not xs:
                return 0.0
            return _round9(xs[min(int(p * (len(xs) - 1)), len(xs) - 1)])

        collectives = []
        for ev in doc.get("events") or []:
            name = str(ev.get("name", ""))
            if not name.startswith(_COLLECTIVE_SPAN_PREFIXES):
                continue
            if ev.get("ph") != "X" or "dur" not in ev:
                continue
            args = ev.get("args") or {}
            entry: Dict[str, Any] = {
                "name": name,
                "ts": _round9(ev.get("ts", 0.0)),
                "dur_s": _round9(ev["dur"]),
            }
            nbytes = args.get("nbytes", args.get("bytes"))
            if nbytes is not None:
                entry["nbytes"] = int(nbytes)
            for k in ("op", "hop", "rounds", "wire_dtype", "group",
                      "plan"):
                if k in args:
                    entry[k] = args[k]
            collectives.append(entry)
        collectives.sort(key=lambda e: (e["ts"], e["name"]))
        out["ranks"][str(r)] = {
            "step_count": len(steps),
            "steps": steps,
            "step_p50_s": pct(durs, 0.50),
            "step_p99_s": pct(durs, 0.99),
            "gap_p50_s": pct(gaps, 0.50),
            "plan": doc.get("plan") or {},
            "clock": doc.get("clock") or {},
            "collectives": collectives,
            "events_total": len(doc.get("events") or []),
        }
    if driver is not None:
        plans = []
        for ev in driver.get("events") or []:
            if ev.get("name") == "hvd_sim_plan":
                plans.append(dict(ev.get("args") or {}))
        out["driver"] = {
            "events_total": len(driver.get("events") or []),
            "plans": sorted(
                plans, key=lambda p: int(p.get("group", 0))
            ),
        }
    return out


def write_stats(path: str, stats: dict) -> None:
    """Stable serialization for the --stats artifact (same discipline
    as :func:`write_trace`)."""
    from ..utils.checkpoint import _atomic_write

    payload = json.dumps(
        stats, sort_keys=True, separators=(",", ":")
    ).encode()
    _atomic_write(path, lambda f: f.write(payload))


def write_trace(path: str, doc: dict) -> None:
    """Stable serialization (sorted keys, fixed separators) so identical
    inputs give identical bytes."""
    from ..utils.checkpoint import _atomic_write

    payload = json.dumps(
        doc, sort_keys=True, separators=(",", ":")
    ).encode()
    _atomic_write(path, lambda f: f.write(payload))
