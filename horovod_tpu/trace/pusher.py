"""Worker-side trace shipping + driver-side skew attribution.

Same transport pattern as ``metrics/export.MetricsPusher``: workers PUSH
their bounded span windows to the driver's KV rendezvous store (one
small JSON PUT per interval, scope ``trace``, key ``rank.<rank>``); the
driver never scrapes workers. The driver's supervision loop collects the
windows (``ElasticDriver._trace_collect``), persists them next to the
worker logs for ``tools/trace_merge.py``, and feeds the per-step end
timestamps into :class:`StepSkewTracker` — the seam behind
``hvd_step_skew_seconds`` and ``hvd_straggler_total{rank}``.

Clock alignment: at pusher start (worker attach) the driver's wall clock
is sampled over the KV plane (``GET /clock``) a few times; the estimate
``offset = driver_time - (t_send + t_recv)/2`` from the minimum-RTT ping
is RECORDED as trace metadata on every pushed window — never silently
applied to timestamps (docs/timeline.md "Fleet tracing" spells out the
caveat). Skew numbers therefore compare raw wall clocks; on NTP-synced
fleets that is the honest signal, and the recorded offsets let a reader
re-align lanes by hand when it is not.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from . import KV_SCOPE, _count
from . import tap as _tap

logger = logging.getLogger("horovod_tpu.trace")

# Fault event-log lines shipped per window (the tail is enough to
# correlate injections with spans; the full log lives on disk).
EVENT_LOG_TAIL = 200

CLOCK_PINGS = 5


def estimate_clock_offset(addr: str, port: int,
                          pings: int = CLOCK_PINGS) -> Optional[Dict[str, float]]:
    """Estimate this process's wall-clock offset against the driver via
    the KV server's ``/clock`` endpoint: of ``pings`` samples the one
    with the smallest RTT wins (its midpoint is the best bound on when
    the driver read its clock). Returns ``{"offset_s", "rtt_s"}`` or
    None when the endpoint is unreachable."""
    import http.client

    best: Optional[Tuple[float, float]] = None  # (rtt, offset)
    for _ in range(max(pings, 1)):
        try:
            t0 = time.time()
            conn = http.client.HTTPConnection(addr, port, timeout=5)
            try:
                conn.request("GET", "/clock")
                resp = conn.getresponse()
                data = resp.read()
                if resp.status != 200:
                    continue
            finally:
                conn.close()
            t1 = time.time()
            driver_t = float(json.loads(data.decode())["time"])
        except Exception:  # noqa: BLE001 - advisory estimate only
            continue
        rtt = t1 - t0
        offset = driver_t - (t0 + t1) / 2.0
        if best is None or rtt < best[0]:
            best = (rtt, offset)
    if best is None:
        return None
    return {"offset_s": best[1], "rtt_s": best[0]}


class TracePusher:
    """Background publisher of this rank's span window to the driver's
    KV store. Push failures are swallowed — tracing must never take down
    training; the KV client's bounded retry/backoff absorbs transient
    driver unreachability."""

    def __init__(self, addr: str, port: int, rank: int,
                 interval: Optional[float] = None):
        import os

        from ..run.http_server import KVStoreClient

        from . import TRACE_PUSH_INTERVAL_ENV

        self._kv = KVStoreClient(addr, port)
        self._rank = int(rank)
        if interval is None:
            try:
                interval = float(
                    os.environ.get(TRACE_PUSH_INTERVAL_ENV, "") or 2.0
                )
            except ValueError:
                interval = 2.0
        self._interval = max(float(interval), 0.05)
        # Clock-offset estimate at attach, recorded into the tap's
        # metadata (and the hvd_trace_clock_offset_seconds gauge).
        est = estimate_clock_offset(addr, port)
        if est is not None:
            _tap().set_clock(est["offset_s"], est["rtt_s"])
            from .. import metrics as _metrics

            if _metrics.ACTIVE:
                _metrics.TAP.set(
                    "hvd_trace_clock_offset_seconds", est["offset_s"]
                )
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="hvd_trace_pusher", daemon=True
        )
        self._thread.start()

    def push_once(self) -> None:
        doc = _tap().window()
        if not doc:
            return
        # Ship the deterministic fault event-log tail alongside the
        # spans so the merged trace interleaves injections with the
        # activity they perturbed.
        try:
            from ..fault import injector as _fault

            doc["event_log"] = _fault.events()[-EVENT_LOG_TAIL:]
        except Exception:  # noqa: BLE001
            doc["event_log"] = []
        try:
            self._kv.put(
                KV_SCOPE, f"rank.{self._rank}",
                json.dumps(doc, sort_keys=True).encode(),
            )
            _count("hvd_trace_pushes_total")
        except Exception:  # noqa: BLE001 - advisory plane only
            logger.debug("trace push failed", exc_info=True)

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            self.push_once()

    def stop(self) -> None:
        if self._stop.is_set():
            return
        self._stop.set()
        self._thread.join(timeout=5.0)
        # Final push so short jobs still land their terminal window.
        self.push_once()


def decode_window(payload: bytes) -> Optional[Dict[str, Any]]:
    """Driver-side decode of one pushed window (None on junk)."""
    try:
        doc = json.loads(payload.decode())
    except (ValueError, UnicodeDecodeError):
        return None
    return doc if isinstance(doc, dict) else None


class StepSkewTracker:
    """Driver-side per-step cross-rank skew attribution.

    Feed it the freshest windows per rank; for every step index that ALL
    currently-reporting ranks have finished (and that was not already
    charged), it emits ``(step, skew_s, worst_rank)`` where skew is the
    spread of raw wall-clock step-end times and worst_rank the last
    finisher. Each step is charged exactly once — the pushed windows are
    cumulative, so re-observing an index must not double-count."""

    def __init__(self, threshold_s: Optional[float] = None):
        from . import straggler_threshold_s

        self.threshold_s = (
            straggler_threshold_s() if threshold_s is None
            else float(threshold_s)
        )
        self._done: set = set()
        # Keep the charged-set bounded for long runs: indices below the
        # watermark are implicitly done.
        self._watermark = -1
        # Generation the charged-set is keyed to: after an elastic
        # resize ranks are renumbered (and workers restart their step
        # ledgers), so cumulative windows from the old world must never
        # charge the new world's ranks. ``None`` = ungated (non-elastic
        # callers feed windows with no ``gen`` stamp).
        self.generation: Optional[int] = None

    def reset_generation(self, gen: Optional[int] = None) -> None:
        """Re-key the tracker for a new world generation: drop every
        charged index and only consume windows stamped with ``gen``
        from now on. Charges from the OLD generation die with it — a
        parked or removed rank is never charged for steps it did not
        run (tests/test_selfdrive.py locks this)."""
        self._done = set()
        self._watermark = -1
        self.generation = None if gen is None else int(gen)

    def update(self, windows: Dict[int, Dict[str, Any]]
               ) -> List[Tuple[int, float, int]]:
        if self.generation is not None:
            windows = {
                r: doc for r, doc in windows.items()
                if int(doc.get("gen", 0) or 0) == self.generation
            }
        if len(windows) < 2:
            return []
        per_rank: Dict[int, Dict[int, float]] = {}
        for rank, doc in windows.items():
            ends: Dict[int, float] = {}
            for entry in doc.get("steps") or []:
                try:
                    idx, _t0, t1 = entry
                    ends[int(idx)] = float(t1)
                except (TypeError, ValueError):
                    continue
            if ends:
                per_rank[int(rank)] = ends
        if len(per_rank) < 2:
            return []
        common = set.intersection(*(set(e) for e in per_rank.values()))
        out: List[Tuple[int, float, int]] = []
        for idx in sorted(common):
            if idx <= self._watermark or idx in self._done:
                continue
            ends = {r: e[idx] for r, e in per_rank.items()}
            worst = max(ends, key=lambda r: (ends[r], r))
            skew = max(ends.values()) - min(ends.values())
            out.append((idx, skew, worst))
            self._done.add(idx)
        # Compact: everything at-or-below the smallest pending gap.
        while (self._watermark + 1) in self._done:
            self._done.discard(self._watermark + 1)
            self._watermark += 1
        return out
