"""Fleet-wide distributed tracing (docs/timeline.md "Fleet tracing").

The per-rank catapult timeline (``utils/timeline.py``) answers "what did
THIS process do"; this package makes the fleet answerable as ONE
artifact:

- **Span ring + KV shipping**: every rank keeps a bounded in-memory ring
  of recent spans/events (wall-clock stamped) and a background pusher
  ships the window to the driver over the existing KV rendezvous plane
  (same pattern as the metrics snapshot pusher). ``tools/trace_merge.py``
  renders the driver-collected windows as one Perfetto/Chrome trace with
  one process lane per rank plus the driver's elastic/HA events on their
  own lane.
- **Step spans + straggler attribution**: ``make_train_step`` (and the
  elastic ``State.commit`` seam) record host-side step-boundary
  timestamps with the step index and the active plan/correlation ids
  (fusion path, topo plan algorithm, ``wire_dtype``); the driver compares
  per-step end times across ranks into the ``hvd_step_skew_seconds``
  histogram and ``hvd_straggler_total{rank}`` counters.
- **Flight recorder**: the ring doubles as an always-on crash recorder —
  dumped atomically (``utils/checkpoint.py`` tmp+fsync+replace
  discipline) on guard abort, stall-ladder escalation, SIGTERM, and
  uncaught crashes, so "the last N seconds before death, all ranks,
  aligned" survives the process (``tools/trace_merge.py --postmortem``).

Tap discipline — identical to ``fault/injector.py`` / ``metrics`` /
``guard``: with no trace knob set (the production default) the
module-level :data:`ACTIVE` flag is False, :data:`TAP` IS the shared
no-op singleton :data:`NULL_TAP`, instrumented call sites skip the tap
entirely (``if _trace.ACTIVE: ...`` is the whole overhead), and
:func:`wrap_step` returns the step function UNCHANGED (``wrap_step(f)
is f`` — the zero-overhead proof the tests assert).

Clock caveat: rings are stamped with ``time.time()`` (wall clock). The
per-worker offset the pusher estimates against the driver's ``/clock``
endpoint (KV ping RTT/2) is RECORDED as trace metadata, never silently
applied — cross-rank comparisons in the merged trace must be read with
the per-lane ``hvd_clock_offset`` metadata in hand (docs/timeline.md).
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

logger = logging.getLogger("horovod_tpu.trace")

TRACE_ENV = "HOROVOD_TRACE"
TRACE_DIR_ENV = "HOROVOD_TRACE_DIR"
TRACE_RING_ENV = "HOROVOD_TRACE_RING_EVENTS"
TRACE_PUSH_INTERVAL_ENV = "HOROVOD_TRACE_PUSH_INTERVAL_S"
TRACE_STRAGGLER_THRESHOLD_ENV = "HOROVOD_TRACE_STRAGGLER_THRESHOLD_S"

# KV scope worker trace windows are pushed under (driver-side collection
# reads the same scope; mirrors metrics/export.KV_SCOPE).
KV_SCOPE = "trace"

DEFAULT_RING_EVENTS = 2048
DEFAULT_STRAGGLER_THRESHOLD_S = 0.01

# Current flight-dump / pushed-window schema.
SCHEMA = 1


def _ring_capacity() -> int:
    try:
        n = int(os.environ.get(TRACE_RING_ENV, "") or DEFAULT_RING_EVENTS)
    except ValueError:
        n = DEFAULT_RING_EVENTS
    return max(n, 16)


def straggler_threshold_s() -> float:
    """Cross-rank step skew above which the slowest rank is charged one
    ``hvd_straggler_total{rank}`` count (driver-side)."""
    try:
        return float(
            os.environ.get(TRACE_STRAGGLER_THRESHOLD_ENV, "")
            or DEFAULT_STRAGGLER_THRESHOLD_S
        )
    except ValueError:
        return DEFAULT_STRAGGLER_THRESHOLD_S


def trace_dir() -> Optional[str]:
    """Directory for flight-recorder dumps and driver-collected rank
    windows (None = flight dumps disabled)."""
    d = os.environ.get(TRACE_DIR_ENV, "").strip()
    return d or None


def _rank() -> int:
    v = os.environ.get("HOROVOD_RANK", "")
    return int(v) if v.isdigit() else 0


def _count(name: str, value: float = 1.0, **labels) -> None:
    from .. import metrics as _metrics

    if _metrics.ACTIVE:
        _metrics.TAP.inc(name, value, **labels)


class TraceTap:
    """The live tap: a thread-safe bounded ring of span/event records
    plus the step ledger the straggler attribution feeds on.

    Record shape (plain dicts so windows JSON through the KV plane
    unchanged): ``{"name", "ph" ("X"|"i"|"B"|"E"|"M"), "ts" (wall-clock
    seconds), "dur" (seconds, "X" only), "cat", "tid", "args"}``."""

    def __init__(self, ring_capacity: Optional[int] = None):
        cap = ring_capacity or _ring_capacity()
        self._lock = threading.Lock()
        self._ring: "deque[dict]" = deque(maxlen=cap)
        # (step_index, t_begin, t_end) wall-clock step boundaries — the
        # feed the driver's skew tracker consumes.
        self._steps: "deque[tuple]" = deque(maxlen=cap)
        self._step_idx = 0
        # Wrapped-step activity: while a wrap_step tap is recording real
        # step spans, the State.commit marker stays a plain instant so
        # one training step is never double-counted in the skew feed.
        self._wrapped_steps = 0
        self._last_commit_t: Optional[float] = None
        self._commit_idx = 0
        # Correlation ids noted at trace time by the fusion/compositor
        # layers; stamped onto every step span (docs/timeline.md).
        self._plan_args: Dict[str, Any] = {}
        # Clock-offset estimate vs the driver (recorded metadata, never
        # applied to timestamps).
        self.clock: Dict[str, Any] = {
            "offset_s": 0.0, "rtt_s": 0.0, "estimated": False,
        }
        self.rank = _rank()

    # ------------------------------------------------------------ record
    def event(self, name: str, ph: str = "i", cat: str = "event",
              dur: Optional[float] = None, ts: Optional[float] = None,
              tid: int = 0, **args) -> dict:
        rec: Dict[str, Any] = {
            "name": name,
            "ph": ph,
            "ts": time.time() if ts is None else float(ts),
            "cat": cat,
            "tid": int(tid),
        }
        if dur is not None:
            rec["dur"] = float(dur)
        if args:
            rec["args"] = args
        with self._lock:
            self._ring.append(rec)
        return rec

    @contextmanager
    def span(self, name: str, cat: str = "phase", **args):
        t0 = time.time()
        try:
            yield
        finally:
            self.event(name, ph="X", cat=cat, ts=t0,
                       dur=time.time() - t0, **args)

    @contextmanager
    def request(self, request_id: Any, **args):
        """One serving-request span (docs/serving.md): an ``hvd_request``
        "X" event on cat ``request`` covering admission → completion,
        stamped with the request id — the serving analogue of the step
        span, renderable by ``tools/trace_merge.py`` on the same lane
        machinery."""
        t0 = time.time()
        try:
            yield
        finally:
            self.event(
                "hvd_request", ph="X", cat="request", ts=t0,
                dur=time.time() - t0, request_id=str(request_id), **args,
            )

    def timeline_event(self, ev: dict) -> None:
        """Mirror one catapult-timeline record into the ring (wall-clock
        restamped — the timeline's own clock is perf_counter-relative).
        Called from ``utils/timeline.py`` under the ACTIVE gate."""
        rec = {
            "name": ev.get("name", ""),
            "ph": ev.get("ph", "i"),
            "ts": time.time(),
            "cat": "timeline",
            "tid": int(ev.get("tid", 0) or 0),
        }
        args = ev.get("args")
        if args:
            rec["args"] = args
        with self._lock:
            self._ring.append(rec)

    # ------------------------------------------------------- step spans
    def begin_step(self):
        with self._lock:
            idx = self._step_idx
            self._step_idx += 1
        return idx, time.time()

    def end_step(self, token, **args) -> None:
        idx, t0 = token
        t1 = time.time()
        rec = {
            "name": "hvd_step",
            "ph": "X",
            "ts": t0,
            "dur": t1 - t0,
            "cat": "step",
            "tid": 0,
            "args": {"step": idx, **self.plan_args(), **args},
        }
        with self._lock:
            self._ring.append(rec)
            self._steps.append((idx, t0, t1))
            self._wrapped_steps += 1

    @contextmanager
    def step(self, **args):
        token = self.begin_step()
        try:
            yield token[0]
        finally:
            self.end_step(token, **args)

    def commit_step(self, **args) -> None:
        """Mark one elastic commit boundary (``State.commit``). Between
        two commits lies exactly one training step for loops that commit
        per step, so the inter-commit window doubles as the step span —
        unless a :func:`wrap_step` tap is already recording real step
        spans, in which case this stays a plain instant marker (no
        double-counting in the skew feed)."""
        now = time.time()
        with self._lock:
            wrapped = self._wrapped_steps > 0
            last = self._last_commit_t
            self._last_commit_t = now
            idx = self._commit_idx
            self._commit_idx += 1
            self._ring.append({
                "name": "hvd_commit",
                "ph": "i",
                "ts": now,
                "cat": "step",
                "tid": 0,
                "args": {"commit": idx, **args},
            })
            if not wrapped and last is not None:
                self._steps.append((idx - 1, last, now))

    def step_summary(self) -> Dict[str, Any]:
        """Local step-span statistics (``bench.py`` report block)."""
        with self._lock:
            durs = sorted(t1 - t0 for _, t0, t1 in self._steps)
        if not durs:
            return {"steps": 0}

        def pct(p: float) -> float:
            return durs[min(int(p * (len(durs) - 1)), len(durs) - 1)]

        return {
            "steps": len(durs),
            "p50_s": round(pct(0.50), 6),
            "p99_s": round(pct(0.99), 6),
        }

    # ------------------------------------------------- correlation ids
    def note_plan(self, **kw) -> None:
        """Record the active plan/correlation ids (fusion bucket plan,
        topo algorithm, wire dtype) — stamped onto every later step span
        so one trace links step → bucket → collective → hop."""
        with self._lock:
            self._plan_args.update(
                {k: v for k, v in kw.items() if v is not None}
            )

    def plan_args(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._plan_args)

    # ------------------------------------------------------- shipping
    def window(self) -> Dict[str, Any]:
        """The pushable/dumpable view of this rank's recent activity —
        plain data only, bounded by the ring capacity. The window is
        stamped with the CURRENT elastic generation (0 outside elastic
        runs): rank numbers are only meaningful within a generation, so
        the driver's skew attribution must never mix windows across a
        resize (a renumbered or departed rank would be charged for a
        stranger's steps)."""
        with self._lock:
            events = [dict(e) for e in self._ring]
            steps = [list(s) for s in self._steps]
        gen = os.environ.get("HOROVOD_ELASTIC_GEN", "")
        return {
            "schema": SCHEMA,
            "rank": self.rank,
            "gen": int(gen) if gen.isdigit() else 0,
            "clock": dict(self.clock),
            "plan": self.plan_args(),
            "events": events,
            "steps": steps,
        }

    def reset_steps(self) -> None:
        """Restart the step ledger at a world re-formation boundary:
        after an elastic resize ranks are renumbered and a freshly
        promoted worker starts counting from 0, so carrying the old
        cumulative step indices across the generation would misalign
        every cross-rank comparison. The event ring is kept (history is
        still history); only the step-index feed restarts."""
        with self._lock:
            self._steps.clear()
            self._step_idx = 0
            self._wrapped_steps = 0
            self._last_commit_t = None
            self._commit_idx = 0

    def set_clock(self, offset_s: float, rtt_s: float) -> None:
        self.clock = {
            "offset_s": float(offset_s),
            "rtt_s": float(rtt_s),
            "estimated": True,
        }
        self.event(
            "hvd_clock_offset", ph="M", cat="clock",
            offset_s=float(offset_s), rtt_s=float(rtt_s),
        )

    # -------------------------------------------------- flight recorder
    def flight_dump(self, reason: str,
                    directory: Optional[str] = None) -> Optional[str]:
        """Atomically persist the ring (checkpoint.py tmp+fsync+replace
        discipline) as this rank's flight-recorder dump. Returns the
        path, or None when no trace directory is configured. Must never
        raise — it runs on abort/crash paths."""
        try:
            d = directory or trace_dir()
            if not d:
                logger.warning(
                    "flight recorder: no %s configured; dropping the "
                    "%r dump", TRACE_DIR_ENV, reason,
                )
                return None
            os.makedirs(d, exist_ok=True)
            doc = self.window()
            doc["reason"] = reason
            doc["dumped_at"] = time.time()
            payload = json.dumps(doc, sort_keys=True).encode()
            path = os.path.join(d, f"flight.rank{self.rank}.json")
            from ..utils.checkpoint import _atomic_write

            _atomic_write(path, lambda f: f.write(payload))
            _count("hvd_trace_flight_dumps_total", reason=reason)
            logger.warning(
                "flight recorder: dumped %d events to %s (reason: %s)",
                len(doc["events"]), path, reason,
            )
            return path
        except Exception:  # noqa: BLE001 - crash paths must stay crashable
            logger.exception("flight recorder dump failed")
            return None


class _NullTraceTap:
    """Shared no-op tap installed while tracing is disabled. Sites gate
    on :data:`ACTIVE` and never reach it; holders of a tap reference pay
    one empty method call."""

    rank = 0
    clock: Dict[str, Any] = {}

    def event(self, *a, **kw) -> dict:
        return {}

    @contextmanager
    def span(self, *a, **kw):
        yield

    @contextmanager
    def request(self, *a, **kw):
        yield

    def timeline_event(self, ev: dict) -> None:
        pass

    def begin_step(self):
        return (0, 0.0)

    def end_step(self, token, **args) -> None:
        pass

    @contextmanager
    def step(self, **args):
        yield 0

    def commit_step(self, **args) -> None:
        pass

    def step_summary(self) -> Dict[str, Any]:
        return {"steps": 0}

    def note_plan(self, **kw) -> None:
        pass

    def plan_args(self) -> Dict[str, Any]:
        return {}

    def window(self) -> Dict[str, Any]:
        return {}

    def reset_steps(self) -> None:
        pass

    def set_clock(self, offset_s: float, rtt_s: float) -> None:
        pass

    def flight_dump(self, reason: str,
                    directory: Optional[str] = None) -> Optional[str]:
        return None


NULL_TAP = _NullTraceTap()

ACTIVE = False
TAP: Any = NULL_TAP

_lock = threading.Lock()
_prev_excepthook = None


def enabled() -> bool:
    return ACTIVE


def tap():
    """The process-wide tap: the live one when enabled, else the shared
    no-op singleton (``trace.tap() is trace.NULL_TAP``)."""
    return TAP


def _excepthook(exc_type, exc, tb):
    """Uncaught-crash hook: dump the flight ring, then defer to the
    previous hook (the default prints the traceback)."""
    try:
        if ACTIVE and not issubclass(exc_type, KeyboardInterrupt):
            TAP.flight_dump(f"crash:{exc_type.__name__}")
    except Exception:  # noqa: BLE001 - the hook must never mask the crash
        pass
    hook = _prev_excepthook or sys.__excepthook__
    hook(exc_type, exc, tb)


def install(active: bool) -> None:
    """(De)activate fleet tracing for this process. Activation arms the
    uncaught-crash flight-dump hook; deactivation restores the previous
    ``sys.excepthook``."""
    global ACTIVE, TAP, _prev_excepthook
    with _lock:
        if active:
            TAP = TraceTap()
            ACTIVE = True
            if sys.excepthook is not _excepthook:
                _prev_excepthook = sys.excepthook
                sys.excepthook = _excepthook
        else:
            TAP = NULL_TAP
            ACTIVE = False
            if sys.excepthook is _excepthook:
                sys.excepthook = _prev_excepthook or sys.__excepthook__
                _prev_excepthook = None


def activate_from_env() -> bool:
    v = os.environ.get(TRACE_ENV, "").strip().lower()
    on = v not in ("", "0", "false", "no", "off")
    # Pointing a trace dir at the recorder without the master switch
    # still arms it — the flight recorder is the always-on half.
    install(on or bool(os.environ.get(TRACE_DIR_ENV, "").strip()))
    return ACTIVE


def reset() -> None:
    install(False)


def wrap_step(fn, **meta):
    """Wrap a step function with the host-side step tap. With tracing
    disabled this returns ``fn`` ITSELF — the zero-overhead contract
    (``wrap_step(f) is f``) the tests assert. ``meta`` is stamped onto
    every step span's args alongside the noted plan/correlation ids."""
    if not ACTIVE:
        return fn
    tap_ref = TAP

    def traced_step(*args, **kwargs):
        token = tap_ref.begin_step()
        out = fn(*args, **kwargs)
        tap_ref.end_step(token, **meta)
        return out

    traced_step.__wrapped__ = fn
    traced_step.__hvd_trace_wrapped__ = True
    traced_step.__name__ = getattr(fn, "__name__", "step")
    return traced_step


def flight_dump(reason: str) -> Optional[str]:
    """Module-level convenience for abort paths: dump when active, no-op
    otherwise."""
    if not ACTIVE:
        return None
    return TAP.flight_dump(reason)


def step_summary() -> Dict[str, Any]:
    return TAP.step_summary()


# Re-exported for the driver/tools (lazy submodule import keeps worker
# import cost at zero when tracing is off).
def __getattr__(name: str):
    if name in ("pusher", "merge"):
        import importlib

        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(name)


# Arm at import (mirrors fault/injector.py, metrics, guard): worker
# processes spawned with HOROVOD_TRACE/HOROVOD_TRACE_DIR in their
# environment record without code changes.
if (os.environ.get(TRACE_ENV, "").strip()
        or os.environ.get(TRACE_DIR_ENV, "").strip()):
    try:
        activate_from_env()
    except Exception:  # noqa: BLE001 - a malformed knob must not take
        # down production init; surfaced by the trace tools/tests.
        logger.exception("could not arm fleet tracing from env")
