"""Tensor fusion as a compile-time bucketing pass.

The reference fuses at runtime: the coordinator packs ready tensors into a
64 MB fusion buffer each 5 ms cycle (``controller.cc:626-750`` FuseResponses,
``fusion_buffer_manager.cc``). Under XLA the equivalent is a *static*
bucketing pass over the gradient pytree: concatenate same-dtype leaves into
buckets up to the fusion threshold and emit ONE ``psum`` per bucket. XLA then
schedules those large collectives back-to-back on ICI, which is exactly the
bandwidth shape the runtime fusion buffer was built to achieve — without any
memcpy: the pack/unpack reshapes fuse into neighbouring ops.

The same pack/unpack is reused by the eager executor when it materializes a
fused Response from the cycle loop.

The streamed (overlap) path lives here too: :func:`reduce_in_backward` is a
``custom_vjp`` identity whose backward rule issues the bucket psums for a
parameter subtree *inside* the backward pass, as soon as that subtree's
cotangents exist. A post-hoc ``fused_allreduce`` over the whole gradient
pytree data-depends on the complete backward pass, so XLA's latency-hiding
scheduler has nothing to hide the collective behind; per-subtree streamed
psums depend only on their own layer suffix and overlap with the remaining
backward compute (docs/overlap.md).
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .. import metrics as _metrics
from .. import trace as _trace
from ..common import env as _env
from ..common.types import ReduceOp, dtype_size, dtype_from_array
from ..parallel.mesh import DATA_AXIS
from . import collectives

logger = logging.getLogger("horovod_tpu")


def default_threshold_bytes(threshold_bytes: Optional[int] = None) -> int:
    """Resolve the fusion threshold: explicit value > HOROVOD_FUSION_THRESHOLD
    env knob > the reference's 64 MB default (operations.cc:411-417)."""
    if threshold_bytes is not None:
        return int(threshold_bytes)
    return _env._get_int(
        _env.HOROVOD_FUSION_THRESHOLD, 64 * 1024 * 1024
    )


def default_first_bucket_bytes(first_bucket_bytes: Optional[int] = None) -> int:
    """Resolve the streamed-mode first-bucket size: explicit value >
    HOROVOD_FUSION_FIRST_BUCKET_BYTES > 1 MiB (the DDP idiom: a small first
    bucket puts bytes on the wire as early in the backward as possible)."""
    if first_bucket_bytes is not None:
        return int(first_bucket_bytes)
    return _env._get_int(
        _env.HOROVOD_FUSION_FIRST_BUCKET_BYTES, 1024 * 1024
    )


def plan_buckets(
    leaves: Sequence[Any], threshold_bytes: int
) -> List[List[int]]:
    """Group leaf indices into fusion buckets.

    Same-dtype tensors are packed greedily in submission order up to
    ``threshold_bytes`` per bucket (reference ``FuseResponses`` packs
    same-dtype/device responses up to the fusion threshold with lookahead,
    ``controller.cc:626-750``; order here is deterministic since the pytree
    order is static). An oversized leaf (a bucket of its own) closes its
    dtype's active bucket: later same-dtype leaves keep fusing, but into a
    FRESH bucket, so bucket emission order stays monotone in submission
    order — a leaf never joins a bucket that sits earlier in the stream
    than an already-emitted oversized one.
    """
    buckets: List[List[int]] = []
    # Active bucket per dtype: (bucket_index, bytes_used)
    active: Dict[str, Tuple[int, int]] = {}
    for i, leaf in enumerate(leaves):
        nbytes = leaf.size * dtype_size(dtype_from_array(leaf))
        key = str(leaf.dtype)
        if nbytes >= threshold_bytes:
            buckets.append([i])
            active.pop(key, None)
            continue
        if key in active:
            bidx, used = active[key]
            if used + nbytes <= threshold_bytes:
                buckets[bidx].append(i)
                active[key] = (bidx, used + nbytes)
                continue
        buckets.append([i])
        active[key] = (len(buckets) - 1, nbytes)
    return buckets


def pack_bucket(leaves: Sequence[jax.Array]) -> jax.Array:
    """Flatten+concat a same-dtype bucket into one 1-D buffer."""
    return jnp.concatenate([l.reshape(-1) for l in leaves], axis=0)


def unpack_bucket(
    buf: jax.Array, shapes: Sequence[Tuple[int, ...]]
) -> List[jax.Array]:
    out: List[jax.Array] = []
    offset = 0
    for shape in shapes:
        n = 1
        for d in shape:
            n *= d
        out.append(lax_slice(buf, offset, n).reshape(shape))
        offset += n
    return out


def lax_slice(buf: jax.Array, offset: int, length: int) -> jax.Array:
    return jax.lax.slice_in_dim(buf, offset, offset + length, axis=0)


def axis_label(axis_name) -> str:
    """The stable ``axis`` label of one reduction axis (or axis tuple)
    for per-axis attribution: ``"data"``, ``"model"``, ``"cross+local"``."""
    return "+".join(str(a) for a in _axes_of(axis_name))


def record_axis_wire_bytes(
    payload_bytes: int,
    axis_name,
    collective: str,
    wire_dtype: str = "f32",
) -> None:
    """Trace-time per-axis bytes-on-wire attribution (one emission per
    compile, the ``hvd_quantized_*`` discipline): ring accounting of
    what ONE step moves over the named axis per chip —
    ``hvd_axis_wire_bytes_total{axis,collective}`` (docs/metrics.md) plus
    a trace-tap plan note so step spans carry the split. This is what
    lets a composed DP x TP program report its DP and TP wire bytes
    SEPARATELY (docs/parallelism.md "Per-axis attribution"). Must be
    called inside the axis-binding trace (the axis size is read off the
    live binding); no-op when neither metrics nor tracing is armed."""
    if not (_metrics.ACTIVE or _trace.ACTIVE):
        return
    n = _axis_size_of(
        tuple(_axes_of(axis_name)) if isinstance(axis_name, (tuple, list))
        else axis_name
    )
    if n <= 1:
        return
    payload = int(payload_bytes)
    if wire_dtype == "int8":
        from ..common.quant import int8_wire_bytes

        payload = int8_wire_bytes(payload)
    if collective in ("allreduce", "psum"):
        onwire = 2 * (n - 1) * payload // n
    else:  # reduce_scatter / all_gather: one ring pass
        onwire = (n - 1) * payload // n
    label = axis_label(axis_name)
    if _metrics.ACTIVE:
        _metrics.TAP.inc(
            "hvd_axis_wire_bytes_total", float(onwire),
            axis=label, collective=collective,
        )
    if _trace.ACTIVE:
        _trace.TAP.note_plan(
            **{f"axis_wire_bytes:{label}:{collective}": int(onwire)}
        )


def fused_allreduce(
    tree: Any,
    *,
    op: ReduceOp = ReduceOp.AVERAGE,
    axis_name: str = DATA_AXIS,
    threshold_bytes: Optional[int] = None,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
    reduce_fn: Callable[..., jax.Array] | None = None,
    label: str = "posthoc",
    wire_dtype: str = "f32",
) -> Any:
    """Allreduce every leaf of a pytree with bucket fusion.

    Must be called inside an axis-binding context (shard_map / pmap). This is
    the compiled-mode equivalent of wrapping every gradient in
    ``hvd.allreduce`` and letting the background loop fuse them.
    ``threshold_bytes=None`` resolves the HOROVOD_FUSION_THRESHOLD knob.
    """
    threshold_bytes = default_threshold_bytes(threshold_bytes)
    leaves, treedef = jax.tree.flatten(tree)
    if not leaves:
        return tree
    buckets = plan_buckets(leaves, threshold_bytes)
    record_axis_wire_bytes(
        sum(l.size * dtype_size(dtype_from_array(l)) for l in leaves),
        axis_name, "allreduce", wire_dtype,
    )
    if _trace.ACTIVE:
        # Correlation ids for the fleet-trace step spans (trace-time,
        # one note per compile): which fusion path reduced how many
        # buckets this step.
        _trace.TAP.note_plan(
            fusion_path=label, fusion_buckets=len(buckets)
        )
    if _metrics.ACTIVE:
        # Trace-time plan stats (one emission per compile, not per step).
        _metrics.TAP.set(
            "hvd_fusion_buckets", float(len(buckets)), path=label
        )
        for bucket in buckets:
            _metrics.TAP.observe(
                "hvd_fusion_bucket_bytes",
                float(sum(
                    leaves[i].size * dtype_size(dtype_from_array(leaves[i]))
                    for i in bucket
                )),
                path=label,
            )
    reduce_fn = reduce_fn or collectives.allreduce
    results: List[jax.Array | None] = [None] * len(leaves)
    for bucket in buckets:
        if len(bucket) == 1:
            i = bucket[0]
            results[i] = reduce_fn(
                leaves[i],
                op=op,
                axis_name=axis_name,
                prescale_factor=prescale_factor,
                postscale_factor=postscale_factor,
            )
            continue
        packed = pack_bucket([leaves[i] for i in bucket])
        reduced = reduce_fn(
            packed,
            op=op,
            axis_name=axis_name,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor,
        )
        unpacked = unpack_bucket(reduced, [leaves[i].shape for i in bucket])
        for i, r in zip(bucket, unpacked):
            results[i] = r
    return jax.tree.unflatten(treedef, results)


# --- streamed (overlap) reduction -------------------------------------------
#
# The post-hoc fused_allreduce above reduces the WHOLE gradient pytree after
# value_and_grad returns, so every psum data-depends on the full backward
# pass and XLA cannot overlap the collective with any compute. The streamed
# path wraps parameter subtrees in a custom_vjp identity whose backward rule
# reduces that subtree's cotangents the moment they exist — the psum's
# operand cone is one layer suffix of the backward, and everything deeper in
# the model is free compute for the latency-hiding scheduler to run behind
# the wire transfer.

# Ops a streamed reduction may use: per-group reduction must equal the
# whole-tree reduction, which holds exactly for elementwise reductions.
# ADASUM normalizes per bucket (bucket plans differ between the paths)
# and stays post-hoc-only. The quantized int8 ring dithers per bucket —
# streamed-quantized equals post-hoc-quantized exactly when the bucket
# plans coincide (per-leaf buckets make it bitwise; docs/overlap.md
# "Quantized wire compression"), and its elementwise SUM/AVERAGE still
# commutes with the group split, so it streams too.
_STREAMABLE_OPS = (
    ReduceOp.SUM, ReduceOp.AVERAGE, ReduceOp.MIN, ReduceOp.MAX,
)

# Ops the int8 wire supports: per-hop requantization accumulates in f32,
# which is only sound for additive reductions.
_QUANTIZABLE_OPS = (ReduceOp.SUM, ReduceOp.AVERAGE)


@dataclass(frozen=True)
class StreamConfig:
    """Hashable reduction spec closed over by the custom_vjp backward rule
    (custom_vjp nondiff args must hash/compare for trace caching)."""

    op: ReduceOp = ReduceOp.AVERAGE
    axis_name: Any = DATA_AXIS  # str, or a (cross, local) tuple
    threshold_bytes: int = 64 * 1024 * 1024
    hierarchical: bool = False
    # Per-bucket plan selection via the topology compositor
    # (docs/topology.md): each streamed bucket's payload is priced on the
    # interconnect model of the bound axes and lowered with the selected
    # algorithm (flat / two-level / split). Set by hierarchical="auto" /
    # "planned" in the public entry points.
    planned: bool = False
    # Pinned topo algorithm for planned mode (the offline tuner's
    # verdict, docs/autotune.md); None = per-bucket cost selection.
    algorithm: Optional[str] = None
    compression: Any = None  # a common.compression.Compressor class or None
    # Int8 wire (ops/quantized.py): each bucket runs quantize -> ring
    # reduce -> dequantize inside the backward trace. Flat mode moves
    # every hop int8; hierarchical/planned modes compress ONLY the
    # outermost (DCN) hop, full precision over ICI (docs/overlap.md
    # "Quantized wire compression").
    quantized: bool = False
    label: str = "stream"
    # Non-finite guard policy applied to this group's cotangents BEFORE
    # the psum (docs/fault_tolerance.md "Data-plane integrity"): "zero"
    # sanitizes locally so one rank's NaN never reaches the wire. Other
    # policies act at the step level (jax/__init__.py) — the streamed
    # group only sanitizes.
    nonfinite: str = "off"
    # Streamed ZeRO-1 (docs/overlap.md "Streamed ZeRO-1"): each bucket
    # runs reduce-scatter instead of allreduce inside the backward
    # trace — the rule returns a SHARD IMAGE (this rank's reduced shard
    # scattered into a zero bucket buffer), so only 1/N of each bucket's
    # cotangents carry data and only (n-1)/n of the payload rides the
    # wire. Consumed by ``parallel/zero.zero1_stream_update``, which
    # round-trips the identical bucket plan.
    zero1: bool = False


def _hier_reduce_fn(x, *, op, axis_name, prescale_factor=1.0,
                    postscale_factor=1.0):
    """Two-level reduce for the streamed path: reduce-scatter on ICI,
    shard psum on DCN, all-gather back (ops/collectives.py)."""
    cross_axis, local_axis = axis_name
    if prescale_factor != 1.0:
        x = x * prescale_factor
    out = collectives.hierarchical_allreduce(
        x, op=op, local_axis=local_axis, cross_axis=cross_axis
    )
    if postscale_factor != 1.0:
        out = out * postscale_factor
    return out


# --- streamed ZeRO-1: per-bucket reduce-scatter ------------------------------
#
# ZeRO-1's gradient exchange is a reduce-scatter, not an allreduce: each
# rank only needs the shard of the summed gradient its optimizer-state
# shard updates. Run per streamed bucket INSIDE the backward trace, the
# RS keeps the overlap property of the streamed path while moving half
# of the ring-allreduce's gradient bytes — and the cotangent that leaves
# the custom_vjp is a SHARD IMAGE (the reduced shard scattered into a
# zero bucket buffer), so only 1/N of each bucket carries live data.
# ``parallel/zero.zero1_stream_update`` recovers the shard bitwise by
# re-packing the same bucket plan and slicing at this rank's offset.


def _axes_of(axis_name) -> Tuple[Any, ...]:
    if isinstance(axis_name, (tuple, list)):
        return tuple(axis_name)
    return (axis_name,)


def zero1_axis_rank(axis_name):
    """This rank's flat index over an axis (or outer-major axis tuple) —
    the shard offset the streamed-zero1 bucket layout is keyed by. The
    outer-major order matches the compositor's flat rank order, so the
    two-level reduce-scatter lowering and this index always agree."""
    from jax import lax

    idx = 0
    for a in _axes_of(axis_name):
        idx = idx * _axis_size_of(a) + lax.axis_index(a)
    return idx


def _axis_size_of(axis_name) -> int:
    from ..common.compat import axis_size

    return axis_size(axis_name)


def zero1_shard_len(total: int, n_shards: int, quantized: bool) -> int:
    """Per-rank shard length of a packed bucket of ``total`` elements:
    ceil-divided over the shards and, on the int8 wire, rounded up to
    the quantizer's BLOCK so every shard keeps whole scale blocks."""
    k = -(-max(int(total), 1) // n_shards)
    if quantized:
        from ..common.quant import BLOCK

        k = -(-k // BLOCK) * BLOCK
    return k


def zero1_group_layout(params: Any, threshold_bytes: Optional[int] = None,
                       first_bucket_bytes: Optional[int] = None):
    """The streamed-zero1 group partition over ``params``: returns
    ``(children, rebuild, groups)`` — or ``(None, None, None)`` when the
    tree has no splittable top level (one implicit group, the whole
    tree). This is the SAME partition ``stream_param_groups`` wraps, and
    the single source both the backward reduce-scatter and the
    shard-local update derive their bucket layout from: a group's
    registered subtree is ``{str(i): children[i] for i in group}`` and
    its bucket plan is ``plan_buckets`` over that subtree's leaves."""
    threshold = default_threshold_bytes(threshold_bytes)
    first = default_first_bucket_bytes(first_bucket_bytes)
    split = _top_level_children(params)
    if split is None:
        return None, None, None
    children, rebuild = split
    groups = plan_layer_groups(
        [_tree_bytes(c) for c in children], threshold, first
    )
    return children, rebuild, groups


def _record_zero1_bucket(n_shards: int, k: int, dsize: int,
                         quantized: bool, label: str) -> None:
    """Trace-time hvd_zero_* gauges (one emission per compile): what one
    bucket's reduce-scatter puts on the wire (ring accounting, n-1 hops
    of one shard — int8+scales per hop on the quantized wire) and the
    per-rank shard bytes each rank keeps."""
    if not _metrics.ACTIVE:
        return
    from ..common.quant import int8_wire_bytes

    shard_bytes = k * dsize
    hop_bytes = (
        int8_wire_bytes(shard_bytes) if quantized else shard_bytes
    )
    _metrics.TAP.inc(
        "hvd_zero_wire_bytes_total",
        float(max(n_shards - 1, 0) * hop_bytes), path=label,
    )
    _metrics.TAP.observe(
        "hvd_zero_shard_bytes", float(shard_bytes), path=label
    )


def fused_reduce_scatter(
    tree: Any,
    *,
    op: ReduceOp = ReduceOp.AVERAGE,
    axis_name: Any = DATA_AXIS,
    threshold_bytes: Optional[int] = None,
    quantized: bool = False,
    ef: Any = None,
    label: str = "zero1",
) -> Tuple[Any, Any]:
    """Per-bucket reduce-scatter of a pytree into shard images.

    Must run inside an axis-binding context. Leaves are bucketed with
    :func:`plan_buckets` (same plan as the allreduce paths), each bucket
    is packed, padded to ``n_shards`` BLOCK-aligned shards, and
    reduce-scattered so rank r keeps the complete reduction of chunk r;
    the shard is scattered back into a zero buffer at this rank's offset
    and unpacked, so the returned tree has ``tree``'s exact structure
    with only this rank's shard elements live — the layout
    ``parallel/zero.zero1_stream_update`` round-trips bitwise.

    Lowerings: a single bound axis runs ``lax.psum_scatter`` (or the
    int8 ring RS with ``quantized=True``, ``ops/quantized.py``); an axis
    tuple runs the compositor's hierarchical reduce-scatter (inner hop
    first — the big payload stays on ICI, only the 1/L shard crosses
    DCN). MIN/MAX have no native reduce-scatter and lower exactly as
    reduce+slice (bitwise, no wire saving); int buckets reduce exactly.

    ``ef`` (quantized only) is the SHARDED error-feedback residual: a
    ``{"b<i>": f32[k_i]}`` dict over the float buckets. Each rank adds
    its residual to its own chunk of the local payload before the ring
    and carries ``corrected - roundtrip(corrected)`` forward — the
    sharded EF-SGD construction (1/N coverage: a rank compensates its
    own contribution to its own shard; docs/overlap.md). Returns
    ``(shard_images, new_ef)`` (``new_ef`` mirrors ``ef``; None when
    ``ef`` is None)."""
    import jax.numpy as jnp
    from jax import lax

    if op not in _STREAMABLE_OPS:
        raise ValueError(
            f"fused_reduce_scatter supports elementwise ops "
            f"{_STREAMABLE_OPS}; got {op}"
        )
    axes = _axes_of(axis_name)
    if quantized:
        if op not in _QUANTIZABLE_OPS:
            raise ValueError(
                f"quantized reduce-scatter supports {_QUANTIZABLE_OPS}; "
                f"got {op}"
            )
        if len(axes) > 1:
            raise ValueError(
                "quantized zero1 runs the flat int8 ring reduce-scatter; "
                "hierarchical (DCN-only) compression is not defined for "
                "the RS+AG decomposition — drop hierarchical or "
                "quantized"
            )
    if ef is not None and not quantized:
        raise ValueError(
            "sharded error feedback (ef=...) only applies to the "
            "quantized zero1 wire"
        )
    threshold_bytes = default_threshold_bytes(threshold_bytes)
    leaves, treedef = jax.tree.flatten(tree)
    if not leaves:
        return tree, ef
    n = _axis_size_of(axes if len(axes) > 1 else axes[0])
    buckets = plan_buckets(leaves, threshold_bytes)
    record_axis_wire_bytes(
        sum(l.size * dtype_size(dtype_from_array(l)) for l in leaves),
        axis_name, "reduce_scatter",
        "int8" if quantized else "f32",
    )
    if _trace.ACTIVE:
        _trace.TAP.note_plan(
            fusion_path=label, fusion_buckets=len(buckets),
            zero1_reduction="reduce-scatter",
        )
    if _metrics.ACTIVE:
        _metrics.TAP.set(
            "hvd_fusion_buckets", float(len(buckets)), path=label
        )
    idx = zero1_axis_rank(axes if len(axes) > 1 else axes[0])
    results: List[jax.Array | None] = [None] * len(leaves)
    new_ef: Dict[str, Any] = {}
    average = op == ReduceOp.AVERAGE
    for bi, bucket in enumerate(buckets):
        bleaves = [leaves[i] for i in bucket]
        packed = pack_bucket(bleaves)
        total = packed.shape[0]
        if total == 0:
            # Zero-length leaves are identities — no ring, no state.
            for i in bucket:
                results[i] = leaves[i]
            continue
        dtype = packed.dtype
        is_float = jnp.issubdtype(dtype, jnp.floating)
        k = zero1_shard_len(total, n, quantized and is_float)
        padded = n * k
        buf = jnp.pad(packed, (0, padded - total))
        if quantized and is_float:
            from .quantized import (
                quantize_roundtrip,
                quantized_ring_reduce_scatter,
            )

            work = buf.astype(jnp.float32)
            ef_key = f"b{bi}"
            if ef is not None:
                if ef_key not in ef:
                    raise ValueError(
                        f"sharded EF residual is missing bucket "
                        f"{ef_key!r} — build it with "
                        f"parallel/zero.init_zero1_stream_state"
                    )
                chunk = lax.dynamic_slice(work, (idx * k,), (k,))
                corrected = chunk + ef[ef_key]
                work = lax.dynamic_update_slice(
                    work, corrected, (idx * k,)
                )
                new_ef[ef_key] = corrected - quantize_roundtrip(corrected)
            shard = quantized_ring_reduce_scatter(
                work, axis_name=axes[0], average=average
            ).astype(dtype)
        elif op in (ReduceOp.SUM, ReduceOp.AVERAGE):
            if len(axes) > 1:
                from ..topo import compositor as _compositor

                shard = _compositor.lower_reducescatter(
                    buf, axes, op=ReduceOp.SUM, algorithm="two-level"
                )
            else:
                shard = lax.psum_scatter(buf, axes[0], tiled=True)
            if average:
                shard = shard / n if is_float else shard // n
        else:
            # MIN/MAX: no native reduce-scatter — reduce then slice
            # (exact, bitwise with the flat reduction; no wire saving).
            red = lax.pmin if op == ReduceOp.MIN else lax.pmax
            full = red(buf, axes if len(axes) > 1 else axes[0])
            shard = lax.dynamic_slice(full, (idx * k,), (k,))
        _record_zero1_bucket(
            n, k, dtype_size(dtype_from_array(packed)),
            quantized and is_float, label,
        )
        image = lax.dynamic_update_slice(
            jnp.zeros((padded,), dtype), shard.astype(dtype), (idx * k,)
        )
        for i, r in zip(
            bucket,
            unpack_bucket(image[:total], [leaves[i].shape for i in bucket]),
        ):
            results[i] = r
    out = jax.tree.unflatten(treedef, results)
    if ef is None:
        return out, None
    missing = set(ef) - set(new_ef)
    if missing:
        raise ValueError(
            f"sharded EF residual carries buckets {sorted(missing)} the "
            f"bucket plan does not — the residual layout is stale for "
            f"this partition (rebuild with init_zero1_stream_state)"
        )
    return out, new_ef


def _reduce_stream_group(cfg: StreamConfig, ct: Any) -> Any:
    """Reduce one registered subtree's cotangents (runs inside the backward
    trace, under the same axis binding as the forward)."""
    if cfg.nonfinite == "zero":
        # Pre-wire sanitization: the healthy ranks' contributions to this
        # group survive a poisoned peer (guard/nonfinite.py).
        from ..guard import nonfinite as _nf

        ct = _nf.sanitize(ct)
    if cfg.zero1:
        # Streamed ZeRO-1: reduce-scatter the bucket (shard images out),
        # no compression layer (the int8 wire is cfg.quantized).
        images, _ = fused_reduce_scatter(
            ct,
            op=cfg.op,
            axis_name=cfg.axis_name,
            threshold_bytes=cfg.threshold_bytes,
            quantized=cfg.quantized,
            label=cfg.label,
        )
        return images
    compression = cfg.compression
    ctxs = None
    if compression is not None:
        leaves, treedef = jax.tree.flatten(ct)
        compressed = [compression.compress(l) for l in leaves]
        ct = jax.tree.unflatten(treedef, [c for c, _ in compressed])
        ctxs = [c for _, c in compressed]
    if cfg.planned:
        from ..topo import compositor as _compositor

        # Built inside the backward trace: axis sizes come from the live
        # bindings, so each bucket is priced on the mesh it runs over.
        # quantized=True prices buckets with wire_dtype=int8 and lowers
        # the selected plan with int8 on the slow hop(s) only.
        reduce_fn = _compositor.planned_reduce_fn(
            _compositor.model_for_axes(cfg.axis_name), cfg.axis_name,
            quantized=cfg.quantized, algorithm=cfg.algorithm,
        )
    elif cfg.quantized:
        from .quantized import quantized_reduce_fn

        reduce_fn = quantized_reduce_fn(
            "two-level" if cfg.hierarchical else "flat", label=cfg.label
        )
    elif cfg.hierarchical:
        reduce_fn = _hier_reduce_fn
    else:
        reduce_fn = None
    reduced = fused_allreduce(
        ct,
        op=cfg.op,
        axis_name=cfg.axis_name,
        threshold_bytes=cfg.threshold_bytes,
        reduce_fn=reduce_fn,
        label=cfg.label,
        # Attribution only: hierarchical/planned wires compress at most
        # the DCN hop, so the flat-int8 accounting would overstate.
        wire_dtype=(
            "int8" if cfg.quantized and not (cfg.planned or cfg.hierarchical)
            else "f32"
        ),
    )
    if compression is not None:
        leaves, treedef = jax.tree.flatten(reduced)
        leaves = [
            compression.decompress(l, c) for l, c in zip(leaves, ctxs)
        ]
        reduced = jax.tree.unflatten(treedef, leaves)
    return reduced


@partial(jax.custom_vjp, nondiff_argnums=(0,))
def _stream_identity(cfg: StreamConfig, tree: Any) -> Any:
    return tree


def _stream_fwd(cfg, tree):
    return tree, None


def _stream_bwd(cfg, _res, ct):
    return (_reduce_stream_group(cfg, ct),)


_stream_identity.defvjp(_stream_fwd, _stream_bwd)


# --- quantized reduction with error feedback ---------------------------------
#
# EF-SGD construction (the standard fix that preserves convergence under
# biased compressors): each rank keeps a rank-local residual e, sends
# Q(g + e) instead of Q(g), and carries e' = (g + e) - Q(g + e) into the
# next step — the quantization error is re-injected instead of lost.
# The residual compensates THIS rank's first quantization (the dominant
# local error; later ring hops re-quantize shared partials, which no
# per-rank state can attribute). Residuals legitimately differ across
# ranks: the guard's digest agreement excludes them
# (guard/digest.strip_rank_local).


def quantized_ef_allreduce(
    tree: Any,
    ef: Any,
    *,
    op: ReduceOp = ReduceOp.AVERAGE,
    axis_name: Any = DATA_AXIS,
    threshold_bytes: Optional[int] = None,
    label: str = "quantized-ef",
) -> Tuple[Any, Any]:
    """Bucket-fused int8-wire allreduce with error feedback: returns
    ``(reduced, new_residual)``. ``ef`` must mirror ``tree``'s structure
    with float32 leaves (``ops/quantized.ef_like``). Float buckets move
    ``corrected = g.astype(f32) + e`` through the int8 ring and emit
    ``corrected - dequant(quant(corrected))`` as the next residual;
    integer buckets reduce exactly and pass their residual through
    unchanged (always zero). The SAME function serves the post-hoc and
    the streamed (per-group) paths, so identical bucket plans give
    bitwise-identical steps."""
    from . import collectives as _c
    from .quantized import (
        quantize_roundtrip,
        quantized_ring_allreduce,
        record_wire_bytes,
    )

    if op not in _QUANTIZABLE_OPS:
        raise ValueError(
            f"quantized reduction supports {_QUANTIZABLE_OPS}; got {op}"
        )
    threshold_bytes = default_threshold_bytes(threshold_bytes)
    leaves, treedef = jax.tree.flatten(tree)
    ef_leaves, ef_treedef = jax.tree.flatten(ef)
    if len(ef_leaves) != len(leaves):
        raise ValueError(
            f"error-feedback residual has {len(ef_leaves)} leaves but the "
            f"gradient tree has {len(leaves)} — build it with ef_like(params)"
        )
    if not leaves:
        return tree, ef
    buckets = plan_buckets(leaves, threshold_bytes)
    record_axis_wire_bytes(
        sum(l.size * dtype_size(dtype_from_array(l)) for l in leaves),
        axis_name, "allreduce", "int8",
    )
    if _trace.ACTIVE:
        # Correlation ids for the fleet-trace step spans (trace-time):
        # the EF int8 wire reduced this many buckets under this label.
        _trace.TAP.note_plan(
            fusion_path=label, fusion_buckets=len(buckets)
        )
    results: List[jax.Array | None] = [None] * len(leaves)
    residuals: List[jax.Array | None] = [None] * len(leaves)
    average = op == ReduceOp.AVERAGE
    for bucket in buckets:
        first = leaves[bucket[0]]
        if not jnp.issubdtype(first.dtype, jnp.floating):
            # Exact sums stay exact: no int8 round trip, residual
            # untouched (zero).
            for i in bucket:
                out = _c.allreduce(leaves[i], op=op, axis_name=axis_name)
                results[i] = out.astype(leaves[i].dtype)
                residuals[i] = ef_leaves[i]
            continue
        corrected = [
            leaves[i].astype(jnp.float32) + ef_leaves[i] for i in bucket
        ]
        packed = pack_bucket(corrected)
        if packed.size == 0:
            for i in bucket:
                results[i] = leaves[i]
                residuals[i] = ef_leaves[i]
            continue
        record_wire_bytes(packed.size * 4, label)
        new_res = packed - quantize_roundtrip(packed)
        reduced = quantized_ring_allreduce(
            packed, axis_name=axis_name, average=average
        )
        shapes = [leaves[i].shape for i in bucket]
        for i, r, e in zip(
            bucket, unpack_bucket(reduced, shapes),
            unpack_bucket(new_res, shapes),
        ):
            results[i] = r.astype(leaves[i].dtype)
            residuals[i] = e
    return (
        jax.tree.unflatten(treedef, results),
        jax.tree.unflatten(ef_treedef, residuals),
    )


@partial(jax.custom_vjp, nondiff_argnums=(0,))
def _stream_identity_ef(cfg: StreamConfig, tree: Any, ef: Any) -> Any:
    return tree


def _stream_ef_fwd(cfg, tree, ef):
    # The residual values ride the forward residuals into the backward
    # rule; the "gradient" the rule returns for ``ef`` IS the next
    # step's residual — that is how per-bucket state computed inside the
    # backward trace escapes the custom_vjp (value_and_grad over
    # (params, ef) hands it back to the step).
    return tree, ef


def _stream_ef_bwd(cfg, ef, ct):
    if cfg.nonfinite == "zero":
        # Sentinel BEFORE the quantizer: a NaN reaching the blockwise
        # amax would poison the whole block's scale, so sanitization
        # must run pre-quantize (docs/fault_tolerance.md).
        from ..guard import nonfinite as _nf

        ct = _nf.sanitize(ct)
        ef = _nf.sanitize(ef)
    if cfg.zero1:
        # Streamed ZeRO-1 with the sharded EF residual: the per-bucket
        # int8 ring RS corrects this rank's own chunk and the fresh
        # shard residual comes back as ef's "gradient".
        return fused_reduce_scatter(
            ct,
            op=cfg.op,
            axis_name=cfg.axis_name,
            threshold_bytes=cfg.threshold_bytes,
            quantized=True,
            ef=ef,
            label=cfg.label,
        )
    reduced, new_ef = quantized_ef_allreduce(
        ct, ef,
        op=cfg.op,
        axis_name=cfg.axis_name,
        threshold_bytes=cfg.threshold_bytes,
        label=cfg.label,
    )
    return reduced, new_ef


_stream_identity_ef.defvjp(_stream_ef_fwd, _stream_ef_bwd)


# Per-thread trace ledger: DistributedOptimizer(overlap=True) consumes it to
# detect a model whose layers were never registered for streaming (the
# silent-fallback hazard the analysis lint warns about).
_stream_trace = threading.local()


def _note_stream_registration(n_leaves: int) -> None:
    d = getattr(_stream_trace, "d", None)
    if d is None:
        d = {"calls": 0, "leaves": 0}
        _stream_trace.d = d
    d["calls"] += 1
    d["leaves"] += int(n_leaves)


def take_stream_registrations() -> Dict[str, int]:
    """Return and reset this thread's (calls, leaves) streamed-registration
    counts since the last take — consumed once per optimizer trace."""
    d = getattr(_stream_trace, "d", None) or {"calls": 0, "leaves": 0}
    _stream_trace.d = {"calls": 0, "leaves": 0}
    return dict(d)


def reduce_in_backward(
    tree: Any,
    *,
    op: ReduceOp = ReduceOp.AVERAGE,
    axis_name: Any = DATA_AXIS,
    threshold_bytes: Optional[int] = None,
    hierarchical: Any = False,
    compression: Any = None,
    quantized: bool = False,
    ef: Any = None,
    label: str = "stream",
    nonfinite: str = "off",
    algorithm: Optional[str] = None,
    zero1: bool = False,
) -> Any:
    """Register a parameter subtree for streamed gradient reduction.

    Identity on the forward pass; the backward rule bucket-allreduces the
    subtree's cotangents as soon as they exist, giving XLA a collective
    whose operand cone is only this subtree's layer suffix — overlappable
    with the rest of the backward. Apply it to each layer (or layer group)
    of the params BEFORE the layer's forward computation consumes them;
    ``make_train_step(overlap=True)`` does this automatically via
    :func:`stream_param_groups`.

    ``quantized=True`` moves each bucket through the int8 wire
    (``ops/quantized.py``) inside the same backward trace — the overlap
    property is unchanged, only the bytes shrink. With ``ef`` (a float32
    residual subtree mirroring ``tree``, see ``ops/quantized.ef_like``)
    the backward applies error feedback: it reduces ``ct + ef`` and the
    next residual comes back as the GRADIENT of ``ef`` — differentiate
    with ``jax.value_and_grad(..., argnums=(0, 1))`` over (params, ef)
    and thread the residual into the next step (``make_train_step`` does
    this automatically).

    ``zero1=True`` switches the bucket reduction from allreduce to
    reduce-scatter (docs/overlap.md "Streamed ZeRO-1"): the backward
    returns SHARD IMAGES — only this rank's shard of each bucket is
    live — consumed by ``parallel/zero.zero1_stream_update``; ``ef``
    then takes the SHARDED residual dict (``{"b<i>": f32[k_i]}``), not a
    params-shaped tree.
    """
    if op not in _STREAMABLE_OPS:
        raise ValueError(
            f"reduce_in_backward supports elementwise ops {_STREAMABLE_OPS};"
            f" got {op} (ADASUM normalizes per bucket and must stay post-hoc)"
        )
    if compression is not None:
        from ..common.compression import Compression

        if compression is Compression.none:
            compression = None
    if quantized:
        if op not in _QUANTIZABLE_OPS:
            raise ValueError(
                f"quantized streaming supports {_QUANTIZABLE_OPS}; got {op}"
            )
        if compression is not None:
            raise ValueError(
                "quantized=True already compresses the wire to int8; "
                "stacking cast compression would add loss for no "
                "bandwidth win"
            )
    if ef is not None and not quantized:
        raise ValueError(
            "error feedback (ef=...) only applies to quantized streaming"
        )
    if zero1:
        if compression is not None:
            raise ValueError(
                "zero1 streaming reduce-scatters raw buckets; cast "
                "compression has no shard-image form — use "
                "quantized=True for the int8 wire instead"
            )
        if algorithm is not None:
            raise ValueError(
                "zero1 streaming lowers reduce-scatter directly (flat "
                "ring or the compositor two-level); a pinned allreduce "
                "algorithm does not apply — drop algorithm="
            )
        if quantized and bool(hierarchical):
            raise ValueError(
                "quantized zero1 runs the flat int8 ring "
                "reduce-scatter; hierarchical (DCN-only) compression is "
                "not defined for the RS+AG decomposition"
            )
    # "planned" = per-bucket compositor plan selection over the axis
    # tuple (hierarchical="auto" at the make_train_step level resolves
    # to this when the mesh carries a (pod, cross, local) hierarchy).
    planned = hierarchical == "planned"
    if algorithm is not None and not planned:
        raise ValueError(
            "algorithm= pins a compositor plan and needs "
            "hierarchical='planned' (or 'auto' resolving to it); with "
            f"hierarchical={hierarchical!r} the pin would be silently "
            "ignored"
        )
    if ef is not None and (planned or bool(hierarchical)):
        raise ValueError(
            "error feedback compensates the flat int8 ring; the "
            "hierarchical DCN-only wire quantizes post-local-reduction "
            "state no per-rank residual can attribute — use ef=None"
        )
    cfg = StreamConfig(
        op=op,
        axis_name=tuple(axis_name) if isinstance(axis_name, list)
        else axis_name,
        threshold_bytes=default_threshold_bytes(threshold_bytes),
        hierarchical=bool(hierarchical) and not planned,
        planned=planned,
        algorithm=algorithm,
        compression=compression,
        quantized=bool(quantized),
        label=label,
        nonfinite=str(nonfinite),
        zero1=bool(zero1),
    )
    _note_stream_registration(len(jax.tree.leaves(tree)))
    if ef is not None:
        return _stream_identity_ef(cfg, tree, ef)
    return _stream_identity(cfg, tree)


def stream_scan_body(
    body_fn: Callable[[Any, Any], Any], **reduce_kw
) -> Callable[[Any, Any], Any]:
    """Scan-body variant for scanned layer stacks: wrap a ``lax.scan`` body
    so the per-layer params slice it consumes is registered for streamed
    backward reduction. The scan's backward then issues one bucket psum per
    layer iteration — the reduction streams across the stack instead of
    waiting for the accumulated stacked gradient. Valid because the
    streamed ops are elementwise: psum of the per-iteration cotangent
    slices equals psum of the stacked gradient."""
    reduce_kw.setdefault("label", "stream-scan")

    def wrapped(carry, xs):
        return body_fn(carry, reduce_in_backward(xs, **reduce_kw))

    return wrapped


def _top_level_children(tree: Any):
    """Split a pytree into its top-level children (the layer granularity
    streamed grouping works at). Returns (children, rebuild) or None when
    the tree has no splittable top level.

    Dict children are walked in SORTED key order — jax's canonical
    flatten order, which is what a dict looks like after any
    jit/shard_map boundary reconstructs it. Host-side consumers (the
    zero1 state init, the tuner's program spec) must see the same
    partition the in-trace registration sees, and insertion order does
    not survive the trace boundary."""
    if isinstance(tree, dict) and tree:
        keys = list(tree.keys())
        try:
            keys = sorted(keys)
        except TypeError:  # unsortable mixed-type keys: keep list order
            pass

        def rebuild(vals, keys=keys, cls=type(tree)):
            out = dict(zip(keys, vals))
            try:
                return cls(out)
            except Exception:  # noqa: BLE001 - exotic Mapping subclass
                return out

        return [tree[k] for k in keys], rebuild
    if isinstance(tree, (list, tuple)) and tree:
        def rebuild(vals, cls=type(tree)):
            return cls(vals)

        return list(tree), rebuild
    return None


def _tree_bytes(tree: Any) -> int:
    return sum(
        l.size * dtype_size(dtype_from_array(l))
        for l in jax.tree.leaves(tree)
    )


def plan_layer_groups(
    layer_bytes: Sequence[int],
    threshold_bytes: int,
    first_bucket_bytes: int,
) -> List[List[int]]:
    """Pack layer indices into streamed-reduction groups, walking in
    REVERSE forward order (the order their gradients materialize in the
    backward pass, torch DDP's bucket assignment). The first group to
    reduce is capped at ``first_bucket_bytes`` so the first collective
    launches as early as possible; later groups fill to the fusion
    threshold. Groups are returned in reduction order; each group's member
    list is sorted in forward order."""
    groups: List[List[int]] = []
    cur: List[int] = []
    cur_bytes = 0
    cap = max(int(first_bucket_bytes), 1)
    for i in reversed(range(len(layer_bytes))):
        cur.append(i)
        cur_bytes += int(layer_bytes[i])
        if cur_bytes >= cap:
            groups.append(sorted(cur))
            cur, cur_bytes = [], 0
            cap = max(int(threshold_bytes), 1)
    if cur:
        groups.append(sorted(cur))
    return groups


def layer_group_bytes(
    layer_bytes: Sequence[int],
    threshold_bytes: int,
    first_bucket_bytes: int,
) -> List[int]:
    """Per-group payload bytes of the :func:`plan_layer_groups`
    partition, in reduction order — the pure accounting the offline
    tuner (``horovod_tpu/tune``) prices with the compositor cost model.
    One source of truth: a tuned partition and the traced partition can
    never disagree because both come from ``plan_layer_groups``."""
    return [
        sum(int(layer_bytes[i]) for i in group)
        for group in plan_layer_groups(
            layer_bytes, threshold_bytes, first_bucket_bytes
        )
    ]


def stream_param_groups(
    params: Any,
    *,
    op: ReduceOp = ReduceOp.AVERAGE,
    axis_name: Any = DATA_AXIS,
    threshold_bytes: Optional[int] = None,
    first_bucket_bytes: Optional[int] = None,
    hierarchical: Any = False,
    compression: Any = None,
    quantized: bool = False,
    ef: Any = None,
    nonfinite: str = "off",
    algorithm: Optional[str] = None,
    zero1: bool = False,
) -> Any:
    """Partition ``params`` by top-level child (for a flax params dict: one
    child per module, in construction ≈ forward order), pack the children
    into DDP-style reverse-order groups with a smaller first bucket, and
    register every group for streamed backward reduction. A tree with no
    splittable top level degrades to one group (still overlappable with the
    optimizer/loss tail, but not intra-backward).

    ``quantized``/``ef`` follow :func:`reduce_in_backward`: with ``ef``
    (same top-level structure as ``params``) each group carries its own
    error-feedback residual slice and the updated residuals come back as
    the gradient of the ``ef`` argument.

    ``zero1=True`` registers each group for streamed reduce-scatter
    (shard images out; docs/overlap.md "Streamed ZeRO-1"); ``ef`` is
    then the SHARDED residual keyed by group (``{"g<gi>": {"b<bi>":
    f32[k]}}``, rows of ``parallel/zero.Zero1State.ef``)."""
    threshold = default_threshold_bytes(threshold_bytes)
    first = default_first_bucket_bytes(first_bucket_bytes)
    split = _top_level_children(params)
    if split is None:
        return reduce_in_backward(
            params, op=op, axis_name=axis_name, threshold_bytes=threshold,
            hierarchical=hierarchical, compression=compression,
            quantized=quantized,
            ef=(ef["g0"] if zero1 and ef is not None else ef),
            label="stream:g0", nonfinite=nonfinite, algorithm=algorithm,
            zero1=zero1,
        )
    children, rebuild = split
    ef_children = None
    if ef is not None and not zero1:
        ef_split = _top_level_children(ef)
        if ef_split is None or len(ef_split[0]) != len(children):
            raise ValueError(
                "ef must mirror params' top-level structure "
                "(build it with ops.quantized.ef_like(params))"
            )
        ef_children = ef_split[0]
    groups = plan_layer_groups(
        [_tree_bytes(c) for c in children], threshold, first
    )
    if _metrics.ACTIVE:
        _metrics.TAP.set("hvd_overlap_groups", float(len(groups)))
    wrapped = list(children)
    for gi, group in enumerate(groups):
        sub = {str(i): children[i] for i in group}
        if zero1 and ef is not None:
            gkey = f"g{gi}"
            if gkey not in ef:
                raise ValueError(
                    f"sharded EF residual is missing group {gkey!r} — "
                    f"build it with parallel/zero.init_zero1_stream_state"
                )
            sub_ef: Any = ef[gkey]
        elif ef_children is not None:
            sub_ef = {str(i): ef_children[i] for i in group}
        else:
            sub_ef = None
        sub = reduce_in_backward(
            sub, op=op, axis_name=axis_name, threshold_bytes=threshold,
            hierarchical=hierarchical, compression=compression,
            quantized=quantized, ef=sub_ef,
            label=f"stream:g{gi}", nonfinite=nonfinite,
            algorithm=algorithm, zero1=zero1,
        )
        for i in group:
            wrapped[i] = sub[str(i)]
    return rebuild(wrapped)
