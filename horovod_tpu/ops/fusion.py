"""Tensor fusion as a compile-time bucketing pass.

The reference fuses at runtime: the coordinator packs ready tensors into a
64 MB fusion buffer each 5 ms cycle (``controller.cc:626-750`` FuseResponses,
``fusion_buffer_manager.cc``). Under XLA the equivalent is a *static*
bucketing pass over the gradient pytree: concatenate same-dtype leaves into
buckets up to the fusion threshold and emit ONE ``psum`` per bucket. XLA then
schedules those large collectives back-to-back on ICI, which is exactly the
bandwidth shape the runtime fusion buffer was built to achieve — without any
memcpy: the pack/unpack reshapes fuse into neighbouring ops.

The same pack/unpack is reused by the eager executor when it materializes a
fused Response from the cycle loop.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..common.types import ReduceOp, dtype_size, dtype_from_array
from ..parallel.mesh import DATA_AXIS
from . import collectives


def plan_buckets(
    leaves: Sequence[Any], threshold_bytes: int
) -> List[List[int]]:
    """Group leaf indices into fusion buckets.

    Same-dtype tensors are packed greedily in submission order up to
    ``threshold_bytes`` per bucket (reference ``FuseResponses`` packs
    same-dtype/device responses up to the fusion threshold with lookahead,
    ``controller.cc:626-750``; order here is deterministic since the pytree
    order is static).
    """
    buckets: List[List[int]] = []
    # Active bucket per dtype: (bucket_index, bytes_used)
    active: Dict[str, Tuple[int, int]] = {}
    for i, leaf in enumerate(leaves):
        nbytes = leaf.size * dtype_size(dtype_from_array(leaf))
        key = str(leaf.dtype)
        if nbytes >= threshold_bytes:
            buckets.append([i])
            continue
        if key in active:
            bidx, used = active[key]
            if used + nbytes <= threshold_bytes:
                buckets[bidx].append(i)
                active[key] = (bidx, used + nbytes)
                continue
        buckets.append([i])
        active[key] = (len(buckets) - 1, nbytes)
    return buckets


def pack_bucket(leaves: Sequence[jax.Array]) -> jax.Array:
    """Flatten+concat a same-dtype bucket into one 1-D buffer."""
    return jnp.concatenate([l.reshape(-1) for l in leaves], axis=0)


def unpack_bucket(
    buf: jax.Array, shapes: Sequence[Tuple[int, ...]]
) -> List[jax.Array]:
    out: List[jax.Array] = []
    offset = 0
    for shape in shapes:
        n = 1
        for d in shape:
            n *= d
        out.append(lax_slice(buf, offset, n).reshape(shape))
        offset += n
    return out


def lax_slice(buf: jax.Array, offset: int, length: int) -> jax.Array:
    return jax.lax.slice_in_dim(buf, offset, offset + length, axis=0)


def fused_allreduce(
    tree: Any,
    *,
    op: ReduceOp = ReduceOp.AVERAGE,
    axis_name: str = DATA_AXIS,
    threshold_bytes: int = 64 * 1024 * 1024,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
    reduce_fn: Callable[..., jax.Array] | None = None,
) -> Any:
    """Allreduce every leaf of a pytree with bucket fusion.

    Must be called inside an axis-binding context (shard_map / pmap). This is
    the compiled-mode equivalent of wrapping every gradient in
    ``hvd.allreduce`` and letting the background loop fuse them.
    """
    leaves, treedef = jax.tree.flatten(tree)
    if not leaves:
        return tree
    buckets = plan_buckets(leaves, threshold_bytes)
    reduce_fn = reduce_fn or collectives.allreduce
    results: List[jax.Array | None] = [None] * len(leaves)
    for bucket in buckets:
        if len(bucket) == 1:
            i = bucket[0]
            results[i] = reduce_fn(
                leaves[i],
                op=op,
                axis_name=axis_name,
                prescale_factor=prescale_factor,
                postscale_factor=postscale_factor,
            )
            continue
        packed = pack_bucket([leaves[i] for i in bucket])
        reduced = reduce_fn(
            packed,
            op=op,
            axis_name=axis_name,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor,
        )
        unpacked = unpack_bucket(reduced, [leaves[i].shape for i in bucket])
        for i, r in zip(bucket, unpacked):
            results[i] = r
    return jax.tree.unflatten(treedef, results)
