"""In-jit collective primitives over named mesh axes.

This is the TPU-native data plane: where the reference dispatches to NCCL /
MPI / Gloo backends (``horovod/common/ops/operation_manager.cc:87-104``), the
TPU build lowers every collective to an XLA collective over a named mesh axis
— ``psum`` / ``all_gather`` / ``ppermute`` / ``all_to_all`` ride ICI within a
slice and DCN across slices, scheduled by the compiler.

These functions are meant to be called *inside* ``shard_map``/``pmap``-traced
code (they need an active axis binding). The eager/op mode wraps them in a
jitted executor; the compiled mode uses them directly inside the training
step.

Reference semantics preserved:
 - op=Average divides by the axis size after summing
   (``horovod/torch/mpi_ops.py:101-124`` divisor logic).
 - allgather concatenates along dim 0, supporting different dim-0 sizes per
   rank via padding+mask (reference ``collective_operations.cc:87-157``
   displacement math; XLA needs static shapes so uneven gather pads to the
   max and the caller slices).
 - broadcast selects the root's value (reference ``MPI_Bcast`` semantics,
   ``mpi_operations.cc:326-356``).
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from ..common.compat import axis_size as _axis_size
from ..common.types import ReduceOp
from ..parallel.mesh import DATA_AXIS


def _maybe_scale(x: jax.Array, factor: float) -> jax.Array:
    if factor == 1.0:
        return x
    # Scale in fp32 for low-precision inputs to avoid bf16/fp16 rounding of
    # the scale itself (reference applies double prescale on host, half.cc).
    if x.dtype in (jnp.bfloat16, jnp.float16):
        return (x.astype(jnp.float32) * factor).astype(x.dtype)
    return x * jnp.asarray(factor, dtype=x.dtype)


def allreduce(
    x: jax.Array,
    *,
    op: ReduceOp = ReduceOp.SUM,
    axis_name: str = DATA_AXIS,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
) -> jax.Array:
    """Allreduce over a named mesh axis. Inside jit this is a single XLA
    AllReduce that XLA fuses/schedules onto ICI."""
    x = _maybe_scale(x, prescale_factor)
    if op in (ReduceOp.SUM, ReduceOp.ADASUM):
        # Plain Adasum at this layer is a sum; the adaptive variant lives in
        # ops/adasum.py and is selected by the runtime.
        out = lax.psum(x, axis_name)
    elif op == ReduceOp.AVERAGE:
        out = lax.pmean(x, axis_name)
    elif op == ReduceOp.MIN:
        out = lax.pmin(x, axis_name)
    elif op == ReduceOp.MAX:
        out = lax.pmax(x, axis_name)
    elif op == ReduceOp.PRODUCT:
        out = _product_allreduce(x, axis_name)
    else:
        raise ValueError(f"Unsupported reduce op: {op}")
    return _maybe_scale(out, postscale_factor)


def _product_allreduce(x: jax.Array, axis_name: str) -> jax.Array:
    """Allreduce with a product: recursive-doubling butterfly of
    ``ppermute`` + multiply — O(bytes) memory and exact fp products (every
    rank applies the identical association), log2(n) rounds. There is no
    ``lax.pprod``; the earlier ``all_gather``+``prod`` formulation held
    n copies of the tensor live. Non-power-of-2 axes fall back to the
    gather (rare: TPU slices are power-of-2)."""
    n = _axis_size(axis_name)
    if n == 1:
        return x
    if n & (n - 1):
        return jnp.prod(lax.all_gather(x, axis_name), axis=0)
    out = x
    t = 1
    while t < n:
        # XOR pairing is a symmetric permutation: each rank both sends to
        # and receives from its butterfly partner.
        perm = [(i, i ^ t) for i in range(n)]
        out = out * lax.ppermute(out, axis_name, perm)
        t *= 2
    return out


def allgather(x: jax.Array, *, axis_name: str = DATA_AXIS) -> jax.Array:
    """Concatenate tensors from all ranks along dim 0 (reference
    ``AllgatherOp``). Requires equal non-0 dims, like the reference
    (``controller.cc:358-597`` validation)."""
    # all_gather with tiled=True concatenates along axis 0, matching
    # MPI_Allgatherv semantics for equal shapes.
    return lax.all_gather(x, axis_name, tiled=True)


def allgatherv(
    x: jax.Array,
    *,
    axis_name: str = DATA_AXIS,
    max_dim0: int,
) -> tuple[jax.Array, jax.Array]:
    """Uneven-dim0 allgather: pads to ``max_dim0``, returns (gathered, sizes)
    where gathered has shape [axis_size * max_dim0, ...] with invalid rows
    zeroed, and sizes[i] is rank i's true dim0. The caller compacts rows
    outside jit (XLA needs static shapes). This mirrors the reference's
    displacement-based Allgatherv (``mpi_operations.cc:83-162``)."""
    n = x.shape[0]
    pad_width = [(0, max_dim0 - n)] + [(0, 0)] * (x.ndim - 1)
    padded = jnp.pad(x, pad_width)
    gathered = lax.all_gather(padded, axis_name, tiled=True)
    sizes = lax.all_gather(jnp.asarray(n, dtype=jnp.int32), axis_name)
    return gathered, sizes


def broadcast(
    x: jax.Array, *, root_rank: int = 0, axis_name: str = DATA_AXIS
) -> jax.Array:
    """Every rank receives the root's value (reference ``MPI_Bcast``,
    ``mpi_operations.cc:326-356``).

    Lowered as a binomial-tree one-to-all over ``ppermute``: ceil(log2(n))
    rounds in which every rank that already holds the root's value forwards
    it one doubling step further (in root-shifted virtual rank space). Moves
    O(bytes) per link with log-depth latency — unlike the earlier masked
    ``psum``, which paid a full ring allreduce (O(size x bytes) ICI
    traffic) to move one rank's tensor."""
    n = _axis_size(axis_name)
    if not 0 <= int(root_rank) < n:
        # The virtual-rank modulo below would silently wrap an
        # out-of-range root onto the wrong rank.
        raise ValueError(
            f"broadcast root_rank {root_rank} out of range for axis "
            f"{axis_name!r} of size {n}"
        )
    if n == 1:
        return x
    # Virtual rank: root is 0; holders after round t are vr < 2^(t+1).
    vr = (lax.axis_index(axis_name) - root_rank) % n
    out = x
    t = 1
    while t < n:
        count = min(t, n - t)  # senders this round: vr in [0, count)
        perm = [
            ((v + root_rank) % n, (v + t + root_rank) % n)
            for v in range(count)
        ]
        received = lax.ppermute(out, axis_name, perm)
        is_receiver = (vr >= t) & (vr < t + count)
        out = jnp.where(is_receiver, received, out)
        t *= 2
    return out


def alltoall(
    x: jax.Array,
    *,
    axis_name: str = DATA_AXIS,
    split_axis: int = 0,
    concat_axis: int = 0,
) -> jax.Array:
    """TPU-native extension (the reference has no alltoall — op set is
    allreduce/allgather/broadcast only, ``message.h:48-50``); required for
    expert parallelism and Ulysses-style sequence parallelism."""
    return lax.all_to_all(
        x, axis_name, split_axis=split_axis, concat_axis=concat_axis, tiled=True
    )


def reducescatter(
    x: jax.Array,
    *,
    op: ReduceOp = ReduceOp.SUM,
    axis_name: str = DATA_AXIS,
    scatter_axis: int = 0,
) -> jax.Array:
    """Reduce-scatter (TPU-native extension; the reference reaches it only
    inside NCCL hierarchical allreduce, ``nccl_operations.cc:151-346``)."""
    if op == ReduceOp.AVERAGE:
        x = x / _axis_size(axis_name)
    elif op not in (ReduceOp.SUM, ReduceOp.ADASUM):
        raise ValueError(f"reducescatter supports SUM/AVERAGE, got {op}")
    return lax.psum_scatter(x, axis_name, scatter_dimension=scatter_axis, tiled=True)


def hierarchical_allreduce(
    x: jax.Array,
    *,
    op: ReduceOp = ReduceOp.SUM,
    local_axis: str = "local",
    cross_axis: str = "cross",
) -> jax.Array:
    """Two-level allreduce: reduce-scatter over ICI (local axis), allreduce
    the shards over DCN (cross axis), then all-gather over ICI.

    Direct TPU re-expression of ``NCCLHierarchicalAllreduce``
    (``nccl_operations.cc:151-346``): ncclReduceScatter → cross-node
    MPI_Allreduce → ncclAllGather, with the D2H/H2D hops deleted because XLA
    moves shards over DCN directly. MIN/MAX lower as per-hop reduction
    chains (regrouping commutes bitwise); PRODUCT/ADASUM raise — the
    reduce-scatter regrouping has no product form here and Adasum's
    hierarchical schedule lives in ``ops/adasum.py``. Lowering delegated
    to the topology compositor (``topo/compositor.py``), which holds the
    general k-level form.
    """
    from ..topo import compositor as _compositor

    # Raises ValueError for unsupported ops (a silent SUM for MIN/MAX
    # was the old failure mode).
    return _compositor.lower_allreduce(
        x, (cross_axis, local_axis), op=op, algorithm="two-level"
    )


def hierarchical_allgather(
    x: jax.Array,
    *,
    local_axis: str = "local",
    cross_axis: str = "cross",
) -> jax.Array:
    """Two-level allgather: gather over ICI, then gather the slice blocks
    over DCN — the TPU re-expression of ``MPIHierarchicalAllgather``
    (``mpi_operations.cc:168-321``); rank order ``cross*local_size+local``
    keeps the concatenation identical to the flat op."""
    from ..topo import compositor as _compositor

    return _compositor.lower_allgather(
        x, (cross_axis, local_axis), algorithm="two-level"
    )


def hierarchical_reducescatter(
    x: jax.Array,
    *,
    op: ReduceOp = ReduceOp.SUM,
    local_axis: str = "local",
    cross_axis: str = "cross",
) -> jax.Array:
    """Two-level reduce-scatter: a local block transpose (free relayout)
    lets the ICI hop reduce-scatter FIRST, so only the 1/local_size shard
    crosses DCN, while the emitted shard matches the flat op's rank
    order."""
    from ..topo import compositor as _compositor

    return _compositor.lower_reducescatter(
        x, (cross_axis, local_axis), op=op, algorithm="two-level"
    )


def hierarchical_broadcast(
    x: jax.Array,
    *,
    root_rank: int = 0,
    local_axis: str = "local",
    cross_axis: str = "cross",
) -> jax.Array:
    """Two-level broadcast: binomial tree inside the root's slice (ICI),
    then per-column trees across slices (DCN) — each stage stays on one
    hop instead of the flat tree's rounds straddling DCN."""
    from ..topo import compositor as _compositor

    return _compositor.lower_broadcast(
        x, (cross_axis, local_axis), root_rank=root_rank,
        algorithm="two-level",
    )


def hierarchical_alltoall(
    x: jax.Array,
    *,
    local_axis: str = "local",
    cross_axis: str = "cross",
) -> jax.Array:
    """Two-level all-to-all: one cross-slice exchange (DCN) grouped by
    destination slice, a local block transpose, then the intra-slice
    exchange (ICI) — flat-equal output in source-rank order."""
    from ..topo import compositor as _compositor

    return _compositor.lower_alltoall(
        x, (cross_axis, local_axis), algorithm="two-level"
    )
