"""Flash attention as a Pallas TPU kernel.

The hot op of the transformer/long-context path, written for the TPU
memory hierarchy: Q/K/V blocks stream HBM -> VMEM, scores and the online-
softmax state live in VMEM scratch, and the [block_q, block_k] score
matmul + [block_k, d] value matmul hit the MXU. O(T) memory instead of
materializing the [T, T] probability matrix.

The reference framework has no kernels at all (it is gradient plumbing;
SURVEY.md §2.3) — this powers the model-side extensions (transformer
models, ring attention's per-block compute). Backward is a custom VJP
that recomputes probabilities blockwise in plain XLA (the standard
rematerialization trade: no [T, T] residual is ever stored).

Interpret mode (``interpret=True``) runs the same kernel on CPU and is
what the tests exercise on the virtual mesh.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                sm_scale: float, causal: bool, block_q: int, block_k: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q = q_ref[0].astype(jnp.float32)   # [Bq, D]
    k = k_ref[0].astype(jnp.float32)   # [Bk, D]
    v = v_ref[0].astype(jnp.float32)   # [Bk, D]

    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * sm_scale                        # [Bq, Bk]

    if causal:
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        mask = q_pos >= k_pos
        s = jnp.where(mask, s, _NEG_INF)

    m_prev = m_ref[:, :1]                       # [Bq, 1]
    l_prev = l_ref[:, :1]
    m_curr = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    alpha = jnp.exp(m_prev - m_curr)
    p = jnp.exp(s - m_curr)                     # [Bq, Bk]
    if causal:
        # A fully-masked row has m_curr == _NEG_INF and would turn the
        # masked entries into exp(0) = 1; re-apply the mask to p.
        p = jnp.where(mask, p, 0.0)
    l_curr = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
    acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    m_ref[:] = jnp.broadcast_to(m_curr, m_ref.shape)
    l_ref[:] = jnp.broadcast_to(l_curr, l_ref.shape)

    @pl.when(ki == pl.num_programs(2) - 1)
    def _finalize():
        l = l_ref[:, :1]
        l = jnp.where(l == 0.0, 1.0, l)         # fully-masked rows -> 0 out
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)


def _flash_fwd_impl(q, k, v, *, sm_scale, causal, block_q, block_k,
                    interpret):
    bh, t_q, d = q.shape
    t_k = k.shape[1]
    block_q = min(block_q, t_q)
    block_k = min(block_k, t_k)
    if t_q % block_q or t_k % block_k:
        raise ValueError(
            f"sequence lengths ({t_q}, {t_k}) must divide by blocks "
            f"({block_q}, {block_k})"
        )
    grid = (bh, t_q // block_q, t_k // block_k)
    from jax.experimental.pallas import tpu as pltpu

    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k,
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((bh, t_q, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v)


def _attention_dense(q, k, v, sm_scale, causal):
    """Plain-XLA reference used by the recompute backward."""
    s = jnp.einsum("bqd,bkd->bqk", q, k).astype(jnp.float32) * sm_scale
    if causal:
        t_q, t_k = s.shape[-2:]
        mask = (
            jnp.arange(t_q)[:, None] >= jnp.arange(t_k)[None, :]
        )
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    return _flash_fwd_impl(
        q, k, v, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )


def _flash_vjp_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    o = _flash(q, k, v, sm_scale, causal, block_q, block_k, interpret)
    return o, (q, k, v)


def _flash_vjp_bwd(sm_scale, causal, block_q, block_k, interpret, res, do):
    q, k, v = res

    def f(q, k, v):
        return _attention_dense(q, k, v, sm_scale, causal).astype(q.dtype)

    _, vjp = jax.vjp(f, q, k, v)
    return vjp(do)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Fused attention over ``[..., T, D]`` (leading dims fold into one
    batch x heads grid axis). Differentiable; backward rematerializes.

    ``interpret`` defaults to True off-TPU so the same code runs in tests
    on the virtual CPU mesh.
    """
    if q.ndim < 3:
        raise ValueError("expected [..., T, D] with at least one batch dim")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    lead = q.shape[:-2]
    t_q, d = q.shape[-2:]
    t_k = k.shape[-2]
    scale = sm_scale if sm_scale is not None else d ** -0.5
    qf = q.reshape((-1, t_q, d))
    kf = k.reshape((-1, t_k, d))
    vf = v.reshape((-1, t_k, d))
    out = _flash(qf, kf, vf, scale, causal, block_q, block_k, interpret)
    return out.reshape(*lead, t_q, d)
