"""Flash attention as a Pallas TPU kernel.

The hot op of the transformer/long-context path, written for the TPU
memory hierarchy: Q/K/V blocks stream HBM -> VMEM, scores and the online-
softmax state live in VMEM scratch, and the [block_q, block_k] score
matmul + [block_k, d] value matmul hit the MXU. O(T) memory instead of
materializing the [T, T] probability matrix.

The reference framework has no kernels at all (it is gradient plumbing;
SURVEY.md §2.3) — this powers the model-side extensions: it is the default
``attn_fn`` of ``models/transformer.py`` (via :func:`flash_attention_bthd`)
and the per-block compute of ``parallel/ring_attention.py`` (via
:func:`flash_attention_block`, which returns the unnormalized numerator and
the online-softmax statistics so ring steps merge outside the kernel).

Backward: :func:`flash_attention` uses a custom VJP that recomputes
probabilities from the saved logsumexp blockwise under a ``lax.scan`` —
O(T * block_k) live memory, never a [T, T] residual. The ring block's VJP
recomputes its single [T, T/n] block densely (the same memory class as the
forward block it differentiates).

Interpret mode (``interpret=True``, the default off-TPU) runs the same
kernels on CPU; the tests exercise it via the transformer/ring test suites
and ``tests/test_models.py``/``tests/test_ring_attention.py`` plus the
dedicated kernel tests in ``tests/test_flash_attention.py``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
_LANES = 128  # TPU lane width; m/l carriers keep a lane dim like the
              # upstream jax flash kernel's lse outputs.


def _compiler_params(**kw):
    """pltpu.CompilerParams was named TPUCompilerParams before jax 0.5."""
    cls = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams"
    )
    return cls(**kw)


def _pick_block(t: int, pref: int) -> int:
    """Largest block <= pref that divides t (XLA/Mosaic needs an exact
    grid). Degrading a little below ``pref`` is fine; degrading to a tiny
    block (prime/odd T) would silently explode the grid into T*T scalar
    steps, so that case stays a hard error like the original kernel."""
    cap = min(pref, t)
    b = cap
    while t % b:
        b -= 1
    if b < 8 and b < cap:
        raise ValueError(
            f"sequence length {t} has no block divisor >= 8 under "
            f"{pref}; pad the sequence or pass explicit block sizes"
        )
    return b


def flashable(t_q: int, t_k: int, block_q: int = 128,
              block_k: int = 128) -> bool:
    """Whether the kernel accepts these sequence lengths (callers with
    arbitrary shapes use this to fall back to dense attention instead of
    crashing on prime/odd lengths)."""
    try:
        _pick_block(t_q, block_q)
        _pick_block(t_k, block_k)
        return True
    except ValueError:
        return False


def _dense_full(q, k, v, causal, sm_scale):
    """Dense [BH, T, D] attention — the graceful fallback for shapes the
    kernel's block constraint rejects."""
    s = jnp.einsum(
        "bqd,bkd->bqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * sm_scale
    if causal:
        t_q, t_k = q.shape[1], k.shape[1]
        mask = jnp.arange(t_q)[:, None] >= jnp.arange(t_k)[None, :]
        s = jnp.where(mask[None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum(
        "bqk,bkd->bqd", p, v.astype(jnp.float32)
    ).astype(q.dtype)


def _fwd_kernel(delta_ref, q_ref, k_ref, v_ref,
                o_ref, m_out_ref, l_out_ref,
                acc_ref, m_ref, l_ref, *,
                sm_scale: float, causal: bool, block_q: int, block_k: int,
                normalize: bool):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q = q_ref[0].astype(jnp.float32)   # [Bq, D]
    k = k_ref[0].astype(jnp.float32)   # [Bk, D]
    v = v_ref[0].astype(jnp.float32)   # [Bk, D]

    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * sm_scale                        # [Bq, Bk]

    if causal:
        # Global positions: q at q_pos, k at k_pos + delta, where delta is
        # the (dynamic) offset of the K block's sequence origin relative to
        # Q's — 0 for self-attention, src*T - rank*T inside ring attention.
        delta = delta_ref[0]
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        ) + delta
        mask = q_pos >= k_pos
        s = jnp.where(mask, s, _NEG_INF)

    m_prev = m_ref[:, :1]                       # [Bq, 1]
    l_prev = l_ref[:, :1]
    m_curr = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    alpha = jnp.exp(m_prev - m_curr)
    p = jnp.exp(s - m_curr)                     # [Bq, Bk]
    if causal:
        # A fully-masked row has m_curr == _NEG_INF and would turn the
        # masked entries into exp(0) = 1; re-apply the mask to p.
        p = jnp.where(mask, p, 0.0)
    l_curr = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
    acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    m_ref[:] = jnp.broadcast_to(m_curr, m_ref.shape)
    l_ref[:] = jnp.broadcast_to(l_curr, l_ref.shape)

    @pl.when(ki == pl.num_programs(2) - 1)
    def _finalize():
        if normalize:
            l = l_ref[:, :1]
            l = jnp.where(l == 0.0, 1.0, l)     # fully-masked rows -> 0 out
            o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)
        else:
            o_ref[0] = acc_ref[:].astype(o_ref.dtype)
        m_out_ref[0] = m_ref[:]
        l_out_ref[0] = l_ref[:]


def _flash_call(q, k, v, delta, *, sm_scale, causal, block_q, block_k,
                normalize, interpret, out_dtype):
    """Run the forward kernel; returns (o, m, l) with m/l of shape
    [bh, t_q] (row max / softmax denominator in the online recurrence)."""
    bh, t_q, d = q.shape
    t_k = k.shape[1]
    block_q = _pick_block(t_q, block_q)
    block_k = _pick_block(t_k, block_k)
    grid = (bh, t_q // block_q, t_k // block_k)

    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, normalize=normalize,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j, ref: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j, ref: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j, ref: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j, ref: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANES),
                         lambda b, i, j, ref: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANES),
                         lambda b, i, j, ref: (b, i, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
    )
    o, m, l = pl.pallas_call(
        kernel,
        out_shape=[
            jax.ShapeDtypeStruct((bh, t_q, d), out_dtype),
            jax.ShapeDtypeStruct((bh, t_q, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((bh, t_q, _LANES), jnp.float32),
        ],
        grid_spec=grid_spec,
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(jnp.asarray(delta, jnp.int32).reshape(1), q, k, v)
    return o, m[:, :, 0], l[:, :, 0]


# --------------------------------------------------------------------------
# Full (self-)attention with blockwise-recompute backward.
# --------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    o, _, _ = _flash_call(
        q, k, v, 0, sm_scale=sm_scale, causal=causal, block_q=block_q,
        block_k=block_k, normalize=True, interpret=interpret,
        out_dtype=q.dtype,
    )
    return o


def _flash_vjp_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    o, m, l = _flash_call(
        q, k, v, 0, sm_scale=sm_scale, causal=causal, block_q=block_q,
        block_k=block_k, normalize=True, interpret=interpret,
        out_dtype=q.dtype,
    )
    lse = m + jnp.log(jnp.where(l == 0.0, 1.0, l))   # [bh, tq]
    return o, (q, k, v, o, lse)


def _flash_vjp_bwd(sm_scale, causal, block_q, block_k, interpret, res, do):
    """Flash backward: probabilities are recomputed per K/V block from the
    saved logsumexp inside a ``lax.scan`` — live memory is O(T * block_k),
    no [T, T] tensor is ever materialized."""
    q, k, v, o, lse = res
    bh, t_q, d = q.shape
    t_k = k.shape[1]
    bk = _pick_block(t_k, block_k)
    n_blocks = t_k // bk

    qf = q.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    # D_i = sum_j dO_ij O_ij (the softmax-jacobian row term).
    D = jnp.sum(dof * o.astype(jnp.float32), axis=-1)   # [bh, tq]
    q_pos = jnp.arange(t_q)

    def body(dq_acc, idx):
        kb = lax.dynamic_slice_in_dim(k, idx * bk, bk, axis=1)
        vb = lax.dynamic_slice_in_dim(v, idx * bk, bk, axis=1)
        kbf = kb.astype(jnp.float32)
        vbf = vb.astype(jnp.float32)
        s = jnp.einsum("bqd,bkd->bqk", qf, kbf) * sm_scale
        if causal:
            k_pos = idx * bk + jnp.arange(bk)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None], s, _NEG_INF)
        p = jnp.exp(s - lse[:, :, None])                # [bh, tq, bk]
        if causal:
            p = jnp.where(mask[None], p, 0.0)
        dp = jnp.einsum("bqd,bkd->bqk", dof, vbf)
        ds = p * (dp - D[:, :, None]) * sm_scale
        dq_acc = dq_acc + jnp.einsum("bqk,bkd->bqd", ds, kbf)
        dk_b = jnp.einsum("bqk,bqd->bkd", ds, qf)
        dv_b = jnp.einsum("bqk,bqd->bkd", p, dof)
        return dq_acc, (dk_b, dv_b)

    dq, (dks, dvs) = lax.scan(
        body, jnp.zeros(q.shape, jnp.float32), jnp.arange(n_blocks)
    )
    dk = jnp.moveaxis(dks, 0, 1).reshape(k.shape)
    dv = jnp.moveaxis(dvs, 0, 1).reshape(v.shape)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Fused attention over ``[..., T, D]`` (leading dims fold into one
    batch x heads grid axis). Differentiable; backward recomputes blockwise.

    ``interpret`` defaults to True off-TPU so the same code runs in tests
    on the virtual CPU mesh.
    """
    if q.ndim < 3:
        raise ValueError("expected [..., T, D] with at least one batch dim")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    lead = q.shape[:-2]
    t_q, d = q.shape[-2:]
    t_k = k.shape[-2]
    scale = sm_scale if sm_scale is not None else d ** -0.5
    qf = q.reshape((-1, t_q, d))
    kf = k.reshape((-1, t_k, d))
    vf = v.reshape((-1, t_k, d))
    out = _flash(qf, kf, vf, scale, causal, block_q, block_k, interpret)
    return out.reshape(*lead, t_q, d)


def flash_attention_bthd(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Layout adapter for the transformer's ``[B, T, H, D]`` attention
    signature (``models/transformer.py``): fold heads into the kernel's
    batch axis, run the fused kernel, unfold. Sequence lengths the kernel's
    block constraint rejects (prime/odd T) take a dense fallback instead of
    raising, so the default attention accepts any shape."""
    B, T, H, D = q.shape
    fold = lambda x: x.transpose(0, 2, 1, 3).reshape(B * H, x.shape[1], D)
    qf, kf, vf = fold(q), fold(k), fold(v)
    scale = sm_scale if sm_scale is not None else D ** -0.5
    if flashable(T, k.shape[1]):
        out = flash_attention(
            qf, kf, vf, causal=causal, sm_scale=scale, interpret=interpret,
        )
    else:
        out = _dense_full(qf, kf, vf, causal, scale)
    return out.reshape(B, H, T, D).transpose(0, 2, 1, 3)


# --------------------------------------------------------------------------
# Ring-attention block: unnormalized numerator + online-softmax stats.
# --------------------------------------------------------------------------

def _dense_block(q, k, v, delta, sm_scale, causal):
    """Dense computation of exactly the kernel's (o_unnorm, m, l) triple —
    the recompute target for the block VJP."""
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    s = jnp.einsum("bqd,bkd->bqk", qf, kf) * sm_scale
    t_q, t_k = q.shape[1], k.shape[1]
    if causal:
        mask = (
            jnp.arange(t_q)[:, None] >= jnp.arange(t_k)[None, :] + delta
        )
        s = jnp.where(mask[None], s, _NEG_INF)
    m = jnp.maximum(jnp.max(s, axis=-1), _NEG_INF)
    p = jnp.exp(s - m[..., None])
    if causal:
        p = jnp.where(mask[None], p, 0.0)
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bqk,bkd->bqd", p, vf)
    return o, m, l


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash_block(q, k, v, delta, sm_scale, causal, block_q, block_k,
                 interpret):
    return _flash_call(
        q, k, v, delta, sm_scale=sm_scale, causal=causal, block_q=block_q,
        block_k=block_k, normalize=False, interpret=interpret,
        out_dtype=jnp.float32,
    )


def _flash_block_vjp_fwd(q, k, v, delta, sm_scale, causal, block_q, block_k,
                         interpret):
    out = _flash_block(q, k, v, delta, sm_scale, causal, block_q, block_k,
                       interpret)
    return out, (q, k, v, delta)


def _flash_block_vjp_bwd(sm_scale, causal, block_q, block_k, interpret, res,
                         cts):
    q, k, v, delta = res

    def f(q, k, v):
        return _dense_block(q, k, v, delta, sm_scale, causal)

    _, vjp = jax.vjp(f, q, k, v)
    dq, dk, dv = vjp(cts)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            jnp.zeros_like(delta))


_flash_block.defvjp(_flash_block_vjp_fwd, _flash_block_vjp_bwd)


def flash_attention_block(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    delta,
    *,
    sm_scale: float,
    causal: bool = True,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> tuple:
    """One ring-attention block: q/k/v are ``[BH, T, D]``; ``delta`` is a
    float scalar giving the K block's global sequence offset minus Q's
    (traced — ring steps compute it from ``lax.axis_index``). Returns
    ``(o_unnormalized_f32, m, l)`` for the caller's online-softmax merge
    (``parallel/ring_attention.py``)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    delta = jnp.asarray(delta, jnp.float32)
    return _flash_block(q, k, v, delta, sm_scale, causal, block_q, block_k,
                        interpret)
