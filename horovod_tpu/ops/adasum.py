"""Adasum adaptive allreduce, re-expressed as a static ppermute schedule.

The reference implements Adasum as VHDD (vector-halving distance-doubling)
over MPI point-to-point (``horovod/common/ops/adasum/adasum.h:186-391``):
log2(n) levels of pairwise exchange, where each pair combines adaptively

    a' = (1 - a.b / (2*||a||^2)) * a  +  (1 - a.b / (2*||b||^2)) * b

(``adasum.h:378-388``) so that orthogonal gradients add and parallel
gradients average — scale-insensitive reduction.

TPU-native formulation: at level ``l`` every rank exchanges its current
combined vector with partner ``rank XOR 2^l`` via ``lax.ppermute`` and
combines locally. Because the pairwise combine is symmetric, both members of
a pair compute the identical result, so after ``log2(n)`` levels all ranks
hold Adasum(a_0..a_{n-1}) — no mirror/allgather phase is needed (the
reference needs one only because it *halves* the payload each level;
``adasum.h:301-327``). This trades up to 2x per-level bandwidth for a purely
static schedule XLA can pipeline over ICI; a reduce-scatter formulation with
``axis_index_groups`` dot-psum is the planned optimization.

Requires a power-of-2 axis size, like the reference
(``horovod/torch/mpi_ops.py:104-120``).
"""

from __future__ import annotations

from typing import Any, List, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..common.compat import axis_size as _axis_size
from ..parallel.mesh import DATA_AXIS


def _pairwise_combine(a: jax.Array, b: jax.Array) -> jax.Array:
    """Adaptive pairwise combine (reference adasum.h:378-388).

    Computed in fp32 for low-precision inputs; falls back to plain average
    when either vector is zero (reference guards: if norm == 0 coefficient
    stays 1, i.e. simple sum of the zero vector)."""
    compute_dtype = jnp.float32 if a.dtype in (jnp.bfloat16, jnp.float16) else a.dtype
    af = a.astype(compute_dtype).reshape(-1)
    bf = b.astype(compute_dtype).reshape(-1)
    ab = jnp.vdot(af, bf)
    aa = jnp.vdot(af, af)
    bb = jnp.vdot(bf, bf)
    coeff_a = jnp.where(aa > 0, 1.0 - ab / (2.0 * jnp.where(aa > 0, aa, 1.0)), 1.0)
    coeff_b = jnp.where(bb > 0, 1.0 - ab / (2.0 * jnp.where(bb > 0, bb, 1.0)), 1.0)
    out = coeff_a * af + coeff_b * bf
    return out.reshape(a.shape).astype(a.dtype)


def adasum_allreduce(x: jax.Array, *, axis_name: str = DATA_AXIS) -> jax.Array:
    """In-jit Adasum over a named mesh axis (power-of-2 size)."""
    n = _axis_size(axis_name)
    if n & (n - 1) != 0:
        raise ValueError(
            f"Adasum requires a power-of-2 number of ranks, got {n} "
            "(reference enforces the same, horovod/torch/mpi_ops.py:104-120)"
        )
    if n == 1:
        return x
    level = 1
    while level < n:
        # partner = idx XOR level, as a static permutation table.
        perm = [(i, i ^ level) for i in range(n)]
        partner_x = lax.ppermute(x, axis_name, perm)
        x = _pairwise_combine(x, partner_x)
        level <<= 1
    return x


def adasum_allreduce_reference(vectors: List[Any]) -> Any:
    """NumPy reference implementation (recursive halving over a list), used
    by the numeric tests the same way the reference tests check VHDD against
    a host-side formula (``test/test_adasum_pytorch.py``)."""
    import numpy as np

    def combine(a, b):
        a = np.asarray(a, dtype=np.float64)
        b = np.asarray(b, dtype=np.float64)
        ab = float(np.vdot(a.ravel(), b.ravel()))
        aa = float(np.vdot(a.ravel(), a.ravel()))
        bb = float(np.vdot(b.ravel(), b.ravel()))
        ca = 1.0 - ab / (2.0 * aa) if aa > 0 else 1.0
        cb = 1.0 - ab / (2.0 * bb) if bb > 0 else 1.0
        return ca * a + cb * b

    vecs = list(vectors)
    while len(vecs) > 1:
        vecs = [combine(vecs[i], vecs[i + 1]) for i in range(0, len(vecs), 2)]
    return vecs[0]


def hierarchical_adasum_reference(vectors: List[Any], local_size: int) -> Any:
    """NumPy reference for the hierarchical variant: node sums are
    reduce-scattered into ``local_size`` contiguous chunks, VHDD combines
    each chunk independently across nodes (per-chunk dot products, exactly
    what each local rank computes on its shard), and the chunks concatenate
    back. Mirrors ``adasum_cuda_operations.cc`` semantics; rank order is
    rank = cross * local_size + local."""
    import numpy as np

    vecs = [np.asarray(v, dtype=np.float64).reshape(-1) for v in vectors]
    assert len(vecs) % local_size == 0
    cross = len(vecs) // local_size
    node_sums = [
        np.sum(vecs[c * local_size:(c + 1) * local_size], axis=0)
        for c in range(cross)
    ]
    n = node_sums[0].size
    pad = (-n) % local_size
    if pad:
        node_sums = [np.concatenate([v, np.zeros(pad)]) for v in node_sums]
    chunk = (n + pad) // local_size
    out_chunks = [
        adasum_allreduce_reference(
            [v[s * chunk:(s + 1) * chunk] for v in node_sums]
        )
        for s in range(local_size)
    ]
    return np.concatenate(out_chunks)[:n].reshape(np.asarray(vectors[0]).shape)


def hierarchical_adasum_allreduce(
    x: jax.Array,
    *,
    local_axis: str = "local",
    cross_axis: str = "cross",
) -> jax.Array:
    """Hierarchical Adasum on a (cross, local) mesh — the TPU re-expression
    of the reference's CUDA variant (``adasum_cuda_operations.cc:1-321``):
    NCCL reduce-scatter within the node → VHDD across nodes on the shards →
    NCCL allgather, with the D2H/H2D staging deleted because the cross hop
    rides DCN directly.

    Each node therefore contributes the *sum* of its local ranks' vectors
    and the adaptive combine runs between node sums; like the reference,
    dividing by local_size to turn the node sum into a node average is the
    framework layer's job (``horovod/tensorflow/__init__.py:98-106``).
    """
    flat = x.reshape(-1)
    n = flat.shape[0]
    local_size = _axis_size(local_axis)
    pad = (-n) % local_size
    if pad:
        flat = jnp.pad(flat, (0, pad))
    shard = lax.psum_scatter(flat, local_axis, scatter_dimension=0, tiled=True)
    shard = adasum_allreduce(shard, axis_name=cross_axis)
    full = lax.all_gather(shard, local_axis, tiled=True)
    if pad:
        full = full[:n]
    return full.reshape(x.shape)


def adasum_reduce_fn(
    x: jax.Array,
    *,
    op=None,
    axis_name=DATA_AXIS,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
) -> jax.Array:
    """Signature-compatible drop-in for ``collectives.allreduce`` so the
    fusion pass can route op=Adasum buckets here.

    ``axis_name`` may be a single named axis (flat VHDD) or a
    ``(cross_axis, local_axis)`` tuple for the hierarchical variant
    (local reduce-scatter → cross VHDD → local allgather)."""
    if prescale_factor != 1.0:
        x = x * prescale_factor
    if isinstance(axis_name, str):
        out = adasum_allreduce(x, axis_name=axis_name)
    else:
        try:
            cross_axis, local_axis = axis_name
        except (TypeError, ValueError):
            raise ValueError(
                "Adasum axis_name must be a named axis or a "
                f"(cross, local) pair; got {axis_name!r}"
            ) from None
        out = hierarchical_adasum_allreduce(
            x, local_axis=local_axis, cross_axis=cross_axis
        )
    if postscale_factor != 1.0:
        out = out * postscale_factor
    return out
