"""Adasum adaptive allreduce, re-expressed as a static ppermute schedule.

The reference implements Adasum as VHDD (vector-halving distance-doubling)
over MPI point-to-point (``horovod/common/ops/adasum/adasum.h:186-391``):
log2(n) levels of pairwise exchange, where each pair combines adaptively

    a' = (1 - a.b / (2*||a||^2)) * a  +  (1 - a.b / (2*||b||^2)) * b

(``adasum.h:378-388``) so that orthogonal gradients add and parallel
gradients average — scale-insensitive reduction.

TPU-native formulation: at level ``l`` every rank exchanges its current
combined vector with partner ``rank XOR 2^l`` via ``lax.ppermute`` and
combines locally. Because the pairwise combine is symmetric, both members of
a pair compute the identical result, so after ``log2(n)`` levels all ranks
hold Adasum(a_0..a_{n-1}) — no mirror/allgather phase is needed (the
reference needs one only because it *halves* the payload each level;
``adasum.h:301-327``). This trades up to 2x per-level bandwidth for a purely
static schedule XLA can pipeline over ICI; a reduce-scatter formulation with
``axis_index_groups`` dot-psum is the planned optimization.

Requires a power-of-2 axis size, like the reference
(``horovod/torch/mpi_ops.py:104-120``).
"""

from __future__ import annotations

from typing import Any, List, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..parallel.mesh import DATA_AXIS


def _pairwise_combine(a: jax.Array, b: jax.Array) -> jax.Array:
    """Adaptive pairwise combine (reference adasum.h:378-388).

    Computed in fp32 for low-precision inputs; falls back to plain average
    when either vector is zero (reference guards: if norm == 0 coefficient
    stays 1, i.e. simple sum of the zero vector)."""
    compute_dtype = jnp.float32 if a.dtype in (jnp.bfloat16, jnp.float16) else a.dtype
    af = a.astype(compute_dtype).reshape(-1)
    bf = b.astype(compute_dtype).reshape(-1)
    ab = jnp.vdot(af, bf)
    aa = jnp.vdot(af, af)
    bb = jnp.vdot(bf, bf)
    coeff_a = jnp.where(aa > 0, 1.0 - ab / (2.0 * jnp.where(aa > 0, aa, 1.0)), 1.0)
    coeff_b = jnp.where(bb > 0, 1.0 - ab / (2.0 * jnp.where(bb > 0, bb, 1.0)), 1.0)
    out = coeff_a * af + coeff_b * bf
    return out.reshape(a.shape).astype(a.dtype)


def adasum_allreduce(x: jax.Array, *, axis_name: str = DATA_AXIS) -> jax.Array:
    """In-jit Adasum over a named mesh axis (power-of-2 size)."""
    n = lax.axis_size(axis_name)
    if n & (n - 1) != 0:
        raise ValueError(
            f"Adasum requires a power-of-2 number of ranks, got {n} "
            "(reference enforces the same, horovod/torch/mpi_ops.py:104-120)"
        )
    if n == 1:
        return x
    level = 1
    while level < n:
        # partner = idx XOR level, as a static permutation table.
        perm = [(i, i ^ level) for i in range(n)]
        partner_x = lax.ppermute(x, axis_name, perm)
        x = _pairwise_combine(x, partner_x)
        level <<= 1
    return x


def adasum_allreduce_reference(vectors: List[Any]) -> Any:
    """NumPy reference implementation (recursive halving over a list), used
    by the numeric tests the same way the reference tests check VHDD against
    a host-side formula (``test/test_adasum_pytorch.py``)."""
    import numpy as np

    def combine(a, b):
        a = np.asarray(a, dtype=np.float64)
        b = np.asarray(b, dtype=np.float64)
        ab = float(np.vdot(a.ravel(), b.ravel()))
        aa = float(np.vdot(a.ravel(), a.ravel()))
        bb = float(np.vdot(b.ravel(), b.ravel()))
        ca = 1.0 - ab / (2.0 * aa) if aa > 0 else 1.0
        cb = 1.0 - ab / (2.0 * bb) if bb > 0 else 1.0
        return ca * a + cb * b

    vecs = list(vectors)
    while len(vecs) > 1:
        vecs = [combine(vecs[i], vecs[i + 1]) for i in range(0, len(vecs), 2)]
    return vecs[0]


def adasum_reduce_fn(
    x: jax.Array,
    *,
    op=None,
    axis_name: str = DATA_AXIS,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
) -> jax.Array:
    """Signature-compatible drop-in for ``collectives.allreduce`` so the
    fusion pass can route op=Adasum buckets here."""
    if not isinstance(axis_name, str):
        raise ValueError(
            "Adasum runs over a single named axis (the ppermute schedule is "
            f"1-D); got axis_name={axis_name!r}. Use a flat data axis, or "
            "the hierarchical Adasum variant once available."
        )
    if prescale_factor != 1.0:
        x = x * prescale_factor
    out = adasum_allreduce(x, axis_name=axis_name)
    if postscale_factor != 1.0:
        out = out * postscale_factor
    return out
