"""Chunked collective-matmul primitives: compute fused into the wire.

The composed DP x TP fast path (docs/parallelism.md) pays the Megatron
row-parallel psum as fully exposed latency — the wire and the MXU
alternate. These two primitives make them share a timeline:

- :func:`all_gather_matmul` — ``y = all_gather(x_shard) @ w``, the
  column-parallel consume of a token-sharded activation: each of the
  n−1 ring hops transfers the next activation chunk while the MXU
  multiplies the one that just arrived, split bidirectionally so both
  ring directions carry half the gathered payload (FlexLink-style).
- :func:`matmul_reduce_scatter` — ``z = reduce_scatter(y @ w)`` over
  the token dim, the row-parallel produce: partial products are
  computed per DESTINATION chunk and reduced along the ring, again
  split over both directions.

``psum(y @ w) == all_gather(matmul_reduce_scatter(y, w))`` over tokens,
which is what makes the fused Megatron block numerically equivalent to
the classic one-psum-per-half-block schedule (tests lock <=5e-7).

Following the ``ops/pallas_attention.py`` pattern, each primitive has
two lowerings selected by backend:

1. an interpret/shard_map REFERENCE — a chunked ``lax.ppermute`` loop
   that is CPU-testable and numerically provable today (this is what
   CI executes, and what the HLO assertions count ppermutes on);
2. a Pallas TPU kernel using double-buffered async remote copies
   (``pltpu.make_async_remote_copy``), one DMA in flight per direction
   while the MXU multiplies the resident chunk.

Both primitives carry a custom VJP whose backward is built from the
DUAL primitive — d(all_gather_matmul)/dx is a matmul_reduce_scatter
and d(matmul_reduce_scatter)/dy is an all_gather_matmul — so the
backward overlaps exactly like the forward (the "path-aware backward").

Wire attribution: every ring pass charges the model axis through
``fusion.record_axis_wire_bytes`` under its own collective label
(``all_gather_matmul`` / ``matmul_reduce_scatter``), (n−1)/n of the
full payload per pass — exact under any chunk count, since sub-chunking
changes pipelining, never bytes.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..common.compat import axis_size as _axis_size

__all__ = [
    "all_gather_matmul",
    "matmul_reduce_scatter",
    "resolve_chunks",
    "ring_hops",
    "fusable",
    "expected_ppermutes",
]


# --------------------------------------------------------- ring shape


def ring_hops(n: int):
    """(forward, backward) hop counts of the bidirectional ring: the
    n−1 transfers split so both directions carry half the payload."""
    n = int(n)
    if n <= 1:
        return 0, 0
    return (n - 1 + 1) // 2, (n - 1) // 2


def resolve_chunks(tokens_per_rank: int, chunks: int = 0) -> int:
    """The effective sub-chunk count: ``chunks`` (or the
    ``HOROVOD_TP_OVERLAP_CHUNKS`` knob when 0) clamped to the largest
    divisor of the per-rank token chunk — a ragged split would change
    bytes-on-wire accounting, so we never allow one."""
    c = int(chunks)
    if c <= 0:
        try:
            c = int(os.environ.get("HOROVOD_TP_OVERLAP_CHUNKS", "0"))
        except ValueError:
            c = 0
    if c <= 0:
        c = 1
    t = max(int(tokens_per_rank), 1)
    c = min(c, t)
    while t % c:
        c -= 1
    return max(c, 1)


def expected_ppermutes(n: int, chunks: int = 1) -> int:
    """ppermute ops ONE primitive's forward ring lowers to: every
    sub-chunk makes the full bidirectional traversal."""
    return (int(n) - 1) * max(int(chunks), 1) if n > 1 else 0


def fusable(tokens: int, n: int) -> bool:
    """Whether the token dim splits evenly over the axis — the fused
    schedule needs equal chunks (callers fall back to the classic
    psum path otherwise)."""
    n = int(n)
    return n > 1 and int(tokens) % n == 0


def _perms(n: int):
    fwd = [(i, (i + 1) % n) for i in range(n)]
    bwd = [(i, (i - 1) % n) for i in range(n)]
    return fwd, bwd


def _record(payload_bytes: int, axis_name: str, collective: str) -> None:
    from . import fusion as _fusion

    _fusion.record_axis_wire_bytes(payload_bytes, axis_name, collective)


# ------------------------------------------- reference ring lowerings


def _upd_tokens(out, val, row_start):
    """dynamic_update_slice of ``val`` into ``out`` at token offset
    ``row_start`` (token dim is -2)."""
    idx = [0] * out.ndim
    idx[-2] = row_start
    return lax.dynamic_update_slice(out, val, tuple(idx))


def _seg_tokens(x, start, size):
    return lax.dynamic_slice_in_dim(x, start, size, axis=-2)


def _ag_matmul_ref(x, w, axis_name: str, chunks: int):
    """Reference all_gather_matmul: bidirectional chunked ppermute ring.

    ``x`` [..., Tc, D] (this rank's token chunk), ``w`` [D, F]. Returns
    [..., n*Tc, F] with source rank j's rows at offset j*Tc — the
    ``lax.all_gather(..., tiled=True)`` order.
    """
    n = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    tc = x.shape[-2]
    local = x @ w
    out = jnp.zeros(x.shape[:-2] + (n * tc, w.shape[-1]), local.dtype)
    out = _upd_tokens(out, local, idx * tc)
    if n <= 1:
        return out
    h_fwd, h_bwd = ring_hops(n)
    perm_f, perm_b = _perms(n)
    c = resolve_chunks(tc, chunks)
    sc = tc // c
    for s in range(c):
        sub = _seg_tokens(x, s * sc, sc)
        fwd = sub
        for k in range(1, h_fwd + 1):
            fwd = lax.ppermute(fwd, axis_name, perm_f)
            src = (idx - k) % n
            out = _upd_tokens(out, fwd @ w, src * tc + s * sc)
        bwd = sub
        for k in range(1, h_bwd + 1):
            bwd = lax.ppermute(bwd, axis_name, perm_b)
            src = (idx + k) % n
            out = _upd_tokens(out, bwd @ w, src * tc + s * sc)
    return out


def _mrs_ref(y, w, axis_name: str, chunks: int):
    """Reference matmul_reduce_scatter: partial products per
    DESTINATION token chunk, reduced bidirectionally along the ring.

    ``y`` [..., T, Fl] (full tokens, local features), ``w`` [Fl, D].
    Returns this rank's [..., T/n, D] chunk of
    ``reduce_scatter(y @ w)`` (token-tiled, SUM).
    """
    n = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    t = y.shape[-2]
    if t % n:
        raise ValueError(
            f"matmul_reduce_scatter needs tokens ({t}) divisible by the "
            f"axis size ({n})"
        )
    tc = t // n
    h_fwd, h_bwd = ring_hops(n)
    perm_f, perm_b = _perms(n)
    c = resolve_chunks(tc, chunks)
    sc = tc // c

    def part(dest, s):
        return _seg_tokens(y, dest * tc + s * sc, sc) @ w

    accs = []
    for s in range(c):
        acc = part(idx, s)
        if h_fwd:
            f = part((idx + h_fwd) % n, s)
            for k in range(h_fwd - 1, 0, -1):
                f = lax.ppermute(f, axis_name, perm_f)
                f = f + part((idx + k) % n, s)
            f = lax.ppermute(f, axis_name, perm_f)
            acc = acc + f
        if h_bwd:
            b = part((idx - h_bwd) % n, s)
            for k in range(h_bwd - 1, 0, -1):
                b = lax.ppermute(b, axis_name, perm_b)
                b = b + part((idx - k) % n, s)
            b = lax.ppermute(b, axis_name, perm_b)
            acc = acc + b
        accs.append(acc)
    return accs[0] if c == 1 else jnp.concatenate(accs, axis=-2)


def _ring_grad_w(circ, full, axis_name: str, circ_is_lhs: bool):
    """The weight-gradient ring shared by both backwards:
    ``sum_j A_j^T @ B_j`` over source ranks j, where one operand's
    chunk circulates (``circ``, this rank's [..., Tc, *]) and the other
    is a local token slice of ``full`` [..., n*Tc, *]. ``circ_is_lhs``
    puts the circulating chunk on the transposed side."""
    n = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    tc = circ.shape[-2]

    def contract(a, b):
        # sum over every batch dim AND tokens: flatten to 2-D.
        a2 = a.reshape(-1, a.shape[-1])
        b2 = b.reshape(-1, b.shape[-1])
        return a2.T @ b2

    def one(chunk, src):
        seg = _seg_tokens(full, src * tc, tc)
        return contract(chunk, seg) if circ_is_lhs else contract(seg, chunk)

    dw = one(circ, idx)
    if n <= 1:
        return dw
    h_fwd, h_bwd = ring_hops(n)
    perm_f, perm_b = _perms(n)
    fwd = circ
    for k in range(1, h_fwd + 1):
        fwd = lax.ppermute(fwd, axis_name, perm_f)
        dw = dw + one(fwd, (idx - k) % n)
    bwd = circ
    for k in range(1, h_bwd + 1):
        bwd = lax.ppermute(bwd, axis_name, perm_b)
        dw = dw + one(bwd, (idx + k) % n)
    return dw


# ----------------------------------------------------- Pallas kernels
#
# TPU-only: double-buffered VMEM chunks moved with async remote copies
# so each hop's DMA flies while the MXU multiplies the resident chunk
# (see /opt/skills guides — the bidirectional ring-collective pattern).
# CI has no TPU; these compile-gate behind ``jax.default_backend()``
# and the interpret reference above is the provable lowering.


def _tpu_compiler_params(collective_id: int):
    from jax.experimental import pallas as pl  # noqa: F401
    from jax.experimental.pallas import tpu as pltpu

    kw = dict(has_side_effects=True, collective_id=int(collective_id))
    try:
        return pltpu.CompilerParams(**kw)
    except (AttributeError, TypeError):
        return pltpu.TPUCompilerParams(**kw)  # pre-0.5 jax


def _ag_matmul_tpu(x, w, axis_name: str, chunks: int):  # pragma: no cover
    """Pallas all-gather-matmul: each phase posts the next chunk's
    remote copy in BOTH ring directions, multiplies the chunk that
    arrived last phase, and writes its output rows."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = _axis_size(axis_name)
    tc, d = x.shape[-2], x.shape[-1]
    f = w.shape[-1]
    h_fwd, h_bwd = ring_hops(n)

    def kernel(x_ref, w_ref, out_ref, buf, send_sem, recv_sem):
        my = lax.axis_index(axis_name)
        right = lax.rem(my + 1, n)
        left = lax.rem(my + n - 1, n)
        barrier = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(barrier, device_id=(left,),
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_signal(barrier, device_id=(right,),
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_wait(barrier, 2)
        # slot 0 rides the forward ring, slot 1 the backward ring.
        buf[0] = x_ref[...]
        buf[1] = x_ref[...]
        out_ref[pl.ds(my * tc, tc), :] = jnp.dot(
            x_ref[...], w_ref[...], preferred_element_type=jnp.float32
        ).astype(out_ref.dtype)
        for k in range(1, max(h_fwd, h_bwd) + 1):
            copies = []
            if k <= h_fwd:
                copies.append(pltpu.make_async_remote_copy(
                    src_ref=buf.at[0], dst_ref=buf.at[0],
                    send_sem=send_sem.at[0], recv_sem=recv_sem.at[0],
                    device_id=(right,),
                    device_id_type=pltpu.DeviceIdType.LOGICAL,
                ))
            if k <= h_bwd:
                copies.append(pltpu.make_async_remote_copy(
                    src_ref=buf.at[1], dst_ref=buf.at[1],
                    send_sem=send_sem.at[1], recv_sem=recv_sem.at[1],
                    device_id=(left,),
                    device_id_type=pltpu.DeviceIdType.LOGICAL,
                ))
            for cp in copies:
                cp.start()
            for cp in copies:
                cp.wait()
            if k <= h_fwd:
                src = lax.rem(my + n - k, n)
                out_ref[pl.ds(src * tc, tc), :] = jnp.dot(
                    buf[0], w_ref[...],
                    preferred_element_type=jnp.float32,
                ).astype(out_ref.dtype)
            if k <= h_bwd:
                src = lax.rem(my + k, n)
                out_ref[pl.ds(src * tc, tc), :] = jnp.dot(
                    buf[1], w_ref[...],
                    preferred_element_type=jnp.float32,
                ).astype(out_ref.dtype)

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n * tc, f), x.dtype),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        scratch_shapes=[
            pltpu.VMEM((2, tc, d), x.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        compiler_params=_tpu_compiler_params(0xC0),
    )(x, w)


def _mrs_tpu(y, w, axis_name: str, chunks: int):  # pragma: no cover
    """Pallas matmul-reduce-scatter: per-destination partials computed
    as the accumulator rides the ring — one hop in flight per direction
    while the MXU produces the next partial."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = _axis_size(axis_name)
    t, fl = y.shape[-2], y.shape[-1]
    tc = t // n
    d = w.shape[-1]
    h_fwd, h_bwd = ring_hops(n)

    def kernel(y_ref, w_ref, out_ref, acc, send_sem, recv_sem):
        my = lax.axis_index(axis_name)
        right = lax.rem(my + 1, n)
        left = lax.rem(my + n - 1, n)
        barrier = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(barrier, device_id=(left,),
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_signal(barrier, device_id=(right,),
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_wait(barrier, 2)

        def part(dest):
            seg = pl.load(
                y_ref, (pl.ds(dest * tc, tc), slice(None))
            )
            return jnp.dot(seg, w_ref[...],
                           preferred_element_type=jnp.float32)

        out = part(my)
        if h_fwd:
            acc[0] = part(lax.rem(my + h_fwd, n)).astype(acc.dtype)
            for k in range(h_fwd - 1, -1, -1):
                cp = pltpu.make_async_remote_copy(
                    src_ref=acc.at[0], dst_ref=acc.at[0],
                    send_sem=send_sem.at[0], recv_sem=recv_sem.at[0],
                    device_id=(right,),
                    device_id_type=pltpu.DeviceIdType.LOGICAL,
                )
                cp.start()
                nxt = part(lax.rem(my + k, n)) if k else None
                cp.wait()
                if k:
                    acc[0] = (acc[0] + nxt.astype(acc.dtype))
            out = out + acc[0].astype(out.dtype)
        if h_bwd:
            acc[1] = part(lax.rem(my + n - h_bwd, n)).astype(acc.dtype)
            for k in range(h_bwd - 1, -1, -1):
                cp = pltpu.make_async_remote_copy(
                    src_ref=acc.at[1], dst_ref=acc.at[1],
                    send_sem=send_sem.at[1], recv_sem=recv_sem.at[1],
                    device_id=(left,),
                    device_id_type=pltpu.DeviceIdType.LOGICAL,
                )
                cp.start()
                nxt = part(lax.rem(my + n - k, n)) if k else None
                cp.wait()
                if k:
                    acc[1] = (acc[1] + nxt.astype(acc.dtype))
            out = out + acc[1].astype(out.dtype)
        out_ref[...] = out.astype(out_ref.dtype)

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((tc, d), y.dtype),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        scratch_shapes=[
            pltpu.VMEM((2, tc, d), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        compiler_params=_tpu_compiler_params(0xC1),
    )(y, w)


def _use_pallas(x) -> bool:
    # 2-D only (the composed path flattens batch dims before calling
    # the TPU kernel; the reference handles any rank).
    return (
        jax.default_backend() == "tpu"
        and x.ndim == 2
        and x.shape[-1] % 128 == 0
    )


# --------------------------------------------------- public primitives


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _agmm(axis_name, chunks, x, w):
    n = _axis_size(axis_name)
    _record(x.size * x.dtype.itemsize * n, axis_name, "all_gather_matmul")
    if _use_pallas(x):  # pragma: no cover - needs a TPU
        return _ag_matmul_tpu(x, w, axis_name, chunks)
    return _ag_matmul_ref(x, w, axis_name, chunks)


def _agmm_fwd(axis_name, chunks, x, w):
    return _agmm(axis_name, chunks, x, w), (x, w)


def _agmm_bwd(axis_name, chunks, res, ct):
    x, w = res
    n = _axis_size(axis_name)
    # dx = reduce_scatter(ct @ w^T): the DUAL primitive — the backward
    # overlaps its wire exactly like the forward.
    _record(ct.size * ct.dtype.itemsize, axis_name, "matmul_reduce_scatter")
    dx = _mrs_ref(ct, w.T, axis_name, chunks).astype(x.dtype)
    # dw = all_gather(x)^T @ ct, accumulated as the x chunks ride the
    # same bidirectional ring (a second pass of the forward's bytes).
    _record(x.size * x.dtype.itemsize * n, axis_name, "all_gather_matmul")
    dw = _ring_grad_w(x, ct, axis_name, circ_is_lhs=True).astype(w.dtype)
    return dx, dw


_agmm.defvjp(_agmm_fwd, _agmm_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _mrs(axis_name, chunks, y, w):
    _record(
        (y.size // max(y.shape[-1], 1)) * w.shape[-1] * y.dtype.itemsize,
        axis_name, "matmul_reduce_scatter",
    )
    if _use_pallas(y):  # pragma: no cover - needs a TPU
        return _mrs_tpu(y, w, axis_name, chunks)
    return _mrs_ref(y, w, axis_name, chunks)


def _mrs_fwd(axis_name, chunks, y, w):
    return _mrs(axis_name, chunks, y, w), (y, w)


def _mrs_bwd(axis_name, chunks, res, ct):
    y, w = res
    n = _axis_size(axis_name)
    # dy = all_gather(ct) @ w^T: again the dual primitive.
    _record(ct.size * ct.dtype.itemsize * n, axis_name, "all_gather_matmul")
    dy = _ag_matmul_ref(ct, w.T, axis_name, chunks).astype(y.dtype)
    # dw = y^T @ all_gather(ct): the ct chunks ride the ring while each
    # arriving chunk contracts with its local y token slice.
    _record(ct.size * ct.dtype.itemsize * n, axis_name, "all_gather_matmul")
    dw = _ring_grad_w(ct, y, axis_name, circ_is_lhs=False).astype(w.dtype)
    return dy, dw


_mrs.defvjp(_mrs_fwd, _mrs_bwd)


def all_gather_matmul(
    x_shard: jax.Array,
    w: jax.Array,
    *,
    axis_name: str,
    chunks: int = 0,
) -> jax.Array:
    """``all_gather(x_shard, tiled over tokens) @ w`` with the gather
    fused into the matmul: chunk k+1 rides the ring while chunk k is on
    the MXU. ``x_shard`` [..., T/n, D] (token dim −2), ``w`` [D, F].
    Returns [..., T, F]. ``chunks`` sub-splits each rank chunk for a
    finer pipeline (0 = ``HOROVOD_TP_OVERLAP_CHUNKS``/auto); bytes on
    wire are chunk-count-invariant. Call inside shard_map."""
    return _agmm(axis_name, int(chunks), x_shard, w)


def matmul_reduce_scatter(
    y: jax.Array,
    w: jax.Array,
    *,
    axis_name: str,
    chunks: int = 0,
) -> jax.Array:
    """``reduce_scatter(y @ w, tiled over tokens)`` with the reduction
    fused into the matmul: each destination chunk's partial product is
    computed as the accumulator for it arrives on the ring. ``y``
    [..., T, Fl], ``w`` [Fl, D]. Returns this rank's [..., T/n, D]
    chunk. ``psum(y @ w) == all_gather(matmul_reduce_scatter(y, w))``.
    Call inside shard_map."""
    return _mrs(axis_name, int(chunks), y, w)
