"""Int8-quantized ring allreduce (in-jit, over a named mesh axis).

TPU-native extension inspired by EQuARX (arXiv 2506.17615, listed in
PAPERS.md): the ring allreduce's two phases move quantized blocks instead
of full-precision values, cutting wire bytes ~4x (fp32) / ~2x (bf16) at a
bounded accuracy cost. Each hop of the reduce-scatter phase dequantizes
the incoming partial into float32, accumulates the local chunk, and
requantizes before forwarding (per-hop requantization — the accumulation
itself is never done in int8, so there is no overflow at any world size).
The all-gather phase forwards completed chunks the same way.

Quantization is symmetric BLOCKWISE int8 (one f32 scale per
``BLOCK=256`` elements): ``q = round(v / s)`` with ``s = max|block| /
127`` (zero-safe), so a small-magnitude gradient leaf packed into a
fusion bucket next to a large one keeps its own scales instead of
rounding to zero against a global amax. Each of the n-1 reduce-scatter hops
adds at most half a quantization step of the running partial's scale, so
the error grows ~sqrt(n) relative to the summed magnitude: measured ~1%
relative L2 at 8 ranks on iid gradient-like data (the unit tests assert
<3%). Use where gradient noise of that order is acceptable — the same
regime the quantized-collective literature targets.

This is the compiled-mode counterpart of the eager wire-compression
knob (``Compression.fp16``): use it where gradient traffic, not compute,
bounds step time — e.g. DCN-crossing data-parallel axes.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..common.compat import axis_size as _axis_size
from ..common.quant import BLOCK, int8_saved_bytes, int8_wire_bytes
from ..parallel.mesh import DATA_AXIS

__all__ = [
    "BLOCK",
    "EFState",
    "ef_like",
    "quantize_roundtrip",
    "quantized_hierarchical_allreduce",
    "quantized_reduce_fn",
    "quantized_ring_allreduce",
    "quantized_ring_reduce_scatter",
]


def _quantize(v: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Symmetric blockwise int8: (q int8 [m], scales f32 [m/BLOCK]).
    ``m`` must be a multiple of BLOCK (callers pad). The arithmetic runs
    in float32 regardless of the input dtype — a bf16 ``v / scale``
    would re-round the quantization grid itself (the bf16 round-trip bug
    the bucket integration surfaced)."""
    vb = v.astype(jnp.float32).reshape(-1, BLOCK)
    amax = jnp.max(jnp.abs(vb), axis=1, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(vb / scale), -127, 127).astype(jnp.int8)
    return q.reshape(-1), scale.reshape(-1)


def _dequantize(q: jax.Array, scales: jax.Array) -> jax.Array:
    vb = q.astype(jnp.float32).reshape(-1, BLOCK) * scales[:, None]
    return vb.reshape(-1)


def _pack(q: jax.Array, scales: jax.Array) -> jax.Array:
    """One wire payload per hop: int8 values ++ the scales' raw bytes
    (EQuARX packs scales with the data the same way — a second permute
    for the scale vector would double the launch count)."""
    sb = lax.bitcast_convert_type(scales, jnp.int8).reshape(-1)
    return jnp.concatenate([q, sb])


def _unpack(buf: jax.Array, k: int) -> Tuple[jax.Array, jax.Array]:
    q = buf[:k]
    nb = k // BLOCK
    scales = lax.bitcast_convert_type(
        buf[k:k + 4 * nb].reshape(nb, 4), jnp.float32
    ).reshape(-1)
    return q, scales


def _ring_rs_phase(chunks, k, n, r, axis_name, shift):
    """Shared int8-wire ring reduce-scatter pass: after n-1 hops rank r
    holds the complete float32 sum of chunk (r + 1 + shift) mod n. The
    allreduce uses shift=0 (then all-gathers); ZeRO-1's reduce-scatter
    uses shift=-1 so rank r finishes holding its own chunk r — one copy
    of the ring-index math serves both."""
    fwd = [(i, (i + 1) % n) for i in range(n)]

    def chunk_at(idx):
        return lax.dynamic_slice(chunks, (idx % n, 0), (1, k))[0]

    def rs_body(step, partial):
        wire = lax.ppermute(_pack(*_quantize(partial)), axis_name, fwd)
        q, s = _unpack(wire, k)
        return _dequantize(q, s) + chunk_at(r - step - 1 + shift)

    return lax.fori_loop(0, n - 1, rs_body, chunk_at(r + shift))


def quantized_ring_reduce_scatter(
    x: jax.Array,
    *,
    axis_name: str = DATA_AXIS,
    average: bool = False,
) -> jax.Array:
    """Reduce-scatter with int8 on the wire: rank r returns the complete
    sum (or average) of chunk r in ``psum_scatter``'s tiled layout.

    ``x`` is the flat input, length n*k with k a multiple of BLOCK
    (callers pad — ``parallel/zero.py`` aligns its shard length). This is
    the reduce-scatter phase of :func:`quantized_ring_allreduce` with the
    chunk labeling shifted by one so rank r finishes holding chunk r
    (the plain ring finishes at chunk (r+1) mod n), which is exactly the
    gradient shard ZeRO-1 needs — composing the int8 wire with sharded
    optimizer state costs no extra hop."""
    if isinstance(axis_name, (tuple, list)):
        raise ValueError(
            "quantized reduce-scatter is the flat int8 ring over ONE "
            "axis; hierarchical (DCN-only) compression is not defined "
            "for the RS+AG decomposition — reduce over a single bound "
            f"axis (got {axis_name!r})"
        )
    n = _axis_size(axis_name)
    orig_dtype = x.dtype
    flat = x.astype(jnp.float32).reshape(-1)
    total = flat.shape[0]
    # Validate BEFORE the n==1 shortcut so misuse fails on debug runs
    # too, not only at scale.
    if total % n != 0 or (total // n) % BLOCK != 0:
        raise ValueError(
            f"quantized reduce-scatter needs len(x) divisible by n*BLOCK "
            f"(= {n * BLOCK}); got {total}"
        )
    if n == 1 or total == 0:
        return flat.astype(orig_dtype)
    r = lax.axis_index(axis_name)
    k = total // n
    chunks = flat.reshape(n, k)
    partial = _ring_rs_phase(chunks, k, n, r, axis_name, shift=-1)
    if average:
        partial = partial / n
    return partial.astype(orig_dtype)


def quantized_ring_allreduce(
    x: jax.Array,
    *,
    axis_name: str = DATA_AXIS,
    average: bool = False,
) -> jax.Array:
    """Sum (or average) ``x`` across ``axis_name`` moving int8 on the wire.

    Must run inside shard_map/pmap with the axis bound. The result has
    ``x``'s shape and dtype; internal accumulation is float32.

    ``axis_name`` may be a tuple of bound axes: the reduction then chains
    one int8 ring per axis, innermost (fastest) first — the "flat
    quantized" lowering of a multi-level plan, every hop compressed.
    """
    if isinstance(axis_name, (tuple, list)):
        axes = tuple(axis_name)
        if len(axes) == 1:
            return quantized_ring_allreduce(
                x, axis_name=axes[0], average=average
            )
        out = x
        for ax in reversed(axes):  # innermost first
            out = quantized_ring_allreduce(out, axis_name=ax)
        if average:
            out = (out.astype(jnp.float32)
                   / _axis_size(axes)).astype(x.dtype)
        return out
    n = _axis_size(axis_name)
    if n == 1:
        return x
    r = lax.axis_index(axis_name)
    fwd = [(i, (i + 1) % n) for i in range(n)]  # ring: send to next rank

    orig_shape, orig_dtype = x.shape, x.dtype
    flat = x.astype(jnp.float32).reshape(-1)
    total = flat.shape[0]
    if total == 0:
        # Zero-length leaves (empty buckets) must be identities, not a
        # degenerate (n, 0) ring of empty permutes.
        return x
    k = -(-total // n)  # ceil
    k = -(-k // BLOCK) * BLOCK  # chunk length a multiple of the scale block
    flat = jnp.pad(flat, (0, n * k - total))
    chunks = flat.reshape(n, k)

    # --- reduce-scatter phase (shared ring pass): after n-1 hops, rank r
    # holds the complete sum of chunk (r + 1) mod n.
    partial = _ring_rs_phase(chunks, k, n, r, axis_name, shift=0)

    # --- all-gather phase: circulate completed chunks; rank r receives
    # chunk (r - step) mod n at step (owned chunk ids decrease by one per
    # hop around the ring). Each chunk is quantized ONCE by its owner and
    # the packed payload is forwarded verbatim, so hops add no error. The
    # owner writes the DEQUANTIZED value for its own chunk too — every
    # rank must produce the identical result (the allreduce contract;
    # keeping the exact partial only locally would let DP replicas drift).
    q0, s0 = _quantize(partial)
    out = jnp.zeros((n, k), jnp.float32)
    out = lax.dynamic_update_slice(
        out, _dequantize(q0, s0)[None], ((r + 1) % n, 0)
    )
    wire0 = _pack(q0, s0)

    def ag_body(step, carry):
        out, wire = carry
        wire = lax.ppermute(wire, axis_name, fwd)
        q, s = _unpack(wire, k)
        out = lax.dynamic_update_slice(
            out, _dequantize(q, s)[None], ((r - step) % n, 0)
        )
        return out, wire

    out, _ = lax.fori_loop(0, n - 1, ag_body, (out, wire0))

    result = out.reshape(-1)[:total].reshape(orig_shape)
    if average:
        result = result / n
    return result.astype(orig_dtype)


# --- wire round-trip (error feedback) ----------------------------------------


def quantize_roundtrip(x: jax.Array) -> jax.Array:
    """``dequant(quant(x))`` with the exact padding/block layout the ring
    applies to a local payload — the compression operator EF-SGD
    compensates. Returns float32 of ``x``'s shape (the residual
    ``x - quantize_roundtrip(x)`` must not re-round through bf16).

    The ring pads the flat payload with zeros to a BLOCK-aligned chunk
    grid before quantizing, so padding here with zeros to the next BLOCK
    boundary reproduces the per-block scales bit-for-bit: the all-zero
    tail blocks quantize to zero with unit scale and contribute no
    error."""
    flat = x.astype(jnp.float32).reshape(-1)
    total = flat.shape[0]
    if total == 0:
        return flat.reshape(x.shape)
    pad = (-total) % BLOCK
    if pad:
        flat = jnp.pad(flat, (0, pad))
    deq = _dequantize(*_quantize(flat))
    return deq[:total].reshape(x.shape)


class EFState(NamedTuple):
    """Optimizer-state wrapper carrying the error-feedback residual next
    to the inner optimizer state. ``residual`` is RANK-LOCAL by design —
    each rank compensates its own quantization error — so the guard's
    cross-rank digest agreement must (and does) exclude it
    (``guard/digest.strip_rank_local``); everything under ``inner``
    stays digest-tracked."""

    inner: Any
    residual: Any


def ef_like(params: Any) -> Any:
    """Zero-initialized error-feedback residual tree for ``params``:
    float32 per leaf regardless of the leaf dtype (a bf16 residual would
    re-round exactly the error it exists to carry)."""
    return jax.tree.map(
        lambda l: jnp.zeros(jnp.shape(l), jnp.float32), params
    )


# --- hierarchical (compressed-on-DCN-only) lowering --------------------------


def _q2l(flat: jax.Array, axes: Tuple[str, ...]) -> jax.Array:
    """k-level allreduce on a flat f32 vector with int8 ONLY on the
    outermost (slowest) hop: RS(inner, full-precision psum_scatter) ->
    recurse on the 1/L shard -> AG(inner). The base case — the single
    outermost axis — is the int8 ring. This is EQuARX's observation made
    structural: the win concentrates on the slow cross-slice hop, so the
    big ICI payload stays exact and only the 1/L shard moves
    compressed."""
    if len(axes) == 1:
        return quantized_ring_allreduce(flat, axis_name=axes[0])
    inner = axes[-1]
    L = _axis_size(inner)
    n = flat.shape[0]
    pad = (-n) % L
    if pad:
        flat = jnp.pad(flat, (0, pad))
    shard = lax.psum_scatter(flat, inner, scatter_dimension=0, tiled=True)
    shard = _q2l(shard, axes[:-1])
    full = lax.all_gather(shard, inner, tiled=True)
    if pad:
        full = full[:n]
    return full


def quantized_hierarchical_allreduce(
    x: jax.Array,
    axes,
    *,
    average: bool = False,
) -> jax.Array:
    """Sum (or average) ``x`` over the hierarchy ``axes`` (outermost
    first, compositor order) with int8 on the outermost hop only: inner
    hops run full-precision reduce-scatter/all-gather (ICI), the
    remaining 1/L shard crosses the slow hop through the int8 ring."""
    axes = tuple(axes) if isinstance(axes, (tuple, list)) else (axes,)
    if len(axes) == 1:
        return quantized_ring_allreduce(x, axis_name=axes[0],
                                        average=average)
    orig_shape, orig_dtype = x.shape, x.dtype
    flat = x.astype(jnp.float32).reshape(-1)
    if flat.shape[0] == 0:
        return x
    out = _q2l(flat, axes)
    if average:
        out = out / _axis_size(axes)
    return out.reshape(orig_shape).astype(orig_dtype)


# --- fusion-bucket reduce_fn -------------------------------------------------


def record_wire_bytes(nbytes: int, label: str) -> None:
    """Trace-time hvd_quantized_* counters (one emission per compile,
    like the fusion-bucket gauges): bytes this bucket puts on the int8
    wire and bytes saved vs full precision."""
    from .. import metrics as _metrics

    if not _metrics.ACTIVE:
        return
    _metrics.TAP.inc(
        "hvd_quantized_wire_bytes_total",
        float(int8_wire_bytes(nbytes)), path=label,
    )
    _metrics.TAP.inc(
        "hvd_quantized_bytes_saved_total",
        float(int8_saved_bytes(nbytes)), path=label,
    )
    _metrics.TAP.inc("hvd_quantized_buckets_total", 1.0, path=label)


def quantized_reduce_fn(mode: str = "flat", label: str = "quantized"):
    """A ``reduce_fn`` for ``ops/fusion.fused_allreduce``: float buckets
    ride the int8 wire, integer buckets reduce exactly (a float32/int8
    round trip would silently corrupt exact sums; buckets are same-dtype
    so per-bucket dispatch loses nothing).

    ``mode``: ``"flat"`` — the int8 ring over the (single or tupled)
    axis; ``"two-level"`` — compressed-on-DCN-only
    (:func:`quantized_hierarchical_allreduce`, axis_name must be the
    hierarchy tuple, outermost first).
    """
    from ..common.types import ReduceOp

    if mode not in ("flat", "two-level"):
        raise ValueError(f"unknown quantized reduce mode {mode!r}")

    def fn(x, *, op, axis_name, prescale_factor=1.0, postscale_factor=1.0):
        if not jnp.issubdtype(x.dtype, jnp.floating):
            from . import collectives as _c

            out = _c.allreduce(
                x, op=op, axis_name=axis_name,
                prescale_factor=prescale_factor,
                postscale_factor=postscale_factor,
            )
            # AVERAGE's true division promotes to float; preserve the
            # bucket dtype like the quantized path does.
            return out.astype(x.dtype)
        if op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
            raise ValueError(
                f"quantized reduction supports SUM/AVERAGE; got {op}"
            )
        if prescale_factor != 1.0:
            x = x * prescale_factor
        record_wire_bytes(x.size * 4, label)
        if mode == "two-level":
            out = quantized_hierarchical_allreduce(
                x, axis_name, average=(op == ReduceOp.AVERAGE)
            )
        else:
            out = quantized_ring_allreduce(
                x, axis_name=axis_name, average=(op == ReduceOp.AVERAGE)
            )
        if postscale_factor != 1.0:
            out = out * postscale_factor
        return out

    return fn
