"""Int8-quantized ring allreduce (in-jit, over a named mesh axis).

TPU-native extension inspired by EQuARX (arXiv 2506.17615, listed in
PAPERS.md): the ring allreduce's two phases move quantized blocks instead
of full-precision values, cutting wire bytes ~4x (fp32) / ~2x (bf16) at a
bounded accuracy cost. Each hop of the reduce-scatter phase dequantizes
the incoming partial into float32, accumulates the local chunk, and
requantizes before forwarding (per-hop requantization — the accumulation
itself is never done in int8, so there is no overflow at any world size).
The all-gather phase forwards completed chunks the same way.

Quantization is symmetric BLOCKWISE int8 (one f32 scale per
``BLOCK=256`` elements): ``q = round(v / s)`` with ``s = max|block| /
127`` (zero-safe), so a small-magnitude gradient leaf packed into a
fusion bucket next to a large one keeps its own scales instead of
rounding to zero against a global amax. Each of the n-1 reduce-scatter hops
adds at most half a quantization step of the running partial's scale, so
the error grows ~sqrt(n) relative to the summed magnitude: measured ~1%
relative L2 at 8 ranks on iid gradient-like data (the unit tests assert
<3%). Use where gradient noise of that order is acceptable — the same
regime the quantized-collective literature targets.

This is the compiled-mode counterpart of the eager wire-compression
knob (``Compression.fp16``): use it where gradient traffic, not compute,
bounds step time — e.g. DCN-crossing data-parallel axes.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..common.compat import axis_size as _axis_size
from ..parallel.mesh import DATA_AXIS

__all__ = ["quantized_ring_allreduce", "quantized_ring_reduce_scatter"]


# Elements sharing one scale. Small enough that a low-magnitude gradient
# leaf (layernorm/bias) packed into a fusion bucket next to a large-
# magnitude one keeps its own scales instead of rounding to zero against
# the bucket's global amax; 4 scale bytes per 256 payload bytes = 1.6%
# wire overhead.
BLOCK = 256


def _quantize(v: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Symmetric blockwise int8: (q int8 [m], scales f32 [m/BLOCK]).
    ``m`` must be a multiple of BLOCK (callers pad)."""
    vb = v.reshape(-1, BLOCK)
    amax = jnp.max(jnp.abs(vb), axis=1, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(vb / scale), -127, 127).astype(jnp.int8)
    return q.reshape(-1), scale.reshape(-1)


def _dequantize(q: jax.Array, scales: jax.Array) -> jax.Array:
    vb = q.astype(jnp.float32).reshape(-1, BLOCK) * scales[:, None]
    return vb.reshape(-1)


def _pack(q: jax.Array, scales: jax.Array) -> jax.Array:
    """One wire payload per hop: int8 values ++ the scales' raw bytes
    (EQuARX packs scales with the data the same way — a second permute
    for the scale vector would double the launch count)."""
    sb = lax.bitcast_convert_type(scales, jnp.int8).reshape(-1)
    return jnp.concatenate([q, sb])


def _unpack(buf: jax.Array, k: int) -> Tuple[jax.Array, jax.Array]:
    q = buf[:k]
    nb = k // BLOCK
    scales = lax.bitcast_convert_type(
        buf[k:k + 4 * nb].reshape(nb, 4), jnp.float32
    ).reshape(-1)
    return q, scales


def _ring_rs_phase(chunks, k, n, r, axis_name, shift):
    """Shared int8-wire ring reduce-scatter pass: after n-1 hops rank r
    holds the complete float32 sum of chunk (r + 1 + shift) mod n. The
    allreduce uses shift=0 (then all-gathers); ZeRO-1's reduce-scatter
    uses shift=-1 so rank r finishes holding its own chunk r — one copy
    of the ring-index math serves both."""
    fwd = [(i, (i + 1) % n) for i in range(n)]

    def chunk_at(idx):
        return lax.dynamic_slice(chunks, (idx % n, 0), (1, k))[0]

    def rs_body(step, partial):
        wire = lax.ppermute(_pack(*_quantize(partial)), axis_name, fwd)
        q, s = _unpack(wire, k)
        return _dequantize(q, s) + chunk_at(r - step - 1 + shift)

    return lax.fori_loop(0, n - 1, rs_body, chunk_at(r + shift))


def quantized_ring_reduce_scatter(
    x: jax.Array,
    *,
    axis_name: str = DATA_AXIS,
    average: bool = False,
) -> jax.Array:
    """Reduce-scatter with int8 on the wire: rank r returns the complete
    sum (or average) of chunk r in ``psum_scatter``'s tiled layout.

    ``x`` is the flat input, length n*k with k a multiple of BLOCK
    (callers pad — ``parallel/zero.py`` aligns its shard length). This is
    the reduce-scatter phase of :func:`quantized_ring_allreduce` with the
    chunk labeling shifted by one so rank r finishes holding chunk r
    (the plain ring finishes at chunk (r+1) mod n), which is exactly the
    gradient shard ZeRO-1 needs — composing the int8 wire with sharded
    optimizer state costs no extra hop."""
    n = _axis_size(axis_name)
    orig_dtype = x.dtype
    flat = x.astype(jnp.float32).reshape(-1)
    total = flat.shape[0]
    # Validate BEFORE the n==1 shortcut so misuse fails on debug runs
    # too, not only at scale.
    if total % n != 0 or (total // n) % BLOCK != 0:
        raise ValueError(
            f"quantized reduce-scatter needs len(x) divisible by n*BLOCK "
            f"(= {n * BLOCK}); got {total}"
        )
    if n == 1:
        return flat.astype(orig_dtype)
    r = lax.axis_index(axis_name)
    k = total // n
    chunks = flat.reshape(n, k)
    partial = _ring_rs_phase(chunks, k, n, r, axis_name, shift=-1)
    if average:
        partial = partial / n
    return partial.astype(orig_dtype)


def quantized_ring_allreduce(
    x: jax.Array,
    *,
    axis_name: str = DATA_AXIS,
    average: bool = False,
) -> jax.Array:
    """Sum (or average) ``x`` across ``axis_name`` moving int8 on the wire.

    Must run inside shard_map/pmap with the axis bound. The result has
    ``x``'s shape and dtype; internal accumulation is float32.
    """
    n = _axis_size(axis_name)
    if n == 1:
        return x
    r = lax.axis_index(axis_name)
    fwd = [(i, (i + 1) % n) for i in range(n)]  # ring: send to next rank

    orig_shape, orig_dtype = x.shape, x.dtype
    flat = x.astype(jnp.float32).reshape(-1)
    total = flat.shape[0]
    k = -(-total // n)  # ceil
    k = -(-k // BLOCK) * BLOCK  # chunk length a multiple of the scale block
    flat = jnp.pad(flat, (0, n * k - total))
    chunks = flat.reshape(n, k)

    # --- reduce-scatter phase (shared ring pass): after n-1 hops, rank r
    # holds the complete sum of chunk (r + 1) mod n.
    partial = _ring_rs_phase(chunks, k, n, r, axis_name, shift=0)

    # --- all-gather phase: circulate completed chunks; rank r receives
    # chunk (r - step) mod n at step (owned chunk ids decrease by one per
    # hop around the ring). Each chunk is quantized ONCE by its owner and
    # the packed payload is forwarded verbatim, so hops add no error. The
    # owner writes the DEQUANTIZED value for its own chunk too — every
    # rank must produce the identical result (the allreduce contract;
    # keeping the exact partial only locally would let DP replicas drift).
    q0, s0 = _quantize(partial)
    out = jnp.zeros((n, k), jnp.float32)
    out = lax.dynamic_update_slice(
        out, _dequantize(q0, s0)[None], ((r + 1) % n, 0)
    )
    wire0 = _pack(q0, s0)

    def ag_body(step, carry):
        out, wire = carry
        wire = lax.ppermute(wire, axis_name, fwd)
        q, s = _unpack(wire, k)
        out = lax.dynamic_update_slice(
            out, _dequantize(q, s)[None], ((r - step) % n, 0)
        )
        return out, wire

    out, _ = lax.fori_loop(0, n - 1, ag_body, (out, wire0))

    result = out.reshape(-1)[:total].reshape(orig_shape)
    if average:
        result = result / n
    return result.astype(orig_dtype)
