"""Tensor- and pipeline-parallel training: numerics vs single-device
references on the 8-way virtual mesh (TPU-native extensions beyond the
reference's DP-only scope; the graft contract's tp/pp shardings)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from horovod_tpu.jax import _shard_map
from horovod_tpu.parallel.mesh import build_mesh
from horovod_tpu.parallel.pp import (
    init_pp_state,
    make_pp_train_step,
    pipeline_apply,
)
from horovod_tpu.parallel.tp import (
    init_tp_state,
    make_tp_train_step,
    shard_mlp_params,
    tp_mlp,
)


def _full_mlp(params_stacked, x):
    """Dense reference: reassemble the full weights from the shards."""
    w1 = jnp.concatenate(list(params_stacked["w1"]), axis=1)
    b1 = jnp.concatenate(list(params_stacked["b1"]), axis=0)
    w2 = jnp.concatenate(list(params_stacked["w2"]), axis=0)
    b2 = jnp.concatenate(list(params_stacked["b2"]), axis=0)
    return jax.nn.gelu(x @ w1 + b1) @ w2 + b2


def test_tp_mlp_forward_matches_dense():
    n = 4
    mesh = build_mesh({"data": 2, "model": n})
    params = shard_mlp_params(jax.random.PRNGKey(0), d_model=8,
                              d_hidden=16, n_shards=n)
    x = jnp.asarray(np.random.RandomState(0).randn(6, 8).astype(np.float32))

    fn = _shard_map(
        lambda p, xb: tp_mlp(jax.tree.map(lambda t: t[0], p), xb,
                             axis_name="model"),
        mesh,
        in_specs=(P("model"), P("data")),
        out_specs=P("data"),
    )
    out = jax.jit(fn)(params, x)
    expected = _full_mlp(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=1e-5, atol=1e-5)


def test_tp_train_step_matches_dense_reference():
    """One DP x TP SGD step must equal the single-device step on the
    reassembled dense weights (grads of a shard are exactly the dense
    grads' slice; the data axis averages)."""
    n = 4
    mesh = build_mesh({"data": 2, "model": n})
    params = shard_mlp_params(jax.random.PRNGKey(1), d_model=8,
                              d_hidden=16, n_shards=n)
    tx = optax.sgd(0.1)
    opt_state = init_tp_state(tx, params)
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(8, 8).astype(np.float32))
    y = jnp.asarray(rng.randn(8, 8).astype(np.float32))

    def loss_fn(p_local, batch):
        xb, yb = batch
        pred = tp_mlp(p_local, xb, axis_name="model")
        return jnp.mean((pred - yb) ** 2)

    step = make_tp_train_step(loss_fn, tx, mesh, donate=False)
    new_params, _, loss = step(params, opt_state, (x, y))

    # Dense reference step.
    def ref_loss(p):
        pred = _full_mlp(p, x)
        return jnp.mean((pred - y) ** 2)

    ref_loss_v, ref_grads = jax.value_and_grad(ref_loss)(params)
    np.testing.assert_allclose(float(loss), float(ref_loss_v), rtol=1e-5)
    # Compare one updated shard against the dense update's slice.
    upd_w1 = np.asarray(new_params["w1"])  # [n, D, F/n]
    ref_w1 = np.asarray(
        jax.tree.map(lambda p, g: p - 0.1 * g, params, ref_grads)["w1"]
    )
    np.testing.assert_allclose(upd_w1, ref_w1, rtol=1e-4, atol=1e-5)


def _stage_fn(p, x, s):
    return jax.nn.relu(x @ p["w"] + p["b"])


def _stacked_stage_params(rng, n_stages, d):
    k = jax.random.split(rng, n_stages)
    return {
        "w": jnp.stack([
            jax.random.normal(k[i], (d, d)) * (d ** -0.5)
            for i in range(n_stages)
        ]),
        "b": jnp.zeros((n_stages, d)),
    }


def _ref_pipeline(params_stacked, x_micro):
    y = x_micro
    for i in range(params_stacked["w"].shape[0]):
        p = jax.tree.map(lambda t, i=i: t[i], params_stacked)
        y = jax.vmap(lambda mb: _stage_fn(p, mb, i))(y)
    return y


def test_pipeline_apply_matches_sequential():
    n_stages = 8
    mesh = build_mesh({"stage": n_stages})
    d = 8
    params = _stacked_stage_params(jax.random.PRNGKey(2), n_stages, d)
    x = jnp.asarray(
        np.random.RandomState(2).randn(4, 2, d).astype(np.float32)
    )  # [n_micro, mb, d]

    def run(p, xm):
        outs = pipeline_apply(_stage_fn, jax.tree.map(lambda t: t[0], p),
                              xm, axis_name="stage")
        # Only the last stage holds real outputs; bring them everywhere.
        import jax.numpy as jnp
        from jax import lax

        mask = (lax.axis_index("stage") == n_stages - 1).astype(outs.dtype)
        return lax.psum(outs * mask, "stage")

    fn = _shard_map(run, mesh, in_specs=(P("stage"), P()), out_specs=P())
    out = jax.jit(fn)(params, x)
    expected = _ref_pipeline(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=1e-5, atol=1e-5)


def test_pp_train_step_matches_sequential_reference():
    n_stages, dp = 4, 2
    mesh = build_mesh({"stage": n_stages, "data": dp})
    d = 8
    params = _stacked_stage_params(jax.random.PRNGKey(3), n_stages, d)
    tx = optax.sgd(0.05)
    opt_state = init_pp_state(tx, params)
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(4, 4, d).astype(np.float32))  # [n_micro, B, d]
    y = jnp.asarray(rng.randn(4, 4, d).astype(np.float32))

    def loss_fn(outs, labels):
        return jnp.mean((outs - labels) ** 2)

    step = make_pp_train_step(loss_fn, _stage_fn, tx, mesh, donate=False)
    new_params, _, loss = step(params, opt_state, x, y)

    def ref_loss(p):
        return loss_fn(_ref_pipeline(p, x), y)

    ref_v, ref_g = jax.value_and_grad(ref_loss)(params)
    np.testing.assert_allclose(float(loss), float(ref_v), rtol=1e-5)
    ref_new = jax.tree.map(lambda p, g: p - 0.05 * g, params, ref_g)
    np.testing.assert_allclose(
        np.asarray(new_params["w"]), np.asarray(ref_new["w"]),
        rtol=1e-4, atol=1e-5,
    )


def test_pp_grad_flows_through_all_stages():
    """Every stage's parameters must receive nonzero gradient through the
    backward pipeline (the ppermute transpose chain)."""
    n_stages = 4
    mesh = build_mesh({"stage": n_stages, "data": 2})
    d = 4
    params = _stacked_stage_params(jax.random.PRNGKey(4), n_stages, d)
    tx = optax.sgd(1.0)
    opt_state = init_pp_state(tx, params)
    x = jnp.ones((2, 4, d))
    y = jnp.zeros((2, 4, d))
    step = make_pp_train_step(
        lambda o, l: jnp.mean((o - l) ** 2), _stage_fn, tx, mesh,
        donate=False,
    )
    new_params, _, _ = step(params, opt_state, x, y)
    moved = np.asarray(
        jnp.abs(new_params["w"] - params["w"]).sum(axis=(1, 2))
    )
    assert (moved > 1e-8).all(), f"stages without gradient: {moved}"


def test_tp_attention_matches_dense():
    """Head-sharded attention (QKV column-parallel, flash per local heads,
    output row-parallel) must equal dense multi-head attention on the
    reassembled weights."""
    from horovod_tpu.parallel.ring_attention import reference_attention
    from horovod_tpu.parallel.tp import shard_attention_params, tp_attention

    n = 4
    H, D = 8, 32
    head_dim = D // H
    mesh = build_mesh({"data": 2, "model": n})
    params = shard_attention_params(jax.random.PRNGKey(5), D, H, n)
    x = jnp.asarray(np.random.RandomState(5).randn(4, 8, D)
                    .astype(np.float32) * 0.5)

    fn = _shard_map(
        lambda p, xb: tp_attention(
            jax.tree.map(lambda t: t[0], p), xb, head_dim=head_dim,
            axis_name="model", causal=True,
        ),
        mesh,
        in_specs=(P("model"), P("data")),
        out_specs=P("data"),
    )
    out = jax.jit(fn)(params, x)

    # Dense reference: reassemble wqkv (per-shard q|k|v column groups).
    wq = jnp.concatenate([w[:, : w.shape[1] // 3] for w in params["wqkv"]],
                         axis=1)
    wk = jnp.concatenate(
        [w[:, w.shape[1] // 3: 2 * w.shape[1] // 3] for w in params["wqkv"]],
        axis=1)
    wv = jnp.concatenate([w[:, 2 * w.shape[1] // 3:] for w in params["wqkv"]],
                         axis=1)
    wo = jnp.concatenate(list(params["wo"]), axis=0)
    bo = jnp.concatenate(list(params["bo"]), axis=0)
    B, T, _ = x.shape
    q = (x @ wq).reshape(B, T, H, head_dim)
    k = (x @ wk).reshape(B, T, H, head_dim)
    v = (x @ wv).reshape(B, T, H, head_dim)
    a = reference_attention(q, k, v, causal=True).reshape(B, T, D)
    expected = a @ wo + bo
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-4, atol=2e-5)


def _lm_pp_setup(n_stages=4, dp=2, d=8, vocab=16, mb=2, n_micro=4, seed=5):
    """Toy LM pipeline: embed table -> per-stage MLP -> vocab head + CE."""
    mesh = build_mesh({"stage": n_stages, "data": dp})
    kp = jax.random.split(jax.random.PRNGKey(seed), 4)
    embed_p = {"table": jax.random.normal(kp[0], (vocab, d)) * 0.5}
    stage_p = {"w": jax.random.normal(kp[1], (n_stages, d, d)) * 0.3}
    head_p = {"proj": jax.random.normal(kp[2], (d, vocab)) * 0.5}
    rng = np.random.RandomState(seed)
    tokens = jnp.asarray(
        rng.randint(0, vocab, (n_micro, mb * dp, 6)), jnp.int32
    )
    labels = jnp.asarray(
        rng.randint(0, vocab, (n_micro, mb * dp, 6)), jnp.int32
    )

    def embed_fn(p, tok):
        return p["table"][tok]

    def stage_fn(p, h, s):
        return jnp.tanh(h @ p["w"])

    def head_loss_fn(p, h, lab):
        logits = h @ p["proj"]
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, lab
        ).mean()

    params = {"embed": embed_p, "stages": stage_p, "head": head_p}
    return mesh, params, tokens, labels, embed_fn, stage_fn, head_loss_fn


def _lm_ref_loss(params, tokens, labels, n_stages):
    h = params["embed"]["table"][tokens]  # [n_micro, B, T, d]
    for s in range(n_stages):
        h = jnp.tanh(h @ params["stages"]["w"][s])
    logits = h @ params["head"]["proj"]
    return optax.softmax_cross_entropy_with_integer_labels(
        logits, labels
    ).mean()


@pytest.mark.parametrize("remat", [True, False])
def test_pp_lm_heterogeneous_matches_sequential(remat):
    """Heterogeneous pipeline (embed on stage 0, head+loss on the last
    stage, hidden-only wire) must match the unpipelined model: same loss
    AND the same post-SGD update for embed, every body stage, and head —
    closing the round-3 'homogeneous stages only' limitation."""
    from horovod_tpu.parallel.pp import init_pp_lm_state, make_pp_lm_train_step

    n_stages = 4
    (mesh, params, tokens, labels,
     embed_fn, stage_fn, head_loss_fn) = _lm_pp_setup(n_stages=n_stages)
    tx = optax.sgd(0.1)
    opt_state = init_pp_lm_state(tx, params)
    step = make_pp_lm_train_step(
        embed_fn, stage_fn, head_loss_fn, tx, mesh,
        remat=remat, donate=False,
    )
    new_params, _, loss = step(params, opt_state, tokens, labels)

    ref_v, ref_g = jax.value_and_grad(
        lambda p: _lm_ref_loss(p, tokens, labels, n_stages)
    )(params)
    np.testing.assert_allclose(float(loss), float(ref_v), rtol=1e-5)
    ref_new = jax.tree.map(lambda p, g: p - 0.1 * g, params, ref_g)
    for path, got, want in (
        ("embed", new_params["embed"]["table"], ref_new["embed"]["table"]),
        ("stages", new_params["stages"]["w"], ref_new["stages"]["w"]),
        ("head", new_params["head"]["proj"], ref_new["head"]["proj"]),
    ):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-5,
            err_msg=path,
        )


def test_pp_lm_trains_loss_down():
    from horovod_tpu.parallel.pp import init_pp_lm_state, make_pp_lm_train_step

    (mesh, params, tokens, labels,
     embed_fn, stage_fn, head_loss_fn) = _lm_pp_setup()
    tx = optax.adam(3e-2)
    opt_state = init_pp_lm_state(tx, params)
    step = make_pp_lm_train_step(
        embed_fn, stage_fn, head_loss_fn, tx, mesh, donate=False,
    )
    first = None
    for _ in range(12):
        params, opt_state, loss = step(params, opt_state, tokens, labels)
        first = first if first is not None else float(loss)
    assert float(loss) < first * 0.9, (first, float(loss))
