"""bench.py must be able to validate itself without a TPU.

Round-2 verdict weak #2: two rounds produced no perf artifact because the
harness could only run against the (flaky) real chip. These tests pin the
escape hatch: ``--platform cpu`` forces the backend at the jax-config level
(the env var alone loses to a sitecustomize hook) and the supervisor emits
machine-readable JSON on both success and failure.
"""

import json
import os
import subprocess
import sys

import pytest

BENCH = os.path.join(os.path.dirname(__file__), os.pardir, "bench.py")


def _run(args, timeout=540):
    env = dict(os.environ)
    # The bench must do its own platform forcing; don't inherit the test
    # harness's virtual-mesh XLA_FLAGS or any pinned JAX_PLATFORMS.
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    env["BENCH_BACKOFF_S"] = "0.5"
    return subprocess.run(
        [sys.executable, BENCH] + args,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        timeout=timeout, text=True, env=env,
    )


def _json_line(stdout: str) -> dict:
    lines = [l for l in stdout.splitlines() if l.strip().startswith("{")]
    assert lines, f"no JSON line in stdout: {stdout!r}"
    return json.loads(lines[-1])


@pytest.mark.slow
def test_smoke_cpu_end_to_end():
    proc = _run([
        "--smoke", "--platform", "cpu", "--cpu-devices", "2",
        "--model", "resnet18", "--num-classes", "10",
    ])
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = _json_line(proc.stdout)
    assert out["metric"] == "resnet18_synthetic_images_per_sec_per_chip"
    assert out["value"] and out["value"] > 0
    assert out["unit"] == "img/s/chip"
    assert out["detail"]["platform"] == "cpu"
    assert out["detail"]["n_chips"] == 2
    # FLOPs cost analysis populated => MFU is computable on TPU.
    assert out["detail"]["flops_per_step_per_chip"], out["detail"]


def test_failure_emits_structured_json():
    """A worker that fails deterministically must still produce one parseable
    JSON line (the round-2 capture died rc=124 with ``parsed: null``)."""
    proc = _run([
        # No --smoke: smoke mode overrides batch-size, and the negative
        # batch must reach the worker to crash it (ValueError from randn)
        # before any compile happens.
        "--platform", "cpu", "--cpu-devices", "1",
        "--model", "resnet18", "--batch-size", "-1", "--image-size", "8",
        "--deadline", "240", "--attempt-timeout", "60",
    ], timeout=300)
    assert proc.returncode != 0
    out = _json_line(proc.stdout)
    assert out["value"] is None
    assert "error" in out and out["error"]


def test_moe_smoke_cpu_end_to_end():
    """DP x EP MoE benchmark path: switch routing + all_to_all over a
    (data, expert) mesh, tokens/s metric, FLOPs reconciliation wired."""
    proc = _run([
        "--smoke", "--platform", "cpu", "--cpu-devices", "4",
        "--model", "moe",
    ])
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = _json_line(proc.stdout)
    assert out["metric"] == "moe_synthetic_tokens_per_sec_per_chip"
    assert out["value"] and out["value"] > 0
    assert out["detail"]["mesh"] == {"data": 1, "expert": 4}
    assert out["detail"]["flops_per_step_per_chip"], out["detail"]


def test_overlap_schedule_parser():
    """The HLO-schedule parser behind the committed overlap evidence
    (PROFILE_OVERLAP_PHASEB_*.json): async pairs are matched by operand
    name including TUPLE-typed (variadic) forms — a miss there would
    turn real latency hiding into a false 'no overlap' verdict — and
    compute between start/done is counted across tuple-shaped fusions."""
    import importlib.util
    import os

    repo = os.path.join(os.path.dirname(__file__), os.pardir)
    spec = importlib.util.spec_from_file_location(
        "tpo", os.path.join(repo, "tools", "tpu_profile_overlap.py")
    )
    tpo = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tpo)

    hlo = "\n".join([
        "ENTRY %main {",
        "  %p0 = f32[8]{0} parameter(0)",
        "  %ars = (f32[64]{0}, f32[32]{0}) all-reduce-start(%g1, %g2), "
        "replica_groups={{0,1}}",
        "  %f.1 = (f32[64]{0}, f32[8]{0}) fusion(%p0), kind=kLoop",
        "  %conv = f32[1,8,8,64]{3,2,1,0} convolution(%x, %k), window={}",
        "  %ard = (f32[64]{0}, f32[32]{0}) all-reduce-done(%ars)",
        "  %sync = f32[64]{0} all-reduce(%f.1), replica_groups={{0,1}}",
        "  %gte = f32[64]{0} get-tuple-element(%ard), index=0",
        "}",
    ])
    stats = tpo._schedule_overlap_stats(hlo)
    assert stats["async_all_reduce_pairs"] == 1, stats
    assert stats["compute_ops_overlapped_per_pair"] == [2], stats
    assert stats["pairs_with_overlap"] == 1, stats
    assert stats["sync_all_reduce_count"] == 1, stats


def test_tp_flag_validation():
    """--tp / --rules parser contract: transformer-only, degree >= 2,
    --rules needs --tp, and --tp defaults its table to gpt."""
    import importlib.util

    spec = importlib.util.spec_from_file_location("bench_mod", BENCH)
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    args = bench._parse_args(
        ["--model", "transformer", "--tp", "2", "--_worker"]
    )
    assert args.rules == "gpt"
    for bad in (
        ["--model", "resnet18", "--tp", "2"],
        ["--model", "transformer", "--tp", "1"],
        ["--model", "transformer", "--rules", "gpt"],
    ):
        with pytest.raises(SystemExit):
            bench._parse_args(bad + ["--_worker"])


def test_tuned_mesh_hash_rejection(tmp_path):
    """--quantized --tuned with a tuning pinned on a DIFFERENT mesh-axes
    hash is a hard error naming BOTH hashes; a params-half mismatch
    alone still falls back with the loud warning."""
    import argparse
    import importlib.util

    import jax.numpy as jnp

    from horovod_tpu import tune as T

    spec = importlib.util.spec_from_file_location("bench_mod", BENCH)
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    params = {"w": jnp.ones((8, 8))}
    pinned_sig = T.step_signature(params, mesh={"data": 8})
    cfg = T.TunedConfig(
        knobs={"fusion_threshold_bytes": 1 << 20,
               "first_bucket_bytes": 1 << 18,
               "wire_dtype": "int8", "topo_algorithm": None},
        signature=pinned_sig, objectives={}, baseline={},
        program="unit",
    )
    path = str(tmp_path / "tuned.json")
    T.save_tuned(cfg, path)

    live_mesh = {"data": 4, "model": 2}
    args = argparse.Namespace(tuned=path, quantized=True)
    with pytest.raises(SystemExit) as e:
        bench._resolve_tuned(args, params, live_mesh)
    msg = str(e.value)
    assert T.mesh_axes_hash(pinned_sig) in msg
    assert T.mesh_axes_hash(T.step_signature(params, mesh=live_mesh)) \
        in msg
    # Without --quantized the same mismatch falls back (no exception),
    # reporting matched=False.
    args = argparse.Namespace(tuned=path, quantized=False)
    kw, detail = bench._resolve_tuned(args, params, live_mesh)
    assert kw is None and detail["matched"] is False
