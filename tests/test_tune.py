"""Compiled-path offline tuner (horovod_tpu/tune, docs/autotune.md
"Compiled-path offline tuning").

Covers the GP/EI port's determinism and its golden-trace agreement with
the native engine (``cpp/src/autotune.cc`` via a test-compiled
``hvd_autotune_gp_probe``), the signature-keyed application seam
(``make_train_step(tuned=...)`` / ``DistributedOptimizer(tuned=...)`` /
staleness fallback), the plan-verifier gate, and the ``hvd_tuned_info``
provenance surface.
"""

import ctypes
import os
import shutil
import subprocess
import tempfile

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from horovod_tpu import metrics as hvd_metrics
from horovod_tpu import tune as T
from horovod_tpu.common.types import ReduceOp
from horovod_tpu.ops.fusion import layer_group_bytes, plan_layer_groups
from horovod_tpu.topo.model import synthetic_model
from horovod_tpu.tune import gp as gp_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _toy_spec(n_layers=6, layer_bytes=1 << 20, name="toy"):
    return T.ProgramSpec(
        name=name,
        layers=tuple((f"l{i}", layer_bytes) for i in range(n_layers)),
        signature={"hash": "deadbeef", "treedef": "t", "leaves": [],
                   "mesh": {}},
    )


# --- GP port ---------------------------------------------------------------


def test_gp_fit_interpolates_observations():
    xs = [(0.1, 0.2, 0.0, 1.0, 0.0), (0.8, 0.5, 1.0, 0.0, 1.0),
          (0.4, 0.9, 0.0, 0.0, 0.0)]
    ys = [10.0, 30.0, 20.0]
    gp = gp_mod.fit(xs, ys)
    assert gp is not None
    # Posterior mean at an observed point tracks its (normalized,
    # centered) observation within the noise floor.
    ymax = max(ys)
    mean = sum(y / ymax for y in ys) / len(ys)
    for x, y in zip(xs, ys):
        mu, var = gp_mod.posterior(gp, x)
        assert abs(mu - (y / ymax - mean)) < 0.1
        assert var > 0


def test_gp_deterministic_sample_sequence():
    """Byte-identical tuned.json (including the full sample history)
    for a fixed seed across two runs."""
    model = synthetic_model(local=4, cross=2, generation="v5e")
    spec = _toy_spec()
    a = T.tune(spec, model, samples=10, seed=3)
    b = T.tune(spec, model, samples=10, seed=3)
    assert a.to_json() == b.to_json()
    c = T.tune(spec, model, samples=10, seed=4)
    # A different seed explores a different design (histories differ
    # even if the winner coincides).
    assert [h["x"] for h in c.history] != [h["x"] for h in a.history]


def _build_probe():
    """Compile autotune.cc + a two-symbol shim into a standalone .so so
    the golden test exercises the REAL C++ file, not a copy."""
    gxx = shutil.which("g++")
    if gxx is None:
        pytest.skip("g++ unavailable")
    td = tempfile.mkdtemp(prefix="gp_probe_")
    shim = os.path.join(td, "shim.cc")
    with open(shim, "w") as f:
        f.write(
            '#include "hvd/core.h"\n'
            "namespace hvd {\n"
            "void Log(LogLevel, const std::string&) {}\n"
            "double NowSec() { return 0.0; }\n"
            "}\n"
        )
    out = os.path.join(td, "libgpprobe.so")
    cmd = [gxx, "-O2", "-std=c++17", "-fPIC", "-shared",
           "-I" + os.path.join(REPO, "cpp", "include"),
           os.path.join(REPO, "cpp", "src", "autotune.cc"), shim,
           "-o", out]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        pytest.skip(f"probe build failed: {proc.stderr[-500:]}")
    return ctypes.CDLL(out)


def test_gp_golden_trace_matches_cpp():
    """The Python port and cpp/src/autotune.cc agree on a 5-D trace:
    posterior means/variances to 1e-9, EI argmax exactly."""
    lib = _build_probe()
    fn = lib.hvd_autotune_gp_probe
    fn.restype = ctypes.c_int
    dbl_p = ctypes.POINTER(ctypes.c_double)
    fn.argtypes = [dbl_p, dbl_p, ctypes.c_int, dbl_p, ctypes.c_int,
                   dbl_p, dbl_p, dbl_p, ctypes.POINTER(ctypes.c_int)]

    xs = [
        (0.25, 0.125, 0.0, 0.0, 0.0),
        (0.75, 0.50, 1.0, 0.0, 1.0),
        (0.125, 0.875, 0.0, 1.0, 0.0),
        (0.50, 0.25, 1.0, 1.0, 1.0),
        (0.875, 0.625, 0.0, 0.0, 1.0),
        (0.375, 0.375, 1.0, 0.0, 0.0),
    ]
    ys = [120.0, 310.0, 95.0, 270.0, 330.0, 180.0]
    cands = [
        (i / 8.0, j / 8.0, float(b0), float(b1), float(w))
        for i in range(0, 9, 2) for j in range(0, 9, 2)
        for b0 in (0, 1) for b1 in (0, 1) for w in (0, 1)
    ]

    n, m = len(xs), len(cands)
    xs_c = (ctypes.c_double * (n * 5))(*[v for x in xs for v in x])
    ys_c = (ctypes.c_double * n)(*ys)
    cd_c = (ctypes.c_double * (m * 5))(*[v for c in cands for v in c])
    mu_c = (ctypes.c_double * m)()
    var_c = (ctypes.c_double * m)()
    ei_c = (ctypes.c_double * m)()
    am_c = ctypes.c_int(-1)
    rc = fn(xs_c, ys_c, n, cd_c, m, mu_c, var_c, ei_c,
            ctypes.byref(am_c))
    assert rc == 0

    gp = gp_mod.fit(xs, ys)
    assert gp is not None
    for i, c in enumerate(cands):
        mu, var = gp_mod.posterior(gp, c)
        assert abs(mu - mu_c[i]) < 1e-9, (i, mu, mu_c[i])
        assert abs(var - var_c[i]) < 1e-9, (i, var, var_c[i])
        assert abs(gp_mod.expected_improvement(gp, c) - ei_c[i]) < 1e-9
    assert gp_mod.ei_argmax(gp, cands) == am_c.value


# --- space / objective -----------------------------------------------------


def test_space_encode_decode_roundtrip():
    space = T.SearchSpace()
    for config in (
        space.default_config(),
        {"fusion_threshold_bytes": 1 << 20,
         "first_bucket_bytes": 1 << 16,
         "topo_algorithm": "split", "wire_dtype": "int8"},
    ):
        assert space.decode(space.encode(config)) == config


def test_space_freezes_topo_on_flat_model():
    space = T.space_for_model(synthetic_model(local=8))
    assert space.topo_choices == ("auto",)
    x = space.encode({"fusion_threshold_bytes": 1 << 20,
                      "first_bucket_bytes": 1 << 16,
                      "topo_algorithm": "two-level",
                      "wire_dtype": "f32"})
    assert space.decode(x)["topo_algorithm"] == "auto"


def test_layer_group_bytes_matches_partition():
    layer_bytes = [3 << 20, 1 << 20, 2 << 20, 512 << 10]
    groups = plan_layer_groups(layer_bytes, 4 << 20, 1 << 20)
    per = layer_group_bytes(layer_bytes, 4 << 20, 1 << 20)
    assert len(per) == len(groups)
    assert sum(per) == sum(layer_bytes)
    for g, b in zip(groups, per):
        assert sum(layer_bytes[i] for i in g) == b


def test_free_objectives_int8_cheaper_on_wire():
    model = synthetic_model(local=4, cross=2, generation="v5e")
    spec = _toy_spec()
    space = T.SearchSpace()
    base = T.free_objectives(spec, space.default_config(), model)
    q = T.free_objectives(
        spec, dict(space.default_config(), wire_dtype="int8"), model
    )
    assert q["wire_bytes"] < base["wire_bytes"]
    assert q["cost_us"] < base["cost_us"]


# --- verifier gate ---------------------------------------------------------


def test_tuner_refuses_corrupted_plan():
    """A corrupted ring schedule (seeded through rounds_fn, the same
    injection seam tests/test_plan_verify.py uses) must abort the pin:
    no TunedConfig comes back."""
    from horovod_tpu.topo.compositor import perm_rounds

    model = synthetic_model(local=8)  # flat: ring/halving stages
    spec = _toy_spec()

    def corrupted(primitive, size):
        rounds = perm_rounds(primitive, size)
        if rounds:
            # Break round 0's bijectivity: everyone sends to rank 0.
            rounds = [[(s, 0) for s, _ in rounds[0]]] + rounds[1:]
        return rounds

    with pytest.raises(T.TuneVerificationError) as exc:
        T.tune(spec, model, samples=4, seed=0, rounds_fn=corrupted)
    assert exc.value.findings


def test_tuner_verifies_clean_grid():
    model = synthetic_model(local=4, cross=2, generation="v5e")
    cfg = T.tune(_toy_spec(), model, samples=6, seed=0)
    assert cfg.search["verified_plans"] >= 1


# --- signature keying ------------------------------------------------------


def test_signature_stable_and_mesh_sensitive():
    params = {"a": jax.ShapeDtypeStruct((4, 4), jnp.float32)}
    s1 = T.step_signature(params, mesh={"data": 8})
    s2 = T.step_signature(params, mesh={"data": 8})
    s3 = T.step_signature(params, mesh={"cross": 2, "local": 4})
    assert s1["hash"] == s2["hash"]
    assert s1["hash"] != s3["hash"]
    assert T.signatures_match(s1, s2)
    assert not T.signatures_match(s1, s3)
    # Params-only comparison ignores the mesh half.
    assert T.signatures_match(s1, s3, require_mesh=False)


# --- application seam ------------------------------------------------------


D = 64


def _mlp_setup(devices):
    import optax

    from horovod_tpu.parallel.mesh import build_mesh

    mesh = build_mesh()
    n = len(devices)
    rng = np.random.RandomState(0)
    params = {
        f"layer{i}": {
            "w": jnp.asarray(rng.randn(D, D).astype(np.float32) * 0.1),
            "b": jnp.asarray(rng.randn(D).astype(np.float32) * 0.1),
        }
        for i in range(3)
    }
    batch = (
        jnp.asarray(rng.randn(2 * n, D).astype(np.float32)),
        jnp.asarray(rng.randn(2 * n, D).astype(np.float32)),
    )

    def loss_fn(p, b):
        x, y = b
        h = x
        for i in range(3):
            h = jnp.tanh(h @ p[f"layer{i}"]["w"] + p[f"layer{i}"]["b"])
        return jnp.mean((h - y) ** 2)

    tx = optax.sgd(0.01)
    return mesh, params, batch, loss_fn, tx


def _hand_cfg(params, mesh, knobs=None):
    sig = T.step_signature(params, mesh=mesh)
    return T.TunedConfig(
        knobs=knobs or {
            "fusion_threshold_bytes": 1 << 20,
            "first_bucket_bytes": 1 << 14,
            "topo_algorithm": "auto",
            "wire_dtype": "f32",
        },
        signature=sig, objectives={}, baseline={}, program="test-mlp",
    )


def test_make_train_step_tuned_matches_hand_set(devices):
    import horovod_tpu.jax as hvdj

    mesh, params, batch, loss_fn, tx = _mlp_setup(devices)
    opt_state = tx.init(params)
    cfg = _hand_cfg(params, mesh)

    tuned_step = hvdj.make_train_step(
        loss_fn, tx, mesh, donate=False, overlap=True, tuned=cfg)
    hand_step = hvdj.make_train_step(
        loss_fn, tx, mesh, donate=False, overlap=True, tuned=False,
        **T.tuned_step_kwargs(cfg))
    untuned_step = hvdj.make_train_step(
        loss_fn, tx, mesh, donate=False, overlap=True, tuned=False)

    p_t, _, _ = tuned_step(params, opt_state, batch)
    info = T.applied_tuned_info()
    assert info and info["matched"] and info["source"] == "arg"
    p_h, _, _ = hand_step(params, opt_state, batch)
    p_u, _, _ = untuned_step(params, opt_state, batch)
    for a, b in zip(jax.tree.leaves(p_t), jax.tree.leaves(p_h)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # f32 regrouping is bitwise-neutral: tuned == untuned too.
    for a, b in zip(jax.tree.leaves(p_t), jax.tree.leaves(p_u)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_make_train_step_stale_signature_falls_back(devices, caplog):
    import logging

    import horovod_tpu.jax as hvdj

    mesh, params, batch, loss_fn, tx = _mlp_setup(devices)
    opt_state = tx.init(params)
    # Signature from DIFFERENT params (extra layer) — stale by
    # construction.
    other = dict(params)
    other["layer3"] = params["layer0"]
    cfg = _hand_cfg(other, mesh)

    stale_step = hvdj.make_train_step(
        loss_fn, tx, mesh, donate=False, overlap=True, tuned=cfg)
    untuned_step = hvdj.make_train_step(
        loss_fn, tx, mesh, donate=False, overlap=True, tuned=False)
    with caplog.at_level(logging.WARNING, logger="horovod_tpu"):
        p_s, _, _ = stale_step(params, opt_state, batch)
    assert any("FALLING BACK" in r.message for r in caplog.records)
    info = T.applied_tuned_info()
    assert info and not info["matched"]
    p_u, _, _ = untuned_step(params, opt_state, batch)
    for a, b in zip(jax.tree.leaves(p_s), jax.tree.leaves(p_u)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_make_train_step_env_knob(devices, tmp_path, monkeypatch):
    import horovod_tpu.jax as hvdj
    from horovod_tpu.common import env as henv

    mesh, params, batch, loss_fn, tx = _mlp_setup(devices)
    opt_state = tx.init(params)
    cfg = _hand_cfg(params, mesh)
    path = tmp_path / "tuned.json"
    T.save_tuned(cfg, str(path))
    monkeypatch.setenv(henv.HOROVOD_TUNED_FILE, str(path))
    step = hvdj.make_train_step(
        loss_fn, tx, mesh, donate=False, overlap=True)
    step(params, opt_state, batch)
    info = T.applied_tuned_info()
    assert info and info["matched"] and info["source"] == "env"
    assert henv.Config.from_env().tuned_file == str(path)


def test_distributed_optimizer_tuned(devices, caplog):
    import logging

    import optax

    import horovod_tpu.jax as hvdj
    from horovod_tpu.jax import _shard_map
    from jax.sharding import PartitionSpec as P

    mesh, params, batch, loss_fn, tx_inner = _mlp_setup(devices)
    cfg = _hand_cfg(params, mesh=None)  # optimizer checks params half only

    def run(tx):
        def step(p, s, b):
            loss, grads = jax.value_and_grad(loss_fn)(p, b)
            updates, s = tx.update(grads, s, p)
            return optax.apply_updates(p, updates), s

        fn = jax.jit(_shard_map(
            step, mesh, in_specs=(P(), P(), P("data")), out_specs=P(),
        ))
        s0 = tx.init(params)
        p1, _ = fn(params, s0, batch)
        return jax.tree.leaves(p1)

    import optax as _optax

    tuned = run(hvdj.DistributedOptimizer(_optax.sgd(0.01), tuned=cfg))
    info = T.applied_tuned_info()
    assert info and info["matched"]
    assert info["where"] == "DistributedOptimizer"
    untuned = run(hvdj.DistributedOptimizer(_optax.sgd(0.01)))
    for a, b in zip(tuned, untuned):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # Stale signature: warns and keeps defaults.
    other = {"only": params["layer0"]}
    stale_cfg = _hand_cfg(other, mesh=None)
    with caplog.at_level(logging.WARNING, logger="horovod_tpu"):
        stale = run(hvdj.DistributedOptimizer(_optax.sgd(0.01),
                                              tuned=stale_cfg))
    assert any("FALLING BACK" in r.message for r in caplog.records)
    for a, b in zip(stale, untuned):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# --- provenance surfaces ---------------------------------------------------


def test_tuned_info_gauge_and_source():
    hvd_metrics.install(True)
    try:
        T.note_applied("file", "cafe0123", True, "test")
        flat = hvd_metrics.flat()
        key = [k for k in flat if k.startswith("hvd_tuned_info")]
        assert key, flat
        assert 'source="file"' in key[0]
        assert T.applied_tuned_info()["matched"] is True
        assert T.current_tuned_source()["source"] == "file"
    finally:
        hvd_metrics.install(False)


def test_current_tuned_source_env(tmp_path, monkeypatch):
    from horovod_tpu.common import env as henv
    import horovod_tpu.tune as tune_mod

    monkeypatch.setattr(tune_mod, "_applied_info", None)
    monkeypatch.delenv(henv.HOROVOD_TUNED_FILE, raising=False)
    assert T.current_tuned_source()["source"] == "none"
    cfg = T.TunedConfig(
        knobs={"fusion_threshold_bytes": 1, "first_bucket_bytes": 1,
               "topo_algorithm": "auto", "wire_dtype": "f32"},
        signature={"hash": "beef"}, objectives={}, baseline={},
    )
    path = tmp_path / "t.json"
    T.save_tuned(cfg, str(path))
    monkeypatch.setenv(henv.HOROVOD_TUNED_FILE, str(path))
    src = T.current_tuned_source()
    assert src["source"] == "env"
    assert src["signature"] == "beef"


def test_executor_stamps_tuned_info_into_verdict(devices, tmp_path,
                                                 monkeypatch):
    """The eager executor's plan verdicts carry the compiled-path tuned
    source (file/env/none + signature hash) next to the native core's
    tuned_flags int."""
    import horovod_tpu.tune as tune_mod
    from horovod_tpu.common import env as henv
    from horovod_tpu.common.topology import Topology
    from horovod_tpu.common.types import TensorTableEntry
    from horovod_tpu.core.xla_executor import XlaPlanExecutor

    cfg = T.TunedConfig(
        knobs={"fusion_threshold_bytes": 1, "first_bucket_bytes": 1,
               "topo_algorithm": "auto", "wire_dtype": "f32"},
        signature={"hash": "feed0123"}, objectives={}, baseline={},
    )
    path = tmp_path / "t.json"
    T.save_tuned(cfg, str(path))
    monkeypatch.setattr(tune_mod, "_applied_info", None)
    monkeypatch.setenv(henv.HOROVOD_TUNED_FILE, str(path))

    topo = Topology(rank=0, size=1, local_rank=0, local_size=1,
                    cross_rank=0, cross_size=1)
    ex = XlaPlanExecutor(topo)
    assert ex.tuned_info()["source"] == "env"
    assert ex.tuned_info()["signature"] == "feed0123"
    plan = {"type": 0, "op": int(ReduceOp.SUM), "participants": 1}
    entries = [TensorTableEntry(
        name="t", tensor=np.ones((4,), np.float32))]
    out = ex.execute(plan, entries, topo)
    np.testing.assert_array_equal(np.asarray(out["t"]), np.ones(4))
    assert plan["tuned_info"]["source"] == "env"
    assert plan["tuned_info"]["signature"] == "feed0123"


def test_tuned_step_kwargs_mapping():
    def mk(topo, wire="f32"):
        return T.TunedConfig(
            knobs={"fusion_threshold_bytes": 123, "first_bucket_bytes": 7,
                   "topo_algorithm": topo, "wire_dtype": wire},
            signature={}, objectives={}, baseline={},
        )

    kw = T.tuned_step_kwargs(mk("flat"))
    assert kw["hierarchical"] is False and kw["topo_algorithm"] is None
    kw = T.tuned_step_kwargs(mk("two-level"))
    assert kw["hierarchical"] == "auto"
    assert kw["topo_algorithm"] == "two-level"
    kw = T.tuned_step_kwargs(mk("auto", wire="int8"))
    assert kw["quantized"] is True and kw["topo_algorithm"] is None
    assert kw["fusion_threshold_bytes"] == 123
    assert kw["first_bucket_bytes"] == 7


def test_free_objectives_fixed_comm_constant_shift():
    """The composed TP term shifts every config's cost identically —
    the argmax is knob-invariant but the recorded costs carry it."""
    model = synthetic_model(local=4, cross=2, generation="v5e")
    spec = _toy_spec()
    space = T.SearchSpace()
    cfg = space.default_config()
    base = T.free_objectives(spec, cfg, model)
    shifted = T.free_objectives(spec, cfg, model, fixed_comm_us=250.0)
    assert shifted["fixed_comm_us"] == 250.0
    assert shifted["cost_us"] == pytest.approx(
        base["cost_us"] + 250.0, abs=0.01
    )
    assert shifted["exposed_us"] == pytest.approx(
        base["exposed_us"] + 250.0, abs=0.01
    )
    assert "fixed_comm_us" not in base


def test_tune_records_fixed_comm_and_keeps_winner():
    model = synthetic_model(local=4, cross=2, generation="v5e")
    spec = _toy_spec()
    plain = T.tune(spec, model, samples=4, verify=False)
    composed = T.tune(spec, model, samples=4, verify=False,
                      fixed_comm_us=123.4)
    assert composed.search["fixed_comm_us"] == 123.4
    # A constant term cannot flip the knob choice.
    assert composed.knobs == plain.knobs


# ---------------------------------------------------------------------------
# TP term: overlap-aware pricing (docs/parallelism.md "Fused TP overlap")
# ---------------------------------------------------------------------------

def _tp_term(compute_us=25.0):
    return T.TPTerm(degree=4, psum_bytes=1 << 16, psums_per_step=8,
                    compute_us=compute_us)


def test_space_roundtrip_with_tp_and_bf16():
    space = T.SearchSpace(tp=True)
    for config in (
        space.default_config(),
        {"fusion_threshold_bytes": 1 << 20,
         "first_bucket_bytes": 1 << 16,
         "topo_algorithm": "split", "wire_dtype": "bf16",
         "tp_chunks": 4},
    ):
        assert space.decode(space.encode(config)) == config
    # Without tp the chunk dim never appears in decoded configs.
    assert "tp_chunks" not in T.SearchSpace().default_config()


def test_tp_term_priced_from_chunked_plan():
    model = synthetic_model(16)
    term = _tp_term()
    classic = T.tp_term_us(model, term, 0)
    fused = T.tp_term_us(model, term, 2)
    assert classic["mode"] == "exposed-psum"
    assert fused["mode"] == "collective_matmul"
    assert fused["chunks"] == 2
    # Any adjacent-matmul time > 0 makes the overlapped rings a strict
    # win over the exposed psum constant.
    assert fused["fixed_comm_us"] < classic["fixed_comm_us"]


def test_tune_tp_rejects_legacy_constant_alongside():
    model = synthetic_model(16)
    with pytest.raises(ValueError, match="not both"):
        T.tune(_toy_spec(), model, samples=4, verify=False,
               tp=_tp_term(), fixed_comm_us=99.0)


def test_tune_tp_records_winner_computed_fixed_comm():
    """search.fixed_comm_us is no longer a caller-supplied constant:
    the tuner recomputes it from the winner's own chunk count, searches
    tp_chunks jointly, verifies the winner's collective-matmul plans,
    and stays run-to-run deterministic."""
    model = synthetic_model(16)
    spec = _toy_spec()
    term = _tp_term()
    cfg = T.tune(spec, model, samples=12, seed=0, tp=term)
    chunks = int(cfg.knobs["tp_chunks"])
    assert chunks >= 1, cfg.knobs
    want = T.tp_term_us(model, term, chunks)["fixed_comm_us"]
    assert cfg.search["fixed_comm_us"] == want
    assert cfg.search["fixed_comm_us"] < (
        T.tp_term_us(model, term, 0)["fixed_comm_us"]
    )
    assert cfg.search["tp"]["chunks"] == chunks
    assert cfg.search["tp"]["degree"] == 4
    # The winner's fused plans passed symbolic verification (2 flavors
    # on top of the wire-plan grid).
    assert cfg.search["verified_plans"] >= 2
    again = T.tune(spec, model, samples=12, seed=0, tp=term)
    assert again.knobs == cfg.knobs
    assert again.search["fixed_comm_us"] == cfg.search["fixed_comm_us"]


def test_tuned_step_kwargs_maps_tp_chunks_to_overlap():
    cfg = T.TunedConfig(
        knobs={"fusion_threshold_bytes": 123, "first_bucket_bytes": 7,
               "topo_algorithm": "flat", "wire_dtype": "f32",
               "tp_chunks": 2},
        signature={}, objectives={}, baseline={},
    )
    assert T.tuned_step_kwargs(cfg)["tp_overlap"] is True
    cfg.knobs["tp_chunks"] = 0
    assert T.tuned_step_kwargs(cfg)["tp_overlap"] is False
