"""Direct tests of the native control-plane core (cpp/libhvd_core.so)
through the C ABI: plan emission, fusion grouping, ticket lifecycle,
duplicate rejection, autotune movement.
"""

import os
import time

import pytest

import horovod_tpu as hvd
from horovod_tpu.common.basics import NativeCore, _CoreError
from horovod_tpu.common.env import Config
from horovod_tpu.common.topology import Topology


SINGLE = Topology(rank=0, size=1, local_rank=0, local_size=1,
                  cross_rank=0, cross_size=1)


@pytest.fixture()
def core(monkeypatch):
    hvd.shutdown()  # the C++ core is a per-process singleton
    # Deterministic fusion for the grouping assertions: a generous
    # quiescence window (20 ms, bounded by a 50 ms cycle) so a loaded CI
    # host's enqueue gaps can't split one Python burst across cycles
    # (the production default seals a solo request after 100 us — that
    # latency optimization is exactly what would flake here).
    # monkeypatch restores/removes the var even if init raises.
    monkeypatch.setenv("HOROVOD_TPU_LINGER_US", "20000")
    c = NativeCore()
    cfg = Config()
    cfg.cycle_time_ms = 50.0
    c.init(cfg, SINGLE)
    yield c
    c.shutdown()


def _drain_plans(core, max_plans=10, timeout_ms=500):
    plans = []
    deadline = time.monotonic() + timeout_ms / 1000.0
    while time.monotonic() < deadline and len(plans) < max_plans:
        p = core.next_plan(timeout_ms=50)
        if isinstance(p, dict):
            plans.append(p)
            core.plan_done(p["id"], 0, "", 0.001, int(p.get("total_bytes", 0)))
        elif p == -1:
            break
    return plans


def test_fusion_groups_same_dtype(core):
    # 3 small f32 allreduces + 1 i32: expect 2 plans (f32 fused, i32 alone).
    for i in range(3):
        core.enqueue(0, f"t{i}", 7, [4, 4], -1, 2, 1.0, 1.0)
    core.enqueue(0, "t_int", 4, [8], -1, 2, 1.0, 1.0)
    plans = _drain_plans(core, max_plans=4)
    by_names = {tuple(sorted(p["names"])): p for p in plans}
    assert ("t0", "t1", "t2") in by_names, plans
    assert ("t_int",) in by_names, plans
    fused = by_names[("t0", "t1", "t2")]
    assert fused["total_bytes"] == 3 * 16 * 4
    assert fused["shapes"] == [[4, 4], [4, 4], [4, 4]]


def test_fusion_respects_threshold():
    hvd.shutdown()
    c = NativeCore()
    cfg = Config()
    cfg.cycle_time_ms = 1.0
    cfg.fusion_threshold_bytes = 100  # tiny: 2 x 16-float tensors don't fit
    c.init(cfg, SINGLE)
    try:
        c.enqueue(0, "a", 7, [16], -1, 2, 1.0, 1.0)
        c.enqueue(0, "b", 7, [16], -1, 2, 1.0, 1.0)
        plans = _drain_plans(c, max_plans=2)
        assert len(plans) == 2
        assert all(len(p["names"]) == 1 for p in plans)
    finally:
        c.shutdown()


def test_ticket_lifecycle(core):
    t = core.enqueue(0, "x", 7, [2], -1, 2, 1.0, 1.0)
    assert t > 0
    state, _ = core.ticket_status(t)
    # complete the plan
    plans = _drain_plans(core, max_plans=1)
    assert plans
    deadline = time.monotonic() + 2
    while time.monotonic() < deadline:
        state, err = core.ticket_status(t)
        if state != 0:
            break
        time.sleep(0.005)
    assert state == 1, (state, err)


def test_ticket_error_propagates(core):
    t = core.enqueue(0, "bad", 7, [2], -1, 2, 1.0, 1.0)
    p = None
    deadline = time.monotonic() + 2
    while time.monotonic() < deadline and not isinstance(p, dict):
        p = core.next_plan(timeout_ms=50)
    assert isinstance(p, dict)
    core.plan_done(p["id"], 1, "boom", 0.0, 0)
    deadline = time.monotonic() + 2
    state = 0
    while time.monotonic() < deadline:
        state, err = core.ticket_status(t)
        if state != 0:
            break
        time.sleep(0.005)
    assert state < 0
    assert "boom" in err


def test_duplicate_name_rejected_at_core(core):
    core.enqueue(0, "dup", 7, [2], -1, 2, 1.0, 1.0)
    with pytest.raises(_CoreError):
        core.enqueue(0, "dup", 7, [2], -1, 2, 1.0, 1.0)
    _drain_plans(core, max_plans=1)


def test_broadcast_not_fused(core):
    core.enqueue(2, "b0", 7, [4], 0, 2, 1.0, 1.0)
    core.enqueue(2, "b1", 7, [4], 0, 2, 1.0, 1.0)
    plans = _drain_plans(core, max_plans=2)
    assert len(plans) == 2
    assert all(p["type"] == 2 and p["root"] == 0 for p in plans)


def test_autotune_moves_params():
    hvd.shutdown()
    c = NativeCore()
    cfg = Config()
    cfg.cycle_time_ms = 1.0
    cfg.autotune = True
    cfg.autotune_warmup_samples = 0
    cfg.autotune_steps_per_sample = 1
    c.init(cfg, SINGLE)
    try:
        initial = (c.cycle_time_ms(), c.fusion_threshold())
        changed = False
        for i in range(40):
            c.enqueue(0, f"at{i}", 7, [1024], -1, 2, 1.0, 1.0)
            deadline = time.monotonic() + 2
            p = None
            while time.monotonic() < deadline and not isinstance(p, dict):
                p = c.next_plan(timeout_ms=50)
            assert isinstance(p, dict)
            c.plan_done(p["id"], 0, "", 0.001, 4096)
            if (c.cycle_time_ms(), c.fusion_threshold()) != initial:
                changed = True
                break
        assert changed, "autotuner never proposed new parameters"
    finally:
        c.shutdown()


def test_join_plan_roundtrip(core):
    t = core.enqueue_join()
    p = None
    deadline = time.monotonic() + 2
    while time.monotonic() < deadline and not isinstance(p, dict):
        p = core.next_plan(timeout_ms=50)
    assert isinstance(p, dict) and p["type"] == 3
    core.plan_done(p["id"], 0, "", 0.0, 0)
    deadline = time.monotonic() + 2
    state = 0
    while time.monotonic() < deadline:
        state, _ = core.ticket_status(t)
        if state != 0:
            break
        time.sleep(0.005)
    assert state == 1


def test_response_cache_roundtrip(core):
    """Second submission of the same signature rides the cache-bit path and
    still completes with a correct plan."""
    core.enqueue(0, "cached", 7, [8], -1, 2, 1.0, 1.0)
    plans = _drain_plans(core, max_plans=1)
    assert plans and core.cache_size() >= 1
    # same name+shape+op again: travels as a cache bit this time
    t = core.enqueue(0, "cached", 7, [8], -1, 2, 1.0, 1.0)
    plans = _drain_plans(core, max_plans=1)
    assert plans and plans[0]["names"] == ["cached"]
    assert plans[0]["shapes"] == [[8]]
    deadline = time.monotonic() + 2
    state = 0
    while time.monotonic() < deadline:
        state, _ = core.ticket_status(t)
        if state != 0:
            break
        time.sleep(0.005)
    assert state == 1


def test_autotune_categorical_flags_in_plans_and_convergence():
    """The tuner explores the categorical dims (cache always; hierarchical
    needs a grid) and the verdict stamps every plan with tuned_flags
    (reference jointly tunes hierarchical_allreduce/hierarchical_allgather/
    cache_enabled, parameter_manager.h:42-246). After the sample budget the
    tuner freezes and the pinned flags keep flowing."""
    hvd.shutdown()
    c = NativeCore()
    cfg = Config()
    cfg.cycle_time_ms = 1.0
    cfg.autotune = True
    cfg.autotune_warmup_samples = 0
    cfg.autotune_steps_per_sample = 1
    c.init(cfg, SINGLE)
    try:
        seen_flags = set()
        # 24 GP samples x 5 scores/median = 120 plans to convergence.
        for i in range(140):
            c.enqueue(0, f"cat{i}", 7, [256], -1, 2, 1.0, 1.0)
            deadline = time.monotonic() + 2
            p = None
            while time.monotonic() < deadline and not isinstance(p, dict):
                p = c.next_plan(timeout_ms=50)
            assert isinstance(p, dict)
            assert p["tuned_flags"] >= 0, p  # autotune on => flags stamped
            seen_flags.add(p["tuned_flags"])
            c.plan_done(p["id"], 0, "", 0.001, 1024)
        # cache dim explored: both cache-on and cache-off must have been
        # proposed at least once across the sweep.
        assert len(seen_flags) > 1, seen_flags
        final = c.tuned_flags()
        # Converged: flags stable from here on.
        for i in range(5):
            c.enqueue(0, f"post{i}", 7, [256], -1, 2, 1.0, 1.0)
            deadline = time.monotonic() + 2
            p = None
            while time.monotonic() < deadline and not isinstance(p, dict):
                p = c.next_plan(timeout_ms=50)
            assert isinstance(p, dict)
            assert p["tuned_flags"] == final, (p, final)
            c.plan_done(p["id"], 0, "", 0.001, 1024)
    finally:
        c.shutdown()


def test_eager_wakeup_beats_cycle_cadence():
    """Event-driven wakeup (TPU-build improvement over the reference's
    fixed RunLoopOnce cadence): with a deliberately huge cycle time, an
    enqueued tensor must still produce a plan almost immediately when
    wakeup is on, and only at the cycle boundary when forced off."""
    hvd.shutdown()

    def time_to_plan(env):
        for k, v in env.items():
            os.environ[k] = v
        try:
            c = NativeCore()
            cfg = Config()
            cfg.cycle_time_ms = 1000.0
            c.init(cfg, SINGLE)
            try:
                t0 = time.monotonic()
                c.enqueue(0, "wake", 7, [4], -1, 2, 1.0, 1.0)
                deadline = time.monotonic() + 3
                p = None
                while time.monotonic() < deadline and not isinstance(p, dict):
                    p = c.next_plan(timeout_ms=50)
                assert isinstance(p, dict)
                dt = time.monotonic() - t0
                c.plan_done(p["id"], 0, "", 0.001, 16)
                return dt
            finally:
                c.shutdown()
        finally:
            for k in env:
                os.environ.pop(k, None)

    fast = time_to_plan({})  # wakeup defaults on
    slow = time_to_plan({"HOROVOD_TPU_EAGER_WAKEUP": "0"})
    # Absolute bounds relaxed for the shared-core CI host (a full-suite
    # run can preempt this process for hundreds of ms); the relative
    # separation is the real claim.
    assert fast < 0.8, f"eager wakeup did not fire: {fast:.3f}s"
    # The cadence path fires at the ~1.0s cycle boundary, so the relative
    # bound must stay below that: demand clear separation, not a multiple
    # of a possibly-preempted `fast`.
    assert slow > 0.8 and slow > fast + 0.2, (
        f"cadence path returned too early: {slow:.3f}s (fast {fast:.3f}s)"
    )


def test_start_timeout_bounds_rendezvous():
    """A worker that never launches must abort rank 0 at
    HOROVOD_START_TIMEOUT (reference --start-timeout), not hang accept()
    forever."""
    hvd.shutdown()
    os.environ["HOROVOD_START_TIMEOUT"] = "3"
    try:
        topo = Topology(rank=0, size=2, local_rank=0, local_size=2,
                        cross_rank=0, cross_size=1)
        c = NativeCore()
        t0 = time.monotonic()
        with pytest.raises(RuntimeError, match="timed out"):
            c.init(Config(), topo, coord_addr="127.0.0.1",
                   coord_port=29437)
        assert time.monotonic() - t0 < 30
    finally:
        os.environ.pop("HOROVOD_START_TIMEOUT", None)


def test_grouped_requests_hold_until_complete(core):
    # First-class group: members enqueued across different cycles still
    # emit as ONE plan once the last member lands (the coordinator holds
    # the group; cycle boundaries are irrelevant).
    gid = 77
    core.enqueue(0, "g.0", 7, [4], -1, 2, 1.0, 1.0, gid, 3)
    # Let several 1 ms cycles pass: the lone member must NOT emit.
    assert _drain_plans(core, max_plans=1, timeout_ms=120) == []
    core.enqueue(0, "g.1", 7, [4], -1, 2, 1.0, 1.0, gid, 3)
    assert _drain_plans(core, max_plans=1, timeout_ms=120) == []
    core.enqueue(0, "g.2", 7, [4], -1, 2, 1.0, 1.0, gid, 3)
    plans = _drain_plans(core, max_plans=2, timeout_ms=500)
    assert len(plans) == 1, plans
    assert sorted(plans[0]["names"]) == ["g.0", "g.1", "g.2"], plans


def test_grouped_fusion_exempt_from_threshold(core):
    # A group larger than the fusion threshold still fuses into one plan
    # (the group explicitly requested one collective).
    import horovod_tpu.common.basics as basics

    gid = 88
    # 3 x 1 MB f32 with a tiny threshold would normally split; grouped
    # must not. (Threshold is a Config field read at init; default is
    # 64 MB, so make the members bigger than a forced-small threshold by
    # re-initing the core with fusion_threshold=16 bytes.)
    core.shutdown()
    c = basics.NativeCore()
    cfg = Config()
    cfg.cycle_time_ms = 1.0
    cfg.fusion_threshold = 16
    c.init(cfg, SINGLE)
    try:
        for i in range(3):
            c.enqueue(0, f"big.{i}", 7, [64], -1, 2, 1.0, 1.0, gid, 3)
        plans = _drain_plans(c, max_plans=3, timeout_ms=500)
        assert len(plans) == 1, plans
        assert len(plans[0]["names"]) == 3, plans
    finally:
        c.shutdown()


def test_grouped_heterogeneous_dtypes_split_counted(core):
    # Mixed-dtype group: one plan per signature, and the split is counted.
    gid = 99
    before = core.grouped_splits()
    core.enqueue(0, "mix.0", 7, [4], -1, 2, 1.0, 1.0, gid, 2)  # f32
    core.enqueue(0, "mix.1", 4, [4], -1, 2, 1.0, 1.0, gid, 2)  # i32
    plans = _drain_plans(core, max_plans=3, timeout_ms=500)
    assert len(plans) == 2, plans
    assert core.grouped_splits() == before + 1


def test_runtime_timeline_start_stop(tmp_path):
    """hvd.start_timeline / stop_timeline (later-reference API): the
    catapult trace can be scoped to a window at runtime."""
    import json

    hvd.shutdown()
    hvd.init()
    try:
        path = str(tmp_path / "tl.json")
        hvd.start_timeline(path, mark_cycles=True)
        with pytest.raises(ValueError):
            hvd.start_timeline(path)        # already active
        import numpy as np

        hvd.allreduce(np.ones((4,), np.float32), name="tl.t")
        hvd.stop_timeline()
        events = json.load(open(path))
        names = {e.get("name") for e in events}
        assert any("XLA_" in str(n) or "ENQUEUE" in str(n) for n in names), names
        assert "CYCLE" in names, names
        # restartable after stop
        path2 = str(tmp_path / "tl2.json")
        hvd.start_timeline(path2, mark_cycles=False)
        hvd.allreduce(np.ones((2,), np.float32), name="tl.t2")
        hvd.stop_timeline()
        events2 = json.load(open(path2))
        assert all(e.get("name") != "CYCLE" for e in events2), events2
    finally:
        hvd.shutdown()
