"""Sharding-rules engine units (parallel/rules.py): first-match-wins
precedence, placement round-trips, Pass 5 preflight, host-side shard
slicing, and parity of the pure-python reference shape table with the
REAL flax transformer tree (docs/parallelism.md)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from horovod_tpu.analysis import CollectiveSafetyError
from horovod_tpu.analysis.sharding_rules import (
    EXAMPLE_GPT_RULES,
    example_gpt_params,
)
from horovod_tpu.parallel import rules as R
from horovod_tpu.parallel.mesh import build_mesh


def _params():
    return {
        "block_0": {
            "attention": {"query": {"kernel": jnp.ones((8, 8))}},
            "mlp": {"up": {"kernel": jnp.ones((8, 32)),
                           "bias": jnp.zeros((32,))}},
        },
        "ln_f": {"scale": jnp.ones((8,)), "bias": jnp.zeros((8,))},
        "step": jnp.zeros(()),
    }


def test_named_tree_paths_flax_shape():
    names = [n for n, _ in R.named_tree_paths(_params())]
    assert "block_0/attention/query/kernel" in names
    assert "block_0/mlp/up/bias" in names
    assert "ln_f/scale" in names
    assert "step" in names


def test_first_match_wins_precedence():
    rules = (
        (r"query/kernel$", (None, "model")),
        (r"kernel$", None),
        (r".*", None),
    )
    specs = R.match_partition_rules(rules, _params())
    assert specs["block_0"]["attention"]["query"]["kernel"] == P(
        None, "model"
    )
    # The later generic rule would replicate — the earlier specific one
    # must win; swap the order and the same leaf replicates.
    swapped = (rules[1], rules[0], rules[2])
    specs2 = R.match_partition_rules(swapped, _params())
    assert specs2["block_0"]["attention"]["query"]["kernel"] == P()


def test_scalars_always_replicate():
    specs = R.match_partition_rules(
        ((r".*", ("model",)),), {"s": jnp.zeros(()), "w": jnp.ones((4,))}
    )
    assert specs["s"] == P()
    assert specs["w"] == P("model")


def test_unmatched_nonscalar_raises():
    with pytest.raises(ValueError, match="no sharding rule matches"):
        R.match_partition_rules(
            ((r"kernel$", None),), {"w": jnp.ones((4, 4))}
        )


def test_preflight_raises_on_unmatched_nonscalar():
    with pytest.raises(CollectiveSafetyError, match="matches no rule"):
        R.preflight_rules(
            ((r"kernel$", None),), {"data": 4, "model": 2},
            {"w": jnp.ones((4, 4))},
        )


def test_preflight_raises_on_unknown_axis_and_indivisible():
    with pytest.raises(CollectiveSafetyError):
        R.preflight_rules(
            ((r".*", (None, "tensor")),), {"data": 4, "model": 2},
            _params(),
        )
    with pytest.raises(CollectiveSafetyError):
        R.preflight_rules(
            ((r".*", ("model", None)),), {"data": 4, "model": 3},
            {"w": jnp.ones((8, 8))},
        )


def test_preflight_accepts_shipped_pair():
    R.preflight_rules(R.GPT_RULES, {"data": 4, "model": 2},
                      jax.tree.map(
                          lambda s: jnp.zeros(s),
                          example_gpt_params(),
                          is_leaf=lambda x: isinstance(x, tuple),
                      ))


def test_resolve_rules_named_and_unknown():
    assert R.resolve_rules("gpt") is R.GPT_RULES
    assert R.resolve_rules(EXAMPLE_GPT_RULES) is EXAMPLE_GPT_RULES
    with pytest.raises(ValueError, match="unknown named rule table"):
        R.resolve_rules("nope")


def test_spec_mentions():
    assert R.spec_mentions(P(None, "model"), ("model",))
    assert not R.spec_mentions(P("data"), ("model",))
    assert not R.spec_mentions(P(), ("model",))
    assert R.spec_mentions((("data", "model"), None), ("model",))


def test_shard_gather_round_trip_bitwise(devices):
    mesh = build_mesh({"data": 4, "model": 2})
    rng = np.random.RandomState(0)
    tree = {
        "w": jnp.asarray(rng.randn(8, 6).astype(np.float32)),
        "b": jnp.asarray(rng.randn(6).astype(np.float32)),
        "s": jnp.float32(3.5),
    }
    rules = ((r"^w$", (None, "model")), (r".*", None))
    specs = R.match_partition_rules(rules, tree)
    sharded = R.shard_tree(tree, specs, mesh)
    back = R.gather_tree(sharded, specs, mesh)
    for a, b in zip(jax.tree.leaves(tree), jax.tree.leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_rules_place_optimizer_state_via_embedded_names():
    import optax

    params = _params()
    tx = optax.adam(1e-3)
    opt_state = tx.init(params)
    rules = (
        (r"query/kernel$", (None, "model")),
        (r"mlp/up/kernel$", (None, "model")),
        (r"mlp/up/bias$", ("model",)),
        (r".*", None),
    )
    specs = R.match_partition_rules(rules, opt_state)
    flat = dict(zip(
        [n for n, _ in R.named_tree_paths(opt_state)],
        R.spec_leaves(specs),
    ))
    mu_q = [v for k, v in flat.items()
            if "mu" in k and "query/kernel" in k]
    assert mu_q and all(s == P(None, "model") for s in mu_q)
    counts = [v for k, v in flat.items() if k.endswith("count")]
    assert counts and all(s == P() for s in counts)


def test_local_shard_tree_slices():
    tree = {"w": jnp.arange(32, dtype=jnp.float32).reshape(4, 8),
            "b": jnp.arange(8, dtype=jnp.float32),
            "n": jnp.ones((3,))}
    rules = ((r"^w$", (None, "model")), (r"^b$", ("model",)),
             (r".*", None))
    specs = R.match_partition_rules(rules, tree)
    local = R.local_shard_tree(tree, specs, {"model": (1, 2)})
    np.testing.assert_array_equal(
        np.asarray(local["w"]), np.asarray(tree["w"][:, 4:])
    )
    np.testing.assert_array_equal(
        np.asarray(local["b"]), np.asarray(tree["b"][4:])
    )
    np.testing.assert_array_equal(
        np.asarray(local["n"]), np.asarray(tree["n"])
    )


def test_example_gpt_params_matches_real_flax_tree():
    """The pure-python linter table must mirror TransformerLM.init leaf
    for leaf (names AND shapes) — the guarantee that lets
    `tools/collective_lint.py sharding` lint the SHIPPED pair with no
    jax import."""
    from horovod_tpu.models.transformer import TransformerLM

    model = TransformerLM(vocab_size=384, d_model=128, n_heads=4,
                          n_layers=2, max_len=128)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    assert R.tree_shape_table(params) == example_gpt_params()


def test_shipped_rules_have_no_overmatch_on_real_tree():
    """Every rule that SHARDS must only hit the leaves it names: the
    embeddings rules are (^|/)-anchored so 'pos_embeddings' is not
    captured by the 'embeddings' rule, and the catch-all replicates the
    rest."""
    import re

    params = example_gpt_params()
    for name in params:
        hits = [i for i, (pat, _) in enumerate(EXAMPLE_GPT_RULES)
                if re.search(pat, name)]
        assert hits, name
    # pos_embeddings must match ITS anchored rule (index 1), not the
    # tok-embeddings rule (index 0).
    first = next(
        i for i, (pat, _) in enumerate(EXAMPLE_GPT_RULES)
        if re.search(pat, "pos_embeddings/embedding")
    )
    assert first == 1
