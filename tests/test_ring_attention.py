"""Ring attention / Ulysses numerics vs the dense reference, over the
8-way virtual mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from horovod_tpu.jax import _shard_map
from horovod_tpu.parallel.mesh import build_mesh
from horovod_tpu.parallel.ring_attention import (
    reference_attention,
    ring_attention,
    ulysses_attention,
)


def _mesh():
    return build_mesh({"seq": len(jax.devices())})


def _qkv(B=2, T=32, H=8, D=16, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(B, T, H, D).astype(np.float32) * 0.5)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_reference(causal):
    mesh = _mesh()
    q, k, v = _qkv()
    fn = _shard_map(
        lambda a, b, c: ring_attention(a, b, c, axis_name="seq",
                                       causal=causal),
        mesh,
        in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
        out_specs=P(None, "seq"),
    )
    out = jax.jit(fn)(q, k, v)
    expected = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(expected), rtol=2e-4, atol=2e-5
    )


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_reference(causal):
    mesh = _mesh()
    q, k, v = _qkv()
    fn = _shard_map(
        lambda a, b, c: ulysses_attention(a, b, c, axis_name="seq",
                                          causal=causal),
        mesh,
        in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
        out_specs=P(None, "seq"),
    )
    out = jax.jit(fn)(q, k, v)
    expected = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(expected), rtol=2e-4, atol=2e-5
    )


def test_ring_attention_bf16():
    mesh = _mesh()
    q, k, v = _qkv()
    q, k, v = (x.astype(jnp.bfloat16) for x in (q, k, v))
    fn = _shard_map(
        lambda a, b, c: ring_attention(a, b, c, axis_name="seq", causal=True),
        mesh,
        in_specs=(P(None, "seq"),) * 3,
        out_specs=P(None, "seq"),
    )
    out = jax.jit(fn)(q, k, v)
    expected = reference_attention(q, k, v, causal=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(expected, np.float32),
        rtol=5e-2, atol=5e-2,
    )


def test_ring_attention_grad_flows():
    """Differentiate THROUGH the shard_map'd ring (the training-step shape):
    gradients must flow backward around the ring (ppermute transpose) and
    match the dense reference."""
    mesh = _mesh()
    q, k, v = _qkv(B=1, T=16, H=2, D=8)

    ring = _shard_map(
        lambda a, b, c: ring_attention(a, b, c, axis_name="seq", causal=True),
        mesh,
        in_specs=(P(None, "seq"),) * 3,
        out_specs=P(None, "seq"),
    )

    def loss(q, k, v):
        return jnp.sum(ring(q, k, v) ** 2)

    gq, gk, gv = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)

    def ref_loss(q, k, v):
        out = reference_attention(q, k, v, causal=True)
        return jnp.sum(out**2)

    rq, rk, rv = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(np.asarray(gq), np.asarray(rq), rtol=1e-3,
                               atol=1e-4)
    np.testing.assert_allclose(np.asarray(gk), np.asarray(rk), rtol=1e-3,
                               atol=1e-4)
    np.testing.assert_allclose(np.asarray(gv), np.asarray(rv), rtol=1e-3,
                               atol=1e-4)


def test_ulysses_rejects_bad_heads():
    mesh = _mesh()
    q, k, v = _qkv(H=4)  # 4 heads, 8-way axis
    fn = _shard_map(
        lambda a, b, c: ulysses_attention(a, b, c, axis_name="seq"),
        mesh,
        in_specs=(P(None, "seq"),) * 3,
        out_specs=P(None, "seq"),
    )
    with pytest.raises(ValueError, match="divisible"):
        jax.jit(fn)(q, k, v)
