"""Pass 3 — symbolic plan verifier tests (horovod_tpu/analysis/plan_verify.py).

Property sweep: every candidate plan ``select_plan`` can emit across the
topo-smoke topology grid verifies clean. Mutation tests: a corrupted
schedule (dropped stage, non-bijective permute round, wrong bytes, wrong
axis, wrong primitive, corrupted split buckets) is rejected with a
finding naming the stage. No jax required anywhere in this file.
"""

import dataclasses

import pytest

from horovod_tpu.common.types import ReduceOp
from horovod_tpu.analysis import verify_plan, verify_plan_grid
from horovod_tpu.analysis.findings import (
    RULE_PLAN_BIJECTION,
    RULE_PLAN_BYTES,
    RULE_PLAN_RESULT,
    RULE_PLAN_STAGE,
)
from horovod_tpu.analysis.plan_verify import (
    DEFAULT_PAYLOADS,
    DEFAULT_TOPOLOGIES,
)
from horovod_tpu.topo import (
    COLLECTIVES,
    candidate_plans,
    perm_rounds,
    select_plan,
    stage_kind,
    synthetic_model,
)

MODELS = [
    (name, synthetic_model(generation="v5e", **sizes))
    for name, sizes in DEFAULT_TOPOLOGIES
]
TWO_LEVEL = synthetic_model(local=4, cross=2, generation="v5e")
THREE_LEVEL = synthetic_model(local=2, cross=2, pod=2, generation="v5e")


# ---------------------------------------------------------------------------
# Property sweep: the whole candidate grid is clean
# ---------------------------------------------------------------------------

def test_grid_verifies_clean():
    findings, verified = verify_plan_grid()
    assert findings == []
    # Every topology contributes plans for every collective; a shrunken
    # grid would mean the compositor stopped emitting candidates.
    assert verified >= 4 * len(COLLECTIVES) * len(DEFAULT_PAYLOADS)


@pytest.mark.parametrize("name,model", MODELS)
@pytest.mark.parametrize("collective", COLLECTIVES)
def test_every_candidate_plan_verifies(name, model, collective):
    ops = (
        (ReduceOp.SUM, ReduceOp.AVERAGE, ReduceOp.MIN, ReduceOp.MAX,
         ReduceOp.PRODUCT)
        if collective == "allreduce" else (ReduceOp.SUM,)
    )
    checked = 0
    for op in ops:
        for nbytes in (1024, 64 << 20):
            for alg, plan in candidate_plans(
                model, collective, nbytes, op=op
            ).items():
                fs = verify_plan(plan, model)
                assert fs == [], (
                    f"{name}/{collective}/{alg}/{op}/{nbytes}: "
                    + "; ".join(f.render() for f in fs)
                )
                checked += 1
    assert checked > 0


def test_selected_plan_is_a_verified_candidate():
    for _, model in MODELS:
        plan = select_plan(model, "allreduce", 64 << 20)
        cands = candidate_plans(model, "allreduce", 64 << 20)
        assert plan.algorithm in cands
        assert verify_plan(plan, model) == []


def test_ineligible_model_collapses_and_verifies():
    gated = synthetic_model(
        local=4, cross=2, generation="v5e", eligible=False
    )
    plan = select_plan(gated, "allreduce", 64 << 20)
    assert len(plan.hop_sizes) == 1  # collapsed to flat
    assert verify_plan(plan, gated) == []


# ---------------------------------------------------------------------------
# Stage metadata (the topo/ side the verifier consumes)
# ---------------------------------------------------------------------------

def test_stage_kind_decomposition():
    assert stage_kind("reduce_scatter-ring") == ("reducescatter", "ring",
                                                 None)
    assert stage_kind("all_gather-doubling") == ("allgather", "doubling",
                                                 None)
    assert stage_kind("reduce_scatter-b1") == ("reducescatter", "", 1)
    assert stage_kind("all_reduce-b0") == ("allreduce", "", 0)
    assert stage_kind("broadcast-tree") == ("broadcast", "tree", None)
    assert stage_kind("block_permute") == ("local", "", None)
    assert stage_kind("made_up")[0] == "?"


def test_perm_rounds_ring_and_halving():
    ring = perm_rounds("all_gather-ring", 4)
    assert len(ring) == 3
    assert ring[0] == [(0, 1), (1, 2), (2, 3), (3, 0)]
    hd = perm_rounds("reduce_scatter-halving", 8)
    assert len(hd) == 3
    for rnd in hd:
        assert sorted(s for s, _ in rnd) == list(range(8))
        assert sorted(d for _, d in rnd) == list(range(8))
    assert perm_rounds("all_reduce", 4) is None  # XLA-native stage
    assert perm_rounds("all_gather-ring", 1) == []


# ---------------------------------------------------------------------------
# Mutation tests: corrupted schedules are rejected, naming the stage
# ---------------------------------------------------------------------------

def _mutate(plan, i, **changes):
    stages = list(plan.stages)
    stages[i] = dataclasses.replace(stages[i], **changes)
    return dataclasses.replace(plan, stages=tuple(stages))


def test_dropped_stage_rejected():
    plan = candidate_plans(TWO_LEVEL, "allreduce", 1 << 20)["two-level"]
    mut = dataclasses.replace(plan, stages=plan.stages[:-1])
    fs = verify_plan(mut, TWO_LEVEL)
    assert RULE_PLAN_RESULT in {f.rule for f in fs}
    assert any("allreduce/two-level" in f.location for f in fs)


def test_dropped_stage_rejected_every_collective():
    for collective in COLLECTIVES:
        cands = candidate_plans(THREE_LEVEL, collective, 1 << 20)
        multi = {a: p for a, p in cands.items() if len(p.stages) > 1}
        assert multi, f"{collective}: no multi-stage candidate"
        for alg, plan in multi.items():
            mut = dataclasses.replace(plan, stages=plan.stages[:-1])
            assert verify_plan(mut, THREE_LEVEL), (
                f"{collective}/{alg}: dropped stage not caught"
            )


def test_wrong_bytes_rejected():
    plan = candidate_plans(TWO_LEVEL, "allreduce", 1 << 20)["two-level"]
    mut = _mutate(plan, 0,
                  bytes_on_wire=plan.stages[0].bytes_on_wire * 2)
    fs = verify_plan(mut, TWO_LEVEL)
    assert [f.rule for f in fs] == [RULE_PLAN_BYTES]
    assert fs[0].details["stage_index"] == 0
    assert "stage[0]" in fs[0].location


def test_wrong_axis_rejected():
    plan = candidate_plans(TWO_LEVEL, "allgather", 1 << 20)["two-level"]
    mut = _mutate(plan, 0, axis="bogus")
    fs = verify_plan(mut, TWO_LEVEL)
    assert fs and fs[0].rule == RULE_PLAN_STAGE
    assert fs[0].details["primitive"] == plan.stages[0].primitive


def test_wrong_primitive_rejected():
    plan = candidate_plans(TWO_LEVEL, "allreduce", 1 << 20)["two-level"]
    mut = _mutate(plan, 0, primitive="all_to_all")
    fs = verify_plan(mut, TWO_LEVEL)
    assert fs and fs[0].rule == RULE_PLAN_STAGE
    mut = _mutate(plan, 0, primitive="frobnicate")
    fs = verify_plan(mut, TWO_LEVEL)
    assert fs and fs[0].rule == RULE_PLAN_STAGE


def test_wrong_round_count_rejected():
    flat8 = synthetic_model(local=8, generation="v5e")
    plan = candidate_plans(flat8, "allreduce", 64 << 20)["ring"]
    mut = _mutate(plan, 0, rounds=plan.stages[0].rounds + 3)
    fs = verify_plan(mut, flat8)
    assert any(f.rule == RULE_PLAN_STAGE for f in fs)


def test_non_bijective_permute_round_rejected():
    flat8 = synthetic_model(local=8, generation="v5e")
    plan = candidate_plans(flat8, "allreduce", 64 << 20)["ring"]

    def corrupt(primitive, size):
        rounds = perm_rounds(primitive, size)
        if rounds:
            rounds = [list(r) for r in rounds]
            rounds[0][0] = (0, rounds[0][1][1])  # duplicate destination
        return rounds

    fs = verify_plan(plan, flat8, rounds_fn=corrupt)
    assert fs and fs[0].rule == RULE_PLAN_BIJECTION
    assert "stage[0]" in fs[0].location
    assert verify_plan(plan, flat8) == []  # pristine rounds stay clean


def test_corrupt_split_buckets_rejected():
    plan = candidate_plans(TWO_LEVEL, "allreduce", 64 << 20)["split"]
    mut = dataclasses.replace(
        plan, split_bytes=(plan.split_bytes[0] + 4096,
                           plan.split_bytes[1]),
    )
    assert any(
        f.rule == RULE_PLAN_RESULT for f in verify_plan(mut, TWO_LEVEL)
    )


def test_hop_size_mismatch_rejected():
    plan = candidate_plans(TWO_LEVEL, "allreduce", 1 << 20)["two-level"]
    other = synthetic_model(local=2, cross=4, generation="v5e")
    fs = verify_plan(plan, other)
    assert fs and fs[0].rule == RULE_PLAN_STAGE


def test_empty_schedule_rejected_multi_rank():
    plan = candidate_plans(TWO_LEVEL, "allreduce", 1 << 20)["two-level"]
    mut = dataclasses.replace(plan, stages=())
    fs = verify_plan(mut, TWO_LEVEL)
    assert fs and fs[0].rule == RULE_PLAN_RESULT


# ---------------------------------------------------------------------------
# Quantized (wire_dtype=int8) plans — PR 9
# ---------------------------------------------------------------------------

def test_int8_candidates_verify_clean():
    for name, model in MODELS:
        for op in (ReduceOp.SUM, ReduceOp.AVERAGE):
            for nbytes in (1024, 64 << 20):
                for alg, plan in candidate_plans(
                    model, "allreduce", nbytes, op=op, wire_dtype="int8"
                ).items():
                    fs = verify_plan(plan, model)
                    assert fs == [], (
                        f"{name}/{alg}/{op}/{nbytes}: "
                        + "; ".join(f.render() for f in fs)
                    )
                    assert plan.wire_dtype == "int8"
                    if plan.stages:
                        assert any(
                            s.wire_dtype == "int8" for s in plan.stages
                        ), alg


def test_int8_rejected_for_non_additive_ops():
    for bad in (ReduceOp.MIN, ReduceOp.MAX, ReduceOp.PRODUCT):
        with pytest.raises(ValueError, match="SUM/AVERAGE"):
            candidate_plans(TWO_LEVEL, "allreduce", 1024, op=bad,
                            wire_dtype="int8")
    with pytest.raises(ValueError, match="allreduce"):
        candidate_plans(TWO_LEVEL, "allgather", 1024, wire_dtype="int8")
    with pytest.raises(ValueError, match="wire_dtype"):
        candidate_plans(TWO_LEVEL, "allreduce", 1024, wire_dtype="fp8")


def _int8_two_level():
    return candidate_plans(
        TWO_LEVEL, "allreduce", 64 << 20, op=ReduceOp.SUM,
        wire_dtype="int8",
    )["two-level"]


def test_int8_stage_with_full_precision_bytes_rejected():
    """A stage claiming wire_dtype=int8 while declaring uncompressed
    bytes is a corrupted compressed-bytes declaration -> RULE_PLAN_BYTES
    naming the stage."""
    plan = _int8_two_level()
    f32 = candidate_plans(
        TWO_LEVEL, "allreduce", 64 << 20, op=ReduceOp.SUM
    )["two-level"]
    stages = tuple(
        dataclasses.replace(s, bytes_on_wire=f32.stages[i].bytes_on_wire)
        if s.wire_dtype == "int8" else s
        for i, s in enumerate(plan.stages)
    )
    fs = verify_plan(dataclasses.replace(plan, stages=stages), TWO_LEVEL)
    assert any(f.rule == RULE_PLAN_BYTES for f in fs), [
        f.render() for f in fs
    ]
    assert any("stage" in f.location for f in fs)


def test_compressed_bytes_without_quantize_stage_rejected():
    """A plan declaring compressed bytes WITHOUT any int8 stage must
    fail verification — compression claimed, no quantizer."""
    plan = _int8_two_level()
    # Strip the wire_dtype markers but keep the compressed byte counts.
    stages = tuple(
        dataclasses.replace(s, wire_dtype="f32") for s in plan.stages
    )
    fs = verify_plan(dataclasses.replace(plan, stages=stages), TWO_LEVEL)
    assert fs, "compression without a quantize stage verified clean"

    # Same corruption on a plan that doesn't even declare int8 at the
    # plan level: the per-stage byte accounting still catches it.
    f32 = candidate_plans(
        TWO_LEVEL, "allreduce", 64 << 20, op=ReduceOp.SUM
    )["two-level"]
    small = tuple(
        dataclasses.replace(s, bytes_on_wire=s.bytes_on_wire // 4)
        if s.primitive == "all_reduce" else s
        for s in f32.stages
    )
    fs2 = verify_plan(dataclasses.replace(f32, stages=small), TWO_LEVEL)
    assert any(f.rule == RULE_PLAN_BYTES for f in fs2)


def test_int8_wrong_op_stage_rejected():
    """wire_dtype=int8 on a MIN plan's stage must be flagged (the grid
    can't emit it; a hand-built or corrupted plan could)."""
    minplan = candidate_plans(
        TWO_LEVEL, "allreduce", 1024, op=ReduceOp.MIN
    )["two-level"]
    stages = tuple(
        dataclasses.replace(
            s, wire_dtype="int8",
        ) for s in minplan.stages
    )
    fs = verify_plan(dataclasses.replace(minplan, stages=stages), TWO_LEVEL)
    assert any(f.rule == RULE_PLAN_STAGE for f in fs)


def test_unknown_wire_dtype_rejected():
    plan = _int8_two_level()
    stages = (dataclasses.replace(plan.stages[0], wire_dtype="fp4"),
              ) + plan.stages[1:]
    fs = verify_plan(dataclasses.replace(plan, stages=stages), TWO_LEVEL)
    assert any("wire_dtype" in f.message for f in fs)


def test_grid_sweeps_int8_plans():
    """verify_plan_grid covers the int8 candidates too (plans_verified
    grew past the f32-only grid)."""
    findings, verified = verify_plan_grid()
    assert findings == []
    assert verified >= 255, verified
