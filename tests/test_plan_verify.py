"""Pass 3 — symbolic plan verifier tests (horovod_tpu/analysis/plan_verify.py).

Property sweep: every candidate plan ``select_plan`` can emit across the
topo-smoke topology grid verifies clean. Mutation tests: a corrupted
schedule (dropped stage, non-bijective permute round, wrong bytes, wrong
axis, wrong primitive, corrupted split buckets) is rejected with a
finding naming the stage. No jax required anywhere in this file.
"""

import dataclasses

import pytest

from horovod_tpu.common.types import ReduceOp
from horovod_tpu.analysis import verify_plan, verify_plan_grid
from horovod_tpu.analysis.findings import (
    RULE_PLAN_BIJECTION,
    RULE_PLAN_BYTES,
    RULE_PLAN_RESULT,
    RULE_PLAN_STAGE,
)
from horovod_tpu.analysis.plan_verify import (
    DEFAULT_PAYLOADS,
    DEFAULT_TOPOLOGIES,
)
from horovod_tpu.topo import (
    COLLECTIVES,
    candidate_plans,
    perm_rounds,
    select_plan,
    stage_kind,
    synthetic_model,
)

MODELS = [
    (name, synthetic_model(generation="v5e", **sizes))
    for name, sizes in DEFAULT_TOPOLOGIES
]
TWO_LEVEL = synthetic_model(local=4, cross=2, generation="v5e")
THREE_LEVEL = synthetic_model(local=2, cross=2, pod=2, generation="v5e")


# ---------------------------------------------------------------------------
# Property sweep: the whole candidate grid is clean
# ---------------------------------------------------------------------------

def test_grid_verifies_clean():
    findings, verified = verify_plan_grid()
    assert findings == []
    # Every topology contributes plans for every collective; a shrunken
    # grid would mean the compositor stopped emitting candidates.
    assert verified >= 4 * len(COLLECTIVES) * len(DEFAULT_PAYLOADS)


@pytest.mark.parametrize("name,model", MODELS)
@pytest.mark.parametrize("collective", COLLECTIVES)
def test_every_candidate_plan_verifies(name, model, collective):
    ops = (
        (ReduceOp.SUM, ReduceOp.AVERAGE, ReduceOp.MIN, ReduceOp.MAX,
         ReduceOp.PRODUCT)
        if collective == "allreduce" else (ReduceOp.SUM,)
    )
    checked = 0
    for op in ops:
        for nbytes in (1024, 64 << 20):
            for alg, plan in candidate_plans(
                model, collective, nbytes, op=op
            ).items():
                fs = verify_plan(plan, model)
                assert fs == [], (
                    f"{name}/{collective}/{alg}/{op}/{nbytes}: "
                    + "; ".join(f.render() for f in fs)
                )
                checked += 1
    assert checked > 0


def test_selected_plan_is_a_verified_candidate():
    for _, model in MODELS:
        plan = select_plan(model, "allreduce", 64 << 20)
        cands = candidate_plans(model, "allreduce", 64 << 20)
        assert plan.algorithm in cands
        assert verify_plan(plan, model) == []


def test_ineligible_model_collapses_and_verifies():
    gated = synthetic_model(
        local=4, cross=2, generation="v5e", eligible=False
    )
    plan = select_plan(gated, "allreduce", 64 << 20)
    assert len(plan.hop_sizes) == 1  # collapsed to flat
    assert verify_plan(plan, gated) == []


# ---------------------------------------------------------------------------
# Stage metadata (the topo/ side the verifier consumes)
# ---------------------------------------------------------------------------

def test_stage_kind_decomposition():
    assert stage_kind("reduce_scatter-ring") == ("reducescatter", "ring",
                                                 None)
    assert stage_kind("all_gather-doubling") == ("allgather", "doubling",
                                                 None)
    assert stage_kind("reduce_scatter-b1") == ("reducescatter", "", 1)
    assert stage_kind("all_reduce-b0") == ("allreduce", "", 0)
    assert stage_kind("broadcast-tree") == ("broadcast", "tree", None)
    assert stage_kind("block_permute") == ("local", "", None)
    assert stage_kind("made_up")[0] == "?"


def test_perm_rounds_ring_and_halving():
    ring = perm_rounds("all_gather-ring", 4)
    assert len(ring) == 3
    assert ring[0] == [(0, 1), (1, 2), (2, 3), (3, 0)]
    hd = perm_rounds("reduce_scatter-halving", 8)
    assert len(hd) == 3
    for rnd in hd:
        assert sorted(s for s, _ in rnd) == list(range(8))
        assert sorted(d for _, d in rnd) == list(range(8))
    assert perm_rounds("all_reduce", 4) is None  # XLA-native stage
    assert perm_rounds("all_gather-ring", 1) == []


# ---------------------------------------------------------------------------
# Mutation tests: corrupted schedules are rejected, naming the stage
# ---------------------------------------------------------------------------

def _mutate(plan, i, **changes):
    stages = list(plan.stages)
    stages[i] = dataclasses.replace(stages[i], **changes)
    return dataclasses.replace(plan, stages=tuple(stages))


def test_dropped_stage_rejected():
    plan = candidate_plans(TWO_LEVEL, "allreduce", 1 << 20)["two-level"]
    mut = dataclasses.replace(plan, stages=plan.stages[:-1])
    fs = verify_plan(mut, TWO_LEVEL)
    assert RULE_PLAN_RESULT in {f.rule for f in fs}
    assert any("allreduce/two-level" in f.location for f in fs)


def test_dropped_stage_rejected_every_collective():
    for collective in COLLECTIVES:
        cands = candidate_plans(THREE_LEVEL, collective, 1 << 20)
        multi = {a: p for a, p in cands.items() if len(p.stages) > 1}
        assert multi, f"{collective}: no multi-stage candidate"
        for alg, plan in multi.items():
            mut = dataclasses.replace(plan, stages=plan.stages[:-1])
            assert verify_plan(mut, THREE_LEVEL), (
                f"{collective}/{alg}: dropped stage not caught"
            )


def test_wrong_bytes_rejected():
    plan = candidate_plans(TWO_LEVEL, "allreduce", 1 << 20)["two-level"]
    mut = _mutate(plan, 0,
                  bytes_on_wire=plan.stages[0].bytes_on_wire * 2)
    fs = verify_plan(mut, TWO_LEVEL)
    assert [f.rule for f in fs] == [RULE_PLAN_BYTES]
    assert fs[0].details["stage_index"] == 0
    assert "stage[0]" in fs[0].location


def test_wrong_axis_rejected():
    plan = candidate_plans(TWO_LEVEL, "allgather", 1 << 20)["two-level"]
    mut = _mutate(plan, 0, axis="bogus")
    fs = verify_plan(mut, TWO_LEVEL)
    assert fs and fs[0].rule == RULE_PLAN_STAGE
    assert fs[0].details["primitive"] == plan.stages[0].primitive


def test_wrong_primitive_rejected():
    plan = candidate_plans(TWO_LEVEL, "allreduce", 1 << 20)["two-level"]
    mut = _mutate(plan, 0, primitive="all_to_all")
    fs = verify_plan(mut, TWO_LEVEL)
    assert fs and fs[0].rule == RULE_PLAN_STAGE
    mut = _mutate(plan, 0, primitive="frobnicate")
    fs = verify_plan(mut, TWO_LEVEL)
    assert fs and fs[0].rule == RULE_PLAN_STAGE


def test_wrong_round_count_rejected():
    flat8 = synthetic_model(local=8, generation="v5e")
    plan = candidate_plans(flat8, "allreduce", 64 << 20)["ring"]
    mut = _mutate(plan, 0, rounds=plan.stages[0].rounds + 3)
    fs = verify_plan(mut, flat8)
    assert any(f.rule == RULE_PLAN_STAGE for f in fs)


def test_non_bijective_permute_round_rejected():
    flat8 = synthetic_model(local=8, generation="v5e")
    plan = candidate_plans(flat8, "allreduce", 64 << 20)["ring"]

    def corrupt(primitive, size):
        rounds = perm_rounds(primitive, size)
        if rounds:
            rounds = [list(r) for r in rounds]
            rounds[0][0] = (0, rounds[0][1][1])  # duplicate destination
        return rounds

    fs = verify_plan(plan, flat8, rounds_fn=corrupt)
    assert fs and fs[0].rule == RULE_PLAN_BIJECTION
    assert "stage[0]" in fs[0].location
    assert verify_plan(plan, flat8) == []  # pristine rounds stay clean


def test_corrupt_split_buckets_rejected():
    plan = candidate_plans(TWO_LEVEL, "allreduce", 64 << 20)["split"]
    mut = dataclasses.replace(
        plan, split_bytes=(plan.split_bytes[0] + 4096,
                           plan.split_bytes[1]),
    )
    assert any(
        f.rule == RULE_PLAN_RESULT for f in verify_plan(mut, TWO_LEVEL)
    )


def test_hop_size_mismatch_rejected():
    plan = candidate_plans(TWO_LEVEL, "allreduce", 1 << 20)["two-level"]
    other = synthetic_model(local=2, cross=4, generation="v5e")
    fs = verify_plan(plan, other)
    assert fs and fs[0].rule == RULE_PLAN_STAGE


def test_empty_schedule_rejected_multi_rank():
    plan = candidate_plans(TWO_LEVEL, "allreduce", 1 << 20)["two-level"]
    mut = dataclasses.replace(plan, stages=())
    fs = verify_plan(mut, TWO_LEVEL)
    assert fs and fs[0].rule == RULE_PLAN_RESULT
