"""Expert-parallel (MoE) tests on the virtual 8-device CPU mesh.

Mirrors the reference test strategy of comparing distributed results
against a locally-computed dense reference (as the Adasum tests compare
VHDD against a NumPy formula, SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu.jax import _shard_map
from horovod_tpu.parallel.ep import (
    MoEParams,
    expert_sharding_specs,
    init_moe_params,
    make_ep_train_step,
    moe_ffn,
)
from horovod_tpu.parallel.mesh import build_mesh


def _dense_reference(params: MoEParams, x: np.ndarray, capacity: int):
    """Per-token dense computation of top-1 MoE with capacity limits,
    evaluated independently per source shard (matching moe_ffn, where
    each device's tokens compete for their own capacity slots)."""
    w_r = np.asarray(params.w_router, np.float32)
    w_in = np.asarray(params.w_in, np.float32)
    w_out = np.asarray(params.w_out, np.float32)
    e_total = w_in.shape[0]

    logits = x @ w_r
    g = np.exp(logits - logits.max(-1, keepdims=True))
    gates = g / g.sum(-1, keepdims=True)
    top = gates.argmax(-1)
    counts = {e: 0 for e in range(e_total)}
    y = np.zeros_like(x)
    for s in range(x.shape[0]):
        e = int(top[s])
        if counts[e] >= capacity:
            continue  # dropped token -> zero output (residual path)
        counts[e] += 1
        h = np.tanh(x[s] @ w_in[e])  # activation=tanh in these tests
        y[s] = gates[s, e] * (h @ w_out[e])
    return y


@pytest.fixture(scope="module")
def ep_mesh(devices):
    return build_mesh({"expert": 4}, devices=devices[:4])


def test_moe_ffn_matches_dense_reference(ep_mesh):
    e_total, d_model, d_hidden = 8, 16, 32
    s_per_dev = 12
    rng = jax.random.PRNGKey(0)
    params = init_moe_params(
        rng, d_model=d_model, d_hidden=d_hidden,
        num_experts=e_total, num_expert_shards=4,
    )
    x = np.random.RandomState(0).randn(4 * s_per_dev, d_model).astype(
        np.float32
    )

    capacity_factor = 4.0  # roomy: almost nothing drops
    capacity = max(1, int(capacity_factor * s_per_dev / e_total))

    def fn(p, xs):
        y, aux = moe_ffn(
            p, xs, expert_axis="expert",
            capacity_factor=capacity_factor, activation=jnp.tanh,
        )
        return y, lax.pmean(aux, "expert")

    shard = _shard_map(
        fn, ep_mesh,
        in_specs=(
            MoEParams(P(), P("expert"), P("expert")), P("expert"),
        ),
        out_specs=(P("expert"), P()),
    )
    y, aux = jax.jit(shard)(params, jnp.asarray(x))
    assert float(aux) > 0.0

    # Reference evaluated per source shard (each device routes its own
    # s_per_dev tokens against per-(expert, source) capacity).
    y_ref = np.concatenate([
        _dense_reference(
            params, x[i * s_per_dev:(i + 1) * s_per_dev], capacity
        )
        for i in range(4)
    ])
    np.testing.assert_allclose(np.asarray(y), y_ref, rtol=1e-4, atol=1e-4)


def test_moe_capacity_drops_tokens(ep_mesh):
    """With capacity_factor forcing tiny buffers, overflow tokens must
    produce exactly zero output rows (Switch residual-path semantics)."""
    e_total, d_model, d_hidden = 4, 8, 8
    s_per_dev = 16
    params = init_moe_params(
        jax.random.PRNGKey(1), d_model=d_model, d_hidden=d_hidden,
        num_experts=e_total, num_expert_shards=4,
    )
    # Router steered so every token picks expert 0.
    params = params._replace(
        w_router=jnp.zeros((d_model, e_total)).at[:, 0].set(5.0)
    )
    x = np.abs(np.random.RandomState(1).randn(64, d_model)).astype(np.float32)

    def fn(p, xs):
        y, _ = moe_ffn(p, xs, expert_axis="expert", capacity_factor=0.3)
        return y

    shard = _shard_map(
        fn, ep_mesh,
        in_specs=(MoEParams(P(), P("expert"), P("expert")), P("expert")),
        out_specs=P("expert"),
    )
    y = np.asarray(jax.jit(shard)(params, jnp.asarray(x)))
    capacity = max(1, int(0.3 * s_per_dev / e_total))
    zero_rows = np.sum(np.all(y == 0.0, axis=-1))
    # Per device only `capacity` tokens survive into expert 0.
    assert zero_rows == 64 - 4 * capacity


def test_ep_train_step_converges(devices):
    """DP x EP end-to-end: loss decreases and expert weights stay sharded."""
    mesh = build_mesh({"data": 2, "expert": 4}, devices=devices)
    e_total, d_model, d_hidden = 4, 8, 16
    rng = jax.random.PRNGKey(2)
    moe = init_moe_params(
        rng, d_model=d_model, d_hidden=d_hidden,
        num_experts=e_total, num_expert_shards=4,
    )
    w_head = jnp.zeros((d_model, 1))
    params = {"moe": moe, "head": w_head}

    tx = optax.adam(1e-2)
    opt_state = tx.init(params)

    batch_x = np.random.RandomState(3).randn(64, d_model).astype(np.float32)
    w_true = np.random.RandomState(4).randn(d_model, 1).astype(np.float32)
    batch_y = batch_x @ w_true

    def loss_fn(p, batch):
        xb, yb = batch
        h, aux = moe_ffn(
            p["moe"], xb, expert_axis="expert", capacity_factor=2.0
        )
        pred = (xb + h) @ p["head"]
        return jnp.mean((pred - yb) ** 2), aux

    step = make_ep_train_step(loss_fn, tx, mesh, params, opt_state)

    batch = (jnp.asarray(batch_x), jnp.asarray(batch_y))
    losses = []
    for _ in range(80):
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.2, losses[::10]


def test_ep_gradient_scale_matches_single_device(devices):
    """One SGD step on a 1x4 EP mesh must produce the same expert weights
    as the identical model stepped on a single device (expert grads must
    NOT carry an extra factor of the expert-group size — adam masks scale
    errors, sgd does not)."""
    e_total, d_model, d_hidden = 4, 8, 16
    moe = init_moe_params(
        jax.random.PRNGKey(5), d_model=d_model, d_hidden=d_hidden,
        num_experts=e_total, num_expert_shards=4,
    )
    params = {"moe": moe, "head": jnp.ones((d_model, 1)) * 0.1}
    tx = optax.sgd(0.5)

    x = np.random.RandomState(5).randn(32, d_model).astype(np.float32)
    y = np.random.RandomState(6).randn(32, 1).astype(np.float32)
    batch = (jnp.asarray(x), jnp.asarray(y))

    def loss_fn(p, batch):
        xb, yb = batch
        # Roomy capacity so EP sharding (8 tokens/source) and the single
        # device (32 tokens) drop nothing and compute identical outputs.
        h, aux = moe_ffn(
            p["moe"], xb, expert_axis="expert", capacity_factor=16.0
        )
        pred = (xb + h) @ p["head"]
        return jnp.mean((pred - yb) ** 2), aux

    def run(mesh_axes, devs):
        mesh = build_mesh(mesh_axes, devices=devs)
        opt_state = tx.init(params)
        step = make_ep_train_step(
            loss_fn, tx, mesh, params, opt_state,
            aux_loss_weight=0.0, donate=False,
        )
        new_params, _, loss = step(params, opt_state, batch)
        return jax.device_get(new_params), float(loss)

    p_ep, loss_ep = run({"data": 1, "expert": 4}, devices[:4])
    p_ref, loss_ref = run({"data": 1, "expert": 1}, devices[:1])

    np.testing.assert_allclose(loss_ep, loss_ref, rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(p_ep["head"]), np.asarray(p_ref["head"]), rtol=1e-4,
        atol=1e-5,
    )
    np.testing.assert_allclose(
        np.asarray(p_ep["moe"].w_in), np.asarray(p_ref["moe"].w_in),
        rtol=1e-4, atol=1e-5,
    )
    np.testing.assert_allclose(
        np.asarray(p_ep["moe"].w_out), np.asarray(p_ref["moe"].w_out),
        rtol=1e-4, atol=1e-5,
    )


def test_expert_sharding_specs():
    moe = init_moe_params(
        jax.random.PRNGKey(0), d_model=4, d_hidden=4,
        num_experts=4, num_expert_shards=2,
    )
    specs = expert_sharding_specs({"moe": moe, "other": jnp.ones(3)})
    assert specs["moe"].w_in == P("expert")
    assert specs["moe"].w_out == P("expert")
    assert specs["moe"].w_router == P()
    assert specs["other"] == P()


def test_init_moe_params_validates_divisibility():
    with pytest.raises(ValueError):
        init_moe_params(
            jax.random.PRNGKey(0), d_model=4, d_hidden=4,
            num_experts=6, num_expert_shards=4,
        )
