"""``hvd.serve()`` — continuous batching over the composed DP x TP fast
path (docs/serving.md): batcher policy units, paged KV-cache pool,
greedy-decode parity of the batched engine against a one-request-at-a-
time reference, selfdrive SLO-trigger units, serving-sim determinism,
serving fault-site validation, and the HOROVOD_SERVE_* knob registry."""

import json
import os
import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from horovod_tpu.common import env as hvd_env
from horovod_tpu.fault.plan import FaultAction, FaultPlan
from horovod_tpu.jax import make_decode_step
from horovod_tpu.models.transformer import TransformerLM, tp_apply
from horovod_tpu.parallel.mesh import build_mesh
from horovod_tpu.run.selfdrive import ServeScalePolicy
from horovod_tpu.serve import (
    ContinuousBatcher,
    PagePool,
    PagePoolExhausted,
    ServeEngine,
    make_decode_state,
)
from horovod_tpu.sim import ServeSimConfig, simulate_serve

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --------------------------------------------------------------- batcher
class TestContinuousBatcher:
    def test_full_precedes_deadline(self):
        b = ContinuousBatcher(max_batch_size=4, max_wait_us=1000)
        for i in range(4):
            assert b.offer(f"r{i}", now_us=0)
        d = b.poll(0)
        assert d.ready and d.reason == "full"
        assert d.request_ids == ("r0", "r1", "r2", "r3")
        assert b.depth() == 0

    def test_deadline_fires_on_head_wait(self):
        b = ContinuousBatcher(max_batch_size=4, max_wait_us=1000)
        b.offer("a", now_us=0)
        b.offer("b", now_us=900)
        assert not b.poll(500).ready
        assert b.poll(500).reason == "waiting"
        d = b.poll(1000)  # head has waited exactly max_wait_us
        assert d.ready and d.reason == "deadline"
        assert d.request_ids == ("a", "b")

    def test_starvation_freedom_bound(self):
        # Under trickle pressure the head is never stranded: the next
        # dispatch instant is exactly head-admission + max_wait_us, and
        # assembly is strictly oldest-first.
        b = ContinuousBatcher(max_batch_size=8, max_wait_us=2000)
        b.offer("head", now_us=100)
        for i in range(3):
            b.offer(f"late{i}", now_us=100 + 300 * (i + 1))
        assert b.next_deadline_us() == 2100
        assert not b.poll(2099).ready
        d = b.poll(2100)
        assert d.ready and d.request_ids[0] == "head"
        assert d.request_ids == ("head", "late0", "late1", "late2")

    def test_deterministic_assembly_for_fixed_trace(self):
        trace = [("a", 0), ("b", 10), ("c", 20), ("d", 30), ("e", 40)]

        def replay():
            b = ContinuousBatcher(max_batch_size=2, max_wait_us=1000)
            out = []
            for rid, t in trace:
                b.offer(rid, now_us=t)
                d = b.poll(t)
                if d.ready:
                    out.append((d.reason, d.request_ids))
            d = b.poll(5000)
            if d.ready:
                out.append((d.reason, d.request_ids))
            return out

        first, second = replay(), replay()
        assert first == second
        assert first == [("full", ("a", "b")), ("full", ("c", "d")),
                         ("deadline", ("e",))]

    def test_queue_bound_refuses(self):
        b = ContinuousBatcher(max_batch_size=8, max_wait_us=10,
                              queue_bound=2)
        assert b.offer("a", 0) and b.offer("b", 0)
        assert not b.offer("c", 0)  # refused, not queued
        assert b.depth() == 2

    def test_requeue_goes_to_front_and_bypasses_bound(self):
        b = ContinuousBatcher(max_batch_size=8, max_wait_us=0,
                              queue_bound=2)
        b.offer("a", 0)
        b.offer("b", 0)
        b.requeue("survivor", enqueued_us=0)  # over the bound: allowed
        d = b.poll(0)
        assert d.request_ids[0] == "survivor"

    def test_duplicate_offer_raises(self):
        b = ContinuousBatcher()
        b.offer("a", 0)
        with pytest.raises(ValueError, match="already queued"):
            b.offer("a", 1)

    def test_max_size_caps_batch(self):
        b = ContinuousBatcher(max_batch_size=8, max_wait_us=0)
        for i in range(6):
            b.offer(i, 0)
        d = b.poll(100, max_size=2)  # KV-page pressure
        assert d.ready and d.request_ids == (0, 1)
        assert b.depth() == 4

    def test_from_env(self):
        b = ContinuousBatcher.from_env({
            hvd_env.HOROVOD_SERVE_MAX_BATCH: "3",
            hvd_env.HOROVOD_SERVE_MAX_WAIT_US: "77",
            hvd_env.HOROVOD_SERVE_QUEUE_BOUND: "5",
        })
        assert (b.max_batch_size, b.max_wait_us, b.queue_bound) == (3, 77, 5)


# -------------------------------------------------------------- KV pages
class TestPagePool:
    def test_alloc_is_deterministic_and_skips_scratch(self):
        pool = PagePool(num_pages=8, page_size=4)
        assert pool.pages_free == 7  # page 0 is the scratch page
        pages = pool.alloc(tokens=9)   # ceil(9/4) = 3 pages
        assert pages == [1, 2, 3]
        assert pool.pages_in_use == 3
        assert PagePool.SCRATCH_PAGE not in pages

    def test_alloc_all_or_nothing(self):
        pool = PagePool(num_pages=4, page_size=4)  # 3 usable pages
        with pytest.raises(PagePoolExhausted):
            pool.alloc(tokens=16)  # needs 4
        assert pool.pages_free == 3  # refusal left the pool untouched
        assert pool.can_admit(12) and not pool.can_admit(13)

    def test_free_and_double_free(self):
        pool = PagePool(num_pages=4, page_size=4)
        pages = pool.alloc(tokens=8)
        pool.free(pages)
        assert pool.pages_free == 3
        with pytest.raises(ValueError):
            pool.free(pages)  # double free is a bug, not a no-op
        with pytest.raises(ValueError):
            pool.free([0])    # scratch page is never owned

    def test_freed_pages_are_reused_deterministically(self):
        def replay():
            pool = PagePool(num_pages=8, page_size=4)
            a = pool.alloc(tokens=8)
            pool.free(a)
            b = pool.alloc(tokens=8)
            return a, b

        first, second = replay(), replay()
        assert first == second  # identical sequence -> identical pages
        assert sorted(first[0]) == sorted(first[1])  # same pages reused

    def test_needs_two_pages_minimum(self):
        with pytest.raises(ValueError):
            PagePool(num_pages=1, page_size=4)

    def test_decode_state_geometry(self):
        cache = make_decode_state(2, num_pages=4, page_size=8,
                                  n_heads=2, head_dim=4)
        assert sorted(cache) == ["block_0", "block_1"]
        k = cache["block_0"]["attention"]["cache_k"]
        assert k.shape == (4, 8, 2, 4)
        assert k.dtype == jnp.bfloat16  # serving default


# ------------------------------------------------------- decode parity
VOCAB, D, HEADS, LAYERS, T = 32, 16, 2, 1, 32


def _tiny_params():
    model = TransformerLM(vocab_size=VOCAB, d_model=D, n_heads=HEADS,
                          n_layers=LAYERS, max_len=T)
    return model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, T), jnp.int32)
    )["params"]


def _prompts(n=6, seed=0):
    rng = np.random.RandomState(seed)
    return [
        [int(t) for t in rng.randint(0, VOCAB, size=rng.randint(1, 6))]
        for _ in range(n)
    ]


def _reference_greedy(params, prompt, max_tokens):
    """One-request-at-a-time full-recompute greedy decode via the dense
    ``tp_apply`` reference — no KV cache, no batching."""
    seq = list(prompt)
    for _ in range(max_tokens):
        logits = tp_apply(
            params, jnp.asarray([seq], jnp.int32), n_heads=HEADS,
            model_axis=None, dtype=jnp.float32,
        )
        seq.append(int(jnp.argmax(logits[0, len(seq) - 1])))
    return seq[len(prompt):]


def _run_engine(params, step, prompts, max_tokens=4, replicas=1):
    engine = ServeEngine(
        params, step,
        n_layers=LAYERS, n_heads=HEADS, head_dim=D // HEADS,
        num_pages=64, page_size=4, max_batch_size=4, max_wait_us=500,
        max_context=T, replicas=replicas, cache_dtype=jnp.float32,
    )
    with engine:
        rids = [engine.submit(p, max_tokens=max_tokens) for p in prompts]
        engine.drain(timeout=120.0)
    return [engine.result(r) for r in rids]


def test_batched_engine_matches_one_at_a_time_reference():
    params = _tiny_params()
    step = make_decode_step(n_heads=HEADS, dtype=jnp.float32)
    prompts = _prompts()
    got = _run_engine(params, step, prompts)
    for prompt, completion in zip(prompts, got):
        assert completion.outcome == "ok"
        assert list(completion.tokens) == \
            _reference_greedy(params, prompt, 4), (
                f"paged batched decode diverged for prompt {prompt}"
            )


def test_tp_sharded_decode_matches_dense(devices):
    params = _tiny_params()
    mesh = build_mesh({"model": 2}, devices=devices[:2])
    dense = make_decode_step(n_heads=HEADS, dtype=jnp.float32)
    tp = make_decode_step(n_heads=HEADS, mesh=mesh, rules="gpt",
                          dtype=jnp.float32)
    prompts = _prompts(n=4, seed=3)
    a = _run_engine(params, dense, prompts)
    b = _run_engine(params, tp, prompts)
    assert [list(c.tokens) for c in a] == [list(c.tokens) for c in b]


def test_make_decode_step_validates_mesh_rules_pairing(devices):
    mesh = build_mesh({"model": 2}, devices=devices[:2])
    with pytest.raises(ValueError, match="rules"):
        make_decode_step(n_heads=HEADS, mesh=mesh)  # mesh without rules
    with pytest.raises(ValueError, match="mesh"):
        make_decode_step(n_heads=HEADS, rules="gpt")  # rules without mesh
    with pytest.raises(ValueError, match="needs axis 'tensor'"):
        make_decode_step(n_heads=HEADS, mesh=mesh, rules="gpt",
                         model_axis="tensor")


def test_engine_refuses_oversized_and_duplicate_requests():
    params = _tiny_params()
    step = make_decode_step(n_heads=HEADS, dtype=jnp.float32)
    engine = ServeEngine(
        params, step,
        n_layers=LAYERS, n_heads=HEADS, head_dim=D // HEADS,
        num_pages=8, page_size=4, max_context=T,
        cache_dtype=jnp.float32,
    )
    with engine:
        with pytest.raises(ValueError):
            engine.submit([], max_tokens=4)  # empty prompt
        with pytest.raises(ValueError):
            engine.submit([1, 2], max_tokens=T)  # prompt+tokens > context
        engine.submit([1, 2], max_tokens=1, request_id="dup")
        with pytest.raises(ValueError):
            engine.submit([3], max_tokens=1, request_id="dup")
        engine.drain(timeout=60.0)


# -------------------------------------------------- selfdrive SLO hook
class TestServeScalePolicy:
    @staticmethod
    def _fill(policy, depth=0.0, viol=0, done=0, beats=None):
        for _ in range(policy.window if beats is None else beats):
            policy.observe(depth, viol, done)

    def test_cold_start_returns_none(self):
        p = ServeScalePolicy(window=4, cooldown=0)
        self._fill(p, depth=100.0, viol=10, done=10, beats=3)
        assert p.decide(1) is None  # window not yet filled

    def test_scale_out_on_queue_depth(self):
        p = ServeScalePolicy(scale_out_depth=16.0, window=2, cooldown=0)
        self._fill(p, depth=20.0, done=5)
        d = p.decide(1)
        assert d is not None and d.action == "scale-out"
        assert d.reason == "queue-depth"

    def test_scale_out_on_slo_burn(self):
        p = ServeScalePolicy(scale_out_depth=100.0, slo_burn=0.1,
                             window=2, cooldown=0)
        self._fill(p, depth=1.0, viol=3, done=10)  # 30% burn
        d = p.decide(1)
        assert d is not None and d.action == "scale-out"
        assert d.reason == "slo-burn"
        assert d.slo_burn == pytest.approx(0.3)

    def test_max_replicas_veto(self):
        p = ServeScalePolicy(scale_out_depth=1.0, window=1, cooldown=0,
                             max_replicas=2)
        self._fill(p, depth=50.0, done=5)
        assert p.decide(2) is None

    def test_scale_in_when_idle_and_min_veto(self):
        p = ServeScalePolicy(scale_in_depth=1.0, window=2, cooldown=0,
                             min_replicas=1)
        self._fill(p, depth=0.0, done=4)
        d = p.decide(2)
        assert d is not None and d.action == "scale-in"
        assert d.reason == "idle"
        p2 = ServeScalePolicy(scale_in_depth=1.0, window=2, cooldown=0)
        self._fill(p2, depth=0.0, done=4)
        assert p2.decide(1) is None  # already at min_replicas

    def test_idle_fleet_is_not_burning(self):
        p = ServeScalePolicy(window=2, cooldown=0)
        self._fill(p, depth=0.0, viol=0, done=0)
        assert p.burn() == 0.0
        assert p.decide(1) is None

    def test_cooldown_blocks_thrash(self):
        p = ServeScalePolicy(scale_out_depth=4.0, window=1, cooldown=2)
        p.observe(10.0, 0, 5)
        assert p.decide(1) is not None
        for _ in range(2):
            p.observe(10.0, 0, 5)
            assert p.decide(1) is None  # inside the cooldown
        p.observe(10.0, 0, 5)
        assert p.decide(1) is not None  # cooldown expired

    def test_from_env(self):
        p = ServeScalePolicy.from_env({
            hvd_env.HOROVOD_SERVE_SCALE_OUT_DEPTH: "9.5",
            hvd_env.HOROVOD_SERVE_SCALE_IN_DEPTH: "0.5",
            hvd_env.HOROVOD_SERVE_SLO_BURN: "0.25",
            hvd_env.HOROVOD_SERVE_SCALE_WINDOW: "3",
            hvd_env.HOROVOD_SERVE_SCALE_COOLDOWN: "1",
        }, min_replicas=2, max_replicas=4)
        assert p.scale_out_depth == 9.5
        assert p.scale_in_depth == 0.5
        assert p.slo_burn == 0.25
        assert (p.window, p.cooldown) == (3, 1)
        assert (p.min_replicas, p.max_replicas) == (2, 4)


# ----------------------------------------------------------- fleet sim
class TestServeSim:
    def test_report_is_deterministic(self):
        cfg = ServeSimConfig(qps=200.0, duration_s=2.0, seed=11)
        a = json.dumps(simulate_serve(cfg), sort_keys=True)
        b = json.dumps(simulate_serve(cfg), sort_keys=True)
        assert a == b

    def test_p99_rises_with_offered_load(self):
        p99 = [
            simulate_serve(
                ServeSimConfig(qps=q, duration_s=2.0, seed=0)
            )["latency_ms"]["p99"]
            for q in (50.0, 400.0, 1600.0)
        ]
        assert p99 == sorted(p99), f"p99 not monotone in qps: {p99}"
        assert p99[0] < p99[-1]

    def test_arrival_seed_changes_trace(self):
        base = ServeSimConfig(qps=200.0, duration_s=2.0, seed=0)
        other = ServeSimConfig(qps=200.0, duration_s=2.0, seed=1)
        assert simulate_serve(base) != simulate_serve(other)

    def test_faults_honored_and_exactly_once(self):
        plan = FaultPlan.from_json(json.dumps({
            "seed": 5,
            "faults": [
                {"kind": "drop", "site": "request", "after": 10,
                 "count": 30},
                {"kind": "kill_replica", "at_step": 3},
            ],
        }))
        cfg = ServeSimConfig(qps=200.0, duration_s=2.0, replicas=2,
                             seed=5)
        rep = simulate_serve(cfg, fault_plan=plan)
        assert rep["dropped"] > 0
        assert rep["replicas_killed"] == 1
        assert rep["requeued"] > 0
        assert rep["unanswered"] == 0  # every admitted request answered
        assert rep["arrivals"] == (
            rep["served"] + rep["dropped"] + rep["rejected"]
        )

    def test_queue_bound_rejects_under_overload(self):
        cfg = ServeSimConfig(qps=4000.0, duration_s=1.0, replicas=1,
                             queue_bound=8, seed=2)
        rep = simulate_serve(cfg)
        assert rep["rejected"] > 0
        assert rep["unanswered"] == 0


# ------------------------------------------------- fault site contract
class TestServingFaultSites:
    def test_kill_replica_defaults_to_replica_site(self):
        a = FaultAction.from_dict(
            {"kind": "kill_replica", "at_step": 1}, 0
        )
        assert a.site == "replica"

    def test_kind_site_mismatches_rejected(self):
        with pytest.raises(ValueError):
            FaultAction.from_dict(
                {"kind": "kill_replica", "site": "request", "at_step": 1},
                0,
            )
        with pytest.raises(ValueError):
            FaultAction.from_dict(
                {"kind": "drop", "site": "replica", "at_step": 1}, 0
            )
        with pytest.raises(ValueError):
            FaultAction.from_dict(
                {"kind": "kill", "site": "request", "at_step": 1}, 0
            )

    def test_request_site_carries_drop_and_delay(self):
        plan = FaultPlan.from_json(json.dumps({
            "seed": 0,
            "faults": [
                {"kind": "drop", "site": "request", "at_step": 1},
                {"kind": "delay", "site": "request", "at_step": 2,
                 "ms": 5},
            ],
        }))
        kinds = {a.kind for a in plan.actions}
        assert kinds == {"drop", "delay"}


# ---------------------------------------------------- knob registry
def _serve_knobs_in_sources():
    """Every HOROVOD_SERVE_* token referenced anywhere in the package."""
    found = set()
    for root, _dirs, files in os.walk(os.path.join(REPO, "horovod_tpu")):
        for fn in files:
            if not fn.endswith(".py"):
                continue
            with open(os.path.join(root, fn)) as f:
                found.update(re.findall(r"HOROVOD_SERVE_[A-Z_]+", f.read()))
    return found


def test_every_serve_knob_is_declared_in_env():
    knobs = _serve_knobs_in_sources()
    assert knobs, "no HOROVOD_SERVE_* knobs found (scan broken?)"
    for knob in sorted(knobs):
        assert getattr(hvd_env, knob, None) == knob, (
            f"{knob} is referenced in sources but not declared in "
            f"common/env.py — unknown serving knobs are a bug"
        )


def test_config_from_env_parses_serve_knobs(monkeypatch):
    values = {
        hvd_env.HOROVOD_SERVE: "1",
        hvd_env.HOROVOD_SERVE_PORT: "8123",
        hvd_env.HOROVOD_SERVE_REPLICAS: "3",
        hvd_env.HOROVOD_SERVE_MAX_BATCH: "16",
        hvd_env.HOROVOD_SERVE_MAX_WAIT_US: "777",
        hvd_env.HOROVOD_SERVE_QUEUE_BOUND: "9",
        hvd_env.HOROVOD_SERVE_SLO_MS: "42.5",
        hvd_env.HOROVOD_SERVE_MAX_TOKENS: "5",
        hvd_env.HOROVOD_SERVE_KV_PAGES: "33",
        hvd_env.HOROVOD_SERVE_PAGE_SIZE: "8",
    }
    for k, v in values.items():
        monkeypatch.setenv(k, v)
    cfg = hvd_env.Config.from_env()
    assert cfg.serve is True
    assert cfg.serve_port == 8123
    assert cfg.serve_replicas == 3
    assert cfg.serve_max_batch == 16
    assert cfg.serve_max_wait_us == 777
    assert cfg.serve_queue_bound == 9
    assert cfg.serve_slo_ms == 42.5
    assert cfg.serve_max_tokens == 5
    assert cfg.serve_kv_pages == 33
    assert cfg.serve_page_size == 8
