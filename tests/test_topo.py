"""Topology-aware collective compositor (docs/topology.md).

Three layers under test:

1. the interconnect MODEL — per-generation defaults, the
   ``HOROVOD_TOPOLOGY_MODEL`` override, the homogeneity eligibility gate,
   stable JSON;
2. PLAN SELECTION — the analytic cost model picking ring vs.
   recursive-halving vs. two-level vs. FlexLink split per (topology,
   payload bytes, op), deterministically;
3. the LOWERINGS — every compositor lowering (allreduce / allgather /
   reduce-scatter / broadcast / alltoall) numerically equal to the flat
   lowering at 2, 4, and 8 simulated ranks, including a three-level
   (pod, cross, local) case and the ICI+DCN concurrent-split allreduce;
   bitwise where the regrouping commutes (MIN/MAX, integer SUM, pure
   data movement), tolerance-checked for float SUM.
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import horovod_tpu.jax as hvdj
from horovod_tpu.common.types import ReduceOp
from horovod_tpu.jax import _shard_map
from horovod_tpu.ops import collectives as C
from horovod_tpu.parallel.mesh import (
    build_hierarchical_mesh,
    build_mesh,
    build_three_level_mesh,
    hierarchy_axes,
)
from horovod_tpu.topo import (
    GENERATION_DEFAULTS,
    InterconnectModel,
    apply_override,
    model_from_topology,
    select_plan,
    synthetic_model,
)
from horovod_tpu.topo import compositor as K


# --- helpers -----------------------------------------------------------------


def _grid_mesh(cross, local, pod=None):
    n = cross * local * (pod or 1)
    if pod:
        return build_three_level_mesh(pod, cross, local,
                                      jax.devices()[:n]), n
    return build_hierarchical_mesh(local, jax.devices()[:n]), n


def _run(mesh, fn, x, axes):
    spec = P(tuple(axes))
    return jax.jit(
        _shard_map(fn, mesh, in_specs=(spec,), out_specs=spec)
    )(x)


GRIDS = [
    pytest.param((2, 1, None), id="2ranks-2x1"),
    pytest.param((2, 2, None), id="4ranks-2x2"),
    pytest.param((2, 4, None), id="8ranks-2x4"),
    pytest.param((2, 2, 2), id="8ranks-2x2x2-threelevel"),
]


def _axes(pod):
    return (("pod",) if pod else ()) + ("cross", "local")


# --- model -------------------------------------------------------------------


def test_synthetic_model_shapes():
    m = synthetic_model(local=4, cross=2, generation="v5e")
    assert [h.name for h in m.hops] == ["dcn", "ici"]
    assert m.size == 8 and m.levels == 2 and m.eligible
    assert m.axes == ("cross", "local")
    m3 = synthetic_model(local=2, cross=2, pod=2)
    assert [h.name for h in m3.hops] == ["dcn-pod", "dcn", "ici"]
    assert m3.size == 8
    flat = synthetic_model(local=8)
    assert flat.levels == 1 and not flat.eligible


def test_generation_defaults_order():
    """The defaults only have to rank hops correctly: ICI strictly faster
    than DCN, DCN strictly faster than inter-pod DCN, per generation."""
    for gen, hops in GENERATION_DEFAULTS.items():
        assert hops["ici"][0] > hops["dcn"][0] >= hops["dcn-pod"][0], gen


def test_model_json_stable_and_roundtrips():
    m = synthetic_model(local=4, cross=2, generation="v4")
    assert m.to_json() == m.to_json()
    back = InterconnectModel.from_dict(json.loads(m.to_json()))
    assert back.hops == m.hops


def test_model_override_inline_json(monkeypatch):
    m = synthetic_model(local=4, cross=2, generation="v5e")
    monkeypatch.setenv(
        "HOROVOD_TOPOLOGY_MODEL",
        '{"dcn": {"bandwidth_gbps": 99.0, "latency_us": 7.0}}',
    )
    out = apply_override(m)
    assert out.hop("dcn").bandwidth_gbps == 99.0
    assert out.hop("dcn").latency_us == 7.0
    assert out.hop("ici") == m.hop("ici")
    assert out.source.endswith("+override")


def test_model_override_full_document(tmp_path, monkeypatch):
    doc = {
        "generation": "custom",
        "hops": [
            {"name": "dcn", "axis": "cross", "size": 2,
             "bandwidth_gbps": 10.0, "latency_us": 80.0},
            {"name": "ici", "axis": "local", "size": 4,
             "bandwidth_gbps": 400.0, "latency_us": 0.5},
        ],
    }
    path = tmp_path / "model.json"
    path.write_text(json.dumps(doc))
    monkeypatch.setenv("HOROVOD_TOPOLOGY_MODEL", str(path))
    out = apply_override(synthetic_model(local=8))
    assert out.generation == "custom"
    assert out.hop("ici").bandwidth_gbps == 400.0
    assert out.eligible  # >1 hop in the replacement document


def test_model_override_unknown_hop_raises(monkeypatch):
    monkeypatch.setenv(
        "HOROVOD_TOPOLOGY_MODEL", '{"icl": {"bandwidth_gbps": 1.0}}'
    )
    with pytest.raises(ValueError, match="icl"):
        apply_override(synthetic_model(local=4, cross=2))


def test_model_from_topology_homogeneity_gate():
    from horovod_tpu.common.topology import Topology

    good = Topology(rank=0, size=8, local_rank=0, local_size=4,
                    cross_rank=0, cross_size=2, is_homogeneous=True)
    m = model_from_topology(good)
    assert m.eligible and m.levels == 2
    ragged = Topology(rank=0, size=8, local_rank=0, local_size=4,
                      cross_rank=0, cross_size=2, is_homogeneous=False)
    m = model_from_topology(ragged)
    assert not m.eligible and m.levels == 1
    single = Topology(rank=0, size=8, local_rank=0, local_size=8,
                      cross_rank=0, cross_size=1, is_homogeneous=True)
    assert not model_from_topology(single).eligible


def test_detect_generation_env(monkeypatch):
    from horovod_tpu.topo import detect_generation

    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5litepod-16")
    assert detect_generation() == "v5e"
    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v4-32")
    assert detect_generation() == "v4"
    monkeypatch.delenv("TPU_ACCELERATOR_TYPE", raising=False)
    monkeypatch.delenv("TPU_TYPE", raising=False)
    assert detect_generation() == "generic"


# --- plan selection ----------------------------------------------------------


def test_plan_selection_by_payload():
    m = synthetic_model(local=4, cross=2, generation="v5e")
    small = select_plan(m, "allreduce", 1024)
    large = select_plan(m, "allreduce", 256 << 20)
    assert small.algorithm == "two-level"
    assert large.algorithm == "split"
    assert large.split_bytes[0] + large.split_bytes[1] == 256 << 20
    # Bandwidth-proportional split: the ICI-share bucket dominates.
    assert large.split_bytes[0] > large.split_bytes[1]


def test_plan_single_level_ring_vs_halving():
    m = synthetic_model(local=8, generation="v5e")
    assert select_plan(m, "allreduce", 64).algorithm == "recursive-halving"
    # A non-power-of-two hop cannot run halving-doubling.
    m6 = synthetic_model(local=6, generation="v5e")
    assert select_plan(m6, "allreduce", 64).algorithm == "ring"


def test_plan_hierarchical_dcn_bytes_below_flat():
    m = synthetic_model(local=4, cross=2, generation="v5e")
    for coll in ("allreduce", "allgather", "reducescatter", "alltoall",
                 "broadcast"):
        plan = select_plan(m, coll, 16 << 20)
        assert plan.algorithm != "flat", coll
        flat_cands = {
            "allreduce": K._candidates_allreduce(m, 16 << 20, ReduceOp.SUM),
            "allgather": K._candidates_allgather(m, 16 << 20),
            "reducescatter": K._candidates_reducescatter(m, 16 << 20),
            "alltoall": K._candidates_alltoall(m, 16 << 20),
            "broadcast": K._candidates_broadcast(m, 16 << 20),
        }[coll]["flat"]
        flat_dcn = sum(s.bytes_on_wire for s in flat_cands
                       if "dcn" in s.hop)
        hier_dcn = sum(v for k, v in plan.bytes_per_hop.items()
                       if "dcn" in k)
        assert hier_dcn < flat_dcn, coll


def test_plan_min_two_level_product_flat():
    m = synthetic_model(local=4, cross=2)
    assert select_plan(m, "allreduce", 1 << 20,
                       op=ReduceOp.MIN).algorithm == "two-level"
    assert select_plan(m, "allreduce", 1 << 20,
                       op=ReduceOp.PRODUCT).algorithm == "flat"


def test_plan_ineligible_model_stays_flat():
    """The homogeneity gate collapses the hierarchy: no two-level/split
    plan may come back — only single-level algorithms over the flattened
    hop (whose ring/halving labels the production paths lower via the
    native collective)."""
    m = synthetic_model(local=4, cross=2, eligible=False)
    plan = select_plan(m, "allreduce", 64 << 20)
    assert plan.algorithm in ("flat", "ring", "recursive-halving")
    assert plan.hop_sizes == (8,)
    assert all(s.hop != "ici" or "dcn" not in s.hop for s in plan.stages)


def test_plan_unknown_collective_raises():
    with pytest.raises(ValueError, match="unknown collective"):
        select_plan(synthetic_model(local=4), "scan", 1024)


def test_collective_plan_api():
    import horovod_tpu as hvd

    out = hvd.collective_plan("allreduce", 1 << 20)
    assert out["collective"] == "allreduce"
    assert "model" in out and "stages" in out
    # jax-binding alias returns the same verdict.
    assert hvdj.collective_plan("allreduce", 1 << 20)["algorithm"] == (
        out["algorithm"]
    )


# --- lowering equality vs flat at 2/4/8 ranks --------------------------------


@pytest.mark.parametrize("grid", GRIDS)
def test_allreduce_two_level_matches_flat(grid):
    cross, local, pod = grid
    mesh, n = _grid_mesh(cross, local, pod)
    axes = _axes(pod)
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(n, 13, 3), jnp.float32)
    flat = _run(mesh, lambda t: jax.lax.psum(t[0], axes)[None], x, axes)
    out = _run(mesh, lambda t: K.lower_allreduce(
        t[0], axes, op=ReduceOp.SUM, algorithm="two-level")[None], x, axes)
    np.testing.assert_allclose(np.asarray(out), np.asarray(flat),
                               rtol=2e-5)
    # AVERAGE folds the divisor in.
    outa = _run(mesh, lambda t: K.lower_allreduce(
        t[0], axes, op=ReduceOp.AVERAGE, algorithm="two-level")[None],
        x, axes)
    np.testing.assert_allclose(np.asarray(outa), np.asarray(flat) / n,
                               rtol=2e-5)


@pytest.mark.parametrize("grid", GRIDS)
def test_allreduce_int_sum_bitwise(grid):
    """Integer SUM regroupings commute exactly: the hierarchical result
    must be bit-identical to the flat psum."""
    cross, local, pod = grid
    mesh, n = _grid_mesh(cross, local, pod)
    axes = _axes(pod)
    x = jnp.asarray(
        np.random.RandomState(2).randint(-1000, 1000, (n, 17)), jnp.int32
    )
    flat = _run(mesh, lambda t: jax.lax.psum(t[0], axes)[None], x, axes)
    out = _run(mesh, lambda t: K.lower_allreduce(
        t[0], axes, op=ReduceOp.SUM, algorithm="two-level")[None], x, axes)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(flat))


@pytest.mark.parametrize("grid", GRIDS)
def test_allreduce_min_max_bitwise(grid):
    cross, local, pod = grid
    mesh, n = _grid_mesh(cross, local, pod)
    axes = _axes(pod)
    x = jnp.asarray(np.random.RandomState(3).randn(n, 9), jnp.float32)
    for op, ref in ((ReduceOp.MIN, jax.lax.pmin),
                    (ReduceOp.MAX, jax.lax.pmax)):
        flat = _run(mesh, lambda t, ref=ref: ref(t[0], axes)[None], x, axes)
        out = _run(mesh, lambda t, op=op: K.lower_allreduce(
            t[0], axes, op=op, algorithm="two-level")[None], x, axes)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(flat))


@pytest.mark.parametrize("nranks", [2, 4, 8])
def test_allreduce_split_matches_flat(nranks):
    """The FlexLink ICI+DCN concurrent-split mode: two pipelined
    hierarchical buckets concatenate to the flat reduction."""
    mesh, n = _grid_mesh(2, nranks // 2)
    axes = ("cross", "local")
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(n, 31), jnp.float32)
    flat = _run(mesh, lambda t: jax.lax.psum(t[0], axes)[None], x, axes)
    frac = K.split_fractions(
        synthetic_model(local=nranks // 2, cross=2, generation="v5e")
    )[0]
    out = _run(mesh, lambda t: K.lower_allreduce(
        t[0], axes, op=ReduceOp.SUM, algorithm="split",
        split_fraction=frac)[None], x, axes)
    np.testing.assert_allclose(np.asarray(out), np.asarray(flat),
                               rtol=2e-5)


@pytest.mark.parametrize("nranks", [2, 4, 8])
@pytest.mark.parametrize("algorithm", ["ring", "recursive-halving"])
def test_allreduce_explicit_schedules_match_flat(nranks, algorithm):
    """The explicit single-hop ppermute schedules (ring reduce-scatter +
    allgather; MPICH halving-doubling)."""
    mesh = build_mesh({"data": nranks}, jax.devices()[:nranks])
    axes = ("data",)
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.randn(nranks, 11), jnp.float32)
    flat = _run(mesh, lambda t: jax.lax.psum(t[0], "data")[None], x, axes)
    out = _run(mesh, lambda t: K.lower_allreduce(
        t[0], axes, op=ReduceOp.SUM, algorithm=algorithm)[None], x, axes)
    np.testing.assert_allclose(np.asarray(out), np.asarray(flat),
                               rtol=2e-5)
    # MIN rides the same schedules bitwise.
    fmin = _run(mesh, lambda t: jax.lax.pmin(t[0], "data")[None], x, axes)
    omin = _run(mesh, lambda t: K.lower_allreduce(
        t[0], axes, op=ReduceOp.MIN, algorithm=algorithm)[None], x, axes)
    np.testing.assert_array_equal(np.asarray(omin), np.asarray(fmin))


def test_recursive_halving_rejects_non_power_of_two():
    """The halving-doubling schedule needs power-of-two hops: the
    lowering guards it at trace time and the planner never offers it."""
    mesh = build_mesh({"data": 6}, jax.devices()[:6])
    x = jnp.zeros((6, 4), jnp.float32)
    with pytest.raises(ValueError, match="power-of-two"):
        jax.jit(_shard_map(
            lambda t: K.lower_allreduce(
                t[0], ("data",), op=ReduceOp.SUM,
                algorithm="recursive-halving")[None],
            mesh, in_specs=(P("data"),), out_specs=P("data"),
        ))(x)
    assert select_plan(
        synthetic_model(local=6), "allreduce", 64
    ).algorithm == "ring"


@pytest.mark.parametrize("grid", GRIDS)
def test_allgather_matches_flat_bitwise(grid):
    cross, local, pod = grid
    mesh, n = _grid_mesh(cross, local, pod)
    axes = _axes(pod)
    x = jnp.asarray(np.random.RandomState(6).randn(n * 2, 5), jnp.float32)
    ref = _run(mesh, lambda t: K.lower_allgather(t, axes, algorithm="flat"),
               x, axes)
    out = _run(mesh, lambda t: K.lower_allgather(
        t, axes, algorithm="two-level"), x, axes)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


@pytest.mark.parametrize("grid", GRIDS)
def test_reducescatter_matches_flat(grid):
    cross, local, pod = grid
    mesh, n = _grid_mesh(cross, local, pod)
    axes = _axes(pod)
    rng = np.random.RandomState(7)
    x = jnp.asarray(rng.randn(n * n * 3, 2), jnp.float32)
    ref = _run(mesh, lambda t: K.lower_reducescatter(
        t, axes, op=ReduceOp.SUM, algorithm="flat"), x, axes)
    out = _run(mesh, lambda t: K.lower_reducescatter(
        t, axes, op=ReduceOp.SUM, algorithm="two-level"), x, axes)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5)
    # int32: regrouped integer sums are exact.
    xi = jnp.asarray(rng.randint(-50, 50, (n * n, 3)), jnp.int32)
    refi = _run(mesh, lambda t: K.lower_reducescatter(
        t, axes, op=ReduceOp.SUM, algorithm="flat"), xi, axes)
    outi = _run(mesh, lambda t: K.lower_reducescatter(
        t, axes, op=ReduceOp.SUM, algorithm="two-level"), xi, axes)
    np.testing.assert_array_equal(np.asarray(outi), np.asarray(refi))


@pytest.mark.parametrize("grid", GRIDS)
def test_broadcast_matches_flat_all_roots(grid):
    cross, local, pod = grid
    mesh, n = _grid_mesh(cross, local, pod)
    axes = _axes(pod)
    xb = jnp.tile(jnp.arange(n, dtype=jnp.float32).reshape(n, 1), (1, 7))
    for root in {0, n - 1, n // 2}:
        expected = np.full((n, 7), root, np.float32)
        for alg in ("two-level", "two-level-sa"):
            out = _run(mesh, lambda t, r=root, a=alg: K.lower_broadcast(
                t[0], axes, root_rank=r, algorithm=a)[None], xb, axes)
            np.testing.assert_array_equal(
                np.asarray(out).reshape(n, 7), expected
            ), (root, alg)


@pytest.mark.parametrize("grid", GRIDS)
def test_alltoall_matches_flat_bitwise(grid):
    cross, local, pod = grid
    mesh, n = _grid_mesh(cross, local, pod)
    axes = _axes(pod)
    x = jnp.arange(n * n * 2 * 3, dtype=jnp.float32).reshape(n * n * 2, 3)
    ref = _run(mesh, lambda t: K.lower_alltoall(t, axes, algorithm="flat"),
               x, axes)
    out = _run(mesh, lambda t: K.lower_alltoall(
        t, axes, algorithm="two-level"), x, axes)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


# --- satellite regressions ---------------------------------------------------


def test_hierarchical_allreduce_rejects_unsupported_ops():
    """Regression: op=PRODUCT used to silently return a SUM."""
    mesh = build_hierarchical_mesh(local_size=4)
    x = jnp.ones((8, 4), jnp.float32)
    with pytest.raises(ValueError, match="PRODUCT"):
        jax.jit(_shard_map(
            lambda t: C.hierarchical_allreduce(
                t[0], op=ReduceOp.PRODUCT)[None],
            mesh, in_specs=(P(("cross", "local")),),
            out_specs=P(("cross", "local")),
        ))(x)


def test_hierarchical_allreduce_min_max_real():
    """MIN/MAX used to silently SUM; now they lower per-hop, bitwise."""
    mesh, n = _grid_mesh(2, 4)
    axes = ("cross", "local")
    x = jnp.asarray(np.random.RandomState(8).randn(n, 6), jnp.float32)
    for op, ref in ((ReduceOp.MIN, jax.lax.pmin),
                    (ReduceOp.MAX, jax.lax.pmax)):
        flat = _run(mesh, lambda t, ref=ref: ref(t[0], axes)[None], x, axes)
        out = _run(mesh, lambda t, op=op: C.hierarchical_allreduce(
            t[0], op=op)[None], x, axes)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(flat))


def test_broadcast_out_of_range_root_raises():
    """Regression: the virtual-rank modulo silently wrapped
    root_rank >= axis_size onto the wrong root."""
    mesh = build_mesh({"data": 8})
    x = jnp.zeros((8, 4), jnp.float32)
    for bad in (8, -1, 100):
        with pytest.raises(ValueError, match="size 8"):
            jax.jit(_shard_map(
                lambda t, b=bad: C.broadcast(
                    t[0], root_rank=b, axis_name="data")[None],
                mesh, in_specs=(P("data"),), out_specs=P("data"),
            ))(x)


def test_hierarchical_collective_variants_exposed():
    """Every collective now has a compositor-backed hierarchical variant
    reachable from the jax binding."""
    mesh, n = _grid_mesh(2, 4)
    axes = ("cross", "local")
    x = jnp.asarray(np.random.RandomState(9).randn(n * 2, 3), jnp.float32)
    ref = _run(mesh, lambda t: K.lower_allgather(t, axes, algorithm="flat"),
               x, axes)
    out = _run(mesh, lambda t: hvdj.hierarchical_allgather(t), x, axes)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    xr = jnp.asarray(np.random.RandomState(10).randn(n * n, 2), jnp.float32)
    refr = _run(mesh, lambda t: K.lower_reducescatter(
        t, axes, op=ReduceOp.SUM, algorithm="flat"), xr, axes)
    outr = _run(mesh, lambda t: hvdj.hierarchical_reducescatter(t), xr, axes)
    np.testing.assert_allclose(np.asarray(outr), np.asarray(refr),
                               rtol=2e-5)
    xa = jnp.arange(n * n, dtype=jnp.float32).reshape(n * n, 1)
    refa = _run(mesh, lambda t: K.lower_alltoall(t, axes, algorithm="flat"),
                xa, axes)
    outa = _run(mesh, lambda t: hvdj.hierarchical_alltoall(t), xa, axes)
    np.testing.assert_array_equal(np.asarray(outa), np.asarray(refa))
    xb = jnp.tile(jnp.arange(n, dtype=jnp.float32).reshape(n, 1), (1, 4))
    outb = _run(mesh, lambda t: hvdj.hierarchical_broadcast(
        t[0], root_rank=5)[None], xb, axes)
    np.testing.assert_array_equal(
        np.asarray(outb).reshape(n, 4), np.full((n, 4), 5, np.float32)
    )


def test_mesh_fallback_warns_and_counts(monkeypatch, caplog):
    """Satellite: the bare-reshape fallback must be loud — warning naming
    the exception plus an hvd_mesh_fallback_total increment."""
    import logging

    from horovod_tpu import metrics
    from jax.experimental import mesh_utils

    def boom(*a, **k):
        raise RuntimeError("no contiguous submesh")

    monkeypatch.setattr(mesh_utils, "create_device_mesh", boom)
    metrics.install(True)
    try:
        with caplog.at_level(logging.WARNING, logger="horovod_tpu"):
            mesh = build_mesh({"data": 8})
        assert mesh.shape["data"] == 8  # still works
        msgs = [r.getMessage() for r in caplog.records]
        assert any("create_device_mesh failed" in m
                   and "RuntimeError" in m
                   and "no contiguous submesh" in m
                   and "ICI adjacency" in m for m in msgs), msgs
        snap = metrics.snapshot()
        series = snap["hvd_mesh_fallback_total"]["series"]
        assert any(s["value"] >= 1 for s in series), series
        assert any(
            s["labels"].get("error") == "RuntimeError" for s in series
        ), series
    finally:
        metrics.reset()


# --- streamed / compiled wiring ----------------------------------------------


def _mlp_loss(params, batch):
    xb, yb = batch
    h = jnp.tanh(xb @ params["l0"]["w"])
    h = h @ params["l1"]["w"]
    return jnp.mean((h - yb) ** 2)


def _mlp_fixtures(n):
    import optax

    rng = np.random.RandomState(0)
    params = {
        "l0": {"w": jnp.asarray(rng.randn(16, 16), jnp.float32)},
        "l1": {"w": jnp.asarray(rng.randn(16, 16), jnp.float32)},
    }
    tx = optax.sgd(0.01)
    batch = (jnp.asarray(rng.randn(n, 16), jnp.float32),
             jnp.asarray(rng.randn(n, 16), jnp.float32))
    return params, tx, tx.init(params), batch


def test_auto_hierarchical_overlap_step_matches_flat():
    """make_train_step(overlap=True, hierarchical="auto") on a
    multi-slice mesh goes hierarchical per bucket and stays numerically
    equal to the flat step."""
    params, tx, opt, batch = _mlp_fixtures(8)
    flat_step = hvdj.make_train_step(_mlp_loss, tx, build_mesh(),
                                     donate=False)
    p1, _, l1 = flat_step(params, opt, batch)
    mesh2 = build_hierarchical_mesh(local_size=4)
    auto_step = hvdj.make_train_step(
        _mlp_loss, tx, mesh2, donate=False, overlap=True,
        hierarchical="auto",
    )
    p2, _, l2 = auto_step(params, opt, batch)
    assert float(l1) == pytest.approx(float(l2), rel=1e-6)
    for k in ("l0", "l1"):
        np.testing.assert_allclose(
            np.asarray(p1[k]["w"]), np.asarray(p2[k]["w"]), rtol=2e-5
        )


def test_auto_hierarchical_three_level_step_matches_flat():
    params, tx, opt, batch = _mlp_fixtures(8)
    flat_step = hvdj.make_train_step(_mlp_loss, tx, build_mesh(),
                                     donate=False)
    p1, _, _ = flat_step(params, opt, batch)
    mesh3 = build_three_level_mesh(2, 2, 2)
    assert hierarchy_axes(mesh3) == ("pod", "cross", "local")
    step3 = hvdj.make_train_step(_mlp_loss, tx, mesh3, donate=False,
                                 hierarchical="auto")
    p3, _, _ = step3(params, opt, batch)
    np.testing.assert_allclose(
        np.asarray(p1["l0"]["w"]), np.asarray(p3["l0"]["w"]), rtol=2e-5
    )


def test_auto_on_flat_mesh_stays_flat():
    """hierarchical="auto" over a plain data mesh must not change the
    program: the lowering stays a single all-reduce (no reduce-scatter
    stage)."""
    params, tx, opt, batch = _mlp_fixtures(8)
    step = hvdj.make_train_step(_mlp_loss, tx, build_mesh(), donate=False,
                                hierarchical="auto")
    avals = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
        (params, opt, batch),
    )
    text = step.lower(*avals).as_text()
    assert "reduce-scatter" not in text and "reduce_scatter" not in text


def test_auto_hierarchical_lowering_contains_reduce_scatter():
    """The "auto" path on a hierarchical mesh must actually change the
    program (not just relabel it)."""
    params, tx, opt, batch = _mlp_fixtures(8)
    mesh2 = build_hierarchical_mesh(local_size=4)
    step = hvdj.make_train_step(_mlp_loss, tx, mesh2, donate=False,
                                hierarchical="auto")
    avals = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
        (params, opt, batch),
    )
    text = step.lower(*avals).as_text()
    assert "reduce_scatter" in text or "reduce-scatter" in text


def test_streamed_planned_records_plan_metrics():
    from horovod_tpu import metrics

    params, tx, opt, batch = _mlp_fixtures(8)
    mesh2 = build_hierarchical_mesh(local_size=4)
    metrics.install(True)
    try:
        step = hvdj.make_train_step(
            _mlp_loss, tx, mesh2, donate=False, overlap=True,
            hierarchical="auto",
        )
        step(params, opt, batch)
        snap = metrics.snapshot()
        assert "hvd_topo_plan_info" in snap, sorted(snap)
        info = snap["hvd_topo_plan_info"]["series"]
        assert any(
            s["labels"].get("collective") == "allreduce"
            and s["labels"].get("where") == "stream"
            for s in info
        ), info
        hops = snap["hvd_topo_bytes_per_hop"]["series"]
        assert {s["labels"].get("hop") for s in hops} >= {"ici", "dcn"}, hops
    finally:
        metrics.reset()


def test_distributed_optimizer_auto_without_mesh_is_safe():
    """DistributedOptimizer(hierarchical="auto") with a single-process
    (ineligible) detected topology must resolve to the flat path and
    work over a plain data mesh."""
    import optax

    params, tx, opt, batch = _mlp_fixtures(8)
    dtx = hvdj.DistributedOptimizer(tx, hierarchical="auto")
    mesh = build_mesh()

    def step(p, o, b):
        loss, grads = jax.value_and_grad(_mlp_loss)(p, b)
        updates, o2 = dtx.update(grads, o, p)
        return optax.apply_updates(p, updates), o2, loss

    fn = jax.jit(_shard_map(
        step, mesh, in_specs=(P(), P(), P("data")), out_specs=P(),
    ))
    p2, _, _ = fn(params, dtx.init(params), batch)
    flat_step = hvdj.make_train_step(_mlp_loss, tx, mesh, donate=False)
    p1, _, _ = flat_step(params, tx.init(params), batch)
    np.testing.assert_allclose(
        np.asarray(p1["l0"]["w"]), np.asarray(p2["l0"]["w"]), rtol=1e-6
    )
