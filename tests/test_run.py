"""Launcher unit tests (pure, mock-level) — parity with the reference's
``test/test_run.py``: arg parsing, host parsing, allocation, config-file
precedence, env synthesis."""

import os
import textwrap

import pytest

from horovod_tpu.run import parse_args, check_build
from horovod_tpu.run import config_parser, launcher


def test_parse_hosts():
    assert launcher.parse_hosts("a:2,b:4") == [("a", 2), ("b", 4)]
    assert launcher.parse_hosts("localhost") == [("localhost", 1)]


def test_parse_hostfile(tmp_path):
    p = tmp_path / "hosts"
    p.write_text(
        textwrap.dedent(
            """
            # comment
            nodeA slots=2
            nodeB slots=4  # trailing
            nodeC
            """
        )
    )
    assert launcher.parse_hostfile(str(p)) == [
        ("nodeA", 2), ("nodeB", 4), ("nodeC", 1)
    ]


def test_allocate_two_hosts():
    slots = launcher.allocate([("a", 2), ("b", 2)], 4)
    assert [s.rank for s in slots] == [0, 1, 2, 3]
    assert [s.hostname for s in slots] == ["a", "a", "b", "b"]
    assert [s.local_rank for s in slots] == [0, 1, 0, 1]
    assert all(s.local_size == 2 for s in slots)
    assert [s.cross_rank for s in slots] == [0, 0, 1, 1]
    assert all(s.cross_size == 2 for s in slots)


def test_allocate_insufficient_slots():
    with pytest.raises(ValueError):
        launcher.allocate([("a", 1)], 3)


def test_parse_args_knobs():
    args = parse_args(
        [
            "-np", "4", "-H", "localhost:4", "--fusion-threshold-mb", "32",
            "--cycle-time-ms", "3.5", "--autotune", "--timeline-filename",
            "/tmp/tl.json", "python", "train.py",
        ]
    )
    assert args.num_proc == 4
    assert args.fusion_threshold_mb == 32
    assert args.cycle_time_ms == 3.5
    assert args.autotune is True
    assert args.command == ["python", "train.py"]


def test_set_env_from_args():
    args = parse_args(
        ["-np", "2", "--fusion-threshold-mb", "32", "--cycle-time-ms", "2",
         "--log-level", "debug", "x"]
    )
    env = config_parser.set_env_from_args({}, args)
    assert env["HOROVOD_FUSION_THRESHOLD"] == str(32 * 1024 * 1024)
    assert env["HOROVOD_CYCLE_TIME"] == "2.0"
    assert env["HOROVOD_LOG_LEVEL"] == "debug"


def test_config_file_with_cli_override(tmp_path):
    cfg = tmp_path / "cfg.yaml"
    cfg.write_text(
        textwrap.dedent(
            """
            fusion:
              threshold-mb: 16
              cycle-time-ms: 7.5
            autotune:
              enabled: true
            timeline:
              filename: /tmp/from_yaml.json
            """
        )
    )
    # CLI sets cycle-time explicitly: must beat YAML; others come from YAML.
    args = parse_args(
        ["-np", "2", "--config-file", str(cfg), "--cycle-time-ms", "2.0", "x"]
    )
    assert args.cycle_time_ms == 2.0
    assert args.fusion_threshold_mb == 16
    assert args.autotune is True
    assert args.timeline_filename == "/tmp/from_yaml.json"


def test_check_build_output():
    out = check_build()
    assert "[X] JAX" in out
    assert "XLA" in out
    assert "[ ] MPI" in out


def test_build_rank_env():
    slot = launcher.SlotInfo("localhost", 1, 4, 1, 2, 0, 2)
    env = launcher.build_rank_env(slot, {"PATH": "/bin"}, "127.0.0.1", 9999,
                                  "127.0.0.1:8888")
    assert env["HOROVOD_RANK"] == "1"
    assert env["HOROVOD_SIZE"] == "4"
    assert env["HOROVOD_LOCAL_RANK"] == "1"
    assert env["HOROVOD_LOCAL_SIZE"] == "2"
    assert env["HOROVOD_CONTROLLER_ADDR"] == "127.0.0.1"
    assert env["HOROVOD_CONTROLLER_PORT"] == "9999"
    assert env["HOROVOD_JAX_COORDINATOR"] == "127.0.0.1:8888"
    assert env["PATH"] == "/bin"


def test_tpu_pod_allocation(monkeypatch):
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "w0,w1,w2,w3")
    slots = launcher.tpu_pod_allocation()
    assert len(slots) == 4
    assert [s.hostname for s in slots] == ["w0", "w1", "w2", "w3"]
    assert all(s.local_size == 1 for s in slots)
    assert [s.cross_rank for s in slots] == [0, 1, 2, 3]


def test_kv_store_roundtrip():
    from horovod_tpu.run.http_server import KVStoreClient, KVStoreServer

    server = KVStoreServer()
    port = server.start()
    try:
        client = KVStoreClient("127.0.0.1", port)
        client.put("global", "k1", b"hello")
        assert client.get("global", "k1") == b"hello"
        assert client.get("global", "missing") is None
        assert client.wait("global", "k1") == b"hello"
    finally:
        server.stop()


def test_disable_cache_and_start_timeout_flags():
    from horovod_tpu.run.run import parse_args

    args = parse_args(["-np", "2", "--disable-cache",
                       "--start-timeout", "45", "python", "x.py"])
    assert args.disable_cache is True
    assert args.start_timeout == 45


def test_ssh_preflight_unreachable_fails_fast(monkeypatch, tmp_path):
    """Reference run/run.py:62-115 parity: a dead host yields one clear
    per-host error before any rank launches; ssh is mocked."""
    import subprocess

    from horovod_tpu.run import launcher
    from horovod_tpu.run.disk_cache import DiskCache

    calls = []

    def fake_run(cmd, **kw):
        calls.append(cmd)
        host = cmd[-2]

        class R:
            returncode = 0 if host == "good-host" else 255

        return R()

    monkeypatch.setattr(subprocess, "run", fake_run)
    cache = DiskCache(str(tmp_path / "c.json"), ttl_seconds=300)
    with pytest.raises(RuntimeError) as e:
        launcher.check_hosts_reachable(
            ["good-host", "bad-host", "localhost"], cache=cache
        )
    assert "bad-host" in str(e.value)
    assert "good-host" not in str(e.value)
    # localhost is never probed.
    assert all("localhost" not in c for c in calls)


def test_ssh_preflight_caches_successes(monkeypatch, tmp_path):
    import subprocess

    from horovod_tpu.run import launcher
    from horovod_tpu.run.disk_cache import DiskCache

    calls = []

    def fake_run(cmd, **kw):
        calls.append(cmd)

        class R:
            returncode = 0

        return R()

    monkeypatch.setattr(subprocess, "run", fake_run)
    cache = DiskCache(str(tmp_path / "c.json"), ttl_seconds=300)
    launcher.check_hosts_reachable(["h1", "h2"], cache=cache)
    assert len(calls) == 2
    # Second launch: cache hits, no ssh spawned.
    launcher.check_hosts_reachable(["h1", "h2"], cache=cache)
    assert len(calls) == 2
    # Expired TTL re-probes.
    expired = DiskCache(str(tmp_path / "c.json"), ttl_seconds=0)
    launcher.check_hosts_reachable(["h1"], cache=expired)
    assert len(calls) == 3


def test_ssh_preflight_failure_not_cached(monkeypatch, tmp_path):
    import subprocess

    from horovod_tpu.run import launcher
    from horovod_tpu.run.disk_cache import DiskCache

    rc = {"v": 255}
    calls = []

    def fake_run(cmd, **kw):
        calls.append(cmd)

        class R:
            returncode = rc["v"]

        return R()

    monkeypatch.setattr(subprocess, "run", fake_run)
    cache = DiskCache(str(tmp_path / "c.json"), ttl_seconds=300)
    with pytest.raises(RuntimeError):
        launcher.check_hosts_reachable(["flaky"], cache=cache)
    # Host fixed: must re-probe (failures are never cached) and pass.
    rc["v"] = 0
    launcher.check_hosts_reachable(["flaky"], cache=cache)
    assert len(calls) == 2


def test_ssh_fanout_end_to_end_via_shim(tmp_path):
    """Two-'host' end-to-end through the REAL ssh fan-out (VERDICT r4 #8:
    the ssh path + ring NIC probe had only unit/mock coverage). A PATH
    shim stands in for the ssh binary — it consumes the option prefix and
    execs the remote command string locally — so every production layer
    runs for real: hostfile parsing, the BatchMode pre-flight, the
    HMAC-authed ring NIC probe over 'hosta'/'hostb' (whose probed
    127.0.0.1 answer is the ONLY reason the unresolvable fake hostnames
    can rendezvous — exercising HOROVOD_PROBED_CONTROLLER_ADDR for
    real), build_remote_command's cd+env-prefix quoting, and the fan-out
    kill/collect loop. For real two-container coverage see
    docker-compose.ssh.yml + tools/ssh_e2e_compose.sh."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    shim_dir = tmp_path / "bin"
    shim_dir.mkdir()
    shim = shim_dir / "ssh"
    shim.write_text(textwrap.dedent("""\
        #!/bin/sh
        # Fake ssh: swallow options, record the target host, run locally.
        while [ $# -gt 0 ]; do
          case "$1" in
            -o|-p) shift 2 ;;
            -*) shift ;;
            *) break ;;
          esac
        done
        host="$1"; shift
        echo "$host" >> "$SSH_SHIM_LOG"
        exec /bin/sh -c "$*"
        """))
    shim.chmod(0o755)

    worker = tmp_path / "worker.py"
    worker.write_text(textwrap.dedent("""\
        import os
        import jax
        jax.config.update('jax_platforms', 'cpu')
        import numpy as np
        import horovod_tpu as hvd
        hvd.init()
        import jax.numpy as jnp
        s = hvd.allreduce(jnp.full((2,), float(hvd.rank() + 1)),
                          op=hvd.Sum, name='e2e')
        print('SSHE2E', hvd.rank(), hvd.size(), float(np.asarray(s)[0]),
              flush=True)
        hvd.shutdown()
        """))

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PATH"] = f"{shim_dir}{os.pathsep}" + env.get("PATH", "")
    env["SSH_SHIM_LOG"] = str(tmp_path / "ssh_calls.log")
    env["PYTHONPATH"] = os.pathsep.join(
        [repo, env.get("PYTHONPATH", "")]).rstrip(os.pathsep)
    out_dir = tmp_path / "out"
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", "2",
         "-H", "hosta:1,hostb:1", "--disable-cache",
         "--output-dir", str(out_dir), sys.executable, str(worker)],
        env=env, cwd=repo, capture_output=True, timeout=240, text=True,
    )
    outs = {}
    for fn in os.listdir(out_dir):
        outs[fn] = (out_dir / fn).read_text()
    assert proc.returncode == 0, (proc.stdout, proc.stderr, outs)
    lines = sorted(
        l for o in outs.values() for l in o.splitlines()
        if l.startswith("SSHE2E")
    )
    # Sum over ranks: 1.0 + 2.0 = 3.0 on both.
    assert lines == ["SSHE2E 0 2 3.0", "SSHE2E 1 2 3.0"], (lines, outs)
    # Both fake hosts went through the ssh binary (pre-flight + probe +
    # fan-out), not through any local-spawn shortcut.
    ssh_hosts = set(
        (tmp_path / "ssh_calls.log").read_text().split()
    )
    assert {"hosta", "hostb"} <= ssh_hosts, ssh_hosts
