"""Unit tests for the gated mxnet/spark integrations using mocked engines
(the reference tests its launcher with mocks the same way,
``test/test_run.py``). Each test runs in a subprocess so the fake modules
never leak into this interpreter's import caches.
"""

import os
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_sub(body: str, timeout=300):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.pathsep.join(
        [REPO, env.get("PYTHONPATH", "")]
    ).rstrip(os.pathsep)
    proc = subprocess.run(
        [sys.executable, "-c", textwrap.dedent(body)],
        capture_output=True, timeout=timeout, text=True, env=env,
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr[-3000:])
    return proc.stdout


FAKE_MXNET = """
    import sys, types
    import numpy as np

    mx = types.ModuleType("mxnet")

    class FakeND:
        def __init__(self, arr, ctx="cpu(0)", dtype=None):
            self._a = np.asarray(arr, dtype=dtype)
            self.context = ctx
        @property
        def dtype(self):
            return self._a.dtype
        def asnumpy(self):
            return self._a
        def __setitem__(self, k, v):
            self._a[k] = v._a if isinstance(v, FakeND) else np.asarray(v)
        def __getitem__(self, k):
            return self._a[k]

    nd = types.ModuleType("mxnet.nd")
    nd.array = lambda a, ctx=None, dtype=None: FakeND(a, ctx or "cpu(0)", dtype)
    mx.nd = nd

    optimizer = types.ModuleType("mxnet.optimizer")
    class Optimizer:
        pass
    optimizer.Optimizer = Optimizer
    mx.optimizer = optimizer

    gluon = types.ModuleType("mxnet.gluon")
    class Trainer:
        def __init__(self, params, optimizer, optimizer_params=None,
                     kvstore=None):
            self._params = list(params)
            self._optimizer = optimizer
            self._scale = 1.0
    gluon.Trainer = Trainer
    mx.gluon = gluon
    sys.modules["mxnet"] = mx
    sys.modules["mxnet.nd"] = nd
"""


def test_mxnet_binding_with_mock_engine():
    out = _run_sub(FAKE_MXNET + """
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    import horovod_tpu as hvd_core
    import horovod_tpu.mxnet as hvd
    FakeND = sys.modules["mxnet"].nd.array(np.zeros(1)).__class__

    hvd.init()
    assert hvd.size() == 1

    # allreduce: identity at size 1, dtype/ctx preserved through the bridge
    t = sys.modules["mxnet"].nd.array(
        np.arange(4, dtype=np.float32), ctx="gpu(7)")
    out = hvd.allreduce(t, average=True, name="mx.ar")
    assert isinstance(out, FakeND) and out.context == "gpu(7)"
    np.testing.assert_allclose(out.asnumpy(), np.arange(4))

    # broadcast_parameters (dict form) writes in place
    p = {"w": sys.modules["mxnet"].nd.array(np.ones(3, np.float32))}
    hvd.broadcast_parameters(p, root_rank=0)
    np.testing.assert_allclose(p["w"].asnumpy(), np.ones(3))

    # DistributedOptimizer reduces before delegating to the wrapped update
    calls = []
    class Inner(sys.modules["mxnet"].optimizer.Optimizer):
        rescale_grad = 1.0
        def update(self, index, weight, grad, state):
            calls.append((index, grad.asnumpy().copy()))
    opt = hvd.DistributedOptimizer(Inner())
    g = sys.modules["mxnet"].nd.array(np.full(2, 6.0, np.float32))
    opt.update(3, None, g, None)
    assert calls and calls[0][0] == 3
    np.testing.assert_allclose(calls[0][1], np.full(2, 6.0))

    # DistributedTrainer divides the gluon scale by size and allreduces
    class Param:
        grad_req = "write"
        name = "w0"
        def __init__(self):
            self._g = sys.modules["mxnet"].nd.array(
                np.full(2, 4.0, np.float32))
        def list_grad(self):
            return [self._g]
    prm = Param()
    tr = hvd.DistributedTrainer([prm], Inner())
    assert tr._scale == 1.0  # size 1
    tr._allreduce_grads()
    np.testing.assert_allclose(prm._g.asnumpy(), np.full(2, 4.0))

    # broadcast_object pickles through the numpy broadcast path
    obj = hvd.broadcast_object({"lr": 0.1, "step": 7}, root_rank=0)
    assert obj == {"lr": 0.1, "step": 7}
    hvd_core.shutdown()
    print("MXNET-MOCK-OK")
    """)
    assert "MXNET-MOCK-OK" in out


def test_mxnet_gate_message_without_engine():
    out = _run_sub("""
    import horovod_tpu.mxnet as hvd
    try:
        hvd.init()
        raise SystemExit("gate did not fire")
    except ImportError as e:
        assert "MXNet is not installed" in str(e), e
    print("GATE-OK")
    """)
    assert "GATE-OK" in out


def test_spark_run_with_mock_engine():
    """horovod_tpu.spark.run() against a fake pyspark whose barrier stage
    forks one process per task: exercises the driver KV rendezvous, host
    collection, slot allocation, per-rank env plumbing, and result
    collection — everything except Spark itself."""
    out = _run_sub("""
    import sys, types, os
    import multiprocessing as mp

    pyspark = types.ModuleType("pyspark")

    class FakeRDD:
        def __init__(self, data, parts):
            self.data, self.parts = list(data), parts
        def barrier(self):
            return self
        def mapPartitions(self, f):
            self._f = f
            return self
        def collect(self):
            ctx = mp.get_context("fork")
            procs = [ctx.Process(target=lambda i=i: list(self._f(iter([i]))))
                     for i in self.data]
            for p in procs: p.start()
            for p in procs: p.join(90)
            bad = [p.exitcode for p in procs if p.exitcode != 0]
            assert not bad, f"task exit codes: {bad}"
            return self.data

    class SparkContext:
        defaultParallelism = 2
        _active = None
        @classmethod
        def getOrCreate(cls):
            if cls._active is None:
                cls._active = cls()
            return cls._active
        def parallelize(self, rng, n):
            return FakeRDD(rng, n)

    pyspark.SparkContext = SparkContext
    sys.modules["pyspark"] = pyspark

    import horovod_tpu.spark as hvd_spark

    def fn(tag):
        # Runs inside a forked task with its rank env applied.
        return (tag, os.environ["HOROVOD_RANK"], os.environ["HOROVOD_SIZE"])

    results = hvd_spark.run(fn, args=("t",), num_proc=2)
    assert len(results) == 2, results
    ranks = sorted(r[1] for r in results)
    assert ranks == ["0", "1"], results
    assert all(r[2] == "2" for r in results), results
    print("SPARK-MOCK-OK")
    """)
    assert "SPARK-MOCK-OK" in out
