"""Elastic resharding (docs/fault_tolerance.md "Elastic resharding").

Covers the planning half (pure interval/transfer arithmetic, layouts,
manifests — importable without jax), the execution half (Zero1State
re-stacking with bitwise gather parity, EF policies, metrics), the
mesh-aware checkpoint path (cross-world-shape round-trips, torn-manifest
refusal, the broadcast/rank-local guard, legacy compatibility), the
elastic snapshot/resize preflights, and the capacity-pricing helpers
(``selfdrive.price_resize``, ``fleet_sim --resize``).
"""

import json
import os
import subprocess
import sys
import types

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

import horovod_tpu.jax as hvdj
from horovod_tpu import metrics as _metrics
from horovod_tpu.parallel import reshard as R
from horovod_tpu.parallel.zero import Zero1State
from horovod_tpu.utils import checkpoint as ckpt

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# Planning half: intervals, transfer plans, layouts, manifests
# ---------------------------------------------------------------------------


def test_reshard_module_is_jax_free_at_import():
    """The planning half must import on a jax-free host (fleet sim)."""
    code = (
        "import sys\n"
        "sys.path.insert(0, %r)\n"
        "import builtins\n"
        "real = builtins.__import__\n"
        "def guard(name, *a, **k):\n"
        "    if name == 'jax' or name.startswith('jax.'):\n"
        "        raise ImportError('jax blocked')\n"
        "    return real(name, *a, **k)\n"
        "builtins.__import__ = guard\n"
        "from horovod_tpu.parallel import reshard\n"
        "print(reshard.shard_len(100, 3))\n" % REPO
    )
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True
    )
    assert out.returncode == 0, out.stderr
    assert out.stdout.strip() == "34"


@pytest.mark.parametrize("quantized", [False, True])
def test_shard_intervals_cover_and_disjoint(quantized):
    rng = np.random.RandomState(7)
    for _ in range(60):
        total = int(rng.randint(1, 5000))
        n = int(rng.randint(1, 9))
        k = R.shard_len(total, n, quantized=quantized)
        if quantized:
            assert k % R._BLOCK == 0 or n * k >= total
        ivs = R.shard_intervals(total, n, k)
        assert len(ivs) == n
        covered = 0
        for i, (s, e) in enumerate(ivs):
            assert 0 <= s <= e <= total
            assert s == min(i * k, total)
            covered += e - s
        assert covered == total


def test_transfer_plan_moves_every_element_once():
    rng = np.random.RandomState(3)
    for _ in range(60):
        total = int(rng.randint(1, 3000))
        n_old, n_new = int(rng.randint(1, 7)), int(rng.randint(1, 7))
        k_old = R.shard_len(total, n_old)
        k_new = R.shard_len(total, n_new)
        moves = R.transfer_plan(total, n_old, k_old, n_new, k_new)
        seen = np.zeros(total, dtype=bool)
        for m in moves:
            assert m.length > 0
            assert 0 <= m.src < n_old and 0 <= m.dst < n_new
            assert m.src_off + m.length <= k_old
            assert m.dst_off + m.length <= k_new
            span = slice(m.start, m.start + m.length)
            assert not seen[span].any(), "element moved twice"
            seen[span] = True
            # Offsets agree with the global interval arithmetic.
            assert m.start == m.src * k_old + m.src_off
            assert m.start == m.dst * k_new + m.dst_off
        assert seen.all(), "element never moved"
        moved, local = R.plan_bytes(moves, 4)
        assert moved + local == total * 4
        if n_old == n_new:
            assert moved == 0


def test_layout_roundtrip_relayout_and_mismatch():
    lay = R.Zero1Layout(
        n_shards=4, quantized=False,
        buckets={
            "g0": {"b0": R.BucketLayout(1000, R.shard_len(1000, 4),
                                        "float32")},
            "g1": {"b0": R.BucketLayout(17, R.shard_len(17, 4),
                                        "float32")},
        },
    )
    back = R.Zero1Layout.from_dict(lay.to_dict())
    assert back.to_dict() == lay.to_dict()
    lay2 = lay.relayout(2)
    assert lay2.n_shards == 2
    assert lay2.total_elements() == lay.total_elements()
    plan = R.plan_zero1_reshard(lay, lay2)
    s = plan.summary()
    assert s["n_old"] == 4 and s["n_new"] == 2
    assert s["moved_bytes"] + s["local_bytes"] == 1017 * 4

    qlay = R.Zero1Layout(n_shards=4, quantized=True,
                         buckets=lay.buckets)
    with pytest.raises(ValueError, match="quantized"):
        R.plan_zero1_reshard(lay, qlay.relayout(2))


def test_resize_redistribution_identity_and_scaling():
    same = R.resize_redistribution(10_000, 4, 8, 8)
    assert same["moved_bytes"] == 0
    assert same["total_bytes"] == 10_000 * 4

    one = R.resize_redistribution(10_000, 4, 8, 4, copies=1)
    three = R.resize_redistribution(10_000, 4, 8, 4, copies=3)
    assert three["moved_bytes"] == 3 * one["moved_bytes"]
    q = R.resize_redistribution(10_000, 4, 8, 4, quantized=True)
    assert q["k_old"] % R._BLOCK == 0


def test_rank_coords_row_major():
    axes = [("data", 2), ("model", 2)]
    coords = [R.rank_coords(axes, r) for r in range(4)]
    assert coords == [
        {"data": 0, "model": 0}, {"data": 0, "model": 1},
        {"data": 1, "model": 0}, {"data": 1, "model": 1},
    ]


def test_leaf_slices_match_manual_slicing():
    mesh = {"data": 2, "model": 2}
    arr = np.arange(8 * 6).reshape(8, 6)
    spec = [["data"], ["model"]]
    parts = {}
    for r in range(4):
        coords = R.rank_coords([("data", 2), ("model", 2)], r)
        sl = R.leaf_slices(spec, arr.shape, mesh, coords)
        parts[(coords["data"], coords["model"])] = arr[sl]
    assert parts[(0, 0)].shape == (4, 3)
    np.testing.assert_array_equal(parts[(1, 1)], arr[4:, 3:])
    with pytest.raises(ValueError, match="divisible"):
        R.leaf_slices(spec, (7, 6), mesh, {"data": 0, "model": 0})


def test_manifest_json_roundtrip_and_torn_refusal():
    params = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
    man = R.build_manifest(params, [("data", 2)], step=3)
    text = man.to_json()
    back = R.LayoutManifest.from_json(text)
    assert back.mesh_axes == [("data", 2)]
    assert back.step == 3
    assert back.world == 2
    assert len(back.leaves) == 2

    doc = json.loads(text)
    doc["mesh_axes"] = [["data", 4]]  # tamper without re-hashing
    with pytest.raises(ValueError, match="torn or hand-edited"):
        R.LayoutManifest.from_json(json.dumps(doc))


# ---------------------------------------------------------------------------
# Execution half: Zero1State resharding
# ---------------------------------------------------------------------------


def _params(d=12, seed=5):
    rng = np.random.RandomState(seed)
    return {
        "a": {"w": jnp.asarray(rng.randn(d, d).astype(np.float32)),
              "b": jnp.asarray(rng.randn(d).astype(np.float32))},
        "c": jnp.asarray(rng.randn(d, 3).astype(np.float32)),
    }


def _filled_state(tx, params, n, quantized=False, seed=9):
    """An init state with deterministic, shard-layout-respecting fills:
    [n, k] leaves carry a global vector split per the layout (pad stays
    zero), [n] scalar stacks carry equal rows."""
    state = hvdj.init_zero1_stream_state(
        tx, params, n, threshold_bytes=1, first_bucket_bytes=1,
        quantized=quantized,
    )
    layout = R.zero1_layout_from_params(
        params, n, threshold_bytes=1, first_bucket_bytes=1,
        quantized=quantized,
    )
    rng = np.random.RandomState(seed)

    def rows(bl, dtype):
        vec = rng.randn(bl.total).astype(dtype)
        out = np.zeros((n, bl.k), dtype)
        for i, (s, e) in enumerate(
            R.shard_intervals(bl.total, n, bl.k)
        ):
            out[i, : e - s] = vec[s:e]
        return out

    def fill(node, bl):
        def f(x):
            a = np.asarray(x)
            if a.ndim >= 2:
                return jnp.asarray(rows(bl, a.dtype))
            if a.ndim == 1:
                return jnp.full(a.shape, float(rng.randint(1, 9)),
                                a.dtype)
            return x
        return jax.tree.map(f, node)

    opt = {
        g: {b: fill(state.opt[g][b], layout.buckets[g][b])
            for b in state.opt[g]}
        for g in state.opt
    }
    ef = None
    if state.ef is not None:
        ef = {
            g: {b: fill(state.ef[g][b], layout.buckets[g][b])
                for b in state.ef[g]}
            for g in state.ef
        }
    return Zero1State(opt=opt, ef=ef), layout


def _gather(state, layout):
    out = []
    for g, b, bl in layout.bucket_items():
        nodes = [state.opt[g][b]]
        if state.ef is not None:
            nodes.append(state.ef[g][b])
        for node in nodes:
            for leaf in jax.tree.leaves(node):
                a = np.asarray(jax.device_get(leaf))
                if a.ndim >= 2:
                    out.append(a.reshape(-1)[: bl.total])
                elif a.ndim == 1:
                    assert (a == a[0]).all()
                    out.append(a[:1])
                else:
                    out.append(a.reshape(1))
    return out


@pytest.mark.parametrize("opt_name", ["sgdm", "adam"])
@pytest.mark.parametrize("quantized", [False, True])
@pytest.mark.parametrize("n_mid", [2, 3, 6])
def test_reshard_gather_parity_roundtrip(opt_name, quantized, n_mid):
    tx = (optax.sgd(0.05, momentum=0.9) if opt_name == "sgdm"
          else optax.adam(1e-3))
    params = _params()
    state, lay4 = _filled_state(tx, params, 4, quantized=quantized)
    ref = _gather(state, lay4)

    mid, rep = R.reshard_zero1_state(state, n_mid, layout=lay4)
    lay_mid = lay4.relayout(n_mid)
    for a, b in zip(ref, _gather(mid, lay_mid)):
        np.testing.assert_array_equal(a, b)
    assert rep["ef_dropped_elements"] == 0
    assert rep["n_old"] == 4 and rep["n_new"] == n_mid

    back, _ = R.reshard_zero1_state(mid, 4, layout=lay_mid)
    for a, b in zip(ref, _gather(back, lay4)):
        np.testing.assert_array_equal(a, b)
    # Identical shard geometry again: stacked leaves match bitwise too.
    for x, y in zip(jax.tree.leaves(state), jax.tree.leaves(back)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_reshard_derives_layout_from_params():
    tx = optax.adam(1e-3)
    params = _params()
    state, lay4 = _filled_state(tx, params, 4)
    new, rep = R.reshard_zero1_state(
        state, 2, params=params, threshold_bytes=1, first_bucket_bytes=1,
        quantized=False,
    )
    for a, b in zip(_gather(state, lay4), _gather(new, lay4.relayout(2))):
        np.testing.assert_array_equal(a, b)
    assert rep["n_new"] == 2


def test_reshard_scalar_rows_must_agree():
    tx = optax.adam(1e-3)
    params = _params()
    state, lay4 = _filled_state(tx, params, 4)
    g = sorted(state.opt)[0]
    b = sorted(state.opt[g])[0]

    def corrupt(x):
        a = np.asarray(x)
        if a.ndim == 1:
            a = a.copy()
            a[0] += 1
            return jnp.asarray(a)
        return x

    bad_opt = {k: dict(v) for k, v in state.opt.items()}
    bad_opt[g][b] = jax.tree.map(corrupt, state.opt[g][b])
    bad = Zero1State(opt=bad_opt, ef=state.ef)
    with pytest.raises(ValueError, match=f"{g}/{b}"):
        R.reshard_zero1_state(bad, 2, layout=lay4)


def test_reshard_layout_world_mismatch_raises():
    tx = optax.sgd(0.1, momentum=0.9)
    params = _params()
    state, lay4 = _filled_state(tx, params, 4)
    with pytest.raises(ValueError, match="different world"):
        R.reshard_zero1_state(state, 2, layout=lay4.relayout(3))


def test_reshard_ef_zero_policy_reports_dropped_mass():
    tx = optax.sgd(0.05, momentum=0.9)
    params = _params()
    state, lay4 = _filled_state(tx, params, 4, quantized=True)
    nonzero = sum(
        int((np.asarray(x) != 0).sum()) for x in jax.tree.leaves(state.ef)
    )
    assert nonzero > 0
    new, rep = R.reshard_zero1_state(
        state, 2, layout=lay4, ef_policy="zero"
    )
    assert rep["ef_dropped_elements"] == nonzero
    assert rep["ef_dropped_mass"] > 0
    for x in jax.tree.leaves(new.ef):
        assert not np.asarray(x).any()


def test_reshard_ef_fold_counts_pad_mass(caplog):
    """Pad-region EF mass has no global position: fold drops it with a
    warning and a nonzero counter — never silently."""
    import logging

    tx = optax.sgd(0.05, momentum=0.9)
    params = _params()
    state, lay4 = _filled_state(tx, params, 4, quantized=True)

    def poison_pad(rows, bl):
        a = np.asarray(rows).copy()
        ivs = R.shard_intervals(bl.total, 4, bl.k)
        poisoned = 0
        for i, (s, e) in enumerate(ivs):
            if e - s < bl.k:
                a[i, e - s:] = 0.25
                poisoned += bl.k - (e - s)
        return jnp.asarray(a), poisoned

    total_poisoned = 0
    ef = {}
    for g in state.ef:
        ef[g] = {}
        for b in state.ef[g]:
            ef[g][b], p = poison_pad(state.ef[g][b],
                                     lay4.buckets[g][b])
            total_poisoned += p
    assert total_poisoned > 0
    bad = Zero1State(opt=state.opt, ef=ef)
    with caplog.at_level(logging.WARNING, logger="horovod_tpu.reshard"):
        _, rep = R.reshard_zero1_state(bad, 2, layout=lay4)
    assert rep["ef_dropped_elements"] == total_poisoned
    assert any("dropped" in r.message for r in caplog.records)


def test_reshard_invalid_ef_policy_and_type():
    tx = optax.sgd(0.1)
    params = _params()
    state, lay4 = _filled_state(tx, params, 4)
    with pytest.raises(ValueError, match="ef_policy"):
        R.reshard_zero1_state(state, 2, layout=lay4, ef_policy="drop")
    with pytest.raises(TypeError, match="Zero1State"):
        R.reshard_zero1_state({"not": "a state"}, 2, layout=lay4)


def test_reshard_tree_multi_node():
    tx = optax.sgd(0.05, momentum=0.9)
    params = _params()
    s1, lay1 = _filled_state(tx, params, 4, seed=1)
    s2, lay2 = _filled_state(tx, params, 4, seed=2)
    tree = {"x": s1, "y": {"z": s2}}
    new_tree, reports = R.reshard_zero1_tree(
        tree, 2, layouts={"x": lay1, "y/z": lay2}
    )
    assert sorted(rep["path"] for rep in reports) == ["x", "y/z"]
    for a, b in zip(_gather(s1, lay1),
                    _gather(new_tree["x"], lay1.relayout(2))):
        np.testing.assert_array_equal(a, b)
    with pytest.raises(ValueError, match="no layout recorded"):
        R.reshard_zero1_tree(tree, 2, layouts={"x": lay1})


def test_reshard_emits_metrics_and_counts_bytes():
    tx = optax.sgd(0.05, momentum=0.9)
    params = _params()
    state, lay4 = _filled_state(tx, params, 4)
    _metrics.install(True)
    try:
        _, rep = R.reshard_zero1_state(
            state, 2, layout=lay4, trigger="quarantine"
        )
        flat = _metrics.flat()
        assert flat['hvd_reshard_total{trigger="quarantine"}'] == 1.0
        assert flat['hvd_reshard_bytes_total{axis="data"}'] == float(
            rep["moved_bytes"]
        )
        assert rep["moved_bytes"] > 0
    finally:
        _metrics.install(False)


# ---------------------------------------------------------------------------
# Mesh-aware checkpoints
# ---------------------------------------------------------------------------


def _save_all_ranks(path, tree, manifest, step=1):
    """Rank 0 last, matching the real barrier discipline."""
    ranks = list(range(manifest.world))
    for r in ranks[1:] + [0]:
        ckpt.save_checkpoint(path, tree, step=step, manifest=manifest,
                             rank=r)


def _ckpt_tree(tx, n, quantized=False, seed=9):
    params = _params(seed=seed)
    state, layout = _filled_state(tx, params, n, quantized=quantized,
                                  seed=seed)
    return {"params": params, "opt": state}, params, layout


@pytest.mark.parametrize("n_from,n_to", [(4, 2), (2, 4)])
def test_checkpoint_cross_world_roundtrip(tmp_path, n_from, n_to):
    tx = optax.sgd(0.05, momentum=0.9)
    tree, params, lay_from = _ckpt_tree(tx, n_from, quantized=True)
    man = R.build_manifest(
        tree, [("data", n_from)],
        specs={"params/a/w": jax.sharding.PartitionSpec("data")},
        zero1_layouts={"opt": lay_from},
    )
    _save_all_ranks(str(tmp_path), tree, man)

    target_state = hvdj.init_zero1_stream_state(
        tx, params, n_to, threshold_bytes=1, first_bucket_bytes=1,
        quantized=True,
    )
    target = {"params": jax.tree.map(jnp.zeros_like, params),
              "opt": target_state}
    restored = ckpt.restore_checkpoint(str(tmp_path), target)
    for a, b in zip(jax.tree.leaves(restored["params"]),
                    jax.tree.leaves(params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    lay_to = lay_from.relayout(n_to)
    for a, b in zip(_gather(restored["opt"], lay_to),
                    _gather(tree["opt"], lay_from)):
        np.testing.assert_array_equal(a, b)


def test_checkpoint_same_world_restore_is_bitwise(tmp_path):
    tx = optax.adam(1e-3)
    tree, params, lay = _ckpt_tree(tx, 2)
    man = R.build_manifest(tree, [("data", 2)], zero1_layouts={"opt": lay})
    _save_all_ranks(str(tmp_path), tree, man)
    target = {
        "params": jax.tree.map(jnp.zeros_like, params),
        "opt": hvdj.init_zero1_stream_state(
            tx, params, 2, threshold_bytes=1, first_bucket_bytes=1,
        ),
    }
    restored = ckpt.restore_checkpoint(str(tmp_path), target)
    for a, b in zip(jax.tree.leaves(restored), jax.tree.leaves(tree)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_checkpoint_dp_tp_to_wider_dp(tmp_path):
    """(data=2, model=2) params-only checkpoint restores onto
    (data=4, model=1): the TP-sharded leaves reassemble from the rank
    slices and the restored globals match the originals exactly."""
    rng = np.random.RandomState(2)
    params = {
        "wq": jnp.asarray(rng.randn(8, 6).astype(np.float32)),
        "wo": jnp.asarray(rng.randn(6, 8).astype(np.float32)),
        "ln": jnp.asarray(rng.randn(8).astype(np.float32)),
    }
    P = jax.sharding.PartitionSpec
    man = R.build_manifest(
        params, [("data", 2), ("model", 2)],
        specs={"wq": P(None, "model"), "wo": P("model")},
    )
    _save_all_ranks(str(tmp_path), params, man)

    target = jax.tree.map(jnp.zeros_like, params)
    restored = ckpt.restore_checkpoint(str(tmp_path), target)
    for k in params:
        np.testing.assert_array_equal(
            np.asarray(restored[k]), np.asarray(params[k])
        )


def test_checkpoint_legacy_replicated_path_unchanged(tmp_path):
    tree = {"w": jnp.arange(12.0).reshape(3, 4), "step": jnp.asarray(7)}
    ckpt.save_checkpoint(str(tmp_path), tree, step=2, use_orbax=False)
    assert not any(
        f.startswith("manifest") for f in os.listdir(tmp_path)
    )
    restored = ckpt.restore_checkpoint(
        str(tmp_path), jax.tree.map(jnp.zeros_like, tree),
        broadcast=False,
    )
    for a, b in zip(jax.tree.leaves(restored), jax.tree.leaves(tree)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_checkpoint_torn_manifest_refuses(tmp_path):
    tx = optax.sgd(0.1)
    tree, params, lay = _ckpt_tree(tx, 2)
    man = R.build_manifest(tree, [("data", 2)], zero1_layouts={"opt": lay})
    _save_all_ranks(str(tmp_path), tree, man)
    target = {
        "params": params,
        "opt": hvdj.init_zero1_stream_state(
            tx, params, 2, threshold_bytes=1, first_bucket_bytes=1,
        ),
    }

    man_file = tmp_path / "manifest_step_1.json"
    blob = man_file.read_text()
    man_file.write_text(blob[: len(blob) // 2])  # torn mid-write
    with pytest.raises(RuntimeError, match="torn or invalid"):
        ckpt.restore_checkpoint(str(tmp_path), target)

    man_file.unlink()  # manifest never landed
    with pytest.raises(RuntimeError, match="torn"):
        ckpt.restore_checkpoint(str(tmp_path), target)

    man_file.write_text(blob)
    (tmp_path / "step_1.rank1.npz").unlink()  # payload missing
    with pytest.raises(RuntimeError, match="rank-1 payload"):
        ckpt.restore_checkpoint(str(tmp_path), target)


def test_restore_broadcast_refuses_rank_local(tmp_path, monkeypatch):
    import horovod_tpu as hvd

    tx = optax.sgd(0.05, momentum=0.9)
    tree, params, lay = _ckpt_tree(tx, 2, quantized=True)
    ckpt.save_checkpoint(str(tmp_path), tree, step=0, use_orbax=False)

    monkeypatch.setattr(hvd, "is_initialized", lambda: True)
    monkeypatch.setattr(hvd, "size", lambda: 2)
    called = []
    monkeypatch.setattr(
        hvd, "broadcast_variables",
        lambda t, root_rank=0: called.append(1) or t,
    )
    with pytest.raises(ValueError, match="RANK-LOCAL") as ei:
        ckpt.restore_checkpoint(str(tmp_path), tree, broadcast=True)
    assert "opt" in str(ei.value)
    assert not called, "broadcast ran despite rank-local state"

    # Replicated trees still broadcast as before.
    ckpt.save_checkpoint(str(tmp_path), params, step=1, use_orbax=False)
    ckpt.restore_checkpoint(str(tmp_path), params, broadcast=True)
    assert called


# ---------------------------------------------------------------------------
# Elastic snapshot / in-process resize preflights
# ---------------------------------------------------------------------------


def _elastic_state(tx, n, with_layout=True):
    from horovod_tpu import elastic

    params = _params()
    z, lay = _filled_state(tx, params, n)
    state = types.SimpleNamespace(
        opt_state=z, _tracked=["opt_state"],
        _saved={"opt_state": z},
    )
    if with_layout:
        elastic.note_zero1_layout(state, "opt_state", lay)
    return state, z, lay


def test_persist_payload_stamps_layout(monkeypatch):
    from horovod_tpu import elastic

    monkeypatch.setenv("HOROVOD_SIZE", "4")
    tx = optax.sgd(0.05, momentum=0.9)
    state, _, lay = _elastic_state(tx, 4)
    payload = elastic._persist_payload(state)
    stamp = payload["__layout__"]
    assert stamp["world"] == 4
    assert stamp["zero1_layout"]["opt_state"]["n_shards"] == 4
    assert "_saved" in payload


def test_snapshot_preflight_reshards_across_worlds(monkeypatch):
    from horovod_tpu import elastic

    tx = optax.sgd(0.05, momentum=0.9)
    monkeypatch.setenv("HOROVOD_SIZE", "4")
    state, z4, lay4 = _elastic_state(tx, 4)
    payload = elastic._persist_payload(state)

    monkeypatch.setenv("HOROVOD_SIZE", "2")
    out = elastic._preflight_snapshot_layout(state, payload, "snap.pkl")
    z2 = out["_saved"]["opt_state"]
    assert R._state_n_shards(z2) == 2
    for a, b in zip(_gather(z4, lay4), _gather(z2, lay4.relayout(2))):
        np.testing.assert_array_equal(a, b)
    assert out["__layout__"]["world"] == 2
    assert state.zero1_layout["opt_state"].n_shards == 2


def test_snapshot_preflight_without_layout_names_both(monkeypatch):
    from horovod_tpu import elastic

    tx = optax.sgd(0.05, momentum=0.9)
    monkeypatch.setenv("HOROVOD_SIZE", "4")
    state, _, _ = _elastic_state(tx, 4, with_layout=False)
    payload = elastic._persist_payload(state)
    monkeypatch.setenv("HOROVOD_SIZE", "2")
    with pytest.raises(RuntimeError) as ei:
        elastic._preflight_snapshot_layout(state, payload, "snap.pkl")
    msg = str(ei.value)
    assert "world=4" in msg and "world=2" in msg
    assert "note_zero1_layout" in msg


def test_snapshot_preflight_replicated_passthrough(monkeypatch):
    from horovod_tpu import elastic

    monkeypatch.setenv("HOROVOD_SIZE", "2")
    state = types.SimpleNamespace(_saved={"w": np.ones(3)})
    payload = {"_saved": {"w": np.ones(3)},
               "__layout__": {"world": 4, "zero1_layout": {}}}
    out = elastic._preflight_snapshot_layout(state, payload, "snap.pkl")
    assert out is payload


def test_reshard_state_for_world_live_and_saved():
    from horovod_tpu import elastic

    tx = optax.sgd(0.05, momentum=0.9)
    state, z4, lay4 = _elastic_state(tx, 4)
    elastic._reshard_state_for_world(state, 4, 2)
    assert R._state_n_shards(state.opt_state) == 2
    assert R._state_n_shards(state._saved["opt_state"]) == 2
    for a, b in zip(_gather(z4, lay4),
                    _gather(state.opt_state, lay4.relayout(2))):
        np.testing.assert_array_equal(a, b)
    assert state.zero1_layout["opt_state"].n_shards == 2


def test_digest_agreement_survives_resize():
    """The first post-resize digest beat must never false-positive a
    heal: each beat recomputes the digest from the live (resharded)
    state, zero1 shard BYTES are rank-local and stripped (intentional
    divergence never mismatches), and only the shard LAYOUT headers are
    compared — so ranks that resharded together agree on the new
    layout, while a rank that missed the reshard mismatches loudly."""
    from horovod_tpu import elastic
    from horovod_tpu.guard import digest as _digest

    tx = optax.sgd(0.05, momentum=0.9)
    state_a, _, _ = _elastic_state(tx, 4)
    state_b, _, _ = _elastic_state(tx, 4)

    # Divergent shard bytes (each rank owns its own rows) digest equal.
    state_b.opt_state = jax.tree.map(
        lambda x: x + 1.0, state_b.opt_state)
    assert _digest.state_digest(state_a) == _digest.state_digest(state_b)

    # Both ranks reshard 4 -> 2: digests agree on the new layout.
    elastic._reshard_state_for_world(state_a, 4, 2)
    elastic._reshard_state_for_world(state_b, 4, 2)
    d_a = _digest.state_digest(state_a)
    d_b = _digest.state_digest(state_b)
    assert d_a == d_b

    # A rank still holding the old layout mismatches — loudly, as an
    # outlier the quorum heals — never a silent false agreement.
    state_c, _, _ = _elastic_state(tx, 4)
    d_c = _digest.state_digest(state_c)
    assert d_c != d_a
    ok, ref, outliers = _digest.find_quorum([d_a, d_b, d_c])
    assert not ok and ref == 0 and outliers == [2]

    # Recorded sharding_specs re-key cleanly after the resize: a data
    # axis resize changes shard shapes but not the leaf structure, so a
    # spec tree recorded before the resize still mirrors the state.
    from jax.sharding import PartitionSpec as P

    state_a.sharding_specs = {
        "opt_state": jax.tree.map(lambda _: P(), state_a.opt_state)}
    assert _digest.state_digest(state_a)  # must not raise


def test_reshard_state_for_world_missing_layout_raises():
    from horovod_tpu import elastic

    tx = optax.sgd(0.05, momentum=0.9)
    state, _, _ = _elastic_state(tx, 4, with_layout=False)
    with pytest.raises(RuntimeError, match="note_zero1_layout"):
        elastic._reshard_state_for_world(state, 4, 2)


# ---------------------------------------------------------------------------
# Capacity pricing: selfdrive.price_resize + fleet_sim --resize
# ---------------------------------------------------------------------------


def test_price_resize_bytes_and_model():
    from horovod_tpu.run.selfdrive import price_resize
    from horovod_tpu.topo.model import synthetic_model

    bare = price_resize(1 << 20, 8, 4)
    assert bare["moved_bytes"] > 0
    assert "modeled_time_us" not in bare
    assert bare["copies"] == 2
    q = price_resize(1 << 20, 8, 4, quantized=True)
    assert q["copies"] == 3

    model = synthetic_model(8)
    priced = price_resize(1 << 20, 8, 4, model=model)
    assert priced["modeled_time_us"] > 0
    assert priced["hop"] in {h.name for h in model.hops}

    same = price_resize(1 << 20, 8, 8)
    assert same["moved_bytes"] == 0


def _fleet_sim(*extra):
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "fleet_sim.py"),
         "--ranks", "16", "--steps", "2", *extra],
        capture_output=True, text=True, cwd=REPO,
    )
    assert out.returncode == 0, out.stderr
    return json.loads(out.stdout)


def test_fleet_sim_resize_honest_zero_without_zero1():
    doc = _fleet_sim("--resize", "16,8")
    blk = doc["resize"]
    assert blk["redistribution_bytes"] == 0
    assert "fault_tolerance.md" in blk["note"]


def test_fleet_sim_resize_prices_zero1_state():
    doc = _fleet_sim("--resize", "16,8", "--zero1", "--wire", "int8")
    blk = doc["resize"]
    assert blk["moved_bytes"] > 0
    assert blk["quantized"] is True
    assert blk["copies"] == 3
    assert blk["modeled_time_us"] > 0
