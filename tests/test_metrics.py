"""Metrics subsystem (horovod_tpu/metrics): registry semantics, the
zero-overhead disabled tap, Prometheus rendering/parsing, driver-side
aggregation over the KV plane, the satellite fixes that rode along, and a
2-rank end-to-end scrape through the real elastic driver
(docs/metrics.md is the prose companion)."""

import json
import os
import subprocess
import sys
import textwrap
import threading
import time
import urllib.request

import pytest

from horovod_tpu import metrics as hvd_metrics
from horovod_tpu.metrics import export as mexport
from horovod_tpu.metrics import registry as mreg

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_metrics_state():
    """Every test starts and ends with the tap in its env-default state
    (inactive in the test environment)."""
    hvd_metrics.reset()
    yield
    hvd_metrics.reset()


# ---------------------------------------------------------------- registry
def test_histogram_bucket_edges():
    h = mreg.Histogram("h", buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.01):   # <= 0.01 bucket
        h.observe(v)
    h.observe(0.05)           # <= 0.1
    h.observe(0.5)            # <= 1.0
    h.observe(2.0)            # +Inf overflow
    (series,) = h.snapshot()["series"]
    assert series["buckets"] == [2, 1, 1, 1]
    assert series["count"] == 5
    assert abs(series["sum"] - 2.565) < 1e-9
    assert h.snapshot()["bucket_edges"] == [0.01, 0.1, 1.0]


def test_histogram_labels_and_count():
    h = mreg.Histogram("h", buckets=(1.0,))
    h.observe(0.5, op="A")
    h.observe(0.5, op="A")
    h.observe(3.0, op="B")
    assert h.count(op="A") == 2
    assert h.count(op="B") == 1
    assert h.count(op="C") == 0


def test_counter_concurrent_increments():
    c = mreg.Counter("c")
    n_threads, per_thread = 8, 5000

    def work():
        for _ in range(per_thread):
            c.inc(1, op="x")

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value(op="x") == n_threads * per_thread


def test_counter_rejects_negative_and_type_clash():
    r = mreg.Registry()
    with pytest.raises(ValueError):
        r.counter("c").inc(-1)
    r.counter("same")
    with pytest.raises(TypeError):
        r.gauge("same")


def test_gauge_set_overwrites():
    g = mreg.Gauge("g")
    g.set(3, shard="a")
    g.set(7, shard="a")
    assert g.value(shard="a") == 7


# ------------------------------------------------------------ tap discipline
def test_disabled_tap_is_shared_noop_singleton():
    assert not hvd_metrics.ACTIVE
    assert hvd_metrics.TAP is hvd_metrics.NULL_TAP
    assert hvd_metrics.tap() is hvd_metrics.NULL_TAP
    # No-ops never record anything.
    hvd_metrics.TAP.inc("hvd_rpc_retries_total")
    hvd_metrics.TAP.observe("hvd_op_execute_seconds", 1.0, op="X")
    hvd_metrics.TAP.set("hvd_queue_depth", 9)
    assert hvd_metrics.snapshot() == {}

    import horovod_tpu as hvd

    assert hvd.metrics() == {}
    assert hvd.metrics_snapshot() == {}


def test_activation_installs_live_tap_and_reset_restores_singleton():
    hvd_metrics.install(True)
    assert hvd_metrics.ACTIVE
    assert hvd_metrics.TAP is not hvd_metrics.NULL_TAP
    hvd_metrics.TAP.inc("hvd_rpc_retries_total", request="Ping")
    snap = hvd_metrics.snapshot()
    assert snap["hvd_rpc_retries_total"]["type"] == "counter"
    # Pre-seeded zero families surface even when they never fired.
    assert "hvd_stall_warnings_total" in snap
    hvd_metrics.reset()
    assert hvd_metrics.TAP is hvd_metrics.NULL_TAP  # the SAME object


def test_activate_from_env(monkeypatch):
    monkeypatch.setenv("HOROVOD_METRICS", "1")
    assert hvd_metrics.activate_from_env()
    monkeypatch.setenv("HOROVOD_METRICS", "0")
    assert not hvd_metrics.activate_from_env()
    assert hvd_metrics.TAP is hvd_metrics.NULL_TAP


def test_callable_module_returns_flat_dict():
    hvd_metrics.install(True)
    hvd_metrics.TAP.inc("hvd_plans_total", 3, op="ALLREDUCE")
    flat = hvd_metrics()  # the hvd.metrics() surface
    assert flat['hvd_plans_total{op="ALLREDUCE"}'] == 3.0


# ------------------------------------------------------------------ export
def _sample_snapshot():
    tap = hvd_metrics.MetricsTap()
    tap.inc("hvd_rpc_retries_total", 2, request="Ping")
    tap.set("hvd_queue_depth", 4)
    tap.observe("hvd_op_execute_seconds", 0.002, op="ALLREDUCE")
    tap.observe("hvd_op_execute_seconds", 0.2, op="ALLREDUCE")
    return tap.snapshot()


def test_render_parse_roundtrip_with_rank_labels():
    snap = _sample_snapshot()
    text = mexport.render_prometheus(
        [({"rank": "0"}, snap), ({"rank": "1"}, snap)]
    )
    parsed = mexport.parse_prometheus(text)
    assert parsed["hvd_rpc_retries_total"]["type"] == "counter"
    ranks = {
        labels["rank"]
        for _, labels, _ in parsed["hvd_rpc_retries_total"]["samples"]
    }
    assert ranks == {"0", "1"}
    # Histogram samples are filed under the base name; cumulative buckets
    # end at the series count.
    hist = parsed["hvd_op_execute_seconds"]
    assert hist["type"] == "histogram"
    counts = {
        (labels["rank"]): v
        for name, labels, v in hist["samples"]
        if name.endswith("_count")
    }
    assert counts == {"0": 2.0, "1": 2.0}
    inf_buckets = [
        v for name, labels, v in hist["samples"]
        if name.endswith("_bucket") and labels["le"] == "+Inf"
    ]
    assert all(v == 2.0 for v in inf_buckets)


def test_render_cumulative_bucket_monotonicity():
    snap = _sample_snapshot()
    text = mexport.render_prometheus([({}, snap)])
    parsed = mexport.parse_prometheus(text)
    series = [
        (float("inf") if labels["le"] == "+Inf" else float(labels["le"]), v)
        for name, labels, v in parsed["hvd_op_execute_seconds"]["samples"]
        if name.endswith("_bucket")
    ]
    series.sort()
    values = [v for _, v in series]
    assert values == sorted(values), "buckets must be cumulative"
    assert values[-1] == 2.0


def test_render_drops_mismatched_histogram_edges():
    t1 = hvd_metrics.MetricsTap()
    t1.registry.histogram("h", buckets=(1.0, 2.0)).observe(0.5)
    t2 = hvd_metrics.MetricsTap()
    t2.registry.histogram("h", buckets=(5.0,)).observe(0.5)
    text = mexport.render_prometheus(
        [({"rank": "0"}, t1.snapshot()), ({"rank": "1"}, t2.snapshot())]
    )
    parsed = mexport.parse_prometheus(text)
    ranks = {
        labels.get("rank")
        for name, labels, _ in parsed["h"]["samples"]
        if name.endswith("_count")
    }
    assert ranks == {"0"}  # the latecomer was dropped, not corrupted


def test_label_escaping_roundtrip():
    tap = hvd_metrics.MetricsTap()
    tap.inc("c_total", 1, path='a"b\\c')
    text = mexport.render_prometheus([({}, tap.snapshot())])
    parsed = mexport.parse_prometheus(text)
    ((_, labels, value),) = parsed["c_total"]["samples"]
    assert value == 1.0
    assert labels["path"] == 'a"b\\c'


def test_aggregate_kv_snapshots_skips_garbage():
    snap = _sample_snapshot()
    entries = {
        "rank.0": json.dumps(
            {"labels": {"rank": "0"}, "snapshot": snap}
        ).encode(),
        "rank.1": b"\xff not json",
    }
    text = mexport.aggregate_kv_snapshots(entries)
    parsed = mexport.parse_prometheus(text)
    assert "hvd_rpc_retries_total" in parsed


# --------------------------------------------------- /metrics on KV server
def test_kv_server_serves_prometheus_text():
    from horovod_tpu.run.http_server import KVStoreClient, KVStoreServer

    hvd_metrics.install(True)
    hvd_metrics.TAP.inc("hvd_elastic_generations_total")
    server = KVStoreServer()
    server.start()
    try:
        kv = KVStoreClient("127.0.0.1", server.port)
        worker_snap = _sample_snapshot()
        kv.put(
            mexport.KV_SCOPE, "rank.1",
            json.dumps(
                {"labels": {"rank": "1"}, "snapshot": worker_snap}
            ).encode(),
        )
        with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/metrics", timeout=10
        ) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith("text/plain")
            text = resp.read().decode()
        parsed = mexport.parse_prometheus(text)
        # The serving process's registry carries the driver-role label...
        gens = parsed["hvd_elastic_generations_total"]["samples"]
        assert any(labels.get("role") == "driver" for _, labels, _ in gens)
        # ...and the pushed worker snapshot its rank label.
        execs = parsed["hvd_op_execute_seconds"]["samples"]
        assert any(labels.get("rank") == "1" for _, labels, _ in execs)
        # The ordinary KV surface still works next to /metrics.
        kv.put("scope", "k", b"v")
        assert kv.get("scope", "k") == b"v"
    finally:
        server.stop()


# ------------------------------------------------------- satellite fixes
def test_respawn_drain_grace_scales_with_detection_windows():
    from horovod_tpu.run.elastic_driver import _respawn_drain_grace

    # Defaults: 2x the 10s heartbeat + 5s margin.
    assert _respawn_drain_grace({}) == 25.0
    # Never below the base scale-down grace.
    assert _respawn_drain_grace(
        {"HOROVOD_ELASTIC_HEARTBEAT_S": "1"}, base=15.0
    ) == 15.0
    # A configured stall window dominates when longer.
    assert _respawn_drain_grace(
        {"HOROVOD_STALL_ABORT_TIME_SECONDS": "60"}
    ) == 65.0
    assert _respawn_drain_grace(
        {"HOROVOD_ELASTIC_HEARTBEAT_S": "40",
         "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS": "30"}
    ) == 85.0
    # Malformed values fall back instead of raising in the driver.
    assert _respawn_drain_grace(
        {"HOROVOD_ELASTIC_HEARTBEAT_S": "nope"}
    ) == 25.0


def test_warn_if_unrestored_gen_gt_1(monkeypatch, caplog):
    import logging

    from horovod_tpu.elastic import _warn_if_unrestored

    monkeypatch.setenv("HOROVOD_ELASTIC_GEN", "3")
    monkeypatch.delenv("HOROVOD_ELASTIC_REQUIRE_SNAPSHOT", raising=False)
    with caplog.at_level(logging.ERROR, logger="horovod_tpu.elastic"):
        _warn_if_unrestored(False)
    assert any("no restored snapshot" in r.message for r in caplog.records)
    # Restored, or a genuine first start: silent.
    caplog.clear()
    _warn_if_unrestored(True)
    monkeypatch.setenv("HOROVOD_ELASTIC_GEN", "1")
    _warn_if_unrestored(False)
    assert not caplog.records
    # The knob upgrades the warning to a hard failure.
    monkeypatch.setenv("HOROVOD_ELASTIC_GEN", "2")
    monkeypatch.setenv("HOROVOD_ELASTIC_REQUIRE_SNAPSHOT", "1")
    with pytest.raises(RuntimeError, match="no restored snapshot"):
        _warn_if_unrestored(False)


def test_probe_free_port_local():
    from horovod_tpu.run.elastic_driver import ElasticDriver

    drv = ElasticDriver.__new__(ElasticDriver)  # no __init__: unit scope
    drv._ssh_port = None
    port = drv._probe_free_port("localhost")
    assert 0 < port < 65536


def test_inline_sync_core_down_wakes_executor_drain():
    """Satellite (native_runtime): an inline synchronize() that observes
    next_plan == -1 must signal the parked executor thread so orphaned
    entry callbacks are drained promptly — not only after every waiter
    leaves."""
    import numpy as np

    import horovod_tpu as hvd

    hvd.shutdown()
    hvd.init()
    rt = hvd._runtime
    from horovod_tpu.core.native_runtime import NativeRuntime

    if not isinstance(rt, NativeRuntime):
        hvd.shutdown()
        pytest.skip("native core unavailable")
    assert not rt._core_down.is_set()
    hvd.allreduce(np.ones(4, np.float32), name="warm")  # consumer works
    # Simulate the core dying under a parked executor: shut the core down
    # (FailAll + next_plan == -1) while a fake waiter keeps the executor
    # parked, then drive the inline-consumer branch once.
    with rt._cv:
        rt._sync_waiters += 1
        rt._no_waiters.clear()
    try:
        rt.core.shutdown()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and not rt._core_down.is_set():
            with rt._consumer_lock:
                plan = rt.core.next_plan(timeout_ms=10)
                if plan == -1:
                    rt._core_down.set()
                    rt._no_waiters.set()
            time.sleep(0.01)
        assert rt._core_down.is_set()
        # The executor thread must exit its park and run the finally
        # drain even though a synchronize() waiter still exists.
        rt._thread.join(timeout=5.0)
        assert not rt._thread.is_alive()
    finally:
        with rt._cv:
            rt._sync_waiters -= 1
        hvd.shutdown()


# ------------------------------------------------------------- dump CLI
def test_metrics_dump_pretty_and_diff(tmp_path, capsys):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import metrics_dump
    finally:
        sys.path.pop(0)

    t = hvd_metrics.MetricsTap()
    t.inc("hvd_plans_total", 2, op="ALLREDUCE")
    t.observe("hvd_op_execute_seconds", 0.25, op="ALLREDUCE")
    a = tmp_path / "a.json"
    a.write_text(json.dumps(t.snapshot()))
    t.inc("hvd_plans_total", 3, op="ALLREDUCE")
    b = tmp_path / "b.json"
    b.write_text(json.dumps(t.snapshot()))

    assert metrics_dump.main([str(a)]) == 0
    out = capsys.readouterr().out
    assert 'hvd_plans_total{op="ALLREDUCE"}' in out
    assert "count=1" in out

    assert metrics_dump.main([str(a), str(b)]) == 0
    out = capsys.readouterr().out
    assert "+3" in out


# ------------------------------------------------------------------- e2e
METRICS_WORKER = """
    import os, time
    import numpy as np
    import jax
    jax.config.update('jax_platforms', 'cpu')
    import horovod_tpu as hvd
    hvd.init()
    assert hvd.size() == 2
    for i in range(80):
        out = np.asarray(hvd.allreduce(
            np.ones(256, np.float32), name=f'metrics.step.{i}',
            op=hvd.Sum))
        assert out[0] == hvd.size()
        time.sleep(0.05)
    print('METRICS_WORKER_DONE', hvd.rank(), flush=True)
    hvd.shutdown()
"""


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def validate_exposition(text: str) -> None:
    """Assertions shared with tools/metrics_smoke.py: the scraped page is
    well-formed Prometheus text carrying per-op latency histograms from
    BOTH ranks, the RPC/KV counter families, and the driver's elastic
    gauges."""
    parsed = mexport.parse_prometheus(text)  # raises on malformed lines
    hist = parsed["hvd_op_execute_seconds"]
    assert hist["type"] == "histogram"
    counts = {
        labels.get("rank"): v
        for name, labels, v in hist["samples"]
        if name.endswith("_count") and labels.get("op") == "ALLREDUCE"
    }
    assert counts.get("0", 0) > 0 and counts.get("1", 0) > 0, counts
    # Cumulative bucket sanity on one series: +Inf equals the count.
    for rank in ("0", "1"):
        inf = [
            v for name, labels, v in hist["samples"]
            if name.endswith("_bucket") and labels.get("rank") == rank
            and labels.get("op") == "ALLREDUCE"
            and labels.get("le") == "+Inf"
        ]
        assert inf and inf[0] == counts[rank]
    assert parsed["hvd_op_negotiate_seconds"]["type"] == "histogram"
    # RPC retry counter family is always exposed (pre-seeded zeros).
    assert parsed["hvd_rpc_retries_total"]["type"] == "counter"
    # KV traffic from the pushers themselves shows up driver-side.
    assert any(
        v > 0 for _, _, v in parsed["hvd_kv_server_requests_total"]["samples"]
    )
    # Driver-role elastic gauges.
    world = {
        labels.get("role"): v
        for _, labels, v in parsed["hvd_elastic_world_size"]["samples"]
    }
    assert world.get("driver") == 2.0
    gens = parsed["hvd_elastic_generations_total"]["samples"]
    assert any(
        labels.get("role") == "driver" and v >= 1 for _, labels, v in gens
    )


def run_metrics_job(timeout=120):
    """Launch a 2-rank CPU-mesh job through the real elastic driver with
    HOROVOD_METRICS=1 and scrape GET /metrics off the driver's rendezvous
    server while it runs. Returns (exit_code, scraped_text, all_output).
    Shared with tools/metrics_smoke.py."""
    import tempfile

    port = _free_port()
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update(
        {
            "JAX_PLATFORMS": "cpu",
            "HOROVOD_CYCLE_TIME": "1",
            "HOROVOD_METRICS": "1",
            "HOROVOD_METRICS_PORT": str(port),
            "HOROVOD_METRICS_PUSH_INTERVAL_S": "0.25",
            "PYTHONPATH": os.pathsep.join(
                [REPO, env.get("PYTHONPATH", "")]
            ).rstrip(os.pathsep),
        }
    )
    with tempfile.TemporaryDirectory() as td:
        script = os.path.join(td, "worker.py")
        with open(script, "w") as f:
            f.write(textwrap.dedent(METRICS_WORKER))
        proc = subprocess.Popen(
            [sys.executable, "-m", "horovod_tpu.run",
             "-np", "2", "--min-np", "2", "--max-np", "2",
             "--output-dir", td, sys.executable, script],
            env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        url = f"http://127.0.0.1:{port}/metrics"
        good_text = None
        last_err = None
        deadline = time.monotonic() + timeout
        try:
            while time.monotonic() < deadline and proc.poll() is None:
                time.sleep(0.25)
                try:
                    with urllib.request.urlopen(url, timeout=5) as resp:
                        text = resp.read().decode()
                    validate_exposition(text)
                    good_text = text
                    break
                except Exception as exc:  # noqa: BLE001 - retry until the
                    last_err = exc       # pushers have reported
            out, _ = proc.communicate(
                timeout=max(5.0, deadline - time.monotonic())
            )
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.communicate()
        text_out = out.decode(errors="replace")
        for fn in sorted(os.listdir(td)):
            if fn.startswith("worker.") and fn.endswith((".out", ".err")):
                with open(os.path.join(td, fn), errors="replace") as f:
                    text_out += f"\n--- {fn} ---\n" + f.read()
        if good_text is None:
            raise AssertionError(
                f"never scraped a valid exposition (last error: "
                f"{last_err!r}); job output:\n{text_out}"
            )
        return proc.returncode, good_text, text_out


def test_two_rank_metrics_scrape_e2e():
    """Acceptance: a 2-rank CPU-mesh run with HOROVOD_METRICS=1 serves
    Prometheus text on the driver's /metrics with per-op histograms from
    both ranks (rank labels), RPC counter families, and elastic gauges;
    the job itself completes cleanly."""
    rc, text, out = run_metrics_job()
    assert rc == 0, out
    assert "METRICS_WORKER_DONE 0" in out and "METRICS_WORKER_DONE 1" in out
    validate_exposition(text)
