"""End-to-end sequence-parallel training: DP(2) x SP(4) mesh with ring
attention inside the transformer, checked against the dense single-device
computation."""

from functools import partial

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from horovod_tpu.models.transformer import TransformerLM
from horovod_tpu.parallel.mesh import build_mesh
from horovod_tpu.parallel.ring_attention import ring_attention
from horovod_tpu.parallel.sp import make_sp_train_step

VOCAB = 64


def _data(B=4, T=32, seed=0):
    rng = np.random.RandomState(seed)
    tokens = rng.randint(0, VOCAB, (B, T)).astype(np.int32)
    labels = np.roll(tokens, -1, axis=1).astype(np.int32)
    return jnp.asarray(tokens), jnp.asarray(labels)


def _loss_fn(model):
    def loss(params, tokens, labels, positions):
        logits = model.apply({"params": params}, tokens, positions=positions)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, labels
        ).mean()

    return loss


def test_sp_training_matches_dense():
    mesh = build_mesh({"data": 2, "seq": 4})
    sp_model = TransformerLM(
        vocab_size=VOCAB, d_model=32, n_heads=4, n_layers=2, max_len=64,
        dtype=jnp.float32,
        attn_fn=partial(ring_attention, axis_name="seq", causal=True),
    )
    dense_model = TransformerLM(
        vocab_size=VOCAB, d_model=32, n_heads=4, n_layers=2, max_len=64,
        dtype=jnp.float32,
    )
    tokens, labels = _data()
    params = dense_model.init(jax.random.PRNGKey(0), tokens[:1])["params"]
    tx = optax.sgd(0.1)
    opt_state = tx.init(params)

    step = make_sp_train_step(_loss_fn(sp_model), tx, mesh, donate=False)

    # dense reference step on the full batch
    @jax.jit
    def dense_step(p, s, tokens, labels):
        def loss(p):
            logits = dense_model.apply({"params": p}, tokens)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, labels
            ).mean()

        l, g = jax.value_and_grad(loss)(p)
        u, s = tx.update(g, s, p)
        return optax.apply_updates(p, u), s, l

    dp = jax.tree.map(lambda x: x, params)
    ds = tx.init(dp)
    for i in range(3):
        params, opt_state, loss = step(params, opt_state, tokens, labels)
        dp, ds, dloss = dense_step(dp, ds, tokens, labels)
        np.testing.assert_allclose(float(loss), float(dloss), rtol=1e-4)

    flat_a = jax.tree.leaves(params)
    flat_b = jax.tree.leaves(dp)
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-5
        )


def test_sp_training_bf16_converges():
    mesh = build_mesh({"data": 2, "seq": 4})
    model = TransformerLM(
        vocab_size=VOCAB, d_model=32, n_heads=4, n_layers=2, max_len=64,
        dtype=jnp.bfloat16, remat=True,
        attn_fn=partial(ring_attention, axis_name="seq", causal=True),
    )
    # init with a dense twin: attn_fn doesn't affect the param structure,
    # and ring attention needs a bound mesh axis that init (outside
    # shard_map) doesn't have.
    init_model = model.clone(attn_fn=None)
    tokens, labels = _data(seed=1)
    params = init_model.init(jax.random.PRNGKey(1), tokens[:1])["params"]
    tx = optax.adam(1e-2)
    opt_state = tx.init(params)
    step = make_sp_train_step(_loss_fn(model), tx, mesh, donate=False)
    losses = []
    for _ in range(15):
        params, opt_state, loss = step(params, opt_state, tokens, labels)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
