"""Process-set API unit tests (single process).

Later-reference parity: ``horovod.ProcessSet`` / ``add_process_set`` /
``remove_process_set`` / ``global_process_set`` and the ``process_set=``
argument on the eager collectives. The multi-rank data-plane behavior
(sub-mesh collectives, member-ordered gathers, global-root broadcasts)
is covered by ``tests/test_multiprocess.py::test_process_sets_*``; this
file pins the API contract and the single-process degenerate semantics.
"""

import numpy as np
import pytest

import horovod_tpu as hvd


@pytest.fixture()
def sess():
    hvd.init()
    yield
    hvd.shutdown()


def test_global_process_set(sess):
    g = hvd.global_process_set
    assert g.process_set_id == 0
    assert g.included()
    assert g.size() == hvd.size() == 1
    assert g.rank() == hvd.rank() == 0
    # The implicit global set never needs (or allows) registration.
    with pytest.raises(ValueError):
        hvd.add_process_set(hvd.ProcessSet(None))


def test_add_remove_lifecycle(sess):
    ps = hvd.add_process_set([0])
    assert ps.process_set_id == 1
    assert ps.included() and ps.rank() == 0 and ps.size() == 1
    # Ids are assigned sequentially and deterministically.
    ps2 = hvd.add_process_set(hvd.ProcessSet([0]))
    assert ps2.process_set_id == 2
    # Double registration of the same object is rejected.
    with pytest.raises(ValueError):
        hvd.add_process_set(ps)
    hvd.remove_process_set(ps2)
    assert ps2.process_set_id is None
    # Removing twice (or the global set) fails loudly.
    with pytest.raises(ValueError):
        hvd.remove_process_set(ps2)
    with pytest.raises(ValueError):
        hvd.remove_process_set(hvd.global_process_set)
    hvd.remove_process_set(ps)


def test_ranks_validation(sess):
    with pytest.raises(ValueError):
        hvd.add_process_set([1])  # out of range for size=1
    with pytest.raises(ValueError):
        hvd.add_process_set([-1])
    with pytest.raises(ValueError):
        hvd.add_process_set([])


def test_unregistered_set_rejected(sess):
    ps = hvd.ProcessSet([0])
    with pytest.raises(ValueError, match="add_process_set"):
        hvd.allreduce(np.ones(2, np.float32), process_set=ps)


def test_collectives_over_singleton_set(sess):
    """size=1 semantics: a set containing this rank behaves like the
    global set (identity collectives), through the full negotiation
    machinery — requests carry the set id end to end."""
    ps = hvd.add_process_set([0])
    x = np.arange(6, dtype=np.float32).reshape(3, 2)
    assert np.allclose(hvd.allreduce(x, op=hvd.Sum, process_set=ps), x)
    assert np.allclose(hvd.allgather(x, process_set=ps), x)
    assert np.allclose(
        hvd.broadcast(x, root_rank=0, process_set=ps), x
    )
    outs = hvd.grouped_allreduce(
        [x, 2.0 * x], op=hvd.Sum, process_set=ps, name="psgrp"
    )
    assert np.allclose(outs[0], x) and np.allclose(outs[1], 2.0 * x)
    objs = hvd.allgather_object({"k": 7}, process_set=ps)
    assert objs == [{"k": 7}]
    hvd.remove_process_set(ps)


def test_shutdown_resets_registry():
    hvd.init()
    ps = hvd.add_process_set([0])
    assert ps.process_set_id == 1
    hvd.shutdown()
    assert ps.process_set_id is None
    # Fresh init restarts id assignment (all ranks stay aligned).
    hvd.init()
    try:
        again = hvd.add_process_set([0])
        assert again.process_set_id == 1
    finally:
        hvd.shutdown()
