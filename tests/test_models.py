"""Model-zoo tests: init + forward shapes + dtype policy for the
reference's headline benchmark families (ResNet / VGG-16 / Inception V3,
``docs/benchmarks.rst:13-14`` upstream)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.models import get_model


@pytest.mark.parametrize(
    "name,size",
    [
        ("resnet18", 64),
        ("resnet50", 64),
        ("vgg16", 64),
        ("inception3", 96),
    ],
)
def test_model_forward_shapes(name, size):
    model = get_model(name, num_classes=10)
    rng = jax.random.PRNGKey(0)
    x = jnp.ones((2, size, size, 3), jnp.float32)
    variables = model.init(rng, x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 10)
    assert logits.dtype == jnp.float32  # head stays fp32
    assert np.all(np.isfinite(np.asarray(logits)))


def test_model_train_step_mutates_batch_stats():
    model = get_model("resnet18", num_classes=10)
    x = jnp.ones((2, 64, 64, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    _, new_state = model.apply(
        variables, x, train=True, mutable=["batch_stats"]
    )
    assert "batch_stats" in new_state


def test_vgg_has_no_batch_stats_and_uses_dropout_rng():
    model = get_model("vgg16", num_classes=10)
    x = jnp.ones((2, 64, 64, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    assert "batch_stats" not in variables
    logits = model.apply(
        variables, x, train=True, rngs={"dropout": jax.random.PRNGKey(1)}
    )
    assert logits.shape == (2, 10)


def test_bf16_compute_policy():
    """Conv params are stored fp32 (flax default param_dtype) while
    compute runs bfloat16 — the MXU-native mixed-precision policy."""
    model = get_model("resnet18", num_classes=10)
    x = jnp.ones((1, 64, 64, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    kernel = variables["params"]["conv_init"]["kernel"]
    assert kernel.dtype == jnp.float32


def test_get_model_unknown_name():
    with pytest.raises(ValueError):
        get_model("alexnet")
