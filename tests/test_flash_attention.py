"""Pallas flash-attention kernel: interpret-mode numerics vs the dense
reference, forward and backward, plus the ring-block merge identity.

(The kernel is also exercised end-to-end as the transformer default
``attn_fn`` in test_models.py and as the ring-attention block compute in
test_ring_attention.py.)
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from horovod_tpu.ops.pallas_attention import (
    flash_attention,
    flash_attention_block,
    flash_attention_bthd,
)
from horovod_tpu.parallel.ring_attention import reference_attention


def _qkv_bhtd(bh=4, t=32, d=16, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(bh, t, d).astype(np.float32) * 0.5)
    return mk(), mk(), mk()


def _dense(q, k, v, causal):
    # [BH, T, D] dense reference via the tested reference_attention
    # ([B, T, H, D] layout with H folded out).
    out = reference_attention(
        q[:, :, None, :], k[:, :, None, :], v[:, :, None, :], causal=causal
    )
    return out[:, :, 0, :]


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("blocks", [(128, 128), (8, 16)])
def test_forward_matches_dense(causal, blocks):
    q, k, v = _qkv_bhtd()
    bq, bk = blocks
    out = flash_attention(q, k, v, causal=causal, block_q=bq, block_k=bk)
    expected = _dense(q, k, v, causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(expected), rtol=2e-4, atol=2e-5
    )


@pytest.mark.parametrize("causal", [False, True])
def test_grad_matches_dense(causal):
    q, k, v = _qkv_bhtd(bh=2, t=16, d=8)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, causal=causal, block_q=8, block_k=8)
            ** 2
        )

    def loss_dense(q, k, v):
        return jnp.sum(_dense(q, k, v, causal) ** 2)

    gf = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-4
        )


def test_bf16_dtype_preserved():
    q, k, v = _qkv_bhtd()
    q, k, v = (x.astype(jnp.bfloat16) for x in (q, k, v))
    out = flash_attention(q, k, v, causal=True)
    assert out.dtype == jnp.bfloat16
    expected = _dense(q, k, v, True)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(expected, np.float32),
        rtol=5e-2, atol=5e-2,
    )


def test_bthd_adapter_matches_reference():
    rng = np.random.RandomState(3)
    B, T, H, D = 2, 16, 4, 8
    mk = lambda: jnp.asarray(rng.randn(B, T, H, D).astype(np.float32) * 0.5)
    q, k, v = mk(), mk(), mk()
    out = flash_attention_bthd(q, k, v, causal=True)
    expected = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(expected), rtol=2e-4, atol=2e-5
    )


def test_block_merge_equals_full():
    """Splitting K/V in two and merging the block triples with the online
    softmax combination must reproduce full attention — the identity the
    ring relies on (each ring step merges one block)."""
    q, k, v = _qkv_bhtd(bh=2, t=16, d=8)
    scale = 8 ** -0.5
    t_half = 8
    k1, k2 = k[:, :t_half], k[:, t_half:]
    v1, v2 = v[:, :t_half], v[:, t_half:]

    # Causal over the concatenated sequence: block 2's keys sit at global
    # offset +t_half relative to q's origin.
    o1, m1, l1 = flash_attention_block(q, k1, v1, 0.0, sm_scale=scale)
    o2, m2, l2 = flash_attention_block(q, k2, v2, float(t_half),
                                       sm_scale=scale)
    m = jnp.maximum(m1, m2)
    c1, c2 = jnp.exp(m1 - m), jnp.exp(m2 - m)
    o = o1 * c1[..., None] + o2 * c2[..., None]
    l = l1 * c1 + l2 * c2
    l = jnp.where(l == 0.0, 1.0, l)
    merged = (o / l[..., None]).astype(q.dtype)

    expected = _dense(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(merged), np.asarray(expected), rtol=2e-4, atol=2e-5
    )


def test_block_grad_flows():
    q, k, v = _qkv_bhtd(bh=2, t=8, d=8)
    scale = 8 ** -0.5

    def loss(q, k, v):
        o, m, l = flash_attention_block(q, k, v, 0.0, sm_scale=scale)
        l = jnp.where(l == 0.0, 1.0, l)
        return jnp.sum((o / l[..., None]) ** 2)

    def ref_loss(q, k, v):
        return jnp.sum(_dense(q, k, v, causal=True) ** 2)

    gf = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    gd = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-4
        )


def test_odd_length_falls_back_to_dense():
    """Prime sequence lengths can't satisfy the kernel's block constraint;
    the [B,T,H,D] adapter (transformer default / Ulysses local attention)
    must fall back to dense instead of raising."""
    rng = np.random.RandomState(5)
    B, T, H, D = 1, 131, 2, 8  # 131 is prime
    mk = lambda: jnp.asarray(rng.randn(B, T, H, D).astype(np.float32) * 0.5)
    q, k, v = mk(), mk(), mk()
    out = flash_attention_bthd(q, k, v, causal=True)
    expected = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(expected), rtol=2e-4, atol=2e-5
    )


def test_kernel_lowers_for_tpu_target():
    """Cross-lower the real (non-interpret) kernel for the TPU platform:
    exercises the Pallas->Mosaic serialization (grid spec, scalar
    prefetch, the lane-dim m/l output blocks) without needing a chip —
    layout/blockspec mistakes fail here at trace time."""
    from functools import partial

    q = jnp.asarray(
        np.random.RandomState(0).randn(2, 256, 64).astype(np.float32)
    )
    f = jax.jit(partial(flash_attention, causal=True, interpret=False))
    try:
        traced = f.trace(q, q, q)
    except (TypeError, AttributeError) as e:  # pragma: no cover - old jax
        pytest.skip(f"trace API unavailable: {e!r}")
    try:
        lowered = traced.lower(lowering_platforms=("tpu",))
    except TypeError as e:  # pragma: no cover - kwarg unavailable
        pytest.skip(f"cross-platform lowering unavailable: {e!r}")
    # Mosaic serialization errors must FAIL, not skip — they are the bug
    # class this test guards against.
    text = lowered.as_text()
    assert "tpu_custom_call" in text


def test_ring_attention_lowers_for_tpu_target():
    """Cross-lower the flash-block ring (scalar-prefetch delta + per-step
    Mosaic kernel + ppermute rotation) for the TPU platform."""
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.jax import _shard_map
    from horovod_tpu.parallel.mesh import build_mesh
    from horovod_tpu.parallel.ring_attention import ring_attention

    n = len(jax.devices())
    mesh = build_mesh({"seq": n})
    q = jnp.asarray(
        np.random.RandomState(0)
        .randn(1, 128 * n, 4, 64).astype(np.float32)
    )
    fn = jax.jit(_shard_map(
        lambda a, b, c: ring_attention(
            a, b, c, axis_name="seq", causal=True, interpret=False
        ),
        mesh, in_specs=(P(None, "seq"),) * 3, out_specs=P(None, "seq"),
    ))
    try:
        traced = fn.trace(q, q, q)
    except (TypeError, AttributeError) as e:  # pragma: no cover - old jax
        pytest.skip(f"trace API unavailable: {e!r}")
    try:
        lowered = traced.lower(lowering_platforms=("tpu",))
    except TypeError as e:  # pragma: no cover - kwarg unavailable
        pytest.skip(f"cross-platform lowering unavailable: {e!r}")
    text = lowered.as_text()
    assert "tpu_custom_call" in text          # the Mosaic flash block
    assert "collective_permute" in text        # the K/V rotation
