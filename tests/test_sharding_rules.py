"""Pass 5 — mesh/sharding-rule validator tests
(horovod_tpu/analysis/sharding_rules.py).

Acceptance matrix: a valid DP x TP rule table is accepted via both the
API/preflight and the CLI; tables with unknown or duplicated mesh axes
and non-divisible dims are rejected; unmatched params and sharded
scalars are reported. The validator itself needs no jax, but jax's real
PartitionSpec must duck-type through.
"""

import json
import os
import subprocess
import sys

import pytest

from horovod_tpu import analysis
from horovod_tpu.analysis import preflight
from horovod_tpu.analysis.findings import (
    RULE_SHARDING_BAD_RULE,
    RULE_SHARDING_DUP_AXIS,
    RULE_SHARDING_INDIVISIBLE,
    RULE_SHARDING_SCALAR,
    RULE_SHARDING_UNKNOWN_AXIS,
    RULE_SHARDING_UNMATCHED,
)
from horovod_tpu.analysis.sharding_rules import (
    EXAMPLE_GPT_MESH,
    EXAMPLE_GPT_RULES,
    example_gpt_params,
    normalize_spec,
    validate_sharding_rules,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MESH = {"data": 4, "model": 2}


def _rules_of(fs):
    return [f.rule for f in fs]


# ---------------------------------------------------------------------------
# Spec normalization
# ---------------------------------------------------------------------------

def test_normalize_spec_shapes():
    assert normalize_spec(None) == ()
    assert normalize_spec("model") == (("model",),)
    assert normalize_spec((None, "model")) == ((), ("model",))
    assert normalize_spec((("data", "model"), None)) == (
        ("data", "model"), (),
    )
    assert normalize_spec(42) is None
    assert normalize_spec((1, 2)) is None


def test_jax_partition_spec_duck_types():
    from jax.sharding import PartitionSpec as P

    assert normalize_spec(P(None, "model")) == ((), ("model",))
    assert validate_sharding_rules(
        [(r".*", P("data", "model"))], MESH, {"w": (8, 8)}
    ) == []


# ---------------------------------------------------------------------------
# Acceptance: the valid DP x TP table
# ---------------------------------------------------------------------------

def test_valid_dp_tp_table_accepted():
    fs = validate_sharding_rules(
        EXAMPLE_GPT_RULES, EXAMPLE_GPT_MESH, example_gpt_params()
    )
    assert fs == []


def test_valid_dp_tp_table_accepted_via_preflight():
    fs = preflight.check_sharding_rules(
        EXAMPLE_GPT_RULES, EXAMPLE_GPT_MESH, example_gpt_params()
    )
    assert fs == []


def test_cli_sharding_target_accepts_reference_table():
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "tools", "collective_lint.py"),
         "--json", "sharding"],
        capture_output=True, text=True, cwd=REPO, timeout=60,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["summary"]["total"] == 0
    assert doc["passes"] == ["sharding"]


# ---------------------------------------------------------------------------
# Rejections
# ---------------------------------------------------------------------------

def test_unknown_axis_rejected():
    fs = validate_sharding_rules(
        [(r".*kernel$", (None, "tensor")), (r".*", None)], MESH
    )
    assert _rules_of(fs) == [RULE_SHARDING_UNKNOWN_AXIS]
    assert fs[0].details["axis"] == "tensor"
    assert fs[0].severity == "error"


def test_duplicate_axis_across_dims_rejected():
    fs = validate_sharding_rules([(r".*", ("model", "model"))], MESH)
    assert _rules_of(fs) == [RULE_SHARDING_DUP_AXIS]
    assert fs[0].details["dims"] == [0, 1]


def test_duplicate_axis_within_dim_rejected():
    fs = validate_sharding_rules(
        [(r".*", (("model", "model"), None))], MESH
    )
    assert _rules_of(fs) == [RULE_SHARDING_DUP_AXIS]


def test_non_divisible_dim_rejected():
    fs = validate_sharding_rules(
        [(r".*", (None, "model")), ], {"data": 4, "model": 3},
        {"w": (8, 10)},
    )
    assert _rules_of(fs) == [RULE_SHARDING_INDIVISIBLE]
    assert fs[0].details == {
        "param": "w", "dim": 1, "size": 10, "factor": 3, "rule_index": 0,
    }


def test_multi_axis_product_divisibility():
    # ("data","model") on dim 0 needs divisibility by 4*2=8.
    fs = validate_sharding_rules(
        [(r".*", (("data", "model"), None))], MESH, {"w": (12, 4)}
    )
    assert _rules_of(fs) == [RULE_SHARDING_INDIVISIBLE]
    assert validate_sharding_rules(
        [(r".*", (("data", "model"), None))], MESH, {"w": (16, 4)}
    ) == []


def test_spec_longer_than_rank_rejected():
    fs = validate_sharding_rules(
        [(r".*", (None, None, "model"))], MESH, {"w": (8, 8)}
    )
    assert _rules_of(fs) == [RULE_SHARDING_INDIVISIBLE]


def test_unmatched_param_rejected():
    fs = validate_sharding_rules(
        [(r"^only_this$", None)], MESH, {"w": (8, 8)}
    )
    assert _rules_of(fs) == [RULE_SHARDING_UNMATCHED]
    # Scalars never need a rule (the engine replicates them).
    assert validate_sharding_rules(
        [(r"^only_this$", None)], MESH, {"step": ()}
    ) == []


def test_sharded_scalar_warned():
    fs = validate_sharding_rules(
        [(r".*", ("model",))], MESH, {"step": ()}
    )
    assert _rules_of(fs) == [RULE_SHARDING_SCALAR]
    assert fs[0].severity == "warning"


def test_bad_regex_and_bad_spec_rejected():
    fs = validate_sharding_rules([(r"[unclosed", None)], MESH)
    assert _rules_of(fs) == [RULE_SHARDING_BAD_RULE]
    fs = validate_sharding_rules([(r".*", 42)], MESH)
    assert _rules_of(fs) == [RULE_SHARDING_BAD_RULE]


def test_first_match_wins_like_match_partition_rules():
    """Rule order is the engine's contract (SNIPPETS.md shape): the
    first matching rule decides, so a later conflicting rule must not
    mask an earlier valid one."""
    rules = [
        (r"kernel$", (None, "model")),
        (r".*", None),
    ]
    assert validate_sharding_rules(
        rules, MESH, {"mlp/kernel": (8, 8)}
    ) == []
    # Swap the order: the catch-all replicates everything, so the
    # (would-be indivisible) kernel rule never fires.
    assert validate_sharding_rules(
        list(reversed(rules)), MESH, {"mlp/kernel": (8, 9)}
    ) == []


def test_preflight_raises_on_errors():
    with pytest.raises(analysis.CollectiveSafetyError):
        preflight.check_sharding_rules(
            [(r".*", (None, "tensor"))], MESH
        )


def test_suppressions_apply():
    specs = [(r".*", (None, "tensor")), (r".*", None)]
    assert validate_sharding_rules(
        specs, MESH, suppress=["sharding-unknown-axis"]
    ) == []
    with analysis.suppressions("sharding-unknown-axis"):
        assert validate_sharding_rules(specs, MESH) == []
