"""Pass 4 — SPMD rank-divergence analyzer tests
(horovod_tpu/analysis/divergence.py).

Acceptance matrix: a seeded rank-divergent collective (collective under
``lax.cond`` on ``axis_index``) is flagged; the guard's psum agreement
seam is recognized as the sanctioned convergence pattern; divergence
over a disjoint mesh axis is allowed; all shipped ``make_train_step``
variants (posthoc, overlap, hierarchical-auto, guard-skip,
quantized-overlap) report zero findings.
"""

import jax
import jax.numpy as jnp
import optax
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

import horovod_tpu.jax as hvdj
from horovod_tpu import analysis
from horovod_tpu.analysis.findings import RULE_RANK_DIVERGENCE
from horovod_tpu.jax import _shard_map
from horovod_tpu.parallel.mesh import build_hierarchical_mesh, build_mesh


def _mesh():
    return build_mesh({"data": len(jax.devices())})


def _wrap(body, mesh, out_spec=P("data")):
    return _shard_map(
        body, mesh, in_specs=(P("data"),), out_specs=out_spec
    )


# ---------------------------------------------------------------------------
# Seeded divergence is flagged
# ---------------------------------------------------------------------------

def test_collective_under_rank_cond_flagged():
    mesh = _mesh()

    def bad(x):
        r = lax.axis_index("data")
        return lax.cond(
            r == 0, lambda v: lax.psum(v, "data"), lambda v: v, x
        )

    fs = analysis.analyze_step(_wrap(bad, mesh), jnp.ones((8, 4)))
    assert [f.rule for f in fs] == [RULE_RANK_DIVERGENCE]
    assert fs[0].severity == "error"
    assert "axis_index" in fs[0].message
    assert fs[0].details["tainted_axes"] == ["data"]
    assert "cond" in fs[0].details["guard"]


def test_collective_under_rank_switch_flagged():
    mesh = _mesh()

    def bad(x):
        r = lax.axis_index("data")
        return lax.switch(
            r % 2,
            [lambda v: lax.psum(v, "data"), lambda v: v * 2],
            x,
        )

    fs = analysis.analyze_step(_wrap(bad, mesh), jnp.ones((8, 4)))
    assert [f.rule for f in fs] == [RULE_RANK_DIVERGENCE]


def test_collective_under_rank_while_flagged():
    mesh = _mesh()

    def bad(x):
        r = lax.axis_index("data")

        def cond(c):
            return c[0] < r

        def body(c):
            return (c[0] + 1, c[1] + lax.psum(c[1], "data"))

        return lax.while_loop(cond, body, (0, x))[1]

    fs = analysis.analyze_step(_wrap(bad, mesh), jnp.ones((8, 4)))
    assert [f.rule for f in fs] == [RULE_RANK_DIVERGENCE]
    assert fs[0].details["guard"] == "while"


def test_laundered_taint_through_arithmetic_flagged():
    """axis_index -> arithmetic -> predicate still taints the guard."""
    mesh = _mesh()

    def bad(x):
        r = lax.axis_index("data")
        derived = (r * 3 + 1) % 5
        return lax.cond(
            derived > 2, lambda v: lax.pmax(v, "data"), lambda v: v, x
        )

    fs = analysis.analyze_step(_wrap(bad, mesh), jnp.ones((8, 4)))
    assert [f.rule for f in fs] == [RULE_RANK_DIVERGENCE]


# ---------------------------------------------------------------------------
# Sanctioned patterns stay clean
# ---------------------------------------------------------------------------

def test_psum_agreement_seam_is_sanctioned():
    """The guard-skip pattern: the flag is psum-agreed before guarding —
    every rank takes the same branch, no divergence."""
    mesh = _mesh()

    def good(x):
        flag = (lax.axis_index("data") == 0).astype(jnp.float32)
        agreed = lax.psum(flag, "data")
        return lax.cond(
            agreed > 0, lambda v: lax.psum(v, "data"), lambda v: v, x
        )

    assert analysis.analyze_step(_wrap(good, mesh),
                                 jnp.ones((8, 4))) == []


def test_collective_free_divergent_branch_allowed():
    mesh = _mesh()

    def masky(x):
        r = lax.axis_index("data")
        return lax.cond(r == 0, lambda v: v * 2, lambda v: v, x)

    assert analysis.analyze_step(_wrap(masky, mesh),
                                 jnp.ones((8, 4))) == []


def test_disjoint_axis_divergence_allowed():
    """A cross-rank divergent predicate guarding a collective over a
    DIFFERENT axis is fine: every member of the collective's group
    shares the predicate value."""
    mesh = build_mesh({"cross": 2, "local": 4})

    def fn(x):
        r = lax.axis_index("cross")
        return lax.cond(
            r == 0,
            lambda v: lax.psum(v, "local"),
            lambda v: lax.pmax(v, "local"),
            x,
        )

    step = _shard_map(fn, mesh, in_specs=(P("cross"),),
                      out_specs=P("cross"))
    assert analysis.analyze_step(step, jnp.ones((8, 4))) == []


def test_fixed_trip_count_loop_allowed():
    mesh = _mesh()

    def ok(x):
        def body(i, c):
            return c + lax.psum(c, "data")

        return lax.fori_loop(0, 3, body, x)

    assert analysis.analyze_step(_wrap(ok, mesh), jnp.ones((8, 4))) == []


def test_straight_line_axis_index_allowed():
    """axis_index feeding data (ppermute/dynamic_slice) is the normal
    SPMD idiom — only tainted *control flow* over a collective is
    flagged."""
    mesh = _mesh()

    def ok(x):
        r = lax.axis_index("data")
        shifted = lax.ppermute(
            x, "data", [(i, (i + 1) % 8) for i in range(8)]
        )
        return shifted + r.astype(x.dtype)

    assert analysis.analyze_step(_wrap(ok, mesh), jnp.ones((8, 4))) == []


# ---------------------------------------------------------------------------
# lint_step integration + shipped variants
# ---------------------------------------------------------------------------

def test_lint_step_folds_divergence_in():
    mesh = _mesh()

    def bad(x):
        r = lax.axis_index("data")
        return lax.cond(
            r == 0, lambda v: lax.psum(v, "data"), lambda v: v, x
        )

    fs = analysis.lint_step(_wrap(bad, mesh), jnp.ones((8, 4)), mesh=mesh)
    assert RULE_RANK_DIVERGENCE in {f.rule for f in fs}
    fs = analysis.lint_step(
        _wrap(bad, mesh), jnp.ones((8, 4)), mesh=mesh, divergence=False
    )
    assert RULE_RANK_DIVERGENCE not in {f.rule for f in fs}


@pytest.mark.parametrize(
    "label,kwargs",
    [
        ("posthoc", {}),
        ("overlap", {"overlap": True}),
        ("hierarchical-auto", {"hierarchical": "auto"}),
        ("guard-skip", {"nonfinite": "skip"}),
        ("quantized-overlap", {"overlap": True, "quantized": True}),
    ],
)
def test_shipped_train_step_variants_are_clean(label, kwargs):
    """Acceptance: zero rank-divergence findings on every shipped
    make_train_step variant (the guard-skip variant exercises the psum
    agreement seam end-to-end)."""
    mesh = (
        build_hierarchical_mesh(4)
        if label == "hierarchical-auto" else _mesh()
    )

    def loss_fn(p, batch):
        return jnp.mean((batch @ p["w"] + p["b"]) ** 2)

    params = {"w": jnp.ones((16, 4)), "b": jnp.zeros((4,))}
    batch = jnp.ones((8, 16))
    tx = optax.sgd(0.01)
    step = hvdj.make_train_step(
        loss_fn, tx, mesh, donate=False, **kwargs
    )
    opt_state = tx.init(params)
    assert analysis.analyze_step(step, params, opt_state, batch) == []
