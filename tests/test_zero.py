"""ZeRO-1 optimizer-state sharding: the sharded schedule (reduce-scatter
grads -> shard-local optax update -> all-gather params) must produce the
SAME training trajectory as the replicated make_train_step, while the
live optimizer state is 1/N per shard."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

import horovod_tpu.jax as hvdj
from horovod_tpu.parallel.mesh import build_mesh
from horovod_tpu.parallel.zero import init_zero1_state, make_zero1_train_step

N_DEV = 8


@pytest.fixture(scope="module")
def mesh():
    return build_mesh({"data": N_DEV})


def _problem(seed=0, d=13):  # deliberately not divisible by 8 (padding path)
    rng = np.random.RandomState(seed)
    params = {
        "w": jnp.asarray(rng.randn(d, 3).astype(np.float32)),
        "b": jnp.zeros((3,), jnp.float32),
    }
    X = jnp.asarray(rng.randn(N_DEV * 4, d).astype(np.float32))
    y = jnp.asarray(rng.randn(N_DEV * 4, 3).astype(np.float32))

    def loss_fn(p, batch):
        xb, yb = batch
        return jnp.mean((xb @ p["w"] + p["b"] - yb) ** 2)

    return params, (X, y), loss_fn


@pytest.mark.parametrize("tx_name", ["sgd_momentum", "adamw"])
def test_zero1_matches_replicated_dp(mesh, tx_name):
    tx = (
        optax.sgd(0.1, momentum=0.9)
        if tx_name == "sgd_momentum" else optax.adamw(1e-2)
    )
    params, batch, loss_fn = _problem()

    rep_step = hvdj.make_train_step(loss_fn, tx, mesh, donate=False)
    rep_params = jax.tree.map(jnp.copy, params)
    rep_state = tx.init(rep_params)

    z_step = make_zero1_train_step(loss_fn, tx, mesh, donate=False)
    z_params = jax.tree.map(jnp.copy, params)
    z_state = init_zero1_state(tx, z_params, N_DEV)

    for _ in range(5):
        rep_params, rep_state, rep_loss = rep_step(
            rep_params, rep_state, batch
        )
        z_params, z_state, z_loss = z_step(z_params, z_state, batch)
        np.testing.assert_allclose(
            float(rep_loss), float(z_loss), rtol=1e-6
        )
    for ka in rep_params:
        np.testing.assert_allclose(
            np.asarray(rep_params[ka]), np.asarray(z_params[ka]),
            rtol=1e-5, atol=1e-6,
        )


def test_zero1_state_is_sharded(mesh):
    """The live state leaves carry a leading [n_shards] axis holding 1/N
    of the flat parameter vector each — that is the memory win."""
    params, batch, loss_fn = _problem(d=16)
    tx = optax.adam(1e-3)
    state = init_zero1_state(tx, params, N_DEV)
    total = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    padded = ((total + N_DEV - 1) // N_DEV) * N_DEV
    mus = [
        leaf for leaf in jax.tree.leaves(state)
        if getattr(leaf, "ndim", 0) == 2
    ]
    assert mus, "expected vector state leaves (mu/nu)"
    for leaf in mus:
        assert leaf.shape == (N_DEV, padded // N_DEV), leaf.shape

    step = make_zero1_train_step(loss_fn, tx, mesh, donate=False)
    p2, s2, loss = step(params, state, batch)
    assert np.isfinite(float(loss))
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(s2)):
        assert a.shape == b.shape


def test_zero1_quantized_tracks_replicated(mesh):
    """quantized=True ZeRO-1 (int8-wire ring reduce-scatter feeding the
    sharded update) follows the full-precision replicated trajectory
    within quantization noise."""
    tx = optax.sgd(0.05, momentum=0.9)
    params, (X, y), loss_fn = _problem(seed=7, d=29)

    rep_step = hvdj.make_train_step(loss_fn, tx, mesh)
    rep_p, rep_s = jax.tree.map(jnp.copy, params), tx.init(params)

    z_state = init_zero1_state(tx, params, N_DEV, quantized=True)
    z_step = make_zero1_train_step(
        loss_fn, tx, mesh, quantized=True, donate=False
    )
    z_p = jax.tree.map(jnp.copy, params)

    for _ in range(10):
        rep_p, rep_s, _ = rep_step(rep_p, rep_s, (X, y))
        z_p, z_state, _ = z_step(z_p, z_state, (X, y))

    for k in params:
        a, b = np.asarray(rep_p[k]), np.asarray(z_p[k])
        # int8 wire adds noise; the trajectories must stay close.
        assert np.abs(a - b).max() < 5e-3 + 0.02 * np.abs(a).max(), (
            k, np.abs(a - b).max(),
        )


def test_quantized_convergence_tracks_fp32(mesh):
    """End-to-end convergence evidence (round-3 VERDICT weak #7): the
    int8-wire and int8+ZeRO-1 training curves must track full-precision
    DP — asserted on the final loss after real optimization steps, not a
    per-call error bound. The committed 300-step artifact is
    BENCH_CONVERGENCE_CPU.json; this CI version runs fewer steps."""
    from horovod_tpu.utils import convergence

    result = convergence.run(steps=40, record_every=10)
    final = result["final_loss"]
    # The curves must actually be training...
    assert final["fp32"] < result["curves"]["fp32"][0] * 0.8
    # ...and the lossy paths must land within 5% of fp32.
    assert result["rel_gap_vs_fp32"]["quantized"] < 0.05, final
    assert result["rel_gap_vs_fp32"]["quantized+zero1"] < 0.05, final
