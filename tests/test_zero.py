"""ZeRO-1 optimizer-state sharding: the sharded schedule (reduce-scatter
grads -> shard-local optax update -> all-gather params) must produce the
SAME training trajectory as the replicated make_train_step, while the
live optimizer state is 1/N per shard."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

import horovod_tpu.jax as hvdj
from horovod_tpu.parallel.mesh import build_mesh
from horovod_tpu.parallel.zero import init_zero1_state, make_zero1_train_step

N_DEV = 8


@pytest.fixture(scope="module")
def mesh():
    return build_mesh({"data": N_DEV})


def _problem(seed=0, d=13):  # deliberately not divisible by 8 (padding path)
    rng = np.random.RandomState(seed)
    params = {
        "w": jnp.asarray(rng.randn(d, 3).astype(np.float32)),
        "b": jnp.zeros((3,), jnp.float32),
    }
    X = jnp.asarray(rng.randn(N_DEV * 4, d).astype(np.float32))
    y = jnp.asarray(rng.randn(N_DEV * 4, 3).astype(np.float32))

    def loss_fn(p, batch):
        xb, yb = batch
        return jnp.mean((xb @ p["w"] + p["b"] - yb) ** 2)

    return params, (X, y), loss_fn


@pytest.mark.parametrize("tx_name", ["sgd_momentum", "adamw"])
def test_zero1_matches_replicated_dp(mesh, tx_name):
    tx = (
        optax.sgd(0.1, momentum=0.9)
        if tx_name == "sgd_momentum" else optax.adamw(1e-2)
    )
    params, batch, loss_fn = _problem()

    rep_step = hvdj.make_train_step(loss_fn, tx, mesh, donate=False)
    rep_params = jax.tree.map(jnp.copy, params)
    rep_state = tx.init(rep_params)

    z_step = make_zero1_train_step(loss_fn, tx, mesh, donate=False)
    z_params = jax.tree.map(jnp.copy, params)
    z_state = init_zero1_state(tx, z_params, N_DEV)

    for _ in range(5):
        rep_params, rep_state, rep_loss = rep_step(
            rep_params, rep_state, batch
        )
        z_params, z_state, z_loss = z_step(z_params, z_state, batch)
        np.testing.assert_allclose(
            float(rep_loss), float(z_loss), rtol=1e-6
        )
    for ka in rep_params:
        np.testing.assert_allclose(
            np.asarray(rep_params[ka]), np.asarray(z_params[ka]),
            rtol=1e-5, atol=1e-6,
        )


def test_zero1_state_is_sharded(mesh):
    """The live state leaves carry a leading [n_shards] axis holding 1/N
    of the flat parameter vector each — that is the memory win."""
    params, batch, loss_fn = _problem(d=16)
    tx = optax.adam(1e-3)
    state = init_zero1_state(tx, params, N_DEV)
    total = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    padded = ((total + N_DEV - 1) // N_DEV) * N_DEV
    mus = [
        leaf for leaf in jax.tree.leaves(state)
        if getattr(leaf, "ndim", 0) == 2
    ]
    assert mus, "expected vector state leaves (mu/nu)"
    for leaf in mus:
        assert leaf.shape == (N_DEV, padded // N_DEV), leaf.shape

    step = make_zero1_train_step(loss_fn, tx, mesh, donate=False)
    p2, s2, loss = step(params, state, batch)
    assert np.isfinite(float(loss))
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(s2)):
        assert a.shape == b.shape
